"""DevicePlugin gRPC integration tests against a KubeletStub.

Python port of the reference's table-driven integration suite
(beta_plugin_test.go:71-599): fake /dev + sysfs in a tempdir, run the real
serve loop, register with a stub kubelet, then drive ListAndWatch/Allocate
as a DevicePlugin client over the plugin's unix socket.  Covers the four
node configs: plain, time-sharing, partitioned, partitioned+time-sharing —
plus health transitions and chip hotplug.
"""

import os
import threading
import time

import grpc
import pytest

from container_engine_accelerators_tpu.deviceplugin import api
from container_engine_accelerators_tpu.deviceplugin import (
    deviceplugin_v1beta1_pb2 as pb,
)
from container_engine_accelerators_tpu.deviceplugin.manager import TpuManager
from container_engine_accelerators_tpu.tpulib import (
    SysfsTpuLib,
    write_fixture,
    write_libtpu_install,
)
from container_engine_accelerators_tpu.utils.config import TPUConfig
from container_engine_accelerators_tpu.utils.device import (
    HEALTHY,
    UNHEALTHY,
    Device,
    Mount,
)
from tests.kubelet_stub import KubeletStub

PLUGIN_ENDPOINT = "tpu-plugin.sock"
NUM_CHIPS = 4


def make_manager(root, config_json=None, num_chips=NUM_CHIPS, topology="2x2x1"):
    write_fixture(root, num_chips, topology=topology)
    cfg = TPUConfig.from_json(config_json or {})
    cfg.add_defaults_and_validate()
    mounts = [
        Mount(
            host_path=write_libtpu_install(root),
            container_path="/usr/local/tpu",
            read_only=True,
        )
    ]
    return TpuManager(
        os.path.join(root, "dev"),
        mounts,
        cfg,
        lib=SysfsTpuLib(root),
        device_check_interval_s=0.3,
        socket_check_interval_s=0.1,
    )


class PluginHarness:
    """Runs the real serve loop in a thread next to a KubeletStub."""

    def __init__(self, tmp_path, config_json=None, num_chips=NUM_CHIPS):
        self.root = str(tmp_path / "root")
        os.makedirs(self.root)
        self.plugin_dir = str(tmp_path / "device-plugin")
        os.makedirs(self.plugin_dir)
        self.manager = make_manager(self.root, config_json, num_chips)
        self.stub = KubeletStub(os.path.join(self.plugin_dir, api.KUBELET_SOCKET))
        self.channel = None
        self.thread = None

    def __enter__(self):
        self.stub.start()
        self.manager.start()
        self.thread = threading.Thread(
            target=self.manager.serve,
            args=(self.plugin_dir,),
            kwargs={"plugin_endpoint": PLUGIN_ENDPOINT},
            daemon=True,
        )
        self.thread.start()
        # Wait for registration to prove the plugin is up.
        self.register_request = self.stub.requests.get(timeout=10)
        self.channel = grpc.insecure_channel(
            f"unix:{os.path.join(self.plugin_dir, PLUGIN_ENDPOINT)}"
        )
        grpc.channel_ready_future(self.channel).result(timeout=10)
        self.client = api.DevicePluginClient(self.channel)
        return self

    def __exit__(self, *exc):
        if self.channel is not None:
            self.channel.close()
        self.manager.stop()
        self.thread.join(timeout=5)
        self.stub.stop()
        return False

    def device_map(self, stream):
        resp = next(stream)
        return {d.ID: d.health for d in resp.devices}


def allocate_ids(harness, ids):
    req = pb.AllocateRequest()
    creq = req.container_requests.add()
    creq.devicesIDs.extend(ids)
    return harness.client.allocate(req, timeout=5)


# ---- registration ----------------------------------------------------------


def test_registers_with_kubelet(tmp_path):
    with PluginHarness(tmp_path) as h:
        r = h.register_request
        assert r.resource_name == "google.com/tpu"
        assert r.version == "v1beta1"
        assert r.endpoint == PLUGIN_ENDPOINT


# ---- plain config ----------------------------------------------------------


def test_list_and_watch_plain(tmp_path):
    with PluginHarness(tmp_path) as h:
        stream = h.client.list_and_watch(pb.Empty(), timeout=10)
        devices = h.device_map(stream)
        assert devices == {f"accel{i}": HEALTHY for i in range(NUM_CHIPS)}


def test_allocate_plain(tmp_path):
    with PluginHarness(tmp_path) as h:
        resp = allocate_ids(h, ["accel1", "accel2"])
        assert len(resp.container_responses) == 1
        cresp = resp.container_responses[0]
        paths = sorted(d.host_path for d in cresp.devices)
        assert paths == [
            os.path.join(h.root, "dev", "accel1"),
            os.path.join(h.root, "dev", "accel2"),
        ]
        for d in cresp.devices:
            assert d.container_path == d.host_path
            assert d.permissions == "mrw"
        assert len(cresp.mounts) == 1
        assert cresp.mounts[0].host_path == os.path.join(
            h.root, "home/kubernetes/bin/tpu"
        )
        assert cresp.mounts[0].container_path == "/usr/local/tpu"
        assert cresp.mounts[0].read_only is True
        assert dict(cresp.envs) == {}


def test_allocate_unknown_device_rejected(tmp_path):
    with PluginHarness(tmp_path) as h:
        with pytest.raises(grpc.RpcError) as exc_info:
            allocate_ids(h, ["accel9"])
        assert exc_info.value.code() == grpc.StatusCode.INVALID_ARGUMENT


def test_unhealthy_device_flow(tmp_path):
    with PluginHarness(tmp_path) as h:
        stream = h.client.list_and_watch(pb.Empty(), timeout=10)
        assert h.device_map(stream)["accel0"] == HEALTHY
        # Health checker pushes a transition; ListAndWatch re-announces.
        h.manager.health_events.put(Device(id="accel0", health=UNHEALTHY))
        devices = h.device_map(stream)
        assert devices["accel0"] == UNHEALTHY
        assert devices["accel1"] == HEALTHY
        with pytest.raises(grpc.RpcError):
            allocate_ids(h, ["accel0"])


def test_hotplug_restarts_server(tmp_path):
    """New chip appears → plugin re-registers and advertises it
    (ref: beta_plugin_test.go:366-377)."""
    with PluginHarness(tmp_path, num_chips=2) as h:
        open(os.path.join(h.root, "dev", "accel2"), "w").close()
        # Expect a re-registration within the device check interval.
        second = h.stub.requests.get(timeout=10)
        assert second.resource_name == "google.com/tpu"
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            ch = grpc.insecure_channel(
                f"unix:{os.path.join(h.plugin_dir, PLUGIN_ENDPOINT)}"
            )
            try:
                grpc.channel_ready_future(ch).result(timeout=2)
                stream = api.DevicePluginClient(ch).list_and_watch(
                    pb.Empty(), timeout=5
                )
                devices = h.device_map(stream)
                ch.close()
                if "accel2" in devices:
                    return
            except grpc.RpcError:
                ch.close()
            time.sleep(0.2)
        pytest.fail("hotplugged accel2 never advertised")


def test_socket_deletion_triggers_reregistration(tmp_path):
    """kubelet restart wipes the plugin dir → plugin re-registers
    (ref: manager.go:475-481)."""
    with PluginHarness(tmp_path) as h:
        os.unlink(os.path.join(h.plugin_dir, PLUGIN_ENDPOINT))
        second = h.stub.requests.get(timeout=10)
        assert second.endpoint == PLUGIN_ENDPOINT


# ---- time-sharing ----------------------------------------------------------

TIME_SHARING_CONFIG = {
    "tpuSharingConfig": {
        "tpuSharingStrategy": "time-sharing",
        "maxSharedClientsPerTpu": 2,
    }
}


def test_list_and_watch_time_sharing(tmp_path):
    with PluginHarness(tmp_path, TIME_SHARING_CONFIG) as h:
        stream = h.client.list_and_watch(pb.Empty(), timeout=10)
        devices = h.device_map(stream)
        assert set(devices) == {
            f"accel{i}/vtpu{j}" for i in range(NUM_CHIPS) for j in range(2)
        }


def test_allocate_time_sharing(tmp_path):
    with PluginHarness(tmp_path, TIME_SHARING_CONFIG) as h:
        resp = allocate_ids(h, ["accel1/vtpu0"])
        cresp = resp.container_responses[0]
        assert [d.host_path for d in cresp.devices] == [
            os.path.join(h.root, "dev", "accel1")
        ]
        # Two virtual devices in one request is invalid under time-sharing.
        with pytest.raises(grpc.RpcError) as exc_info:
            allocate_ids(h, ["accel1/vtpu0", "accel1/vtpu1"])
        assert exc_info.value.code() == grpc.StatusCode.INVALID_ARGUMENT


def test_time_sharing_inherits_health(tmp_path):
    with PluginHarness(tmp_path, TIME_SHARING_CONFIG) as h:
        stream = h.client.list_and_watch(pb.Empty(), timeout=10)
        h.device_map(stream)
        h.manager.health_events.put(Device(id="accel0", health=UNHEALTHY))
        devices = h.device_map(stream)
        assert devices["accel0/vtpu0"] == UNHEALTHY
        assert devices["accel0/vtpu1"] == UNHEALTHY
        assert devices["accel1/vtpu0"] == HEALTHY


# ---- partitioned (sub-slice) ----------------------------------------------

PARTITION_CONFIG = {"tpuPartitionSize": "2x1"}


def test_list_and_watch_partitioned(tmp_path):
    with PluginHarness(tmp_path, PARTITION_CONFIG) as h:
        stream = h.client.list_and_watch(pb.Empty(), timeout=10)
        devices = h.device_map(stream)
        assert devices == {"slice0": HEALTHY, "slice1": HEALTHY}


def test_allocate_partitioned_maps_to_member_chips(tmp_path):
    with PluginHarness(tmp_path, PARTITION_CONFIG) as h:
        resp = allocate_ids(h, ["slice0"])
        cresp = resp.container_responses[0]
        # 2x1 sub-slice on a 2x2x1 host: slice0 = chips at (0,0),(1,0).
        assert sorted(d.host_path for d in cresp.devices) == [
            os.path.join(h.root, "dev", "accel0"),
            os.path.join(h.root, "dev", "accel1"),
        ]
        envs = dict(cresp.envs)
        assert envs["TPU_VISIBLE_DEVICES"] == "0,1"
        assert envs["TPU_CHIPS_PER_PROCESS_BOUNDS"] == "2,1,1"
        assert envs["TPU_PROCESS_BOUNDS"] == "1,1,1"


def test_chip_fault_takes_down_owning_slice(tmp_path):
    with PluginHarness(tmp_path, PARTITION_CONFIG) as h:
        stream = h.client.list_and_watch(pb.Empty(), timeout=10)
        h.device_map(stream)
        h.manager.health_events.put(Device(id="accel3", health=UNHEALTHY))
        devices = h.device_map(stream)
        assert devices["slice1"] == UNHEALTHY
        assert devices["slice0"] == HEALTHY


# ---- partitioned + time-sharing -------------------------------------------

PARTITION_SHARING_CONFIG = {
    "tpuPartitionSize": "2x1",
    "tpuSharingConfig": {
        "tpuSharingStrategy": "time-sharing",
        "maxSharedClientsPerTpu": 2,
    },
}


def test_partitioned_time_sharing(tmp_path):
    with PluginHarness(tmp_path, PARTITION_SHARING_CONFIG) as h:
        stream = h.client.list_and_watch(pb.Empty(), timeout=10)
        devices = h.device_map(stream)
        assert set(devices) == {
            f"slice{i}/vtpu{j}" for i in range(2) for j in range(2)
        }
        resp = allocate_ids(h, ["slice1/vtpu1"])
        cresp = resp.container_responses[0]
        assert sorted(d.host_path for d in cresp.devices) == [
            os.path.join(h.root, "dev", "accel2"),
            os.path.join(h.root, "dev", "accel3"),
        ]
        assert dict(cresp.envs)["TPU_VISIBLE_DEVICES"] == "2,3"
