"""tpulib sysfs backend tests: the node filesystem contract."""

import os

import pytest

from container_engine_accelerators_tpu.tpulib import SysfsTpuLib, write_fixture
from container_engine_accelerators_tpu.tpulib.sysfs import post_event


def test_enumeration_and_attrs(tmp_path):
    write_fixture(str(tmp_path), 4, topology="2x2x1", hbm_total=16 * 2**30)
    lib = SysfsTpuLib(str(tmp_path))
    assert lib.chip_count() == 4
    chips = lib.chips()
    assert [c.name for c in chips] == ["accel0", "accel1", "accel2", "accel3"]
    assert chips[0].coords == (0, 0, 0)
    assert chips[3].coords == (1, 1, 0)
    assert chips[0].topology == (2, 2, 1)
    hbm = lib.hbm_info("accel0")
    assert hbm.total_bytes == 16 * 2**30
    assert hbm.used_bytes == 0
    assert lib.duty_cycle("accel0") == 0
    assert lib.health("accel0") == "ok"


def test_empty_root(tmp_path):
    lib = SysfsTpuLib(str(tmp_path))
    assert lib.chip_count() == 0
    assert lib.chips() == []


def test_event_queue_fifo_and_consume(tmp_path):
    write_fixture(str(tmp_path), 1)
    lib = SysfsTpuLib(str(tmp_path))
    post_event(str(tmp_path), 48, "accel0", "first")
    post_event(str(tmp_path), 63, None, "second")
    e1 = lib.wait_for_event(1.0)
    assert (e1.code, e1.device, e1.message) == (48, "accel0", "first")
    e2 = lib.wait_for_event(1.0)
    assert (e2.code, e2.device) == (63, None)
    assert lib.wait_for_event(0.1) is None


def test_bad_chip_name_rejected(tmp_path):
    write_fixture(str(tmp_path), 1)
    lib = SysfsTpuLib(str(tmp_path))
    with pytest.raises(ValueError):
        lib.chip_info("nvidia0")


def test_model_attr_through_interface(tmp_path):
    """model() is part of the TpuLib seam (metrics labels consume it), not
    a private-attribute probe."""
    root = str(tmp_path)
    write_fixture(root, 1)
    lib = SysfsTpuLib(root)
    assert lib.model("accel0") == "tpu"  # fixture writes no model attr
    with open(
        os.path.join(root, "sys/class/accel/accel0/device/model"), "w"
    ) as f:
        f.write("tpu-v5e\n")
    assert lib.model("accel0") == "tpu-v5e"

    from container_engine_accelerators_tpu.tpulib.types import TpuLib

    assert TpuLib().model("accel0") == "tpu"  # interface default
