"""Fleet telemetry: windowed-rate export, exemplar round trip, SLOs.

The ISSUE 5 acceptance surface:

- e2e HTTP scrape: `agent_rate` / `agent_goodput` / `agent_gauge`
  appear on the real endpoint, decay to zero when traffic stops, and
  survive the MetricServer's periodic `_reset`;
- exemplar round trip: force a slow op, scrape `agent_exemplar`, and
  `cmd/agent_trace.py --exemplar` resolves the scraped id to the full
  trace tree;
- SLOs: a lossy-link fleet scenario that CONVERGES still fails its
  goodput SLO — the report carries an `slo` section and
  `cmd/fleet_sim.py` exits 3 on breach (2 stays non-convergence);
- `cmd/agent_top.py --once` renders rates/goodput/p99/SLO status
  against a live MetricServer.
"""

import importlib.util
import json
import os
import re
import sys
import time

import pytest
from prometheus_client import CollectorRegistry

from container_engine_accelerators_tpu.fleet.controller import run_scenario
from container_engine_accelerators_tpu.fleet.telemetry import (
    FleetTelemetry,
    parse_slo_spec,
)
from container_engine_accelerators_tpu.metrics import counters
from container_engine_accelerators_tpu.metrics.metrics import MetricServer
from container_engine_accelerators_tpu.obs import histo, timeseries, trace
from container_engine_accelerators_tpu.utils.retry import RetryPolicy

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

FAST_BIND = RetryPolicy(max_attempts=8, initial_backoff_s=0.05,
                        max_backoff_s=0.2, deadline_s=10.0)


@pytest.fixture(autouse=True)
def clean_telemetry():
    timeseries.reset()
    trace.reset()
    yield
    timeseries.reset()
    trace.reset()


class _NoChips:
    def collect_tpu_device(self, name):  # pragma: no cover
        raise RuntimeError("no chips")

    def devices(self):
        return []

    def model(self, name):  # pragma: no cover
        return "none"


def _server(tmp_path):
    return MetricServer(
        collector=_NoChips(),
        registry=CollectorRegistry(),
        pod_resources_socket=str(tmp_path / "missing.sock"),
        port=0,
        collection_interval_s=3600,
    )


def _scrape(port):
    import urllib.request

    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}/metrics", timeout=10
    ) as resp:
        return resp.read().decode()


def _load_cli(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "cmd", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------------------
# e2e scrape: rates / goodput / gauges
# ---------------------------------------------------------------------------


class TestRateScrape:
    def test_rates_goodput_gauges_end_to_end(self, tmp_path):
        counters.inc("e2e.rate.marker", 50)
        timeseries.record("goodput.link.n0->n1", 8192)
        timeseries.record("goodput.flow.r0.a.b", 4096)
        timeseries.gauge("dcn.chunks.inflight", 3)
        server = _server(tmp_path)
        server.start(retry=FAST_BIND)
        try:
            server.collect_once()
            body = _scrape(server.port)
            rate = self._sample(body, "agent_rate",
                                'event="e2e.rate.marker"')
            assert rate is not None and rate > 0
            link = self._sample(body, "agent_goodput",
                                'name="n0->n1",scope="link"')
            assert link is not None and link > 0
            flow = self._sample(body, "agent_goodput",
                                'name="r0.a.b",scope="flow"')
            assert flow is not None and flow > 0
            assert self._sample(body, "agent_gauge",
                                'name="dcn.chunks.inflight"') == 3.0

            # Decay: a series whose last traffic fell out of the window
            # exports an explicit 0.0 — a stopped flow scrapes as zero,
            # it does not vanish.
            timeseries.record("goodput.link.idle->idle", 999,
                              now=time.monotonic() - 60)
            server.collect_once()
            body = _scrape(server.port)
            assert self._sample(body, "agent_goodput",
                                'name="idle->idle",scope="link"') == 0.0

            # Survive the periodic registry reset: wholesale republish.
            server._last_reset -= 2 * 60
            server.collect_once()
            body = _scrape(server.port)
            rate2 = self._sample(body, "agent_rate",
                                 'event="e2e.rate.marker"')
            assert rate2 is not None and rate2 > 0
            assert self._sample(body, "agent_gauge",
                                'name="dcn.chunks.inflight"') == 3.0
        finally:
            server.stop()

    @staticmethod
    def _sample(body, family, labels):
        m = re.search(rf"^{family}\{{{re.escape(labels)}\}} (\S+)$",
                      body, re.M)
        return float(m.group(1)) if m else None


# ---------------------------------------------------------------------------
# exemplar round trip: slow op -> scrape -> agent_trace --exemplar
# ---------------------------------------------------------------------------


class TestExemplarRoundTrip:
    def test_scraped_exemplar_resolves_to_trace_tree(self, tmp_path,
                                                     capsys):
        histo.reset()
        jsonl = str(tmp_path / "trace.jsonl")
        trace.configure(jsonl)
        with trace.span("slow.op", histogram="slow.op", who="outer"):
            with trace.span("slow.inner"):
                time.sleep(0.03)
        with trace.span("slow.op", histogram="slow.op", who="fast"):
            pass
        trace.configure(None)  # flush before the CLI reads it

        server = _server(tmp_path)
        server.start(retry=FAST_BIND)
        try:
            server.collect_once()
            body = _scrape(server.port)
        finally:
            server.stop()
        rows = re.findall(
            r'agent_exemplar\{bucket="(\d+)",op="slow\.op",'
            r'trace="([0-9a-f]+)"\} (\S+)', body)
        assert rows, f"no exemplar rows in scrape:\n{body[:2000]}"
        worst_trace = max(rows, key=lambda r: float(r[2]))[1]

        at = _load_cli("agent_trace")
        at.main([jsonl, "--exemplar", "slow.op"])
        out = capsys.readouterr()
        result = json.loads(out.out.strip().splitlines()[-1])
        # The CLI resolved the SAME trace the scrape named: metric ->
        # trace in one hop.
        assert result["trace"] == worst_trace
        assert result["spans"] == 2
        assert "slow.inner" in out.err  # the tree, not just the id

    def test_exemplar_accepts_scraped_trace_id_directly(self, tmp_path,
                                                        capsys):
        jsonl = str(tmp_path / "t.jsonl")
        trace.configure(jsonl)
        with trace.span("an.op") as s:
            pass
        trace.configure(None)
        at = _load_cli("agent_trace")
        at.main([jsonl, "--exemplar", s.trace_id[:10]])  # prefix ok
        result = json.loads(capsys.readouterr().out.strip()
                            .splitlines()[-1])
        assert result["trace"] == s.trace_id

    def test_exemplar_miss_is_a_clear_error(self, tmp_path):
        jsonl = str(tmp_path / "t.jsonl")
        trace.configure(jsonl)
        with trace.span("an.op"):
            pass
        trace.configure(None)
        at = _load_cli("agent_trace")
        with pytest.raises(SystemExit, match="no span named"):
            at.main([jsonl, "--exemplar", "no.such.op"])


# ---------------------------------------------------------------------------
# SLOs: spec parsing, evaluation, the converges-but-breaches scenario
# ---------------------------------------------------------------------------


class TestSloSpec:
    def test_known_keys_parse(self):
        spec = parse_slo_spec({"p99_leg_ms": "250",
                               "min_goodput_bps": 1024})
        assert spec == {"p99_leg_ms": 250.0, "min_goodput_bps": 1024.0}

    def test_unknown_and_malformed_keys_skip_not_crash(self):
        spec = parse_slo_spec({"p99_leg_ms": 10, "not_an_slo": 5,
                               "min_goodput_bps": "lots"})
        assert spec == {"p99_leg_ms": 10.0}

    def test_empty_spec_is_vacuously_ok(self):
        t = FleetTelemetry({}, _FakeLinks({}), None)
        section = t.evaluate({})
        assert section["ok"] is True and section["checks"] == []

    def test_non_mapping_slo_section_degrades_not_crashes(self):
        # YAML authoring typo: `slo: [p99_leg_ms]` — costs the SLOs,
        # never the run (the TPU_FAULT_SPEC rule).
        assert parse_slo_spec(["p99_leg_ms"]) == {}
        assert parse_slo_spec("p99_leg_ms=5") == {}

    def test_empty_pipelined_payload_never_divides_by_zero(self):
        # The retransmit-ratio gauge divides by the chunk count; an
        # empty payload must short-circuit before the round loop.
        from container_engine_accelerators_tpu.parallel import (
            dcn_pipeline,
        )

        out = dcn_pipeline.send_pipelined(None, "f", b"", "127.0.0.1", 1)
        assert out == {"bytes": 0, "chunks": 0, "stripes": 0,
                       "rounds": 0, "lane": "none"}


class _FakeLinks:
    def __init__(self, report):
        self._report = report

    def report(self):
        return self._report


class TestSloEvaluation:
    def test_floor_and_ceiling_verdicts_and_gauges(self):
        histo.reset()
        links = {"a->b": {"bytes": 1 << 20, "frames": 10, "drops": 4,
                          "dups": 1, "blocked": 0}}
        t = FleetTelemetry({}, _FakeLinks(links), {
            "min_goodput_bps": 1e12,        # unreachable floor: breach
            "max_retransmit_ratio": 0.49,   # (4+1)/10 = 0.5: breach
            "max_dedup_ratio": 0.2,         # 1/10 = 0.1: ok
        })
        section = t.evaluate(links)
        by_key = {c["slo"]: c for c in section["checks"]}
        assert not section["ok"]
        assert not by_key["min_goodput_bps"]["ok"]
        assert not by_key["max_retransmit_ratio"]["ok"]
        assert by_key["max_dedup_ratio"]["ok"]
        # Verdicts are live gauges for agent_top / flight recorder.
        gauges = timeseries.gauges()
        assert gauges["slo.min_goodput_bps.ok"] == 0.0
        assert gauges["slo.max_dedup_ratio.ok"] == 1.0
        assert gauges["slo.max_retransmit_ratio.value"] == \
            pytest.approx(0.5)

    def test_p99_ceiling_reads_leg_histogram(self):
        histo.reset()
        t = FleetTelemetry({}, _FakeLinks({}), {"p99_leg_ms": 100})
        histo.observe("fleet.leg", 0.2)  # le bucket 262144us ≈ 262ms
        assert t.evaluate({})["ok"] is False
        t2 = FleetTelemetry({}, _FakeLinks({}), {"p99_leg_ms": 1000})
        histo.observe("fleet.leg", 0.2)
        assert t2.evaluate({})["ok"] is True

    def test_p99_judges_this_run_only(self):
        """Histograms are process-global; a previous scenario's slow
        legs must not breach (or mask) THIS run's p99 SLO — the
        aggregator baselines the buckets at boot, like the controller
        baselines counters."""
        histo.reset()
        histo.observe("fleet.leg", 5.0)  # an earlier run's disaster
        t = FleetTelemetry({}, _FakeLinks({}), {"p99_leg_ms": 100})
        histo.observe("fleet.leg", 0.00005)  # this run: 50µs legs
        section = t.evaluate({})
        assert section["ok"] is True, section
        assert section["measured"]["p99_leg_ms"] < 1
        # And with NO legs this run at all, p99 reads 0, not the past.
        t2 = FleetTelemetry({}, _FakeLinks({}), {"p99_leg_ms": 100})
        assert t2.evaluate({})["measured"]["p99_leg_ms"] == 0.0


class TestFleetSlo:
    """Scenario-level: converged is necessary but no longer sufficient."""

    LOSSY = {
        "name": "lossy-but-alive",
        "nodes": 2,
        "racks": 1,
        "chips": 2,
        "topology": "1x2x1",
        "rounds": 3,
        "payload_bytes": 2048,
        "land_timeout_s": 0.4,
        "faults": [
            {"round": 1, "link": "node:n0->node:n1:drop:1"},
        ],
    }

    def test_lossy_scenario_converges_but_breaches_goodput_slo(self):
        scenario = dict(self.LOSSY,
                        slo={"min_goodput_bps": 1e12,
                             "max_dedup_ratio": 1.0})
        report = run_scenario(scenario)
        assert report["converged"], report["rounds"][-1]
        assert report["links"]["n0->n1"]["drops"] >= 1
        slo = report["slo"]
        assert slo["ok"] is False
        breached = {c["slo"] for c in slo["checks"] if not c["ok"]}
        assert "min_goodput_bps" in breached
        # The same scenario under an honest floor passes.
        timeseries.reset()
        report2 = run_scenario(dict(self.LOSSY,
                                    slo={"min_goodput_bps": 1.0}))
        assert report2["converged"] and report2["slo"]["ok"]

    def test_report_carries_telemetry_rounds(self):
        report = run_scenario(dict(self.LOSSY, faults=[]))
        rounds = report["telemetry"]["rounds"]
        assert len(rounds) == self.LOSSY["rounds"]
        last = rounds[-1]
        assert set(last["nodes"]) == {"n0", "n1"}
        assert any(v > 0 for v in last["links_goodput_bps"].values())
        assert all(n["goodput_bps"] >= 0 for n in last["nodes"].values())

    def test_fleet_sim_exits_3_on_slo_breach(self, tmp_path, capsys):
        path = str(tmp_path / "lossy.json")
        with open(path, "w") as f:
            json.dump(dict(self.LOSSY, faults=[]), f)
        fs = _load_cli("fleet_sim")
        rc = fs.main(["--scenario", path,
                      "--slo", "min_goodput_bps=1e12"])
        assert rc == 3
        out = capsys.readouterr()
        assert json.loads(out.out.strip().splitlines()[-1])["slo"][
            "ok"] is False
        assert "FAIL" in out.err  # the SLO table names the breach
        # And with a sane floor the same scenario exits 0.
        timeseries.reset()
        rc = fs.main(["--scenario", path, "--slo", "min_goodput_bps=1"])
        assert rc == 0

    def test_fleet_sim_rejects_typoed_slo_key(self, capsys):
        """An operator-typed --slo is an explicit CI gate: a typo'd
        key must fail the invocation, never silently evaluate zero
        checks and exit 0."""
        fs = _load_cli("fleet_sim")
        rc = fs.main(["--slo", "min_goodput=64"])  # missing _bps
        assert rc == 2
        assert "min_goodput_bps" in capsys.readouterr().err


# ---------------------------------------------------------------------------
# agent_top --once against a live MetricServer
# ---------------------------------------------------------------------------


class TestAgentTop:
    def test_once_renders_rates_goodput_p99_and_slo(self, tmp_path,
                                                    capsys):
        histo.reset()
        counters.inc("top.rate.marker", 9)
        timeseries.record("goodput.link.n0->n1", 4 << 20)
        timeseries.gauge("slo.min_goodput_bps.ok", 0.0)
        timeseries.gauge("slo.min_goodput_bps.value", 17.0)
        timeseries.gauge("dcn.stripes.active", 2)
        for _ in range(3):
            with trace.span("dcn.send", histogram="dcn.send"):
                pass
        server = _server(tmp_path)
        server.start(retry=FAST_BIND)
        try:
            server.collect_once()
            top = _load_cli("agent_top")
            rc = top.main(["--port", str(server.port), "--once"])
        finally:
            server.stop()
        assert rc == 0
        out = capsys.readouterr().out
        assert "top.rate.marker" in out          # rates
        assert "n0->n1" in out                   # goodput
        assert "dcn.send" in out and "p99_us" in out  # latency
        assert "BREACH" in out                   # SLO status rendered
        assert "dcn.stripes.active" in out       # gauges

    def test_once_fails_cleanly_without_server(self, capsys):
        top = _load_cli("agent_top")
        rc = top.main(["--port", "1", "--once"])  # nothing listens there
        assert rc == 1
        assert "failed" in capsys.readouterr().err

    def test_percentiles_from_cumulative_buckets(self):
        top = _load_cli("agent_top")
        buckets = {128: 99, 1 << 20: 100}  # cumulative le counts
        assert top.percentile_from_buckets(buckets, 100, 0.5) == 128
        assert top.percentile_from_buckets(buckets, 100, 0.99) == 128
        assert top.percentile_from_buckets(buckets, 100, 1.0) == 1 << 20
        assert top.percentile_from_buckets({}, 0, 0.5) == 0.0

    def test_demo_mode_is_self_contained(self, capsys):
        top = _load_cli("agent_top")
        assert top.main(["--demo", "--once"]) == 0
        out = capsys.readouterr().out
        assert "goodput" in out and "SLO status" in out

    def test_hotspot_panel_from_profile_scrape(self, tmp_path,
                                               capsys):
        """ISSUE 14 satellite: the hotspot panel — top subsystems by
        sample share from the same server's /profile endpoint, idle
        split out so a parked pool never drowns the busy share."""
        from container_engine_accelerators_tpu.obs import profiler

        profiler.reset()
        profiler.ingest("a.stage;b.copy", "shm-staging", 30)
        profiler.ingest("a.send;b.sock", "xferd", 10)
        profiler.ingest("park.ed", "idle", 60)
        counters.inc("top.prof.marker")
        server = _server(tmp_path)
        server.start(retry=FAST_BIND)
        try:
            server.collect_once()
            top = _load_cli("agent_top")
            rc = top.main(["--port", str(server.port), "--once"])
        finally:
            server.stop()
            profiler.reset()
        assert rc == 0
        out = capsys.readouterr().out
        assert "hotspot (cpu sample share)" in out
        shm_line = next(l for l in out.splitlines()
                        if l.startswith("shm-staging"))
        assert "75.0%" in shm_line  # 30 of 40 busy samples
        assert "(idle threads)" in out

    def test_hotspot_panel_absent_without_profile(self, tmp_path,
                                                  capsys):
        """An agent without /profile samples (or an unreachable
        endpoint) costs the panel, never the screen."""
        from container_engine_accelerators_tpu.obs import profiler

        profiler.reset()
        counters.inc("top.noprof.marker")
        server = _server(tmp_path)
        server.start(retry=FAST_BIND)
        try:
            server.collect_once()
            top = _load_cli("agent_top")
            rc = top.main(["--port", str(server.port), "--once"])
        finally:
            server.stop()
        assert rc == 0
        assert "hotspot" not in capsys.readouterr().out

    def test_demo_seeds_hotspot_panel(self, capsys):
        from container_engine_accelerators_tpu.obs import profiler

        profiler.reset()
        top = _load_cli("agent_top")
        try:
            assert top.main(["--demo", "--once"]) == 0
        finally:
            profiler.reset()
        out = capsys.readouterr().out
        assert "hotspot (cpu sample share)" in out
        assert "shm-staging" in out


class TestProfileReport:
    def test_report_merges_local_profiler_as_coordinator(self):
        """In the one-process rig the coordinator's sampler IS the
        fleet's: profile_report folds its run-delta in under the
        `coordinator` key, baselined at telemetry boot so a previous
        run's samples never leak in."""
        from container_engine_accelerators_tpu.obs import profiler

        profiler.reset()
        profiler.ingest("stale.run", "other", 7)  # pre-boot history
        t = FleetTelemetry({}, None, None, scrape=False)
        profiler.ingest("this.run;hot.code", "dcn_pipeline", 5)
        try:
            report = t.profile_report()
            coord = report["nodes"]["coordinator"]
            assert coord["samples"] == 5  # delta, not 12
            assert [e["stack"] for e in coord["top"]] \
                == ["this.run;hot.code"]
            assert report["fleet"]["samples"] == 5
            assert report["fleet"]["subsystems"] \
                == {"dcn_pipeline": 5}
        finally:
            profiler.reset()

    def test_empty_report_shape(self):
        from container_engine_accelerators_tpu.obs import profiler

        profiler.reset()
        t = FleetTelemetry({}, None, None, scrape=True)
        report = t.profile_report()
        assert report == {"nodes": {},
                          "fleet": {"samples": 0, "dropped": 0,
                                    "subsystems": {}, "top": []}}


class TestLearnedSloLimits:
    """ISSUE 17: history-learned SLO limits overlay the pinned spec
    tighten-only — a ceiling may come down toward the fleet's
    demonstrated baseline, never up past the scenario's pinned
    limit."""

    def test_learned_ceiling_tightens_and_is_labeled(self):
        histo.reset()
        learned = {"p99_leg_ms": {"limit": 100.0, "source": "learned",
                                  "n": 5}}
        t = FleetTelemetry({}, _FakeLinks({}), {"p99_leg_ms": 1000},
                           learned_slo=learned)
        histo.observe("fleet.leg", 0.2)  # ~262ms: inside pinned,
        section = t.evaluate({})         # outside learned
        assert section["ok"] is False
        (check,) = [c for c in section["checks"]
                    if c["slo"] == "p99_leg_ms"]
        assert check["limit"] == 100.0
        assert check["limit_source"] == "learned"
        assert check["pinned_limit"] == 1000.0
        assert check["history_n"] == 5

    def test_learned_never_relaxes_a_ceiling(self):
        histo.reset()
        learned = {"p99_leg_ms": {"limit": 5000.0,
                                  "source": "learned", "n": 8}}
        t = FleetTelemetry({}, _FakeLinks({}), {"p99_leg_ms": 1000},
                           learned_slo=learned)
        histo.observe("fleet.leg", 0.2)
        (check,) = [c for c in t.evaluate({})["checks"]
                    if c["slo"] == "p99_leg_ms"]
        assert check["limit"] == 1000.0
        assert "limit_source" not in check

    def test_pinned_fallback_entries_are_ignored(self):
        histo.reset()
        learned = {"p99_leg_ms": {"limit": 1.0, "source": "pinned",
                                  "n": 1}}
        t = FleetTelemetry({}, _FakeLinks({}), {"p99_leg_ms": 1000},
                           learned_slo=learned)
        histo.observe("fleet.leg", 0.2)
        (check,) = [c for c in t.evaluate({})["checks"]
                    if c["slo"] == "p99_leg_ms"]
        assert check["limit"] == 1000.0 and check["ok"]
