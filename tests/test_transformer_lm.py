"""Transformer LM tests: the dense model trains, and the
sequence-parallel (ring / Ulysses) step matches the dense step's loss
and gradients — proving the long-context path is numerically the same
model, just sharded along the sequence."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from container_engine_accelerators_tpu.models.lm_train import (
    create_lm_train_state,
    make_lm_train_step,
    next_token_targets,
    prepare_seq_parallel_batch,
)
from container_engine_accelerators_tpu.models.transformer import (
    transformer_lm,
)
from container_engine_accelerators_tpu.parallel import create_mesh

VOCAB, B, T = 97, 4, 32  # batch divisible by the 4-way data axis
CFG = dict(
    vocab_size=VOCAB,
    num_layers=2,
    num_heads=4,
    head_dim=8,
    mlp_dim=64,
    dtype=jnp.float32,  # f32 so dense vs sharded comparisons are tight
)


@pytest.fixture(scope="module")
def tokens():
    rng = np.random.default_rng(0)
    return jnp.asarray(rng.integers(0, VOCAB, (B, T)), jnp.int32)


def _state(model, tokens):
    return create_lm_train_state(
        model, jax.random.PRNGKey(0), tokens,
        tx=optax.sgd(0.1),  # plain SGD keeps the update linear in grads
    )


def test_dense_lm_trains(tokens):
    mesh = create_mesh(data=4, model=2)
    model = transformer_lm(**CFG)
    state = _state(model, tokens)
    step_fn, placed = make_lm_train_step(mesh, state)
    labels, mask = next_token_targets(tokens)
    losses = []
    s = placed
    for _ in range(5):
        s, m = step_fn(s, tokens, labels, mask)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0]  # it learns the synthetic batch
    assert int(jax.device_get(s.step)) == 5


@pytest.mark.parametrize("kv_heads", [None, 2])
@pytest.mark.parametrize("kind", ["ring", "ulysses", "ring-zigzag"])
def test_seq_parallel_matches_dense(tokens, kind, kv_heads):
    """kv_heads=2 additionally pins GQA under every sequence-parallel
    scheme: the K/V broadcast happens before the ring/all-to-all
    machinery, and the post-step param comparison covers its backward
    (query-head grads summing into the shared K/V projections)."""
    CFG = dict(globals()["CFG"], num_kv_heads=kv_heads)
    mesh = create_mesh(data=4, model=2)
    labels, mask = next_token_targets(tokens)

    dense_model = transformer_lm(**CFG)
    dense_state = _state(dense_model, tokens)
    dense_step, dense_placed = make_lm_train_step(mesh, dense_state)
    d_state, d_metrics = dense_step(dense_placed, tokens, labels, mask)

    sp_model = transformer_lm(**CFG, seq_parallel=kind)
    sp_state = _state(sp_model, tokens)
    sp_step, sp_placed = make_lm_train_step(mesh, sp_state,
                                            seq_parallel=kind)
    sp_toks, sp_labels, sp_mask = prepare_seq_parallel_batch(
        tokens, kind, n_shards=4
    )
    s_state, s_metrics = sp_step(sp_placed, sp_toks, sp_labels, sp_mask)

    np.testing.assert_allclose(
        float(s_metrics["loss"]), float(d_metrics["loss"]),
        atol=1e-5, rtol=1e-5,
    )
    # Post-SGD-step params equal ⇔ gradients equal.
    for a, b in zip(
        jax.tree_util.tree_leaves(jax.device_get(d_state.params)),
        jax.tree_util.tree_leaves(jax.device_get(s_state.params)),
    ):
        np.testing.assert_allclose(a, b, atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("kind", ["ring", "ulysses"])
def test_seq_parallel_matches_dense_bf16(tokens, kind):
    """Production dtype: softmax statistics run in f32 inside every
    scheme, so bf16 models agree across dense/ring/ulysses too (looser
    tolerance — the matmul inputs are still bf16)."""
    cfg = dict(CFG, dtype=jnp.bfloat16)
    mesh = create_mesh(data=4, model=2)
    labels, mask = next_token_targets(tokens)

    dense_state = _state(transformer_lm(**cfg), tokens)
    dense_step, dense_placed = make_lm_train_step(mesh, dense_state)
    _, d_metrics = dense_step(dense_placed, tokens, labels, mask)

    sp_state = _state(transformer_lm(**cfg, seq_parallel=kind), tokens)
    sp_step, sp_placed = make_lm_train_step(mesh, sp_state,
                                            seq_parallel=kind)
    _, s_metrics = sp_step(sp_placed, tokens, labels, mask)

    np.testing.assert_allclose(
        float(s_metrics["loss"]), float(d_metrics["loss"]),
        atol=2e-2, rtol=2e-3,
    )


@pytest.mark.parametrize("extra", [{}, {"num_experts": 4},
                                   {"num_kv_heads": 2}],
                         ids=["dense", "moe", "gqa"])
def test_fsdp_matches_dp_and_shards_optimizer_state(tokens, extra):
    """param_sharding="fsdp": identical math to the replicated dp step
    (loss exact; post-step params exact for the dense case, within a
    small fraction of one update step for MoE/GQA — see below), with
    params AND optimizer buffers actually sharded over the data axis —
    the ZeRO memory claim, asserted on the placed shard sizes.
    Parametrized over MoE (expert weights are the big tensors the data
    rule shards) and GQA."""
    cfg = dict(CFG, **extra)
    mesh = create_mesh(data=4, model=2)
    labels, mask = next_token_targets(tokens)

    def adamw_state():
        # adamw, not the module default sgd: the ZeRO memory claim is
        # about the Adam moment buffers.
        return create_lm_train_state(
            transformer_lm(**cfg), jax.random.PRNGKey(0), tokens,
            tx=optax.adamw(1e-2),
        )

    dp_step, dp_placed = make_lm_train_step(mesh, adamw_state())
    d_state, d_metrics = dp_step(dp_placed, tokens, labels, mask)

    fs_step, fs_placed = make_lm_train_step(
        mesh, adamw_state(), param_sharding="fsdp",
    )
    f_state, f_metrics = fs_step(fs_placed, tokens, labels, mask)

    np.testing.assert_allclose(
        float(f_metrics["loss"]), float(d_metrics["loss"]),
        atol=1e-6, rtol=1e-6,
    )
    # Post-step params: the dense case is bit-stable at float precision
    # (the regression guard for fsdp placement bugs).  The MoE/GQA
    # einsum orders differ enough between layouts that Adam's
    # m/(sqrt(v)+eps) amplifies a single-ulp gradient-rounding
    # difference into ~1e-4 of update, so those compare at a fraction
    # of one lr=1e-2 step (the pre-update loss IS compared tightly).
    tol = 2e-6 if not extra else 1e-3
    for a, b in zip(
        jax.tree_util.tree_leaves(jax.device_get(d_state.params)),
        jax.tree_util.tree_leaves(jax.device_get(f_state.params)),
    ):
        np.testing.assert_allclose(a, b, atol=tol, rtol=tol)

    # The big tensors really live 1/(dp*tp) per chip, optimizer
    # moments included.
    def frac(leaf):
        return leaf.addressable_shards[0].data.size / leaf.size

    big_param_fracs = [
        frac(x) for x in jax.tree_util.tree_leaves(f_state.params)
        if x.size >= 4096
    ]
    big_opt_fracs = [
        frac(x) for x in jax.tree_util.tree_leaves(f_state.opt_state)
        if hasattr(x, "addressable_shards") and x.size >= 4096
    ]
    assert big_param_fracs and max(big_param_fracs) <= 1 / 8 + 1e-9
    assert big_opt_fracs and max(big_opt_fracs) <= 1 / 8 + 1e-9
    # ... where the megatron layout replicates along data (1/tp only).
    mg_fracs = [
        frac(x) for x in jax.tree_util.tree_leaves(d_state.params)
        if x.size >= 4096
    ]
    assert min(mg_fracs) >= 1 / 2 - 1e-9


def test_dense_mode_tensor_parallel_shards_params(tokens):
    """--model-par actually shards weights: dense-mode placement uses the
    Megatron-style rule, not full replication."""
    mesh = create_mesh(data=4, model=2)
    state = _state(transformer_lm(**CFG), tokens)
    _, placed = make_lm_train_step(mesh, state)
    specs = {
        str(leaf.sharding.spec)
        for leaf in jax.tree_util.tree_leaves(placed.params)
    }
    assert any("model" in s for s in specs), specs


def test_rotary_positions_are_global(tokens):
    """A sequence-parallel shard must rotate with global offsets: shifting
    the position base changes the logits (sanity check that positions
    actually matter and are threaded through)."""
    model = transformer_lm(**CFG)
    variables = model.init(jax.random.PRNGKey(0), tokens)
    a = model.apply(variables, tokens, jnp.arange(T))
    b = model.apply(variables, tokens, jnp.arange(T) + 7)
    assert not np.allclose(jax.device_get(a), jax.device_get(b))


@pytest.mark.slow
def test_lm_driver_ring_resume(tmp_path):
    """The real LM driver end-to-end with ring sequence parallelism,
    including checkpoint resume across two invocations."""
    import importlib.util
    import os

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "train_lm_main", os.path.join(repo, "cmd", "train_lm.py"))
    train_lm = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(train_lm)

    common = [
        "--vocab-size", "97", "--num-layers", "1", "--num-heads", "4",
        "--head-dim", "8", "--mlp-dim", "32", "--seq-len", "64",
        "--train-batch-size", "2", "--seq-parallel", "ring",
        "--steps-per-eval", "1",
        "--checkpoint-dir", str(tmp_path / "lm-ck"),
        "--checkpoint-interval", "2",
    ]
    train_lm.main(common + ["--train-steps", "2"])
    train_lm.main(common + ["--train-steps", "3"])

    from container_engine_accelerators_tpu.models.checkpoint import (
        TrainCheckpointer,
    )

    ck = TrainCheckpointer(str(tmp_path / "lm-ck"))
    assert ck.manager.latest_step() == 3
    ck.close()


def test_checkpoint_roundtrip_lm(tokens, tmp_path):
    """The LM state checkpoints through the same TrainCheckpointer."""
    from container_engine_accelerators_tpu.models.checkpoint import (
        TrainCheckpointer,
    )

    mesh = create_mesh(data=4, model=2)
    model = transformer_lm(**CFG)
    state = _state(model, tokens)
    step_fn, placed = make_lm_train_step(mesh, state)
    labels, mask = next_token_targets(tokens)
    placed, _ = step_fn(placed, tokens, labels, mask)

    ck = TrainCheckpointer(str(tmp_path / "lm"))
    ck.save(placed, wait=True)
    fresh = _state(model, tokens)
    _, fresh_placed = make_lm_train_step(mesh, fresh)
    restored, step = ck.restore_latest(fresh_placed)
    ck.close()
    assert step == 1
    for a, b in zip(
        jax.tree_util.tree_leaves(jax.device_get(placed.params)),
        jax.tree_util.tree_leaves(jax.device_get(restored.params)),
    ):
        np.testing.assert_array_equal(a, b)


def test_train_params_load_into_decode_model():
    """Train-then-serve contract: params from a train-mode (scanned)
    model must load directly into a decode-mode model — both modes share
    one param-tree layout (cache scans along the same layer axis)."""
    import optax

    from container_engine_accelerators_tpu.models.generate import generate
    from container_engine_accelerators_tpu.models.lm_train import (
        create_lm_train_state,
    )
    from container_engine_accelerators_tpu.models.transformer import (
        transformer_lm,
    )

    cfg = dict(vocab_size=64, num_layers=2, num_heads=2, head_dim=8,
               mlp_dim=32)
    train_model = transformer_lm(**cfg)
    toks = jnp.zeros((2, 8), jnp.int32)
    state = create_lm_train_state(
        train_model, jax.random.PRNGKey(0), toks, tx=optax.sgd(0.1)
    )
    out = generate(
        transformer_lm(**cfg, decode=True), state.params,
        jnp.ones((2, 3), jnp.int32), 4,
    )
    assert out.shape == (2, 7)
    assert bool(jnp.all(out[:, :3] == 1))  # prompt teacher-forced
