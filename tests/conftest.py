"""Test harness setup.

All tests run hardware-free, mirroring the reference's test strategy
(SURVEY.md §4): the hardware surface is a filesystem layout, so tests fake
it with tempdirs; JAX-level tests run on a virtual 8-device CPU mesh.
"""

import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

from container_engine_accelerators_tpu.utils.cpuenv import (  # noqa: E402
    cpu_mesh_env,
    in_tpu_harness,
)

# Tests need a virtual 8-device CPU mesh.  Under the axon TPU environment,
# sitecustomize pre-initializes JAX with the TPU backend before conftest
# runs, so env changes here are too late — re-exec the test process with
# the TPU plugin disabled and CPU forced.
if in_tpu_harness() and os.environ.get("CEA_TPU_TESTS") != "1":
    os.execve(
        sys.executable,
        [sys.executable, "-m", "pytest"] + sys.argv[1:],
        cpu_mesh_env(8),
    )

# Plain environments: set before jax is imported anywhere.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# NOTE: do NOT enable the persistent XLA compilation cache here.  On
# XLA:CPU, reloading AOT results intermittently trips machine-feature
# mismatches ("+prefer-no-scatter is not supported on the host") and
# then deadlocks multi-device collective rendezvous (fatal abort).
# Suite speed comes from structural test design instead: scanned layers,
# reduced block plans, shared train-step compiles.

import pytest  # noqa: E402


# Long-lived harness threads the leak gate must tolerate: pytest's own
# machinery, concurrent.futures pools parked by design (jax/XLA host
# callbacks), and foreign C threads surfacing as Dummy-*.  Everything
# the stack itself spawns is daemon= by decision (enforced by the
# thread-daemon lint rule), so a NON-daemon survivor here is a test
# bug: a worker someone forgot to join.
_THREAD_ALLOWLIST_PREFIXES = (
    "pytest",
    "Dummy-",
    "ThreadPoolExecutor",
    "asyncio_",
)


@pytest.fixture(autouse=True)
def _thread_leak_gate():
    """Fail any test that leaves a new non-daemon thread alive after
    teardown (with a short join grace for workers mid-wind-down).
    Daemon threads get a pass — they cannot wedge interpreter
    shutdown, and the suite's servers/daemons all use them."""
    import threading
    import time as _time

    before = set(threading.enumerate())
    yield

    def _leaked():
        return [
            t for t in threading.enumerate()
            if t not in before and t.is_alive() and not t.daemon
            and not t.name.startswith(_THREAD_ALLOWLIST_PREFIXES)
        ]

    deadline = _time.monotonic() + 2.0
    while _leaked() and _time.monotonic() < deadline:
        _time.sleep(0.02)
    left = _leaked()
    assert not left, (
        f"test leaked non-daemon thread(s): "
        f"{sorted(t.name for t in left)} — join them in teardown (or "
        f"mark an intentionally long-lived harness thread daemon=True)"
    )


@pytest.hookimpl(hookwrapper=True)
def pytest_runtest_protocol(item):
    """Per-test deadman switch.

    XLA:CPU multi-device collectives can (rarely) deadlock in their
    in-process rendezvous on small hosts — observed as a device_get
    blocked >15 min in a test that normally takes 7s.  The block is
    inside native code, so SIGALRM-style in-thread timeouts never fire;
    faulthandler's watchdog thread does: dump all stacks and hard-exit,
    turning an infinite CI hang into a bounded, diagnosable failure.
    """
    import faulthandler

    faulthandler.dump_traceback_later(600, exit=True)
    yield
    faulthandler.cancel_dump_traceback_later()


@pytest.fixture(scope="session")
def tiny_sharded():
    """Session-shared tiny-ResNet sharded train step on the 4x2 mesh.

    The dp x tp step compile (~20s on 8 virtual CPU devices) is the
    single most duplicated cost in the suite; test_models and
    test_checkpoint exercise the same program, so compile it once.
    Returns (mesh, model, x, y, step_fn, placed) — treat `placed` as
    immutable (every step returns a fresh state).
    """
    import jax
    import jax.numpy as jnp

    from container_engine_accelerators_tpu.models import resnet
    from container_engine_accelerators_tpu.models.train import (
        create_train_state,
        make_sharded_train_step,
    )
    from container_engine_accelerators_tpu.parallel import create_mesh

    mesh = create_mesh(data=4, model=2)
    model = resnet(depth=18, num_classes=10, num_filters=8,
                   small_inputs=True)
    x = jnp.ones((8, 32, 32, 3))
    y = jnp.zeros((8,), jnp.int32)
    state = create_train_state(model, jax.random.PRNGKey(1), x)
    step_fn, placed = make_sharded_train_step(mesh, state)
    # step_fn DONATES its state argument, so the one `placed` cannot be
    # shared across tests — each consumer gets a fresh copy on the same
    # shardings (the compile, not the placement, is the expensive part).
    # The template lives on the HOST: device_put can alias a device
    # array into the new placement, and donation would then delete the
    # template out from under the next caller (observed on the scalar
    # step leaf).  numpy leaves cannot be aliased or donated.
    shardings = jax.tree_util.tree_map(lambda a: a.sharding, placed)
    host_state = jax.device_get(state)
    del placed

    def fresh_placed():
        return jax.device_put(host_state, shardings)

    return mesh, model, x, y, step_fn, fresh_placed


@pytest.fixture
def fake_dev(tmp_path):
    """A fake /dev tree with TPU device nodes, like the reference's tempdir
    /dev fixtures (beta_plugin_test.go:244-263)."""
    dev = tmp_path / "dev"
    dev.mkdir()

    def make(*names):
        for n in names:
            (dev / n).touch()
        return str(dev)

    make("accel0", "accel1", "accel2", "accel3")
    return str(dev)


@pytest.fixture(autouse=True, scope="module")
def _drop_jax_executables_per_module():
    """Free compiled XLA executables at module boundaries.

    The suite grew past the point where one serial pytest process can
    hold every test's compiled graph: the 2026-07-31 full run died at
    90% with 'LLVM compilation error: Cannot allocate memory' ->
    SIGSEGV while compiling the spec-prefix composition.  Graphs are
    not shared across modules (each module builds its own shapes), so
    clearing per module caps memory at one module's worth for the
    cost of nothing but the yield."""
    yield
    # Only when jax was actually imported: a never-imported jax has no
    # caches, and node-daemon/YAML-only modules (runnable from the
    # jax-free requirements-node.txt env) must not gain the dependency.
    jx = sys.modules.get("jax")
    if jx is not None:
        jx.clear_caches()
