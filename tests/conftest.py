"""Test harness setup.

All tests run hardware-free, mirroring the reference's test strategy
(SURVEY.md §4): the hardware surface is a filesystem layout, so tests fake
it with tempdirs; JAX-level tests run on a virtual 8-device CPU mesh.
"""

import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

from container_engine_accelerators_tpu.utils.cpuenv import (  # noqa: E402
    cpu_mesh_env,
    in_tpu_harness,
)

# Tests need a virtual 8-device CPU mesh.  Under the axon TPU environment,
# sitecustomize pre-initializes JAX with the TPU backend before conftest
# runs, so env changes here are too late — re-exec the test process with
# the TPU plugin disabled and CPU forced.
if in_tpu_harness() and os.environ.get("CEA_TPU_TESTS") != "1":
    os.execve(
        sys.executable,
        [sys.executable, "-m", "pytest"] + sys.argv[1:],
        cpu_mesh_env(8),
    )

# Plain environments: set before jax is imported anywhere.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import pytest  # noqa: E402


@pytest.fixture
def fake_dev(tmp_path):
    """A fake /dev tree with TPU device nodes, like the reference's tempdir
    /dev fixtures (beta_plugin_test.go:244-263)."""
    dev = tmp_path / "dev"
    dev.mkdir()

    def make(*names):
        for n in names:
            (dev / n).touch()
        return str(dev)

    make("accel0", "accel1", "accel2", "accel3")
    return str(dev)
