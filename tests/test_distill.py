"""Draft distillation end to end (cmd/make_distill_data.py).

The claim worth testing is BEHAVIORAL: a draft trained on the target's
own samples must predict the target better than an untrained draft —
measured where it matters, as the speculative decoder's acceptance
rate.  The pipeline under test is the real composition: train target
-> sample corpus -> train draft on the shards -> speculate.
"""

import importlib.util
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

TARGET = ["--num-layers", "2", "--num-heads", "2", "--head-dim", "8",
          "--mlp-dim", "64", "--vocab-size", "32"]
DRAFT = ["--num-layers", "1", "--num-heads", "2", "--head-dim", "8",
         "--mlp-dim", "32", "--vocab-size", "32"]


def _load(name, rel):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, rel))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _accept_rate(target_params, draft_cfg, draft_params, prompts):
    from container_engine_accelerators_tpu.models.speculative import (
        generate_speculative,
    )
    from container_engine_accelerators_tpu.models.transformer import (
        transformer_lm,
    )

    model = transformer_lm(vocab_size=32, num_layers=2, num_heads=2,
                           head_dim=8, mlp_dim=64, decode=True)
    draft = transformer_lm(**draft_cfg, decode=True)
    _, stats = generate_speculative(
        model, target_params, draft, draft_params, prompts, 32, k=4)
    return float(stats["accepted"].sum()) / max(
        float(stats["drafted"].sum()), 1.0)


@pytest.mark.slow
def test_distilled_draft_beats_random_acceptance(tmp_path):
    import optax

    from container_engine_accelerators_tpu.models.checkpoint import (
        TrainCheckpointer,
    )
    from container_engine_accelerators_tpu.models.lm_train import (
        create_lm_train_state,
    )
    from container_engine_accelerators_tpu.models.transformer import (
        transformer_lm,
    )

    # 1. Train a target long enough to have structure (synthetic data
    #    still induces strong low-entropy continuations at tiny vocab).
    train = _load("train_lm_distill_t", "cmd/train_lm.py")
    train.main(TARGET + [
        "--seq-len", "32", "--train-batch-size", "16",
        "--train-steps", "30", "--steps-per-eval", "10",
        "--checkpoint-dir", str(tmp_path / "target_ck"),
        "--checkpoint-interval", "30",
    ])

    # 2. Sample a distillation corpus from it.
    mk = _load("make_distill_data", "cmd/make_distill_data.py")
    mk.main(TARGET + [
        "--checkpoint-dir", str(tmp_path / "target_ck"),
        "--out", str(tmp_path / "corpus"),
        "--tokens", "40000", "--batch", "16",
        "--prompt-len", "4", "--gen-len", "28",
    ])

    # 3. Train the draft on the corpus.
    train2 = _load("train_lm_distill_d", "cmd/train_lm.py")
    train2.main(DRAFT + [
        "--seq-len", "32", "--train-batch-size", "16",
        "--train-steps", "60", "--steps-per-eval", "20",
        "--data-dir", str(tmp_path / "corpus"),
        "--checkpoint-dir", str(tmp_path / "draft_ck"),
        "--checkpoint-interval", "60",
    ])

    # 4. Acceptance rates on the REAL speculative decoder.
    d_cfg = dict(vocab_size=32, num_layers=1, num_heads=2, head_dim=8,
                 mlp_dim=32)
    t_state = create_lm_train_state(
        transformer_lm(vocab_size=32, num_layers=2, num_heads=2,
                       head_dim=8, mlp_dim=64),
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32),
        tx=optax.adamw(3e-4, weight_decay=0.1))
    ck = TrainCheckpointer(str(tmp_path / "target_ck"))
    t_state, step = ck.restore_latest(t_state)
    ck.close()
    assert step is not None

    def draft_params(ckpt=None, seed=123):
        st = create_lm_train_state(
            transformer_lm(**d_cfg), jax.random.PRNGKey(seed),
            jnp.zeros((1, 8), jnp.int32),
            tx=optax.adamw(3e-4, weight_decay=0.1))
        if ckpt:
            c = TrainCheckpointer(ckpt)
            st, got = c.restore_latest(st)
            c.close()
            assert got is not None
        return st.params

    prompts = jnp.asarray(
        np.random.default_rng(9).integers(0, 32, (4, 4)), jnp.int32)
    distilled = _accept_rate(t_state.params, d_cfg,
                             draft_params(str(tmp_path / "draft_ck")),
                             prompts)
    random_init = _accept_rate(t_state.params, d_cfg, draft_params(),
                               prompts)
    # The margin is the whole point; on repeated runs distilled lands
    # far above the random draft (which hovers near 1/vocab).
    assert distilled > random_init + 0.1, (distilled, random_init)


def test_make_distill_data_refuses_missing_checkpoint(tmp_path):
    mk = _load("make_distill_data2", "cmd/make_distill_data.py")
    os.makedirs(tmp_path / "empty_ck", exist_ok=True)
    with pytest.raises(SystemExit, match="no checkpoint"):
        mk.main(TARGET + [
            "--checkpoint-dir", str(tmp_path / "empty_ck"),
            "--out", str(tmp_path / "c"), "--tokens", "100",
        ])


def test_make_distill_data_refuses_populated_out(tmp_path):
    from container_engine_accelerators_tpu.data.tokens import (
        write_token_shards,
    )

    write_token_shards(str(tmp_path / "c"), [np.asarray([1, 2], np.uint32)])
    mk = _load("make_distill_data3", "cmd/make_distill_data.py")
    with pytest.raises(SystemExit, match="refusing to mix"):
        mk.main(TARGET + [
            "--checkpoint-dir", str(tmp_path / "whatever"),
            "--out", str(tmp_path / "c"), "--tokens", "100",
        ])
