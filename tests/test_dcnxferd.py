"""dcnxferd daemon tests: spawn the real native binary, drive the real
UDS protocol (the role nccl-test pods play against tcpgpudmarxd)."""

import os
import signal
import socket
import subprocess
import time

import pytest

from container_engine_accelerators_tpu.parallel.dcn_client import (
    DcnXferClient,
    DcnXferError,
)

BIN = os.path.join(os.path.dirname(__file__), "..",
                   "native", "dcnxferd", "build", "dcnxferd")
# Sanitizer builds point DCNXFERD_BIN at the instrumented binary
# (make test-asan), the `go test -race` analog for our native surface.
BIN = os.environ.get("DCNXFERD_BIN", BIN)

pytestmark = pytest.mark.skipif(
    not os.path.exists(BIN), reason="dcnxferd not built (run `make native`)"
)


@pytest.fixture
def daemon(tmp_path):
    uds = str(tmp_path / "tpu-dcn")
    proc = subprocess.Popen(
        [BIN, "--uds_path", uds, "--pool_bytes", str(8 << 20),
         "--max_flows", "4", "--verbose", "2"],
        stderr=subprocess.PIPE, text=True,
    )
    sock_path = os.path.join(uds, "xferd.sock")
    deadline = time.time() + 10
    while not os.path.exists(sock_path):
        assert proc.poll() is None, proc.stderr.read()
        assert time.time() < deadline, "daemon never created its socket"
        time.sleep(0.02)
    yield uds
    proc.send_signal(signal.SIGTERM)
    proc.wait(timeout=10)


def test_version_and_ping(daemon):
    with DcnXferClient(daemon) as c:
        assert c.version() == "dcnxferd/1.2"
        c.ping()


def test_register_transfer_release_flow(daemon):
    with DcnXferClient(daemon) as c:
        resp = c.register_flow("g0", peer="slice1-h0", bytes=1 << 20)
        assert resp["buffer_bytes"] >= 1 << 20
        assert c.record_transfer("g0", 4096) == 4096
        assert c.record_transfer("g0", 4096) == 8192
        stats = c.stats()
        assert stats["active_flows"] == 1
        assert stats["total_transferred"] == 8192
        assert stats["flows"][0]["peer"] == "slice1-h0"
        c.release_flow("g0")
        assert c.stats()["active_flows"] == 0
        assert c.stats()["pool_used"] == 0


def test_pool_exhaustion_and_duplicate_flow(daemon):
    with DcnXferClient(daemon) as c:
        c.register_flow("big", bytes=6 << 20)
        with pytest.raises(DcnXferError, match="pool exhausted"):
            c.register_flow("too-big", bytes=4 << 20)
        with pytest.raises(DcnXferError, match="already exists"):
            c.register_flow("big")
        # Released memory is reusable.
        c.release_flow("big")
        c.register_flow("big2", bytes=6 << 20)


def test_max_flows(daemon):
    with DcnXferClient(daemon) as c:
        for i in range(4):
            c.register_flow(f"f{i}", bytes=4096)
        with pytest.raises(DcnXferError, match="max flows"):
            c.register_flow("f4", bytes=4096)


def test_client_disconnect_releases_its_flows(daemon):
    c1 = DcnXferClient(daemon)
    c1.register_flow("orphan", bytes=1 << 20)
    with DcnXferClient(daemon) as c2:
        assert c2.stats()["active_flows"] == 1
        # Another client cannot touch c1's flow.
        with pytest.raises(DcnXferError, match="another client"):
            c2.release_flow("orphan")
        c1.close()
        deadline = time.time() + 5
        while c2.stats()["active_flows"] != 0:
            assert time.time() < deadline, "orphaned flow never released"
            time.sleep(0.02)
        assert c2.stats()["pool_used"] == 0


def test_rejects_hostile_input(daemon):
    with DcnXferClient(daemon) as c:
        with pytest.raises(DcnXferError, match="invalid flow name"):
            c.register_flow('evil"name')
        with pytest.raises(DcnXferError, match="invalid flow name"):
            c.register_flow("x" * 100)
        c.register_flow("ok", bytes=4096)
        with pytest.raises(DcnXferError, match="invalid 'bytes'"):
            c.record_transfer("ok", -1)
        with pytest.raises(DcnXferError, match="invalid 'bytes'"):
            c._call(op="record_transfer", flow="ok", bytes="abc")
        assert c.stats()["total_transferred"] == 0


def test_slow_reader_does_not_block_other_clients(daemon):
    # A client that pipelines requests without reading responses must not
    # stall the event loop for everyone else.
    stuck = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    stuck.connect(os.path.join(daemon, "xferd.sock"))
    stuck.sendall(b'{"op":"stats"}\n' * 2000)  # never reads
    with DcnXferClient(daemon, timeout_s=5) as c:
        for i in range(10):
            c.ping()  # would time out if the daemon were blocked
    stuck.close()


def test_bad_json_and_unknown_op(daemon):
    sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    sock.connect(os.path.join(daemon, "xferd.sock"))
    f = sock.makefile("r")
    sock.sendall(b"this is not json\n")
    assert '"ok":false' in f.readline()
    sock.sendall(b'{"op":"frobnicate"}\n')
    assert "unknown op" in f.readline()
    sock.close()


@pytest.fixture
def daemon_pair(tmp_path):
    """Two daemons on one host — the two-node DCN data-plane rig."""
    procs, dirs = [], []
    for name in ("a", "b"):
        uds = str(tmp_path / f"dcn-{name}")
        proc = subprocess.Popen(
            [BIN, "--uds_path", uds, "--pool_bytes", str(16 << 20),
             "--max_flows", "4", "--data_port", "0", "--verbose", "2"],
            stderr=subprocess.PIPE, text=True,
        )
        procs.append(proc)
        dirs.append(uds)
    for proc, uds in zip(procs, dirs):
        sock_path = os.path.join(uds, "xferd.sock")
        deadline = time.time() + 10
        while not os.path.exists(sock_path):
            assert proc.poll() is None, proc.stderr.read()
            assert time.time() < deadline
            time.sleep(0.02)
    yield dirs
    for proc in procs:
        proc.send_signal(signal.SIGTERM)
        proc.wait(timeout=10)


class TestDataPlane:
    """Cross-daemon TCP transfers (the rxdm RX-datapath analog)."""

    def test_data_port_reported(self, daemon_pair):
        with DcnXferClient(daemon_pair[0]) as c:
            assert 0 < c.data_port() < 65536

    def test_send_lands_in_peer_flow(self, daemon_pair):
        uds_a, uds_b = daemon_pair
        nbytes = 6 << 20
        with DcnXferClient(uds_a) as a, DcnXferClient(uds_b) as b:
            a.register_flow("g0", peer="b", bytes=1 << 20)
            b.register_flow("g0", peer="a", bytes=1 << 20)
            port = b.data_port()
            res = a.send("g0", "127.0.0.1", port, nbytes)
            assert res["bytes"] == nbytes
            assert res["gbps"] > 0

            # Receive side accounts asynchronously; poll for arrival.
            deadline = time.time() + 10
            while time.time() < deadline:
                stats = b.stats()
                if stats["total_rx"] >= nbytes:
                    break
                time.sleep(0.05)
            assert stats["total_rx"] == nbytes
            flow = next(f for f in stats["flows"] if f["flow"] == "g0")
            assert flow["rx_bytes"] == nbytes
            assert stats["rx_unmatched"] == 0
            # Sender accounted the transfer on its own flow too.
            a_flow = next(f for f in a.stats()["flows"] if f["flow"] == "g0")
            assert a_flow["transferred"] == nbytes

    def test_send_to_unregistered_peer_flow_counts_unmatched(
            self, daemon_pair):
        uds_a, uds_b = daemon_pair
        with DcnXferClient(uds_a) as a, DcnXferClient(uds_b) as b:
            a.register_flow("lonely", bytes=1 << 20)
            port = b.data_port()
            a.send("lonely", "127.0.0.1", port, 1 << 20)
            deadline = time.time() + 10
            while time.time() < deadline:
                stats = b.stats()
                if stats["rx_unmatched"] >= (1 << 20):
                    break
                time.sleep(0.05)
            assert stats["rx_unmatched"] == 1 << 20

    def test_send_unknown_flow_rejected(self, daemon_pair):
        with DcnXferClient(daemon_pair[0]) as a:
            with pytest.raises(DcnXferError, match="unknown flow"):
                a.send("nope", "127.0.0.1", 1, 1)

    def test_send_connect_refused_reported(self, daemon_pair):
        with DcnXferClient(daemon_pair[0]) as a:
            a.register_flow("g1", bytes=1 << 20)
            with pytest.raises(DcnXferError, match="connect"):
                a.send("g1", "127.0.0.1", 1, 1 << 20)

    def test_default_data_port_is_ephemeral(self, daemon):
        # The plain fixture passes no --data_port; the default (0) binds
        # an ephemeral port rather than disabling the data plane.
        with DcnXferClient(daemon) as c:
            assert 0 < c.data_port() < 65536

    def test_put_then_read_roundtrip(self, daemon):
        """Local staging via the data plane, read back via control op."""
        payload = bytes(range(256)) * 64  # 16 KiB, non-trivial content
        with DcnXferClient(daemon) as c:
            c.register_flow("stage", bytes=len(payload))
            c.put("stage", payload)
            deadline = time.time() + 10
            while time.time() < deadline:
                flow = next(f for f in c.stats()["flows"]
                            if f["flow"] == "stage")
                if flow["rx_bytes"] >= len(payload):
                    break
                time.sleep(0.02)
            assert c.read("stage", len(payload)) == payload
            # Offset reads window into the staging buffer.
            assert c.read("stage", 256, offset=256) == payload[256:512]

    def test_payload_survives_daemon_to_daemon_transfer(self, daemon_pair):
        """Content (not just byte counts) crosses the two-daemon path:
        put -> send -> peer read, the full rxdm-analog datapath."""
        uds_a, uds_b = daemon_pair
        payload = os.urandom(1 << 20)
        with DcnXferClient(uds_a) as a, DcnXferClient(uds_b) as b:
            a.register_flow("x", bytes=len(payload))
            b.register_flow("x", bytes=len(payload))
            a.put("x", payload)
            _wait_rx(a, "x", len(payload))
            a.send("x", "127.0.0.1", b.data_port(), len(payload))
            _wait_rx(b, "x", len(payload))
            assert b.read("x", len(payload)) == payload

    def test_read_before_any_frame_is_an_error(self, daemon):
        """ADVICE r03: reading an empty staging buffer must not return
        zeros with ok=true — there is no data, say so."""
        with DcnXferClient(daemon) as c:
            c.register_flow("empty", bytes=4096)
            with pytest.raises(DcnXferError, match="no completed frame"):
                c.read("empty", 16)

    def test_shorter_second_frame_clamps_stale_tail(self, daemon):
        """After a shorter second frame, the first frame's tail beyond
        frame_bytes is stale and must not be readable."""
        big = bytes(range(256)) * 16     # 4096
        small = b"\xaa" * 512
        with DcnXferClient(daemon) as c:
            c.register_flow("clamp", bytes=len(big))
            c.put("clamp", big)
            _wait_rx(c, "clamp", len(big))
            assert c.read("clamp", len(big)) == big
            c.put("clamp", small)
            _wait_rx(c, "clamp", len(big) + len(small))
            # Full-size read comes back clamped to the new frame.
            assert c.read("clamp", len(big)) == small
            flow = next(f for f in c.stats()["flows"]
                        if f["flow"] == "clamp")
            assert flow["frame_bytes"] == len(small)
            # Offsets past the staged frame error instead of returning
            # the stale first-frame tail.
            with pytest.raises(DcnXferError, match="beyond staged data"):
                c.read("clamp", 16, offset=len(small))

    def test_read_frame_exact_chunk_multiple(self, daemon):
        """A frame that is an exact multiple of the client's READ_CHUNK
        must read back fully — the chunk loop has to stop AT the frame
        boundary rather than issue one more call the daemon rejects."""
        payload = os.urandom(1 << 20)  # exactly 2 x READ_CHUNK
        with DcnXferClient(daemon) as c:
            c.register_flow("exact", bytes=len(payload))
            c.put("exact", payload)
            _wait_rx(c, "exact", len(payload))
            assert c.read("exact", len(payload)) == payload
            # Asking for MORE than staged also returns short, not error.
            assert c.read("exact", len(payload) + 4096) == payload

    def test_read_respects_ownership_and_bounds(self, daemon):
        c1 = DcnXferClient(daemon)
        c1.register_flow("own", bytes=4096)
        with DcnXferClient(daemon) as c2:
            with pytest.raises(DcnXferError, match="another client"):
                c2.read("own", 16)
        with pytest.raises(DcnXferError, match="offset"):
            c1.read("own", 16, offset=4096)
        c1.close()


def _wait_rx(client, flow, nbytes, timeout=10):
    deadline = time.time() + timeout
    while time.time() < deadline:
        f = next(x for x in client.stats()["flows"] if x["flow"] == flow)
        if f["rx_bytes"] >= nbytes:
            return
        time.sleep(0.02)
    raise AssertionError(f"flow {flow} never received {nbytes} bytes")
