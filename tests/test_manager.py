"""Manager unit tests: discovery, env contract (ref: manager_test.go:143-214)."""

import os

import pytest

from container_engine_accelerators_tpu.deviceplugin.manager import TpuManager
from container_engine_accelerators_tpu.tpulib import (
    SysfsTpuLib,
    write_fixture,
    write_libtpu_install,
)
from container_engine_accelerators_tpu.utils.config import TPUConfig
from container_engine_accelerators_tpu.utils.device import HEALTHY, Mount

HBM = 16 * 2**30


def make_manager(tmp_path, config_json, num_chips=1):
    root = str(tmp_path)
    write_fixture(root, num_chips, hbm_total=HBM)
    cfg = TPUConfig.from_json(config_json)
    cfg.add_defaults_and_validate()
    mounts = [
        Mount(
            host_path=write_libtpu_install(root),
            container_path="/usr/local/tpu",
            read_only=True,
        )
    ]
    m = TpuManager(
        os.path.join(root, "dev"), mounts, cfg, lib=SysfsTpuLib(root)
    )
    m.start()
    return m


CORE_SHARING = {
    "tpuSharingConfig": {
        "tpuSharingStrategy": "core-sharing",
        "maxSharedClientsPerTpu": 4,
    }
}


def test_core_sharing_envs_single_client(tmp_path):
    """MPS-env analog (ref: manager.go:312-325): one of 4 clients gets 25%
    of the TensorCore and a quarter of HBM."""
    m = make_manager(tmp_path, CORE_SHARING)
    envs = m.envs(["accel0/vtpu0"])
    assert envs["TPU_CORE_PERCENTAGE"] == "25"
    assert envs["TPU_HBM_LIMIT_BYTES"] == str(HBM // 4)
    assert envs["XLA_PYTHON_CLIENT_MEM_FRACTION"] == "0.2500"


def test_core_sharing_envs_multi_client(tmp_path):
    m = make_manager(tmp_path, CORE_SHARING)
    envs = m.envs(["accel0/vtpu0", "accel0/vtpu1", "accel0/vtpu2"])
    assert envs["TPU_CORE_PERCENTAGE"] == "75"
    assert envs["TPU_HBM_LIMIT_BYTES"] == str(3 * HBM // 4)


def test_plain_config_no_envs(tmp_path):
    m = make_manager(tmp_path, {}, num_chips=4)
    assert m.envs(["accel0"]) == {}


def test_discovery_and_hotplug_detection(tmp_path):
    m = make_manager(tmp_path, {}, num_chips=2)
    assert set(m.devices) == {"accel0", "accel1"}
    assert all(d.health == HEALTHY for d in m.devices.values())
    assert not m.has_additional_chips_installed()
    open(os.path.join(str(tmp_path), "dev", "accel2"), "w").close()
    assert m.has_additional_chips_installed()


def test_check_device_paths(tmp_path):
    root = str(tmp_path)
    os.makedirs(os.path.join(root, "dev"))
    cfg = TPUConfig.from_json({})
    cfg.add_defaults_and_validate()
    m = TpuManager(os.path.join(root, "dev"), [], cfg, lib=SysfsTpuLib(root))
    assert not m.check_device_paths()
    open(os.path.join(root, "dev", "accel0"), "w").close()
    assert m.check_device_paths()


def test_core_sharing_requires_chips(tmp_path):
    root = str(tmp_path)
    os.makedirs(os.path.join(root, "dev"))
    cfg = TPUConfig.from_json(CORE_SHARING)
    cfg.add_defaults_and_validate()
    m = TpuManager(os.path.join(root, "dev"), [], cfg, lib=SysfsTpuLib(root))
    with pytest.raises(RuntimeError, match="core-sharing requires"):
        m.start()


def test_hotplug_restart_recomputes_partitions(tmp_path):
    """Regression: hotplug restart must re-run partitioning, not just chip
    discovery, or new chips stay unschedulable behind a stale slice table."""
    from container_engine_accelerators_tpu.tpulib.sysfs import write_fixture

    root = str(tmp_path)
    write_fixture(root, 2, topology="2x1x1")
    cfg = TPUConfig.from_json({"tpuPartitionSize": "2x1"})
    cfg.add_defaults_and_validate()
    m = TpuManager(os.path.join(root, "dev"), [], cfg, lib=SysfsTpuLib(root))
    m.start()
    assert set(m.list_physical_devices()) == {"slice0"}
    # Tray upgrade: 2 more chips appear and the host topology becomes 2x2.
    write_fixture(root, 4, topology="2x2x1")
    assert m.has_additional_chips_installed()
    m.start()
    assert set(m.list_physical_devices()) == {"slice0", "slice1"}
