"""Prefix-cache exactness (models/prefix_cache.py).

The contract: splicing a cached prefix KV block and prefilling only the
suffix must produce EXACTLY the tokens of a full ``generate()`` over
the concatenated prompt — greedy and seeded-sampled, across bucket-pad
shapes, batch broadcast, and GQA.  Plus the host-side LRU semantics the
serving handler depends on.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from container_engine_accelerators_tpu.models.generate import generate
from container_engine_accelerators_tpu.models.lm_train import (
    create_lm_train_state,
)
from container_engine_accelerators_tpu.models.prefix_cache import (
    PrefixCache,
    generate_with_prefix,
)
from container_engine_accelerators_tpu.models.transformer import (
    transformer_lm,
)

CFG = dict(vocab_size=97, num_layers=2, num_heads=2, head_dim=8,
           mlp_dim=32)


def _params(cfg, seed=3):
    state = create_lm_train_state(
        transformer_lm(**cfg), jax.random.PRNGKey(seed),
        jnp.zeros((1, 8), jnp.int32), tx=optax.sgd(0.1),
    )
    return state.params


@pytest.fixture(scope="module")
def params():
    return _params(CFG)


def _check_exact(cfg, params, prefix_ids, suffix_rows, max_new,
                 temperature=0.0, pfx_bucket=None, suf_bucket=None):
    """generate_with_prefix == generate(concat) for every row."""
    model = transformer_lm(**cfg, decode=True)
    cache = PrefixCache(model, params,
                        max_prefix_len=pfx_bucket or len(prefix_ids))
    kv, plen = cache.get_or_build(tuple(prefix_ids))

    s_real = len(suffix_rows[0])
    s_pad = (suf_bucket or s_real) - s_real
    suffix = jnp.asarray(
        [row + [0] * s_pad for row in suffix_rows], jnp.int32)
    rng = jax.random.PRNGKey(7)
    got = np.asarray(generate_with_prefix(
        model, params, kv, plen, suffix, max_new,
        temperature=temperature, rng=rng, suffix_len=s_real))

    full = jnp.asarray(
        [list(prefix_ids) + row for row in suffix_rows], jnp.int32)
    want = np.asarray(generate(
        model, params, full, max_new, temperature=temperature, rng=rng))

    n = s_real + max_new
    want_tail = want[:, len(prefix_ids):len(prefix_ids) + n]
    assert (got[:, :n] == want_tail).all(), (got[:, :n], want_tail)


def test_greedy_exact_no_padding(params):
    _check_exact(CFG, params, [5, 17, 42], [[7, 9], [1, 3]], 8)


def test_greedy_exact_bucket_padded_prefix_and_suffix(params):
    # prefix 3 real in an 8-bucket, suffix 2 real in a 4-bucket
    _check_exact(CFG, params, [5, 17, 42], [[7, 9], [1, 3]], 8,
                 pfx_bucket=8, suf_bucket=4)


def test_sampled_exact_with_shared_rng(params):
    # Sampling consumes rng only in the decode loop, which both paths
    # share — seeded outputs must match exactly too.
    _check_exact(CFG, params, [5, 17, 42], [[7, 9]], 8,
                 temperature=0.7, pfx_bucket=8, suf_bucket=4)


def test_gqa_exact():
    gqa = dict(CFG, num_heads=4, num_kv_heads=2)
    _check_exact(gqa, _params(gqa, 11), [2, 4, 6, 8], [[9, 7, 5]], 6,
                 pfx_bucket=8)


def test_single_row_and_longer_prefix(params):
    _check_exact(CFG, params, [3, 1, 4, 1, 5, 9, 2, 6], [[8]], 10,
                 pfx_bucket=8, suf_bucket=2)


def test_lru_and_stats(params):
    model = transformer_lm(**CFG, decode=True)
    cache = PrefixCache(model, params, max_prefix_len=8, max_entries=2)
    a, b, c = (1, 2), (3, 4), (5, 6)
    cache.get_or_build(a)
    cache.get_or_build(b)
    cache.get_or_build(a)          # refresh a: b is now LRU
    cache.get_or_build(c)          # evicts b
    st = cache.stats()
    assert st == {"entries": 2, "hits": 1, "misses": 3, "evictions": 1}
    cache.get_or_build(b)          # rebuilt
    assert cache.stats()["misses"] == 4
    with pytest.raises(ValueError):
        cache.get_or_build(tuple(range(9)))  # > max_prefix_len
    with pytest.raises(ValueError):
        cache.get_or_build(())


def test_entry_reuse_is_byte_identical(params):
    """Two requests hitting the same entry get the same object (no
    rebuild) and identical generations."""
    model = transformer_lm(**CFG, decode=True)
    cache = PrefixCache(model, params, max_prefix_len=8)
    kv1, _ = cache.get_or_build((5, 17, 42))
    kv2, plen = cache.get_or_build((5, 17, 42))
    assert kv1 is kv2 and cache.stats()["hits"] == 1
    suffix = jnp.asarray([[7, 9]], jnp.int32)
    g1 = generate_with_prefix(model, params, kv2, plen, suffix, 6)
    g2 = generate_with_prefix(model, params, kv2, plen, suffix, 6)
    assert (np.asarray(g1) == np.asarray(g2)).all()
