"""Chaos suite: every node-agent data path must self-heal, provably.

The fault-injection framework (utils/faults.py, TPU_FAULT_SPEC) and the
kill/restart doubles (tests/xferd_stub.py, tests/kubelet_stub.py, the
real native daemon) drive the three scenarios the ISSUE pins:

1. xferd daemon killed and restarted mid-flow → ResilientDcnXferClient
   reconnects, replays its flow table, and the transfer completes;
2. kubelet socket deleted mid-watch → the plugin re-registers and
   re-announces devices (with an injected Register failure absorbed);
3. unattributed critical event → ALL devices Unhealthy → quiescence
   window passes → all recover to Healthy —

all with zero manual intervention.  `make chaos` re-runs this file
under several TPU_FAULT_SPEC permutations; tests that need exact fault
accounting therefore arm a private injector via ``faults.armed`` rather
than reading the process env.
"""

import json
import os
import queue
import signal
import subprocess
import threading
import time

import pytest

from container_engine_accelerators_tpu.metrics import counters
from container_engine_accelerators_tpu.obs import flight, histo, trace
from container_engine_accelerators_tpu.parallel import dcn
from container_engine_accelerators_tpu.parallel.dcn_client import (
    DcnXferClient,
    DcnXferError,
    ResilientDcnXferClient,
)
from container_engine_accelerators_tpu.utils import faults
from container_engine_accelerators_tpu.utils.retry import RetryPolicy
from tests.xferd_stub import XferdStub

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
XFERD_BIN = os.environ.get(
    "DCNXFERD_BIN",
    os.path.join(REPO, "native", "dcnxferd", "build", "dcnxferd"),
)

# Fast budget for tests: same shape as production, millisecond scale.
FAST_RETRY = RetryPolicy(
    max_attempts=8, initial_backoff_s=0.01, max_backoff_s=0.1, deadline_s=15.0
)


# ---------------------------------------------------------------------------
# RetryPolicy unit tests
# ---------------------------------------------------------------------------


class TestRetryPolicy:
    def test_backoff_grows_and_caps(self):
        p = RetryPolicy(initial_backoff_s=0.1, multiplier=2.0,
                        max_backoff_s=0.5, jitter=0.0)
        assert [p.backoff_s(i) for i in range(4)] == [0.1, 0.2, 0.4, 0.5]

    def test_jitter_bounds(self):
        p = RetryPolicy(initial_backoff_s=1.0, jitter=0.25)
        for _ in range(50):
            assert 0.75 <= p.backoff_s(0) <= 1.25

    def test_call_succeeds_after_transient_failures(self):
        p = RetryPolicy(max_attempts=4, initial_backoff_s=0.001,
                        max_backoff_s=0.002)
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise OSError("transient")
            return "ok"

        assert p.call(flaky) == "ok"
        assert len(calls) == 3

    def test_call_reraises_after_budget(self):
        p = RetryPolicy(max_attempts=3, initial_backoff_s=0.001,
                        max_backoff_s=0.002)
        calls = []

        def always():
            calls.append(1)
            raise OSError("down")

        with pytest.raises(OSError, match="down"):
            p.call(always)
        assert len(calls) == 3

    def test_deadline_stops_attempts_early(self):
        p = RetryPolicy(max_attempts=100, initial_backoff_s=10.0,
                        deadline_s=1.0, jitter=0.0)
        # First backoff (10s) already exceeds the deadline: one attempt.
        assert len(list(p.attempts(sleep=lambda s: None))) == 1

    def test_injectable_sleep_is_used(self):
        slept = []
        p = RetryPolicy(max_attempts=3, initial_backoff_s=0.5, jitter=0.0)
        list(p.attempts(sleep=slept.append))
        assert slept == [0.5, 1.0]


# ---------------------------------------------------------------------------
# FaultInjector unit tests
# ---------------------------------------------------------------------------


class TestFaultInjector:
    def test_spec_fires_at_nth_hit(self):
        inj = faults.FaultInjector.from_spec("dcn.send:fail@3")
        inj.check("dcn.send")
        inj.check("dcn.send")
        with pytest.raises(faults.FaultInjectedError):
            inj.check("dcn.send")
        inj.check("dcn.send")  # one-shot: 4th hit is clean
        assert inj.fired("dcn.send") == 1

    def test_repeat_and_forever(self):
        inj = faults.FaultInjector.from_spec("a:drop@2x2;b:fail@1x*")
        inj.check("a")
        for _ in range(2):
            with pytest.raises(faults.InjectedConnectionDrop):
                inj.check("a")
        inj.check("a")
        for _ in range(5):
            with pytest.raises(faults.FaultInjectedError):
                inj.check("b")

    def test_sites_are_independent(self):
        inj = faults.FaultInjector.from_spec("a:fail@1")
        inj.check("unrelated.site")
        with pytest.raises(faults.FaultInjectedError):
            inj.check("a")

    @pytest.mark.parametrize("bad", [
        "garbage", "site:", ":fail", "a:frobnicate@1", "a:fail@zero",
        "a:fail@-1", "a:fail@1x0", "@@;;,,", "a:fail@1x1x1",
        # "x-1" must NOT collide with the internal forever sentinel.
        "a:fail@1x-1",
    ])
    def test_malformed_spec_never_raises(self, bad):
        inj = faults.FaultInjector.from_spec(bad)
        assert inj.rules == []
        inj.check("a")  # and an unarmed injector is a no-op

    def test_malformed_entries_do_not_poison_valid_ones(self):
        inj = faults.FaultInjector.from_spec("nonsense;a:fail@1;also bad")
        with pytest.raises(faults.FaultInjectedError):
            inj.check("a")

    def test_env_arming_via_reload(self, monkeypatch):
        # Restore the PRIOR spec afterwards (not an emptied env): under
        # `make chaos` the process-wide spec must stay armed for the
        # rest of the session, or the permutation gate tests nothing.
        prior = os.environ.get(faults.TPU_FAULT_SPEC_ENV)
        monkeypatch.setenv(faults.TPU_FAULT_SPEC_ENV, "x:fail@1")
        inj = faults.reload()
        try:
            with pytest.raises(faults.FaultInjectedError):
                faults.check("x")
            assert inj.fired("x") == 1
        finally:
            if prior is None:
                monkeypatch.delenv(faults.TPU_FAULT_SPEC_ENV)
            else:
                monkeypatch.setenv(faults.TPU_FAULT_SPEC_ENV, prior)
            faults.reload()

    def test_fault_mode_is_an_oserror(self):
        # Production sites rely on this: the injected error must travel
        # the same except-paths as a real socket failure.
        assert issubclass(faults.FaultInjectedError, OSError)
        assert issubclass(faults.InjectedConnectionDrop, OSError)


# ---------------------------------------------------------------------------
# DCN: fail-fast contract preserved; resilience opt-in
# ---------------------------------------------------------------------------


@pytest.fixture
def xstub(tmp_path):
    stub = XferdStub(str(tmp_path / "tpu-dcn")).start()
    yield stub
    stub.stop()


class TestDcnFaultSites:
    def test_base_client_stays_fail_fast_under_injection(self, xstub):
        """The seed contract is unchanged: one transport fault poisons a
        plain DcnXferClient; only ResilientDcnXferClient recovers."""
        with faults.armed("dcn.send:fail@1"):
            c = DcnXferClient(xstub.uds_dir)
            with pytest.raises(DcnXferError, match="connection failed"):
                c.ping()
            with pytest.raises(DcnXferError, match="reconnect"):
                c.ping()  # poisoned for good
            c.close()

    def test_base_client_connect_fault(self, xstub):
        with faults.armed("dcn.connect:drop@1"):
            with pytest.raises(OSError):
                DcnXferClient(xstub.uds_dir)

    def test_resilient_client_absorbs_send_fault(self, xstub):
        with faults.armed("dcn.send:fail@2") as inj:
            with ResilientDcnXferClient(xstub.uds_dir,
                                        retry=FAST_RETRY) as c:
                c.register_flow("f0", bytes=4096)
                # This call eats the injected fault: reconnect, replay
                # f0 (daemon released it on disconnect, so accounting
                # restarts at zero), then the retried op lands.
                assert c.record_transfer("f0", 100) == 100
                assert c.record_transfer("f0", 100) == 200
            assert inj.fired("dcn.send") == 1

    def test_resilient_client_absorbs_connect_faults(self, xstub):
        with faults.armed("dcn.connect:drop@1x2") as inj:
            with ResilientDcnXferClient(xstub.uds_dir,
                                        retry=FAST_RETRY) as c:
                c.ping()
            assert inj.fired("dcn.connect") == 2

    def test_empty_exchange_shard_never_touches_the_data_plane(
            self, xstub):
        """The empty-shard short-circuit, proved the hard way: the
        stub daemon has NO data plane (no data_port/put/send), so an
        exchange of zero bytes only completes if the leg really is
        register + barrier + release and nothing else."""
        from container_engine_accelerators_tpu.parallel import dcn

        hit = []
        with ResilientDcnXferClient(xstub.uds_dir,
                                    retry=FAST_RETRY) as c:
            got = dcn.exchange_shard(
                c, local_flow="empty.tx", peer_flow="empty.rx",
                data=b"", peer_host="127.0.0.1", peer_port=1,
                barrier=lambda: hit.append(1), timeout_s=5)
            assert got == b"" and hit == [1]
            assert c.stats()["active_flows"] == 0  # released on exit

    def test_daemon_level_errors_still_fail_fast(self, xstub):
        """Only transport loss retries; an ok:false reply must surface
        immediately (retrying a rejected request is wrong).  Private
        empty injector: exact reconnect accounting must not absorb a
        `make chaos` global spec's injected faults."""
        with faults.armed(""):
            with ResilientDcnXferClient(xstub.uds_dir,
                                        retry=FAST_RETRY) as c:
                c.register_flow("dup", bytes=4096)
                before = counters.get("dcn.reconnect.attempts")
                with pytest.raises(DcnXferError, match="already exists"):
                    c.register_flow("dup", bytes=4096)
                assert counters.get("dcn.reconnect.attempts") == before


@pytest.mark.chaos
class TestDcnDaemonChaos:
    def test_stub_restart_mid_flow_replays_and_completes(self, xstub):
        """Scenario 1 (stub form): daemon dies mid-flow, comes back;
        the client reconnects, replays the flow table, and finishes
        accounting — zero manual intervention."""
        with ResilientDcnXferClient(xstub.uds_dir, retry=FAST_RETRY) as c:
            c.register_flow("g0", peer="peer-a", bytes=8192)
            c.register_flow("g1", peer="peer-b", bytes=8192)
            assert c.record_transfer("g0", 4096) == 4096

            xstub.stop(crash=True)  # SIGKILL analog: socket path lingers
            xstub.start()

            # Daemon restart lost all state; the op rides a reconnect
            # that re-registers BOTH flows first (accounting restarts
            # from zero on the fresh daemon — connection == lifetime).
            assert c.record_transfer("g0", 4096) == 4096
            stats = c.stats()
            assert stats["generation"] == 2
            assert {f["flow"] for f in stats["flows"]} == {"g0", "g1"}
            assert c.record_transfer("g1", 1) == 1

    def test_restart_while_daemon_down_rides_backoff(self, xstub):
        """The daemon stays down across several backoff rounds; the call
        blocks, retries, and completes once it returns."""
        with ResilientDcnXferClient(xstub.uds_dir, retry=FAST_RETRY) as c:
            c.register_flow("g0", bytes=4096)
            xstub.stop(crash=True)

            def restart_later():
                time.sleep(0.25)
                xstub.start()

            t = threading.Thread(target=restart_later)
            t.start()
            try:
                assert c.record_transfer("g0", 7) == 7  # blocks + recovers
            finally:
                t.join()

    def test_budget_exhaustion_turns_terminal(self, xstub):
        """Graceful degradation: past the budget the client raises a
        clear terminal error immediately instead of hammering."""
        tiny = RetryPolicy(max_attempts=3, initial_backoff_s=0.01,
                           max_backoff_s=0.02)
        c = ResilientDcnXferClient(xstub.uds_dir, retry=tiny)
        c.register_flow("g0", bytes=4096)
        xstub.stop(crash=True)
        with pytest.raises(DcnXferError, match="unreachable after 3"):
            c.ping()
        with pytest.raises(DcnXferError, match="terminal"):
            c.ping()  # no further reconnect attempts

    def test_release_drops_flow_from_replay_table(self, xstub):
        with ResilientDcnXferClient(xstub.uds_dir, retry=FAST_RETRY) as c:
            c.register_flow("keep", bytes=4096)
            c.register_flow("gone", bytes=4096)
            c.release_flow("gone")
            xstub.stop(crash=True)
            xstub.start()
            c.ping()  # forces reconnect + replay
            assert {f["flow"] for f in c.stats()["flows"]} == {"keep"}


@pytest.mark.chaos
@pytest.mark.skipif(not os.path.exists(XFERD_BIN),
                    reason="dcnxferd not built (run `make native`)")
class TestRealDaemonChaos:
    """Scenario 1 against the REAL native daemon, data plane included:
    SIGKILL mid-flow, restart on the same UDS path, transfer completes."""

    def _spawn(self, uds):
        proc = subprocess.Popen(
            [XFERD_BIN, "--uds_path", uds, "--pool_bytes", str(8 << 20),
             "--max_flows", "4", "--data_port", "0"],
            stderr=subprocess.PIPE, text=True,
        )
        sock = os.path.join(uds, "xferd.sock")
        deadline = time.time() + 10
        while not os.path.exists(sock):
            assert proc.poll() is None, proc.stderr.read()
            assert time.time() < deadline, "daemon never created its socket"
            time.sleep(0.02)
        return proc

    def test_exchange_shard_legs_repeat_without_flow_leak(self, tmp_path):
        """The production transfer path (dcn.exchange_shard) releases its
        flows per leg: a second leg with the same names must not hit the
        daemon's duplicate-flow rejection, and flow count returns to 0."""
        uds_a = str(tmp_path / "dcn-a")
        uds_b = str(tmp_path / "dcn-b")
        pa, pb_ = self._spawn(uds_a), self._spawn(uds_b)
        try:
            with ResilientDcnXferClient(uds_a, retry=FAST_RETRY) as ca, \
                    ResilientDcnXferClient(uds_b, retry=FAST_RETRY) as cb:
                ports = {"a": ca.data_port(), "b": cb.data_port()}
                for leg in range(2):  # same flow names both legs
                    barrier = threading.Barrier(2, timeout=30)
                    results = {}

                    def side(name, client, peer, payload):
                        results[name] = dcn.exchange_shard(
                            client,
                            local_flow=f"shard-{name}",
                            peer_flow=f"shard-{peer}",
                            data=payload,
                            peer_host="127.0.0.1",
                            peer_port=ports[peer],
                            barrier=barrier.wait,
                            timeout_s=30,
                        )

                    pay_a = bytes([leg]) * 8192
                    pay_b = bytes([leg + 128]) * 8192
                    ta = threading.Thread(
                        target=side, args=("a", ca, "b", pay_a))
                    tb = threading.Thread(
                        target=side, args=("b", cb, "a", pay_b))
                    ta.start(), tb.start()
                    ta.join(timeout=60), tb.join(timeout=60)
                    assert results["a"] == pay_b  # A read B's shard
                    assert results["b"] == pay_a
                assert ca.stats()["active_flows"] == 0
                assert cb.stats()["active_flows"] == 0
        finally:
            for p in (pa, pb_):
                if p.poll() is None:
                    p.send_signal(signal.SIGTERM)
                    p.wait(timeout=10)

    def test_kill9_restart_mid_flow_transfer_completes(self, tmp_path):
        uds = str(tmp_path / "tpu-dcn")
        payload = bytes(range(256)) * 64  # 16 KiB
        proc = self._spawn(uds)
        try:
            with ResilientDcnXferClient(uds, retry=FAST_RETRY) as c:
                c.register_flow("stage", bytes=len(payload))
                c.put("stage", payload)
                dcn.wait_flow_rx(c, "stage", len(payload))
                assert c.read("stage", len(payload)) == payload

                proc.send_signal(signal.SIGKILL)  # mid-flow crash
                proc.wait(timeout=10)
                proc = self._spawn(uds)

                # Same client, zero manual intervention — and no
                # caller-side put-again workaround: read itself notices
                # the restarted daemon's blank staging, restages the
                # cached payload through the data plane (re-resolving
                # the NEW data port via the reconnected control plane),
                # waits for it to land, and returns the bytes.
                restaged0 = counters.get("dcn.read.restaged")
                assert c.read("stage", len(payload)) == payload
                assert counters.get("dcn.read.restaged") == restaged0 + 1
        finally:
            if proc.poll() is None:
                proc.send_signal(signal.SIGTERM)
                proc.wait(timeout=10)


# ---------------------------------------------------------------------------
# Device plugin: kubelet restart + injected Register failures
# ---------------------------------------------------------------------------


def _make_manager(tmp_path):
    from container_engine_accelerators_tpu.deviceplugin.manager import (
        TpuManager,
    )
    from container_engine_accelerators_tpu.tpulib import (
        SysfsTpuLib,
        write_fixture,
    )
    from container_engine_accelerators_tpu.utils.config import TPUConfig

    root = str(tmp_path)
    write_fixture(root, 4)
    cfg = TPUConfig.from_json({})
    cfg.add_defaults_and_validate()
    m = TpuManager(
        os.path.join(root, "dev"), [], cfg, lib=SysfsTpuLib(root),
        socket_check_interval_s=0.05,
    )
    m.start()
    return m


@pytest.fixture
def serving_manager(tmp_path):
    """In-process manager serving against a KubeletStub (the in-process
    half of tests/test_plugin_daemon.py's subprocess rig)."""
    from container_engine_accelerators_tpu.deviceplugin import api
    from tests.kubelet_stub import KubeletStub

    plugdir = str(tmp_path / "plugins")
    os.makedirs(plugdir)
    stub = KubeletStub(os.path.join(plugdir, api.KUBELET_SOCKET))
    stub.start()
    manager = _make_manager(tmp_path)
    t = threading.Thread(
        target=manager.serve, args=(plugdir,), daemon=True
    )
    t.start()
    yield manager, stub, plugdir
    manager.stop()
    t.join(timeout=10)
    stub.stop()


def _dial(plugdir, endpoint):
    import grpc

    from container_engine_accelerators_tpu.deviceplugin import api

    ch = grpc.insecure_channel(f"unix://{os.path.join(plugdir, endpoint)}")
    return api.DevicePluginClient(ch)


@pytest.mark.chaos
class TestKubeletChaos:
    def test_socket_deleted_mid_watch_reregisters(self, serving_manager):
        """Scenario 2: kubelet restart wipes the plugin dir; the manager
        notices within the socket poll, re-registers on a fresh socket,
        and re-announces all devices."""
        from container_engine_accelerators_tpu.deviceplugin import (
            deviceplugin_v1beta1_pb2 as pb,
        )

        manager, stub, plugdir = serving_manager
        reg1 = stub.requests.get(timeout=10)
        assert reg1.resource_name == "google.com/tpu"
        sock1 = os.path.join(plugdir, reg1.endpoint)
        assert os.path.exists(sock1)

        before = counters.get("kubelet.reregister")
        os.unlink(sock1)  # kubelet restarted and wiped the dir

        reg2 = stub.requests.get(timeout=10)
        resp = next(_dial(plugdir, reg2.endpoint).list_and_watch(pb.Empty()))
        assert {d.ID for d in resp.devices} == {f"accel{i}" for i in range(4)}
        assert all(d.health == "Healthy" for d in resp.devices)
        assert counters.get("kubelet.reregister") == before + 1

    def test_injected_register_failure_is_retried(self, tmp_path):
        """`kubelet.register:fail@1` (the TPU_FAULT_SPEC form) must cost
        one backoff round, not the DaemonSet pod."""
        from container_engine_accelerators_tpu.deviceplugin import api
        from tests.kubelet_stub import KubeletStub

        plugdir = str(tmp_path / "plugins")
        os.makedirs(plugdir)
        stub = KubeletStub(os.path.join(plugdir, api.KUBELET_SOCKET))
        stub.start()
        manager = _make_manager(tmp_path)
        with faults.armed("kubelet.register:fail@1") as inj:
            t = threading.Thread(
                target=manager.serve, args=(plugdir,), daemon=True
            )
            t.start()
            try:
                reg = stub.requests.get(timeout=10)
                assert reg.resource_name == "google.com/tpu"
                assert inj.fired("kubelet.register") == 1
            finally:
                manager.stop()
                t.join(timeout=10)
                stub.stop()


# ---------------------------------------------------------------------------
# Health: Unhealthy → quiescence → Healthy
# ---------------------------------------------------------------------------


@pytest.fixture
def health_rig(tmp_path):
    from container_engine_accelerators_tpu.health import TpuHealthChecker

    manager = _make_manager(tmp_path)
    hc = TpuHealthChecker(manager, manager.lib, recovery_window_s=0.2)
    return manager, hc


def _drain(q):
    out = []
    while True:
        try:
            out.append(q.get_nowait())
        except queue.Empty:
            return out


def _apply(manager):
    """Drain the health queue into device state, as ListAndWatch does."""
    events = _drain(manager.health_events)
    for d in events:
        manager.set_device_health(d.id, d.health)
    return events


@pytest.mark.chaos
class TestHealthRecoveryChaos:
    def test_unattributed_event_all_unhealthy_then_all_recover(
            self, health_rig):
        """Scenario 3: a critical event with no device attribution takes
        every device Unhealthy; after the quiescence window every one is
        re-announced Healthy — zero manual intervention."""
        from container_engine_accelerators_tpu.tpulib.types import (
            TpuErrorEvent,
        )
        from container_engine_accelerators_tpu.utils.device import (
            HEALTHY,
            UNHEALTHY,
        )

        manager, hc = health_rig
        hc.catch_error(TpuErrorEvent(code=48, device=None))
        _apply(manager)
        assert all(
            d.health == UNHEALTHY for d in manager.list_devices().values()
        )

        # Inside the window: nothing recovers.
        assert hc.maybe_recover() == 0
        # Past the window (driven via `now`: deterministic, no sleep):
        assert hc.maybe_recover(now=time.monotonic() + 1.0) == 4
        _apply(manager)
        assert all(
            d.health == HEALTHY for d in manager.list_devices().values()
        )

    def test_fresh_critical_event_restamps_quiescence(self, health_rig):
        """A chip that keeps faulting never recovers: each critical
        event pushes its window out."""
        from container_engine_accelerators_tpu.tpulib.types import (
            TpuErrorEvent,
        )

        manager, hc = health_rig
        hc.catch_error(TpuErrorEvent(code=48, device="accel1"))
        first_stamp = hc._unhealthy_since["accel1"]
        # It faults again: the stamp must move forward.
        hc.catch_error(TpuErrorEvent(code=48, device="accel1"))
        second_stamp = hc._unhealthy_since["accel1"]
        assert second_stamp >= first_stamp
        # A `now` that clears the FIRST stamp's window but not the
        # second's must not recover (deterministic: driven off the
        # recorded stamps, no wall-clock sleeps).
        assert hc.maybe_recover(now=second_stamp + 0.19) == 0
        assert hc.maybe_recover(now=second_stamp + 0.21) == 1

    def test_refault_after_recovery_escalates_window(self, health_rig):
        """A chip that only faults under load goes quiet the moment the
        kubelet stops scheduling onto it; plain quiescence would flap it
        Healthy/Unhealthy forever.  A re-fault soon after a recovery
        must double the next window."""
        from container_engine_accelerators_tpu.tpulib.types import (
            TpuErrorEvent,
        )

        manager, hc = health_rig  # window = 0.2s
        flaps0 = counters.get("health.flap_backoff")
        hc.catch_error(TpuErrorEvent(code=48, device="accel0"))
        stamp = hc._unhealthy_since["accel0"]
        assert hc.maybe_recover(now=stamp + 0.21) == 1

        # Re-fault "immediately" (well within FLAP_RESET_FACTOR windows).
        hc.catch_error(TpuErrorEvent(code=48, device="accel0"))
        stamp2 = hc._unhealthy_since["accel0"]
        assert counters.get("health.flap_backoff") == flaps0 + 1
        # One window is no longer enough; two is.
        assert hc.maybe_recover(now=stamp2 + 0.21) == 0
        assert hc.maybe_recover(now=stamp2 + 0.41) == 1

        # A re-fault long after the recovery is forgiven: window resets.
        # (Pin the recovery stamp far in the past — the synthetic `now`
        # values above live ahead of the real clock catch_error uses.)
        hc._recovered_at["accel0"] = time.monotonic() - 60.0
        hc.catch_error(TpuErrorEvent(code=48, device="accel0"))
        stamp3 = hc._unhealthy_since["accel0"]
        assert hc._flaps.get("accel0", 0) == 0
        assert hc.maybe_recover(now=stamp3 + 0.21) == 1

    def test_recovery_disabled_preserves_reference_semantics(self, tmp_path):
        from container_engine_accelerators_tpu.health import TpuHealthChecker
        from container_engine_accelerators_tpu.tpulib.types import (
            TpuErrorEvent,
        )

        manager = _make_manager(tmp_path)
        hc = TpuHealthChecker(manager, manager.lib, recovery_window_s=None)
        hc.catch_error(TpuErrorEvent(code=48, device="accel0"))
        assert hc.maybe_recover(now=time.monotonic() + 1e6) == 0

    def test_transition_counters_exported(self, health_rig):
        from container_engine_accelerators_tpu.tpulib.types import (
            TpuErrorEvent,
        )

        manager, hc = health_rig
        down0 = counters.get("health.unhealthy")
        up0 = counters.get("health.recovered")
        hc.catch_error(TpuErrorEvent(code=48, device="accel2"))
        hc.maybe_recover(now=time.monotonic() + 1.0)
        assert counters.get("health.unhealthy") == down0 + 1
        assert counters.get("health.recovered") == up0 + 1

    def test_vanished_device_not_reannounced(self, health_rig):
        from container_engine_accelerators_tpu.tpulib.types import (
            TpuErrorEvent,
        )

        manager, hc = health_rig
        hc.catch_error(TpuErrorEvent(code=48, device="accel3"))
        _apply(manager)
        with manager.devices_mutex:
            del manager.devices["accel3"]  # hotplug removed it
        assert hc.maybe_recover(now=time.monotonic() + 1.0) == 0
        assert _drain(manager.health_events) == []

    def test_partitioned_slice_reheals_when_all_chips_recover(self, tmp_path):
        """On a partitioned node the kubelet sees slices, not chips: a
        recovered chip must re-heal its slice — but only once EVERY
        member chip is healthy again."""
        from container_engine_accelerators_tpu.deviceplugin.manager import (
            TpuManager,
        )
        from container_engine_accelerators_tpu.tpulib import (
            SysfsTpuLib,
            write_fixture,
        )
        from container_engine_accelerators_tpu.utils.config import TPUConfig
        from container_engine_accelerators_tpu.utils.device import (
            HEALTHY,
            UNHEALTHY,
        )

        root = str(tmp_path)
        write_fixture(root, 4, topology="2x2x1")
        cfg = TPUConfig.from_json({"tpuPartitionSize": "2x2"})
        cfg.add_defaults_and_validate()
        m = TpuManager(
            os.path.join(root, "dev"), [], cfg, lib=SysfsTpuLib(root)
        )
        m.start()
        (slice_id,) = m.list_physical_devices().keys()

        recovered0 = counters.get("health.slice_recovered")
        m.set_device_health("accel0", UNHEALTHY)
        m.set_device_health("accel1", UNHEALTHY)
        assert m.list_physical_devices()[slice_id].health == UNHEALTHY

        # One chip back is not enough — the slice needs all four.
        m.set_device_health("accel0", HEALTHY)
        assert m.list_physical_devices()[slice_id].health == UNHEALTHY
        assert counters.get("health.slice_recovered") == recovered0
        m.set_device_health("accel1", HEALTHY)
        assert m.list_physical_devices()[slice_id].health == HEALTHY
        # Capacity-returned is its own signal (one per slice heal, not
        # one per chip): a re-announce of an already-Healthy chip must
        # not double-count.
        assert counters.get("health.slice_recovered") == recovered0 + 1
        m.set_device_health("accel0", HEALTHY)
        assert counters.get("health.slice_recovered") == recovered0 + 1

    def test_event_stream_fault_does_not_kill_monitoring(self, tmp_path):
        """`health.stream:drop@1`: the listener thread absorbs the
        injected stream fault, backs off, and still catches the NEXT
        real event — and recovery keeps running through the outage."""
        from container_engine_accelerators_tpu.health import TpuHealthChecker
        from container_engine_accelerators_tpu.tpulib.sysfs import post_event
        from container_engine_accelerators_tpu.utils.device import UNHEALTHY

        manager = _make_manager(tmp_path)
        hc = TpuHealthChecker(
            manager, manager.lib,
            recovery_window_s=None, event_wait_timeout_s=0.1,
        )
        with faults.armed("health.stream:drop@1") as inj:
            hc.start()
            try:
                deadline = time.monotonic() + 10
                while inj.fired("health.stream") == 0:
                    assert time.monotonic() < deadline
                    time.sleep(0.01)
                post_event(str(tmp_path), code=48, device="accel0",
                           message="HBM ECC")
                e = manager.health_events.get(timeout=10)
                assert (e.id, e.health) == ("accel0", UNHEALTHY)
            finally:
                hc.stop()


# ---------------------------------------------------------------------------
# Observability of chaos: traces, fault annotations, flight recorder
# ---------------------------------------------------------------------------


@pytest.mark.chaos
class TestChaosObservability:
    def test_daemon_kill_trace_covers_connect_fault_reconnect_replay(
            self, xstub, tmp_path, monkeypatch):
        """The ISSUE's acceptance bar: a daemon-kill chaos run with
        TPU_TRACE_FILE set leaves a parseable JSONL whose spans tell
        the whole story — connect, the injected fault, the reconnect,
        the flow replay — and the replay hangs off the same trace as
        the op that triggered it."""
        path = str(tmp_path / "chaos-trace.jsonl")
        monkeypatch.setenv(trace.TRACE_FILE_ENV, path)
        trace.reset()  # pick up the env, as a fresh agent process would
        try:
            with faults.armed("dcn.send:fail@3"):
                with ResilientDcnXferClient(xstub.uds_dir,
                                            retry=FAST_RETRY) as c:
                    c.register_flow("f0", bytes=4096)
                    # Injected fault on this op's send -> reconnect +
                    # replay of f0 -> retried op lands.
                    assert c.record_transfer("f0", 64) == 64
                    # Then a REAL daemon kill/restart mid-flow.
                    xstub.stop(crash=True)
                    xstub.start()
                    assert c.record_transfer("f0", 64) == 64
        finally:
            trace.reset()  # close the sink before reading it

        spans = [json.loads(line) for line in open(path)]  # parseable
        by_name = {}
        for s in spans:
            by_name.setdefault(s["name"], []).append(s)
        assert {"dcn.connect", "dcn.send", "dcn.replay"} <= set(by_name)
        # The injected fault is stamped on the span it killed.
        faulted = [s for s in spans
                   if (s.get("attrs") or {}).get("fault") == "dcn.send"]
        assert faulted and faulted[0]["status"] == "error"
        # Replay is a child of the reconnect machinery on the SAME
        # trace as the faulted op (one story, not three fragments), and
        # wraps a fresh connect.
        replays = by_name["dcn.replay"]
        assert any(r["trace"] == faulted[0]["trace"] for r in replays)
        replay_ids = {r["span"] for r in replays}
        assert any(s["parent"] in replay_ids
                   for s in by_name["dcn.connect"])
        # Latency histograms populated for the hot path ops the
        # MetricServer will export (export itself: test_metrics.py).
        snap = histo.snapshot()
        assert snap["dcn.send"]["count"] > 0
        assert snap["dcn.replay"]["count"] > 0

    def test_terminal_failure_emits_flight_record(self, xstub, tmp_path,
                                                  monkeypatch):
        """A resilient client latching terminal must leave the evidence
        behind: one JSON blob with the last spans and the counter
        snapshot (the ISSUE's flight-recorder bar)."""
        path = str(tmp_path / "flight.jsonl")
        monkeypatch.setenv(flight.FLIGHT_FILE_ENV, path)
        tiny = RetryPolicy(max_attempts=2, initial_backoff_s=0.01,
                           max_backoff_s=0.02)
        c = ResilientDcnXferClient(xstub.uds_dir, retry=tiny)
        c.register_flow("f0", bytes=4096)
        xstub.stop(crash=True)
        with pytest.raises(DcnXferError, match="unreachable"):
            c.ping()
        blobs = [json.loads(line) for line in open(path)]
        terminal = [b for b in blobs if "latched terminal" in b["reason"]]
        assert terminal, [b["reason"] for b in blobs]
        blob = terminal[-1]
        assert blob["spans"], "flight dump carried no spans"
        assert "counters" in blob and "histograms" in blob
        assert blob["counters"].get("dcn.retry.exhausted", 0) >= 1

    def test_k8s_patch_conflict_chaos_rides_409_retry(self, tmp_path):
        """Satellite: `k8s.patch:conflict@1` injects a 409 into the
        maintenance watcher's taint patch; the read-modify-write loop
        must re-read and converge, zero manual intervention."""
        from container_engine_accelerators_tpu.health import (
            maintenance as mw,
        )
        from tests.test_maintenance import FakeApi, fetcher

        api = FakeApi()
        with faults.armed("k8s.patch:conflict@1") as inj:
            got = mw.reconcile(
                api, "n0", fetcher("TERMINATE_ON_HOST_MAINTENANCE"),
                events_dir=str(tmp_path / "events"),
            )
        assert got == "TERMINATE_ON_HOST_MAINTENANCE"
        assert inj.fired("k8s.patch") == 1
        (taints,) = api.patches  # the retry landed exactly one patch
        assert taints[0]["value"] == "TERMINATE_ON_HOST_MAINTENANCE"
        assert counters.get("fault.fired.k8s.patch") >= 1

    def test_k8s_patch_hard_failure_still_propagates(self, tmp_path):
        """A non-conflict injected failure must NOT be eaten by the 409
        loop — run_forever's outer catch owns it, like any real API
        outage."""
        from container_engine_accelerators_tpu.health import (
            maintenance as mw,
        )
        from tests.test_maintenance import FakeApi, fetcher

        api = FakeApi()
        with faults.armed("k8s.patch:fail@1"):
            with pytest.raises(faults.FaultInjectedError):
                mw.reconcile(
                    api, "n0", fetcher("TERMINATE_ON_HOST_MAINTENANCE"),
                    events_dir=str(tmp_path / "events"),
                )
        assert api.patches == []  # nothing half-applied


# ---------------------------------------------------------------------------
# Counters surface through the Prometheus exporter
# ---------------------------------------------------------------------------


def test_agent_event_counters_exported_via_metrics(tmp_path):
    from container_engine_accelerators_tpu.metrics import MetricServer

    class NoChips:
        def devices(self):
            return []

        def collect_tpu_device(self, name):  # pragma: no cover
            raise AssertionError

        def model(self, name):  # pragma: no cover
            return "tpu"

    counters.inc("dcn.reconnect.success", 3)
    server = MetricServer(
        collector=NoChips(),
        pod_resources_socket=str(tmp_path / "nope.sock"),
    )
    server.collect_once()  # pod-resources outage is absorbed (existing test)
    value = server.registry.get_sample_value(
        "agent_events", {"event": "dcn.reconnect.success"}
    )
    assert value is not None and value >= 3
