"""End-to-end tests of the scheduler-layer daemon BINARIES.

test_scheduler.py covers the logic in-process; these run
``cmd/topology_scheduler.py`` and ``cmd/label_nodes.py`` as
subprocesses — the way their Deployment/DaemonSet manifests do — against
a live fake K8s API server (plain http.server + the real urllib
transport) and a fake GCE metadata server, asserting pods get bound
on-slice and nodes get stamped with the exact topology labels the
scheduler's distance function consumes.
"""

import json
import re
import subprocess
import sys
import threading
from http.server import BaseHTTPRequestHandler, HTTPServer

import pytest

from container_engine_accelerators_tpu.scheduler import topology

REPO = __import__("os").path.dirname(
    __import__("os").path.dirname(__import__("os").path.abspath(__file__))
)
GATE = "gke.io/topology-aware-auto-j1"


def _node(name, slice_id, coords):
    return {
        "metadata": {"name": name, "labels": {
            topology.PLACEMENT_GROUP_LABEL: "pg0",
            topology.CLUSTER_LABEL: "c0",
            topology.RACK_LABEL: "r0",
            topology.HOST_LABEL: name,
            topology.SLICE_LABEL: slice_id,
            topology.COORDS_LABEL: coords,
            topology.TPU_TOPOLOGY_LABEL: "4x2x1",
        }},
        "status": {"allocatable": {"cpu": "8", "memory": "32Gi",
                                   "google.com/tpu": "4"}},
        "spec": {},
    }


def _pod(name, index):
    labels = {"job-name": "j1"}
    if index is not None:
        labels["batch.kubernetes.io/job-completion-index"] = str(index)
    return {
        "kind": "Pod",
        "metadata": {"name": name, "namespace": "default", "labels": labels,
                     "creationTimestamp": "2026-07-30T00:00:00Z"},
        "spec": {"schedulingGates": [{"name": GATE}],
                 "containers": [{"name": "c", "resources": {"requests": {
                     "cpu": "1", "memory": "1Gi", "google.com/tpu": "4"}}}]},
    }


@pytest.fixture
def fake_api():
    state = {
        "pods": {p["metadata"]["name"]: p
                 for p in [_pod("j1-0", 0), _pod("j1-1", 1)]},
        "bound": {},
        "patched_nodes": {},
        "nodes": [_node("n0", "s0", "0,0,0"), _node("n1", "s0", "2,0,0"),
                  _node("far", "s9", "0,0,0")],
    }

    class H(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def _send(self, obj, code=200):
            body = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if self.path == "/api/v1/namespaces":
                self._send({"items": [{"metadata": {"name": "default"}}]})
            elif re.match(r"/api/v1/namespaces/default/pods$", self.path):
                self._send({"items": list(state["pods"].values())})
            elif self.path == "/api/v1/nodes":
                self._send({"items": state["nodes"]})
            elif re.match(r"/api/v1/nodes/(.+)$", self.path):
                name = re.match(r"/api/v1/nodes/(.+)$", self.path).group(1)
                node = next((n for n in state["nodes"]
                             if n["metadata"]["name"] == name), None)
                if node is None:
                    self._send({"kind": "Status"}, 404)
                else:
                    self._send(node)
            else:
                m = re.match(r"/api/v1/namespaces/default/pods/(.+)$",
                             self.path)
                if m and m.group(1) in state["pods"]:
                    self._send(state["pods"][m.group(1)])
                else:
                    self._send({"kind": "Status"}, 404)

        def do_PUT(self):
            m = re.match(r"/api/v1/namespaces/default/pods/(.+)$", self.path)
            n = int(self.headers["Content-Length"])
            body = json.loads(self.rfile.read(n))
            state["pods"][m.group(1)] = body
            terms = body["spec"].get("affinity", {}).get(
                "nodeAffinity", {}).get(
                "requiredDuringSchedulingIgnoredDuringExecution", {}).get(
                "nodeSelectorTerms", [])
            state["bound"][m.group(1)] = (
                terms[0]["matchExpressions"][0]["values"][0] if terms
                else None
            )
            self._send(body)

        def do_PATCH(self):
            m = re.match(r"/api/v1/nodes/(.+)$", self.path)
            n = int(self.headers["Content-Length"])
            body = json.loads(self.rfile.read(n))
            state["patched_nodes"][m.group(1)] = body
            node = next((x for x in state["nodes"]
                         if x["metadata"]["name"] == m.group(1)), None)
            if node is not None and "taints" in body.get("spec", {}):
                node["spec"]["taints"] = body["spec"]["taints"]
            self._send(body)

    srv = HTTPServer(("127.0.0.1", 0), H)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    yield f"http://127.0.0.1:{srv.server_port}", state
    srv.shutdown()


def test_scheduler_binary_binds_gated_job(fake_api):
    host, state = fake_api
    out = subprocess.run(
        [sys.executable, "cmd/topology_scheduler.py", "--once",
         "--api-host", host, "--settle-seconds", "0"],
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    assert "bound 2 pods" in out.stdout
    # ICI neighbors in slice s0, not the cross-slice node.
    assert set(state["bound"].values()) == {"n0", "n1"}
    for pod in state["pods"].values():
        assert not pod["spec"].get("schedulingGates")


@pytest.fixture
def fake_metadata():
    answers = {
        "/instance/name": "tpu-node-3",
        "/instance/attributes/physical_host": "/c7/r2/h9",
        "/instance/attributes/tpu-env": (
            "TPU_NAME: 'slice-a'\nTOPOLOGY: '4x2x1'\nWORKER_ID: '1'\n"
        ),
        "/instance/maintenance-event": "TERMINATE_ON_HOST_MAINTENANCE",
    }

    class H(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):
            path = self.path.replace("/computeMetadata/v1", "")
            if self.headers.get("Metadata-Flavor") != "Google":
                self.send_response(403)
                self.end_headers()
                return
            body = answers.get(path)
            if body is None:
                self.send_response(404)
                self.end_headers()
                return
            data = body.encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

    srv = HTTPServer(("127.0.0.1", 0), H)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    yield f"http://127.0.0.1:{srv.server_port}/computeMetadata/v1"
    srv.shutdown()


def test_maintenance_watcher_binary_taints_and_posts(fake_api,
                                                     fake_metadata,
                                                     tmp_path):
    host, state = fake_api
    ev_dir = str(tmp_path / "events")
    out = subprocess.run(
        [sys.executable, "cmd/maintenance_watcher.py", "--once",
         "--api-host", host, "--metadata-base", fake_metadata,
         "--node-name", "n0", "--events-dir", ev_dir],
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    assert "TERMINATE_ON_HOST_MAINTENANCE" in out.stdout
    taints = state["patched_nodes"]["n0"]["spec"]["taints"]
    assert taints == [{"key": "google.com/tpu-maintenance",
                       "value": "TERMINATE_ON_HOST_MAINTENANCE",
                       "effect": "NoSchedule"}]
    import os as _os
    (fname,) = _os.listdir(ev_dir)
    event = json.load(open(_os.path.join(ev_dir, fname)))
    assert event["code"] == 80


def test_labeler_binary_stamps_topology_labels(fake_api, fake_metadata):
    host, state = fake_api
    out = subprocess.run(
        [sys.executable, "cmd/label_nodes.py", "--once",
         "--api-host", host, "--metadata-base", fake_metadata],
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    patch = state["patched_nodes"]["tpu-node-3"]
    labels = patch["metadata"]["labels"]
    assert labels[topology.CLUSTER_LABEL] == "c7"
    assert labels[topology.RACK_LABEL] == "r2"
    assert labels[topology.HOST_LABEL] == "h9"
    assert labels[topology.SLICE_LABEL] == "slice-a"
    assert labels[topology.TPU_TOPOLOGY_LABEL] == "4x2x1"
    # worker 1 on a 4x2x1 slice with 2x2x1 per-host sub-mesh -> (2,0,0)
    assert labels[topology.COORDS_LABEL] == "2,0,0"
