"""Health checker unit tests (ref: health_check/health_checker_test.go:31-243).

Synthetic events are fed to catch_error directly; assertions check which
devices flip Unhealthy: non-critical skipped, unknown-device skipped,
device-less event ⇒ ALL unhealthy.
"""

import os
import queue

import pytest

from container_engine_accelerators_tpu.deviceplugin.manager import TpuManager
from container_engine_accelerators_tpu.health import TpuHealthChecker
from container_engine_accelerators_tpu.tpulib import SysfsTpuLib, write_fixture
from container_engine_accelerators_tpu.tpulib.sysfs import post_event
from container_engine_accelerators_tpu.tpulib.types import TpuErrorEvent
from container_engine_accelerators_tpu.utils.config import TPUConfig
from container_engine_accelerators_tpu.utils.device import UNHEALTHY


@pytest.fixture
def manager(tmp_path):
    root = str(tmp_path)
    write_fixture(root, 4)
    cfg = TPUConfig.from_json({})
    cfg.add_defaults_and_validate()
    m = TpuManager(os.path.join(root, "dev"), [], cfg, lib=SysfsTpuLib(root))
    m.start()
    return m


def drain(q):
    out = []
    while True:
        try:
            out.append(q.get_nowait())
        except queue.Empty:
            return out


def test_critical_event_marks_device_unhealthy(manager):
    hc = TpuHealthChecker(manager, manager.lib)
    hc.catch_error(TpuErrorEvent(code=48, device="accel2"))
    events = drain(manager.health_events)
    assert [(e.id, e.health) for e in events] == [("accel2", UNHEALTHY)]


def test_non_critical_event_skipped(manager):
    hc = TpuHealthChecker(manager, manager.lib)
    hc.catch_error(TpuErrorEvent(code=13, device="accel2"))
    assert drain(manager.health_events) == []


def test_configured_code_becomes_critical(manager):
    hc = TpuHealthChecker(manager, manager.lib, critical_codes=[31, 72])
    hc.catch_error(TpuErrorEvent(code=31, device="accel1"))
    hc.catch_error(TpuErrorEvent(code=72, device="accel0"))
    assert {e.id for e in drain(manager.health_events)} == {"accel0", "accel1"}


def test_unknown_device_ignored(manager):
    hc = TpuHealthChecker(manager, manager.lib)
    hc.catch_error(TpuErrorEvent(code=48, device="accel9"))
    assert drain(manager.health_events) == []


def test_deviceless_event_marks_all_unhealthy(manager):
    hc = TpuHealthChecker(manager, manager.lib)
    hc.catch_error(TpuErrorEvent(code=48, device=None))
    assert {e.id for e in drain(manager.health_events)} == {
        "accel0",
        "accel1",
        "accel2",
        "accel3",
    }


def test_event_loop_end_to_end(manager, tmp_path):
    """Events posted to the node queue flow through wait_for_event into the
    manager's health queue (the fault-injection path, SURVEY.md §5)."""
    hc = TpuHealthChecker(manager, manager.lib)
    hc.start()
    try:
        post_event(str(tmp_path), code=48, device="accel3", message="HBM ECC")
        e = manager.health_events.get(timeout=10)
        assert (e.id, e.health) == ("accel3", UNHEALTHY)
    finally:
        hc.stop()


# -- external chip-fault injector (TPU_CHIP_FAULT_FILE, ISSUE 11) -----------


def _file_checker(manager, tmp_path, **kw):
    path = str(tmp_path / "chip_faults")
    kw.setdefault("recovery_window_s", 300.0)
    return TpuHealthChecker(manager, manager.lib, fault_file=path,
                            **kw), path


def test_fault_file_line_marks_device_unhealthy(manager, tmp_path):
    hc, path = _file_checker(manager, tmp_path)
    assert hc.poll_fault_file() == 0  # no injector yet: not an error
    with open(path, "w") as f:
        f.write("fault accel1 48\n")
    assert hc.poll_fault_file() == 1
    events = drain(manager.health_events)
    assert [(e.id, e.health) for e in events] == [("accel1", UNHEALTHY)]
    # Already-consumed lines are not replayed.
    assert hc.poll_fault_file() == 0


def test_fault_file_code_defaults_and_criticality(manager, tmp_path):
    hc, path = _file_checker(manager, tmp_path)
    with open(path, "w") as f:
        f.write("fault accel0\n")     # no code -> 48 (critical)
        f.write("fault accel2 13\n")  # non-critical code: no flip
    assert hc.poll_fault_file() == 2  # both lines APPLIED as events
    assert {e.id for e in drain(manager.health_events)} == {"accel0"}


def test_fault_file_clear_recovers_immediately(manager, tmp_path):
    from container_engine_accelerators_tpu.utils.device import HEALTHY

    hc, path = _file_checker(manager, tmp_path)
    with open(path, "w") as f:
        f.write("fault accel3 48\n")
    hc.poll_fault_file()
    assert drain(manager.health_events)[0].health == UNHEALTHY
    # The 300s quiescence window notwithstanding: an external clear is
    # the operator saying FIXED — recovery rides the normal path, now.
    with open(path, "a") as f:
        f.write("clear accel3\n")
    assert hc.poll_fault_file() == 1
    events = drain(manager.health_events)
    assert [(e.id, e.health) for e in events] == [("accel3", HEALTHY)]


def test_fault_file_malformed_lines_skipped(manager, tmp_path):
    from container_engine_accelerators_tpu.metrics import counters

    hc, path = _file_checker(manager, tmp_path)
    m0 = counters.get("health.fault_file.malformed")
    with open(path, "w") as f:
        f.write("garbage line here and more\n")
        f.write("fault\n")              # missing device
        f.write("fault accel1 nope\n")  # non-numeric code
        f.write("# a comment\n")
        f.write("\n")
        f.write("fault accel1 48\n")    # the one good line
    assert hc.poll_fault_file() == 1
    assert counters.get("health.fault_file.malformed") == m0 + 3
    assert {e.id for e in drain(manager.health_events)} == {"accel1"}


def test_fault_file_partial_line_waits_for_newline(manager, tmp_path):
    hc, path = _file_checker(manager, tmp_path)
    with open(path, "w") as f:
        f.write("fault accel2 48")  # injector caught mid-write
    assert hc.poll_fault_file() == 0
    assert drain(manager.health_events) == []
    with open(path, "a") as f:
        f.write("\n")
    assert hc.poll_fault_file() == 1
    assert {e.id for e in drain(manager.health_events)} == {"accel2"}


def test_fault_file_truncation_rereads_from_top(manager, tmp_path):
    hc, path = _file_checker(manager, tmp_path)
    with open(path, "w") as f:
        f.write("fault accel0 48\nfault accel0 48\n")
    assert hc.poll_fault_file() == 2
    drain(manager.health_events)
    # Rotation: the new (shorter) file's lines must not be skipped.
    # (Detection is size-based: a rotated file at least as long as the
    # consumed offset reads as an append — the documented limit.)
    with open(path, "w") as f:
        f.write("fault accel1 48\n")
    assert hc.poll_fault_file() == 1
    assert {e.id for e in drain(manager.health_events)} == {"accel1"}


def test_fault_file_env_resolution(manager, tmp_path, monkeypatch):
    from container_engine_accelerators_tpu.health.health_checker import (
        FAULT_FILE_ENV,
    )

    path = str(tmp_path / "env_faults")
    monkeypatch.setenv(FAULT_FILE_ENV, path)
    hc = TpuHealthChecker(manager, manager.lib)
    assert hc.fault_file == path
    monkeypatch.delenv(FAULT_FILE_ENV)
    assert TpuHealthChecker(manager, manager.lib).fault_file is None
