"""Health checker unit tests (ref: health_check/health_checker_test.go:31-243).

Synthetic events are fed to catch_error directly; assertions check which
devices flip Unhealthy: non-critical skipped, unknown-device skipped,
device-less event ⇒ ALL unhealthy.
"""

import os
import queue

import pytest

from container_engine_accelerators_tpu.deviceplugin.manager import TpuManager
from container_engine_accelerators_tpu.health import TpuHealthChecker
from container_engine_accelerators_tpu.tpulib import SysfsTpuLib, write_fixture
from container_engine_accelerators_tpu.tpulib.sysfs import post_event
from container_engine_accelerators_tpu.tpulib.types import TpuErrorEvent
from container_engine_accelerators_tpu.utils.config import TPUConfig
from container_engine_accelerators_tpu.utils.device import UNHEALTHY


@pytest.fixture
def manager(tmp_path):
    root = str(tmp_path)
    write_fixture(root, 4)
    cfg = TPUConfig.from_json({})
    cfg.add_defaults_and_validate()
    m = TpuManager(os.path.join(root, "dev"), [], cfg, lib=SysfsTpuLib(root))
    m.start()
    return m


def drain(q):
    out = []
    while True:
        try:
            out.append(q.get_nowait())
        except queue.Empty:
            return out


def test_critical_event_marks_device_unhealthy(manager):
    hc = TpuHealthChecker(manager, manager.lib)
    hc.catch_error(TpuErrorEvent(code=48, device="accel2"))
    events = drain(manager.health_events)
    assert [(e.id, e.health) for e in events] == [("accel2", UNHEALTHY)]


def test_non_critical_event_skipped(manager):
    hc = TpuHealthChecker(manager, manager.lib)
    hc.catch_error(TpuErrorEvent(code=13, device="accel2"))
    assert drain(manager.health_events) == []


def test_configured_code_becomes_critical(manager):
    hc = TpuHealthChecker(manager, manager.lib, critical_codes=[31, 72])
    hc.catch_error(TpuErrorEvent(code=31, device="accel1"))
    hc.catch_error(TpuErrorEvent(code=72, device="accel0"))
    assert {e.id for e in drain(manager.health_events)} == {"accel0", "accel1"}


def test_unknown_device_ignored(manager):
    hc = TpuHealthChecker(manager, manager.lib)
    hc.catch_error(TpuErrorEvent(code=48, device="accel9"))
    assert drain(manager.health_events) == []


def test_deviceless_event_marks_all_unhealthy(manager):
    hc = TpuHealthChecker(manager, manager.lib)
    hc.catch_error(TpuErrorEvent(code=48, device=None))
    assert {e.id for e in drain(manager.health_events)} == {
        "accel0",
        "accel1",
        "accel2",
        "accel3",
    }


def test_event_loop_end_to_end(manager, tmp_path):
    """Events posted to the node queue flow through wait_for_event into the
    manager's health queue (the fault-injection path, SURVEY.md §5)."""
    hc = TpuHealthChecker(manager, manager.lib)
    hc.start()
    try:
        post_event(str(tmp_path), code=48, device="accel3", message="HBM ECC")
        e = manager.health_events.get(timeout=10)
        assert (e.id, e.health) == ("accel3", UNHEALTHY)
    finally:
        hc.stop()
