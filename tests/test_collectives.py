"""Collectives rig tests: correctness of the sweep machinery on the CPU
mesh (bandwidth numbers are meaningless on CPU; semantics are not)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from container_engine_accelerators_tpu.collectives.bench import (
    _bus_factor,
    _make_collective,
    _parse_size,
    run_sweep,
)


def test_parse_size():
    assert _parse_size("1M") == 2**20
    assert _parse_size("512M") == 512 * 2**20
    assert _parse_size("2G") == 2 * 2**30
    assert _parse_size("128K") == 128 * 2**10
    assert _parse_size("4096") == 4096


def test_bus_factors_match_nccl_tests_conventions():
    assert _bus_factor("all_reduce", 8) == pytest.approx(2 * 7 / 8)
    assert _bus_factor("all_gather", 8) == pytest.approx(7 / 8)
    assert _bus_factor("reduce_scatter", 8) == pytest.approx(7 / 8)
    assert _bus_factor("ppermute", 8) == 1.0


def test_all_reduce_value_correct():
    """One chained all_reduce rep: every shard must hold the global sum."""
    mesh = Mesh(np.array(jax.devices()), ("x",))
    n = len(jax.devices())
    fn = _make_collective("all_reduce", mesh)
    x = jnp.arange(n * 4, dtype=jnp.float32)
    out = fn(x, 1)
    # psum of shards: shard i holds x[i*4:(i+1)*4]; sum over i.
    expected = x.reshape(n, 4).sum(0)
    np.testing.assert_allclose(np.asarray(out).reshape(n, 4)[0], expected)
    np.testing.assert_allclose(np.asarray(out).reshape(n, 4)[-1], expected)


def test_ppermute_ring_rotates():
    mesh = Mesh(np.array(jax.devices()), ("x",))
    n = len(jax.devices())
    fn = _make_collective("ppermute", mesh)
    x = jnp.repeat(jnp.arange(n, dtype=jnp.float32), 2)  # shard i = [i, i]
    out = np.asarray(fn(x, 1)).reshape(n, 2)
    # One ring shift: device (i+1) now holds i's data.
    for i in range(n):
        assert out[(i + 1) % n][0] == i


def test_ppermute_full_ring_roundtrip():
    """n chained shifts must return every shard to its origin."""
    mesh = Mesh(np.array(jax.devices()), ("x",))
    n = len(jax.devices())
    fn = _make_collective("ppermute", mesh)
    x = jnp.arange(n * 2, dtype=jnp.float32)
    np.testing.assert_allclose(np.asarray(fn(x, n)), np.asarray(x))


@pytest.mark.parametrize("op", ["all_reduce", "all_gather", "reduce_scatter",
                                "ppermute"])
def test_sweep_runs_all_ops(op):
    results = run_sweep(
        min_bytes=2**12, max_bytes=2**13, iters=2, warmup=1, op=op,
        dtype=jnp.float32,
    )
    assert len(results) == 2
    for r in results:
        assert r.time_us > 0
        assert r.bus_bw_gbps > 0
        assert r.size_bytes >= 2**12


def test_per_iter_sweep_reports_percentiles():
    """--percentiles timing: every round spanned individually, p50/p99
    populated and ordered, and the bench.<op> histogram fed."""
    from container_engine_accelerators_tpu.obs import histo, trace

    trace.reset()
    histo.reset()
    try:
        results = run_sweep(
            min_bytes=2**12, max_bytes=2**12, iters=3, warmup=1,
            op="all_reduce", dtype=jnp.float32, per_iter=True,
        )
    except NotImplementedError as e:  # pre-existing jax shard_map gap
        pytest.skip(f"chained collectives unavailable on this jax: {e}")
    (r,) = results
    assert r.p50_us is not None and r.p99_us is not None
    assert 0 < r.p50_us <= r.p99_us
    iter_spans = [s for s in trace.tail() if s["name"] == "bench.iter"]
    assert len(iter_spans) == 3
    assert histo.snapshot()["bench.all_reduce"]["count"] == 3
    # Default timing stays percentile-free (no per-round dispatch).
    plain = run_sweep(min_bytes=2**12, max_bytes=2**12, iters=2, warmup=1,
                      op="all_reduce", dtype=jnp.float32)
    assert plain[0].p50_us is None


def test_bad_step_factor_rejected():
    with pytest.raises(ValueError, match="step factor"):
        run_sweep(min_bytes=2**12, max_bytes=2**13, step_factor=1, iters=1,
                  warmup=1)


def test_per_rank_payload_accounting():
    """nccl-tests convention: size_bytes is the per-rank payload, not the
    global array size (which is n x larger for all_reduce)."""
    results = run_sweep(
        min_bytes=2**12, max_bytes=2**12, iters=2, warmup=1,
        op="all_reduce", dtype=jnp.float32,
    )
    assert results[0].size_bytes == 2**12
    gathered = run_sweep(
        min_bytes=2**13, max_bytes=2**13, iters=2, warmup=1,
        op="all_gather", dtype=jnp.float32,
    )
    assert gathered[0].size_bytes == 2**13


# ---- CLI verdict path (the nccl-test rig's PASS/FAIL bar) ------------------


def _run_cli(tmp_path, extra):
    from container_engine_accelerators_tpu.collectives.bench import main

    verdict_file = tmp_path / "verdict.json"
    rc = main(
        ["-b", "64K", "-e", "128K", "--iters", "2", "--warmup", "1",
         "--op", "all_reduce", "--verdict-json", str(verdict_file)] + extra
    )
    import json

    return rc, json.loads(verdict_file.read_text())


def test_cli_pass_verdict_artifact(tmp_path):
    rc, v = _run_cli(tmp_path, ["--line-rate-gbps", "1e-6"])
    assert rc == 0
    assert v["pass"] is True
    assert v["op"] == "all_reduce" and v["devices"] == len(jax.devices())
    assert v["line_rate_fraction"] > 1
    assert len(v["results"]) == 2
    assert all(r["bus_bw_gbps"] > 0 for r in v["results"])


def test_cli_fail_verdict_artifact(tmp_path):
    # A line rate no rig can reach: the bar must FAIL with rc 1.
    rc, v = _run_cli(tmp_path, ["--line-rate-gbps", "1e9"])
    assert rc == 1
    assert v["pass"] is False
    assert v["line_rate_fraction"] < 1


def test_cli_no_bar_records_null_verdict(tmp_path):
    rc, v = _run_cli(tmp_path, [])
    assert rc == 0
    assert v["pass"] is None and v["line_rate_gbps"] is None
