"""Self-tuning data plane: the closed-loop controller's decision table,
its registry, and the pipeline integration (ISSUE 11).

The decision table is pinned row by row against a pure FlowTuner —
shrink-on-retransmit, back-off-on-loss, grow-while-goodput-scales,
narrow-when-fan-out-costs, hysteresis (no flap on a noisy signal),
floor/ceiling clamps, kill-switch inertness — because the controller
is the part that must stay correct under everything the chaos suites
throw at the pipeline.  Integration runs against a real PyXferd pair
with the proc-mode link shim injecting loss, proving the loop closes
end to end with exactly-once intact while the grid changes between
retry rounds.  The fleet scenario e2e (degrade mid-run, heal, goodput
floor) is marked slow; `make tune` runs everything.
"""

import uuid

import pytest

from container_engine_accelerators_tpu.fleet.xferd import PyXferd
from container_engine_accelerators_tpu.metrics import counters
from container_engine_accelerators_tpu.obs import timeseries
from container_engine_accelerators_tpu.parallel import (
    dcn_pipeline,
    dcn_tune,
)
from container_engine_accelerators_tpu.parallel.dcn_client import (
    ResilientDcnXferClient,
)
from container_engine_accelerators_tpu.utils.retry import RetryPolicy

FAST_RETRY = RetryPolicy(
    max_attempts=6, initial_backoff_s=0.01, max_backoff_s=0.1,
    deadline_s=10.0,
)

BASE_CHUNK = 1 << 20
BASE_STRIPES = 2


@pytest.fixture(autouse=True)
def _fresh_tuners():
    dcn_tune.reset()
    yield
    dcn_tune.reset()


def tuner(**cfg_kw):
    cfg_kw.setdefault("min_chunk_bytes", 4096)
    return dcn_tune.FlowTuner("t:1", dcn_tune.TuneConfig(**cfg_kw))


def clean(t, goodput=1000.0, n=1, lane="socket"):
    out = []
    for _ in range(n):
        out.append(t.on_round(attempted=8, failed=0,
                              bytes_confirmed=int(goodput),
                              elapsed_s=1.0, lane=lane))
    return out


def lossy(t, retx=0.5, lane="socket"):
    failed = int(8 * retx)
    return t.on_round(attempted=8, failed=failed,
                      bytes_confirmed=(8 - failed) * 100,
                      elapsed_s=1.0, lane=lane)


class TestDecisionTable:
    def test_shrink_on_retransmit_halves_chunk(self):
        t = tuner()
        t.plan(BASE_CHUNK, 1)  # one stripe: no stripe lever to take
        assert lossy(t, retx=0.125) == "shrink_chunk"
        assert t.plan(BASE_CHUNK, 1)[0] == BASE_CHUNK // 2
        # Repeated loss keeps shrinking (multiplicative decrease is
        # NOT cooldown-gated) down to the floor.
        while lossy(t, retx=0.5) == "shrink_chunk":
            pass
        assert t.plan(BASE_CHUNK, 1)[0] == 4096
        c0 = counters.get("dcn.tune.clamped")
        assert lossy(t, retx=0.5) is None  # both levers at floor
        assert counters.get("dcn.tune.clamped") == c0 + 1

    def test_backoff_stripes_on_heavy_loss_before_chunk(self):
        t = tuner()
        t.plan(BASE_CHUNK, 4)
        assert lossy(t, retx=0.5) == "backoff_stripe"
        assert t.stripes_now() == 3
        # Light loss (below backoff_retx) goes for the chunk instead.
        assert lossy(t, retx=0.125) == "shrink_chunk"
        assert t.stripes_now() == 3

    def test_grow_while_goodput_scales(self):
        t = tuner(max_stripes=4)
        t.plan(BASE_CHUNK, 2)
        seen = []
        for _ in range(14):
            s = t.stripes_now()
            seen.append(t.on_round(attempted=8, failed=0,
                                   bytes_confirmed=1000 * s,
                                   elapsed_s=1.0))
        # Every up-probe paid off (perfect scaling): grown 2->3->4 and
        # kept both times; at the ceiling the one exploratory narrow
        # probe reverts (narrower measurably loses) and a floor pins
        # the optimum — no oscillation after that.
        assert seen.count("grow_stripe") == 2
        assert seen.count("keep_stripe") == 2
        assert t.stripes_now() == 4  # ceiling reached, scaling held

    def test_probe_reverts_when_goodput_stops_scaling(self):
        t = tuner()
        t.plan(BASE_CHUNK, BASE_STRIPES)
        decisions = clean(t, goodput=1000, n=8)  # flat: growth never pays
        assert "grow_stripe" in decisions
        assert "revert_stripe" in decisions
        assert "keep_stripe" not in decisions[:decisions.index(
            "revert_stripe")]

    def test_no_flap_after_revert(self):
        """The hysteresis headline: once a probe reverted, the same
        value is never re-probed while its bound lives — a noisy flat
        signal settles instead of oscillating.  (bound_ttl pinned
        high: TTL re-exploration has its own test below.)"""
        t = tuner(bound_ttl_obs=1000)
        t.plan(BASE_CHUNK, BASE_STRIPES)
        decisions = clean(t, goodput=1000, n=30)
        # Bounded exploration: one up-probe (reverted), one down-probe
        # (reverted: flat noise must not drain stripes), then silence.
        assert decisions.count("grow_stripe") == 1
        assert decisions.count("narrow_stripe") == 1
        tail = decisions[-15:]
        assert set(tail) == {None}
        assert t.stripes_now() == BASE_STRIPES

    def test_bounds_expire_and_reexplore(self):
        """A bound pinned by one (possibly noisy) measurement ages out
        after bound_ttl_obs clean observations: the tuner re-probes —
        bounded, infrequent — instead of freezing the grid forever on
        a loss-free link."""
        t = tuner(bound_ttl_obs=6)
        t.plan(BASE_CHUNK, BASE_STRIPES)
        decisions = clean(t, goodput=1000, n=40)
        assert decisions.count("grow_stripe") >= 2  # re-explored
        # ...but re-exploration is rare: far more silence than moves.
        assert decisions.count(None) > len(decisions) * 0.6
        assert t.stripes_now() == BASE_STRIPES  # flat noise: no drift

    def test_narrow_probe_kept_when_fanout_costs(self):
        """The loopback-rig shape: per-stripe overhead, 1 stripe beats
        2 — the controller must find the optimum BELOW its base."""
        per_stripe = {1: 530, 2: 430, 3: 380}
        t = tuner()
        t.plan(BASE_CHUNK, 2)
        for _ in range(14):
            s = t.stripes_now()
            t.on_round(attempted=8, failed=0,
                       bytes_confirmed=per_stripe.get(s, 300),
                       elapsed_s=1.0)
        assert t.stripes_now() == 1

    def test_cooldown_blocks_new_probe_right_after_a_move(self):
        """Hysteresis: after a kept probe (a move), the next probe
        cannot launch until the cooldown has passed — even though the
        clean streak already qualifies."""
        t = tuner(cooldown_obs=2, grow_clean_rounds=1, max_stripes=8)
        t.plan(BASE_CHUNK, 2)
        seen = []
        for _ in range(6):
            s = t.stripes_now()
            seen.append(t.on_round(attempted=8, failed=0,
                                   bytes_confirmed=1000 * s,
                                   elapsed_s=1.0))
        # grow (obs1), judged kept (obs2, a move), then TWO cooldown
        # observations before the next probe may launch.
        i = seen.index("keep_stripe")
        assert seen[i + 1] is None and seen[i + 2] is None
        assert seen[i + 3] == "grow_stripe"

    def test_loss_clears_probe_bounds(self):
        t = tuner()
        t.plan(BASE_CHUNK, BASE_STRIPES)
        clean(t, goodput=1000, n=8)  # probe + revert: ceiling learned
        assert t.snapshot()["stripe_ceiling"] is not None
        lossy(t, retx=0.125)
        assert t.snapshot()["stripe_ceiling"] is None

    def test_exposed_ratio_objective_vetoes_probe(self):
        """Goodput up but overlap WORSE: the probe still reverts —
        dcn.exposed_ratio is the objective, not a bystander.  (Two
        failing observations: the probe's noise patience spends one.)"""
        t = tuner(grow_clean_rounds=1, cooldown_obs=0,
                  probe_patience=2)
        t.plan(BASE_CHUNK, BASE_STRIPES)
        t.on_transfer(True, exposed_ratio=0.3)
        assert clean(t, goodput=1000, n=1)[0] == "grow_stripe"
        t.on_transfer(True, exposed_ratio=0.9)  # overlap collapsed
        assert clean(t, goodput=5000, n=1)[0] is None  # patience
        assert clean(t, goodput=5000, n=1)[0] == "revert_stripe"

    def test_chunk_recovers_to_base_after_heal(self):
        t = tuner()
        t.plan(BASE_CHUNK, 1)
        lossy(t, retx=0.25)
        lossy(t, retx=0.25)
        assert t.plan(BASE_CHUNK, 1)[0] == BASE_CHUNK // 4
        decisions = clean(t, n=12)
        assert decisions.count("grow_chunk") == 2
        assert t.plan(BASE_CHUNK, 1)[0] == BASE_CHUNK
        # Recovery stops AT base: the grid never grows past what the
        # operator configured.
        assert "grow_chunk" not in clean(t, n=8)
        assert t.plan(BASE_CHUNK, 1)[0] == BASE_CHUNK

    def test_shm_lane_bypasses_stripe_adaptation_keeps_chunk(self):
        t = tuner()
        t.plan(BASE_CHUNK, BASE_STRIPES)
        # Heavy loss on the shm lane: no stripe lever there — the
        # chunk shrinks instead.
        assert lossy(t, retx=0.5, lane="shm") == "shrink_chunk"
        assert t.stripes_now() == BASE_STRIPES
        # Clean shm rounds never launch stripe probes either.
        assert "grow_stripe" not in clean(t, n=8, lane="shm")
        assert "narrow_stripe" not in clean(t, n=8, lane="shm")

    def test_incomparable_samples_never_feed_probe_verdicts(self):
        """shm rounds (memcpy-class B/s) and partial retry rounds
        (fixed-overhead-dominated B/s) are not capability evidence: a
        probe judged against a baseline they skewed would revert what
        works or keep what doesn't."""
        t = tuner(grow_clean_rounds=1, cooldown_obs=0)
        t.plan(BASE_CHUNK, BASE_STRIPES)
        # Inflate-attempt via shm rounds at memcpy speed: clean
        # evidence for the streak, but NEVER baseline samples — the
        # probe below must be judged against socket-lane goodput.
        clean(t, goodput=10_000_000, n=4, lane="shm")
        assert clean(t, goodput=1000, n=1)[0] == "grow_stripe"
        # Probed-grid rounds that are PARTIAL neither qualify nor
        # spend patience — the verdict waits for comparable evidence.
        for _ in range(6):
            assert t.on_round(attempted=2, failed=0,
                              bytes_confirmed=50, elapsed_s=1.0,
                              full_round=False) is None
        assert t.snapshot()["probing"]
        # A full round with honestly-scaled goodput keeps the probe.
        assert t.on_round(attempted=8, failed=0, bytes_confirmed=2000,
                          elapsed_s=1.0) == "keep_stripe"

    def test_failed_transfer_counts_as_full_loss(self):
        t = tuner()
        t.plan(BASE_CHUNK, 4)
        t.on_transfer(False)
        assert t.stripes_now() == 3  # backoff fired

    def test_floor_ceiling_clamps(self):
        t = tuner(min_chunk_bytes=65536, max_stripes=3, min_stripes=2)
        chunk, stripes = t.plan(32768, 8)
        assert chunk == 32768  # a base below the floor stays put —
        #                        the floor bounds shrinking, it never
        #                        raises the operator's grid
        assert stripes == 3    # base above the ceiling clamps down
        chunk, stripes = t.plan(1 << 20, 1)
        assert stripes == 2    # min_stripes floor
        # Shrinking a small base is a no-op at its own floor.
        t2 = tuner(min_chunk_bytes=65536, min_stripes=1)
        t2.plan(32768, 1)
        assert lossy(t2, retx=0.5) is None  # clamped, not shrunk
        assert t2.plan(32768, 1)[0] == 32768

    def test_malformed_env_knobs_degrade_to_defaults(self):
        cfg = dcn_tune.TuneConfig(env={
            dcn_tune.MIN_CHUNK_ENV: "not-a-number",
            dcn_tune.MAX_STRIPES_ENV: "-3",
        })
        assert cfg.min_chunk_bytes == dcn_tune.DEFAULT_MIN_CHUNK_BYTES
        assert cfg.max_stripes == dcn_tune.DEFAULT_MAX_STRIPES


class TestCpuBoundHold:
    """The profiler verdict acted on: while the cpu_bound latch is
    set, stripe-growth probes are HELD (dcn.tune.cpu_hold), not
    reverted — a hold is not a move, so hysteresis never resets and
    growth resumes the instant the latch clears."""

    @staticmethod
    def _tuner_with_shares(shares, **cfg_kw):
        seq = list(shares)

        def share():
            return seq.pop(0) if len(seq) > 1 else seq[0]

        cfg_kw.setdefault("min_chunk_bytes", 4096)
        t = dcn_tune.FlowTuner(
            "t:cpu", dcn_tune.TuneConfig(**cfg_kw),
            staging_share=share)
        t.plan(BASE_CHUNK, 2)
        return t

    def test_hold_suppresses_growth_then_resumes(self):
        # Staging share climbs 0.10 -> 0.20 with flat goodput: the
        # latch sets on obs 2 (clean streak still below the growth
        # law), obs 3 would grow but is HELD, and with share flat the
        # latch clears so obs 4 grows — one observation of lag on
        # each edge, exactly as designed.
        t = self._tuner_with_shares(
            [0.10, 0.20, 0.20], grow_clean_rounds=3, max_stripes=4)
        h0 = counters.get("dcn.tune.cpu_hold")
        assert clean(t, n=2) == [None, None]
        assert timeseries.gauges()["dcn.tune.cpu_bound"] == 1.0
        assert clean(t) == [None]  # growth-eligible, held instead
        assert counters.get("dcn.tune.cpu_hold") == h0 + 1
        assert timeseries.gauges()["dcn.tune.cpu_bound"] == 0.0
        assert clean(t) == ["grow_stripe"]  # latch gone: growth back
        assert t.stripes_now() == 3

    def test_hold_is_not_a_move_no_hysteresis_reset(self):
        # Share climbs every observation: the latch never clears and
        # every growth-eligible observation is a hold.  If a hold
        # reset _since_move the cooldown would swallow alternate
        # observations and the hold count would halve — each extra
        # clean round must produce its own dcn.tune.cpu_hold.
        t = self._tuner_with_shares(
            [0.10, 0.20, 0.30, 0.40, 0.50, 0.60],
            grow_clean_rounds=3, cooldown_obs=1, max_stripes=4)
        h0 = counters.get("dcn.tune.cpu_hold")
        assert clean(t, n=6) == [None] * 6
        assert counters.get("dcn.tune.cpu_hold") == h0 + 4
        assert t.stripes_now() == 2  # never grew, never reverted

    def test_goodput_scaling_defeats_the_latch(self):
        # Share climbs but goodput climbs with it (beyond the slack):
        # the host is spending more CPU AND moving more bytes — that
        # is healthy scaling, not saturation, and growth proceeds.
        t = self._tuner_with_shares(
            [0.10, 0.20, 0.30, 0.40], grow_clean_rounds=3,
            max_stripes=4)
        h0 = counters.get("dcn.tune.cpu_hold")
        out = [clean(t, goodput=1000.0 * (1.3 ** i))[0]
               for i in range(4)]
        assert "grow_stripe" in out
        assert counters.get("dcn.tune.cpu_hold") == h0
        assert timeseries.gauges()["dcn.tune.cpu_bound"] == 0.0


class TestKillSwitch:
    def test_enabled_by_default(self):
        """The soak world (fleet/soak.py) is the standing evidence:
        absent the env var, the closed loop is ON.  TPU_DCN_TUNE=0
        remains the kill switch."""
        assert dcn_tune.tune_enabled(env={})
        assert dcn_pipeline.PipelineConfig(env={}).tuned

    def test_env_values(self):
        for raw in ("1", "true", "on", "yes"):
            assert dcn_tune.tune_enabled(env={dcn_tune.TUNE_ENV: raw})
        # "" is EXPLICITLY-set-empty — still off: an operator that
        # blanked the var asked for the static grid, default flip or
        # not.
        for raw in ("0", "false", "off", ""):
            assert not dcn_tune.tune_enabled(
                env={dcn_tune.TUNE_ENV: raw})

    def test_config_override_beats_env(self):
        env = {dcn_tune.TUNE_ENV: "1"}
        assert dcn_pipeline.PipelineConfig(env=env).tuned
        assert not dcn_pipeline.PipelineConfig(env=env,
                                               tuned=False).tuned

    def test_kill_switch_is_inert(self, tmp_path):
        """tuned=False: send_pipelined never consults the registry —
        today's static grid runs byte-for-byte (same chunk count, same
        stripe count, no controller state created)."""
        a = PyXferd(str(tmp_path / "a"), node="ka").start()
        b = PyXferd(str(tmp_path / "b"), node="kb").start()
        ca = ResilientDcnXferClient(str(tmp_path / "a"),
                                    retry=FAST_RETRY)
        cb = ResilientDcnXferClient(str(tmp_path / "b"),
                                    retry=FAST_RETRY)
        try:
            payload = bytes(range(256)) * 64  # 16 KiB
            cfg = dcn_pipeline.PipelineConfig(
                chunk_bytes=4096, stripes=2, shm=False, tuned=False)
            flow = f"kill-{uuid.uuid4().hex[:8]}"
            cb.register_flow(flow, bytes=len(payload))
            ca.register_flow(flow, bytes=len(payload))
            res = dcn_pipeline.send_pipelined(
                ca, flow, payload, "127.0.0.1", b.data_port, cfg,
                timeout_s=10)
            assert res["chunks"] == 4 and res["stripes"] == 2
            assert dcn_tune.snapshot() == {}  # registry never touched
            got = dcn_pipeline.read_pipelined(cb, flow, len(payload),
                                              cfg, timeout_s=10)
            assert got == payload
        finally:
            for c in (ca, cb):
                try:
                    c.close()
                except OSError:
                    pass
            a.stop()
            b.stop()


class TestRegistry:
    def test_same_key_same_tuner(self):
        t1 = dcn_tune.tuner_for("h:1")
        t2 = dcn_tune.tuner_for("h:1")
        assert t1 is t2
        assert dcn_tune.tuner_for("h:2") is not t1

    def test_lru_eviction_bounds_the_registry(self):
        keys = [f"h:{i}" for i in range(dcn_tune.MAX_TUNERS + 8)]
        for k in keys:
            dcn_tune.tuner_for(k)
        snap = dcn_tune.snapshot()
        assert len(snap) == dcn_tune.MAX_TUNERS
        # The oldest keys (a respawned daemon's dead ports) aged out.
        assert "h:0" not in snap and keys[-1] in snap

    def test_fresh_key_means_fresh_state(self):
        """The SIGKILL-respawn contract: a respawned daemon binds a
        fresh port, so its tuner starts from the static grid."""
        t = dcn_tune.tuner_for("h:1")
        t.plan(BASE_CHUNK, 1)
        lossy(t, retx=0.25)
        assert t.plan(BASE_CHUNK, 1)[0] < BASE_CHUNK
        t2 = dcn_tune.tuner_for("h:9999")  # the respawn's new port
        assert t2.plan(BASE_CHUNK, 1)[0] == BASE_CHUNK

    def test_plan_publishes_gauges(self):
        t = dcn_tune.tuner_for("h:1")
        t.plan(123456, 3)
        g = timeseries.gauges()
        assert g["dcn.tune.chunk_bytes"] == 123456.0
        assert g["dcn.tune.stripes"] == 3.0
        assert g["dcn.tune.flows"] >= 1.0


@pytest.fixture
def pair(tmp_path):
    a = PyXferd(str(tmp_path / "a"), node="ta").start()
    b = PyXferd(str(tmp_path / "b"), node="tb").start()
    ca = ResilientDcnXferClient(str(tmp_path / "a"), retry=FAST_RETRY)
    cb = ResilientDcnXferClient(str(tmp_path / "b"), retry=FAST_RETRY)
    yield a, b, ca, cb
    for c in (ca, cb):
        try:
            c.close()
        except OSError:
            pass
    a.stop()
    b.stop()


TUNED_CFG_KW = dict(chunk_bytes=4096, stripes=2, shm=False, tuned=True)


class TestPipelineIntegration:
    def _xfer(self, pair, payload, cfg, flow=None):
        a, b, ca, cb = pair
        flow = flow or f"ti-{uuid.uuid4().hex[:8]}"
        cb.register_flow(flow, bytes=len(payload))
        ca.register_flow(flow, bytes=len(payload))
        res = dcn_pipeline.send_pipelined(
            ca, flow, payload, "127.0.0.1", b.data_port, cfg,
            timeout_s=15)
        got = dcn_pipeline.read_pipelined(cb, flow, len(payload), cfg,
                                          timeout_s=15)
        return res, got

    def test_loss_shrinks_grid_and_stays_exactly_once(self, pair):
        """The loop closed end to end: the link shim eats chunks, the
        tuner reacts between retry rounds and transfers, the payload
        still lands byte-exact under the SAME seqs (chaos-suite
        exactly-once while the grid changes mid-transfer)."""
        a, b, _ca, _cb = pair
        payload = bytes(range(256)) * 64  # 16 KiB = 4 chunks
        cfg = dcn_pipeline.PipelineConfig(**TUNED_CFG_KW)
        shrink0 = counters.get("dcn.tune.shrink_chunk")
        backoff0 = counters.get("dcn.tune.backoff_stripe")
        # Eat the first 4 outbound frames toward b: round 0 loses every
        # chunk, the retry round re-sends all four under the same seqs.
        a.set_link_fault("127.0.0.1", b.data_port, "drop", 4)
        res, got = self._xfer(pair, payload, cfg)
        assert got == payload
        assert res["rounds"] >= 2
        moved = (counters.get("dcn.tune.shrink_chunk") > shrink0
                 or counters.get("dcn.tune.backoff_stripe") > backoff0)
        assert moved, "a fully-lost round must move the controller"
        # The NEXT transfer toward this destination plans the adapted
        # grid — more chunks than the static 4 (chunk shrank) or fewer
        # stripes (backoff); either way the plan moved.
        t = dcn_tune.tuner_for(f"127.0.0.1:{b.data_port}")
        chunk, stripes = t.plan(4096, 2)
        assert chunk < 4096 or stripes < 2

    def test_retransmit_ratio_published_per_round(self, pair,
                                                  monkeypatch):
        """Satellite: the gauge reflects loss the moment a round ends,
        not only at transfer completion — the value published after
        the FIRST round already counts the chunks that round lost."""
        a, b, _ca, _cb = pair
        published = []
        real_gauge = timeseries.gauge

        def spy(name, value):
            if name == "dcn.pipeline.retransmit_ratio":
                published.append(value)
            return real_gauge(name, value)

        monkeypatch.setattr(dcn_pipeline.timeseries, "gauge", spy)
        payload = bytes(range(256)) * 64  # 4 chunks of 4096
        cfg = dcn_pipeline.PipelineConfig(chunk_bytes=4096, stripes=2,
                                          shm=False, tuned=False)
        a.set_link_fault("127.0.0.1", b.data_port, "drop", 2)
        res, got = self._xfer(pair, payload, cfg)
        assert got == payload and res["rounds"] == 2
        # After round 0: 2 of 4 chunks pending -> 0.5, BEFORE any
        # retry round started.  After round 1: 2 resent, 0 pending.
        assert published[0] == pytest.approx(0.5)
        assert published[-1] == pytest.approx(0.5)

    def test_tuned_roundtrip_clean_link_matches_static_grid(self, pair):
        """First transfer to a fresh destination: the plan IS the
        static grid (learning starts from the operator's base)."""
        payload = bytes(range(256)) * 64
        cfg = dcn_pipeline.PipelineConfig(**TUNED_CFG_KW)
        res, got = self._xfer(pair, payload, cfg)
        assert got == payload
        assert res["chunks"] == 4 and res["stripes"] == 2


@pytest.mark.slow
class TestTunedFleetScenario:
    """The acceptance scenario shapes, in-process for speed: a link
    degrades mid-run (loss + latency through the fleet fabric), heals,
    and the report proves the controller acted AND goodput recovered
    above the floor — zero knob changes mid-run."""

    def test_degrade_heal_recovers_goodput(self):
        from container_engine_accelerators_tpu.fleet.controller import (
            run_scenario,
        )

        report = run_scenario({
            "name": "tune-degrade-inproc",
            "nodes": 3,
            "racks": 1,
            "chips": 2,
            "topology": "1x2x1",
            "rounds": 8,
            "payload_bytes": 65536,
            "pipelined": True,
            "tuned": True,
            "shm": False,
            "chunk_bytes": 16384,
            "stripes": 2,
            "faults": [
                {"round": 2, "link": "node:n0->node:n1:latency:20",
                 "for": 3},
                {"round": 2, "link": "node:n0->node:n1:drop:6"},
            ],
            "slo": {"min_final_goodput_bps": 1000},
        })
        assert report["converged"]
        assert report["slo"]["ok"], report["slo"]
        delta = report["agent_events_delta"]
        assert any(k.startswith("dcn.tune.") for k in delta), delta

    def test_proc_scenario_file_is_the_ci_gate(self):
        """scenarios/tune_link_degrade.json — the `make tune` leg:
        proc-mode fleet, link degraded via the worker link shim,
        heal, goodput floor judged from HTTP-scraped telemetry."""
        import os

        from container_engine_accelerators_tpu.fleet.controller import (
            load_scenario,
            run_scenario,
        )

        path = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "scenarios", "tune_link_degrade.json")
        report = run_scenario(load_scenario(path))
        assert report["proc"] and report["converged"]
        assert report["slo"]["ok"], report["slo"]
        delta = report["agent_events_delta"]
        assert any(k.startswith("dcn.tune.") for k in delta), delta
