"""Driver-contract tests: entry() and dryrun_multichip() must work."""

import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

import jax


def test_entry_compiles(monkeypatch):
    monkeypatch.setenv("GRAFT_BATCH", "2")
    monkeypatch.setenv("GRAFT_IMAGE_SIZE", "64")
    import __graft_entry__ as ge

    fn, args = ge.entry()
    out = jax.jit(fn)(*args)
    jax.block_until_ready(out)
    assert out.shape == (2, 1000)


@pytest.mark.slow  # duplicates the driver MULTICHIP artifact; `make test-all` / CI
def test_dryrun_multichip_8():
    import __graft_entry__ as ge

    ge.dryrun_multichip(8)
