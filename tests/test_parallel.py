"""Mesh/sharding helpers + DCN cluster-resolution tests."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from container_engine_accelerators_tpu.parallel import (
    batch_sharding,
    create_hybrid_mesh,
    create_mesh,
    shard_params,
)
from container_engine_accelerators_tpu.parallel.dcn import resolve_cluster
from container_engine_accelerators_tpu.parallel.mesh import _param_spec


class TestMesh:
    def test_create_mesh_all_data(self):
        mesh = create_mesh()
        assert dict(mesh.shape) == {"data": 8, "model": 1}

    def test_create_mesh_dp_tp(self):
        mesh = create_mesh(data=4, model=2)
        assert dict(mesh.shape) == {"data": 4, "model": 2}

    def test_bad_factorization_rejected(self):
        with pytest.raises(ValueError):
            create_mesh(data=3, model=2)
        with pytest.raises(ValueError):
            create_mesh(model=3)

    def test_hybrid_mesh_exposes_dcn_axis(self):
        # 2 "slices" of 4 devices each on the virtual CPU mesh.
        mesh = create_hybrid_mesh(ici_data=4, ici_model=1, num_slices=2)
        assert dict(mesh.shape) == {"dcn": 2, "data": 4, "model": 1}

    def test_batch_sharding_spans_dcn_and_data(self):
        mesh = create_hybrid_mesh(ici_data=4, ici_model=1, num_slices=2)
        sh = batch_sharding(mesh)
        assert sh.spec == P(("dcn", "data"))


class TestParamSpec:
    def test_conv_kernel_sharded_on_output_channels(self):
        # HWIO conv kernel: output channel axis (last) wins ties.
        assert _param_spec((3, 3, 64, 128), 2) == P(None, None, None, "model")

    def test_dense_kernel(self):
        assert _param_spec((256, 512), 4) == P(None, "model")

    def test_small_param_replicated(self):
        assert _param_spec((7,), 4) == P()
        assert _param_spec((), 4) == P()

    def test_indivisible_replicated(self):
        assert _param_spec((65, 33), 4) == P()

    def test_model_size_one_replicates(self):
        assert _param_spec((256, 512), 1) == P()

    def test_shard_params_tree(self):
        mesh = create_mesh(data=4, model=2)
        params = {"w": jnp.ones((8, 16)), "b": jnp.ones((3,))}
        sh = shard_params(params, mesh)
        assert sh["w"].spec == P(None, "model")
        assert sh["b"].spec == P()


class TestResolveCluster:
    def test_single_process_default(self):
        assert resolve_cluster({}) == (None, 1, 0)

    def test_explicit_coordinator(self):
        addr, n, pid = resolve_cluster(
            {
                "TPU_WORKER_COUNT": "4",
                "TPU_WORKER_ID": "2",
                "TPU_COORDINATOR_ADDR": "host0:9999",
            }
        )
        assert (addr, n, pid) == ("host0:9999", 4, 2)

    def test_coordinator_port_defaulted(self):
        addr, _, _ = resolve_cluster(
            {
                "TPU_WORKER_COUNT": "2",
                "TPU_WORKER_ID": "0",
                "TPU_COORDINATOR_ADDR": "host0",
            }
        )
        assert addr == "host0:8476"

    def test_derived_from_job_dns(self):
        addr, n, pid = resolve_cluster(
            {
                "TPU_WORKER_COUNT": "2",
                "JOB_COMPLETION_INDEX": "1",
                "JOB_NAME": "allreduce",
            }
        )
        assert addr == "allreduce-0.allreduce:8476"
        assert (n, pid) == (2, 1)

    def test_missing_worker_id_rejected(self):
        with pytest.raises(ValueError, match="TPU_WORKER_ID"):
            resolve_cluster({"TPU_WORKER_COUNT": "2"})

    def test_out_of_range_rejected(self):
        with pytest.raises(ValueError, match="outside"):
            resolve_cluster({"TPU_WORKER_COUNT": "2", "TPU_WORKER_ID": "5"})

    def test_no_dns_material_rejected(self):
        with pytest.raises(ValueError, match="JOB_NAME"):
            resolve_cluster({"TPU_WORKER_COUNT": "2", "TPU_WORKER_ID": "0"})
