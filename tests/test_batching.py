"""Continuous-batching engine (models/batching.py).

The load-bearing property: interleaved slot-based decoding must be
TOKEN-IDENTICAL to per-request generate() — requests joining the fleet
mid-flight, at different depths, with slot reuse, change nothing about
any request's output.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from container_engine_accelerators_tpu.models.batching import (
    DecodeEngine,
    bucket_len,
)
from container_engine_accelerators_tpu.models.generate import generate
from container_engine_accelerators_tpu.models.lm_train import (
    create_lm_train_state,
)
from container_engine_accelerators_tpu.models.transformer import (
    transformer_lm,
)

CFG = dict(vocab_size=97, num_layers=2, num_heads=4, head_dim=8,
           mlp_dim=32, num_kv_heads=2)


@pytest.fixture(scope="module")
def params():
    state = create_lm_train_state(
        transformer_lm(**CFG), jax.random.PRNGKey(3),
        jnp.zeros((1, 8), jnp.int32), tx=optax.sgd(0.1),
    )
    return state.params


@pytest.fixture(scope="module")
def decode_model():
    return transformer_lm(**CFG, decode=True)


# Module-level shared jit: repeated solo references at equal shapes
# (several tests reuse the same prompt-length/max-new pairs) are cache
# hits instead of fresh eager traces — part of the VERDICT r4 item-6
# suite-cost work.
_solo_generate = jax.jit(generate,
                         static_argnames=("model", "max_new_tokens"))


def _solo(decode_model, params, prompt_ids, n):
    """Per-request generate()'s generated tokens (the reference)."""
    prompt = jnp.asarray([prompt_ids], jnp.int32)
    out = np.asarray(_solo_generate(model=decode_model, params=params,
                                    prompt=prompt, max_new_tokens=n))
    return out[0, len(prompt_ids): len(prompt_ids) + n].tolist()


def test_interleaved_requests_match_solo_generate(decode_model, params):
    eng = DecodeEngine(decode_model, params, max_slots=3, max_len=32)
    r1 = eng.submit([5, 17, 42], max_new=7)
    eng.step()
    eng.step()
    # r2 joins while r1 is mid-flight, at a different depth and bucket.
    r2 = eng.submit([88, 3], max_new=5)
    eng.step()
    r3 = eng.submit([7, 9, 11, 2, 6], max_new=4)
    eng.run_until_drained()
    assert eng.result(r1) == _solo(decode_model, params, [5, 17, 42], 7)
    assert eng.result(r2) == _solo(decode_model, params, [88, 3], 5)
    assert eng.result(r3) == _solo(decode_model, params,
                                   [7, 9, 11, 2, 6], 4)


def test_slot_reuse_is_clean(decode_model, params):
    """A retired slot's leftover cache must not leak into the next
    request that lands on it (single-slot engine forces reuse)."""
    eng = DecodeEngine(decode_model, params, max_slots=1, max_len=32)
    r1 = eng.submit([5, 17, 42], max_new=6)
    eng.run_until_drained()
    r2 = eng.submit([88, 3, 9], max_new=6)
    eng.run_until_drained()
    assert eng.result(r1) == _solo(decode_model, params, [5, 17, 42], 6)
    assert eng.result(r2) == _solo(decode_model, params, [88, 3, 9], 6)


def test_fleet_full_and_capacity_guards(decode_model, params):
    eng = DecodeEngine(decode_model, params, max_slots=1, max_len=16)
    eng.submit([1, 2], max_new=3)
    with pytest.raises(RuntimeError, match="no free slot"):
        eng.submit([3], max_new=2)
    eng.run_until_drained()
    with pytest.raises(ValueError, match="slot holds"):
        eng.submit([1] * 10, max_new=10)  # 10 + 10 > 16


def test_eos_retires_early(decode_model, params):
    """With eos_id set to the first token generate() would emit at some
    step, the engine must stop that request there."""
    solo = _solo(decode_model, params, [5, 17, 42], 7)
    eos = solo[3]
    eng = DecodeEngine(decode_model, params, max_slots=2, max_len=32,
                       eos_id=eos)
    r = eng.submit([5, 17, 42], max_new=7)
    eng.run_until_drained()
    got = eng.result(r)
    assert got == solo[: got.index(eos) + 1]
    assert got[-1] == eos and len(got) <= len(solo)


def test_bucket_len():
    assert [bucket_len(n, 16) for n in (1, 2, 3, 5, 9, 16)] == \
        [1, 2, 4, 8, 16, 16]


@pytest.mark.slow
def test_bench_serving_cli():
    """cmd/bench_serving.py end-to-end at toy scale: both paths run,
    prefill agreement gates, the JSON line is well-formed."""
    import contextlib
    import importlib.util
    import io
    import json as _json
    import os

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "bench_serving_cli", os.path.join(repo, "cmd", "bench_serving.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = mod.main(["--slots", "2", "--requests", "4", "--max-new", "6",
                       "--prompt-lens", "3,5"])
    assert rc == 0
    line = _json.loads(buf.getvalue().strip().splitlines()[-1])
    assert line["metric"] == "serving_continuous_batching_ttft_speedup"
    assert line["value"] > 0 and line["throughput_speedup"] > 0
    assert 0.5 <= line["exact_match_fraction"] <= 1.0
    # Any mismatch must have been triaged as a bf16 near-tie — a real
    # divergence asserts inside main() before the JSON line prints.
    assert isinstance(line["tie_mismatches"], list)


def test_engine_loop_concurrent_requests_match_solo(decode_model, params):
    """EngineLoop: more threads than slots, all blocking concurrently —
    every response must equal its solo generate(), and the fleet-full
    wait path must release as slots drain."""
    import threading

    from container_engine_accelerators_tpu.models.batching import (
        EngineLoop,
    )

    loop = EngineLoop(DecodeEngine(decode_model, params, max_slots=2,
                                   max_len=32))
    prompts = [[5, 17, 42], [88, 3], [7, 9, 11], [2, 6]]
    results = {}

    def ask(i):
        results[i] = loop.generate(prompts[i], 5)

    threads = [threading.Thread(target=ask, args=(i,))
               for i in range(len(prompts))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300)
    assert len(results) == len(prompts)
    for i, p in enumerate(prompts):
        assert results[i] == _solo(decode_model, params, p, 5), i


def test_prefix_spliced_slots_match_solo_generate(decode_model, params):
    """Engine x prefix-cache: a slot started from a spliced prefix
    block must emit exactly generate(prefix + suffix)'s tokens, while
    plain and prefix requests interleave in the same fleet."""
    from container_engine_accelerators_tpu.models.prefix_cache import (
        PrefixCache,
    )

    pc = PrefixCache(decode_model, params, max_prefix_len=4)
    prefix = (5, 17, 42)
    entry = pc.get_or_build(prefix)

    eng = DecodeEngine(decode_model, params, max_slots=3, max_len=32)
    r1 = eng.submit([7, 9], max_new=6, prefix=entry)
    eng.step()
    # A plain request joins mid-flight; then a second prefix request
    # reusing the same entry at a different depth.
    r2 = eng.submit([88, 3], max_new=5)
    eng.step()
    r3 = eng.submit([1], max_new=4, prefix=entry)
    eng.run_until_drained()
    assert eng.result(r1) == _solo(decode_model, params,
                                   list(prefix) + [7, 9], 6)
    assert eng.result(r2) == _solo(decode_model, params, [88, 3], 5)
    assert eng.result(r3) == _solo(decode_model, params,
                                   list(prefix) + [1], 4)


def test_prefix_slot_capacity_guard(decode_model, params):
    from container_engine_accelerators_tpu.models.prefix_cache import (
        PrefixCache,
    )

    pc = PrefixCache(decode_model, params, max_prefix_len=16)
    entry = pc.get_or_build(tuple(range(1, 13)))  # bucket 16
    eng = DecodeEngine(decode_model, params, max_slots=1, max_len=16)
    with pytest.raises(ValueError, match="slot"):
        eng.submit([1, 2, 3, 4, 5], max_new=4, prefix=entry)


# ---- speculative continuous batching (SpecDecodeEngine, round 5) ----
#
# The load-bearing property extends models/speculative.py's exactness
# chain: interleaved draft/verify ROUNDS over the fleet must be
# token-identical to per-request generate_speculative at any
# acceptance rate — self-draft (acceptance ~1) and a random shallow
# draft (acceptance ~0) bracket it.

from container_engine_accelerators_tpu.models.batching import (  # noqa: E402
    SpecDecodeEngine,
)
from container_engine_accelerators_tpu.models.speculative import (  # noqa: E402
    generate_speculative,
)

D_CFG = dict(CFG, num_layers=1)


@pytest.fixture(scope="module")
def draft():
    state = create_lm_train_state(
        transformer_lm(**D_CFG), jax.random.PRNGKey(9),
        jnp.zeros((1, 8), jnp.int32), tx=optax.sgd(0.1),
    )
    return transformer_lm(**D_CFG, decode=True), state.params


_solo_generate_spec = jax.jit(
    generate_speculative,
    static_argnames=("model", "draft_model", "max_new_tokens", "k"))


def _solo_spec(decode_model, params, dm, dp, prompt_ids, n, k,
               prefix=None):
    prompt = jnp.asarray([prompt_ids], jnp.int32)
    out, _ = _solo_generate_spec(
        model=decode_model, params=params, draft_model=dm,
        draft_params=dp, prompt=prompt, max_new_tokens=n, k=k,
        prefix=prefix)
    return np.asarray(out)[0, len(prompt_ids): len(prompt_ids) + n].tolist()


@pytest.mark.parametrize("which", ["self", "1L"])
def test_spec_engine_matches_solo_speculative(decode_model, params,
                                              draft, which):
    dm, dp = (decode_model, params) if which == "self" else draft
    eng = SpecDecodeEngine(decode_model, params, dm, dp, max_slots=3,
                           max_len=40, k=3)
    r1 = eng.submit([5, 17, 42], max_new=7)
    eng.step()
    # r2/r3 join mid-flight at different depths and buckets; r4 reuses
    # a drained slot.
    r2 = eng.submit([88, 3], max_new=5)
    eng.step()
    r3 = eng.submit([7, 9, 11, 2, 6], max_new=6)
    eng.run_until_drained()
    # r4 reuses r1's (prompt-len 3, n=7) shape so its solo-spec
    # reference is a compile-cache hit (suite-cost work).
    r4 = eng.submit([1, 2, 3], max_new=7)
    eng.run_until_drained()
    for rid, ids, n in [(r1, [5, 17, 42], 7), (r2, [88, 3], 5),
                        (r3, [7, 9, 11, 2, 6], 6), (r4, [1, 2, 3], 7)]:
        assert eng.result(rid) == _solo_spec(
            decode_model, params, dm, dp, ids, n, 3), (which, rid)
    assert eng.spec_rounds > 0 and eng.spec_drafted > 0
    rate = eng.spec_accepted / eng.spec_drafted
    # Self-draft accepts ~everything (not asserted exact: the [S,1]
    # draft step and [S,k+1] verify chunk tile differently, and a bf16
    # argmax near-tie can flip on-chip — batching.py's own caveat); a
    # random 1-layer draft accepts almost nothing.  The bracket makes
    # the machinery's cost measurable.
    assert rate > 0.9 if which == "self" else rate < 0.5


def test_spec_engine_prefix_spliced_and_mixed(decode_model, params,
                                              draft):
    from container_engine_accelerators_tpu.models.prefix_cache import (
        PrefixCache,
    )

    dm, dp = draft
    pfx_ids = (11, 22, 33, 44, 55)
    t_kv, t_len = PrefixCache(decode_model, params,
                              max_prefix_len=16).get_or_build(pfx_ids)
    d_kv, _ = PrefixCache(dm, dp, max_prefix_len=16).get_or_build(pfx_ids)
    eng = SpecDecodeEngine(decode_model, params, dm, dp, max_slots=2,
                           max_len=48, k=3)
    ra = eng.submit([5, 17], max_new=6, prefix=(t_kv, d_kv, t_len))
    # A plain (unspliced) request shares the same fleet.
    rb = eng.submit([3, 1, 4, 1, 5], max_new=5)
    eng.run_until_drained()
    assert eng.result(ra) == _solo_spec(
        decode_model, params, dm, dp, [5, 17], 6, 3,
        prefix=(t_kv, d_kv, t_len))
    assert eng.result(rb) == _solo_spec(
        decode_model, params, dm, dp, [3, 1, 4, 1, 5], 5, 3)


def test_spec_engine_margin_admission(decode_model, params, draft):
    """A request that would let a final verify round write past the
    lane must be rejected up front (margin = k tail slots)."""
    dm, dp = draft
    eng = SpecDecodeEngine(decode_model, params, dm, dp, max_slots=1,
                           max_len=16, k=4)
    with pytest.raises(ValueError, match="slot holds"):
        eng.submit([1, 2, 3], max_new=10)  # 3 + 10 + 4 = 17 > 16
    eng.submit([1, 2, 3], max_new=9)  # 3 + 9 + 4 = 16: exactly fits
    eng.run_until_drained()


def test_spec_engine_eos_retires_early(decode_model, params):
    """EOS inside an accepted run of drafts truncates and retires the
    slot mid-round (self-draft so whole rounds are accepted)."""
    eng = SpecDecodeEngine(decode_model, params, decode_model, params,
                           max_slots=1, max_len=40, k=3)
    full = SpecDecodeEngine(decode_model, params, decode_model, params,
                            max_slots=1, max_len=40, k=3)
    want = _solo_spec(decode_model, params, decode_model, params,
                      [5, 17, 42], 8, 3)
    eos = want[3]  # stop partway through the sequence
    eng.eos_id = eos
    rid = eng.submit([5, 17, 42], max_new=8)
    eng.run_until_drained()
    got = eng.result(rid)
    assert got == want[: want.index(eos) + 1]
    # The untouched engine still produces the full sequence.
    rid2 = full.submit([5, 17, 42], max_new=8)
    full.run_until_drained()
    assert full.result(rid2) == want


def test_tp_engine_matches_solo_generate(decode_model, params):
    """Tensor-parallel continuous batching (round 5): with params
    Megatron-sharded and the fleet cache's KV heads sharded over the
    model axis, interleaved slot decoding must still equal
    single-device per-request generate()."""
    from container_engine_accelerators_tpu.parallel import (
        create_mesh,
        shard_params,
    )

    mesh = create_mesh(data=1, model=2, devices=jax.devices()[:2])
    tp_params = jax.device_put(params, shard_params(params, mesh))
    eng = DecodeEngine(decode_model, tp_params, max_slots=3, max_len=32,
                       mesh=mesh)
    r1 = eng.submit([5, 17, 42], max_new=7)
    eng.step()
    r2 = eng.submit([88, 3], max_new=5)
    eng.run_until_drained()
    r3 = eng.submit([1, 2, 3], max_new=7)  # slot reuse on the mesh
    eng.run_until_drained()
    assert eng.result(r1) == _solo(decode_model, params, [5, 17, 42], 7)
    assert eng.result(r2) == _solo(decode_model, params, [88, 3], 5)
    assert eng.result(r3) == _solo(decode_model, params, [1, 2, 3], 7)
    # The fleet cache is genuinely distributed, not replicated.
    kv_specs = {
        str(x.sharding.spec)
        for x in jax.tree_util.tree_leaves(eng.cache) if x.ndim >= 4
    }
    assert any("model" in s for s in kv_specs), kv_specs


# ---- sampled continuous batching (round 5) --------------------------


def test_sampled_lanes_match_per_request_generate(decode_model, params):
    """A sampled request in the fleet rides its OWN PRNGKey(seed)
    chain with generate()'s split/categorical discipline: tokens equal
    per-request generate(temperature, rng=PRNGKey(seed)) exactly, for
    any mix of greedy and sampled lanes — and independently of fleet
    composition."""
    def solo_sampled(ids, n, temp, seed):
        prompt = jnp.asarray([ids], jnp.int32)
        out = np.asarray(generate(decode_model, params, prompt, n,
                                  temperature=temp,
                                  rng=jax.random.PRNGKey(seed)))
        return out[0, len(ids): len(ids) + n].tolist()

    eng = DecodeEngine(decode_model, params, max_slots=3, max_len=32)
    r1 = eng.submit([5, 17, 42], max_new=6, temperature=0.7, seed=9)
    eng.step()
    r2 = eng.submit([88, 3], max_new=5)  # greedy joins mid-flight
    eng.step()
    r3 = eng.submit([7, 9, 11], max_new=4, temperature=1.3, seed=4)
    eng.run_until_drained()
    assert eng.result(r1) == solo_sampled([5, 17, 42], 6, 0.7, 9)
    assert eng.result(r2) == _solo(decode_model, params, [88, 3], 5)
    assert eng.result(r3) == solo_sampled([7, 9, 11], 4, 1.3, 4)

    # Fleet-composition independence: the same request alone in a
    # 1-slot engine produces the same tokens.
    eng2 = DecodeEngine(decode_model, params, max_slots=1, max_len=32)
    ra = eng2.submit([5, 17, 42], max_new=6, temperature=0.7, seed=9)
    eng2.run_until_drained()
    assert eng2.result(ra) == eng.result(r1)


def test_sampled_lane_with_prefix_matches_generate_with_prefix(
        decode_model, params):
    from container_engine_accelerators_tpu.models.prefix_cache import (
        PrefixCache,
        generate_with_prefix,
    )

    entry = PrefixCache(decode_model, params,
                        max_prefix_len=4).get_or_build((5, 17, 42))
    eng = DecodeEngine(decode_model, params, max_slots=2, max_len=32)
    rp = eng.submit([7, 9], max_new=5, prefix=entry, temperature=0.9,
                    seed=11)
    eng.run_until_drained()
    kv, plen = entry
    want = np.asarray(generate_with_prefix(
        decode_model, params, kv, plen,
        jnp.asarray([[7, 9]], jnp.int32), 5, temperature=0.9,
        rng=jax.random.PRNGKey(11)))
    assert eng.result(rp) == want[0, 2:7].tolist()


def test_spec_engine_sampled_lanes_match_per_request(decode_model,
                                                     params, draft):
    """Sampled lanes in the SPECULATIVE fleet run the rejection round
    per slot on the request's own seed chain: token-identical to
    per-request generate_speculative_sampled, mixed freely with
    greedy spec lanes, independent of fleet composition."""
    from container_engine_accelerators_tpu.models.speculative import (
        generate_speculative_sampled,
    )

    dm, dp = draft

    def solo(ids, n, temp, seed):
        out, _ = generate_speculative_sampled(
            decode_model, params, dm, dp,
            jnp.asarray([ids], jnp.int32), n, k=3, temperature=temp,
            rng=jax.random.PRNGKey(seed))
        return np.asarray(out)[0, len(ids): len(ids) + n].tolist()

    eng = SpecDecodeEngine(decode_model, params, dm, dp, max_slots=3,
                           max_len=40, k=3)
    r1 = eng.submit([5, 17, 42], max_new=6, temperature=0.7, seed=9)
    eng.step()
    r2 = eng.submit([88, 3], max_new=5)  # greedy spec lane mid-flight
    eng.step()
    r3 = eng.submit([7, 9, 11], max_new=4, temperature=1.3, seed=4)
    eng.run_until_drained()
    assert eng.result(r1) == solo([5, 17, 42], 6, 0.7, 9)
    assert eng.result(r2) == _solo_spec(decode_model, params, dm, dp,
                                        [88, 3], 5, 3)
    assert eng.result(r3) == solo([7, 9, 11], 4, 1.3, 4)

    # Fleet-composition independence for the sampled spec lane.
    eng2 = SpecDecodeEngine(decode_model, params, dm, dp, max_slots=1,
                            max_len=40, k=3)
    ra = eng2.submit([5, 17, 42], max_new=6, temperature=0.7, seed=9)
    eng2.run_until_drained()
    assert eng2.result(ra) == eng.result(r1)


def test_sampled_lane_on_tp_mesh_matches_single_device(decode_model,
                                                       params):
    """Sampled lanes x tensor parallelism: the per-request key chain
    is sharding-independent, so a sampled lane on the tp mesh equals
    single-device per-request sampled generate."""
    from container_engine_accelerators_tpu.parallel import (
        create_mesh,
        shard_params,
    )

    mesh = create_mesh(data=1, model=2, devices=jax.devices()[:2])
    tp_params = jax.device_put(params, shard_params(params, mesh))
    eng = DecodeEngine(decode_model, tp_params, max_slots=2,
                       max_len=32, mesh=mesh)
    r = eng.submit([5, 17, 42], max_new=6, temperature=0.7, seed=9)
    eng.submit([88, 3], max_new=4)  # greedy shares the fleet
    eng.run_until_drained()
    out = np.asarray(generate(
        decode_model, params, jnp.asarray([[5, 17, 42]], jnp.int32), 6,
        temperature=0.7, rng=jax.random.PRNGKey(9)))
    assert eng.result(r) == out[0, 3:9].tolist()


@pytest.mark.slow
def test_bench_serving_cli_sampled():
    """cmd/bench_serving.py --temperature (round 5): the sampled
    sequential-reference lambdas and seed plumbing run end-to-end and
    the exact-floor gate passes — on CPU the engine's key chains
    replicate generate()'s bit-for-bit, so agreement is 1.0."""
    import contextlib
    import importlib.util
    import io
    import json as _json
    import os

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "bench_serving_cli_sampled",
        os.path.join(repo, "cmd", "bench_serving.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = mod.main(["--slots", "2", "--requests", "4", "--max-new",
                       "6", "--prompt-lens", "3,5",
                       "--temperature", "1.0"])
    assert rc == 0
    line = _json.loads(buf.getvalue().strip().splitlines()[-1])
    assert line["metric"].endswith("_sampledT1")
    assert line["exact_match_fraction"] == 1.0
    # Speculative + sampled through the same CLI (rejection rounds in
    # the fleet, 1L draft so real rejections happen).
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = mod.main(["--slots", "2", "--requests", "3", "--max-new",
                       "5", "--prompt-lens", "4", "--temperature",
                       "1.0", "--speculative", "2",
                       "--spec-draft", "1L"])
    assert rc == 0
    line = _json.loads(buf.getvalue().strip().splitlines()[-1])
    assert line["metric"].endswith("_speck21L_sampledT1")
    assert line["exact_match_fraction"] == 1.0
    assert 0 <= line["spec_accept_rate"] < 0.95
