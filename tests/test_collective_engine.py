"""Topology-aware collective engine tests (collectives/topo|synth|runner).

Three layers, matching the subsystem: the comm graph (tiers, fault
evidence, the relative-goodput slowness pass, planning signatures),
schedule synthesis (every lowerable (collective, algorithm, fleet
shape) verified against the in-memory simulator; the cost model's
algorithm choice; re-synthesis on signature change), and the runner
(schedules executed over a real in-process fleet through the link
table — busbw accounting, failure semantics, fault -> resynth ->
heal -> recover).  Whole-scenario e2es are marked ``slow`` (the
tier-1 budget rule); the fast layers cover the machinery.
"""

import json

import pytest

from container_engine_accelerators_tpu.collectives import synth
from container_engine_accelerators_tpu.collectives.topo import (
    DEGRADED_LINK_PENALTY,
    PARTITIONED_LINK_PENALTY,
    TIER_ALPHA_S,
    TIER_BW_BPS,
    CommGraph,
)
from container_engine_accelerators_tpu.fleet.controller import (
    DEFAULT_COLLECTIVE_SCENARIO,
    FleetController,
    run_scenario,
)
from container_engine_accelerators_tpu.fleet.links import LinkTable
from container_engine_accelerators_tpu.fleet.topology import (
    TIER_CROSS_RACK,
    TIER_ICI,
    TIER_INTRA_RACK,
    FleetTopology,
    build_specs,
)
from container_engine_accelerators_tpu.metrics import counters


def _graph(nodes=4, racks=2, faults=(), rates=None, specs=None):
    topo = FleetTopology(specs or build_specs(nodes, racks=racks))
    links = LinkTable(topo)
    for f in faults:
        assert links.apply(f), f"fault {f!r} armed nothing"
    return CommGraph.build(topo, links=links,
                           rates=rates or (lambda a, b: 0.0))


# ---- comm graph ------------------------------------------------------------


class TestCommGraph:
    def test_every_ordered_pair_is_an_edge_with_its_tier(self):
        g = _graph(4, racks=2)
        assert g.edge("n0", "n2").tier == TIER_INTRA_RACK
        assert g.edge("n0", "n1").tier == TIER_CROSS_RACK
        names = g.nodes()
        assert all(g.edge(a, b) is not None
                   for a in names for b in names if a != b)

    def test_ici_tier_for_same_slice_hosts(self):
        specs = build_specs(2, racks=1, topology="4x2x1")
        specs[0].slice_id = specs[1].slice_id = "s0"
        specs[1].coords = "1,0,0"
        g = _graph(specs=specs)
        assert g.edge("n0", "n1").tier == TIER_ICI

    def test_partition_prices_infinite_and_directional(self):
        g = _graph(faults=["node:n0->node:n1:partition"])
        assert not g.up("n0", "n1")
        assert g.leg_cost_s("n0", "n1", 1024) == float("inf")
        assert g.up("n1", "n0")
        assert g.leg_cost_s("n1", "n0", 1024) < 1.0

    def test_latency_lands_in_alpha(self):
        clean = _graph().leg_cost_s("n0", "n1", 4096)
        g = _graph(faults=["node:n0->node:n1:latency:20"])
        assert g.edge("n0", "n1").degraded
        assert g.leg_cost_s("n0", "n1", 4096) == pytest.approx(
            clean + 0.020)

    def test_drop_budget_discounts_bandwidth(self):
        clean = _graph().leg_cost_s("n0", "n1", 1 << 20)
        g = _graph(faults=["node:n0->node:n1:drop:5"])
        assert g.edge("n0", "n1").degraded
        degraded = g.leg_cost_s("n0", "n1", 1 << 20)
        assert degraded > clean
        beta_clean = clean - TIER_ALPHA_S[TIER_CROSS_RACK]
        beta_degraded = degraded - TIER_ALPHA_S[TIER_CROSS_RACK]
        assert beta_degraded == pytest.approx(4 * beta_clean)

    def test_signature_moves_on_fault_and_heal_only(self):
        topo = FleetTopology(build_specs(4, racks=2))
        links = LinkTable(topo)
        build = lambda: CommGraph.build(  # noqa: E731
            topo, links=links, rates=lambda a, b: 0.0)
        clean = build().signature()
        assert clean == ()
        links.apply("rack:r0<->rack:r1:latency:10")
        faulted = build().signature()
        assert faulted != clean and len(faulted) == 8
        links.apply("rack:r0<->rack:r1:heal")
        assert build().signature() == clean

    def test_slow_pass_flags_active_laggard_not_idle_links(self):
        """Goodput evidence is relative: an ACTIVE edge far under its
        tier's best flags `slow`; idle edges (decayed windows) and the
        healthy peers never do — and the flag stays OUT of the
        planning signature (measurement noise must not re-plan)."""
        rates = {("n0", "n1"): 2e6, ("n1", "n0"): 1e5,
                 ("n2", "n3"): 2e6}

        def rate(a, b):
            return rates.get((a, b), 0.0)

        g = _graph(rates=rate)
        assert not g.edge("n0", "n1").slow      # the tier peak
        assert g.edge("n1", "n0").slow          # active, 5% of peak
        assert not g.edge("n2", "n3").slow      # healthy peer
        assert not g.edge("n3", "n2").slow      # idle: no evidence
        assert g.edge("n1", "n0").suspect
        assert not g.edge("n1", "n0").degraded
        assert g.signature() == ()
        # ...but it does shape cost and the placement penalty.
        assert g.leg_cost_s("n1", "n0", 1 << 20) > \
            g.leg_cost_s("n0", "n1", 1 << 20)
        assert g.node_health()["n1"]["degraded_links"] == 1

    def test_rates_below_trust_floor_are_not_evidence(self):
        g = _graph(rates=lambda a, b: 512.0)  # everything "active" low
        assert not any(g.edge(a, b).slow for a in g.nodes()
                       for b in g.nodes() if a != b)

    def test_node_health_rollup(self):
        g = _graph(faults=["node:n0<->node:n1:partition",
                           "node:n2->node:n3:latency:5"])
        health = g.node_health()
        assert health["n0"]["partitioned_links"] == 2  # both directions
        assert health["n2"]["degraded_links"] == 1
        assert health["n3"]["degraded_links"] == 1

    def test_penalty_ordering(self):
        assert PARTITIONED_LINK_PENALTY > DEGRADED_LINK_PENALTY > 0

    def test_rack_major_order(self):
        g = _graph(6, racks=2)
        assert g.order() == ["n0", "n2", "n4", "n1", "n3", "n5"]


# ---- chunk math ------------------------------------------------------------


class TestPartition:
    def test_even_split(self):
        assert synth.partition(8, 4) == [(0, 2), (2, 2), (4, 2), (6, 2)]

    def test_remainder_spreads_forward(self):
        assert synth.partition(10, 4) == [(0, 3), (3, 3), (6, 2), (8, 2)]

    def test_tiny_payload_yields_zero_chunks(self):
        parts = synth.partition(2, 4)
        assert [ln for _, ln in parts] == [1, 1, 0, 0]
        assert sum(ln for _, ln in parts) == 2

    def test_bus_factor_matches_bench_conventions(self):
        assert synth.bus_factor("all_reduce", 8) == pytest.approx(2 * 7 / 8)
        assert synth.bus_factor("all_gather", 8) == pytest.approx(7 / 8)
        assert synth.bus_factor("reduce_scatter", 8) == pytest.approx(7 / 8)
        assert synth.bus_factor("ppermute", 8) == 1.0


# ---- synthesis -------------------------------------------------------------


SHAPES = [(1, 2), (1, 3), (1, 4), (2, 4), (2, 6), (3, 6)]


class TestSynthesis:
    @pytest.mark.parametrize("racks,nodes", SHAPES)
    @pytest.mark.parametrize("collective", synth.COLLECTIVES)
    @pytest.mark.parametrize("algorithm", synth.ALGORITHMS)
    def test_every_lowerable_schedule_is_simulation_correct(
            self, racks, nodes, collective, algorithm):
        g = _graph(nodes, racks=racks)
        try:
            sched = synth.synthesize(g, collective, 1000,
                                     algorithm=algorithm)
        except synth.SynthesisError:
            pytest.skip("not lowerable for this shape")
        inputs = synth.make_inputs(collective, sched.order, 1000, seed=7)
        out = synth.simulate(sched, inputs)
        expected = synth.expected_outputs(collective, sched.order,
                                          inputs, 1000)
        for name, (off, ln, want) in expected.items():
            assert bytes(out[name][off:off + ln]) == want, \
                f"{collective}/{algorithm} wrong on {name}"

    def test_payload_smaller_than_node_count_still_correct(self):
        g = _graph(4, racks=2)
        for algorithm in synth.ALGORITHMS:
            sched = synth.synthesize(g, "all_reduce", 3,
                                     algorithm=algorithm)
            inputs = synth.make_inputs("all_reduce", sched.order, 3)
            out = synth.simulate(sched, inputs)
            want = synth.expected_outputs("all_reduce", sched.order,
                                          inputs, 3)
            for name, (off, ln, exp) in want.items():
                assert bytes(out[name][off:off + ln]) == exp

    def test_hierarchical_guards(self):
        with pytest.raises(synth.SynthesisError):
            synth.synthesize(_graph(4, racks=1), "all_reduce", 1000,
                             algorithm="hierarchical")
        lopsided = build_specs(5, racks=2)  # 3 + 2 nodes
        with pytest.raises(synth.SynthesisError):
            synth.synthesize(_graph(specs=lopsided), "all_reduce",
                             1000, algorithm="hierarchical")
        # all_gather / reduce_scatter now HAVE two-level lowerings;
        # the shape guards still apply to them.
        with pytest.raises(synth.SynthesisError):
            synth.synthesize(_graph(specs=lopsided), "all_gather",
                             1000, algorithm="hierarchical")
        sched = synth.synthesize(_graph(4, racks=2), "all_gather",
                                 1000, algorithm="hierarchical")
        assert sched.algorithm == "hierarchical"

    def test_auto_choice_skips_unlowerable_candidates(self):
        sched = synth.synthesize(_graph(4, racks=1), "all_reduce", 1000)
        assert sched.algorithm in ("ring", "tree")
        lopsided = build_specs(5, racks=2)  # unequal racks
        sched = synth.synthesize(_graph(specs=lopsided), "all_gather",
                                 1000)
        assert sched.algorithm in ("ring", "tree")

    def test_degraded_cross_rack_tier_selects_hierarchical(self):
        g = _graph(4, racks=2,
                   faults=["rack:r0<->rack:r1:latency:25"])
        costs = {a: synth.synthesize(g, "all_reduce", 262144,
                                     algorithm=a).est_cost_s
                 for a in synth.ALGORITHMS}
        assert costs["hierarchical"] < costs["ring"]
        assert costs["hierarchical"] < costs["tree"]
        assert synth.synthesize(g, "all_reduce",
                                262144).algorithm == "hierarchical"

    def test_uniform_fast_links_prefer_ring_for_large_payloads(self):
        """With no slow tier the alpha terms wash out and ring's lower
        per-node byte volume wins at large S — the cost model keeps a
        genuine tradeoff, not a hierarchical hardcode."""
        specs = build_specs(8, racks=1)
        g = _graph(specs=specs)
        big = 64 << 20
        costs = {a: synth.synthesize(g, "all_reduce", big,
                                     algorithm=a).est_cost_s
                 for a in ("ring", "tree")}
        assert costs["ring"] < costs["tree"]
        assert synth.synthesize(g, "all_reduce", big).algorithm == "ring"

    def test_cost_model_serializes_endpoint_fanin(self):
        """A tree root receiving n-1 concurrent transfers pays their
        SUM, not their max — root contention is the whole reason tree
        loses at scale."""
        g = _graph(4, racks=1)
        sched = synth.synthesize(g, "all_reduce", 1 << 20,
                                 algorithm="tree")
        up_group = sched.steps[0]
        single = g.leg_cost_s(up_group[0].src, up_group[0].dst,
                              up_group[0].nbytes)
        assert synth.estimate_cost_s(g, [up_group]) == pytest.approx(
            3 * single)

    def test_partitioned_graph_prices_infinite_but_still_plans(self):
        g = _graph(4, racks=2,
                   faults=["rack:r0<->rack:r1:partition"])
        sched = synth.synthesize(g, "all_reduce", 4096)
        assert sched.est_cost_s == float("inf")
        assert sched.to_dict()["est_cost_ms"] is None

    def test_schedule_to_dict_is_json_clean(self):
        sched = synth.synthesize(_graph(4, racks=2), "all_reduce", 4096)
        assert json.dumps(sched.to_dict())

    def test_synthesizer_caches_until_signature_moves(self):
        topo = FleetTopology(build_specs(4, racks=2))
        links = LinkTable(topo)
        build = lambda: CommGraph.build(  # noqa: E731
            topo, links=links, rates=lambda a, b: 0.0)
        s = synth.Synthesizer("all_reduce", 4096)
        before = counters.get("collective.resynth")
        first = s.schedule_for(build())
        assert s.schedule_for(build()) is first
        assert s.resynth_count == 0
        assert counters.get("collective.resynth") == before

        links.apply("rack:r0<->rack:r1:latency:25")
        second = s.schedule_for(build())
        assert second is not first
        assert s.resynth_count == 1
        assert counters.get("collective.resynth") == before + 1
        assert s.current() is second

        links.apply("rack:r0<->rack:r1:heal")
        third = s.schedule_for(build())
        assert third is not second
        assert s.resynth_count == 2


# ---- config ----------------------------------------------------------------


def test_collective_config_from_scenario_drops_unknown_keys():
    from container_engine_accelerators_tpu.collectives.runner import (
        CollectiveConfig,
    )

    cfg = CollectiveConfig.from_scenario(
        {"op": "all_gather", "bytes": 1234, "definitely_a_typo": 9})
    assert cfg.op == "all_gather"
    assert cfg.bytes == 1234
    assert not hasattr(cfg, "definitely_a_typo")
    assert CollectiveConfig.from_scenario(None).op == "all_reduce"


# ---- runner over the in-process rig ----------------------------------------


class TestRunner:
    def _fleet(self, nodes=3, racks=1):
        return FleetController({
            "name": "engine-test", "nodes": nodes, "racks": racks,
            "chips": 2, "topology": "1x2x1", "rounds": 0,
            "metrics": False,
        }).boot()

    def _engine(self, ctl, **cfg_kw):
        from container_engine_accelerators_tpu.collectives.runner import (
            CollectiveConfig,
            CollectiveEngine,
        )

        cfg_kw.setdefault("op", "all_reduce")
        cfg_kw.setdefault("bytes", 8192)
        return CollectiveEngine(ctl.nodes, ctl.topology,
                                links=ctl.links,
                                cfg=CollectiveConfig(**cfg_kw))

    def test_round_moves_real_bytes_and_accounts_busbw(self):
        ctl = self._fleet()
        try:
            engine = self._engine(ctl)
            try:
                before = counters.get("collective.transfers")
                entry = engine.run_round(0)
                assert entry["ok"], entry
                assert entry["busbw_bps"] > 0
                assert entry["algbw_bps"] > 0
                assert entry["time_ms"] > 0
                assert counters.get("collective.transfers") \
                    == before + entry["transfers"]
                # Every frame crossed the link table: the rig's links
                # carry exactly the schedule's bytes.
                delivered = sum(l["bytes"] for l
                                in ctl.links.report().values())
                assert delivered > 0
                from container_engine_accelerators_tpu.obs import (
                    timeseries,
                )

                gauges = timeseries.gauges()
                assert gauges["collective.busbw_bps"] == pytest.approx(
                    entry["busbw_bps"], rel=0.01)
            finally:
                engine.close()
        finally:
            ctl.close()

    @pytest.mark.parametrize("collective", synth.COLLECTIVES)
    def test_each_collective_verifies_on_the_wire(self, collective):
        ctl = self._fleet()
        try:
            engine = self._engine(ctl, op=collective, bytes=4096)
            try:
                entry = engine.run_round(1)
                assert entry["ok"], entry
                assert entry["collective"] == collective
            finally:
                engine.close()
        finally:
            ctl.close()

    def test_fault_resynthesizes_and_heal_recovers(self):
        ctl = self._fleet(nodes=4, racks=2)
        try:
            engine = self._engine(ctl, bytes=16384)
            try:
                healthy = engine.run_round(0)
                assert healthy["ok"] and healthy["resynth"] == 0

                ctl.links.apply("rack:r0<->rack:r1:latency:25")
                degraded = engine.run_round(1)
                assert degraded["ok"]
                assert degraded["resynth"] == 1
                assert degraded["busbw_bps"] < healthy["busbw_bps"]

                ctl.links.apply("rack:r0<->rack:r1:heal")
                recovered = engine.run_round(2)
                assert recovered["resynth"] == 1
                assert recovered["busbw_bps"] > degraded["busbw_bps"]
                assert engine.synth.resynth_count == 2
            finally:
                engine.close()
        finally:
            ctl.close()

    def test_partition_fails_round_without_wedging(self):
        ctl = self._fleet(nodes=4, racks=2)
        try:
            engine = self._engine(ctl, bytes=4096, leg_attempts=1,
                                  leg_deadline_s=2.0,
                                  land_timeout_s=0.5)
            try:
                ctl.links.apply("rack:r0<->rack:r1:partition")
                failures0 = counters.get("collective.failures")
                entry = engine.run_round(0)
                assert not entry["ok"]
                assert entry["error"]
                assert entry["busbw_bps"] == 0.0
                assert counters.get("collective.failures") > failures0
                ctl.links.apply("rack:r0<->rack:r1:heal")
                entry = engine.run_round(1)
                assert entry["ok"], entry
            finally:
                engine.close()
        finally:
            ctl.close()


# ---- whole-scenario e2e (slow: the tier-1 budget rule) ---------------------


@pytest.mark.slow
class TestCollectiveScenarios:
    def test_builtin_scenario_degrades_resynthesizes_recovers(self):
        report = run_scenario(dict(DEFAULT_COLLECTIVE_SCENARIO))
        assert report["converged"]
        assert report["slo"]["ok"]
        assert report["collective"]["resynth"] >= 2
        rounds = [leg for rnd in report["rounds"] for leg in rnd["legs"]
                  if leg.get("workload") == "collective"]
        assert all(r["ok"] for r in rounds)
        # The fault is round 2 `for: 2`: degraded busbw must dip below
        # the healthy rounds and recover by the end.
        degraded = min(r["busbw_bps"] for r in rounds[2:4])
        assert degraded < rounds[0]["busbw_bps"]
        assert rounds[-1]["busbw_bps"] > degraded

    def test_xrack_degrade_scenario_file_passes_its_slo(self):
        from container_engine_accelerators_tpu.fleet.controller import (
            load_scenario,
        )

        report = run_scenario(load_scenario(
            "scenarios/collective_xrack_degrade.json"))
        assert report["converged"]
        assert report["slo"]["ok"], report["slo"]
        assert report["collective"]["resynth"] >= 2

    def test_proc_mode_collective_with_mirrored_fault(self):
        report = run_scenario({
            "name": "coll-proc", "proc": True,
            "workload": "collective",
            "nodes": 4, "racks": 2, "chips": 2, "topology": "1x2x1",
            "rounds": 4, "payload_bytes": 16384,
            "collective": {"op": "all_reduce", "bytes": 16384,
                           "land_timeout_s": 6.0,
                           "leg_deadline_s": 15.0},
            "faults": [{"round": 1,
                        "link": "rack:r0<->rack:r1:latency:25",
                        "for": 2}],
            "slo": {"min_final_busbw_bps": 10000},
        })
        assert report["converged"]
        assert report["slo"]["ok"], report["slo"]
        # The coordinator mirror gave the planner the fault evidence
        # even though no frame routes through the coordinator table.
        assert report["collective"]["resynth"] >= 2
        rounds = [leg for rnd in report["rounds"]
                  for leg in rnd["legs"]
                  if leg.get("workload") == "collective"]
        assert rounds[1]["busbw_bps"] < rounds[0]["busbw_bps"]

    def test_compare_cli_hierarchical_beats_ring(self, capsys):
        from container_engine_accelerators_tpu.collectives import runner

        rc = runner.main([
            "--compare", "--nodes", "4", "--racks", "2",
            "--bytes", "65536", "--xrack-latency-ms", "25",
            "--rounds", "2", "--margin", "1.2",
        ])
        assert rc == 0
        verdict = json.loads(
            capsys.readouterr().out.strip().splitlines()[-1])
        assert verdict["pass"]
        assert verdict["ratio"] >= 1.2
