"""Fleet simulation rig: multi-node chaos, link faults, fleet traces.

The single-node chaos suite (tests/test_chaos.py) proves each agent
self-heals; this file proves the *fleet* does: N emulated nodes wired
through a link table, rack partitions and asymmetric loss injected at
the LINK level (not the endpoint), survivors re-converging once the
fault clears, frame sequencing delivering exactly once under replay,
and one trace id spanning every process a transfer touches.

Long scenarios are marked ``slow`` (the tier-1 budget rule); the fast
units and the headline partition/reconverge + dedup tests stay in the
default tier.  ``make fleet`` runs the whole file.
"""

import importlib.util
import json
import os
import subprocess
import sys
import time
import uuid

import pytest

from container_engine_accelerators_tpu.fleet import (
    DEFAULT_SCENARIO,
    EmulatedNode,
    FleetController,
    FleetNet,
    LinkTable,
    NodeSpec,
    PyXferd,
)
from container_engine_accelerators_tpu.fleet.controller import run_scenario
from container_engine_accelerators_tpu.fleet.links import parse_link_fault
from container_engine_accelerators_tpu.fleet.topology import (
    TIER_CROSS_RACK,
    TIER_ICI,
    TIER_INTRA_RACK,
    FleetTopology,
    build_specs,
)
from container_engine_accelerators_tpu.metrics import counters
from container_engine_accelerators_tpu.obs import trace
from container_engine_accelerators_tpu.parallel import dcn, dcn_pipeline
from container_engine_accelerators_tpu.parallel.dcn_client import (
    DcnXferError,
    ResilientDcnXferClient,
)
from container_engine_accelerators_tpu.utils.retry import RetryPolicy
from tests.mp_runner import run_procs

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

FAST_RETRY = RetryPolicy(
    max_attempts=8, initial_backoff_s=0.01, max_backoff_s=0.1,
    deadline_s=15.0,
)


def _flow_stat(client, flow):
    return next(f for f in client.stats()["flows"] if f["flow"] == flow)


def _wait_stable_rx(client, flow, expect, settle_s=0.25):
    """Wait until rx hits ``expect`` and PROVE it stays there — the
    exactly-once assertions need 'no double-landing', which a plain
    wait cannot show."""
    dcn.wait_flow_rx(client, flow, expect, timeout_s=10)
    deadline = time.monotonic() + settle_s
    while time.monotonic() < deadline:
        assert _flow_stat(client, flow)["rx_bytes"] == expect
        time.sleep(0.02)


# ---------------------------------------------------------------------------
# Link-fault spec grammar + topology model
# ---------------------------------------------------------------------------


class TestLinkFaultSpec:
    def test_bidirectional_partition(self):
        f = parse_link_fault("rack:r0<->rack:r1:partition")
        assert (f.sel_a, f.sel_b) == ("rack:r0", "rack:r1")
        assert f.bidirectional and f.action == "partition"

    def test_directional_latency_ms(self):
        f = parse_link_fault("node:n0->node:n2:latency:5")
        assert not f.bidirectional
        assert f.action == "latency" and f.param == pytest.approx(0.005)

    def test_wildcard_drop(self):
        f = parse_link_fault("*->rack:r1:drop:3")
        assert f.sel_a == "*" and f.action == "drop" and f.param == 3

    def test_inverse(self):
        part = parse_link_fault("rack:r0<->rack:r1:partition")
        assert part.inverse().action == "heal"
        lat = parse_link_fault("node:a->node:b:latency:7")
        assert lat.inverse().param == 0.0
        assert parse_link_fault("*<->*:drop:2").inverse() is None

    def test_spec_roundtrip_is_json_clean(self):
        for s in ("rack:r0<->rack:r1:partition", "node:a->node:b:latency:5",
                  "*->rack:r1:drop:3"):
            f = parse_link_fault(s)
            assert parse_link_fault(f.spec()) == f

    @pytest.mark.parametrize("bad", [
        "garbage", "rack:r0:partition", "rack:r0<->rack:r1:frobnicate",
        "rack:r0<->rack:r1:latency:-1", "rack:r0<->rack:r1:drop:0",
        "<->:partition", "node:n0->node:n1:partition:5",
    ])
    def test_malformed_specs_never_raise(self, bad):
        assert parse_link_fault(bad) is None


class TestFleetTopology:
    def _fleet(self):
        return FleetTopology(build_specs(4, racks=2))

    def test_round_robin_racks(self):
        topo = self._fleet()
        assert topo.specs["n0"].rack == "r0"
        assert topo.specs["n1"].rack == "r1"
        assert topo.specs["n2"].rack == "r0"

    def test_selectors(self):
        topo = self._fleet()
        assert topo.select("*") == ["n0", "n1", "n2", "n3"]
        assert topo.select("node:n2") == ["n2"]
        assert topo.select("rack:r1") == ["n1", "n3"]
        assert topo.select("rack:nope") == []
        assert topo.select("zone:z1") == []

    def test_tiers_use_production_distance(self):
        specs = build_specs(4, racks=2)
        # Two hosts in one slice: ICI territory for the scheduler.
        specs[2].slice_id = specs[0].slice_id = "sliceX"
        topo = FleetTopology(specs)
        assert topo.tier("n0", "n2") == TIER_ICI
        specs[2].slice_id = None
        topo = FleetTopology(specs)
        assert topo.tier("n0", "n2") == TIER_INTRA_RACK  # both r0
        assert topo.tier("n0", "n1") == TIER_CROSS_RACK

    def test_node_coords_reach_the_production_distance(self):
        """NodeSpec carries REAL mesh coords (the labels no longer
        hardcode "0,0,0"): two hosts of one slice at different
        coordinates are distinguishable to the production distance —
        actual ICI torus hops, not an aliased zero."""
        from container_engine_accelerators_tpu.scheduler import (
            topology as sched_topo,
        )

        specs = build_specs(2, racks=1, topology="4x2x1")
        specs[0].slice_id = specs[1].slice_id = "sliceX"
        specs[0].coords = "0,0,0"
        specs[1].coords = "2,0,0"
        assert specs[1].labels()[sched_topo.COORDS_LABEL] == "2,0,0"
        topo = FleetTopology(specs)
        # 2 hops on the 4-wide torus axis — non-zero AND below the
        # DCN floor, so the pair still classifies as ICI.
        assert topo.distance("n0", "n1") == 2.0
        assert topo.tier("n0", "n1") == TIER_ICI
        # Farther coords cost more: the distance function actually
        # discriminates between member hosts now.
        specs[1].coords = "1,1,0"
        assert FleetTopology(specs).distance("n0", "n1") == 2.0
        specs[1].coords = "1,0,0"
        assert FleetTopology(specs).distance("n0", "n1") == 1.0

    def test_scenario_node_lists_carry_slice_and_coords(self):
        """Explicit scenario node dicts pass slice/coords through to
        the specs, so multi-host-slice fleets are declarable."""
        from container_engine_accelerators_tpu.fleet.controller import (
            _scenario_specs,
        )

        specs = _scenario_specs({"nodes": [
            {"name": "h0", "slice": "s0", "coords": "0,0,0"},
            {"name": "h1", "slice": "s0", "coords": "1,0,0"},
        ]})
        topo = FleetTopology(specs)
        assert topo.distance("h0", "h1") == 1.0
        assert topo.tier("h0", "h1") == TIER_ICI


class TestLinkTable:
    def _table(self):
        return LinkTable(FleetTopology(build_specs(4, racks=2)))

    def test_partition_is_bidirectional_and_heals(self):
        t = self._table()
        pairs = t.apply("rack:r0<->rack:r1:partition")
        assert ("n0", "n1") in pairs and ("n1", "n0") in pairs
        assert not t.state("n0", "n1").up
        assert not t.state("n3", "n2").up
        assert t.state("n0", "n2").up  # intra-rack untouched
        t.apply("rack:r0<->rack:r1:heal")
        assert t.state("n0", "n1").up

    def test_directional_fault_leaves_reverse_up(self):
        t = self._table()
        t.apply("node:n0->node:n1:partition")
        assert not t.state("n0", "n1").up
        assert t.state("n1", "n0").up

    def test_drop_budget_accumulates_and_heal_clears(self):
        t = self._table()
        t.apply("node:n0->node:n1:drop:2")
        t.apply("node:n0->node:n1:drop:1")
        assert t.state("n0", "n1").drop_next == 3
        t.apply("node:n0<->node:n1:heal")
        assert t.state("n0", "n1").drop_next == 0

    def test_report_is_tier_annotated(self):
        t = self._table()
        t.apply("node:n0->node:n1:latency:2")
        rep = t.report()
        assert rep["n0->n1"]["tier"] == TIER_CROSS_RACK
        assert rep["n0->n1"]["up"] is True

    def test_malformed_spec_applies_nothing(self):
        t = self._table()
        assert t.apply("not a spec") == []


# ---------------------------------------------------------------------------
# PyXferd: protocol fidelity + the data plane
# ---------------------------------------------------------------------------


@pytest.fixture
def xferd_pair(tmp_path):
    a = PyXferd(str(tmp_path / "a"), node="na").start()
    b = PyXferd(str(tmp_path / "b"), node="nb").start()
    ca = ResilientDcnXferClient(str(tmp_path / "a"), retry=FAST_RETRY)
    cb = ResilientDcnXferClient(str(tmp_path / "b"), retry=FAST_RETRY)
    yield a, b, ca, cb
    for c in (ca, cb):
        try:
            c.close()
        except OSError:
            pass
    a.stop()
    b.stop()


PAYLOAD = bytes(range(256)) * 16  # 4 KiB
N = len(PAYLOAD)


def _transfer(ca, cb, b, flow=None, payload=PAYLOAD):
    """One one-way leg na → nb; returns the landed bytes."""
    flow = flow or f"f-{uuid.uuid4().hex[:8]}"
    cb.register_flow(flow, bytes=len(payload))
    ca.register_flow(flow, bytes=len(payload))
    ca.put(flow, payload)
    dcn.wait_flow_rx(ca, flow, len(payload), timeout_s=10)
    ca.send(flow, "127.0.0.1", b.data_port, len(payload))
    dcn.wait_flow_rx(cb, flow, len(payload), timeout_s=10)
    return flow, cb.read(flow, len(payload))


class TestPyXferdProtocol:
    def test_version_advertises_v2_frames(self, xferd_pair):
        _a, _b, ca, _cb = xferd_pair
        assert ca.version().startswith("pyxferd/")

    def test_control_plane_contract(self, xferd_pair):
        _a, _b, ca, _cb = xferd_pair
        ca.ping()
        ca.register_flow("g0", peer="peer", bytes=8192)
        with pytest.raises(DcnXferError, match="already exists"):
            ca.register_flow("g0")
        assert ca.record_transfer("g0", 100) == 100
        assert ca.record_transfer("g0", 100) == 200
        stats = ca.stats()
        assert stats["generation"] == 1
        assert {f["flow"] for f in stats["flows"]} == {"g0"}
        ca.release_flow("g0")
        assert ca.stats()["active_flows"] == 0

    def test_data_plane_roundtrip(self, xferd_pair):
        a, b, ca, cb = xferd_pair
        _flow, got = _transfer(ca, cb, b)
        assert got == PAYLOAD

    def test_send_without_staging_is_a_daemon_error(self, xferd_pair):
        _a, b, ca, _cb = xferd_pair
        ca.register_flow("empty", bytes=64)
        with pytest.raises(DcnXferError, match="nothing staged"):
            # Bypass the resilient restage (there is no cached payload
            # for a flow never put) — the error must surface verbatim.
            ca.send("empty", "127.0.0.1", b.data_port, 64)


@pytest.mark.chaos
class TestFrameDedup:
    """ROADMAP 'DCN data-plane idempotence': per-flow frame seq +
    receiver dedup window == exactly-once delivery under every replay
    shape."""

    def test_lost_response_replay_lands_exactly_once(self, xferd_pair):
        """THE kill-mid-send scenario: the sender's daemon processed
        the send (frame delivered) but died before answering.  The
        client reconnects, replays its flows, restages, and re-sends
        the SAME seq — the receiver's dedup window drops it."""
        a, b, ca, cb = xferd_pair
        flow, _ = _transfer(ca, cb, b, flow="f")
        d0 = counters.get("dcn.frames.deduped")
        r0 = counters.get("dcn.send.restaged")

        a.drop_response_once("send")
        resp = ca.send(flow, "127.0.0.1", b.data_port, N)
        assert resp["ok"]
        _wait_stable_rx(cb, flow, 2 * N)  # seq2 once — not 3*N
        assert counters.get("dcn.frames.deduped") == d0 + 1
        assert counters.get("dcn.send.restaged") == r0 + 1
        assert cb.read(flow, N) == PAYLOAD

    def test_receiver_kill9_mid_transfer_replay_exactly_once(
            self, xferd_pair):
        """Kill -9 the RECEIVING daemon mid-transfer; after it
        restarts (fresh dedup window, fresh accounting) the replay
        lands exactly once into the fresh state."""
        a, b, ca, cb = xferd_pair
        flow, _ = _transfer(ca, cb, b, flow="f")

        b.stop(crash=True)
        b.start()
        cb.ping()  # reconnect + flow-table replay re-registers `f`
        ca.send(flow, "127.0.0.1", b.data_port, N)
        _wait_stable_rx(cb, flow, N)  # exactly once — not 2*N
        assert cb.read(flow, N) == PAYLOAD
        assert cb.stats()["generation"] == 2

    def test_sender_kill9_restages_and_resends(self, xferd_pair):
        """Kill -9 the SENDING daemon: the staged payload is gone; the
        client's send path restages from its cache and the transfer
        still completes."""
        a, b, ca, cb = xferd_pair
        flow, _ = _transfer(ca, cb, b, flow="f")

        a.stop(crash=True)
        a.start()
        resp = ca.send(flow, "127.0.0.1", b.data_port, N)
        assert resp["ok"]
        _wait_stable_rx(cb, flow, 2 * N)

    def test_lost_frame_retransmit_lands(self, tmp_path):
        """Loss ≠ replay: a frame eaten in flight never landed, so the
        retransmit (a NEW send) must pass the dedup window."""
        topo = FleetTopology(build_specs(2, racks=2))
        table = LinkTable(topo)
        net = FleetNet(table)
        a = PyXferd(str(tmp_path / "a"), node="n0", net=net).start()
        b = PyXferd(str(tmp_path / "b"), node="n1", net=net).start()
        net.register("n0", a)
        net.register("n1", b)
        ca = ResilientDcnXferClient(str(tmp_path / "a"), retry=FAST_RETRY)
        cb = ResilientDcnXferClient(str(tmp_path / "b"), retry=FAST_RETRY)
        try:
            cb.register_flow("f", bytes=N)
            ca.register_flow("f", bytes=N)
            ca.put("f", PAYLOAD)
            dcn.wait_flow_rx(ca, "f", N, timeout_s=10)

            table.apply("node:n0->node:n1:drop:1")
            resp = ca.send("f", "127.0.0.1", b.data_port, N)
            assert resp["ok"]  # the sender cannot tell — that's loss
            time.sleep(0.1)
            assert _flow_stat(cb, "f")["rx_bytes"] == 0

            ca.send("f", "127.0.0.1", b.data_port, N)  # retransmit
            _wait_stable_rx(cb, "f", N)
            link = table.report()["n0->n1"]
            assert link["drops"] == 1 and link["frames"] == 1
            assert cb.read("f", N) == PAYLOAD
        finally:
            ca.close()
            cb.close()
            a.stop()
            b.stop()


# Small grid so the chaos scenarios exercise real multi-chunk
# transfers in milliseconds: 16 KiB payload = 4 chunks.  The chaos
# bar holds on BOTH data lanes — the zero-copy same-host shm lane
# (the default in the one-process rig) and the socket lane cross-host
# deployments ride — so the chunk-chaos scenarios run once per lane.
# tuned=False here and in LANE_CFGS: these scenarios assert the
# static wire contract — the (now default-on) loop would adapt it.
PIPE_CFG = dcn_pipeline.PipelineConfig(chunk_bytes=4096, stripes=2,
                                       tuned=False)
PIPE_PAYLOAD = bytes(range(256)) * 64  # 16 KiB
PIPE_N = len(PIPE_PAYLOAD)

LANE_CFGS = {
    # ring=False: these scenarios arm drop_response("send"), i.e. the
    # per-chunk control-op shape.  The descriptor-ring handoff has no
    # per-chunk ops to drop — its work-done-answer-lost chaos story
    # (doorbell response dies, completer lands anyway, retry dedups)
    # lives in tests/test_dcn_shm.py::TestRingHandoff.
    "shm": dcn_pipeline.PipelineConfig(chunk_bytes=4096, stripes=2,
                                       shm=True, ring=False,
                                       tuned=False),
    # ring=False on the socket row too: with the universal ring the
    # socket lane is also descriptor-driven by default, which removes
    # the per-chunk "send" ops these drops target.  The ring-driven
    # socket lane's chaos story lives in tests/test_dcn_ring.py.
    "socket": dcn_pipeline.PipelineConfig(chunk_bytes=4096, stripes=2,
                                          shm=False, ring=False,
                                          tuned=False),
}


@pytest.mark.chaos
class TestPipelinedChunkChaos:
    """ISSUE 4 chaos bar: exactly-once PER CHUNK.  After any replay or
    loss, the assembled payload is byte-exact — no duplicated chunk,
    no zero-filled chunk.  Parametrized over the shm and socket lanes
    (ISSUE 6 fault parity): the lane moves bytes, never authority, so
    every verdict/dedup expectation is lane-invariant."""

    def _fleet_pair(self, tmp_path):
        topo = FleetTopology(build_specs(2, racks=2))
        table = LinkTable(topo)
        net = FleetNet(table)
        a = PyXferd(str(tmp_path / "a"), node="n0", net=net).start()
        b = PyXferd(str(tmp_path / "b"), node="n1", net=net).start()
        net.register("n0", a)
        net.register("n1", b)
        ca = ResilientDcnXferClient(str(tmp_path / "a"), retry=FAST_RETRY)
        cb = ResilientDcnXferClient(str(tmp_path / "b"), retry=FAST_RETRY)
        return net, table, a, b, ca, cb

    @pytest.mark.parametrize("lane", sorted(LANE_CFGS))
    def test_kill_mid_send_lost_response_chunks_land_once(
            self, xferd_pair, lane):
        """THE kill-mid-send shape, chunk edition: the sender's daemon
        streams a chunk but the op response dies with the control
        connection.  The retry round re-sends under the SAME seqs; the
        already-landed chunk dedups, the rest land — the assembled
        payload is byte-exact with no double-landed bytes."""
        cfg = LANE_CFGS[lane]
        a, b, ca, cb = xferd_pair
        cb.register_flow("pk", bytes=PIPE_N)
        ca.register_flow("pk", bytes=PIPE_N)
        d0 = counters.get("dcn.frames.deduped")
        a.drop_response_once("send")
        res = dcn_pipeline.send_pipelined(
            ca, "pk", PIPE_PAYLOAD, "127.0.0.1", b.data_port, cfg,
            timeout_s=10)
        assert res["rounds"] >= 2  # the lost response forced a retry
        assert res["lane"] == lane
        _wait_stable_rx(cb, "pk", PIPE_N)  # exactly PIPE_N — not PIPE_N + a chunk
        assert counters.get("dcn.frames.deduped") == d0 + 1
        assert dcn_pipeline.read_pipelined(cb, "pk", PIPE_N, cfg) \
            == PIPE_PAYLOAD

    def test_receiver_kill9_mid_pipelined_transfer(self, tmp_path):
        """Kill -9 the receiving daemon with chunks in flight: the
        transfer fails loudly (the fleet fabric routes by live data
        port), and the caller-level retry after the restart lands a
        complete, byte-exact payload into the fresh daemon — no
        zero-filled chunks from the dead incarnation."""
        net, _table, a, b, ca, cb = self._fleet_pair(tmp_path)
        try:
            cb.register_flow("rk", bytes=PIPE_N)
            ca.register_flow("rk", bytes=PIPE_N)
            b.stop(crash=True)
            with pytest.raises(DcnXferError, match="unconfirmed"):
                dcn_pipeline.send_pipelined(
                    ca, "rk", PIPE_PAYLOAD, "127.0.0.1", b.data_port,
                    PIPE_CFG, timeout_s=3)
            b.start()
            net.register("n1", b)
            cb.ping()  # reconnect + flow-table replay re-registers rk
            res = dcn_pipeline.send_pipelined(
                ca, "rk", PIPE_PAYLOAD, "127.0.0.1", b.data_port,
                PIPE_CFG, timeout_s=10)
            assert res["rounds"] == 1
            _wait_stable_rx(cb, "rk", PIPE_N)
            assert cb.stats()["generation"] == 2
            assert dcn_pipeline.read_pipelined(cb, "rk", PIPE_N, PIPE_CFG) \
                == PIPE_PAYLOAD
        finally:
            ca.close()
            cb.close()
            a.stop()
            b.stop()

    @pytest.mark.parametrize("lane", sorted(LANE_CFGS))
    def test_link_loss_retransmits_only_lost_chunks(self, tmp_path,
                                                    lane):
        """Loss ≠ replay, chunk edition: the link eats two chunk
        frames in flight; the sender's fabric verdicts say 'dropped',
        the retry round re-sends exactly those chunks under their
        original seqs, and they LAND (never-landed seqs pass the
        window) — zero dups, byte-exact assembly."""
        cfg = LANE_CFGS[lane]
        net, table, a, b, ca, cb = self._fleet_pair(tmp_path)
        try:
            cb.register_flow("lk", bytes=PIPE_N)
            ca.register_flow("lk", bytes=PIPE_N)
            d0 = counters.get("dcn.frames.deduped")
            table.apply("node:n0->node:n1:drop:2")
            res = dcn_pipeline.send_pipelined(
                ca, "lk", PIPE_PAYLOAD, "127.0.0.1", b.data_port,
                cfg, timeout_s=10)
            assert res["rounds"] == 2
            assert res["lane"] == lane
            _wait_stable_rx(cb, "lk", PIPE_N)
            link = table.report()["n0->n1"]
            assert link["drops"] == 2
            assert link["dups"] == 0  # lost chunks were never replays
            assert counters.get("dcn.frames.deduped") == d0
            assert dcn_pipeline.read_pipelined(cb, "lk", PIPE_N, cfg) \
                == PIPE_PAYLOAD
        finally:
            ca.close()
            cb.close()
            a.stop()
            b.stop()

    def test_shm_lane_node_kill_downgrade_exactly_once(self, tmp_path):
        """The satellite's mid-run restart shape: a transfer completes
        on the shm lane, the sending daemon is SIGKILLed and comes
        back WITHOUT the capability, and the next transfer on the SAME
        flow rides the socket lane — byte-exact, no dups, the seq
        numbering continuous across the lane switch."""
        net, _table, a, b, ca, cb = self._fleet_pair(tmp_path)
        try:
            cb.register_flow("dg", bytes=PIPE_N)
            ca.register_flow("dg", bytes=PIPE_N)
            res = dcn_pipeline.send_pipelined(
                ca, "dg", PIPE_PAYLOAD, "127.0.0.1", b.data_port,
                LANE_CFGS["shm"], timeout_s=10)
            assert res["lane"] == "shm"
            assert dcn_pipeline.read_pipelined(
                cb, "dg", PIPE_N, LANE_CFGS["shm"]) == PIPE_PAYLOAD
            a.stop(crash=True)
            a.shm_enabled = False  # restarts as a capability-less build
            a.start()
            net.register("n0", a)
            ca.ping()  # reconnect + flow replay + capability re-probe
            d0 = counters.get("dcn.frames.deduped")
            res = dcn_pipeline.send_pipelined(
                ca, "dg", PIPE_PAYLOAD[::-1], "127.0.0.1", b.data_port,
                LANE_CFGS["shm"], timeout_s=10)
            assert res["lane"] == "socket"
            _wait_stable_rx(cb, "dg", 2 * PIPE_N)
            assert counters.get("dcn.frames.deduped") == d0
            assert dcn_pipeline.read_pipelined(
                cb, "dg", PIPE_N, LANE_CFGS["shm"]) \
                == PIPE_PAYLOAD[::-1]
        finally:
            ca.close()
            cb.close()
            a.stop()
            b.stop()

    def test_pipelined_fleet_scenario_converges_under_partition(self):
        """The fleet rig's ring workload over the pipelined path:
        partition mid-run, heal, re-converge — the `make fleet`
        acceptance leg in miniature.  One-process fleet nodes are
        same-host, so these legs ride the shm lane; the scenario's
        `shm: false` knob pins the socket lane for the parity run
        below."""
        report = run_scenario({
            "name": "pipelined-partition",
            "nodes": 3,
            "racks": 3,
            "rounds": 4,
            "payload_bytes": 32768,
            "pipelined": True,
            "tuned": False,  # static-grid assertions below
            "chunk_bytes": 8192,
            "stripes": 2,
            "faults": [
                {"round": 1, "link": "rack:r0<->rack:r1:partition",
                 "for": 2},
            ],
        })
        assert report["converged"]
        r1 = report["rounds"][1]["legs"]
        assert any(not leg.get("ok", False) for leg in r1)
        assert all(leg["ok"] for leg in report["rounds"][-1]["legs"])
        assert report["agent_events_delta"].get(
            "dcn.pipeline.transfers", 0) > 0
        assert report["agent_events_delta"].get(
            "dcn.shm.transfers", 0) > 0

    def test_socket_lane_scenario_knob_pins_the_lane(self):
        """`shm: false` in a scenario spec keeps every leg on the
        socket lane — the fault-parity run `make fleet` drives via
        --no-shm."""
        report = run_scenario({
            "name": "pipelined-socket-parity",
            "nodes": 2,
            "racks": 2,
            "rounds": 2,
            "payload_bytes": 16384,
            "pipelined": True,
            "tuned": False,  # static-grid assertions below
            "chunk_bytes": 8192,
            "stripes": 2,
            "shm": False,
            "faults": [],
        })
        assert report["converged"]
        assert report["agent_events_delta"].get(
            "dcn.pipeline.transfers", 0) > 0
        assert report["agent_events_delta"].get(
            "dcn.shm.transfers", 0) == 0


@pytest.mark.chaos
class TestReadRestaging:
    def test_read_after_daemon_restart_restages_transparently(
            self, xferd_pair):
        """ROADMAP 'resilient read restaging': the caller-side
        put-again workaround moves into the client."""
        a, _b, ca, _cb = xferd_pair
        ca.register_flow("stage", bytes=N)
        ca.put("stage", PAYLOAD)
        dcn.wait_flow_rx(ca, "stage", N, timeout_s=10)
        assert ca.read("stage", N) == PAYLOAD

        r0 = counters.get("dcn.read.restaged")
        a.stop(crash=True)
        a.start()
        # Zero manual intervention: reconnect + replay + restage + read.
        assert ca.read("stage", N) == PAYLOAD
        assert counters.get("dcn.read.restaged") == r0 + 1

    def test_peer_landed_flow_has_no_cache_and_stays_empty(
            self, xferd_pair):
        """Restaging only applies to payloads THIS client staged; a
        peer-landed flow lost to a restart still reads empty (only the
        peer can re-send it)."""
        a, b, ca, cb = xferd_pair
        flow, _ = _transfer(ca, cb, b, flow="f")
        b.stop(crash=True)
        b.start()
        cb.ping()
        assert cb.read(flow, N) == b""


# ---------------------------------------------------------------------------
# Trace context across nodes and processes
# ---------------------------------------------------------------------------


@pytest.mark.chaos
class TestCrossNodeTrace:
    def test_in_process_transfer_is_one_trace(self, xferd_pair):
        """Client op, sender-daemon send, receiver-daemon land: one
        trace id end to end (control protocol + frame meta carry it)."""
        a, b, ca, cb = xferd_pair
        trace.reset()
        cb.register_flow("t", bytes=N)
        ca.register_flow("t", bytes=N)
        ca.put("t", PAYLOAD)
        dcn.wait_flow_rx(ca, "t", N, timeout_s=10)
        with trace.span("test.transfer") as root:
            ca.send("t", "127.0.0.1", b.data_port, N)
        # No settle sleep: land_frame records the xferd.land span
        # BEFORE waking rx waiters (the notify sits in a finally after
        # the span closes), so a returned wait_flow_rx guarantees the
        # span is in the buffer.
        dcn.wait_flow_rx(cb, "t", N, timeout_s=10)
        spans = trace.tail()
        mine = [s for s in spans if s["trace"] == root.trace_id]
        names = {s["name"] for s in mine}
        assert {"test.transfer", "dcn.send", "xferd.op",
                "xferd.send", "xferd.land"} <= names
        land = next(s for s in mine if s["name"] == "xferd.land")
        assert land["attrs"]["node"] == "nb"
        assert land["attrs"]["src"] == "na"

    def test_cross_process_transfer_merges_to_one_trace(self, tmp_path):
        """The ISSUE acceptance bar: one cross-node transfer, two
        processes, two JSONLs, ONE trace id — merged by
        cmd/agent_trace.py."""
        workdir = str(tmp_path)
        trace_id, root_span = os.urandom(8).hex(), os.urandom(4).hex()
        files = {}
        envs, cmds = [], []
        for role in ("recv", "send"):
            env = dict(os.environ)
            env.pop("TPU_FAULT_SPEC", None)  # determinism under make chaos
            files[role] = os.path.join(workdir, f"{role}.jsonl")
            env.update({
                "FLEET_ROLE": role,
                "FLEET_WORKDIR": workdir,
                "FLEET_PAYLOAD": str(N),
                "TPU_TRACE_FILE": files[role],
                "TPU_TRACE_CONTEXT": f"{trace_id}:{root_span}",
            })
            envs.append(env)
            cmds.append([sys.executable,
                         os.path.join(REPO, "tests",
                                      "fleet_trace_worker.py")])
        run_procs(cmds, envs, cwd=REPO, timeout=120)

        per_side = {}
        for role, path in files.items():
            spans = [json.loads(line) for line in open(path)]
            per_side[role] = [s for s in spans if s["trace"] == trace_id]
            assert per_side[role], f"{role} JSONL carries no trace spans"
        # The receiver's LANDING span rode the frame meta, not just the
        # env: it must hang off the sender's xferd.send context.
        recv_names = {s["name"] for s in per_side["recv"]}
        send_names = {s["name"] for s in per_side["send"]}
        assert "xferd.land" in recv_names
        assert "xferd.send" in send_names

        # And cmd/agent_trace.py merges the two files into one story.
        spec = importlib.util.spec_from_file_location(
            "agent_trace", os.path.join(REPO, "cmd", "agent_trace.py")
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        spans, _skipped = mod.load_spans(list(files.values()))
        merged = [s for s in spans if s["trace"] == trace_id]
        assert len(merged) == sum(len(v) for v in per_side.values())
        shown = mod.print_tree(spans, trace_id,
                               file=open(os.devnull, "w"))
        assert shown == len(merged)


# ---------------------------------------------------------------------------
# Fleet scenarios
# ---------------------------------------------------------------------------


@pytest.mark.chaos
class TestFleetScenarios:
    def test_rack_partition_fleet_reconverges(self):
        """The headline scenario (ISSUE acceptance): ≥4 nodes, a rack
        partitioned mid-workload plus a chip fault, then the partition
        heals and every surviving node re-converges — devices
        re-announced Healthy, DCN legs completing again."""
        h0 = counters.get("health.recovered")
        report = run_scenario(dict(DEFAULT_SCENARIO, rounds=6))
        assert report["converged"], report["rounds"][-1]

        # The partition was real: cross-rack sends were blocked...
        blocked = sum(l["blocked"] for l in report["links"].values())
        assert blocked > 0
        assert report["agent_events_delta"].get("fleet.link.blocked",
                                                0) == blocked
        mid = [r for r in report["rounds"]
               if any("link" in f for f in r["faults"])][0]
        assert all(not leg["ok"] for leg in mid["legs"]
                   if "skipped" not in leg)
        # ...and every node finished healthy with its final legs ok.
        for name, node in report["nodes"].items():
            assert node["healthy"] == node["total"], (name, node)
        assert all(leg["ok"] for leg in report["rounds"][-1]["legs"])
        # The chip fault recovered through the production health path.
        assert counters.get("health.recovered") == h0 + 1

    def test_fleet_sim_cli_runs_partition_scenario(self):
        """cmd/fleet_sim.py: ≥4-node scheduled-rack-partition run exits
        0 and emits the per-node/per-link JSON report."""
        env = dict(os.environ)
        env.pop("TPU_FAULT_SPEC", None)
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "cmd", "fleet_sim.py"),
             "--rounds", "5"],
            capture_output=True, text=True, timeout=300, env=env,
            cwd=REPO,
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        report = json.loads(proc.stdout.strip().splitlines()[-1])
        assert report["converged"]
        assert len(report["nodes"]) >= 4
        assert any(l["blocked"] for l in report["links"].values())
        assert "converged: True" in proc.stderr

    @pytest.mark.slow
    def test_node_kill_survivors_reconverge(self):
        """A node's daemon dies for two rounds: its legs are skipped,
        the N-1 survivors keep exchanging, and after the restart the
        fleet re-converges with the daemon on generation 2."""
        scenario = {
            "name": "node-churn",
            "nodes": 4, "racks": 2, "rounds": 6,
            "payload_bytes": 1024,
            "faults": [
                {"round": 1, "action": "kill", "node": "n2", "for": 2},
            ],
        }
        report = run_scenario(scenario)
        assert report["converged"], report["rounds"][-1]
        down_round = report["rounds"][1]
        skipped = [leg for leg in down_round["legs"] if "skipped" in leg]
        survivors = [leg for leg in down_round["legs"]
                     if "skipped" not in leg]
        assert len(skipped) == 2  # n1->n2 and n2->n3
        assert survivors and all(leg["ok"] for leg in survivors)
        assert report["nodes"]["n2"]["daemon_generation"] == 2
        assert not report["nodes"]["n2"]["down"]

    @pytest.mark.slow
    def test_asymmetric_loss_and_latency(self):
        """Link-level ≠ endpoint-level: one direction drops a frame
        (the leg retries through), the reverse stays clean, and
        injected latency shows up in the per-link accounting."""
        scenario = {
            "name": "lossy-link",
            "nodes": 2, "racks": 2, "rounds": 3,
            "payload_bytes": 1024,
            "land_timeout_s": 0.5,
            "faults": [
                {"round": 1, "link": "node:n0->node:n1:drop:1"},
                {"round": 1, "link": "node:n1->node:n0:latency:2"},
            ],
        }
        report = run_scenario(scenario)
        assert report["converged"], report["rounds"]
        fwd = report["links"]["n0->n1"]
        rev = report["links"]["n1->n0"]
        assert fwd["drops"] == 1 and rev["drops"] == 0
        assert rev["latency_injected_ms"] > 0
        lossy = report["rounds"][1]["legs"][0]
        assert lossy["ok"] and lossy["attempts"] > 1

    @pytest.mark.slow
    def test_per_node_metric_servers(self):
        """`metrics: true` boots one MetricServer per node on an
        ephemeral port, scrapeable while the scenario runs."""
        import urllib.request

        ctl = FleetController({
            "name": "metrics", "nodes": 2, "racks": 1, "rounds": 1,
            "payload_bytes": 512, "metrics": True, "faults": [],
        })
        try:
            report = ctl.run()
            assert report["converged"]
            for name, node in ctl.nodes.items():
                port = report["nodes"][name]["metrics_port"]
                node.metrics.collect_once()
                body = urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics", timeout=10
                ).read().decode()
                assert "duty_cycle_tpu_node" in body
        finally:
            ctl.close()

    def test_partitioned_node_slice_reheals_with_counter(self, tmp_path):
        """Fleet node with sub-slice partitioning: a chip fault takes
        the slice down; recovery re-heals it once every member chip is
        healthy, counted as health.slice_recovered."""
        spec = NodeSpec(name="pn", chips=4, topology="2x2x1",
                        partition_size="2x2")
        node = EmulatedNode(spec, str(tmp_path / "pn"))
        try:
            s0 = counters.get("health.slice_recovered")
            node.inject_chip_fault("accel1")
            assert node.device_health() == {"slice0": "Unhealthy"}
            assert node.force_recover() == 1
            assert node.device_health() == {"slice0": "Healthy"}
            assert counters.get("health.slice_recovered") == s0 + 1
        finally:
            node.close()
