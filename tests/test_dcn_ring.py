"""Universal submission-ring data plane: the socket lane driven by
posted descriptors (ISSUE 19).

The client posts (off, len, seq) descriptors plus ONE doorbell per
round; the daemon's completer drives them through the normal send
machinery and publishes per-slot verdicts plus a completion cursor the
client polls lock-free out of shared memory.  These tests pin the
contract:

- one doorbell per round on the socket lane (no per-chunk control op);
- ring-full backpressure posts in ring-sized batches and BLOCKS the
  poster — extra doorbells, never dropped descriptors;
- completer death/refusal downgrades to the classic per-chunk path
  (``dcn.ring.fallback``) under the SAME seqs;
- producer mode pulls chunks INSIDE the completion window (after the
  doorbell), and exchange_shard's capture-tee keeps one-shot producers
  replayable across fallback legs.

The proc-mode half (SIGKILL mid-ring, lost doorbell answers) proves
the same invariants across real process boundaries with scraped dedup
evidence, in the tests/test_fleet_proc.py idiom.
"""

import os
import threading
import time
import uuid

import pytest

import container_engine_accelerators_tpu.fleet.xferd as xferd_mod
from container_engine_accelerators_tpu.fleet.proc import ProcNode
from container_engine_accelerators_tpu.fleet.topology import NodeSpec
from container_engine_accelerators_tpu.fleet.xferd import PyXferd
from container_engine_accelerators_tpu.metrics import counters
from container_engine_accelerators_tpu.parallel import dcn, dcn_pipeline
from container_engine_accelerators_tpu.parallel.dcn_client import (
    DcnXferError,
    ResilientDcnXferClient,
)
from container_engine_accelerators_tpu.utils.retry import RetryPolicy

FAST_RETRY = RetryPolicy(
    max_attempts=6, initial_backoff_s=0.01, max_backoff_s=0.1,
    deadline_s=10.0,
)

# The ring-socket shape under test: submission ring on, zero-copy shm
# lane off (the ring must prove itself on the TCP lane), static grid
# (tuned=False — these suites assert exact chunk/doorbell counts).
RING_CFG = dcn_pipeline.PipelineConfig(chunk_bytes=4096, stripes=2,
                                       shm=False, shm_direct=False,
                                       ring=True, tuned=False)
# The legacy per-chunk shape the ring is judged against.
CLASSIC_CFG = dcn_pipeline.PipelineConfig(chunk_bytes=4096, stripes=2,
                                          shm=False, shm_direct=False,
                                          ring=False, tuned=False)
PAYLOAD = bytes(range(256)) * 64  # 16 KiB == 4 chunks under the grid
N = len(PAYLOAD)


@pytest.fixture
def pair(tmp_path):
    # ring=True pins the capability regardless of TPU_DCN_SHM_RING:
    # these tests assert ring behavior, not the kill switch's default.
    a = PyXferd(str(tmp_path / "a"), node="ra", ring=True).start()
    b = PyXferd(str(tmp_path / "b"), node="rb", ring=True).start()
    ca = ResilientDcnXferClient(str(tmp_path / "a"), retry=FAST_RETRY)
    cb = ResilientDcnXferClient(str(tmp_path / "b"), retry=FAST_RETRY)
    yield a, b, ca, cb
    for c in (ca, cb):
        try:
            c.close()
        except OSError:
            pass
    a.stop()
    b.stop()


def _flow(prefix="ring"):
    return f"{prefix}-{uuid.uuid4().hex[:8]}"


def _open(ca, cb, flow, nbytes=N):
    cb.register_flow(flow, bytes=nbytes)
    ca.register_flow(flow, bytes=nbytes)


class TestRingSocketLane:
    def test_one_doorbell_per_round(self, pair):
        """The tentpole pin: a multi-chunk socket-lane round costs
        exactly ONE control op (the doorbell) — descriptors and
        completion ride shared memory, payload rides TCP."""
        a, b, ca, cb = pair
        flow = _flow()
        _open(ca, cb, flow)
        posts0 = counters.get("dcn.shm.ring.posts")
        rounds0 = counters.get("dcn.ring.socket.rounds")
        res = dcn_pipeline.send_pipelined(
            ca, flow, PAYLOAD, "127.0.0.1", b.data_port, RING_CFG,
            timeout_s=15)
        assert res["lane"] == "socket" and res["rounds"] == 1
        assert counters.get("dcn.shm.ring.posts") == posts0 + 1
        assert counters.get("dcn.ring.socket.rounds") == rounds0 + 1
        dcn.wait_flow_rx(cb, flow, N, timeout_s=10)
        assert dcn_pipeline.read_pipelined(
            cb, flow, N, RING_CFG) == PAYLOAD

    def test_ring_full_backpressure_blocks_not_drops(
            self, pair, monkeypatch):
        """A round larger than the ring posts in ring-sized batches:
        the poster BLOCKS until the previous batch's cursor drains
        (one extra doorbell per extra batch, ``dcn.ring.backpressure``
        counted) and every chunk still lands byte-exact — descriptors
        are never silently dropped."""
        monkeypatch.setattr(xferd_mod, "RING_SLOTS", 2)
        a, b, ca, cb = pair
        flow = _flow("bp")
        _open(ca, cb, flow)
        posts0 = counters.get("dcn.shm.ring.posts")
        bp0 = counters.get("dcn.ring.backpressure")
        res = dcn_pipeline.send_pipelined(
            ca, flow, PAYLOAD, "127.0.0.1", b.data_port, RING_CFG,
            timeout_s=15)
        # 4 chunks over a 2-slot ring: two batches, two doorbells,
        # one blocked-poster event — and still one logical round.
        assert res["lane"] == "socket" and res["rounds"] == 1
        assert counters.get("dcn.shm.ring.posts") == posts0 + 2
        assert counters.get("dcn.ring.backpressure") == bp0 + 1
        dcn.wait_flow_rx(cb, flow, N, timeout_s=10)
        assert dcn_pipeline.read_pipelined(
            cb, flow, N, RING_CFG) == PAYLOAD

    def test_completer_refusal_falls_back_to_classic(
            self, pair, monkeypatch):
        """An unusable ring handoff (attach refused — the completer-
        death shape) downgrades the SAME transfer to the classic
        per-chunk path: ``dcn.ring.fallback`` counts it, no doorbell
        is charged, and the payload lands byte-exact."""
        a, b, ca, cb = pair
        monkeypatch.setattr(
            a, "_ring_attach",
            lambda req: {"ok": False, "error": "completer dead"})
        flow = _flow("fb")
        _open(ca, cb, flow)
        posts0 = counters.get("dcn.shm.ring.posts")
        fb0 = counters.get("dcn.ring.fallback")
        res = dcn_pipeline.send_pipelined(
            ca, flow, PAYLOAD, "127.0.0.1", b.data_port, RING_CFG,
            timeout_s=15)
        assert res["lane"] == "socket" and res["rounds"] == 1
        assert counters.get("dcn.ring.fallback") == fb0 + 1
        assert counters.get("dcn.shm.ring.posts") == posts0
        dcn.wait_flow_rx(cb, flow, N, timeout_s=10)
        assert dcn_pipeline.read_pipelined(
            cb, flow, N, RING_CFG) == PAYLOAD

    def test_ring_kill_switch_stays_classic(self, pair):
        """cfg.ring=False (TPU_DCN_SHM_RING=0) pins the legacy
        per-chunk socket pipeline: no ring attach, no doorbell, no
        fallback noise — the escape hatch stays byte-identical."""
        a, b, ca, cb = pair
        flow = _flow("ks")
        _open(ca, cb, flow)
        posts0 = counters.get("dcn.shm.ring.posts")
        rounds0 = counters.get("dcn.ring.socket.rounds")
        fb0 = counters.get("dcn.ring.fallback")
        res = dcn_pipeline.send_pipelined(
            ca, flow, PAYLOAD, "127.0.0.1", b.data_port, CLASSIC_CFG,
            timeout_s=15)
        assert res["lane"] == "socket"
        assert counters.get("dcn.shm.ring.posts") == posts0
        assert counters.get("dcn.ring.socket.rounds") == rounds0
        assert counters.get("dcn.ring.fallback") == fb0
        dcn.wait_flow_rx(cb, flow, N, timeout_s=10)
        assert dcn_pipeline.read_pipelined(
            cb, flow, N, CLASSIC_CFG) == PAYLOAD

    def test_set_ring_delay_clamped(self, pair):
        """The grey-fault knob (slow completer, soak's slow_ring
        grammar) clamps to [0, 2] seconds — a fault injector cannot
        turn 'slow' into 'wedged forever'."""
        a, _b, _ca, _cb = pair
        assert a.set_ring_delay(99.0) == 2.0
        assert a.set_ring_delay(-5.0) == 0.0
        assert a.set_ring_delay(0.25) == 0.25
        a.set_ring_delay(0.0)


class TestProducerMode:
    def test_producer_pulled_after_doorbell(self, pair):
        """Producer chunks are pulled INSIDE the completion window:
        every pull happens after the round's doorbell posted, so
        production time hides behind the DCN leg instead of preceding
        it — the overlap exchange_shard's producer mode exists for."""
        a, b, ca, cb = pair
        flow = _flow("pr")
        _open(ca, cb, flow)
        posts0 = counters.get("dcn.shm.ring.posts")
        pt0 = counters.get("dcn.ring.producer.transfers")
        pulls = []

        def produce():
            for off in range(0, N, 4096):
                pulls.append(counters.get("dcn.shm.ring.posts"))
                yield PAYLOAD[off:off + 4096]

        res = dcn_pipeline.send_pipelined(
            ca, flow, None, "127.0.0.1", b.data_port, RING_CFG,
            timeout_s=15, producer=produce(), nbytes=N)
        assert res["lane"] == "socket" and res["rounds"] == 1
        assert counters.get("dcn.ring.producer.transfers") == pt0 + 1
        assert len(pulls) == 4
        assert all(p > posts0 for p in pulls), pulls
        dcn.wait_flow_rx(cb, flow, N, timeout_s=10)
        assert dcn_pipeline.read_pipelined(
            cb, flow, N, RING_CFG) == PAYLOAD

    def test_producer_ended_early_raises(self, pair):
        a, b, ca, cb = pair
        flow = _flow("pe")
        _open(ca, cb, flow)
        with pytest.raises(DcnXferError, match="ended early"):
            dcn_pipeline.send_pipelined(
                ca, flow, None, "127.0.0.1", b.data_port, RING_CFG,
                timeout_s=15, producer=iter([PAYLOAD[:4096]]),
                nbytes=N)

    def test_data_and_producer_are_exclusive(self, pair):
        _a, b, ca, _cb = pair
        with pytest.raises(ValueError, match="data OR producer"):
            dcn_pipeline.send_pipelined(
                ca, "x", PAYLOAD, "127.0.0.1", b.data_port, RING_CFG,
                producer=iter([b"y"]), nbytes=N)
        with pytest.raises(ValueError, match="nbytes"):
            dcn_pipeline.send_pipelined(
                ca, "x", None, "127.0.0.1", b.data_port, RING_CFG,
                producer=iter([b"y"]))


def _producer_exchange(pair, data_a, data_b, **kw):
    """Both workers of the 2-process collective leg on threads, each
    side feeding its shard through a one-shot producer — the
    tests/dcn_xfer_worker.py pattern with production overlapped."""
    a, b, ca, cb = pair
    barrier = threading.Barrier(2)
    out, errs = {}, []

    def chunks(payload):
        for off in range(0, len(payload), 4096):
            yield payload[off:off + 4096]

    def worker(name, client, data, peer_daemon, tx, rx):
        try:
            out[name] = dcn.exchange_shard(
                client, local_flow=tx, peer_flow=rx,
                producer=chunks(data), nbytes=len(data),
                peer_host="127.0.0.1", peer_port=peer_daemon.data_port,
                barrier=barrier.wait, timeout_s=15, **kw)
        except BaseException as e:  # surfaces in the test, not a hang
            errs.append(e)
            barrier.abort()

    ts = [
        threading.Thread(target=worker,
                         args=("a", ca, data_a, b, "rex.a", "rex.b")),
        threading.Thread(target=worker,
                         args=("b", cb, data_b, a, "rex.b", "rex.a")),
    ]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=30)
    if errs:
        raise errs[0]
    return out


class TestExchangeShardProducer:
    def test_two_sided_producer_exchange_rides_the_ring(
            self, pair, monkeypatch):
        """The full collective leg with BOTH shards producer-fed:
        shm pinned off so each side takes the ring-socket lane, and
        both read back the peer's shard byte-exact."""
        monkeypatch.setenv("TPU_DCN_SHM", "0")
        monkeypatch.setenv(dcn_pipeline.CHUNK_BYTES_ENV, "4096")
        pt0 = counters.get("dcn.ring.producer.transfers")
        out = _producer_exchange(pair, PAYLOAD, PAYLOAD[::-1],
                                 pipelined=True)
        assert out["a"] == PAYLOAD[::-1] and out["b"] == PAYLOAD
        assert counters.get("dcn.ring.producer.transfers") == pt0 + 2

    def test_serial_fallback_materializes_one_shot_producer(
            self, pair):
        """A producer-fed shard forced down the SERIAL path: the
        capture-tee materializes the one-shot iterator, so the leg
        that never stages chunk-wise still sends the full payload."""
        small_a, small_b = b"s" * 512, b"t" * 512
        out = _producer_exchange(pair, small_a, small_b,
                                 pipelined=False)
        assert out["a"] == small_b and out["b"] == small_a

    def test_producer_length_mismatch_raises(self, pair):
        _a, b, ca, _cb = pair
        with pytest.raises(DcnXferError, match="expected"):
            dcn.exchange_shard(
                ca, local_flow="rex.m", peer_flow="rex.n",
                producer=iter([b"x" * 100]), nbytes=512,
                peer_host="127.0.0.1", peer_port=b.data_port,
                timeout_s=5, pipelined=False)


# ---------------------------------------------------------------------------
# Proc-mode chaos: real process boundaries, scraped evidence
# ---------------------------------------------------------------------------

PIPE_PAYLOAD = bytes(range(256)) * 64  # 16 KiB = 4 chunks
PIPE_N = len(PIPE_PAYLOAD)


def _spec(name):
    return NodeSpec(name=name, chips=2, topology="1x2x1")


def _node(tmp_path, name, **kw):
    kw.setdefault("handshake_timeout_s", 60.0)
    env = dict(os.environ)
    env.pop("TPU_FAULT_SPEC", None)  # determinism under make chaos
    env.pop("TPU_DCN_SHM_RING", None)  # ring capability on
    kw.setdefault("env", env)
    return ProcNode(_spec(name), str(tmp_path / name), **kw)


def _flow_stat(client, flow):
    return next(f for f in client.stats()["flows"] if f["flow"] == flow)


def _wait_stable_rx(client, flow, expect, settle_s=0.25):
    dcn.wait_flow_rx(client, flow, expect, timeout_s=10)
    deadline = time.monotonic() + settle_s
    while time.monotonic() < deadline:
        assert _flow_stat(client, flow)["rx_bytes"] == expect
        time.sleep(0.02)


def _scrape_after_collect(port, settle_s=0.8):
    from container_engine_accelerators_tpu.fleet.telemetry import (
        scrape_metric_server,
    )
    time.sleep(settle_s)
    return scrape_metric_server(port, timeout_s=5.0)


@pytest.mark.slow
@pytest.mark.chaos
class TestRingChaosProc:
    def test_doorbell_lost_falls_back_same_seqs_dedup_scraped(
            self, tmp_path):
        """The doorbell's answer dies with the sender's control
        connection — work enqueued, answer lost.  The SAME transfer
        downgrades to the classic per-chunk round (dcn.ring.fallback)
        and re-sends the SAME seqs; the completer's late sends and the
        fallback round referee through the receiver WORKER's dedup
        window — exactly-once proven from scraped counters."""
        a = _node(tmp_path, "na")
        b = _node(tmp_path, "nb")
        try:
            b.client.register_flow("rdb", bytes=PIPE_N)
            a.client.register_flow("rdb", bytes=PIPE_N)
            a.drop_response_once("shm_post")
            fb0 = counters.get("dcn.ring.fallback")
            res = dcn_pipeline.send_pipelined(
                a.client, "rdb", PIPE_PAYLOAD, "127.0.0.1",
                b.daemon.data_port, RING_CFG, timeout_s=10)
            assert res["lane"] == "socket"
            assert counters.get("dcn.ring.fallback") == fb0 + 1
            _wait_stable_rx(b.client, "rdb", PIPE_N)  # exactly once
            s = _scrape_after_collect(b.metrics_port)
            landed = s.value("agent_events",
                             event="xferd.frames.landed")
            deduped = s.value("agent_events",
                              event="dcn.frames.deduped")
            # 4 chunks landed once each; every duplicate delivery
            # (enqueued completer vs fallback round, same seqs)
            # deduped away.
            assert landed == 4.0
            assert deduped >= 1.0
            assert dcn_pipeline.read_pipelined(
                b.client, "rdb", PIPE_N, RING_CFG) == PIPE_PAYLOAD
        finally:
            a.close()
            b.close()

    def test_sender_sigkill_mid_ring_fallback_then_exactly_once(
            self, tmp_path):
        """SIGKILL the sender's daemon mid-ring (doorbell posted,
        completer armed slow, zero sends out): the wedged transfer
        fails LOUDLY — never silently dropped descriptors — and after
        the supervised respawn the SAME payload re-posts through a
        FRESH ring and lands exactly once: scraped landed count,
        byte-exact read-back.  (The fallback decision against a dead
        completer is covered by the doorbell-lost test above; a dead
        LOCAL daemon fails the whole transfer loudly, classic path
        included, because there is no data port left to stage to.)"""
        a = _node(tmp_path, "na")
        b = _node(tmp_path, "nb")
        try:
            b.client.register_flow("rk9", bytes=PIPE_N)
            a.client.register_flow("rk9", bytes=PIPE_N)
            # Slow completer: first send would happen 2 s after the
            # doorbell — the kill below lands mid-ring, deterministic-
            # ally before ANY chunk leaves the dying incarnation.
            assert a.ring_delay(2.0) == 2.0

            errs = []

            def send_wedged():
                try:
                    dcn_pipeline.send_pipelined(
                        a.client, "rk9", PIPE_PAYLOAD, "127.0.0.1",
                        b.daemon.data_port, RING_CFG, timeout_s=2.5)
                except DcnXferError as e:
                    errs.append(e)

            t = threading.Thread(target=send_wedged)
            t.start()
            time.sleep(0.8)  # doorbell + staging done, no sends yet
            a.kill_daemon()  # SIGKILL: zero teardown lines run
            t.join(timeout=30)
            assert not t.is_alive()
            assert errs and "unconfirmed" in str(errs[0])

            a.restart_daemon()
            assert a.snapshot()["daemon_generation"] == 2
            a.client.ping()  # reconnect + flow replay + re-probe
            res = dcn_pipeline.send_pipelined(
                a.client, "rk9", PIPE_PAYLOAD, "127.0.0.1",
                b.daemon.data_port, RING_CFG, timeout_s=10)
            assert res["lane"] == "socket" and res["rounds"] == 1
            _wait_stable_rx(b.client, "rk9", PIPE_N)  # exactly once
            sb = _scrape_after_collect(b.metrics_port)
            assert sb.value("agent_events",
                            event="xferd.frames.landed") == 4.0
            # The fresh incarnation rang exactly one doorbell for the
            # re-posted round (its counters started at zero).
            sa = _scrape_after_collect(a.metrics_port, settle_s=0.0)
            assert sa.value("agent_events",
                            event="dcn.shm.ring.posts") == 1.0
            assert dcn_pipeline.read_pipelined(
                b.client, "rk9", PIPE_N, RING_CFG) == PIPE_PAYLOAD
        finally:
            a.close()
            b.close()
