"""Input pipeline (container_engine_accelerators_tpu/data/ +
native/tokpack).

The properties that matter: the shard format round-trips (Python writer,
native packer, memory-mapped reader all agree), reads cross shard
boundaries and wrap modularly, the step->batch mapping is pure (resume
replays exactly), and the prefetch thread surfaces errors instead of
swallowing them.
"""

import json
import os
import subprocess

import numpy as np
import pytest

from container_engine_accelerators_tpu.data import (
    TokenBatchLoader,
    TokenShardReader,
    write_token_shards,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOKPACK = os.path.join(REPO, "native", "tokpack", "build", "tokpack")


def _dataset(tmp_path, streams):
    d = str(tmp_path / "ds")
    write_token_shards(d, [np.asarray(s, np.uint32) for s in streams])
    return d


def test_write_read_roundtrip_across_shards(tmp_path):
    d = _dataset(tmp_path, [[1, 2, 3], [4, 5], [6, 7, 8, 9]])
    r = TokenShardReader(d)
    assert r.total_tokens == 9
    # Within one shard, across a boundary, and wrapping the end.
    assert r.read(0, 3).tolist() == [1, 2, 3]
    assert r.read(2, 4).tolist() == [3, 4, 5, 6]
    assert r.read(7, 4).tolist() == [8, 9, 1, 2]
    # Longer than the dataset: wraps repeatedly.
    assert r.read(0, 11).tolist() == [1, 2, 3, 4, 5, 6, 7, 8, 9, 1, 2]


def test_reader_rejects_stale_index_and_empty(tmp_path):
    d = _dataset(tmp_path, [[1, 2, 3]])
    # Truncate the shard behind the index's back.
    shard = os.path.join(d, "00000.tokens")
    with open(shard, "r+b") as f:
        f.truncate(4)
    with pytest.raises(ValueError, match="stale"):
        TokenShardReader(d)
    with pytest.raises(FileNotFoundError):
        TokenShardReader(str(tmp_path / "nonexistent"))


def test_loader_mapping_is_pure_and_resumable(tmp_path):
    d = _dataset(tmp_path, [list(range(100))])
    loader = TokenBatchLoader(TokenShardReader(d), batch_size=2,
                              seq_len=5)
    # Pure: same step -> same batch, twice.
    t1, l1, m1 = loader.batch_at(3)
    t2, l2, m2 = loader.batch_at(3)
    assert (t1 == t2).all() and (l1 == l2).all() and (m1 == m2).all()
    # Labels are next-token within the window.
    assert (l1[:, :-1] == t1[:, 1:]).all()
    assert (l1[:, -1] == t1[:, -1] + 1).all()  # range dataset
    assert m1.all()
    # Resume: iterating from step k equals the pure mapping at k, k+1.
    got = list(loader.iter_batches(3, 2))
    assert (got[0][0] == t1).all()
    assert (got[1][0] == loader.batch_at(4)[0]).all()
    # Rows advance contiguously: row r of step s starts at
    # (s*B + r)*T.
    assert t1[0, 0] == (3 * 2 + 0) * 5
    assert t1[1, 0] == (3 * 2 + 1) * 5


def test_loader_vocab_overflow_raises_at_consumer(tmp_path):
    d = _dataset(tmp_path, [[1, 2, 7000]])
    loader = TokenBatchLoader(TokenShardReader(d), batch_size=1,
                              seq_len=2, vocab_size=100)
    with pytest.raises(ValueError, match="vocab"):
        list(loader.iter_batches(0, 1))


def test_write_rejects_empty_stream(tmp_path):
    """ADVICE r4: a zero-length stream would write a 0-byte shard that
    TokenShardReader cannot memory-map (opaque mmap crash); the writer
    must reject it at the format level instead."""
    with pytest.raises(ValueError, match="empty token stream"):
        write_token_shards(str(tmp_path / "ds"),
                           [np.asarray([], np.uint32)])
    # A GOOD stream ahead of the empty one must not leave an orphan
    # shard behind (validation precedes any write).
    with pytest.raises(ValueError, match="empty token stream"):
        write_token_shards(str(tmp_path / "ds"),
                           [np.asarray([1, 2, 3], np.uint32),
                            np.asarray([], np.uint32)])
    made = (tmp_path / "ds")
    assert not made.exists() or not list(made.glob("*.tokens"))


def test_prefetch_producer_exits_when_iterator_abandoned(tmp_path):
    """ADVICE r4: abandoning iter_batches mid-stream (exception or
    early break in the training loop) must not park the producer
    thread forever on a full queue."""
    import threading
    import time

    d = _dataset(tmp_path, [list(range(10_000))])
    loader = TokenBatchLoader(TokenShardReader(d), batch_size=1,
                              seq_len=4, prefetch=1)
    it = loader.iter_batches(0, 500)
    next(it)  # producer now blocks on the size-1 queue
    time.sleep(0.1)
    alive = [t for t in threading.enumerate()
             if t.name == "tokenloader-prefetch"]
    assert alive, "producer thread not found (rename broke the test?)"
    it.close()  # abandon: the finally must set the closed event
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline and any(
            t.is_alive() for t in alive):
        time.sleep(0.05)
    assert not any(t.is_alive() for t in alive), (
        "producer thread leaked after iterator close")


def test_steps_per_epoch(tmp_path):
    d = _dataset(tmp_path, [list(range(100))])
    loader = TokenBatchLoader(TokenShardReader(d), batch_size=2,
                              seq_len=5)
    assert loader.steps_per_epoch() == 10


@pytest.mark.skipif(not os.path.exists(TOKPACK),
                    reason="native tokpack not built (make native)")
class TestTokpack:
    def test_pack_matches_python_writer(self, tmp_path):
        src = tmp_path / "corpus.txt"
        toks = list(range(1, 23))
        src.write_text(" ".join(map(str, toks[:10])) + "\n"
                       + "\n".join(map(str, toks[10:])) + "\n")
        out = str(tmp_path / "packed")  # tokpack creates it
        proc = subprocess.run(
            [TOKPACK, "--out", out, "--shard-tokens", "8", str(src)],
            capture_output=True, text=True, timeout=60)
        assert proc.returncode == 0, proc.stderr
        r = TokenShardReader(out)
        assert r.total_tokens == len(toks)
        assert r.read(0, len(toks)).tolist() == toks
        # 22 tokens at 8/shard -> 3 shards, last short.
        idx = json.load(open(os.path.join(out, "index.json")))
        assert [s["tokens"] for s in idx["shards"]] == [8, 8, 6]

    def test_stdin_and_parse_error(self, tmp_path):
        out = str(tmp_path / "packed")
        proc = subprocess.run(
            [TOKPACK, "--out", out, "-"], input="5 6 7\n",
            capture_output=True, text=True, timeout=60)
        assert proc.returncode == 0, proc.stderr
        assert TokenShardReader(out).read(0, 3).tolist() == [5, 6, 7]

        bad = tmp_path / "bad.txt"
        bad.write_text("12 x 9\n")
        proc = subprocess.run(
            [TOKPACK, "--out", str(tmp_path / "p2"), str(bad)],
            capture_output=True, text=True, timeout=60)
        assert proc.returncode == 2
        assert "unexpected byte" in proc.stderr

    def test_refuses_existing_shards(self, tmp_path):
        """Re-packing into a populated dir must fail loudly, never
        splice corpora under a stale index."""
        out = str(tmp_path / "packed")
        subprocess.run([TOKPACK, "--out", out, "-"], input="1 2 3\n",
                       capture_output=True, text=True, timeout=60)
        proc = subprocess.run(
            [TOKPACK, "--out", out, "-"], input="9 9\n",
            capture_output=True, text=True, timeout=60)
        assert proc.returncode == 2
        assert "refusing to mix" in proc.stderr
        # The original dataset is untouched.
        assert TokenShardReader(out).read(0, 3).tolist() == [1, 2, 3]

    def test_int32_overflow_guard_in_loader(self, tmp_path):
        out = str(tmp_path / "packed")
        proc = subprocess.run(
            [TOKPACK, "--out", out, "-"], input="1 2147483650 2\n",
            capture_output=True, text=True, timeout=60)
        assert proc.returncode == 0, proc.stderr  # valid uint32
        loader = TokenBatchLoader(TokenShardReader(out), batch_size=1,
                                  seq_len=2)
        with pytest.raises(ValueError, match="int32"):
            loader.batch_at(0)

    def test_usage_errors(self, tmp_path):
        proc = subprocess.run([TOKPACK], capture_output=True, text=True,
                              timeout=60)
        assert proc.returncode == 1


@pytest.mark.slow
def test_train_lm_on_real_dataset_end_to_end(tmp_path):
    """cmd/train_lm.py --data-dir: the driver trains on packed shards
    (loss finite, checkpoint written) instead of synthetic streams."""
    import importlib.util

    rng = np.random.default_rng(0)
    d = _dataset(tmp_path, [rng.integers(0, 64, 4000)])
    spec = importlib.util.spec_from_file_location(
        "train_lm_data", os.path.join(REPO, "cmd", "train_lm.py"))
    train = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(train)
    train.main([
        "--vocab-size", "64", "--num-layers", "1", "--num-heads", "2",
        "--head-dim", "8", "--mlp-dim", "32", "--seq-len", "16",
        "--train-batch-size", "8", "--train-steps", "3",
        "--steps-per-eval", "1", "--data-dir", d,
        "--checkpoint-dir", str(tmp_path / "ck"),
        "--checkpoint-interval", "3",
    ])
    assert os.path.isdir(tmp_path / "ck")


# --- image/label array shards (data/arrays.py) -----------------------

from container_engine_accelerators_tpu.data import (  # noqa: E402
    ArrayShardReader,
    ImageBatchLoader,
    write_array_shards,
)


def _image_dataset(tmp_path, counts, shape=(4, 4, 3), dtype=np.uint8):
    rng = np.random.default_rng(0)
    d = str(tmp_path / "imgs")
    batches = []
    label = 0
    for n in counts:
        imgs = rng.integers(0, 255, (n,) + shape).astype(dtype) \
            if dtype == np.uint8 else rng.random((n,) + shape, dtype)
        labels = np.arange(label, label + n, dtype=np.int32) % 10
        label += n
        batches.append((imgs, labels))
    write_array_shards(d, batches)
    return d


def test_array_roundtrip_across_shards(tmp_path):
    d = _image_dataset(tmp_path, [3, 2, 4])
    r = ArrayShardReader(d)
    assert r.total_samples == 9
    assert r.sample_shape == (4, 4, 3)
    imgs, labels = r.read(2, 4)  # crosses shard 0->1->2
    assert imgs.shape == (4, 4, 4, 3)
    assert labels.tolist() == [2, 3, 4, 5]
    _, wrap = r.read(7, 4)
    assert wrap.tolist() == [7, 8, 0, 1]


def test_image_loader_pure_scaled_and_bounded(tmp_path):
    d = _image_dataset(tmp_path, [10])
    loader = ImageBatchLoader(ArrayShardReader(d), batch_size=4)
    x1, y1 = loader.batch_at(2)
    x2, y2 = loader.batch_at(2)
    assert (x1 == x2).all() and (y1 == y2).all()
    assert x1.dtype == np.float32 and 0.0 <= x1.min() <= x1.max() <= 1.0
    assert y1.tolist() == [8, 9, 0, 1]  # modular wrap at sample 10
    bad = ImageBatchLoader(ArrayShardReader(d), batch_size=4,
                           num_classes=5)
    with pytest.raises(ValueError, match="num_classes"):
        list(bad.iter_batches(0, 3))


def test_image_loader_shards_partition_the_global_batch(tmp_path):
    """Union of the per-process shards == the global batch, in order
    (the multi-host contract train_resnet's --data-dir relies on)."""
    d = _image_dataset(tmp_path, [10])
    r = ArrayShardReader(d)
    whole = ImageBatchLoader(r, batch_size=4)
    left = ImageBatchLoader(r, batch_size=4, shard=(0, 2))
    right = ImageBatchLoader(r, batch_size=4, shard=(1, 2))
    gx, gy = whole.batch_at(3)
    lx, ly = left.batch_at(3)
    rx, ry = right.batch_at(3)
    assert (np.concatenate([lx, rx]) == gx).all()
    assert (np.concatenate([ly, ry]) == gy).all()
    with pytest.raises(ValueError, match="shard"):
        ImageBatchLoader(r, batch_size=4, shard=(0, 3))


def test_array_writer_refuses_populated_dir(tmp_path):
    d = _image_dataset(tmp_path, [3])
    with pytest.raises(ValueError, match="refusing to mix"):
        write_array_shards(d, [(np.zeros((2, 4, 4, 3), np.uint8),
                                np.zeros(2, np.int32))])


def test_array_reader_rejects_mismatch_and_token_index(tmp_path):
    d = _image_dataset(tmp_path, [3])
    with open(os.path.join(d, "00000.labels"), "r+b") as f:
        f.truncate(4)
    with pytest.raises(ValueError, match="in index"):
        ArrayShardReader(d)
    tok = _dataset(tmp_path, [[1, 2, 3]])
    with pytest.raises(ValueError, match="sample_shape"):
        ArrayShardReader(tok)


@pytest.mark.slow
def test_train_resnet_on_real_dataset_end_to_end(tmp_path):
    """cmd/train_resnet.py --data-dir trains on packed image shards."""
    import importlib.util

    rng = np.random.default_rng(0)
    d = str(tmp_path / "imgs")
    write_array_shards(d, [
        (rng.integers(0, 255, (16, 32, 32, 3)).astype(np.uint8),
         rng.integers(0, 10, 16).astype(np.int32)),
    ])
    spec = importlib.util.spec_from_file_location(
        "train_resnet_data", os.path.join(REPO, "cmd", "train_resnet.py"))
    train = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(train)
    train.main([
        "--resnet-depth", "18", "--train-batch-size", "8",
        "--image-size", "32", "--num-classes", "10",
        "--train-steps", "2", "--steps-per-eval", "1",
        "--data-dir", d, "--model-dir", str(tmp_path / "out"),
    ])
    assert (tmp_path / "out" / "params.msgpack").stat().st_size > 0


def test_train_resnet_rejects_shape_mismatch(tmp_path):
    import importlib.util

    rng = np.random.default_rng(0)
    d = str(tmp_path / "imgs")
    write_array_shards(d, [
        (rng.integers(0, 255, (8, 16, 16, 3)).astype(np.uint8),
         np.zeros(8, np.int32)),
    ])
    spec = importlib.util.spec_from_file_location(
        "train_resnet_data2", os.path.join(REPO, "cmd", "train_resnet.py"))
    train = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(train)
    with pytest.raises(SystemExit, match="image-size"):
        train.main([
            "--resnet-depth", "18", "--train-batch-size", "8",
            "--image-size", "32", "--num-classes", "10",
            "--train-steps", "4", "--data-dir", d,
        ])
