"""Sketch-guided schedule search tests (collectives/search).

The search is the fourth, pin-only synthesis family: enumerate a
sketch grammar (ring orders, rack-gateway choices, cross-rack style,
chunk granularity) over the MEASURED comm graph, score with the same
alpha-beta cost model the auto chooser uses, and only ever emit a
candidate the in-memory oracle verified.  These tests pin the grammar
shape, the verify-everything contract, the degraded-edge avoidance
that is the whole point (the pinned asymmetric rig), pin-only-ness,
the Synthesizer cache/resynth integration, and that every schedule —
searched or family — satisfies the routed runner's hazard-free
condition.
"""

import pytest

from container_engine_accelerators_tpu.collectives import search, synth
from container_engine_accelerators_tpu.collectives.runner import (
    DEFAULT_SPINE_FAULTS,
    CollectiveEngine,
)
from container_engine_accelerators_tpu.collectives.topo import CommGraph
from container_engine_accelerators_tpu.fleet.links import LinkTable
from container_engine_accelerators_tpu.fleet.topology import (
    FleetTopology,
    build_specs,
)
from container_engine_accelerators_tpu.metrics import counters
from container_engine_accelerators_tpu.obs import timeseries


def _graph(nodes=4, racks=2, faults=(), rates=None, specs=None):
    topo = FleetTopology(specs or build_specs(nodes, racks=racks))
    links = LinkTable(topo)
    for f in faults:
        assert links.apply(f), f"fault {f!r} armed nothing"
    return CommGraph.build(topo, links=links,
                           rates=rates or (lambda a, b: 0.0))


def _spine_rig():
    """The pinned asymmetric rig the --compare gate runs: 5 nodes on
    2 unequal racks (r0={n0,n2,n4}, r1={n1,n3}) with latency faults
    on the rack-major ring's wrap edges — the shape where every auto
    family pays a degraded edge and the search must not."""
    return _graph(5, racks=2, faults=DEFAULT_SPINE_FAULTS)


def _degraded_pairs(graph):
    return {(a, b) for a in graph.nodes() for b in graph.nodes()
            if a != b and graph.edge(a, b).degraded}


def _legs(steps):
    return [(t.src, t.dst) for group in steps for t in group]


# ---- sketch grammar --------------------------------------------------------


class TestSketchGrammar:
    def test_single_rack_enumerates_only_ring_sketches(self):
        sk = search.sketches(_graph(4, racks=1), 4096)
        assert sk, "grammar empty on a trivial fleet"
        assert {s.kind for s in sk} == {"ring"}
        for s in sk:
            assert sorted(s.order) == ["n0", "n1", "n2", "n3"]

    def test_multi_rack_adds_gateway_family(self):
        sk = search.sketches(_graph(4, racks=2), 4096)
        kinds = {s.kind for s in sk}
        assert kinds == {"ring", "gateway"}
        gws = [s for s in sk if s.kind == "gateway"]
        assert {s.xr_style for s in gws} == {"direct", "ring"}
        assert {s.intra_style for s in gws} <= {"star", "ring"}
        # gateway sketches name exactly one member per rack
        for s in gws:
            assert len(s.gateways) == 2
        # direct style varies exchange granularity; every label is
        # unique (the trace event that records the winner relies on
        # labels being identities)
        assert len({s.label() for s in sk}) == len(sk)

    def test_grammar_is_bounded(self):
        # 8 nodes / 4 racks: the caps (GATEWAYS_PER_RACK,
        # MAX_GATEWAY_COMBOS, bounded two-opt) keep enumeration tiny.
        sk = search.sketches(_graph(8, racks=4), 65536)
        assert 0 < len(sk) <= 128


# ---- search: verified, cheaper, degraded-edge avoiding ---------------------


class TestSearch:
    @pytest.mark.parametrize("collective", synth.COLLECTIVES)
    @pytest.mark.parametrize("shape", [(4, 1), (4, 2), (5, 2), (8, 4)])
    def test_searched_schedule_verifies_on_every_shape(
            self, collective, shape):
        nodes, racks = shape
        g = _graph(nodes, racks=racks)
        sched = synth.synthesize(g, collective, 4096,
                                 algorithm="searched")
        assert sched.algorithm == "searched"
        inputs = synth.make_inputs(collective, sched.order, 4096,
                                   seed=3)
        out = synth.simulate(sched, inputs)
        want = synth.expected_outputs(collective, sched.order,
                                      inputs, 4096)
        for name, (off, ln, data) in want.items():
            assert bytes(out[name][off:off + ln]) == data, name

    def test_searched_avoids_the_degraded_spine(self):
        g = _spine_rig()
        degraded = _degraded_pairs(g)
        assert degraded, "spine faults armed nothing"
        sched = synth.synthesize(g, "all_reduce", 65536,
                                 algorithm="searched")
        used = set(_legs(sched.steps))
        assert not (used & degraded), (
            f"searched schedule pays degraded edges {used & degraded}")

    def test_searched_models_cheaper_than_every_auto_family(self):
        g = _spine_rig()
        searched = synth.synthesize(g, "all_reduce", 65536,
                                    algorithm="searched")
        for algo in synth.AUTO_ALGORITHMS:
            try:
                fam = synth.synthesize(g, "all_reduce", 65536,
                                       algorithm=algo)
            except synth.SynthesisError:
                continue  # hierarchical can't lower unequal racks
            assert searched.est_cost_s < fam.est_cost_s, algo

    def test_counters_and_margin_gauge_move(self):
        before_cand = counters.get("collective.search.candidates")
        before_ver = counters.get("collective.search.verified")
        synth.synthesize(_spine_rig(), "all_reduce", 65536,
                         algorithm="searched")
        assert counters.get("collective.search.candidates") \
            > before_cand
        assert counters.get("collective.search.verified") > before_ver
        # On the spine rig the best family pays the degraded edges,
        # so the recorded modeled margin is decisively > 1.
        assert timeseries.gauges()["collective.search.margin"] > 1.0

    def test_fully_partitioned_fleet_ships_least_bad(self):
        """A node cut off in BOTH directions leaves no finite
        candidate — the search keeps the families' mid-partition
        contract: the least-bad schedule still ships (legs will fail,
        the heal's signature change re-synthesizes) rather than
        wedging planning."""
        g = _graph(3, racks=1,
                   faults=["node:n0->node:n1:partition",
                           "node:n1->node:n0:partition",
                           "node:n0->node:n2:partition",
                           "node:n2->node:n0:partition"])
        sched = synth.synthesize(g, "all_reduce", 4096,
                                 algorithm="searched")
        assert sched.algorithm == "searched"
        assert sched.est_cost_s == float("inf")

    def test_partition_with_a_route_around_is_pruned(self):
        """One directed partition on a multi-rack fleet: candidates
        through it price infinite and are pruned; the winner is
        finite and never crosses the cut."""
        g = _graph(4, racks=2,
                   faults=["node:n0->node:n1:partition"])
        before = counters.get("collective.search.pruned")
        sched = synth.synthesize(g, "all_reduce", 8192,
                                 algorithm="searched")
        assert sched.est_cost_s != float("inf")
        assert ("n0", "n1") not in set(_legs(sched.steps))
        assert counters.get("collective.search.pruned") > before


# ---- pin-only + synthesizer integration ------------------------------------


class TestPinOnly:
    def test_searched_is_registered_but_never_auto(self):
        assert "searched" in synth.ALGORITHMS
        assert "searched" not in synth.AUTO_ALGORITHMS
        # auto choice on the rig where searched would win still stays
        # inside the auto families
        sched = synth.synthesize(_spine_rig(), "all_reduce", 65536)
        assert sched.algorithm in synth.AUTO_ALGORITHMS

    def test_synthesizer_caches_and_resynthesizes_searched(self):
        topo = FleetTopology(build_specs(4, racks=2))
        links = LinkTable(topo)
        build = lambda: CommGraph.build(  # noqa: E731
            topo, links=links, rates=lambda a, b: 0.0)
        s = synth.Synthesizer("all_reduce", 8192,
                              algorithm="searched")
        first = s.schedule_for(build())
        assert first.algorithm == "searched"
        assert s.schedule_for(build()) is first  # signature held
        links.apply("rack:r0<->rack:r1:latency:25")
        faulted = s.schedule_for(build())
        assert faulted is not first
        assert faulted.algorithm == "searched"
        assert s.resynth_count == 1
        # the replanned schedule routes around the fresh evidence
        degraded = _degraded_pairs(build())
        assert degraded
        # cross-rack legs can't vanish (the collective must cross),
        # but the faulted plan was scored against the degraded costs
        assert faulted.est_cost_s > first.est_cost_s


# ---- hazard freedom (the routed runner's precondition) ---------------------


class TestHazardFreedom:
    @pytest.mark.parametrize("collective", synth.COLLECTIVES)
    @pytest.mark.parametrize("algorithm", synth.ALGORITHMS)
    @pytest.mark.parametrize("shape", [(4, 1), (4, 2), (6, 2)])
    def test_every_lowerable_schedule_is_hazard_free(
            self, collective, algorithm, shape):
        """Routed execution snapshots nothing: within one barrier
        group no leg may read a region another leg writes, and
        same-region writes must both reduce.  Every family and every
        searched schedule satisfies this by construction — so routed
        mode never needs the coordinator fallback for schedules we
        synthesize ourselves."""
        nodes, racks = shape
        g = _graph(nodes, racks=racks)
        try:
            sched = synth.synthesize(g, collective, 4096,
                                     algorithm=algorithm)
        except synth.SynthesisError:
            pytest.skip(f"{algorithm} does not lower "
                        f"{collective}@{shape}")
        assert CollectiveEngine._hazard_free(sched), (
            f"{algorithm} {collective} {shape} emitted a hazard")
