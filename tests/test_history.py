"""Fleet history ledger (ISSUE 17): obs/history.py — the append-only
run ledger, robust baseline math, attributed trend verdicts, learned
sentinel thresholds — plus the ``agent_trend`` CLI over it.

Durability legs the satellite checklist pins: a torn final line from
a killed writer is a counted skip, rotation keeps one previous
generation, two processes appending concurrently interleave whole
lines, and a malformed ``TPU_HISTORY_DIR`` degrades to recording-off
with a counted ``history.disabled`` — never a crash.
"""

import importlib.util
import json
import os
import subprocess
import sys

import pytest

from container_engine_accelerators_tpu.metrics import counters
from container_engine_accelerators_tpu.obs import history

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_cli(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "cmd", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(autouse=True)
def _no_ambient_history(monkeypatch):
    """Tests drive the ledger through explicit roots — an operator's
    real TPU_HISTORY_DIR must never leak in (or get written to)."""
    monkeypatch.delenv(history.HISTORY_DIR_ENV, raising=False)
    monkeypatch.delenv(history.HISTORY_CAP_ENV, raising=False)


# ---------------------------------------------------------------------------
# ledger append + read
# ---------------------------------------------------------------------------


class TestRunLedger:
    def test_record_round_trip(self, tmp_path):
        led = history.RunLedger(str(tmp_path))
        rec = led.record("dcn_bench", "dcn_bench:shm:4096",
                         {"mbps": 1234.5}, run_id="r1", seed=7,
                         cpu_attr={"shm-staging": 0.6, "other": 0.4},
                         dominant_phase="dcn.shm.stage",
                         sentinels={"leak_slopes": {"fds": 0.1}},
                         slo={"ok": True})
        assert rec["schema"] == history.SCHEMA_VERSION
        assert rec["version"]  # VERSION stamp (or "unknown")
        got = led.records(kind="dcn_bench",
                          cfg_key="dcn_bench:shm:4096")
        assert len(got) == 1
        assert got[0]["run_id"] == "r1"
        assert got[0]["seed"] == 7
        assert got[0]["metrics"] == {"mbps": 1234.5}
        assert got[0]["cpu_attr"]["shm-staging"] == 0.6
        assert got[0]["dominant_phase"] == "dcn.shm.stage"
        assert got[0]["sentinels"]["leak_slopes"]["fds"] == 0.1
        assert got[0]["slo"] == {"ok": True}

    def test_filters(self, tmp_path):
        led = history.RunLedger(str(tmp_path))
        led.record("a", "k1", {"x": 1.0})
        led.record("a", "k2", {"y": 2.0})
        led.record("b", "k1", {"x": 3.0})
        assert len(led.records()) == 3
        assert len(led.records(kind="a")) == 2
        assert len(led.records(cfg_key="k1")) == 2
        assert len(led.records(metric="y")) == 1
        assert len(led.records(kind="a", cfg_key="k1",
                               metric="x")) == 1

    def test_unconfigured_env_is_silently_off(self):
        led = history.RunLedger()
        assert not led.enabled
        assert led.record("k", "c", {"m": 1.0}) is None
        assert led.records() == []

    def test_torn_final_line_is_counted_skip(self, tmp_path):
        """A writer killed mid-append leaves a torn last line: the
        read side skips it, counts it, and returns every whole
        record — never a crash."""
        led = history.RunLedger(str(tmp_path))
        led.record("k", "c", {"m": 1.0}, run_id="whole")
        with open(led.path, "ab") as fh:
            fh.write(b'{"schema": 1, "run_id": "torn", "metr')
        before = counters.get("history.skipped")
        got = led.records()
        assert [r["run_id"] for r in got] == ["whole"]
        assert counters.get("history.skipped") == before + 1

    def test_corrupt_and_wrong_shape_lines_skipped(self, tmp_path):
        led = history.RunLedger(str(tmp_path))
        led.record("k", "c", {"m": 1.0}, run_id="good")
        with open(led.path, "ab") as fh:
            fh.write(b"\xff\xfe not json\n")      # undecodable
            fh.write(b'"a json string"\n')         # not a dict
            fh.write(b'{"no": "metrics"}\n')       # not a run record
        before = counters.get("history.skipped")
        assert [r["run_id"] for r in led.records()] == ["good"]
        assert counters.get("history.skipped") == before + 3

    def test_rotation_keeps_one_generation(self, tmp_path):
        """Past the cap the live file becomes ``.1`` (the trace-sink
        discipline) and reads stitch rotated-then-live oldest
        first."""
        led = history.RunLedger(str(tmp_path), cap_bytes=600)
        before = counters.get("history.rotated")
        for i in range(12):
            led.record("k", "c", {"m": float(i)}, run_id=f"r{i}")
        assert os.path.exists(led.path + ".1")
        assert counters.get("history.rotated") > before
        got = led.records()
        # Whatever survived rotation is in append order, the newest
        # record always last (it just went to the live file).
        vals = [r["metrics"]["m"] for r in got]
        assert vals == sorted(vals)
        assert vals[-1] == 11.0

    def test_concurrent_append_two_processes(self, tmp_path):
        """Two recorders appending concurrently interleave WHOLE
        lines (single O_APPEND write per record): every record
        parses, none are lost or torn."""
        script = (
            "import sys; sys.path.insert(0, sys.argv[1])\n"
            "from container_engine_accelerators_tpu.obs import "
            "history\n"
            "led = history.RunLedger(sys.argv[2], cap_bytes=0)\n"
            "for i in range(120):\n"
            "    led.record('k', 'c', {'m': float(i)},\n"
            "               run_id=f'{sys.argv[3]}-{i}',\n"
            "               cpu_attr={'serving': 0.5, 'other': 0.5})\n"
        )
        procs = [
            subprocess.Popen(
                [sys.executable, "-c", script, REPO, str(tmp_path),
                 tag])
            for tag in ("a", "b")
        ]
        for p in procs:
            assert p.wait(timeout=60) == 0
        before = counters.get("history.skipped")
        got = history.RunLedger(str(tmp_path)).records()
        assert counters.get("history.skipped") == before
        ids = [r["run_id"] for r in got]
        assert len(ids) == 240 and len(set(ids)) == 240

    def test_malformed_dir_disables_with_counted_event(self, tmp_path):
        """TPU_HISTORY_DIR pointing at a FILE cannot hold a ledger:
        recording turns off loudly (history.disabled) and every
        record is a no-op — the run itself is untouched."""
        bogus = tmp_path / "not-a-dir"
        bogus.write_text("occupied")
        before = counters.get("history.disabled")
        led = history.RunLedger(str(bogus))
        assert not led.enabled
        assert counters.get("history.disabled") == before + 1
        assert led.record("k", "c", {"m": 1.0}) is None
        assert led.records() == []

    def test_env_resolution(self, tmp_path, monkeypatch):
        monkeypatch.setenv(history.HISTORY_DIR_ENV, str(tmp_path))
        led = history.RunLedger()
        assert led.enabled
        led.record("k", "c", {"m": 2.0})
        assert len(history.RunLedger().records()) == 1

    def test_unreadable_ledger_raises_ledger_error(self, tmp_path):
        led = history.RunLedger(str(tmp_path))
        # A directory squatting on the ledger path: exists, cannot be
        # read as a file — the exit-2 signal, distinct from "empty".
        os.mkdir(led.path)
        with pytest.raises(history.LedgerError):
            led.records()


# ---------------------------------------------------------------------------
# baseline math + learned thresholds
# ---------------------------------------------------------------------------


class TestBaselineMath:
    def test_median_and_mad(self):
        assert history.median([3, 1, 2]) == 2
        assert history.median([4, 1, 2, 3]) == 2.5
        assert history.median([]) == 0.0
        assert history.mad([1, 1, 1]) == 0.0
        # values 1..5: deviations from median 3 are [2,1,0,1,2]
        assert history.mad([1, 2, 3, 4, 5]) == 1.0

    def test_metric_direction(self):
        assert history.metric_direction("p99_e2e_ms") == "lower"
        assert history.metric_direction("leak_slope.fds") == "lower"
        assert history.metric_direction("max_dedup_ratio") == "lower"
        assert history.metric_direction("min_goodput_bps") == "higher"
        assert history.metric_direction("mbps") == "higher"
        # Unknown names default to throughput-shaped.
        assert history.metric_direction("frobnications") == "higher"

    def test_learned_limit_pinned_fallback(self):
        out = history.learned_limit([0.4, 0.5], pinned=2.0,
                                    min_runs=3)
        assert out["source"] == "pinned"
        assert out["limit"] == 2.0

    def test_learned_limit_tightens_ceiling(self):
        out = history.learned_limit([0.4, 0.5, 0.45, 0.5, 0.4],
                                    pinned=2.0, min_runs=3)
        assert out["source"] == "learned"
        # median 0.45 + 3*max(MAD 0.05, floor) — far below pinned.
        assert 0.45 < out["limit"] < 1.0
        assert out["ceiling"] == 2.0

    def test_learned_limit_never_relaxes_past_pinned(self):
        """History worse than the pinned budget must not loosen it:
        the ceiling clamp is the hard bound."""
        out = history.learned_limit([5.0, 6.0, 5.5, 6.5],
                                    pinned=2.0, min_runs=3)
        assert out["source"] == "learned"
        assert out["limit"] == 2.0

    def test_learned_limit_floor_kind(self):
        """Floor-shaped budgets (min_goodput_bps) learn median -
        k*MAD and may only come UP from the pinned floor."""
        out = history.learned_limit([100.0, 102.0, 98.0, 101.0],
                                    pinned=10.0, min_runs=3,
                                    kind="floor")
        assert out["source"] == "learned"
        assert 10.0 < out["limit"] < 100.0
        # A pinned floor ABOVE history: learned may not sink past it.
        out = history.learned_limit([100.0, 102.0, 98.0],
                                    pinned=99.0, min_runs=3,
                                    kind="floor")
        assert out["limit"] == 99.0


def _prior(values, metric="p99_ms", cpu_attr=None, phase=None):
    return [{"metrics": {metric: v},
             **({"cpu_attr": cpu_attr} if cpu_attr else {}),
             **({"dominant_phase": phase} if phase else {})}
            for v in values]


class TestTrendVerdict:
    def test_no_baseline_when_thin(self):
        v = history.trend_verdict(_prior([40.0, 41.0]), "p99_ms",
                                  44.0)
        assert v["status"] == "no_baseline" and v["ok"]

    def test_ok_inside_band(self):
        v = history.trend_verdict(_prior([40.0, 41.0, 40.5, 41.5]),
                                  "p99_ms", 41.0)
        assert v["status"] == "ok" and v["ok"]
        assert v["median"] == pytest.approx(40.75)

    def test_regression_latency_up(self):
        v = history.trend_verdict(_prior([40.0, 41.0, 40.5, 41.5]),
                                  "p99_ms", 80.0)
        assert v["status"] == "regressed" and not v["ok"]
        assert v["delta_pct"] > 90

    def test_improvement_never_gates(self):
        v = history.trend_verdict(_prior([40.0, 41.0, 40.5, 41.5]),
                                  "p99_ms", 20.0)
        assert v["status"] == "improved" and v["ok"]

    def test_throughput_direction(self):
        prior = _prior([900.0, 905.0, 910.0], metric="mbps")
        assert history.trend_verdict(prior, "mbps", 400.0)["status"] \
            == "regressed"
        assert history.trend_verdict(prior, "mbps", 1500.0)["status"] \
            == "improved"

    def test_mad_floor_tolerates_flat_history_noise(self):
        """A perfectly flat history has MAD 0 — the floor keeps
        ordinary scheduling noise inside the band."""
        prior = _prior([100.0] * 6, metric="mbps")
        assert history.trend_verdict(prior, "mbps", 99.0)["status"] \
            == "ok"

    def test_attribution_names_the_mover(self):
        base_attr = {"serving": 0.6, "shm-staging": 0.2,
                     "dcn_pipeline": 0.2}
        prior = _prior([40.0, 41.0, 40.5, 41.5],
                       cpu_attr=base_attr, phase="dcn.chunk.send")
        v = history.trend_verdict(
            prior, "p99_ms", 80.0,
            cpu_attr={"serving": 0.45, "shm-staging": 0.38,
                      "dcn_pipeline": 0.17},
            dominant_phase="dcn.chunk.stage")
        attr = v["attribution"]
        movers = {m["subsystem"]: m["delta_pts"]
                  for m in attr["subsystems"]}
        assert movers["shm-staging"] == pytest.approx(18.0)
        assert movers["serving"] == pytest.approx(-15.0)
        assert attr["dominant_phase"] == "dcn.chunk.stage"
        assert attr["prior_dominant_phase"] == "dcn.chunk.send"
        line = history.format_verdict(v)
        assert "REGRESSED" in line
        assert "shm-staging share +18.0pts" in line
        assert "dcn.chunk.stage (was dcn.chunk.send)" in line

    def test_attribution_flat_shares_reported_flat(self):
        attr = history.attribute(
            {"serving": 0.5, "other": 0.5}, None,
            _prior([1.0], cpu_attr={"serving": 0.51, "other": 0.49}))
        assert attr["subsystems"] == []
        assert set(attr["flat"]) == {"serving", "other"}


class TestFleetReportEvidence:
    def test_extracts_measured_shares_and_phase(self):
        report = {
            "slo": {"measured": {"min_goodput_bps": 5e6,
                                 "p99_leg_ms": 12.5,
                                 "elapsed_s": 9.0,
                                 "stale_entries_skipped": 2}},
            "profile": {"fleet": {"subsystems": {
                "serving": 30, "shm-staging": 10, "idle": 200}}},
            "critical_path": {"dominant_phase": "dcn.chunk.send"},
        }
        metrics, cpu_attr, phase = \
            history.fleet_report_evidence(report)
        assert metrics == {"min_goodput_bps": 5e6,
                           "p99_leg_ms": 12.5}
        assert cpu_attr["serving"] == pytest.approx(0.75)
        assert cpu_attr["shm-staging"] == pytest.approx(0.25)
        assert "idle" not in cpu_attr
        assert phase == "dcn.chunk.send"

    def test_absent_sections_attribute_nothing(self):
        metrics, cpu_attr, phase = history.fleet_report_evidence({})
        assert metrics == {} and cpu_attr is None and phase is None


# ---------------------------------------------------------------------------
# agent_trend CLI
# ---------------------------------------------------------------------------


class TestAgentTrendCli:
    def _seed_regression(self, root):
        """Four quiet runs then one regressed run with a planted
        shm-staging CPU skew — the acceptance fixture shape."""
        led = history.RunLedger(str(root))
        for i in range(4):
            led.record("fleet_serving", "fleet-serving:n4",
                       {"p99_e2e_ms": 40.0 + i * 0.5,
                        "sustained_qps": 900.0 + i},
                       cpu_attr={"serving": 0.6, "shm-staging": 0.2,
                                 "dcn_pipeline": 0.2},
                       dominant_phase="serve.batch")
        led.record("fleet_serving", "fleet-serving:n4",
                   {"p99_e2e_ms": 80.0, "sustained_qps": 895.0},
                   cpu_attr={"serving": 0.45, "shm-staging": 0.38,
                             "dcn_pipeline": 0.17},
                   dominant_phase="dcn.chunk.stage")

    def test_regression_exits_1_and_names_subsystem(
            self, tmp_path, capsys):
        self._seed_regression(tmp_path)
        at = _load_cli("agent_trend")
        rc = at.main(["--dir", str(tmp_path)])
        assert rc == 1
        captured = capsys.readouterr()
        assert "shm-staging share +18.0pts" in captured.err
        assert "REGRESSED" in captured.err
        summary = json.loads(captured.out.strip().splitlines()[-1])
        assert summary["regressed"] == 1 and not summary["ok"]
        bad = [s for s in summary["series"]
               if s["verdict"]["status"] == "regressed"]
        assert [s["metric"] for s in bad] == ["p99_e2e_ms"]

    def test_clean_history_exits_0(self, tmp_path, capsys):
        led = history.RunLedger(str(tmp_path))
        for i in range(5):
            led.record("dcn_bench", "dcn_bench:shm:4096",
                       {"mbps": 1000.0 + i})
        at = _load_cli("agent_trend")
        assert at.main(["--dir", str(tmp_path)]) == 0
        summary = json.loads(
            capsys.readouterr().out.strip().splitlines()[-1])
        assert summary["ok"]

    def test_unreadable_ledger_exits_2(self, tmp_path, capsys):
        os.mkdir(os.path.join(str(tmp_path), history.LEDGER_NAME))
        at = _load_cli("agent_trend")
        assert at.main(["--dir", str(tmp_path)]) == 2

    def test_no_history_dir_exits_2(self, capsys):
        at = _load_cli("agent_trend")
        assert at.main([]) == 2

    def test_min_runs_flag_judges_thin_history(self, tmp_path,
                                               capsys):
        """The two-run `make trend` fixture: with --min-runs 1 a
        single prior run is a baseline."""
        led = history.RunLedger(str(tmp_path))
        led.record("dcn_bench", "k", {"mbps": 1000.0})
        led.record("dcn_bench", "k", {"mbps": 400.0})
        at = _load_cli("agent_trend")
        assert at.main(["--dir", str(tmp_path),
                        "--min-runs", "1"]) == 1

    def test_import_seeds_bench_rounds_idempotently(self, tmp_path,
                                                    capsys):
        at = _load_cli("agent_trend")
        rounds = [os.path.join(REPO, f"BENCH_r0{n}.json")
                  for n in (1, 2, 4, 5)]
        rounds += [os.path.join(REPO, f"MULTICHIP_r0{n}.json")
                   for n in (1, 2)]
        argv = ["--dir", str(tmp_path)]
        for r in rounds:
            argv += ["--import", r]
        at.main(argv)
        err = capsys.readouterr().err
        # r01 failed (rc=1): skipped with a note, never a crash.
        assert "BENCH_r01.json: skipped" in err
        assert "BENCH_r02.json: imported" in err
        led = history.RunLedger(str(tmp_path))
        bench = led.records(kind="bench_hw")
        assert len(bench) == 3  # r02, r04, r05 carry parsed metrics
        assert all(r["run_id"].startswith("import-") for r in bench)
        multi = led.records(kind="multichip")
        assert [r["metrics"]["ok"] for r in multi] == [0.0, 1.0]
        # Re-import: no duplicate records.
        at.main(argv)
        capsys.readouterr()
        assert len(history.RunLedger(str(tmp_path)).records()) \
            == len(bench) + len(multi)

    def test_filters_scope_the_tables(self, tmp_path, capsys):
        self._seed_regression(tmp_path)
        led = history.RunLedger(str(tmp_path))
        for i in range(4):
            led.record("dcn_bench", "k", {"mbps": 1000.0})
        at = _load_cli("agent_trend")
        rc = at.main(["--dir", str(tmp_path), "--kind", "dcn_bench"])
        assert rc == 0  # the regression lives in fleet_serving
        summary = json.loads(
            capsys.readouterr().out.strip().splitlines()[-1])
        assert {s["kind"] for s in summary["series"]} == {"dcn_bench"}


# ---------------------------------------------------------------------------
# e2e acceptance: planted CPU burn across two fleet-serving runs
# ---------------------------------------------------------------------------


@pytest.mark.slow
class TestFleetServingTrendAcceptance:
    def test_planted_cpu_burn_attributed_across_two_runs(
            self, tmp_path, monkeypatch, capsys):
        """The ISSUE 17 acceptance run: a quiet bench_serving --fleet
        run records the baseline; a second run with planted CPU-burn
        threads spinning inside parallel/dcn_shm.py (the profiler's
        shm-staging subsystem) both starves the serving path (GIL)
        and skews cpu_attr — agent_trend must exit 1 and name
        shm-staging in the attribution."""
        import threading

        from container_engine_accelerators_tpu.parallel import dcn_shm

        monkeypatch.setenv(history.HISTORY_DIR_ENV, str(tmp_path))
        bs = _load_cli("bench_serving")
        argv = ["--fleet", "--fleet-seconds", "2"]
        assert bs.main(list(argv)) == 0
        capsys.readouterr()

        stop = threading.Event()

        def burn():
            env = {}
            while not stop.is_set():
                for _ in range(1000):
                    dcn_shm.shm_enabled(env)

        burners = [threading.Thread(target=burn, daemon=True)
                   for _ in range(4)]
        for t in burners:
            t.start()
        try:
            # rc is not asserted: GIL starvation may push the run
            # into serving errors (exit 1) — the ledger record lands
            # either way, which is the point.
            bs.main(list(argv))
        finally:
            stop.set()
            for t in burners:
                t.join(10)
        capsys.readouterr()

        at = _load_cli("agent_trend")
        rc = at.main(["--dir", str(tmp_path), "--kind",
                      "fleet_serving", "--min-runs", "1"])
        err = capsys.readouterr().err
        assert rc == 1, err
        regressed = [l for l in err.splitlines() if "REGRESSED" in l]
        assert regressed, err
        assert any("shm-staging share +" in l for l in regressed), err
