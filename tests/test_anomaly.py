"""Grey-failure detection (obs/anomaly.py): peer-relative robust
z-scoring, the hysteresis verdict ladder, and the closed-loop
precision/recall judge against the soak world's seeded schedule.

The scoring and ladder layers are judged with SYNTHETIC evidence and
deliberately enormous planted deviations — one sick node among healthy
peers must convict only the sick node, a fleet-wide slowdown must
convict nobody, an idle window must contribute nothing, and none of it
may hinge on a flaky threshold.  The real composed proof — a proc-mode
soak where a scripted ``slow_ring`` grey node is confirmed, SIGKILLed,
respawned, and cleared — runs once, short and ``slow``-marked;
``make anomaly`` drives it plus the seeded CLI gate.
"""

import importlib.util
import os
import time

import pytest

from container_engine_accelerators_tpu.fleet import soak
from container_engine_accelerators_tpu.fleet.soak import SoakSchedule
from container_engine_accelerators_tpu.fleet.telemetry import (
    SLO_KEYS,
    FleetTelemetry,
)
from container_engine_accelerators_tpu.fleet.xferd import PyXferd
from container_engine_accelerators_tpu.obs import anomaly
from container_engine_accelerators_tpu.obs.anomaly import (
    CONFIRMED,
    HEALTHY,
    SUSPECT,
    AnomalyDetector,
    Evidence,
    TruthWindow,
    bucket_delta_p99_us,
    detection_report,
    robust_zscores,
)
from container_engine_accelerators_tpu.scheduler import (
    topology as sched_topo,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

NAMES = ["n0", "n1", "n2"]


def _load_cli(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "cmd", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _ev(values, direction="high", abs_floor=0.1):
    return [Evidence("m", values, direction=direction,
                     abs_floor=abs_floor)]


HOT = {"a": 100.0, "b": 1.0, "c": 1.0}
QUIET = {"a": 1.0, "b": 1.0, "c": 1.0}


# ---------------------------------------------------------------------------
# peer-relative robust z-scores
# ---------------------------------------------------------------------------


class TestRobustZScores:
    def test_one_sick_of_three_convicts_only_the_sick(self):
        """The healthy majority pins the median and the MAD collapses
        to the floor — the sick node's z is enormous, its peers' 0."""
        zs = robust_zscores({"a": 100.0, "b": 1.0, "c": 1.1},
                            direction="high", abs_floor=0.5)
        assert zs["a"] > 50.0
        assert zs["b"] == 0.0
        assert zs["c"] < 2.0

    def test_uniform_slowdown_convicts_nobody(self):
        """A GLOBAL slowdown (a loaded host) moves the median with the
        fleet: nobody deviates from peers, nobody scores."""
        zs = robust_zscores({"a": 500.0, "b": 500.0, "c": 500.0},
                            direction="high", abs_floor=0.5)
        assert all(z == 0.0 for z in zs.values())

    def test_idle_degenerate_window_is_not_evidence(self):
        """Median AND MAD under the absolute floor = an idle fleet: no
        dispersion baseline, no conviction — the ledger's no_baseline
        verdict applied across space."""
        zs = robust_zscores({"a": 0.01, "b": 0.0, "c": 0.0},
                            direction="high", abs_floor=1.0)
        assert all(z == 0.0 for z in zs.values())

    def test_outlier_among_idle_peers_still_convicts(self):
        """Idleness is judged on EVERY value, not the median: a 65ms
        p99 among sub-floor peers is the textbook one-sick-of-N, and
        a median-based idle test would wave it through."""
        zs = robust_zscores({"a": 65536.0, "b": 128.0, "c": 256.0},
                            direction="high", abs_floor=4096.0)
        assert zs["a"] > 10.0
        assert zs["b"] == 0.0 and zs["c"] == 0.0

    def test_too_few_peers_no_verdict(self):
        zs = robust_zscores({"a": 100.0, "b": 1.0},
                            direction="high", abs_floor=0.1,
                            min_peers=3)
        assert zs == {"a": 0.0, "b": 0.0}

    def test_good_direction_deviation_never_scores(self):
        """A node FASTER than its peers is not sick."""
        zs = robust_zscores({"a": 0.1, "b": 10.0, "c": 10.0},
                            direction="high", abs_floor=0.1)
        assert zs["a"] == 0.0

    def test_low_direction_scores_the_starved_node(self):
        """Goodput-shaped: direction="low" convicts the node BELOW its
        peers, never the ones above."""
        zs = robust_zscores({"a": 10.0, "b": 1000.0, "c": 1000.0},
                            direction="low", abs_floor=64.0)
        assert zs["a"] > 5.0
        assert zs["b"] == 0.0 and zs["c"] == 0.0


# ---------------------------------------------------------------------------
# the hysteresis verdict ladder
# ---------------------------------------------------------------------------


class TestVerdictLadder:
    def _det(self):
        return AnomalyDetector(dump_on_confirm=False)

    def test_single_window_spike_suspects_but_never_confirms(self):
        """One hot window steps healthy->suspect; quiet windows after
        it must decay and CLEAR without ever confirming — flap
        resistance is the ladder's whole contract."""
        det = self._det()
        det.observe(0, _ev(HOT))
        assert det.state["a"] == SUSPECT
        for w in range(1, 8):
            det.observe(w, _ev(QUIET))
        assert det.state["a"] == HEALTHY
        assert det.confirmations == []
        assert det.score["a"] < 0.5

    def test_sustained_deviation_confirms(self):
        det = self._det()
        det.observe(0, _ev(HOT))
        det.observe(1, _ev(HOT))
        assert det.state["a"] == CONFIRMED
        (conf,) = det.confirmations
        assert conf["entity"] == "a" and conf["window"] == 1
        # Peers never left healthy.
        assert det.state["b"] == HEALTHY

    def test_clear_needs_consecutive_quiet_windows(self):
        """One quiet window between hot ones resets nothing: clearing
        demands clear_windows CONSECUTIVE windows under clear_z."""
        det = self._det()
        det.observe(0, _ev(HOT))
        det.observe(1, _ev(HOT))
        assert det.state["a"] == CONFIRMED
        det.observe(2, _ev(QUIET))   # score 6 — loud, not quiet
        det.observe(3, _ev(HOT))     # hot again
        assert det.state["a"] == CONFIRMED
        for w in range(4, 12):
            det.observe(w, _ev(QUIET))
        assert det.state["a"] == HEALTHY

    def test_absent_entity_holds_state_and_score(self):
        """No observation is not evidence of health: a stale/down
        entity is excluded from scoring AND from decay."""
        det = self._det()
        det.observe(0, _ev(HOT))
        det.observe(1, _ev(HOT))
        assert det.state["a"] == CONFIRMED
        score = det.score["a"]
        det.observe(2, _ev(QUIET), absent={"a"})
        det.observe(3, _ev(QUIET), absent={"a"})
        assert det.state["a"] == CONFIRMED
        assert det.score["a"] == score

    def test_flagged_windows_record_suspect_and_worse(self):
        det = self._det()
        det.observe(3, _ev(HOT))
        det.observe(4, _ev(HOT))
        assert det.flagged["a"] == [3, 4]
        assert "b" not in det.flagged

    def test_report_shape(self):
        det = self._det()
        det.observe(0, _ev(HOT))
        rep = det.report()
        assert rep["enabled"] and rep["windows"] == 1
        assert rep["verdicts"]["a"]["state"] == "suspect"
        assert rep["flagged_windows"] == {"a": [0]}

    def test_warmup_windows_swallow_boot_transients(self):
        """Evidence inside the warmup is counted but never scored —
        the boot round's cold-start legs must not seed suspicion."""
        det = AnomalyDetector(
            anomaly.AnomalyConfig(warmup_windows=1),
            dump_on_confirm=False)
        assert det.observe(0, _ev(HOT)) == {}
        assert det.state == {} and det.flagged == {}
        assert det.windows_observed == 1
        det.observe(1, _ev(HOT))
        assert det.state["a"] == SUSPECT

    def test_per_stream_rel_floor_mutes_quantized_noise(self):
        """A stream with rel_floor=0.5 (windowed byte counts) caps a
        healthy node's burst-alignment dip well under suspect_z even
        when its two peers agree exactly and the MAD collapses."""
        det = AnomalyDetector(dump_on_confirm=False)
        noisy = [Evidence("bytes",
                          {"a": 49152.0, "b": 262144.0,
                           "c": 262144.0},
                          direction="low", abs_floor=4096.0,
                          rel_floor=0.5)]
        inst = det.observe(0, noisy)
        assert inst["a"] < det.cfg.suspect_z
        assert det.state.get("a", HEALTHY) == HEALTHY
        # Same values through the default floor WOULD convict: the
        # override is what holds the stream to corroborating duty.
        zs = robust_zscores({"a": 49152.0, "b": 262144.0,
                             "c": 262144.0},
                            direction="low", abs_floor=4096.0)
        assert zs["a"] > det.cfg.suspect_z


# ---------------------------------------------------------------------------
# the kill switch
# ---------------------------------------------------------------------------


class TestKillSwitch:
    def test_disabled_detector_is_inert(self, monkeypatch):
        monkeypatch.setenv(anomaly.KILL_SWITCH_ENV, "0")
        assert not anomaly.enabled()
        det = AnomalyDetector()
        assert not det.enabled
        assert det.observe(0, _ev(HOT)) == {}
        assert det.windows_observed == 0
        assert det.state == {} and det.score == {}

    def test_disabled_penalty_is_zero_even_with_state(self,
                                                      monkeypatch):
        monkeypatch.setenv(anomaly.KILL_SWITCH_ENV, "0")
        det = AnomalyDetector()
        det.state["h0"] = CONFIRMED  # forced — observe won't set it
        pen = det.scheduler_penalty()
        node = {"node_labels": {sched_topo.HOST_LABEL: "h0"}}
        assert pen(node, node) == 0.0

    def test_default_is_enabled(self, monkeypatch):
        monkeypatch.delenv(anomaly.KILL_SWITCH_ENV, raising=False)
        assert anomaly.enabled()


# ---------------------------------------------------------------------------
# the scheduler surcharge
# ---------------------------------------------------------------------------


class TestSchedulerPenalty:
    def _node(self, host):
        return {"node_labels": {sched_topo.HOST_LABEL: host}}

    def test_surcharges_by_state_and_never_vetoes(self):
        det = AnomalyDetector(dump_on_confirm=False)
        det.state["h_conf"] = CONFIRMED
        det.state["h_susp"] = SUSPECT
        pen = det.scheduler_penalty(suspect_surcharge=50.0,
                                    confirmed_surcharge=500.0)
        healthy = self._node("h_ok")
        assert pen(healthy, healthy) == 0.0
        assert pen(self._node("h_susp"), healthy) == 50.0
        assert pen(self._node("h_conf"), healthy) == 500.0
        both = pen(self._node("h_conf"), self._node("h_susp"))
        assert both == 550.0  # additive, finite — never a veto

    def test_unknown_host_pays_nothing(self):
        det = AnomalyDetector(dump_on_confirm=False)
        det.state["h0"] = CONFIRMED
        pen = det.scheduler_penalty()
        assert pen({}, {"node_labels": {}}) == 0.0


# ---------------------------------------------------------------------------
# windowed p99 from scraped cumulative buckets
# ---------------------------------------------------------------------------


class TestBucketDeltaP99:
    def test_first_window_is_the_full_histogram(self):
        cur = {"1000": 5.0, "8000": 5.0, "+Inf": 5.0}
        assert bucket_delta_p99_us(cur, {}) == 1000.0

    def test_delta_sees_only_the_new_observations(self):
        """The old fast observations must not dilute a window whose
        NEW observations are all slow."""
        base = {"1000": 50.0, "8000": 50.0, "+Inf": 50.0}
        cur = {"1000": 50.0, "8000": 55.0, "+Inf": 55.0}
        assert bucket_delta_p99_us(cur, base) == 8000.0

    def test_no_new_observations_is_none(self):
        base = {"1000": 5.0, "+Inf": 5.0}
        assert bucket_delta_p99_us(dict(base), base) is None
        assert bucket_delta_p99_us({}, {}) is None

    def test_counter_regression_is_respawn_not_evidence(self):
        base = {"1000": 50.0, "+Inf": 50.0}
        cur = {"1000": 3.0, "+Inf": 3.0}  # worker restarted
        assert bucket_delta_p99_us(cur, base) is None


# ---------------------------------------------------------------------------
# the closed-loop judge
# ---------------------------------------------------------------------------


class TestDetectionReport:
    def test_flag_within_k_detects_with_latency(self):
        truth = [TruthWindow("n1", window=4, lifetime=1)]
        det = detection_report(truth, {"n1": [5, 6]}, windows=12, k=2)
        assert det["recall"] == 1.0 and not det["missed"]
        assert det["detections"][0]["detect_windows"] == 1
        assert det["detect_windows_max"] == 1.0

    def test_flag_past_k_is_a_miss(self):
        truth = [TruthWindow("n1", window=4, lifetime=1)]
        det = detection_report(truth, {"n1": [9]}, windows=12, k=2)
        assert det["recall"] == 0.0
        assert det["missed"][0]["node"] == "n1"

    def test_false_positive_only_on_clean_windows(self):
        """A flag inside any scheduled fault's footprint (lifetime +
        settle decay) is shared fate, not a detector bug; the same
        flag held across quiet windows is."""
        truth = [TruthWindow("n1", window=2, lifetime=1)]
        flagged = {"n1": [2, 3],      # the detection
                   "n0": [3],         # collateral during the fault
                   "n2": [10, 11]}    # persistent flag, QUIET fleet
        det = detection_report(truth, flagged, windows=12, k=2,
                               settle_windows=2)
        assert det["recall"] == 1.0
        assert det["false_positives"] == [
            {"node": "n2", "window": 10},
            {"node": "n2", "window": 11}]
        assert det["false_positive_count"] == 2

    def test_transient_single_window_flag_is_not_a_false_positive(self):
        """One hot window that self-clears is below the same
        persistence bar the ladder demands for conviction — a loaded
        host's scheduling hiccup, not a page."""
        det = detection_report([], {"n2": [11]}, windows=14,
                               settle_windows=2)
        assert det["false_positive_count"] == 0
        # Two isolated transients are still transients...
        det = detection_report([], {"n2": [5, 11]}, windows=14,
                               settle_windows=2)
        assert det["false_positive_count"] == 0
        # ...but consecutive windows are persistence.
        det = detection_report([], {"n2": [10, 11]}, windows=14,
                               settle_windows=2)
        assert det["false_positive_count"] == 2

    def test_chaos_windows_extend_the_footprint(self):
        """Non-grey scheduled faults (kills, link drops) carry no
        truth entry but their windows are still not clean."""
        det = detection_report([], {"n0": [7]}, windows=12,
                               chaos_windows={7})
        assert det["false_positive_count"] == 0

    def test_no_truth_is_vacuous(self):
        det = detection_report([], {}, windows=10)
        assert det["recall"] == 1.0
        assert det["detect_windows_max"] == 0.0
        assert det["clean_windows"] == 10


# ---------------------------------------------------------------------------
# the slow_shm grey fault: schedule grammar + daemon throttle
# ---------------------------------------------------------------------------


class TestSlowShmSchedule:
    def test_shm_scenarios_add_the_window_five_leg(self):
        s = SoakSchedule(99, NAMES, shm=True)
        (slow,) = s.faults_for(5)
        assert slow["slow_shm"] in NAMES and slow["for"] == 1
        assert s.last_deterministic == 5

    def test_socket_scenarios_never_draw_slow_shm(self):
        """A socket-only fleet never commits to shm — the fault would
        be a no-op and the judge would count an undetectable truth."""
        s = SoakSchedule(99, NAMES)
        assert s.last_deterministic == 4
        for w in range(60):
            for entry in s.faults_for(w):
                assert "slow_shm" not in entry

    def test_shm_flag_never_perturbs_other_draws(self):
        """slow_shm draws from a band the non-shm grammar leaves
        clean: any window where the socket grammar drew something must
        draw EXACTLY the same thing with shm on."""
        plain = SoakSchedule(1234, NAMES)
        shm = SoakSchedule(1234, NAMES, shm=True)
        for w in range(6, 60):
            a, b = plain.faults_for(w), shm.faults_for(w)
            if a:
                assert a == b
            elif b:
                (extra,) = b
                assert "slow_shm" in extra

    def test_set_shm_delay_clamped(self, tmp_path):
        d = PyXferd(str(tmp_path / "a"), node="a")
        assert d.set_shm_delay(99.0) == 2.0
        assert d.set_shm_delay(-5.0) == 0.0
        assert d.set_shm_delay(0.25) == 0.25
        assert d.set_shm_delay(0.0) == 0.0


class TestRecordTruth:
    class _Stub:
        def __init__(self, tel):
            self.telemetry = tel

    class _Tel:
        def __init__(self):
            self.anomaly_truth = []
            self.anomaly_chaos = set()

    def test_grey_family_faults_become_truth_with_footprint(self):
        tel = self._Tel()
        world = self._Stub(tel)
        soak.SoakWorld._record_truth(
            world, 3, {"slow_shm": "n1", "for": 1, "applied": 2})
        (t,) = tel.anomaly_truth
        assert t == {"node": "n1", "window": 3, "lifetime": 1,
                     "kind": "slow_shm"}
        # Footprint: lifetime + the settle decay allowance.
        span = 1 + soak.ANOMALY_SETTLE_WINDOWS + 1
        assert tel.anomaly_chaos == set(range(3, 3 + span))

    def test_non_grey_faults_mark_chaos_only(self):
        tel = self._Tel()
        soak.SoakWorld._record_truth(
            self._Stub(tel), 1,
            {"action": "kill", "node": "n0", "for": 1, "applied": 1})
        assert tel.anomaly_truth == []
        assert 1 in tel.anomaly_chaos

    def test_unapplied_faults_are_not_truth(self):
        tel = self._Tel()
        soak.SoakWorld._record_truth(
            self._Stub(tel), 2,
            {"grey": "nX", "for": 1, "applied": 0,
             "skipped": "unknown node"})
        assert tel.anomaly_truth == [] and tel.anomaly_chaos == set()


# ---------------------------------------------------------------------------
# SLO wiring (fleet/telemetry.py)
# ---------------------------------------------------------------------------


class _FakeLinks:
    def report(self):
        return {}


class TestDetectionSlo:
    def test_slo_key_registered_as_ceiling(self):
        kind, _ = SLO_KEYS["max_grey_detection_windows"]
        assert kind == "ceiling"

    def test_no_truth_measures_zero(self):
        t = FleetTelemetry({}, _FakeLinks(), None)
        assert t._grey_detection_windows() == 0.0

    def test_detected_truth_measures_worst_latency(self):
        t = FleetTelemetry({}, _FakeLinks(), None)
        t.anomaly_truth.append({"node": "n1", "window": 2,
                                "lifetime": 1, "kind": "grey"})
        t.anomaly.windows_observed = 8
        t.anomaly.flagged["n1"] = [3]
        assert t._grey_detection_windows() == 1.0

    def test_a_miss_measures_the_run_length(self):
        t = FleetTelemetry({}, _FakeLinks(), None)
        t.anomaly_truth.append({"node": "n1", "window": 2,
                                "lifetime": 1, "kind": "grey"})
        t.anomaly.windows_observed = 9
        assert t._grey_detection_windows() == 9.0

    def test_report_carries_detection_only_with_truth(self):
        t = FleetTelemetry({}, _FakeLinks(), None)
        assert "detection" not in t.anomaly_report()
        t.anomaly_truth.append({"node": "n1", "window": 0,
                                "lifetime": 1, "kind": "grey"})
        assert "detection" in t.anomaly_report()

    def test_sparse_histo_stream_borrows_held_peer_baseline(self):
        """A node with no shm commits this window contributes its
        LAST measured p99 as peer baseline — otherwise one quiet node
        drops the stream under min_peers exactly when a peer's
        throttle spikes (how the seeded slow_shm was once missed)."""
        tel = FleetTelemetry({}, _FakeLinks(), None)
        per_node = {n: {"goodput_bps": 0.0} for n in NAMES}
        op = "xferd.shm.commit.p99_us"
        tel._anom_window = {op: {"n0": 128.0, "n1": 128.0,
                                 "n2": 256.0}}
        tel._anomaly_observe(0, per_node, [])
        tel._anom_window = {op: {"n1": 65536.0}}
        tel._anomaly_observe(1, per_node, [])
        assert tel.anomaly.state.get("n1") == SUSPECT
        # The stand-ins age out instead of impersonating live
        # evidence forever: after ANOMALY_HOLD_WINDOWS the stream
        # goes quiet rather than replaying stale p99s.
        for _ in range(4):
            filled = tel._anom_hold_fill(op, {"n1": 65536.0},
                                         per_node, set())
        assert set(filled) == {"n1"}


# ---------------------------------------------------------------------------
# the agent_top suspicion panel
# ---------------------------------------------------------------------------


class TestAgentTopSuspicionPanel:
    def _fams(self, gauges, events=()):
        fams = {f: [] for f in ("agent_rate", "agent_goodput",
                                "agent_gauge", "agent_latency",
                                "agent_exemplar", "agent_events")}
        fams["agent_gauge"] = [({"name": n}, v) for n, v in gauges]
        fams["agent_events"] = [({"event": n}, v) for n, v in events]
        return fams

    def test_panel_rows_scores_and_verdicts(self):
        top = _load_cli("agent_top")
        model = top.digest(self._fams(
            [("anomaly.score.n0", 0.2), ("anomaly.state.n0", 0.0),
             ("anomaly.score.n2", 7.4), ("anomaly.state.n2", 2.0)],
            events=[("anomaly.confirmed", 1.0),
                    ("anomaly.suspect", 2.0)]))
        rows = model["suspicion"]["rows"]
        assert [r["node"] for r in rows] == ["n2", "n0"]  # worst first
        assert rows[0]["state"] == 2
        assert model["suspicion"]["confirmed"] == 1.0
        # The raw anomaly gauges do not double-render in the gauge
        # panel.
        assert not any(n.startswith("anomaly.")
                       for n, _ in model["gauges"])
        out = top.render(model, "test")
        assert "suspicion (grey-failure)" in out
        assert "CONFIRMED-GREY" in out
        assert "healthy" in out
        assert "#" in out  # the score bar

    def test_panel_absent_without_detector_gauges(self):
        top = _load_cli("agent_top")
        model = top.digest(self._fams([("dcn.stripes.active", 2.0)]))
        assert model["suspicion"] is None
        assert "suspicion" not in top.render(model, "test")

    def test_demo_seeds_the_panel(self, capsys):
        top = _load_cli("agent_top")
        rc = top.main(["--demo", "--once"])
        assert rc == 0
        out = capsys.readouterr().out
        assert "suspicion (grey-failure)" in out
        assert "CONFIRMED-GREY" in out


# ---------------------------------------------------------------------------
# the composed proof: scripted grey -> confirm -> SIGKILL -> clear
# ---------------------------------------------------------------------------


class _ScriptedSchedule:
    """A deterministic stand-in for SoakSchedule: a sustained
    slow_ring grey on a known node (node-local completer throttle, so
    the attribution is unambiguous — the ``grey:`` kind smears link
    latency onto every peer), then a SIGKILL of the same node — the
    confirm must come from the peer-relative evidence, and the clear
    must survive the respawn's counter resets."""

    def __init__(self, names):
        self.names = list(names)
        self.grey_node = self.names[-1]
        self.last_deterministic = 5

    def faults_for(self, window):
        if window == 1:
            return [{"slow_ring": self.grey_node, "for": 3}]
        if window == 5:
            return [{"action": "kill", "node": self.grey_node,
                     "for": 1}]
        return []


@pytest.mark.slow
class TestGreyConfirmAndClearE2E:
    def test_scripted_grey_is_confirmed_then_cleared(self):
        t0 = time.monotonic()
        world = soak.SoakWorld(
            {"nodes": 3, "proc": True, "shm": True,
             "shm_direct": False, "min_windows": 14,
             "payload_bytes": 32768, "chunk_bytes": 8192,
             "slo": {"min_final_goodput_bps": 1024,
                     "max_dedup_ratio": 0.9,
                     "max_grey_detection_windows": 4}},
            duration_s=8.0, window_s=1.0, seed=77)
        try:
            world.schedule = _ScriptedSchedule(
                list(world.topology.specs))
            grey = world.schedule.grey_node
            report = world.run()
        finally:
            world.close()
        assert report["converged"]
        anom = report["anomaly"]
        assert anom["enabled"]
        # The grey node was CONFIRMED from peer-relative evidence...
        assert any(c["entity"] == grey
                   for c in anom["confirmations"]), anom
        # ...and cleared by the end: the heal plus the respawn's fresh
        # process left nothing to convict.
        assert anom["verdicts"][grey]["state"] == "healthy", anom
        det = anom["detection"]
        assert det["truth"] >= 1
        assert det["recall"] == 1.0, det
        assert det["false_positive_count"] == 0, det
        # The detection-latency SLO measurement landed.
        (check,) = [c for c in report["slo"]["checks"]
                    if c["slo"] == "max_grey_detection_windows"]
        assert check["value"] <= anomaly.DETECT_WINDOWS_K
        assert time.monotonic() - t0 < 120
