"""Forward op + daemon-routed collective chaos tests.

Two layers.  Wire level: the ``forward`` op itself on a standalone
daemon pair — capability handshake and the capability-less handle,
daemon→daemon movement with reduce combining, caller-assigned seq
discipline, the lost-response replay converging exactly-once (the
dedup evidence the ISSUE's chaos gate asks for), and the
``_combine_into``/``synth.combine`` cross-check that pins the two
reduce implementations together.  Fleet level: routed rounds on a
real in-process fleet — the zero-coordinator-payload proof, link loss
on the forwarded hop retried under the SAME seq (daemon in-op retry
AND the engine-level re-post after the daemon's budget), a
forward-less daemon downgrading mid-schedule, and a killed daemon
failing the round cleanly then recovering after restart.
"""

import time
import uuid

import pytest

from container_engine_accelerators_tpu.collectives import synth
from container_engine_accelerators_tpu.collectives.runner import (
    CollectiveConfig,
    CollectiveEngine,
)
from container_engine_accelerators_tpu.fleet import (
    FleetController,
    PyXferd,
)
from container_engine_accelerators_tpu.fleet.xferd import _combine_into
from container_engine_accelerators_tpu.metrics import counters
from container_engine_accelerators_tpu.obs import timeseries
from container_engine_accelerators_tpu.parallel import dcn
from container_engine_accelerators_tpu.parallel.dcn_client import (
    DcnXferError,
    ResilientDcnXferClient,
)
from container_engine_accelerators_tpu.utils.retry import RetryPolicy

FAST_RETRY = RetryPolicy(
    max_attempts=8, initial_backoff_s=0.01, max_backoff_s=0.1,
    deadline_s=15.0,
)

PAYLOAD = bytes(range(256)) * 16  # 4 KiB
N = len(PAYLOAD)


@pytest.fixture
def xferd_pair(tmp_path):
    a = PyXferd(str(tmp_path / "a"), node="na").start()
    b = PyXferd(str(tmp_path / "b"), node="nb").start()
    ca = ResilientDcnXferClient(str(tmp_path / "a"), retry=FAST_RETRY)
    cb = ResilientDcnXferClient(str(tmp_path / "b"), retry=FAST_RETRY)
    yield a, b, ca, cb
    for c in (ca, cb):
        try:
            c.close()
        except OSError:
            pass
    a.stop()
    b.stop()


def _flow():
    return f"fwd-{uuid.uuid4().hex[:8]}"


def _stage_both(ca, cb, flow, a_bytes, b_bytes):
    """Routed-round setup discipline: the flow registered and staged
    on BOTH daemons (the destination's baseline is what reduce legs
    combine into)."""
    for c, data in ((ca, a_bytes), (cb, b_bytes)):
        c.register_flow(flow, bytes=len(data))
        c.put(flow, data)
        dcn.wait_flow_rx(c, flow, len(data), timeout_s=10)


def _wait_counter(name, floor, timeout_s=5.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if counters.get(name) >= floor:
            return True
        time.sleep(0.01)
    return False


# ---- wire level ------------------------------------------------------------


class TestForwardWire:
    def test_capability_advertised_and_removable(self, tmp_path,
                                                 xferd_pair):
        _a, _b, ca, _cb = xferd_pair
        assert ca.supports_forward()
        legacy = PyXferd(str(tmp_path / "legacy"), node="nl",
                         forward=False).start()
        try:
            cl = ResilientDcnXferClient(str(tmp_path / "legacy"),
                                        retry=FAST_RETRY)
            try:
                assert not cl.supports_forward()
                cl.register_flow("f", bytes=N)
                cl.put("f", PAYLOAD)
                with pytest.raises(DcnXferError,
                                   match="unknown op"):
                    cl.forward("f", "127.0.0.1", _b.data_port, 64,
                               seq=1)
            finally:
                cl.close()
        finally:
            legacy.stop()

    def test_forward_moves_range_daemon_to_daemon(self, xferd_pair):
        a, b, ca, cb = xferd_pair
        flow = _flow()
        base = bytes(N)  # zeros on the destination
        _stage_both(ca, cb, flow, PAYLOAD, base)
        off, ln = 512, 1024
        before_frames = counters.get("xferd.forward.frames")
        before_lane = timeseries.gauges().get(
            "dcn.lane.forward.total_bytes", 0)
        resp = ca.forward(flow, "127.0.0.1", b.data_port, ln,
                          offset=off, seq=1, total=N)
        assert resp["bytes"] == ln
        dcn.wait_flow_rx(cb, flow, N + ln, timeout_s=10)
        # plain (non-reduce) forward overwrites the range, leaves the
        # rest of the destination untouched
        landed = cb.read(flow, N)
        assert landed[off:off + ln] == PAYLOAD[off:off + ln]
        assert landed[:off] == base[:off]
        assert landed[off + ln:] == base[off + ln:]
        # the hop is its own lane: forward counters/gauges move …
        assert counters.get("xferd.forward.frames") \
            == before_frames + 1
        assert timeseries.gauges()["dcn.lane.forward.total_bytes"] \
            == before_lane + ln

    def test_reduce_forward_combines_like_synth(self, xferd_pair):
        a, b, ca, cb = xferd_pair
        flow = _flow()
        base = bytes(reversed(PAYLOAD))
        _stage_both(ca, cb, flow, PAYLOAD, base)
        ca.forward(flow, "127.0.0.1", b.data_port, N, seq=1,
                   total=N, reduce=True)
        dcn.wait_flow_rx(cb, flow, 2 * N, timeout_s=10)
        want = bytearray(base)
        synth.combine(want, 0, PAYLOAD)
        assert cb.read(flow, N) == bytes(want)

    def test_seq_is_caller_assigned_and_required(self, xferd_pair):
        _a, b, ca, cb = xferd_pair
        flow = _flow()
        _stage_both(ca, cb, flow, PAYLOAD, bytes(N))
        with pytest.raises(DcnXferError, match="seq"):
            ca.forward(flow, "127.0.0.1", b.data_port, 64, seq=0)

    def test_unstaged_range_errors_after_bounded_wait(self,
                                                     xferd_pair):
        _a, b, ca, _cb = xferd_pair
        flow = _flow()
        ca.register_flow(flow, bytes=N)  # registered, nothing staged
        with pytest.raises(DcnXferError, match="not staged"):
            ca.forward(flow, "127.0.0.1", b.data_port, 64, seq=1,
                       stage_wait_ms=50)

    def test_lost_response_replay_converges_exactly_once(
            self, xferd_pair):
        """The chaos gate's dedup evidence: the daemon forwards the
        frame, the answer is lost (conn severed before responding),
        the resilient client replays the op — SAME caller-assigned
        seq — and the destination dedups the second frame.  A reduce
        leg makes double-landing detectable byte-for-byte: applied
        twice, the result would differ."""
        a, b, ca, cb = xferd_pair
        flow = _flow()
        base = bytes(reversed(PAYLOAD))
        _stage_both(ca, cb, flow, PAYLOAD, base)
        before_dedup = counters.get("dcn.frames.deduped")
        a.drop_response_once("forward")
        ca.forward(flow, "127.0.0.1", b.data_port, N, seq=7,
                   total=N, reduce=True)
        # both frames reach the destination eventually; the second is
        # dropped by the seq window
        assert _wait_counter("dcn.frames.deduped", before_dedup + 1)
        dcn.wait_flow_rx(cb, flow, 2 * N, timeout_s=10)
        want = bytearray(base)
        synth.combine(want, 0, PAYLOAD)
        assert cb.read(flow, N) == bytes(want), \
            "replayed reduce leg applied more than once"

    @pytest.mark.parametrize("size", [3, 1024])
    def test_combine_into_matches_synth_combine(self, size):
        """The daemon's landing-side reduce and the oracle's reduce
        must be the same function, at both the small-buffer loop and
        the vectorized path."""
        total = size + 64
        dst_a = bytearray(bytes((i * 5) % 251 for i in range(total)))
        dst_b = bytearray(dst_a)
        payload = bytes((i * 11 + 3) % 249 for i in range(size))
        _combine_into(dst_a, 32, payload)
        synth.combine(dst_b, 32, payload)
        assert dst_a == dst_b


# ---- fleet level: routed rounds under chaos --------------------------------


class TestRoutedChaos:
    def _fleet(self, nodes=3, racks=1):
        return FleetController({
            "name": "routed-chaos", "nodes": nodes, "racks": racks,
            "chips": 2, "topology": "1x2x1", "rounds": 0,
            "metrics": False,
        }).boot()

    def _engine(self, ctl, **cfg_kw):
        cfg_kw.setdefault("op", "all_reduce")
        cfg_kw.setdefault("bytes", 8192)
        cfg_kw.setdefault("routed", True)
        return CollectiveEngine(ctl.nodes, ctl.topology,
                                links=ctl.links,
                                cfg=CollectiveConfig(**cfg_kw))

    def test_routed_round_is_pure_control_plane(self):
        ctl = self._fleet()
        try:
            engine = self._engine(ctl)
            try:
                before_lane = timeseries.gauges().get(
                    "dcn.lane.forward.total_bytes", 0)
                entry = engine.run_round(0)
                assert entry["ok"], entry
                routed = entry["routed"]
                assert routed["forward_legs"] > 0
                assert routed["forward_bytes"] > 0
                assert routed["downgraded_legs"] == 0
                # THE claim: zero payload bytes through the
                # coordinator's clients — every forwarded byte is on
                # the daemons' forward lane instead.
                assert routed["coordinator_payload_bytes"] == 0
                lane = timeseries.gauges()[
                    "dcn.lane.forward.total_bytes"]
                assert lane - before_lane == routed["forward_bytes"]
            finally:
                engine.close()
        finally:
            ctl.close()

    def test_link_drop_is_retried_in_daemon_under_same_seq(self):
        """drop:1 on a scheduled hop: the source daemon's in-op retry
        retransmits the SAME seq and the round completes verified —
        the coordinator never notices."""
        ctl = self._fleet()
        try:
            assert ctl.links.apply("node:n0->node:n1:drop:1")
            before = counters.get("fleet.link.dropped")
            engine = self._engine(ctl)
            try:
                entry = engine.run_round(0)
                assert entry["ok"], entry
                assert counters.get("fleet.link.dropped") \
                    == before + 1
                # the daemon reported its retry up through the leg
                # verdict into the round accounting
                assert entry["routed"]["forward_retries"] >= 1
            finally:
                engine.close()
        finally:
            ctl.close()

    def test_drop_budget_exhaustion_reposts_same_seq_from_engine(
            self):
        """drop:3 eats the daemon's whole per-hop budget: the leg
        verdict comes back terminal, the engine re-posts the leg —
        SAME seq, landed-or-dup either way — and the round still
        completes verified."""
        ctl = self._fleet()
        try:
            assert ctl.links.apply("node:n0->node:n1:drop:3")
            before_drop = counters.get("fleet.link.dropped")
            before_retry = counters.get("collective.forward.retried")
            engine = self._engine(ctl)
            try:
                entry = engine.run_round(0)
                assert entry["ok"], entry
                assert counters.get("fleet.link.dropped") \
                    == before_drop + 3
                assert counters.get("collective.forward.retried") \
                    > before_retry
            finally:
                engine.close()
        finally:
            ctl.close()

    def test_forwardless_daemon_downgrades_mid_schedule(self):
        """One daemon loses the forward capability: its legs answer
        "unknown op" and the engine downgrades them to
        coordinator-routed legs mid-schedule — same seqs, round still
        verifies, and the lane accounting shows exactly the
        downgraded bytes crossing the coordinator."""
        ctl = self._fleet()
        try:
            ctl.nodes["n1"].daemon.forward_enabled = False
            before = counters.get("collective.forward.downgraded")
            engine = self._engine(ctl)
            try:
                entry = engine.run_round(0)
                assert entry["ok"], entry
                routed = entry["routed"]
                assert routed["downgraded_legs"] > 0
                assert routed["forward_legs"] > 0  # others forwarded
                assert routed["coordinator_payload_bytes"] > 0
                assert counters.get("collective.forward.downgraded") \
                    > before
            finally:
                engine.close()
        finally:
            ctl.close()

    def test_killed_daemon_fails_round_cleanly_then_recovers(self):
        ctl = self._fleet()
        try:
            engine = self._engine(ctl)
            try:
                assert engine.run_round(0)["ok"]
                ctl.nodes["n2"].kill_daemon()
                entry = engine.run_round(1)
                assert not entry["ok"]
                assert "down" in entry["error"]
                ctl.nodes["n2"].restart_daemon()
                entry = engine.run_round(2)
                assert entry["ok"], entry
                assert entry["routed"]["coordinator_payload_bytes"] \
                    == 0
            finally:
                engine.close()
        finally:
            ctl.close()
