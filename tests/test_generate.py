"""KV-cache decode correctness (models/generate.py).

The serving path's load-bearing property: decode-mode attention with a
cache must agree with the train-mode (full-sequence) forward — greedy
generation is then exactly iterated argmax of the full forward.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from container_engine_accelerators_tpu.models.generate import generate
from container_engine_accelerators_tpu.models.lm_train import (
    create_lm_train_state,
)
from container_engine_accelerators_tpu.models.transformer import (
    transformer_lm,
)

CFG = dict(vocab_size=97, num_layers=2, num_heads=2, head_dim=8,
           mlp_dim=32)


@pytest.fixture(scope="module")
def params():
    state = create_lm_train_state(
        transformer_lm(**CFG), jax.random.PRNGKey(3),
        jnp.zeros((1, 8), jnp.int32), tx=optax.sgd(0.1),
    )
    return state.params


def _train_mode_argmax_continue(params, prompt, n):
    """Reference: iterated argmax of the TRAIN-mode full forward."""
    model = transformer_lm(**CFG)
    toks = prompt
    for _ in range(n):
        logits = model.apply(
            {"params": params}, toks,
            positions=jnp.arange(toks.shape[1]),
        )
        nxt = jnp.argmax(logits[:, -1, :], axis=-1)
        toks = jnp.concatenate([toks, nxt[:, None]], axis=1)
    return toks


def test_greedy_decode_matches_train_mode_forward(params):
    prompt = jnp.asarray([[5, 17, 42], [88, 3, 9]], jnp.int32)
    got = generate(transformer_lm(**CFG, decode=True), params, prompt, 5)
    want = _train_mode_argmax_continue(params, prompt, 5)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_sampled_decode_valid_and_seeded(params):
    prompt = jnp.asarray([[1, 2]], jnp.int32)
    model = transformer_lm(**CFG, decode=True)
    a = generate(model, params, prompt, 8, temperature=1.0,
                 rng=jax.random.PRNGKey(0))
    b = generate(model, params, prompt, 8, temperature=1.0,
                 rng=jax.random.PRNGKey(0))
    c = generate(model, params, prompt, 8, temperature=1.0,
                 rng=jax.random.PRNGKey(1))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))  # seeded
    assert not np.array_equal(np.asarray(a), np.asarray(c))  # varies
    assert np.asarray(a).min() >= 0 and np.asarray(a).max() < 97


def test_bucketed_prompt_matches_exact_length(params):
    """The serving bucket seam (ADVICE r03): a prompt padded to a
    larger bucket with prompt_len passed must produce the SAME tokens
    over [0, prompt_len + max_new) as the exact-length call — pads must
    neither enter the KV cache nor perturb the continuation."""
    model = transformer_lm(**CFG, decode=True)
    prompt = jnp.asarray([[5, 17, 42]], jnp.int32)
    exact = generate(model, params, prompt, 5)
    padded = jnp.asarray([[5, 17, 42, 0, 0, 0, 0, 0]], jnp.int32)
    bucketed = generate(model, params, padded, 5, prompt_len=3)
    np.testing.assert_array_equal(
        np.asarray(exact), np.asarray(bucketed)[:, : 3 + 5]
    )


def test_serve_lm_bucket_len():
    import importlib.util
    import os

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "serve_lm_buckets", os.path.join(repo, "cmd", "serve_lm.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert [mod.bucket_len(n, 64) for n in (1, 2, 3, 5, 8, 9, 64)] == \
        [1, 2, 4, 8, 8, 16, 64]
    # The cap itself is always an allowed bucket, even when not 2**k.
    assert mod.bucket_len(50, 48) == 48
    # Total distinct buckets stays logarithmic in the cap.
    assert len({mod.bucket_len(n, 64) for n in range(1, 65)}) <= 7


def test_generate_requires_decode_model(params):
    with pytest.raises(ValueError, match="decode=True"):
        generate(transformer_lm(**CFG), params,
                 jnp.zeros((1, 2), jnp.int32), 1)


GQA_CFG = dict(vocab_size=97, num_layers=2, num_heads=4, head_dim=8,
               mlp_dim=32, num_kv_heads=2)


class TestGQA:
    """Grouped-query attention: the decode path groups query heads over
    a kv_heads-sized cache (never materializing the repeat) while the
    train path broadcasts K/V up to MHA kernels — greedy decode equal to
    iterated train-mode argmax proves the two factorizations agree."""

    @pytest.fixture(scope="class")
    def gqa_params(self):
        state = create_lm_train_state(
            transformer_lm(**GQA_CFG), jax.random.PRNGKey(3),
            jnp.zeros((1, 8), jnp.int32), tx=optax.sgd(0.1),
        )
        return state.params

    def test_greedy_decode_matches_train_mode(self, gqa_params):
        model = transformer_lm(**GQA_CFG)
        prompt = jnp.asarray([[5, 17, 42], [88, 3, 9]], jnp.int32)
        toks = prompt
        for _ in range(5):
            logits = model.apply(
                {"params": gqa_params}, toks,
                positions=jnp.arange(toks.shape[1]),
            )
            nxt = jnp.argmax(logits[:, -1, :], axis=-1)
            toks = jnp.concatenate([toks, nxt[:, None]], axis=1)
        got = generate(transformer_lm(**GQA_CFG, decode=True),
                       gqa_params, prompt, 5)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(toks))

    def test_cache_and_projections_shrink_to_kv_heads(self, gqa_params):
        model = transformer_lm(**GQA_CFG, decode=True)
        prompt = jnp.asarray([[5, 17, 42]], jnp.int32)
        variables = model.init(
            jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
        )
        cache = variables["cache"]["blocks"]["block"]["attn"]
        # [layers, batch, max_len, KV heads, head_dim]
        assert cache["cached_key"].shape[3] == 2
        assert cache["cached_value"].shape[3] == 2
        k_kernel = gqa_params["blocks"]["block"]["attn"]["k"]["kernel"]
        q_kernel = gqa_params["blocks"]["block"]["attn"]["q"]["kernel"]
        assert k_kernel.shape[-2] == 2 and q_kernel.shape[-2] == 4

    def test_kv_heads_must_divide_heads(self):
        bad = dict(GQA_CFG, num_kv_heads=3)
        with pytest.raises(ValueError, match="not divisible"):
            transformer_lm(**bad).init(
                jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
            )


class TestChunkedPrefill:
    """prefill_chunked == prefill, bit-for-bit, across chunk shapes
    (the long-prompt memory bound must be a pure refactor of the
    math)."""

    @pytest.mark.parametrize("chunk", [1, 2, 3, 4, 7, 16])
    def test_matches_single_shot(self, params, chunk):
        from container_engine_accelerators_tpu.models.generate import (
            prefill,
            prefill_chunked,
        )

        model = transformer_lm(**CFG, decode=True)
        prompt = jnp.asarray(
            [[5, 17, 42, 7, 9, 1, 3], [8, 8, 2, 6, 4, 88, 11]],
            jnp.int32)
        c1, l1 = prefill(model, params, prompt, 7, 16)
        c2, l2 = prefill_chunked(model, params, prompt, 7, 16, chunk)
        assert jnp.allclose(l1, l2, atol=0, rtol=0)
        for a, b in zip(jax.tree_util.tree_leaves(c1),
                        jax.tree_util.tree_leaves(c2)):
            assert (a == b).all()

    def test_generate_with_chunked_prefill_is_exact(self, params):
        model = transformer_lm(**CFG, decode=True)
        prompt = jnp.asarray([[5, 17, 42, 7, 9, 1]], jnp.int32)
        want = generate(model, params, prompt, 6)
        got = generate(model, params, prompt, 6, prefill_chunk=4)
        assert (want == got).all()

    def test_bucket_padded_traced_prompt_len(self, params):
        """prompt_len traced and NOT at a chunk boundary: the last-row
        selection must pick the containing chunk."""
        model = transformer_lm(**CFG, decode=True)
        exact = jnp.asarray([[5, 17, 42, 7, 9]], jnp.int32)
        padded = jnp.concatenate(
            [exact, jnp.zeros((1, 3), jnp.int32)], axis=1)
        want = generate(model, params, exact, 5)
        fn = jax.jit(lambda p, n: generate(model, params, p, 5,
                                           prompt_len=n,
                                           prefill_chunk=3))
        got = fn(padded, 5)
        assert (got[:, :10] == want[:, :10]).all()

    def test_rejects_bad_chunk(self, params):
        from container_engine_accelerators_tpu.models.generate import (
            prefill_chunked,
        )

        model = transformer_lm(**CFG, decode=True)
        with pytest.raises(ValueError, match="chunk"):
            prefill_chunked(model, params,
                            jnp.zeros((1, 4), jnp.int32), 4, 8, 0)
