"""partition_tpu one-shot tool tests (table-driven over fixture trees,
mirroring partition_gpu_test.go:19-63 + the §4 fake-FS strategy)."""

import importlib.util
import json
import os

import pytest

from container_engine_accelerators_tpu.tpulib.sysfs import write_fixture

_spec = importlib.util.spec_from_file_location(
    "partition_tpu",
    os.path.join(os.path.dirname(__file__), "..", "cmd", "partition_tpu.py"),
)
partition_tpu = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(partition_tpu)


def write_config(path, partition_size):
    with open(path, "w") as f:
        json.dump({"tpuPartitionSize": partition_size}, f)


def run(tmp_path, *extra, config=True, partition_size="1x1", chips=4):
    root = str(tmp_path / "root")
    cfg = str(tmp_path / "tpu_config.json")
    if chips:
        write_fixture(root, chips, topology="2x2x1")
    else:
        os.makedirs(os.path.join(root, "sys/class/accel"), exist_ok=True)
    if config:
        write_config(cfg, partition_size)
    rc = partition_tpu.main(
        ["--tpu-config", cfg, "--sysfs-root", root, *extra]
    )
    return rc, root


def read_state(root):
    path = partition_tpu.default_state_file(root)
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return json.load(f)


def test_no_config_file_is_noop(tmp_path):
    rc, root = run(tmp_path, config=False)
    assert rc == 0
    assert read_state(root) is None


def test_empty_partition_size_is_noop(tmp_path):
    rc, root = run(tmp_path, partition_size="")
    assert rc == 0
    assert read_state(root) is None


def test_invalid_config_takes_no_action(tmp_path):
    # Mirrors partition_gpu.go:88-92: unparseable config => exit 0, no action.
    rc, root = run(tmp_path, partition_size="3x9")
    assert rc == 0
    assert read_state(root) is None


def test_partitions_1x1_makes_four_single_chip_slices(tmp_path):
    rc, root = run(tmp_path, partition_size="1x1")
    assert rc == 0
    state = read_state(root)
    assert state["partitionSize"] == "1x1"
    assert state["hostTopology"] == "2x2x1"
    assert [p["id"] for p in state["partitions"]] == [
        "slice0", "slice1", "slice2", "slice3"]
    assert all(len(p["chips"]) == 1 for p in state["partitions"])


def test_partitions_2x1_makes_two_slices(tmp_path):
    rc, root = run(tmp_path, partition_size="2x1")
    assert rc == 0
    state = read_state(root)
    assert len(state["partitions"]) == 2
    assert state["partitions"][0]["chips"] == ["accel0", "accel1"]
    assert state["partitions"][1]["chips"] == ["accel2", "accel3"]


def test_untileable_size_fails(tmp_path):
    # 2x2x2 is a valid config value but cannot tile a 2x2x1 host.
    rc, root = run(tmp_path, partition_size="2x2x2")
    assert rc == 1
    assert read_state(root) is None


def test_no_chips_fails(tmp_path):
    rc, _ = run(tmp_path, chips=0)
    assert rc == 1


def test_idempotent_rerun_and_relayout(tmp_path):
    rc, root = run(tmp_path, partition_size="1x1")
    assert rc == 0
    cfg = str(tmp_path / "tpu_config.json")
    # Re-run with same layout: verify-only, still 0.
    assert partition_tpu.main(["--tpu-config", cfg, "--sysfs-root", root]) == 0
    # New layout replaces the old state.
    write_config(cfg, "2x2")
    assert partition_tpu.main(["--tpu-config", cfg, "--sysfs-root", root]) == 0
    state = read_state(root)
    assert state["partitionSize"] == "2x2"
    assert len(state["partitions"]) == 1


def set_boot_id(root, value):
    d = os.path.join(root, "proc/sys/kernel/random")
    os.makedirs(d, exist_ok=True)
    with open(os.path.join(d, "boot_id"), "w") as f:
        f.write(value + "\n")


def test_reboot_to_apply_pending_then_commit(tmp_path, monkeypatch):
    rc, root = run(tmp_path, partition_size="1x1")
    assert rc == 0
    set_boot_id(root, "boot-1")
    rebooted = []
    monkeypatch.setattr(partition_tpu, "reboot_node",
                        lambda: rebooted.append(True) or True)
    cfg = str(tmp_path / "tpu_config.json")
    write_config(cfg, "2x2")
    args = ["--tpu-config", cfg, "--sysfs-root", root, "--reboot-to-apply"]

    # Layout change with a live layout: record PENDING, request reboot,
    # exit 1 (cannot proceed until restart, partition_gpu.go:126-131).
    assert partition_tpu.main(args) == 1
    assert rebooted == [True]
    state = read_state(root)
    assert state["pendingReboot"] is True
    assert state["bootId"] == "boot-1"

    # Re-run with the SAME boot id (reboot never happened / kubelet
    # restarted the init container): retry the reboot, stay pending.
    assert partition_tpu.main(args) == 1
    assert rebooted == [True, True]
    assert read_state(root)["pendingReboot"] is True

    # Re-run after a real reboot (boot id changed): commit and verify.
    set_boot_id(root, "boot-2")
    assert partition_tpu.main(args) == 0
    state = read_state(root)
    assert "pendingReboot" not in state
    assert state["partitionSize"] == "2x2"
    assert rebooted == [True, True]  # no further reboot
