"""NRI device injector tests.

Pure-logic tests for annotation parsing / device stat (mirroring
nri_device_injector_test.go:25-190 but root-free: FIFOs exercise the
real-lstat path, char/block devices use the lstat seam), plus a
protocol-level test: a fake containerd runtime speaks real mux+ttrpc
frames to the plugin over a socketpair.
"""

import os
import socket
import stat
import threading

import pytest

from container_engine_accelerators_tpu.metrics import counters
from container_engine_accelerators_tpu.nri import injector
from container_engine_accelerators_tpu.nri import mux as nri_mux
from container_engine_accelerators_tpu.nri import nri_v1alpha1_pb2 as pb
from container_engine_accelerators_tpu.nri.plugin import (
    PLUGIN_SERVICE,
    RUNTIME_SERVICE,
    DeviceInjectorPlugin,
    event_mask,
)
from container_engine_accelerators_tpu.utils.retry import RetryPolicy
from container_engine_accelerators_tpu.nri.ttrpc import (
    TtrpcClient,
    TtrpcError,
    TtrpcServer,
)


# ---- annotation parsing ----------------------------------------------------


def ann(ctr, value):
    return {injector.CTR_DEVICE_KEY_PREFIX + ctr: value}


def test_get_devices_parses_yaml_list():
    devices = injector.get_devices("tpu", ann("tpu", """
- path: /dev/accel0
- path: /dev/accel1
  file_mode: 0o660
"""))
    assert [d["path"] for d in devices] == ["/dev/accel0", "/dev/accel1"]


def test_get_devices_json_is_valid_yaml():
    devices = injector.get_devices(
        "c", ann("c", '[{"path": "/dev/vfio/0"}]'))
    assert devices == [{"path": "/dev/vfio/0"}]


def test_get_devices_dedupes_by_path_keeping_first():
    devices = injector.get_devices("c", ann("c", """
- path: /dev/accel0
  uid: 1
- path: /dev/accel0
  uid: 2
"""))
    assert len(devices) == 1
    assert devices[0]["uid"] == 1


def test_get_devices_ignores_other_containers_and_absent():
    assert injector.get_devices("other", ann("c", "- path: /dev/x")) == []
    assert injector.get_devices("c", {}) == []
    assert injector.get_devices("c", None) == []


@pytest.mark.parametrize("bad", ["{not yaml: [", "just-a-string",
                                 "- type: c\n  major: 1"])
def test_get_devices_invalid_annotation_raises(bad):
    with pytest.raises(ValueError):
        injector.get_devices("c", ann("c", bad))


def test_get_devices_rejects_yaml_aliases():
    # Alias expansion (billion-laughs) must be refused outright: pod
    # annotations are untrusted input to a node-critical daemon.
    bomb = "a: &a [x,x,x,x,x]\nb: &b [*a,*a,*a,*a]\nc: [*b,*b,*b,*b]\n"
    with pytest.raises(ValueError):
        injector.get_devices("c", ann("c", bomb))


# ---- device stat -----------------------------------------------------------


def test_to_linux_device_fifo_real_lstat(tmp_path):
    path = str(tmp_path / "fifo")
    os.mkfifo(path)
    device = injector.to_linux_device({"path": path})
    assert device.type == "p"
    assert device.path == path


def test_to_linux_device_char_via_seam():
    class St:
        st_mode = stat.S_IFCHR | 0o600
        st_rdev = os.makedev(245, 3)
    device = injector.to_linux_device(
        {"path": "/dev/accel0", "file_mode": 0o660, "uid": 7, "gid": 8},
        lstat=lambda p: St(),
    )
    assert (device.type, device.major, device.minor) == ("c", 245, 3)
    assert device.file_mode.value == 0o660
    assert device.uid.value == 7
    assert device.gid.value == 8


def test_to_linux_device_missing_path_raises():
    with pytest.raises(ValueError):
        injector.to_linux_device({"path": "/nonexistent/device"})


def test_to_linux_device_regular_file_rejected(tmp_path):
    path = str(tmp_path / "plain")
    open(path, "w").close()
    with pytest.raises(ValueError, match="invalid device type"):
        injector.to_linux_device({"path": path})


# ---- protocol-level: fake containerd runtime -------------------------------


class FakeRuntime:
    """The containerd side of the NRI socket: mux trunk + ttrpc both ways."""

    def __init__(self, sock):
        self.mux = nri_mux.Mux(sock)
        self.registered = threading.Event()
        self.register_req = None
        server = TtrpcServer(self.mux.open(nri_mux.RUNTIME_SERVICE_CONN))
        server.register(RUNTIME_SERVICE, "RegisterPlugin", self._register)
        self.client = TtrpcClient(self.mux.open(nri_mux.PLUGIN_SERVICE_CONN))
        self.mux.start_reader()
        threading.Thread(target=server.serve, daemon=True).start()

    def _register(self, payload):
        self.register_req = pb.RegisterPluginRequest.FromString(payload)
        self.registered.set()
        return pb.Empty().SerializeToString()

    def configure(self):
        raw = self.client.call(
            PLUGIN_SERVICE, "Configure",
            pb.ConfigureRequest(runtime_name="containerd",
                                runtime_version="2.0").SerializeToString())
        return pb.ConfigureResponse.FromString(raw)

    def create_container(self, pod_annotations, ctr_name):
        req = pb.CreateContainerRequest(
            pod=pb.PodSandbox(name="pod", namespace="ns",
                              annotations=pod_annotations),
            container=pb.Container(name=ctr_name),
        )
        raw = self.client.call(PLUGIN_SERVICE, "CreateContainer",
                               req.SerializeToString())
        return pb.CreateContainerResponse.FromString(raw)


@pytest.fixture
def rig(tmp_path):
    runtime_sock, plugin_sock = socket.socketpair()
    plugin = DeviceInjectorPlugin()
    t = threading.Thread(target=plugin.run_on_socket, args=(plugin_sock,),
                         daemon=True)
    t.start()
    runtime = FakeRuntime(runtime_sock)
    yield runtime
    runtime_sock.close()
    plugin_sock.close()


def test_plugin_registers_and_subscribes_create_container(rig):
    assert rig.registered.wait(5)
    assert rig.register_req.plugin_name == "device_injector_nri"
    assert rig.register_req.plugin_idx == "10"
    resp = rig.configure()
    assert resp.events == event_mask(pb.CREATE_CONTAINER)


def test_create_container_injects_annotated_devices(rig, tmp_path):
    assert rig.registered.wait(5)
    fifo = str(tmp_path / "accel-fifo")
    os.mkfifo(fifo)
    resp = rig.create_container(ann("tpu-ctr", f"- path: {fifo}"), "tpu-ctr")
    assert len(resp.adjust.linux.devices) == 1
    device = resp.adjust.linux.devices[0]
    assert device.path == fifo
    assert device.type == "p"


def test_create_container_without_annotation_is_empty_adjustment(rig):
    assert rig.registered.wait(5)
    resp = rig.create_container({}, "plain-ctr")
    assert len(resp.adjust.linux.devices) == 0


def test_create_container_bad_annotation_errors(rig):
    assert rig.registered.wait(5)
    with pytest.raises(TtrpcError):
        rig.create_container(ann("c", "- major: 1"), "c")


def test_file_mode_string_forms(tmp_path):
    # PyYAML leaves '0o660' as a string; YAML 1.1 '0660' parses as octal
    # int; both must reach the wire as 0o660 = 432.
    fifo = str(tmp_path / "f")
    os.mkfifo(fifo)
    for raw in [f"- path: {fifo}\n  file_mode: 0o660",
                f"- path: {fifo}\n  file_mode: 0660",
                f"- path: {fifo}\n  file_mode: 432"]:
        devices = injector.get_devices("c", ann("c", raw))
        d = injector.to_linux_device(devices[0])
        assert d.file_mode.value == 0o660, raw


def test_shutdown_terminates_plugin(tmp_path):
    runtime_sock, plugin_sock = socket.socketpair()
    plugin = DeviceInjectorPlugin()
    t = threading.Thread(target=plugin.run_on_socket, args=(plugin_sock,),
                         daemon=True)
    t.start()
    runtime = FakeRuntime(runtime_sock)
    assert runtime.registered.wait(5)
    runtime.client.call(PLUGIN_SERVICE, "Shutdown",
                        pb.Empty().SerializeToString())
    t.join(timeout=5)
    assert not t.is_alive()
    runtime_sock.close()
    plugin_sock.close()


# ---- reconnect resilience (ROADMAP "NRI injector resilience") --------------


FAST_RECONNECT = RetryPolicy(max_attempts=6, initial_backoff_s=0.01,
                             max_backoff_s=0.05, deadline_s=10.0)


def test_plugin_reconnects_after_trunk_loss(tmp_path):
    """containerd restarts are routine: the trunk dies, the plugin must
    re-dial with backoff and RE-REGISTER on the fresh connection —
    counted as `nri.reconnect` — instead of exiting with the runtime."""
    sock_path = str(tmp_path / "nri.sock")
    listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    listener.bind(sock_path)
    listener.listen(2)
    plugin = DeviceInjectorPlugin(socket_path=sock_path)
    before = counters.get("nri.reconnect")
    t = threading.Thread(target=plugin.run,
                         kwargs={"retry": FAST_RECONNECT}, daemon=True)
    t.start()
    try:
        conn1, _ = listener.accept()
        rt1 = FakeRuntime(conn1)
        assert rt1.registered.wait(5)
        # The "containerd restart": the trunk dies mid-life.  Shutdown
        # before close so the FIN reaches the plugin's blocked reader
        # (close() alone never wakes a thread already inside recv()).
        conn1.shutdown(socket.SHUT_RDWR)
        conn1.close()

        conn2, _ = listener.accept()  # the plugin re-dialed
        rt2 = FakeRuntime(conn2)
        assert rt2.registered.wait(5), "no re-registration on reconnect"
        assert counters.get("nri.reconnect") == before + 1
        # The reconnected session is fully functional, not a zombie.
        assert rt2.configure().events == event_mask(pb.CREATE_CONTAINER)

        rt2.client.call(PLUGIN_SERVICE, "Shutdown",
                        pb.Empty().SerializeToString())
        t.join(timeout=5)
        assert not t.is_alive()
        conn2.close()
    finally:
        listener.close()


def test_runtime_dropping_sessions_is_bounded_not_a_spin(tmp_path):
    """A half-up runtime that ACCEPTS and instantly drops the trunk
    must cost backoff and a bounded budget, not a zero-sleep reconnect
    spin (the dial succeeds, so the dial budget alone never fires)."""
    sock_path = str(tmp_path / "crashloop.sock")
    listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    listener.bind(sock_path)
    listener.listen(8)
    stop = threading.Event()

    def dropper():
        while not stop.is_set():
            try:
                conn, _ = listener.accept()
            except OSError:
                return
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            conn.close()

    threading.Thread(target=dropper, daemon=True).start()
    plugin = DeviceInjectorPlugin(socket_path=sock_path)
    tiny = RetryPolicy(max_attempts=3, initial_backoff_s=0.01,
                       max_backoff_s=0.02)
    before = counters.get("nri.reconnect.failed")
    try:
        with pytest.raises(OSError, match="keeps dropping"):
            plugin.run(retry=tiny)
        assert counters.get("nri.reconnect.failed") == before + 1
    finally:
        stop.set()
        listener.close()


def test_reconnect_budget_exhaustion_is_loud(tmp_path):
    """A runtime that never comes back must cost the plugin its budget
    and then a clear error (`nri.reconnect.failed`) — bounded backoff,
    not an unbounded spin and not a silent exit."""
    plugin = DeviceInjectorPlugin(
        socket_path=str(tmp_path / "never-there.sock"))
    tiny = RetryPolicy(max_attempts=2, initial_backoff_s=0.01,
                       max_backoff_s=0.02)
    before = counters.get("nri.reconnect.failed")
    with pytest.raises(OSError):
        plugin.run(retry=tiny)
    assert counters.get("nri.reconnect.failed") == before + 1


def test_mux_rejects_oversized_frame():
    import struct
    a, b = socket.socketpair()
    mux = nri_mux.Mux(b)
    conn = mux.open(1)
    mux.start_reader()
    a.sendall(struct.pack(">II", 1, 0xFFFFFFFF))  # corrupt length
    with pytest.raises(EOFError):
        conn.read_exact(1)
    a.close()
    b.close()


def test_mux_large_frame_survives_the_trunk():
    """Multi-MiB trunk frames arrive complete and uncorrupted: the mux
    write path rides netio.sendall (capped per-syscall, short-write
    proof) — this rig's loopback stack truncates very large
    single-syscall sends, and one short write on the trunk would
    desynchronize every frame after it (the PR 6 lesson, now pinned
    here and enforced repo-wide by the raw-socket-send lint rule)."""
    a, b = socket.socketpair()
    tx, rx = nri_mux.Mux(a), nri_mux.Mux(b)
    conn_tx = tx.open(1)
    conn_rx = rx.open(1)
    rx.start_reader()
    payload = bytes(range(256)) * (4 << 12)  # 4 MiB, patterned
    trailer = b"after-the-big-one"
    writer = threading.Thread(
        target=lambda: (conn_tx.write(payload), conn_tx.write(trailer)),
        daemon=True)
    writer.start()
    got = conn_rx.read_exact(len(payload))
    assert got == payload  # complete AND byte-exact
    # Framing stayed synchronized: the next frame reads clean too.
    assert conn_rx.read_exact(len(trailer)) == trailer
    writer.join(timeout=30)
    assert not writer.is_alive()
    for s in (a, b):
        s.close()
