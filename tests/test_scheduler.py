"""Topology scheduler tests: pure logic + fake-API end-to-end.

Mirrors the reference's hardware-free strategy (SURVEY.md §4): the K8s
surface is a seam (CoreV1 over an injectable transport), so the whole
gate→assign→bind flow runs against in-memory cluster state.
"""

import pytest

from container_engine_accelerators_tpu.scheduler import daemon as sched
from container_engine_accelerators_tpu.scheduler import labeler, topology
from container_engine_accelerators_tpu.scheduler.k8s import CoreV1
from container_engine_accelerators_tpu.scheduler.quantity import parse_quantity


# ---- fixtures --------------------------------------------------------------


def make_node(name, tpu=4, cpu="8", mem="16Gi", pg="pg0", cluster="c0",
              rack="r0", host=None, slice_id=None, coords=None,
              tpu_topology=None, taints=None, extra_labels=None):
    labels = {
        topology.PLACEMENT_GROUP_LABEL: pg,
        topology.CLUSTER_LABEL: cluster,
        topology.RACK_LABEL: rack,
        topology.HOST_LABEL: host or name,
    }
    if slice_id:
        labels[topology.SLICE_LABEL] = slice_id
    if coords:
        labels[topology.COORDS_LABEL] = coords
    if tpu_topology:
        labels[topology.TPU_TOPOLOGY_LABEL] = tpu_topology
    labels.update(extra_labels or {})
    return {
        "metadata": {"name": name, "labels": labels},
        "spec": {"taints": taints or []},
        "status": {"allocatable": {
            "cpu": cpu, "memory": mem, sched.TPU_RESOURCE: str(tpu)
        }},
    }


def make_pod(name, job="job-a", index=None, gate="gke.io/topology-aware-auto-job-a",
             tpu=4, cpu="1", mem="1Gi", namespace="default", node_name=None,
             created="2026-01-01T00:00:00Z", tolerations=None):
    spec = {
        "containers": [{
            "name": "main",
            "resources": {"requests": {
                "cpu": cpu, "memory": mem, sched.TPU_RESOURCE: str(tpu)
            }},
        }],
    }
    if gate:
        spec["schedulingGates"] = [{"name": gate}]
    if node_name:
        spec["nodeName"] = node_name
    if tolerations:
        spec["tolerations"] = tolerations
    labels = {sched.JOB_NAME_LABEL: job}
    if index is not None:
        labels[sched.COMPLETION_INDEX_LABEL] = str(index)
    return {
        "metadata": {
            "name": name, "namespace": namespace, "labels": labels,
            "creationTimestamp": created,
        },
        "spec": spec,
        "status": {},
    }


class FakeCoreV1(CoreV1):
    """In-memory cluster honouring the CoreV1 surface."""

    def __init__(self, nodes, pods, namespaces=("default",)):
        super().__init__(transport=None)
        self.nodes = nodes
        self.pods = {(p["metadata"]["namespace"], p["metadata"]["name"]): p
                     for p in pods}
        self.namespaces = list(namespaces)
        self.replaced = []

    def list_namespaces(self):
        return [{"metadata": {"name": n}} for n in self.namespaces]

    def list_namespaced_pods(self, namespace):
        return [p for (ns, _), p in self.pods.items() if ns == namespace]

    def list_nodes(self):
        return self.nodes

    def read_namespaced_pod(self, name, namespace):
        return self.pods[(namespace, name)]

    def replace_namespaced_pod(self, name, namespace, pod):
        self.pods[(namespace, name)] = pod
        self.replaced.append((namespace, name))
        return pod

    def patch_node_labels(self, name, labels):
        for node in self.nodes:
            if node["metadata"]["name"] == name:
                node["metadata"].setdefault("labels", {}).update(labels)
                return node
        raise KeyError(name)


# ---- quantity --------------------------------------------------------------


@pytest.mark.parametrize("raw,expected", [
    ("100m", 0.1), ("2", 2.0), ("1Gi", 2**30), ("1G", 1e9),
    ("512Ki", 512 * 1024), (4, 4.0), (None, 0.0), ("", 0.0), ("1.5", 1.5),
    ("100n", 1e-7), ("250u", 2.5e-4),
])
def test_parse_quantity(raw, expected):
    assert parse_quantity(raw) == pytest.approx(expected)


def test_parse_quantity_malformed_counts_as_zero():
    # One garbage pod spec must not crash the scheduling daemon.
    assert parse_quantity("not-a-number") == 0.0
    assert parse_quantity("12QQ") == 0.0


def test_transport_network_error_becomes_api_exception():
    from container_engine_accelerators_tpu.scheduler.k8s import (
        ApiException, in_cluster_transport,
    )
    t = in_cluster_transport(host="http://127.0.0.1:1",  # nothing listens
                             token_path="/nonexistent", ca_path="/nonexistent")
    with pytest.raises(ApiException):
        t("GET", "/api/v1/nodes")


# ---- topology distance -----------------------------------------------------


def test_ici_distance_within_slice_beats_dcn():
    a = {"node_labels": make_node("a", slice_id="s0", coords="0,0,0",
                                  tpu_topology="4x4x4")["metadata"]["labels"]}
    b = {"node_labels": make_node("b", slice_id="s0", coords="2,0,0",
                                  tpu_topology="4x4x4")["metadata"]["labels"]}
    c = {"node_labels": make_node("c", rack="r1", slice_id="s1",
                                  coords="0,0,0")["metadata"]["labels"]}
    ici = topology.node_topology_distance(a, b)
    dcn = topology.node_topology_distance(a, c)
    assert ici == 2.0
    # pg+cluster match, rack differs — plus the cross-slice floor.
    assert dcn == topology.DCN_MIN + (
        topology.DCN_FAR / topology.DCN_LEVEL_FACTOR ** 2
    )
    assert ici < dcn


def test_ici_distance_uses_torus_wraparound():
    # 0 -> 3 on a ring of 4 is 1 hop backwards, not 3 forwards.
    assert topology.ici_hop_distance((0, 0, 0), (3, 0, 0), (4, 4, 4)) == 1.0
    assert topology.ici_hop_distance((0, 0, 0), (3, 0, 0), None) == 3.0


def test_same_host_distance_floor_and_missing_labels_far():
    # Nodes without slice/coords can only talk over DCN, so even
    # co-located ones carry the cross-slice floor (never cheaper than
    # any in-slice ICI path).
    a = {"node_labels": make_node("a")["metadata"]["labels"]}
    b = {"node_labels": make_node("b", host="a")["metadata"]["labels"]}
    assert topology.node_topology_distance(a, b) == topology.DCN_MIN
    assert topology.node_topology_distance(a, {"node_labels": {}}) == (
        topology.DCN_MIN + topology.DCN_FAR
    )


def test_topology_key_orders_slice_neighbors_adjacent():
    nodes = [
        make_node("n2", slice_id="s0", coords="2,0,0", tpu_topology="8x2x1"),
        make_node("n0", slice_id="s0", coords="0,0,0", tpu_topology="8x2x1"),
        make_node("n1", slice_id="s0", coords="1,0,0", tpu_topology="8x2x1"),
    ]
    infos = [{"name": n["metadata"]["name"],
              "node_labels": n["metadata"]["labels"]} for n in nodes]
    # Same DCN host label would collapse ordering; distinct hosts here, so
    # override host to a constant to isolate the coords tiebreak.
    for info in infos:
        info["node_labels"][topology.HOST_LABEL] = "h"
    infos.sort(key=topology.node_topology_key)
    assert [i["name"] for i in infos] == ["n0", "n1", "n2"]


# ---- labeler ---------------------------------------------------------------


def test_worker_coords_row_major_tiling():
    # 4x4x4 slice, 2x2x1 per host -> host grid 2x2x4.
    assert labeler.worker_coords(0, (4, 4, 4)) == (0, 0, 0)
    assert labeler.worker_coords(1, (4, 4, 4)) == (0, 0, 1)
    assert labeler.worker_coords(4, (4, 4, 4)) == (0, 2, 0)
    assert labeler.worker_coords(15, (4, 4, 4)) == (2, 2, 3)


def test_parse_tpu_env():
    env = labeler.parse_tpu_env(
        "ACCELERATOR_TYPE: 'v5p-32'\nTOPOLOGY: '4x4x1'\nWORKER_ID: '3'\n"
        "TPU_NAME: 'slice-a'\n"
    )
    assert env["ACCELERATOR_TYPE"] == "v5p-32"
    assert env["WORKER_ID"] == "3"


def test_update_node_labels_patches_dcn_and_ici_labels():
    meta = {
        "/instance/name": "node-1",
        "/instance/attributes/physical_host": "/cc/rr/hh",
        "/instance/attributes/tpu-env":
            "TPU_NAME: 'slice-a'\nTOPOLOGY: '4x4x1'\nWORKER_ID: '1'\n",
    }
    api = FakeCoreV1([make_node("node-1")], [])
    labels = labeler.update_node_labels(api, meta.get)
    assert labels[topology.CLUSTER_LABEL] == "cc"
    assert labels[topology.RACK_LABEL] == "rr"
    assert labels[topology.HOST_LABEL] == "hh"
    assert labels[topology.SLICE_LABEL] == "slice-a"
    # host grid (2,2,1); worker 1 -> grid idx (0,1,0) -> chip origin (0,2,0)
    assert labels[topology.COORDS_LABEL] == "0,2,0"
    node_labels = api.nodes[0]["metadata"]["labels"]
    assert node_labels[topology.CLUSTER_LABEL] == "cc"


def test_update_node_labels_missing_metadata():
    api = FakeCoreV1([make_node("node-1")], [])
    assert labeler.update_node_labels(api, {}.get) is None


def test_malformed_topology_metadata_skips_ici_labels():
    meta = {
        "/instance/name": "node-1",
        "/instance/attributes/physical_host": "/cc/rr/hh",
        "/instance/attributes/tpu-env":
            "TPU_NAME: 's'\nTOPOLOGY: 'garbage'\nWORKER_ID: '1'\n",
    }
    api = FakeCoreV1([make_node("node-1")], [])
    labels = labeler.update_node_labels(api, meta.get)
    assert labels[topology.CLUSTER_LABEL] == "cc"  # DCN labels still stamped
    assert topology.TPU_TOPOLOGY_LABEL not in labels
    assert topology.COORDS_LABEL not in labels


# ---- daemon: discovery -----------------------------------------------------


def test_find_pod_gates_and_schedulable_pods():
    pods = [
        make_pod("a-0", index=0),
        make_pod("a-1", index=1),
        make_pod("other", gate="some-other-gate"),
        make_pod("ungated", gate=None),
    ]
    gates = sched.find_pod_gates(pods, sched.DEFAULT_GATE_PREFIX)
    assert gates == {"gke.io/topology-aware-auto-job-a"}
    recs = sched.find_schedulable_pods(pods, "gke.io/topology-aware-auto-job-a")
    assert set(recs) == {"a-0", "a-1"}
    assert recs["a-0"]["tpu"] == 4
    assert recs["a-0"]["cpu"] == 1.0


def test_find_schedulable_nodes_filters_and_subtracts():
    nodes = [
        make_node("good", tpu=4),
        make_node("busy", tpu=4),
        make_node("tainted", taints=[{"key": "k", "value": "v",
                                      "effect": "NoSchedule"}]),
        {"metadata": {"name": "unlabeled", "labels": {}},
         "spec": {}, "status": {"allocatable": {"cpu": "8", "memory": "1Gi"}}},
    ]
    running = make_pod("r", gate=None, node_name="busy", tpu=4)
    running["status"] = {"containerStatuses": [{"state": {"running": {}}}]}
    out = sched.find_schedulable_nodes(nodes, [running], tolerations=[])
    assert set(out) == {"good", "busy"}
    assert out["good"]["tpu"] == 4
    assert out["busy"]["tpu"] == 0


def test_tainted_node_allowed_with_toleration():
    taint = [{"key": "google.com/tpu", "value": "present", "effect": "NoSchedule"}]
    nodes = [make_node("t", taints=taint)]
    tol = [{"key": "google.com/tpu", "operator": "Exists"}]
    assert "t" in sched.find_schedulable_nodes(nodes, [], tol)
    tol_wrong = [{"key": "google.com/tpu", "operator": "Equal", "value": "absent"}]
    assert sched.find_schedulable_nodes(nodes, [], tol_wrong) == {}


def test_prefer_no_schedule_taint_does_not_block():
    """PreferNoSchedule is a soft preference: the real kube-scheduler
    still places pods there, so it must not disqualify a candidate
    (VERDICT r03 weak-5 — the reference blocks on it, wrongly)."""
    nodes = [make_node("soft", taints=[
        {"key": "k", "value": "v", "effect": "PreferNoSchedule"}])]
    assert "soft" in sched.find_schedulable_nodes(nodes, [], tolerations=[])


def test_no_execute_taint_blocks_and_effect_scoped_toleration():
    taint = [{"key": "k", "value": "v", "effect": "NoExecute"}]
    nodes = [make_node("n", taints=taint)]
    assert sched.find_schedulable_nodes(nodes, [], []) == {}
    # Toleration scoped to a different effect does NOT tolerate it.
    wrong_eff = [{"key": "k", "operator": "Exists", "effect": "NoSchedule"}]
    assert sched.find_schedulable_nodes(nodes, [], wrong_eff) == {}
    # Effect-less toleration matches all effects.
    any_eff = [{"key": "k", "operator": "Exists"}]
    assert "n" in sched.find_schedulable_nodes(nodes, [], any_eff)


def test_exists_toleration_ignores_value():
    """operator: Exists with a (technically invalid) value set must
    still match on key alone — the value is ignored, not compared."""
    taint = [{"key": "k", "value": "actual", "effect": "NoSchedule"}]
    nodes = [make_node("n", taints=taint)]
    tol = [{"key": "k", "operator": "Exists", "value": "different"}]
    assert "n" in sched.find_schedulable_nodes(nodes, [], tol)


def test_empty_key_exists_toleration_tolerates_everything():
    taints = [{"key": "a", "value": "1", "effect": "NoSchedule"},
              {"key": "b", "value": "2", "effect": "NoExecute"}]
    nodes = [make_node("n", taints=taints)]
    tol = [{"operator": "Exists"}]
    assert "n" in sched.find_schedulable_nodes(nodes, [], tol)
    # But an empty key with Equal matches nothing.
    assert sched.find_schedulable_nodes(nodes, [], [{"operator": "Equal"}]) == {}


def test_default_operator_is_equal():
    taint = [{"key": "k", "value": "v", "effect": "NoSchedule"}]
    nodes = [make_node("n", taints=taint)]
    assert "n" in sched.find_schedulable_nodes(
        nodes, [], [{"key": "k", "value": "v"}])
    assert sched.find_schedulable_nodes(
        nodes, [], [{"key": "k", "value": "w"}]) == {}


def test_assignment_search_budget_returns_valid_placement():
    """A 200-node pool with a 64-pod job is exponential for the raw
    search (VERDICT r03 weak-6); the budget must return a feasible
    assignment quickly instead of hanging the daemon loop."""
    import time as _time

    nodes = [
        {"name": f"n{i:03d}", "cpu": 8.0, "memory": 2**34, "tpu": 4,
         "node_labels": make_node(
             f"n{i:03d}", rack=f"r{i // 16}",
         )["metadata"]["labels"]}
        for i in range(200)
    ]
    sorted_nodes = sorted(nodes, key=sched.node_topology_key)
    pods = [{"name": f"p{i}", "index": str(i), "cpu": 1.0,
             "memory": 2**20, "tpu": 4, "node_selector": None}
            for i in range(64)]
    sorted_pods = sorted(pods, key=sched.pod_sorting_key)
    t0 = _time.monotonic()
    assignment = sched.calculate_pods_assignment(
        sorted_nodes, sorted_pods, search_budget_s=0.5
    )
    elapsed = _time.monotonic() - t0
    assert elapsed < 5.0, f"search did not respect its budget ({elapsed:.1f}s)"
    assert len(assignment) == 64
    assert assignment == sorted(assignment)  # strictly increasing = valid
    assert all(0 <= a < 200 for a in assignment)


def test_assignment_search_exhaustive_when_budget_none():
    """Small instances with budget=None must still find the optimum
    (same behavior as before the guard)."""
    def ninfo(name, rack):
        return {"name": name, "cpu": 8.0, "memory": 2**34, "tpu": 4,
                "node_labels": make_node(name, rack=rack)
                ["metadata"]["labels"]}

    # Optimal pair is the two same-rack nodes, which the topology sort
    # places adjacent; first-feasible would grab a cross-rack pair only
    # if it came first, so shuffle racks to make optimality observable.
    nodes = [ninfo("a", "r0"), ninfo("b", "r1"), ninfo("c", "r1")]
    sorted_nodes = sorted(nodes, key=sched.node_topology_key)
    pods = [{"name": f"p{i}", "index": str(i), "cpu": 1.0,
             "memory": 2**20, "tpu": 4, "node_selector": None}
            for i in range(2)]
    assignment = sched.calculate_pods_assignment(
        sorted_nodes, pods, search_budget_s=None
    )
    chosen = {sorted_nodes[i]["node_labels"][topology.RACK_LABEL]
              for i in assignment}
    assert chosen == {"r1"}  # the same-rack pair


def test_pod_sorting_key_numeric_suffix():
    assert sched.pod_sorting_key({"name": "xxx-pod2", "index": None}) < \
        sched.pod_sorting_key({"name": "xxx-pod10", "index": None})
    assert sched.pod_sorting_key({"name": "p", "index": "7"}) == (0, "", 7)


def test_cross_slice_always_costs_more_than_any_ici_path():
    """The DCN floor: a cross-slice neighbor (even same rack/host) must
    never undercut an in-slice ICI path, or the packer prefers DCN
    traffic over ICI (caught live by the round-3 verify drive)."""
    def info(name, slice_id, coords):
        n = make_node(name, host="h0", slice_id=slice_id, coords=coords,
                      tpu_topology="16x16x16")
        return {"name": name, "node_labels": n["metadata"]["labels"]}

    far_ici = topology.node_topology_distance(
        info("a", "s0", "0,0,0"), info("b", "s0", "8,8,8")
    )  # worst-case torus path on the largest slice shape: 24 hops
    cross = topology.node_topology_distance(
        info("a", "s0", "0,0,0"), info("c", "s1", "0,0,0")
    )  # identical rack+host labels, different slice
    assert far_ici == 24.0
    assert cross > far_ici
    # Hierarchy ordering still discriminates above the floor.
    d_same = cross
    other_rack = info("d", "s1", "0,0,0")
    other_rack["node_labels"] = dict(other_rack["node_labels"])
    other_rack["node_labels"][topology.RACK_LABEL] = "r9"
    d_rack = topology.node_topology_distance(
        info("a", "s0", "0,0,0"), other_rack
    )
    assert d_rack > d_same


def test_pod_sorting_key_mixed_indexed_and_unindexed():
    """A job mixing indexed and unindexed pods must sort without a
    TypeError (the reference crashes here: int vs tuple keys,
    schedule-daemon.py:40-50) — indexed pods order first, by index."""
    pods = [
        {"name": "solo-pod3", "index": None},
        {"name": "idx", "index": "1"},
        {"name": "solo-pod1", "index": None},
        {"name": "idx2", "index": "0"},
    ]
    ordered = sorted(pods, key=sched.pod_sorting_key)
    assert [p["name"] for p in ordered] == [
        "idx2", "idx", "solo-pod1", "solo-pod3"
    ]


# ---- daemon: assignment ----------------------------------------------------


def _infos(nodes):
    return sorted(
        ({"name": n["metadata"]["name"], "cpu": 8.0, "memory": 2**34,
          "tpu": 4, "node_labels": n["metadata"]["labels"]} for n in nodes),
        key=topology.node_topology_key,
    )


def test_assignment_prefers_same_slice_ici_neighbors():
    nodes = _infos([
        make_node("s0-h0", host="h0", slice_id="s0", coords="0,0,0",
                  tpu_topology="4x2x1"),
        make_node("s0-h1", host="h1", slice_id="s0", coords="2,0,0",
                  tpu_topology="4x2x1"),
        make_node("far", rack="r9", host="h9", slice_id="s9", coords="0,0,0"),
    ])
    pods = [
        {"name": "p-0", "namespace": "default", "index": "0", "cpu": 1.0,
         "memory": 1.0, "tpu": 4, "node_selector": None},
        {"name": "p-1", "namespace": "default", "index": "1", "cpu": 1.0,
         "memory": 1.0, "tpu": 4, "node_selector": None},
    ]
    assignment = sched.calculate_pods_assignment(nodes, pods)
    chosen = {nodes[i]["name"] for i in assignment}
    assert chosen == {"s0-h0", "s0-h1"}


def test_assignment_respects_capacity_and_selector():
    nodes = _infos([make_node("a"), make_node("b")])
    nodes[0]["tpu"] = 0  # full
    pods = [{"name": "p", "namespace": "default", "index": "0", "cpu": 1.0,
             "memory": 1.0, "tpu": 4, "node_selector": None}]
    assignment = sched.calculate_pods_assignment(nodes, pods)
    assert [nodes[i]["name"] for i in assignment] == \
        [n["name"] for n in nodes if n["tpu"] == 4]

    pods[0]["node_selector"] = {"nonexistent": "label"}
    assert sched.calculate_pods_assignment(nodes, pods) == []


def test_assignment_infeasible_when_pods_exceed_nodes():
    nodes = _infos([make_node("only")])
    pods = [
        {"name": f"p-{i}", "namespace": "default", "index": str(i),
         "cpu": 1.0, "memory": 1.0, "tpu": 4, "node_selector": None}
        for i in range(2)
    ]
    assert sched.calculate_pods_assignment(nodes, pods) == []


# ---- daemon: end-to-end ----------------------------------------------------


def test_run_once_binds_job_to_slice():
    nodes = [
        make_node("s0-h0", host="h0", slice_id="s0", coords="0,0,0",
                  tpu_topology="4x2x1"),
        make_node("s0-h1", host="h1", slice_id="s0", coords="2,0,0",
                  tpu_topology="4x2x1"),
        make_node("lone", rack="r9", host="h9", slice_id="s9", coords="0,0,0"),
    ]
    pods = [make_pod("a-0", index=0), make_pod("a-1", index=1)]
    api = FakeCoreV1(nodes, pods)
    d = sched.SchedulerDaemon(api, settle_s=0, sleep=lambda *_: None)
    assert d.run_once() == 2

    bound_nodes = set()
    for (_, name) in api.replaced:
        pod = api.pods[("default", name)]
        assert pod["spec"]["schedulingGates"] == []
        terms = pod["spec"]["affinity"]["nodeAffinity"][
            "requiredDuringSchedulingIgnoredDuringExecution"]["nodeSelectorTerms"]
        bound_nodes.add(terms[0]["matchExpressions"][0]["values"][0])
    assert bound_nodes == {"s0-h0", "s0-h1"}


def test_run_once_no_gates_is_noop():
    api = FakeCoreV1([make_node("n")], [make_pod("p", gate=None)])
    d = sched.SchedulerDaemon(api, settle_s=0, sleep=lambda *_: None)
    assert d.run_once() == 0
    assert api.replaced == []


def test_jobs_scheduled_fifo_by_creation_time():
    nodes = [make_node("n0"), make_node("n1")]
    pods = [
        make_pod("new-0", job="new", gate="gke.io/topology-aware-auto-x",
                 created="2026-01-02T00:00:00Z", tpu=4),
        make_pod("old-0", job="old", gate="gke.io/topology-aware-auto-x",
                 created="2026-01-01T00:00:00Z", tpu=4),
    ]
    api = FakeCoreV1(nodes, pods)
    d = sched.SchedulerDaemon(api, settle_s=0, sleep=lambda *_: None)
    d.run_once()
    # Both fit (2 nodes); the older job must have been bound first.
    assert api.replaced[0][1] == "old-0"


# ---- link-health annotations (collectives/topo.py -> the packer) -----------


def _fleet_penalty(*faults, specs=None):
    """A scheduler penalty built the production way: a fleet topology,
    real link-table faults, a CommGraph snapshot."""
    from container_engine_accelerators_tpu.collectives.topo import CommGraph
    from container_engine_accelerators_tpu.fleet.links import LinkTable
    from container_engine_accelerators_tpu.fleet.topology import (
        FleetTopology,
        NodeSpec,
    )

    specs = specs or [NodeSpec(name="a", rack="r0"),
                      NodeSpec(name="b", rack="r0"),
                      NodeSpec(name="c", rack="r1")]
    fleet = FleetTopology(specs)
    links = LinkTable(fleet)
    for f in faults:
        assert links.apply(f), f"fault {f!r} armed nothing"
    graph = CommGraph.build(fleet, links=links, rates=lambda a, b: 0.0)
    return graph.scheduler_link_penalty()


def _two_pods():
    return [
        {"name": f"p-{i}", "namespace": "default", "index": str(i),
         "cpu": 1.0, "memory": 1.0, "tpu": 4, "node_selector": None}
        for i in range(2)
    ]


def test_assignment_avoids_node_behind_partitioned_link():
    """Healthy fleet: the packer picks the same-rack pair (a, b).
    With the a<->b fabric partitioned, the link-health annotation must
    steer it onto a cross-rack pair instead — placement reacting to
    the fault, not just the transfer plane."""
    nodes = _infos([make_node("a", rack="r0"),
                    make_node("b", rack="r0"),
                    make_node("c", rack="r1")])
    pods = _two_pods()
    baseline = sched.calculate_pods_assignment(nodes, pods,
                                               search_budget_s=None)
    assert {nodes[i]["name"] for i in baseline} == {"a", "b"}

    penalty = _fleet_penalty("node:a<->node:b:partition")
    steered = sched.calculate_pods_assignment(
        nodes, pods, search_budget_s=None, link_penalty=penalty)
    chosen = {nodes[i]["name"] for i in steered}
    assert "c" in chosen and chosen != {"a", "b"}


def test_assignment_avoids_node_behind_lossy_link():
    """Degraded (not partitioned) links steer the same way: loss
    injection on the a<->b pair prices it above a healthy cross-rack
    placement."""
    nodes = _infos([make_node("a", rack="r0"),
                    make_node("b", rack="r0"),
                    make_node("c", rack="r1")])
    penalty = _fleet_penalty("node:a<->node:b:drop:5")
    steered = sched.calculate_pods_assignment(
        nodes, _two_pods(), search_budget_s=None, link_penalty=penalty)
    chosen = {nodes[i]["name"] for i in steered}
    assert "c" in chosen and chosen != {"a", "b"}


def test_assignment_degrades_to_least_bad_when_nothing_healthy():
    """A penalty is finite, never a veto: when every candidate pair
    sits behind a partitioned link, the packer still returns the
    least-bad assignment — capacity over purity (and the graceful
    fallback the annotation source documents)."""
    from container_engine_accelerators_tpu.fleet.topology import NodeSpec

    nodes = _infos([make_node("a", rack="r0"),
                    make_node("b", rack="r0")])
    penalty = _fleet_penalty(
        "node:a<->node:b:partition",
        specs=[NodeSpec(name="a", rack="r0"),
               NodeSpec(name="b", rack="r0")])
    assignment = sched.calculate_pods_assignment(
        nodes, _two_pods(), search_budget_s=None, link_penalty=penalty)
    assert {nodes[i]["name"] for i in assignment} == {"a", "b"}


def test_assignment_unknown_hosts_cost_nothing():
    """Candidates the fleet has never heard of (a real cluster's other
    nodes) are not penalized — the annotation source only ever ADDS
    evidence it actually has."""
    penalty = _fleet_penalty("node:a<->node:b:partition")
    stranger = {"node_labels": {topology.HOST_LABEL: "zz-unknown"}}
    known = {"node_labels": {topology.HOST_LABEL: "a"}}
    assert penalty(stranger, known) == 0.0
    assert penalty(stranger, stranger) == 0.0


def test_scheduler_daemon_binds_around_partitioned_link():
    """The fake-API end-to-end: a SchedulerDaemon armed with the
    link-health source binds the job AROUND the node behind the
    partitioned fabric."""
    nodes = [make_node("a", rack="r0"), make_node("b", rack="r0"),
             make_node("c", rack="r1")]
    pods = [make_pod("j-0", index=0), make_pod("j-1", index=1)]
    api = FakeCoreV1(nodes, pods)
    d = sched.SchedulerDaemon(
        api, settle_s=0, sleep=lambda *_: None,
        link_penalty=_fleet_penalty("node:a<->node:b:partition"))
    assert d.run_once() == 2
    bound = set()
    for (_, name) in api.replaced:
        pod = api.pods[("default", name)]
        terms = pod["spec"]["affinity"]["nodeAffinity"][
            "requiredDuringSchedulingIgnoredDuringExecution"][
            "nodeSelectorTerms"]
        bound.add(terms[0]["matchExpressions"][0]["values"][0])
    assert "c" in bound and bound != {"a", "b"}


def test_scheduler_daemon_healthy_fleet_unchanged_by_annotations():
    """With no faults armed the annotation source is a no-op: the
    daemon makes the same placement it would have made bare."""
    nodes = [make_node("a", rack="r0"), make_node("b", rack="r0"),
             make_node("c", rack="r1")]
    pods = [make_pod("j-0", index=0), make_pod("j-1", index=1)]
    api = FakeCoreV1(nodes, pods)
    d = sched.SchedulerDaemon(api, settle_s=0, sleep=lambda *_: None,
                              link_penalty=_fleet_penalty())
    assert d.run_once() == 2
    bound = set()
    for (_, name) in api.replaced:
        pod = api.pods[("default", name)]
        terms = pod["spec"]["affinity"]["nodeAffinity"][
            "requiredDuringSchedulingIgnoredDuringExecution"][
            "nodeSelectorTerms"]
        bound.add(terms[0]["matchExpressions"][0]["values"][0])
    assert bound == {"a", "b"}


def test_live_penalty_sees_faults_armed_between_passes():
    """A bare scheduler_link_penalty() closure is a frozen snapshot;
    LinkHealthPenalty re-snapshots the link table, so a fault armed
    AFTER the daemon was constructed steers the next pass — the
    placement-reacts-to-faults contract for a long-lived daemon."""
    from container_engine_accelerators_tpu.collectives.topo import (
        LinkHealthPenalty,
    )
    from container_engine_accelerators_tpu.fleet.links import LinkTable
    from container_engine_accelerators_tpu.fleet.topology import (
        FleetTopology,
        NodeSpec,
    )

    fleet = FleetTopology([NodeSpec(name="a", rack="r0"),
                           NodeSpec(name="b", rack="r0"),
                           NodeSpec(name="c", rack="r1")])
    links = LinkTable(fleet)
    penalty = LinkHealthPenalty(fleet, links,
                                rates=lambda a, b: 0.0, refresh_s=0)
    nodes = _infos([make_node("a", rack="r0"),
                    make_node("b", rack="r0"),
                    make_node("c", rack="r1")])
    healthy = sched.calculate_pods_assignment(
        nodes, _two_pods(), search_budget_s=None, link_penalty=penalty)
    assert {nodes[i]["name"] for i in healthy} == {"a", "b"}

    # The fault arms AFTER the penalty object exists — the next pass
    # must see it.
    links.apply("node:a<->node:b:partition")
    steered = sched.calculate_pods_assignment(
        nodes, _two_pods(), search_budget_s=None, link_penalty=penalty)
    chosen = {nodes[i]["name"] for i in steered}
    assert "c" in chosen and chosen != {"a", "b"}

    # ...and the heal steers it back.
    links.apply("node:a<->node:b:heal")
    healed = sched.calculate_pods_assignment(
        nodes, _two_pods(), search_budget_s=None, link_penalty=penalty)
    assert {nodes[i]["name"] for i in healed} == {"a", "b"}
