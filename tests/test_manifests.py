"""Manifest sanity tests.

The reference ships ~2,900 lines of YAML whose only validation is use on
real clusters; here every manifest in the repo is parsed and
structurally checked on CI instead (selector/label agreement, container
volume mounts resolving to declared volumes, and device-plugin CLI args
actually accepted by the binary's argparser).
"""

import glob
import os

import pytest
import yaml

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

MANIFESTS = sorted(
    p
    for p in glob.glob(os.path.join(REPO, "**", "*.yaml"), recursive=True)
    if "/.git/" not in p and "/build/" not in p
)


def _docs(path):
    with open(path) as f:
        return [d for d in yaml.safe_load_all(f) if d]


def _pod_specs(doc):
    """Yield every PodSpec found in a manifest document."""
    kind = doc.get("kind")
    if kind == "Pod":
        yield doc["spec"]
    elif kind in ("DaemonSet", "Deployment", "StatefulSet", "Job"):
        yield doc["spec"]["template"]["spec"]
    elif kind == "CronJob":
        yield doc["spec"]["jobTemplate"]["spec"]["template"]["spec"]


def test_manifests_exist():
    assert MANIFESTS, "no YAML manifests found in repo"


@pytest.mark.parametrize("path", MANIFESTS, ids=lambda p: os.path.relpath(p, REPO))
def test_manifest_parses(path):
    docs = _docs(path)
    assert docs, f"{path} contains no YAML documents"
    for doc in docs:
        assert isinstance(doc, dict)
        assert "kind" in doc, f"{path}: document missing kind"
        assert "apiVersion" in doc, f"{path}: document missing apiVersion"


@pytest.mark.parametrize("path", MANIFESTS, ids=lambda p: os.path.relpath(p, REPO))
def test_selectors_match_template_labels(path):
    for doc in _docs(path):
        if doc.get("kind") not in ("DaemonSet", "Deployment", "StatefulSet"):
            continue
        sel = doc["spec"]["selector"]["matchLabels"]
        labels = doc["spec"]["template"]["metadata"]["labels"]
        for k, v in sel.items():
            assert labels.get(k) == v, (
                f"{path}: selector {k}={v} not in template labels {labels}"
            )


@pytest.mark.parametrize("path", MANIFESTS, ids=lambda p: os.path.relpath(p, REPO))
def test_volume_mounts_resolve(path):
    for doc in _docs(path):
        for spec in _pod_specs(doc):
            volumes = {v["name"] for v in spec.get("volumes", [])}
            for c in spec.get("containers", []) + spec.get("initContainers", []):
                for vm in c.get("volumeMounts", []):
                    assert vm["name"] in volumes, (
                        f"{path}: container {c['name']} mounts undeclared "
                        f"volume {vm['name']}"
                    )


def _find_container(path, name):
    for doc in _docs(path):
        for spec in _pod_specs(doc):
            for c in spec.get("containers", []) + spec.get("initContainers", []):
                if c["name"] == name:
                    return c
    raise AssertionError(f"container {name} not found in {path}")


def _load_cmd_module(filename):
    """exec a cmd/ driver by path (argparsers live behind main guards)."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        filename.replace(".py", "_manifest"),
        os.path.join(REPO, "cmd", filename),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_device_plugin_manifest_args_accepted():
    """The DS command line must be parseable by the real binary."""
    parse_args = _load_cmd_module("tpu_device_plugin.py").parse_args

    c = _find_container(os.path.join(REPO, "cmd", "device-plugin.yaml"),
                        "tpu-device-plugin")
    argv = [a for a in c["command"] if a.startswith("--")]
    args = parse_args(argv)
    assert args.enable_container_tpu_metrics
    assert args.enable_health_monitoring
    assert args.host_path == "/home/kubernetes/bin/tpu"


def test_device_plugin_manifest_mounts_required_paths():
    c = _find_container(os.path.join(REPO, "cmd", "device-plugin.yaml"),
                        "tpu-device-plugin")
    mounts = {vm["mountPath"] for vm in c["volumeMounts"]}
    for required in (
        "/var/lib/kubelet/device-plugins",  # plugin + kubelet sockets
        "/dev",                             # /dev/accel*
        "/sys",                             # tpulib sysfs contract
        "/var/lib/kubelet/pod-resources",   # metrics container join
        "/var/run/tpu",                     # health-event queue
    ):
        assert required in mounts, f"device plugin DS missing mount {required}"


def test_collectives_configmap_flags_accepted_by_bench():
    """run-collective.sh must invoke bench.py with flags its parser knows."""
    import re

    from container_engine_accelerators_tpu.collectives import bench

    path = os.path.join(REPO, "ici-collectives", "xla-collectives-config.yaml")
    (doc,) = _docs(path)
    script = doc["data"]["run-collective.sh"]
    used = set(re.findall(r"(--[a-z][a-z0-9_-]+)", script))
    # Flags inside LIBTPU_INIT_ARGS belong to libtpu, not the bench CLI.
    used = {f for f in used if not f.startswith("--xla")}

    # bench builds its parser inside main(); recover the known option
    # strings from a --help invocation.
    import contextlib
    import io

    buf = io.StringIO()
    with contextlib.suppress(SystemExit), contextlib.redirect_stdout(buf):
        bench.main(["--help"])
    helptext = buf.getvalue()
    for flag in used:
        assert flag in helptext, f"configmap uses unknown bench flag {flag}"


def test_collectives_test_pods_symmetric_and_wired():
    """Both rig variants: worker ids 0/1, shared coordinator, dcnxferd
    flags accepted by the native binary's parser."""
    for fname in (
        "xla-collectives-test.yaml",
        "xla-collectives-test-latest.yaml",
        "xla-collectives-test-without-hostnetwork.yaml",
        "xla-collectives-test-unprivileged-without-hostnetwork.yaml",
    ):
        path = os.path.join(REPO, "ici-collectives", fname)
        pods = [d for d in _docs(path) if d["kind"] == "Pod"]
        assert len(pods) == 2, f"{fname}: expected 2 pods"
        ids = set()
        for pod in pods:
            test_c = next(
                c for c in pod["spec"]["containers"]
                if c["name"] == "xla-collectives-test"
            )
            env = {e["name"]: e.get("value") for e in test_c["env"]}
            ids.add(env["TPU_WORKER_ID"])
            assert env["TPU_WORKER_COUNT"] == "2"
            assert env["TPU_COORDINATOR_ADDR"].startswith(
                "xla-collectives-host-1"
            )
            daemon = next(
                c for c in pod["spec"]["containers"] if c["name"] == "dcn-daemon"
            )
            flags = [a for a in daemon["command"] if a.startswith("--")]
            for f in flags:
                assert f in ("--uds_path", "--pool_bytes", "--max_flows",
                             "--verbose"), f"{fname}: unknown dcnxferd flag {f}"
        assert ids == {"0", "1"}, f"{fname}: worker ids {ids}"


def test_collectives_rig_matrix_axes():
    """The 4-variant matrix must actually vary along the privilege and
    hostNetwork axes it claims (the reference ships the same 4-flavor
    spread: nccl-test{,-latest,-without-hostnetwork,-unprivileged-...})."""
    expect = {
        # fname -> (daemon privileged?, hostNetwork?)
        "xla-collectives-test.yaml": (True, True),
        "xla-collectives-test-latest.yaml": (True, True),
        "xla-collectives-test-without-hostnetwork.yaml": (True, False),
        "xla-collectives-test-unprivileged-without-hostnetwork.yaml":
            (False, False),
    }
    for fname, (priv, hostnet) in expect.items():
        path = os.path.join(REPO, "ici-collectives", fname)
        for pod in (d for d in _docs(path) if d["kind"] == "Pod"):
            spec = pod["spec"]
            assert bool(spec.get("hostNetwork")) is hostnet, fname
            daemon = next(
                c for c in spec["containers"] if c["name"] == "dcn-daemon"
            )
            sc = daemon.get("securityContext", {})
            assert bool(sc.get("privileged")) is priv, fname
            if not hostnet:
                # Pod-network rendezvous needs the stable pod DNS name.
                assert spec.get("subdomain"), f"{fname}: missing subdomain"
            if not priv:
                # Unprivileged daemons get device nodes from the NRI
                # injector annotation.
                ann = pod["metadata"]["annotations"]
                assert "devices.gke.io/container.dcn-daemon" in ann, fname


def test_latest_rig_runs_full_matrix_with_artifacts():
    path = os.path.join(REPO, "ici-collectives", "xla-collectives-test-latest.yaml")
    for pod in (d for d in _docs(path) if d["kind"] == "Pod"):
        test_c = next(
            c for c in pod["spec"]["containers"]
            if c["name"] == "xla-collectives-test"
        )
        assert "matrix.sh" in test_c["command"][-1], "latest rig must sweep the op matrix"
        env = {e["name"]: e.get("value") for e in test_c["env"]}
        assert env.get("ARTIFACT_DIR") == "/artifacts"
        mounts = {m["mountPath"] for m in test_c["volumeMounts"]}
        assert "/artifacts" in mounts

    # matrix.sh itself must cover all four ops and emit per-op verdicts.
    (cfg,) = _docs(os.path.join(REPO, "ici-collectives", "xla-collectives-config.yaml"))
    matrix = cfg["data"]["matrix.sh"]
    for op in ("all_reduce", "all_gather", "reduce_scatter", "ppermute"):
        assert op in matrix
    assert "--verdict-json" in matrix


def test_recorded_sweep_artifact_is_a_pass():
    """The committed virtual-mesh verdict artifact stays parseable and
    internally consistent (peak matches the per-size results)."""
    import json

    path = os.path.join(
        REPO, "ici-collectives", "results", "sweep-virtual-cpu8.json"
    )
    with open(path) as f:
        v = json.load(f)
    assert v["op"] == "all_reduce" and v["devices"] == 8
    assert v["pass"] is True
    peak = max(r["bus_bw_gbps"] for r in v["results"])
    assert abs(peak - v["peak_busbw_gbps"]) < 1e-9
    assert v["line_rate_fraction"] >= v["pass_threshold"]


def test_preloaded_smoke_manifests_never_pull():
    """The preloaded-installer smoke DSes (analog of the reference's
    test/nvidia_gpu/daemonset-*-preloaded*.yaml) must really use the
    node-preloaded image: :fixed tag + imagePullPolicy Never, and the
    COS test variant must pin itself to TEST-labeled nodes only."""
    for fname, test_nodes in (
        ("daemonset-preloaded-test.yaml", True),
        ("daemonset-ubuntu-preloaded.yaml", False),
    ):
        path = os.path.join(REPO, "test", "tpu", fname)
        (doc,) = _docs(path)
        spec = doc["spec"]["template"]["spec"]
        installer = next(
            c for c in spec["initContainers"] if c["name"] == "libtpu-installer"
        )
        assert installer["image"].endswith(":fixed"), fname
        assert installer["imagePullPolicy"] == "Never", fname
        terms = spec["affinity"]["nodeAffinity"][
            "requiredDuringSchedulingIgnoredDuringExecution"
        ]["nodeSelectorTerms"]
        keys = {m["key"] for t in terms for m in t["matchExpressions"]}
        expected = (
            "cloud.google.com/gke-tpu-accelerator-test"
            if test_nodes else "cloud.google.com/gke-tpu-accelerator"
        )
        assert expected in keys, f"{fname}: affinity keys {keys}"


def test_installer_entrypoint_is_executable_bash():
    path = os.path.join(REPO, "libtpu-installer", "ubuntu", "entrypoint.sh")
    with open(path) as f:
        first = f.readline()
    assert first.startswith("#!/bin/bash")
    assert os.access(path, os.X_OK), "entrypoint.sh must be executable"


def test_lm_serving_manifest_args_accepted():
    """The LM serving Deployment's command line must be parseable by
    the real server AND pass its flag-composition checks (a manifest
    carrying a rejected pairing would CrashLoop on the cluster)."""
    mod = _load_cmd_module("serve_lm.py")

    c = _find_container(
        os.path.join(REPO, "demo", "serving", "jax-lm-serving.yaml"),
        "jax-lm-serving-container")
    # The EXACT argv the container runs (everything after the script
    # path) — a stray positional token must fail here like it would on
    # the cluster, and the shared validate_args applies the same
    # composition gates main() enforces.
    assert c["command"][0] == "python3"
    argv = c["command"][2:]
    args = mod.parse_args(argv)
    mod.validate_args(args)
    # The demo ships the serving levers on.
    assert args.slots and args.prefix_cache
    assert args.weights == "int8" and args.kv_heads == 4

    # Train/serve architecture coherence (ADVICE r4): the serving
    # Deployment restores the training Job's checkpoint, so every
    # architecture flag must agree or the pod CrashLoops on an orbax
    # tree mismatch.
    train_mod = _load_cmd_module("train_lm.py")
    tc = _find_container(
        os.path.join(REPO, "demo", "tpu-training", "lm-data-tpu.yaml"),
        "lm-data-tpu")
    targs = train_mod.parse_args(tc["command"][2:])
    for f in ("num_layers", "num_heads", "head_dim", "mlp_dim",
              "kv_heads", "vocab_size"):
        assert getattr(args, f) == getattr(targs, f), (
            f"serving manifest {f}={getattr(args, f)} != training "
            f"manifest {f}={getattr(targs, f)}")


def test_lm_data_manifest_args_accepted_and_wired():
    """The data-pipeline training Job: trainer argv parses, the init
    container packs into the dir the trainer reads, and both mount the
    shared volume."""
    mod = _load_cmd_module("train_lm.py")

    path = os.path.join(REPO, "demo", "tpu-training", "lm-data-tpu.yaml")
    c = _find_container(path, "lm-data-tpu")
    argv = c["command"][2:]
    args = mod.parse_args(argv)
    assert args.data_dir == "/data/shards"
    assert args.checkpoint_dir == "/data/ckpt"

    job = next(d for d in _docs(path) if d["kind"] == "Job")
    pod = job["spec"]["template"]["spec"]
    init = pod["initContainers"][0]
    script = "\n".join(init["command"])
    assert "--out /data/shards" in script  # packer fills what trainer reads
    assert "tokpack" in script
    data_mounts = {
        cc["name"]: {m["name"] for m in cc["volumeMounts"]}
        for cc in pod["containers"] + pod["initContainers"]
    }
    assert all("data" in m for m in data_mounts.values())


def test_manifest_app_paths_exist_in_image():
    """Every /app/<path> a shipped manifest or script invokes must
    exist in the release image: either under a tree the Dockerfile
    copies wholesale (cmd/, demo/, example/, the package) with the
    file present in the repo, or via an explicit COPY destination (the
    native binaries are copied file-by-file — round 5 caught the
    lm-data Job's tokpack path missing exactly this way)."""
    import re

    dockerfile = open(os.path.join(REPO, "Dockerfile")).read()
    wholesale = tuple(
        m.rstrip("/") for m in re.findall(
            r"^COPY (\S+)/ \1/$", dockerfile, re.M))
    assert "cmd" in wholesale and "demo" in wholesale
    # Only genuine COPY destinations count — a comment or CMD line
    # mentioning the path must not satisfy the guard.
    explicit = set(re.findall(r"^\s*(?:COPY|ADD)\b[^\n]*?/app/(\S+)$",
                              dockerfile, re.M))

    refs = set()
    scan = MANIFESTS + sorted(
        glob.glob(os.path.join(REPO, "**", "*.sh"), recursive=True))
    for path in scan:
        if "/.git/" in path or "/build/" in path:
            continue
        refs.update(re.findall(r"/app/([\w./-]+)", open(path).read()))
    assert refs, "no /app references found — the scan broke"
    for ref in sorted(refs):
        top = ref.split("/")[0]
        ok = (os.path.exists(os.path.join(REPO, ref))
              if top in wholesale else ref in explicit)
        assert ok, (f"a manifest/script references /app/{ref} but the "
                    f"Dockerfile neither copies its tree wholesale "
                    f"(with the file present in the repo) nor COPYs "
                    f"it explicitly")
