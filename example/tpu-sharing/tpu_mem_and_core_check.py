#!/usr/bin/env python3
"""Verify the TPU-sharing env contract inside a container.

Analog of the reference's CUDA MPS check
(ref: example/cuda-mps/cuda_mem_and_sm_count.c — prints visible SM count
and memory so operators can confirm CUDA_MPS_ACTIVE_THREAD_PERCENTAGE /
CUDA_MPS_PINNED_DEVICE_MEM_LIMIT took effect).  The TPU sharing contract
(sharing/sharing.py, manager.Envs analog) is:

    TPU_CORE_PERCENTAGE   — TensorCore fraction granted to this client
    TPU_HBM_LIMIT_BYTES   — HBM cap for this client

This prints the granted contract plus what the runtime actually sees,
and exits non-zero when a declared HBM cap is not being enforced.
"""

import os
import sys


def main() -> int:
    core_pct = os.environ.get("TPU_CORE_PERCENTAGE")
    hbm_limit = os.environ.get("TPU_HBM_LIMIT_BYTES")
    print(f"TPU_CORE_PERCENTAGE = {core_pct or '<unset>'}")
    print(f"TPU_HBM_LIMIT_BYTES = {hbm_limit or '<unset>'}")

    try:
        import jax

        devices = jax.devices()
    except Exception as e:
        print(f"could not initialize JAX: {e}")
        return 1

    print(f"visible devices: {len(devices)}")
    ok = True
    for d in devices:
        stats = d.memory_stats() or {}
        limit = stats.get("bytes_limit")
        print(f"  {d.device_kind} id={d.id} bytes_limit={limit}")
        if hbm_limit and limit and limit > int(hbm_limit):
            print(f"  ERROR: runtime limit {limit} exceeds granted "
                  f"{hbm_limit}")
            ok = False
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
