"""Fixed-shape sample shards (images + labels), memory-mapped.

The image-side twin of tokens.py, for the ResNet/Inception demos the
reference feeds from mounted ImageNet through tf.data
(demo/gpu-training/generate_job.sh:54-70).  A dataset directory holds
``NNNNN.images`` (raw sample arrays, any fixed shape/dtype) and
``NNNNN.labels`` (int32) pairs plus an ``index.json`` recording the
sample shape/dtype and per-shard counts.  Readers memory-map both
files, so a job touches only the samples its batches slice.

uint8 storage is the intended format for images (4x smaller than f32
on disk and over the network); the loader scales it to [0, 1] f32 on
the host, off the step path.
"""

import json
import os
from typing import Iterable, List, Tuple

import numpy as np

INDEX_NAME = "index.json"
FORMAT_VERSION = 1


def write_array_shards(directory: str,
                       batches: Iterable[Tuple[np.ndarray, np.ndarray]],
                       ) -> List[str]:
    """Write (images, labels) pairs, one shard each; rebuild the index.

    Every images array must share dtype and per-sample shape; labels
    are int32 with matching leading dimension.

    A directory that already holds shards is refused: unlike token
    shards (any uint32 file is valid data), array shards carry
    per-dataset shape/dtype, and folding stale files into a rebuilt
    index could silently reinterpret old bytes under the new sample
    shape.
    """
    os.makedirs(directory, exist_ok=True)
    stale = [f for f in os.listdir(directory) if f.endswith(".images")]
    if stale:
        raise ValueError(
            f"{directory} already holds {stale[0]} — refusing to mix "
            f"datasets (write into a fresh directory)")
    paths = []
    sample_shape = None
    dtype = None
    count = 0
    for images, labels in batches:
        images = np.ascontiguousarray(images)
        labels = np.ascontiguousarray(labels, dtype="<i4")
        if images.shape[0] != labels.shape[0] or labels.ndim != 1:
            raise ValueError(
                f"shard {count}: images {images.shape} vs labels "
                f"{labels.shape}")
        if sample_shape is None:
            sample_shape, dtype = images.shape[1:], images.dtype
        elif images.shape[1:] != sample_shape or images.dtype != dtype:
            raise ValueError(
                f"shard {count}: shape/dtype {images.shape[1:]}"
                f"/{images.dtype} != first shard {sample_shape}/{dtype}")
        base = os.path.join(directory, f"{count:05d}")
        for suffix, arr in ((".images", images), (".labels", labels)):
            tmp = base + suffix + ".tmp"
            with open(tmp, "wb") as f:
                f.write(arr.tobytes())
            os.replace(tmp, base + suffix)
        paths.append(base + ".images")
        count += 1
    if sample_shape is None:
        raise ValueError("no batches given")
    _write_index(directory, sample_shape, dtype)
    return paths


def _write_index(directory, sample_shape, dtype) -> None:
    sample_bytes = int(np.prod(sample_shape)) * dtype.itemsize
    shards = sorted(
        f for f in os.listdir(directory) if f.endswith(".images")
    )
    index = {
        "version": FORMAT_VERSION,
        "sample_shape": list(int(d) for d in sample_shape),
        "dtype": dtype.name,
        "shards": [
            {"name": s[:-7],
             "samples": os.path.getsize(os.path.join(directory, s))
             // sample_bytes}
            for s in shards
        ],
    }
    tmp = os.path.join(directory, INDEX_NAME + ".tmp")
    with open(tmp, "w") as f:
        json.dump(index, f, indent=1)
    os.replace(tmp, os.path.join(directory, INDEX_NAME))


class ArrayShardReader:
    """One logical (images, labels) stream with modular slicing."""

    def __init__(self, directory: str):
        index_path = os.path.join(directory, INDEX_NAME)
        try:
            with open(index_path) as f:
                index = json.load(f)
        except OSError as e:
            raise FileNotFoundError(
                f"{index_path}: not an array dataset (write one with "
                f"data.write_array_shards)") from e
        if index.get("version") != FORMAT_VERSION:
            raise ValueError(
                f"{index_path}: format version {index.get('version')!r}"
                f" != {FORMAT_VERSION}")
        if "sample_shape" not in index:
            raise ValueError(f"{index_path}: token-dataset index? "
                             f"(no sample_shape)")
        self.sample_shape = tuple(index["sample_shape"])
        self.dtype = np.dtype(index["dtype"])
        self._images = []
        self._labels = []
        self._starts = []
        total = 0
        for entry in index["shards"]:
            base = os.path.join(directory, entry["name"])
            img = np.memmap(base + ".images", dtype=self.dtype, mode="r")
            img = img.reshape((-1,) + self.sample_shape)
            lab = np.memmap(base + ".labels", dtype="<i4", mode="r")
            if img.shape[0] != entry["samples"] \
                    or lab.shape[0] != entry["samples"]:
                raise ValueError(
                    f"{base}: {img.shape[0]} images / {lab.shape[0]} "
                    f"labels on disk != {entry['samples']} in index")
            self._images.append(img)
            self._labels.append(lab)
            self._starts.append(total)
            total += entry["samples"]
        if total == 0:
            raise ValueError(f"{directory}: dataset has 0 samples")
        self.total_samples = total

    def read(self, start: int, n: int):
        """(images [n, ...], labels [n]) at logical offset (modular)."""
        images = np.empty((n,) + self.sample_shape, dtype=self.dtype)
        labels = np.empty((n,), np.int32)
        filled = 0
        pos = int(start) % self.total_samples
        while filled < n:
            i = int(np.searchsorted(self._starts, pos, side="right") - 1)
            off = pos - self._starts[i]
            take = min(n - filled, self._images[i].shape[0] - off)
            images[filled:filled + take] = self._images[i][off:off + take]
            labels[filled:filled + take] = self._labels[i][off:off + take]
            filled += take
            pos = (pos + take) % self.total_samples
        return images, labels
