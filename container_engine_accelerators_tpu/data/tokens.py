"""Token shard format: flat uint32 streams, memory-mapped reads.

A dataset is a directory of ``NNNNN.tokens`` files (little-endian
uint32, no header — the format a native packer can emit with plain
writes; see native/tokpack) plus an ``index.json`` carrying the shard
token counts and a format version.  Readers memory-map each shard, so
a training job touches only the pages its global-batch slices actually
read — the property that matters on a pod where every host maps the
same dataset but reads a disjoint batch shard.

The reference's analog is the mounted-ImageNet + tf.data path of the
demo trainers (demo/gpu-training/generate_job.sh:54-70); here the
format is deliberately trivial so the WRITER can be anything (the
in-tree native packer, a Python script, a Beam job) and the contract
is just "uint32s + index.json".
"""

import json
import os
from typing import Iterable, List

import numpy as np

INDEX_NAME = "index.json"
FORMAT_VERSION = 1
_DTYPE = np.dtype("<u4")


def write_token_shards(directory: str, streams: Iterable[np.ndarray],
                       name_offset: int = 0) -> List[str]:
    """Write each stream as one shard; (re)write ``index.json``.

    Appending to an existing dataset: pass ``name_offset`` = number of
    existing shards; the index is rebuilt from the directory contents
    so it always reflects what is actually on disk.
    """
    # Validate EVERY stream before writing ANY shard: a mid-loop
    # rejection would leave earlier shards on disk with no index
    # rebuild — an orphan a later write's directory-scan rebuild would
    # silently adopt.
    arrays = []
    for i, stream in enumerate(streams):
        arr = np.ascontiguousarray(np.asarray(stream), dtype=_DTYPE)
        if arr.ndim != 1:
            raise ValueError(f"stream {i}: want 1-D tokens, got "
                             f"shape {arr.shape}")
        if arr.size == 0:
            # A 0-byte shard would crash TokenShardReader inside
            # np.memmap with an opaque mmap error (ADVICE r4); fail at
            # the format level, at write time.
            raise ValueError(f"stream {i}: empty token stream — a "
                             f"zero-byte shard cannot be memory-mapped")
        arrays.append(arr)
    os.makedirs(directory, exist_ok=True)
    paths = []
    for i, arr in enumerate(arrays):
        path = os.path.join(directory, f"{name_offset + i:05d}.tokens")
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(arr.tobytes())
        os.replace(tmp, path)  # never a half-written shard at its name
        paths.append(path)
    _write_index(directory)
    return paths


def _write_index(directory: str) -> None:
    shards = sorted(
        f for f in os.listdir(directory) if f.endswith(".tokens")
    )
    index = {
        "version": FORMAT_VERSION,
        "shards": [
            {"name": s,
             "tokens": os.path.getsize(os.path.join(directory, s))
             // _DTYPE.itemsize}
            for s in shards
        ],
    }
    tmp = os.path.join(directory, INDEX_NAME + ".tmp")
    with open(tmp, "w") as f:
        json.dump(index, f, indent=1)
    os.replace(tmp, os.path.join(directory, INDEX_NAME))


class TokenShardReader:
    """Memory-mapped view over a shard directory as ONE logical token
    stream with O(1) random slicing.

    ``read(start, n)`` returns ``n`` tokens starting at logical offset
    ``start`` (wrapping around the end of the dataset — epochs are the
    caller's modular arithmetic, which keeps the step->data mapping a
    pure function; see loader.py).
    """

    def __init__(self, directory: str):
        index_path = os.path.join(directory, INDEX_NAME)
        try:
            with open(index_path) as f:
                index = json.load(f)
        except OSError as e:
            raise FileNotFoundError(
                f"{index_path}: not a token dataset (write one with "
                f"data.write_token_shards or native/tokpack)") from e
        if index.get("version") != FORMAT_VERSION:
            raise ValueError(
                f"{index_path}: format version {index.get('version')!r}"
                f" != {FORMAT_VERSION}")
        self.directory = directory
        self._maps = []
        self._starts = []  # logical start offset of each shard
        total = 0
        for entry in index["shards"]:
            path = os.path.join(directory, entry["name"])
            m = np.memmap(path, dtype=_DTYPE, mode="r")
            if m.size != entry["tokens"]:
                raise ValueError(
                    f"{path}: {m.size} tokens on disk != "
                    f"{entry['tokens']} in index (stale index.json?)")
            self._maps.append(m)
            self._starts.append(total)
            total += m.size
        if total == 0:
            raise ValueError(f"{directory}: dataset has 0 tokens")
        self.total_tokens = total

    def read(self, start: int, n: int) -> np.ndarray:
        """``n`` tokens at logical offset ``start`` (modular)."""
        out = np.empty((n,), dtype=np.uint32)
        filled = 0
        pos = int(start) % self.total_tokens
        while filled < n:
            shard_i = int(
                np.searchsorted(self._starts, pos, side="right") - 1)
            m = self._maps[shard_i]
            off = pos - self._starts[shard_i]
            take = min(n - filled, m.size - off)
            out[filled:filled + take] = m[off:off + take]
            filled += take
            pos = (pos + take) % self.total_tokens
        return out
