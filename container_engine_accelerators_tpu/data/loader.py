"""Deterministic token-batch loader with background prefetch.

Two properties carry the whole design:

1. **The step->batch mapping is a pure function.**  Row ``r`` of step
   ``s`` is the ``seq_len+1``-token window at logical offset
   ``(s * batch_size + r) * seq_len`` (modular over the dataset), read
   through the memory-mapped :class:`~.tokens.TokenShardReader`.  No
   iterator state exists to checkpoint: resuming a preempted job at
   step ``k`` (models/checkpoint.py restores ``k``) replays exactly the
   batches steps ``k, k+1, ...`` would have seen — data-pipeline resume
   for free, and every host of a pod computes the identical global
   batch (the multi-host contract cmd/train_lm.py's ``globalize``
   already assumes for its synthetic streams).

2. **Prefetch happens off the step path.**  A daemon thread keeps a
   small queue of ready numpy batches while the accelerator runs the
   current step; the reference leaned on tf.data's C++ pipeline for the
   same overlap (demo/gpu-training/generate_job.sh:54-70).

Labels are next-token within the same window (the reader hands out
``seq_len + 1`` tokens), so every position has a real target and the
mask is all-ones — no batch-boundary dead positions.
"""

import queue
import threading
from typing import Iterator, Optional, Tuple

import numpy as np

from container_engine_accelerators_tpu.data.arrays import ArrayShardReader
from container_engine_accelerators_tpu.data.tokens import TokenShardReader

Batch = Tuple[np.ndarray, np.ndarray, np.ndarray]


def _prefetched(batch_fn, start_step: int, num_steps: int,
                prefetch: int) -> Iterator:
    """Yield ``batch_fn(s)`` for s in [start, start+num) in order,
    produced by a background thread.  Producer errors (e.g. vocab
    overflow) are re-raised at the consuming step, not swallowed.

    An abandoned iterator (exception/SystemExit mid-training, partial
    consumption) must not leak the producer: a blocking ``q.put``
    would park the thread forever once the consumer stops draining
    (ADVICE r4), so every put polls a ``closed`` event that the
    consumer's ``finally`` sets — generator close/GC wakes the
    producer within one poll interval and it exits."""
    q: "queue.Queue" = queue.Queue(maxsize=max(prefetch, 1))
    closed = threading.Event()

    def put_until_closed(item) -> bool:
        while not closed.is_set():
            try:
                q.put(item, timeout=0.2)
                return True
            except queue.Full:
                continue
        return False

    def produce():
        try:
            for s in range(start_step, start_step + num_steps):
                if not put_until_closed(batch_fn(s)):
                    return
        except BaseException as e:  # noqa: BLE001 — re-raised below
            put_until_closed(e)

    threading.Thread(target=produce, daemon=True,
                     name="tokenloader-prefetch").start()
    try:
        for _ in range(num_steps):
            item = q.get()
            if isinstance(item, BaseException):
                raise item
            yield item
    finally:
        closed.set()


class TokenBatchLoader:
    def __init__(self, reader: TokenShardReader, batch_size: int,
                 seq_len: int, vocab_size: Optional[int] = None,
                 prefetch: int = 2):
        if batch_size < 1 or seq_len < 1:
            raise ValueError("batch_size and seq_len must be >= 1")
        self.reader = reader
        self.batch_size = batch_size
        self.seq_len = seq_len
        self.vocab_size = vocab_size
        self.prefetch = prefetch

    def batch_at(self, step: int) -> Batch:
        """(tokens, labels, mask), each [B, T] — pure in ``step``."""
        b, t = self.batch_size, self.seq_len
        window = np.stack([
            self.reader.read((step * b + r) * t, t + 1)
            for r in range(b)
        ])
        peak = int(window.max())
        if peak >= 2**31:
            # The on-disk contract is full-range uint32; int32 batches
            # would wrap this negative and gather a garbage embedding.
            raise ValueError(
                f"dataset token {peak} >= 2**31 overflows the int32 "
                f"batch dtype (step {step})")
        if self.vocab_size is not None and peak >= self.vocab_size:
            raise ValueError(
                f"dataset token {peak} >= model vocab "
                f"{self.vocab_size} (step {step}): retokenize or "
                f"raise --vocab-size")
        tokens = window[:, :-1].astype(np.int32)
        labels = window[:, 1:].astype(np.int32)
        return tokens, labels, np.ones((b, t), np.float32)

    def iter_batches(self, start_step: int,
                     num_steps: int) -> Iterator[Batch]:
        """Prefetched batches for steps [start, start+num) in order."""
        return _prefetched(self.batch_at, start_step, num_steps,
                           self.prefetch)

    def steps_per_epoch(self) -> int:
        """Steps to consume the dataset once (floor; the modular
        mapping keeps running past it seamlessly)."""
        return max(
            1,
            self.reader.total_tokens
            // (self.batch_size * self.seq_len),
        )


class ImageBatchLoader:
    """Image/label twin of :class:`TokenBatchLoader` — same pure
    step->batch mapping (global batch ``s`` is samples
    ``[s*B, (s+1)*B)``, modular) and the same prefetch thread.

    ``shard=(pid, num_procs)`` makes ``batch_at`` return only this
    process's rows of the global batch — image rows are independent
    (unlike token labels, which cross sequence shards), so a host
    never has to materialize or scale the other hosts' slices.  The
    mapping stays a pure function of (step, shard): resume is exact
    and the union over shards is exactly the global batch.

    uint8 storage is scaled to [0, 1] float32 on the host ([0, 1) only
    for images that never saturate); float storage passes through.
    ``num_classes`` bounds labels the way ``vocab_size`` bounds tokens.
    """

    def __init__(self, reader: ArrayShardReader, batch_size: int,
                 num_classes: Optional[int] = None, prefetch: int = 2,
                 shard: Tuple[int, int] = (0, 1)):
        pid, num_procs = shard
        if batch_size < 1:
            raise ValueError("batch_size must be >= 1")
        if num_procs < 1 or not 0 <= pid < num_procs \
                or batch_size % num_procs:
            raise ValueError(
                f"shard {shard} invalid for batch_size {batch_size}")
        self.reader = reader
        self.batch_size = batch_size
        self.num_classes = num_classes
        self.prefetch = prefetch
        self.shard = shard

    def batch_at(self, step: int):
        pid, num_procs = self.shard
        local = self.batch_size // num_procs
        images, labels = self.reader.read(
            step * self.batch_size + pid * local, local)
        if self.num_classes is not None:
            peak = int(labels.max())
            if peak >= self.num_classes:
                raise ValueError(
                    f"dataset label {peak} >= num_classes "
                    f"{self.num_classes} (step {step})")
        if images.dtype == np.uint8:
            images = images.astype(np.float32) / 255.0
        else:
            images = images.astype(np.float32, copy=False)
        return images, labels

    def iter_batches(self, start_step: int, num_steps: int):
        return _prefetched(self.batch_at, start_step, num_steps,
                           self.prefetch)

    def steps_per_epoch(self) -> int:
        return max(1, self.reader.total_samples // self.batch_size)
