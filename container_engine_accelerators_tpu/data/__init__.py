"""TPU-first input pipeline: memory-mapped token shards, deterministic
step->batch mapping, background prefetch.

The reference's demo trainers stream their datasets through tf.data's
C++ runtime (demo/gpu-training/generate_job.sh:54-70 mounts ImageNet
into the TF trainer); this package is the in-tree equivalent for the
JAX workloads, with the resume/multi-host properties the rest of the
framework already guarantees for model state.
"""

from container_engine_accelerators_tpu.data.tokens import (  # noqa: F401
    TokenShardReader,
    write_token_shards,
)
from container_engine_accelerators_tpu.data.arrays import (  # noqa: F401
    ArrayShardReader,
    write_array_shards,
)
from container_engine_accelerators_tpu.data.loader import (  # noqa: F401
    ImageBatchLoader,
    TokenBatchLoader,
)
