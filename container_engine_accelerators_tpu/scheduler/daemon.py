"""Topology-aware scheduling daemon for gated TPU job pods.

Behavioral parity with the reference scheduler
(ref: gpudirect-tcpxo/topology-scheduler/schedule-daemon.py):

- pods carrying a scheduling gate prefixed ``gke.io/topology-aware-auto-``
  are collected per gate (:197-205), grouped by job and FIFO-ordered by
  creation time (:26-37,368-369);
- candidate nodes must carry topology labels, have every taint tolerated,
  and have free capacity = allocatable − Σ(requests of pods already on
  the node) (:127-194);
- pods sorted by completion index, nodes by topology key; an exhaustive
  increasing-index search picks the assignment minimizing the summed
  neighbor distance (:329-360) — here ICI hops within a slice, DCN
  hierarchy across (topology.py);
- binding removes the gate and pins ``kubernetes.io/hostname`` via
  required nodeAffinity, then PUTs the pod back (:298-326).

Everything operates on plain Kubernetes-JSON dicts, so the whole flow is
unit-testable against fixture dicts with no cluster (SURVEY.md §4).
"""

import logging
import time
from itertools import groupby
from typing import Callable, Dict, List, Optional, Set

from container_engine_accelerators_tpu.scheduler.k8s import ApiException, CoreV1
from container_engine_accelerators_tpu.scheduler.quantity import parse_quantity
from container_engine_accelerators_tpu.scheduler.topology import (
    PLACEMENT_GROUP_LABEL,
    node_topology_distance,
    node_topology_key,
)

log = logging.getLogger(__name__)

DEFAULT_GATE_PREFIX = "gke.io/topology-aware-auto-"
TPU_RESOURCE = "google.com/tpu"
JOB_NAME_LABEL = "job-name"
COMPLETION_INDEX_LABEL = "batch.kubernetes.io/job-completion-index"


# ---- pod/job ordering ------------------------------------------------------


def split_pods_based_on_jobs(pods) -> List[List[dict]]:
    """Group schedulable-pod dicts by job name (consecutive groupby, as in
    the reference; callers sort groups by creation time right after)."""
    return [
        list(group)
        for _, group in groupby(pods, key=lambda p: p.get("job_name"))
    ]


def job_creation_time(job: List[dict]):
    return job[0].get("creation_time") or ""


def pod_sorting_key(pod: dict):
    """Uniform 3-tuple key: indexed pods first by completion index, then
    unindexed pods by (name-prefix, numeric-suffix) so 'xxx-pod2' sorts
    before 'xxx-pod10'.

    The reference returns ``int`` for indexed pods and ``tuple`` for
    unindexed ones (schedule-daemon.py:40-50), so a job mixing both
    crashes ``sorted()`` with a TypeError; one key shape fixes that
    without changing the order within either class.
    """
    if pod.get("index") is not None:
        return (0, "", int(pod["index"]))
    name = pod["name"]
    stripped = name.rstrip("0123456789")
    suffix = name[len(stripped):]
    return (1, stripped, int(suffix) if suffix else 0)


# ---- discovery -------------------------------------------------------------


def find_pod_gates(pods: List[dict], prefix: str) -> Set[str]:
    """All gate names with the topology prefix across pending pods."""
    gates = set()
    for pod in pods:
        for g in pod.get("spec", {}).get("schedulingGates", []) or []:
            if g.get("name", "").startswith(prefix):
                gates.add(g["name"])
    return gates


def _container_requests(spec: dict):
    cpu = mem = tpu = 0.0
    for container in spec.get("containers", []):
        req = (container.get("resources") or {}).get("requests") or {}
        cpu += parse_quantity(req.get("cpu", 0))
        mem += parse_quantity(req.get("memory", 0))
        tpu += int(parse_quantity(req.get(TPU_RESOURCE, 0)))
    return cpu, mem, tpu


def find_schedulable_pods(pods: List[dict], gate_name: str) -> Dict[str, dict]:
    """Pods still carrying ``gate_name``, flattened to scheduling records."""
    out = {}
    for pod in pods:
        spec = pod.get("spec", {})
        if not any(
            g.get("name") == gate_name
            for g in spec.get("schedulingGates", []) or []
        ):
            continue
        meta = pod.get("metadata", {})
        labels = meta.get("labels") or {}
        cpu, mem, tpu = _container_requests(spec)
        rec = {
            "name": meta.get("name"),
            "namespace": meta.get("namespace", "default"),
            "index": labels.get(COMPLETION_INDEX_LABEL),
            "job_name": labels.get(JOB_NAME_LABEL),
            "creation_time": meta.get("creationTimestamp"),
            "cpu": cpu,
            "memory": mem,
            "tpu": tpu,
            "node_selector": spec.get("nodeSelector"),
            "tolerations": spec.get("tolerations") or [],
        }
        out[rec["name"]] = rec
        log.info(
            "schedulable pod %s/%s cpu=%s mem=%s tpu=%s index=%s",
            rec["namespace"], rec["name"], cpu, mem, tpu, rec["index"],
        )
    return out


def _pod_used_resources(pod: dict):
    """Requests of a pod already placed on a node; terminated containers
    free their share (ref: schedule-daemon.py:94-109)."""
    statuses = (pod.get("status") or {}).get("containerStatuses")
    spec = pod.get("spec", {})
    if statuses is None:
        return _container_requests(spec)
    cpu = mem = tpu = 0.0
    for container, st in zip(spec.get("containers", []), statuses):
        if (st.get("state") or {}).get("terminated") is not None:
            continue
        req = (container.get("resources") or {}).get("requests") or {}
        cpu += parse_quantity(req.get("cpu", 0))
        mem += parse_quantity(req.get("memory", 0))
        tpu += int(parse_quantity(req.get(TPU_RESOURCE, 0)))
    return cpu, mem, tpu


def pods_tolerations(job: List[dict]) -> List[dict]:
    """Jobs are homogeneous: all pods share one toleration set."""
    return job[0].get("tolerations") or [] if job else []


def _toleration_matches(tol: dict, taint: dict) -> bool:
    """Kubernetes toleration semantics (the reference collapses these to
    a key lookup, schedule-daemon.py:127-194; this build implements the
    real rules — see VERDICT r03 weak-5):

    - empty toleration key + operator Exists tolerates every taint;
    - operator Exists ignores ``value`` (the API rejects Exists+value,
      but a hand-written manifest may carry one — ignore it here too);
    - operator Equal (the default) compares values;
    - empty toleration effect matches all effects, otherwise exact.
    """
    op = tol.get("operator") or "Equal"
    key = tol.get("key")
    if not key:
        if op != "Exists":
            return False
    elif key != taint.get("key"):
        return False
    if op != "Exists" and (tol.get("value") or "") != (taint.get("value") or ""):
        return False
    eff = tol.get("effect") or ""
    return eff == "" or eff == taint.get("effect")


def _taints_tolerated(taints, tolerations) -> bool:
    """True when no *blocking* taint is left untolerated.

    ``PreferNoSchedule`` is a soft preference — the real kube-scheduler
    still places pods on such nodes, so it never disqualifies a
    candidate here.  ``NoSchedule``/``NoExecute`` (and any unknown or
    missing effect, conservatively) block unless tolerated.
    ``tolerationSeconds`` bounds post-placement eviction on NoExecute,
    not admission, so it is rightly ignored at scheduling time.
    """
    for taint in taints or []:
        if taint.get("effect") == "PreferNoSchedule":
            continue
        if not any(_toleration_matches(t, taint) for t in tolerations or []):
            return False
    return True


def find_schedulable_nodes(
    nodes: List[dict], pods: List[dict], tolerations: List[dict]
) -> Dict[str, dict]:
    """Topology-labeled, untainted-or-tolerated nodes with free capacity."""
    out = {}
    for node in nodes:
        meta = node.get("metadata", {})
        name = meta.get("name")
        labels = meta.get("labels") or {}
        if PLACEMENT_GROUP_LABEL not in labels:
            log.info("skipping node %s: no topology metadata", name)
            continue
        if not _taints_tolerated(node.get("spec", {}).get("taints"), tolerations):
            log.info("skipping node %s: untolerated taint", name)
            continue

        alloc = (node.get("status") or {}).get("allocatable") or {}
        free_cpu = parse_quantity(alloc.get("cpu", 0))
        free_mem = parse_quantity(alloc.get("memory", 0))
        free_tpu = int(parse_quantity(alloc.get(TPU_RESOURCE, 0)))
        for pod in pods:
            if pod.get("spec", {}).get("nodeName") == name:
                cpu, mem, tpu = _pod_used_resources(pod)
                free_cpu -= cpu
                free_mem -= mem
                free_tpu -= tpu

        info = {
            "name": name,
            "cpu": free_cpu,
            "memory": free_mem,
            "tpu": free_tpu,
            "node_labels": labels,
        }
        out[name] = info
        log.info(
            "candidate node %s cpu=%s mem=%s tpu=%s key=%s",
            name, free_cpu, free_mem, free_tpu, node_topology_key(info),
        )
    return out


# ---- assignment search -----------------------------------------------------


def can_schedule(node: dict, pod: dict) -> bool:
    selector = pod.get("node_selector")
    labels = node["node_labels"]
    if selector:
        for key, value in selector.items():
            if labels.get(key) != value:
                return False
    return (
        node["cpu"] >= pod["cpu"]
        and node["memory"] >= pod["memory"]
        and node["tpu"] >= pod["tpu"]
    )


def calculate_pods_assignment(
    sorted_nodes: List[dict],
    sorted_pods: List[dict],
    search_budget_s: Optional[float] = 2.0,
    link_penalty: Optional[Callable[[dict, dict], float]] = None,
) -> List[int]:
    """Exhaustive strictly-increasing-index assignment search minimizing
    Σ distance(consecutive pods' nodes) (ref: schedule-daemon.py:329-360).

    Node order is the topology sort, so increasing indices enumerate
    physically-contiguous candidate sets; strict monotonicity both halves
    the search space and enforces one pod per node.

    The raw search is exponential in the worst case — C(nodes, pods)
    candidate sets, so a 200-node pool with a 64-pod job would hang the
    daemon's 1 s loop (VERDICT r03 weak-6; the reference has no guard).
    ``search_budget_s`` caps wall-clock: on expiry the best assignment
    found so far is returned (the search reaches its first feasible —
    lexicographically smallest, i.e. most topology-packed-prefix —
    assignment almost immediately, so a truncated answer is still a
    valid, usually near-optimal placement).  Pass ``None`` to search
    exhaustively.

    ``link_penalty`` is the optional link-health annotation source
    (e.g. ``collectives.topo.CommGraph.scheduler_link_penalty``): a
    callable adding a distance surcharge between two candidate nodes
    when the fabric between them is known partitioned or lossy.  The
    packer then *avoids* nodes behind bad links whenever a healthier
    placement exists, and — because a penalty is finite, never a veto
    — still returns the least-bad assignment when nothing healthy
    fits (capacity over purity: a degraded placement beats no
    placement).
    """
    if not sorted_pods:
        return []

    def _distance(a: dict, b: dict) -> float:
        d = node_topology_distance(a, b)
        if link_penalty is not None:
            d += link_penalty(a, b)
        return d
    assignment = [-i for i in reversed(range(1, len(sorted_pods) + 1))]
    best, best_distance = [], float("inf")
    deadline = (
        time.monotonic() + search_budget_s
        if search_budget_s is not None else None
    )
    iters = 0

    while True:
        iters += 1
        if deadline is not None and iters % 1024 == 0 \
                and time.monotonic() >= deadline:
            log.warning(
                "assignment search budget (%.1fs) exhausted after %d "
                "candidates (%d nodes, %d pods); returning best found",
                search_budget_s, iters, len(sorted_nodes), len(sorted_pods),
            )
            break
        all_ok = True
        i = len(assignment) - 1
        while i >= 0 and all_ok:
            assignment[i] += 1
            if assignment[i] == len(sorted_nodes):
                break
            if assignment[i] >= 0 and can_schedule(
                sorted_nodes[assignment[i]], sorted_pods[i]
            ):
                i -= 1
            elif i < len(assignment) - 1 and assignment[i] == assignment[i + 1] - 1:
                all_ok = False
        if assignment[-1] == len(sorted_nodes):
            break
        if all_ok:
            distance = sum(
                _distance(
                    sorted_nodes[assignment[i]], sorted_nodes[assignment[i - 1]]
                )
                for i in range(1, len(sorted_pods))
            )
            if distance < best_distance:
                best, best_distance = assignment.copy(), distance

    return best


# ---- binding ---------------------------------------------------------------


def schedule_pod_on_node(
    api: CoreV1, pod_name: str, namespace: str, node_name: str, gate_name: str
) -> bool:
    """Remove the gate, pin the hostname via nodeAffinity, PUT the pod."""
    try:
        pod = api.read_namespaced_pod(pod_name, namespace)
        gates = pod.get("spec", {}).get("schedulingGates", []) or []
        if not any(g.get("name") == gate_name for g in gates):
            return False
        pod["spec"]["schedulingGates"] = [
            g for g in gates if g.get("name") != gate_name
        ]
        pod["spec"]["affinity"] = {
            "nodeAffinity": {
                "requiredDuringSchedulingIgnoredDuringExecution": {
                    "nodeSelectorTerms": [{
                        "matchExpressions": [{
                            "key": "kubernetes.io/hostname",
                            "operator": "In",
                            "values": [node_name],
                        }]
                    }]
                }
            }
        }
        api.replace_namespaced_pod(pod_name, namespace, pod)
        log.info("pod %s/%s scheduled on %s", namespace, pod_name, node_name)
        return True
    except ApiException as e:
        log.error("binding %s/%s failed: %s", namespace, pod_name, e)
        return False


# ---- daemon ----------------------------------------------------------------


class SchedulerDaemon:
    def __init__(
        self,
        api: CoreV1,
        gate_prefix: str = DEFAULT_GATE_PREFIX,
        interval_s: float = 1.0,
        ignored_namespaces: Optional[List[str]] = None,
        settle_s: float = 5.0,
        sleep=time.sleep,
        search_budget_s: Optional[float] = 2.0,
        link_penalty: Optional[Callable[[dict, dict], float]] = None,
    ):
        self.api = api
        self.gate_prefix = gate_prefix
        self.interval_s = interval_s
        self.ignored_namespaces = set(ignored_namespaces or [])
        self.settle_s = settle_s  # job-atomicity heuristic (ref :455-457)
        self._sleep = sleep
        # Per-job cap on the assignment search (None = exhaustive).
        self.search_budget_s = search_budget_s
        # Optional link-health annotation source (see
        # calculate_pods_assignment).  The callable is consulted per
        # pass, but whether it SEES faults armed between passes is the
        # callable's own contract: a bare
        # CommGraph.scheduler_link_penalty() closure is a frozen
        # snapshot; wire collectives.topo.LinkHealthPenalty for a
        # source that re-snapshots the link table between passes.
        self.link_penalty = link_penalty

    def list_pods(self) -> List[dict]:
        pods = []
        for ns in self.api.list_namespaces():
            name = ns.get("metadata", {}).get("name")
            if name and name not in self.ignored_namespaces:
                pods.extend(self.api.list_namespaced_pods(name))
        return pods

    def schedule_gate(self, pods: List[dict], gate: str) -> int:
        """One pass for one gate; returns the number of pods bound."""
        pods_to_schedule = find_schedulable_pods(pods, gate)
        nodes = self.api.list_nodes()
        log.info("gate %s: %d pods to schedule", gate, len(pods_to_schedule))

        bound = 0
        jobs = split_pods_based_on_jobs(pods_to_schedule.values())
        for job in sorted(jobs, key=job_creation_time):
            job_name = job[0].get("job_name")
            candidates = find_schedulable_nodes(nodes, pods, pods_tolerations(job))
            sorted_pods = sorted(job, key=pod_sorting_key)
            sorted_nodes = sorted(candidates.values(), key=node_topology_key)
            assignment = calculate_pods_assignment(
                sorted_nodes, sorted_pods,
                search_budget_s=self.search_budget_s,
                link_penalty=self.link_penalty,
            )
            if not assignment:
                log.info("no placement for job %s under gate %s", job_name, gate)
                continue
            for i, pod in enumerate(sorted_pods):
                node = sorted_nodes[assignment[i]]
                if schedule_pod_on_node(
                    self.api, pod["name"], pod["namespace"], node["name"], gate
                ):
                    bound += 1
        return bound

    def run_once(self) -> int:
        pods = self.list_pods()
        gates = find_pod_gates(pods, self.gate_prefix)
        log.info("%d pods, %d gates", len(pods), len(gates))
        if not gates:
            return 0
        self._sleep(self.settle_s)
        bound = 0
        for gate in gates:
            pods = self.list_pods()  # re-list: stragglers may have appeared
            bound += self.schedule_gate(pods, gate)
        return bound

    def run_forever(self):
        while True:
            t0 = time.time()
            try:
                self.run_once()
            except ApiException as e:
                log.error("scheduling pass failed: %s", e)
            elapsed = time.time() - t0
            if elapsed < self.interval_s:
                self._sleep(self.interval_s - elapsed)
