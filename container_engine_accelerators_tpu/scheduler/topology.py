"""Topology keys and distances for TPU-aware placement.

The reference orders nodes by (placement-group, cluster, rack, host)
labels and scores an assignment by a hierarchical label-prefix distance
(ref: gpudirect-tcpxo/topology-scheduler/schedule-daemon.py:63-91).  The
TPU-native extension: nodes inside one TPU slice also carry ICI mesh
coordinates, and the distance between two hosts in the same slice is the
torus hop distance between their coordinates — so the assignment search
packs a job's pods onto ICI neighbors first, then minimizes DCN
(cluster/rack/host) spread across slices.

Node labels consumed (stamped by labeler.py):
  cloud.google.com/gke-placement-group   opaque placement group id
  topology.gke.io/cluster|rack|host      DCN physical hierarchy
  topology.tpu.gke.io/slice              TPU slice id (pod name)
  topology.tpu.gke.io/coords             host origin in slice mesh, "x,y,z"
  cloud.google.com/gke-tpu-topology      slice mesh bounds, e.g. "4x4x4"
"""

from typing import Optional, Tuple

# A mismatch at the outermost hierarchy level costs DCN_FAR; each matching
# level divides by DCN_LEVEL_FACTOR (same envelope as the reference,
# schedule-daemon.py:66-70).  Every cross-slice distance additionally
# carries the DCN_MIN floor: without it, a cross-slice node in the same
# rack cost 1e6/100^3 = 1.0 — CHEAPER than 2 ICI hops — and the packer
# preferred hopping slices (= DCN traffic) over ICI neighbors.  DCN_MIN
# exceeds any intra-slice ICI path (largest slices are ~tens of hops),
# so ICI always wins; the hierarchy ordering rides on top additively.
DCN_FAR = 1_000_000.0
DCN_LEVEL_FACTOR = 100.0
DCN_MIN = 1_000.0

PLACEMENT_GROUP_LABEL = "cloud.google.com/gke-placement-group"
CLUSTER_LABEL = "topology.gke.io/cluster"
RACK_LABEL = "topology.gke.io/rack"
HOST_LABEL = "topology.gke.io/host"
SLICE_LABEL = "topology.tpu.gke.io/slice"
COORDS_LABEL = "topology.tpu.gke.io/coords"
TPU_TOPOLOGY_LABEL = "cloud.google.com/gke-tpu-topology"


def parse_coords(raw: Optional[str]) -> Optional[Tuple[int, ...]]:
    """'1,2,0' -> (1, 2, 0); None/garbage -> None."""
    if not raw:
        return None
    try:
        return tuple(int(p) for p in raw.replace("x", ",").split(","))
    except ValueError:
        return None


def parse_topology(raw: Optional[str]) -> Optional[Tuple[int, ...]]:
    """'4x4x4' -> (4, 4, 4)."""
    if not raw:
        return None
    try:
        return tuple(int(p) for p in raw.split("x"))
    except ValueError:
        return None


def node_topology_key(node_info: dict) -> tuple:
    """Sort key: DCN hierarchy, then slice, then ICI coordinates.

    Nodes missing the DCN labels sort as an empty key (the reference does
    the same and filters them out earlier, schedule-daemon.py:74-91).
    """
    labels = node_info["node_labels"]
    if not all(
        k in labels
        for k in (PLACEMENT_GROUP_LABEL, CLUSTER_LABEL, RACK_LABEL, HOST_LABEL)
    ):
        return ()
    key = (
        labels[PLACEMENT_GROUP_LABEL],
        labels[CLUSTER_LABEL],
        labels[RACK_LABEL],
        labels[HOST_LABEL],
    )
    slice_id = labels.get(SLICE_LABEL)
    coords = parse_coords(labels.get(COORDS_LABEL))
    if slice_id is not None and coords is not None:
        key += (slice_id, coords)
    return key


def ici_hop_distance(
    a: Tuple[int, ...], b: Tuple[int, ...], bounds: Optional[Tuple[int, ...]]
) -> float:
    """Torus hop distance between two ICI coordinates.

    With mesh ``bounds`` (wraparound links, standard on full TPU pod
    slices) each axis contributes min(|d|, bound - |d|) hops.
    """
    total = 0.0
    for axis in range(min(len(a), len(b))):
        d = abs(a[axis] - b[axis])
        if bounds is not None and axis < len(bounds) and bounds[axis] > 0:
            d = min(d, bounds[axis] - d)
        total += d
    return total


def node_topology_distance(node1: dict, node2: dict) -> float:
    """Distance between two nodes for the assignment objective.

    Same slice + both have coords → ICI torus hops (small, < DCN floor).
    Otherwise → DCN_MIN floor (so crossing slices always costs more than
    any ICI path) plus the hierarchical distance: DCN_FAR at the first
    differing level of (placement-group, cluster, rack, host), divided
    by DCN_LEVEL_FACTOR per matching level; bare DCN_MIN when all four
    match (co-located slices).
    """
    l1, l2 = node1["node_labels"], node2["node_labels"]
    slice1, slice2 = l1.get(SLICE_LABEL), l2.get(SLICE_LABEL)
    if slice1 is not None and slice1 == slice2:
        c1 = parse_coords(l1.get(COORDS_LABEL))
        c2 = parse_coords(l2.get(COORDS_LABEL))
        if c1 is not None and c2 is not None:
            bounds = parse_topology(l1.get(TPU_TOPOLOGY_LABEL))
            return ici_hop_distance(c1, c2, bounds)
        return 0.0

    k1, k2 = node_topology_key(node1)[:4], node_topology_key(node2)[:4]
    result = DCN_FAR
    for i in range(min(len(k1), len(k2))):
        if k1[i] != k2[i]:
            return DCN_MIN + result
        result /= DCN_LEVEL_FACTOR
    return DCN_MIN + (0.0 if k1 and k1 == k2 else result)
