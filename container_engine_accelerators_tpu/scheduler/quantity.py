"""Kubernetes resource-quantity parsing.

The reference leans on ``kubernetes.utils.quantity.parse_quantity``
(ref: gpudirect-tcpxo/topology-scheduler/schedule-daemon.py:23,106-108);
that package is not available here, so this is a small self-contained
parser for the quantity grammar the scheduler actually meets: plain
integers/decimals, the ``n``/``u``/``m`` sub-unit suffixes for CPU,
binary suffixes (Ki..Ei) and decimal suffixes (k..E) for memory.
Returns a float in base units (cores / bytes / counts).  An
unparseable quantity logs a warning and counts as 0 rather than
crashing the scheduling daemon on one malformed pod spec.
"""

import logging
from typing import Union

log = logging.getLogger(__name__)

_SUFFIXES = {
    "n": 1e-9,
    "u": 1e-6,
    "m": 1e-3,
    "k": 1e3,
    "M": 1e6,
    "G": 1e9,
    "T": 1e12,
    "P": 1e15,
    "E": 1e18,
    "Ki": 2**10,
    "Mi": 2**20,
    "Gi": 2**30,
    "Ti": 2**40,
    "Pi": 2**50,
    "Ei": 2**60,
}


def parse_quantity(value: Union[str, int, float, None]) -> float:
    """Parse a Kubernetes quantity ('100m', '1Gi', '2', 3) to a float."""
    if value is None:
        return 0.0
    if isinstance(value, (int, float)):
        return float(value)
    s = str(value).strip()
    if not s:
        return 0.0
    try:
        for suffix in sorted(_SUFFIXES, key=len, reverse=True):
            if s.endswith(suffix):
                return float(s[: -len(suffix)]) * _SUFFIXES[suffix]
        # Scientific notation (e.g. "1e3") and plain numbers.
        return float(s)
    except ValueError:
        log.warning("unparseable resource quantity %r, counting as 0", value)
        return 0.0
