"""Minimal Kubernetes API client (stdlib-only).

The reference uses the official ``kubernetes`` Python client
(ref: gpudirect-tcpxo/topology-scheduler/schedule-daemon.py:20-23,420-423);
that package is not available in this image, so this is a thin REST
client over ``urllib`` speaking the same API endpoints the scheduler
needs.  All resources are plain parsed-JSON dicts (the wire format),
which is also what the scheduling logic operates on — so tests inject a
fake ``transport`` and never need a cluster.

In-cluster config mirrors the official client's loader: API server from
``KUBERNETES_SERVICE_HOST``/``_PORT``, bearer token and CA from the
service-account mount.
"""

import json
import os
import ssl
import urllib.error
import urllib.request
from typing import Callable, Dict, List, Optional

SERVICE_ACCOUNT_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"

# transport(method, path, body_dict_or_None) -> parsed-JSON dict
Transport = Callable[[str, str, Optional[dict]], dict]


class ApiException(Exception):
    def __init__(self, status: int, reason: str, body: str = ""):
        super().__init__(f"HTTP {status}: {reason} {body[:200]}")
        self.status = status
        self.reason = reason
        self.body = body


def in_cluster_transport(
    host: Optional[str] = None,
    token_path: str = os.path.join(SERVICE_ACCOUNT_DIR, "token"),
    ca_path: str = os.path.join(SERVICE_ACCOUNT_DIR, "ca.crt"),
) -> Transport:
    """Build a transport using the pod's service-account credentials."""
    if host is None:
        host = "https://{}:{}".format(
            os.environ["KUBERNETES_SERVICE_HOST"],
            os.environ.get("KUBERNETES_SERVICE_PORT", "443"),
        )
    ctx = ssl.create_default_context(
        cafile=ca_path if os.path.exists(ca_path) else None
    )

    def transport(method: str, path: str, body: Optional[dict] = None) -> dict:
        token = ""
        if os.path.exists(token_path):  # re-read: tokens rotate
            with open(token_path) as f:
                token = f.read().strip()
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(host + path, data=data, method=method)
        if token:
            req.add_header("Authorization", "Bearer " + token)
        req.add_header("Accept", "application/json")
        if method == "PATCH":
            req.add_header("Content-Type", "application/strategic-merge-patch+json")
        elif data is not None:
            req.add_header("Content-Type", "application/json")
        try:
            with urllib.request.urlopen(req, context=ctx, timeout=60) as resp:
                return json.loads(resp.read().decode() or "{}")
        except urllib.error.HTTPError as e:
            raise ApiException(e.code, e.reason, e.read().decode(errors="replace"))
        except (urllib.error.URLError, OSError, TimeoutError) as e:
            # Transient network failure: surface as ApiException so the
            # daemon's catch-and-retry loop survives it (daemon.run_forever).
            raise ApiException(0, f"transport error: {e}")

    return transport


class CoreV1:
    """The CoreV1 surface the scheduler and labeler use."""

    def __init__(self, transport: Transport):
        self._t = transport

    def list_namespaces(self) -> List[dict]:
        return self._t("GET", "/api/v1/namespaces").get("items", [])

    def list_namespaced_pods(self, namespace: str) -> List[dict]:
        return self._t("GET", f"/api/v1/namespaces/{namespace}/pods").get(
            "items", []
        )

    def list_nodes(self) -> List[dict]:
        return self._t("GET", "/api/v1/nodes").get("items", [])

    def read_namespaced_pod(self, name: str, namespace: str) -> dict:
        return self._t("GET", f"/api/v1/namespaces/{namespace}/pods/{name}")

    def replace_namespaced_pod(self, name: str, namespace: str, pod: dict) -> dict:
        return self._t("PUT", f"/api/v1/namespaces/{namespace}/pods/{name}", pod)

    def patch_node_labels(self, name: str, labels: Dict[str, str]) -> dict:
        return self._t(
            "PATCH", f"/api/v1/nodes/{name}", {"metadata": {"labels": labels}}
        )

    def read_node(self, name: str) -> dict:
        return self._t("GET", f"/api/v1/nodes/{name}")

    def patch_node_taints(
        self, name: str, taints: List[dict],
        resource_version: Optional[str] = None,
    ) -> dict:
        """Replace the node's taint list wholesale.

        ``spec.taints`` is an ATOMIC list under strategic-merge-patch
        (it has no patchMergeKey), so this patch overwrites whatever is
        there — it does NOT merge per taint key.  Callers doing a
        read-modify-write must pass the ``metadata.resourceVersion``
        from their read: the API server then rejects the patch with 409
        Conflict if the node changed in between, instead of silently
        wiping a concurrently-added taint (e.g.
        ``node.kubernetes.io/not-ready`` from the node controller).
        """
        body: dict = {"spec": {"taints": taints}}
        if resource_version is not None:
            body["metadata"] = {"resourceVersion": resource_version}
        return self._t("PATCH", f"/api/v1/nodes/{name}", body)
