"""Node label daemon: GCE/TPU metadata → topology labels.

The reference daemon reads the ``physical_host`` instance attribute and
stamps ``topology.gke.io/{cluster,rack,host}``
(ref: gpudirect-tcpxo/topology-scheduler/label-nodes-daemon.py:24-55).
The TPU build stamps those same DCN labels plus the slice-local ICI
labels the scheduler's distance function consumes (topology.py):

  topology.tpu.gke.io/slice     TPU pod/slice id (``tpu-env`` TPU_NAME)
  topology.tpu.gke.io/coords    this host's chip-origin in the slice mesh
  cloud.google.com/gke-tpu-topology  slice bounds, e.g. ``4x4x4``

Coordinates derive from the slice topology and the host's worker id:
hosts tile the chip mesh in row-major order with a per-host sub-mesh
(2x2x1 for v4/v5p-style 4-chip hosts), so
``coords = unravel(worker_id, topology // host_bounds) * host_bounds``.

The metadata fetcher is injectable for tests; the real one hits the GCE
metadata server with the ``Metadata-Flavor: Google`` header.
"""

import logging
import time
import urllib.error
import urllib.request
from typing import Callable, Dict, Optional, Tuple

from container_engine_accelerators_tpu.scheduler.k8s import CoreV1
from container_engine_accelerators_tpu.scheduler.topology import (
    CLUSTER_LABEL,
    COORDS_LABEL,
    HOST_LABEL,
    RACK_LABEL,
    SLICE_LABEL,
    TPU_TOPOLOGY_LABEL,
    parse_topology,
)

log = logging.getLogger(__name__)

METADATA_BASE = "http://metadata.google.internal/computeMetadata/v1"
DEFAULT_HOST_BOUNDS = (2, 2, 1)  # chips per host on 4-chip TPU hosts
UPDATE_INTERVAL_S = 600.0

Fetcher = Callable[[str], Optional[str]]


def metadata_fetcher(base: str = METADATA_BASE) -> Fetcher:
    def fetch(path: str) -> Optional[str]:
        req = urllib.request.Request(
            base + path, headers={"Metadata-Flavor": "Google"}
        )
        try:
            with urllib.request.urlopen(req, timeout=10) as resp:
                return resp.read().decode()
        except (urllib.error.URLError, OSError) as e:
            log.warning("metadata fetch %s failed: %s", path, e)
            return None

    return fetch


def parse_tpu_env(raw: str) -> Dict[str, str]:
    """Parse the ``tpu-env`` attribute: ``KEY: 'value'`` per line."""
    out = {}
    for line in raw.splitlines():
        if ":" not in line:
            continue
        key, _, value = line.partition(":")
        out[key.strip()] = value.strip().strip("'\"")
    return out


def worker_coords(
    worker_id: int,
    topology: Tuple[int, ...],
    host_bounds: Tuple[int, ...] = DEFAULT_HOST_BOUNDS,
) -> Tuple[int, ...]:
    """Chip-origin of host ``worker_id`` tiling the slice mesh row-major."""
    grid = tuple(
        max(1, t // h) for t, h in zip(topology, host_bounds)
    )
    rem = worker_id
    idx = []
    for g in reversed(grid):
        idx.append(rem % g)
        rem //= g
    idx = tuple(reversed(idx))
    return tuple(i * h for i, h in zip(idx, host_bounds))


def compute_labels(fetch: Fetcher) -> Optional[Dict[str, str]]:
    """All labels derivable from the metadata server; None if no identity."""
    physical_host = fetch("/instance/attributes/physical_host")
    if physical_host is None:
        log.warning("physical host not found")
        return None
    parts = physical_host.strip().split("/")[1:]
    if len(parts) < 3:
        log.warning("malformed physical_host %r", physical_host)
        return None
    cluster, rack, host = parts[:3]
    labels = {
        CLUSTER_LABEL: cluster,
        RACK_LABEL: rack,
        HOST_LABEL: host,
    }

    tpu_env_raw = fetch("/instance/attributes/tpu-env")
    if tpu_env_raw:
        env = parse_tpu_env(tpu_env_raw)
        slice_id = env.get("TPU_NAME") or env.get("NODE_ID")
        topology_raw = env.get("TOPOLOGY")
        worker_raw = env.get("WORKER_ID") or env.get("AGENT_WORKER_NUMBER")
        if slice_id:
            labels[SLICE_LABEL] = slice_id
        topology = parse_topology(topology_raw)
        if topology is not None:
            labels[TPU_TOPOLOGY_LABEL] = topology_raw
            if worker_raw is not None and worker_raw.isdigit():
                coords = worker_coords(int(worker_raw), topology)
                labels[COORDS_LABEL] = ",".join(str(c) for c in coords)
        elif topology_raw:
            log.warning("malformed TOPOLOGY metadata %r, skipping ICI labels",
                        topology_raw)
    return labels


def update_node_labels(api: CoreV1, fetch: Fetcher) -> Optional[Dict[str, str]]:
    node_name = fetch("/instance/name")
    if node_name is None:
        log.warning("node name not found")
        return None
    labels = compute_labels(fetch)
    if labels is None:
        return None
    api.patch_node_labels(node_name.strip(), labels)
    log.info("updated labels on node %s: %s", node_name.strip(), labels)
    return labels


def run_forever(api: CoreV1, fetch: Optional[Fetcher] = None):
    fetch = fetch or metadata_fetcher()
    while True:
        log.info("starting node label update")
        update_node_labels(api, fetch)
        time.sleep(UPDATE_INTERVAL_S)
