"""Topology-aware scheduler for multi-host TPU jobs.

TPU-native analog of the reference's topology scheduler
(ref: gpudirect-tcpxo/topology-scheduler/schedule-daemon.py,
label-nodes-daemon.py): a label daemon stamps nodes with DCN topology
(cluster/rack/host) plus TPU slice/ICI-coordinate labels, and a
scheduling daemon places scheduling-gated job pods to minimize summed
topology distance — ICI hop distance within a slice, hierarchical DCN
distance across slices.
"""

from container_engine_accelerators_tpu.scheduler.daemon import (
    SchedulerDaemon,
    calculate_pods_assignment,
    find_pod_gates,
    find_schedulable_nodes,
    find_schedulable_pods,
)
from container_engine_accelerators_tpu.scheduler.topology import (
    node_topology_distance,
    node_topology_key,
)

__all__ = [
    "SchedulerDaemon",
    "calculate_pods_assignment",
    "find_pod_gates",
    "find_schedulable_nodes",
    "find_schedulable_pods",
    "node_topology_distance",
    "node_topology_key",
]
