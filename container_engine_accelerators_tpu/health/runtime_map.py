"""Map observed TPU runtime/driver errors onto the error-code registry.

The registry in health_checker.py is OUR contract (the Xid-number
analog) — libtpu does not publish a numeric fault table the way the
NVIDIA driver publishes Xids.  What the runtime actually surfaces to a
workload is an ``XlaRuntimeError`` (or libtpu log line) whose text
carries a gRPC-style status and a free-form message.  This module is
the bridge: classify a captured runtime error into a registry code, and
optionally report it into the event queue the health checker consumes
(``/var/run/tpu/events``, tpulib/sysfs.py) so a REAL on-chip fault
drives the same Unhealthy flow as an injected one.

The patterns below are grounded in errors captured on the attached
chip (see demo/tpu-error/hbm-oom/RESULTS.md for the recorded
transcripts) plus libtpu's documented status usage; anything
unrecognized maps to ``None`` rather than guessing a critical code.

Reference analog: the Xid demo proves the CUDA OOB write produces
Xid 31 in the driver's stream (demo/gpu-error/illegal-memory-access/
vectorAdd.cu:29-35, README); this is the same grounding exercise for
the TPU registry.
"""

import re
from typing import Optional, Tuple

from container_engine_accelerators_tpu.tpulib.sysfs import write_event_file

# Registry codes (health_checker.py docstring).
HBM_ECC = 48
ICI_LINK = 63
CORE_HANG = 72
BAD_HBM_ACCESS = 31
PROGRAM_ABORT = 13

# Ordered (pattern, code, critical) — first match wins.  Hardware-fault
# signatures come before resource/user errors so e.g. an "uncorrectable
# ECC" message inside a RESOURCE_EXHAUSTED wrapper still maps to 48.
_PATTERNS: Tuple[Tuple[str, int, bool], ...] = (
    # Uncorrectable memory faults — chip-fatal.
    (r"uncorrectable|double.?bit|ecc error", HBM_ECC, True),
    # Interconnect faults — chip- (and usually slice-) fatal.
    (r"ici\b.*(link|fail|fatal)|interconnect.*(error|down)", ICI_LINK, True),
    # Hangs: the runtime's deadline/watchdog trips while a program is
    # resident.  Includes the tunnel-visible form (DEADLINE_EXCEEDED on
    # an execute call).
    (r"watchdog|hang detected|deadline_exceeded.*execut", CORE_HANG, True),
    # Wild addressing inside a program.
    (r"(illegal|invalid).*(address|memory access)|out.of.bounds",
     BAD_HBM_ACCESS, True),
    # Resource exhaustion: a USER error (asked for more HBM than exists),
    # not a chip fault — the chip stays schedulable.  Captured on-chip:
    # "RESOURCE_EXHAUSTED: XLA:TPU compile permanent error. Ran out of
    # memory in memory space hbm ..." (RESULTS.md).
    (r"resource_exhausted|ran out of memory|out of memory|oom",
     PROGRAM_ABORT, False),
    # Generic program aborts / cancellations.  Anchored to the status
    # form ("ABORTED: ...") — a bare "aborted" also appears in infra
    # errors like "UNAVAILABLE: socket connection aborted", which are
    # not device-health signals.
    (r"\baborted:|internal: .*(abort|cancel)", PROGRAM_ABORT, False),
)


def classify(error_text: str) -> Optional[Tuple[int, bool]]:
    """(registry code, critical?) for a runtime error string, or None.

    None means "not a recognized device-health signal" — callers must
    NOT fabricate an event for it.
    """
    text = error_text.lower()
    for pattern, code, critical in _PATTERNS:
        if re.search(pattern, text):
            return code, critical
    return None


def report_runtime_error(
    error_text: str,
    device: Optional[str],
    events_dir: str = "/var/run/tpu/events",
) -> Optional[str]:
    """Classify and, if recognized, drop an event file into the queue.

    Returns the event path, or None when the error is not a health
    signal.  The write is atomic (tmpfile + rename), matching the queue
    contract in tpulib/sysfs.py.
    """
    got = classify(error_text)
    if got is None:
        return None
    code, _ = got
    return write_event_file(events_dir, code, device, error_text[:512])
