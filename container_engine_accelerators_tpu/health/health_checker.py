"""TPU health checker: error-event stream → Unhealthy devices.

TPU-native port of the reference's NVML Xid health checker
(ref: pkg/gpu/nvidia/health_check/health_checker.go:31-245).  The event
source is tpulib's error-event stream (driver/runtime fault queue) instead
of NVML's Xid events; the state machine is the same:

- only *critical* codes flip a device to Unhealthy; the default set plus
  any codes from node config / TPU_ERR_CONFIG (health_checker.go:40-62);
- an event with no device attribution marks ALL devices Unhealthy
  (health_checker.go:192-201);
- transitions are pushed into the manager's health queue, which
  ListAndWatch drains and re-announces to the kubelet
  (beta_plugin.go:39-54);
- RECOVERY (ours; the reference has no path back to Healthy): a device
  that has seen no further critical events for ``recovery_window_s``
  is re-announced Healthy through the same queue.  TPU faults are
  frequently transient at the node level — a runtime restart clears a
  TensorCore hang, a re-init clears most ICI link flaps — and without
  recovery a single blip permanently shrinks the node's allocatable
  count until a human deletes the pod.  Every fresh critical event
  re-stamps the quiescence clock, so a genuinely sick chip that keeps
  faulting never recovers — and a chip that re-faults shortly AFTER a
  recovery (load-triggered faults are invisible while nothing schedules
  on it) gets an exponentially escalating window (flap backoff), so it
  decays toward permanently-out rather than killing a workload per
  cycle.  Transition counts are exported through metrics/counters.py
  (``health.unhealthy``, ``health.recovered``, ``health.flap_backoff``).

TPU error code registry (ours; the Xid-number analog):
  48  HBM uncorrectable ECC error          (critical by default, like Xid 48)
  63  ICI link fatal error
  72  TensorCore hang / watchdog timeout
  31  invalid HBM memory access            (the Xid-31 fault-injection demo)
  13  program abort (user error)           (non-critical by default)
  80  host maintenance imminent            (non-critical by default; the
                                            maintenance watcher posts it —
                                            configure via TPU_ERR_CONFIG
                                            for proactive device drain)

The registry is a PROVISIONAL contract: libtpu publishes no numeric
fault table, so these codes are defined by this stack and grounded by
``health/runtime_map.py``, which classifies the error strings the
runtime actually raises (captured on-chip transcripts in
demo/tpu-error/hbm-oom/RESULTS.md) into registry codes and feeds the
same event queue.  Swapping in a future official libtpu event table
means updating runtime_map's patterns, not this state machine.
"""

import logging
import os
import threading
import time
from typing import Dict, Iterable, Optional, Set

from container_engine_accelerators_tpu.metrics import counters
from container_engine_accelerators_tpu.obs import histo, trace
from container_engine_accelerators_tpu.tpulib.types import TpuErrorEvent, TpuLib
from container_engine_accelerators_tpu.utils import faults
from container_engine_accelerators_tpu.utils.device import (
    HEALTHY,
    UNHEALTHY,
    Device,
)

log = logging.getLogger(__name__)

DEFAULT_CRITICAL_CODES = frozenset({48})
EVENT_WAIT_TIMEOUT_S = 5.0  # nvml.WaitForEvent(5000) analog
# Default quiescence window before an Unhealthy device is re-announced
# Healthy.  Chosen >> the event stream's own latency so a fault burst in
# flight can't race the recovery, and long enough that CrashLooping
# workloads on the sick chip have drained.  Tests pass tiny values.
DEFAULT_RECOVERY_WINDOW_S = 300.0
# Quiescence alone cannot see load-triggered faults: an unscheduled bad
# chip is quiet BECAUSE nothing touches it.  A re-fault within
# FLAP_RESET_FACTOR windows of a recovery therefore counts as a flap and
# doubles the next window (capped at 2**MAX_FLAP_DOUBLINGS = 64x, 300s →
# ~5.3h), so a chip that only breaks under traffic decays toward
# effectively-permanent Unhealthy instead of killing a workload every
# 300s forever.
FLAP_RESET_FACTOR = 4
MAX_FLAP_DOUBLINGS = 6

# External chip-fault injector (the NVML-Xid file analog): a path whose
# appended lines are fault/clear events from OUTSIDE this process —
# a sidecar health prober, an operator's kubectl exec, a chaos rig.
# Line grammar, one event per line (malformed lines are logged and
# skipped — the TPU_FAULT_SPEC rule):
#
#   fault <device> [code]     # code defaults to 48 (HBM ECC)
#   clear <device>            # external all-clear: recover NOW
#
# The checker polls the file on every event-loop wakeup (and via
# ``poll_fault_file`` for deterministic drivers like the fleet rig),
# byte-offset incremental with truncation/rotation detection.  A
# ``clear`` rides the normal quiescence-recovery path — same queue,
# same counters — it just expires the window immediately: an external
# "fixed it" must not invent a second recovery state machine.
FAULT_FILE_ENV = "TPU_CHIP_FAULT_FILE"


class TpuHealthChecker:
    def __init__(
        self,
        manager,
        lib: TpuLib,
        critical_codes: Optional[Iterable[int]] = None,
        recovery_window_s: Optional[float] = DEFAULT_RECOVERY_WINDOW_S,
        event_wait_timeout_s: float = EVENT_WAIT_TIMEOUT_S,
        fault_file: Optional[str] = None,
    ):
        self.manager = manager
        self.lib = lib
        self.critical_codes: Set[int] = set(DEFAULT_CRITICAL_CODES)
        self.critical_codes.update(critical_codes or [])
        self.event_wait_timeout_s = event_wait_timeout_s
        # External injector file (TPU_CHIP_FAULT_FILE): env-resolved so
        # fleet proc workers inherit the path with zero plumbing.
        self.fault_file = (fault_file if fault_file is not None
                           else os.environ.get(FAULT_FILE_ENV) or None)
        self._fault_file_pos = 0
        # None disables recovery (strict reference semantics: Unhealthy
        # is forever).
        self.recovery_window_s = recovery_window_s
        self._unhealthy_since: Dict[str, float] = {}
        # First fault of the current Unhealthy episode (NOT re-stamped
        # by repeat faults): the unhealthy→recovered latency histogram
        # measures the whole outage, not just the final quiet window.
        self._unhealthy_first: Dict[str, float] = {}
        self._recovered_at: Dict[str, float] = {}
        self._flaps: Dict[str, int] = {}
        self._mu = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        log.info(
            "starting TPU health checker; critical codes: %s",
            sorted(self.critical_codes),
        )
        self._thread = threading.Thread(
            target=self._listen_to_events, name="tpu-health", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2 * self.event_wait_timeout_s)

    # -- event loop ----------------------------------------------------------

    def _listen_to_events(self) -> None:
        while not self._stop.is_set():
            event = None
            try:
                faults.check("health.stream")
                event = self.lib.wait_for_event(self.event_wait_timeout_s)
            except Exception as e:
                # Keep monitoring alive across transient backend errors
                # (and injected ``health.stream`` faults), but back off
                # so a persistent failure can't spin the CPU.
                log.error("TPU event wait failed: %s; backing off", e)
                counters.inc("health.stream.errors")
                self._stop.wait(self.event_wait_timeout_s)
            if event is not None:
                self.catch_error(event)
            # The external injector file is polled on the same cadence
            # as the event stream — and like recovery below, it keeps
            # working while the stream is down: the injector is a
            # SECOND fault source, not a consumer of the first.
            self.poll_fault_file()
            # Recovery runs even while the event stream is down: an
            # outage of the *detector* must not pin devices Unhealthy.
            self.maybe_recover()

    def catch_error(self, event: TpuErrorEvent) -> None:
        """Decide which devices an event takes down
        (ref: health_checker.go:179-226).  Public so tests can feed
        synthetic events, like the reference's catchError tests.

        The whole decision is one span (``health.event``, histogram of
        the same name): event→unhealthy latency is the time from the
        stream handing us the event to the transitions being queued."""
        with trace.span("health.event", histogram="health.event",
                        code=event.code, device=event.device):
            self._catch_error(event)

    def _catch_error(self, event: TpuErrorEvent) -> None:
        if event.code not in self.critical_codes:
            log.info(
                "TPU error code %d is not critical; skipping (device=%s, %s)",
                event.code,
                event.device,
                event.message,
            )
            return
        if event.device is None:
            log.error(
                "critical TPU error %d with no device attribution: marking "
                "ALL devices unhealthy (%s)",
                event.code,
                event.message,
            )
            for name in list(self.manager.devices):
                self._mark_unhealthy(name)
            return
        if event.device not in self.manager.devices:
            log.warning(
                "critical TPU error %d for unknown device %r; ignoring",
                event.code,
                event.device,
            )
            return
        log.error(
            "critical TPU error %d on %s: %s",
            event.code,
            event.device,
            event.message,
        )
        self._mark_unhealthy(event.device)

    def _mark_unhealthy(self, name: str) -> None:
        now = time.monotonic()
        with self._mu:
            # Re-stamp on EVERY critical event: a device that keeps
            # faulting keeps pushing its quiescence window out.
            self._unhealthy_since[name] = now
            self._unhealthy_first.setdefault(name, now)
            recovered_at = self._recovered_at.pop(name, None)
            if recovered_at is not None and self.recovery_window_s:
                window = self._window_for(name)
                if now - recovered_at < FLAP_RESET_FACTOR * window:
                    # Broke again soon after we re-announced it Healthy:
                    # likely a load-triggered fault that quiescence can't
                    # see.  Escalate the next window.
                    self._flaps[name] = min(
                        self._flaps.get(name, 0) + 1, MAX_FLAP_DOUBLINGS
                    )
                    counters.inc("health.flap_backoff")
                else:
                    self._flaps.pop(name, None)  # stayed good: forgiven
        counters.inc("health.unhealthy")
        self.manager.health_events.put(Device(id=name, health=UNHEALTHY))

    def _window_for(self, name: str) -> float:
        """Effective quiescence window: doubled per recorded flap."""
        return self.recovery_window_s * (2 ** self._flaps.get(name, 0))

    # -- external injector file (TPU_CHIP_FAULT_FILE) ------------------------

    def poll_fault_file(self) -> int:
        """Consume new complete lines from the injector file; returns
        the number of events applied.  Public so deterministic drivers
        (the fleet rig's per-round pump) can poll without the listener
        thread.  A missing file is 'no injector yet', never an error;
        a file that SHRANK was truncated/rotated and is re-read from
        the top (the new incarnation's events must not be skipped)."""
        path = self.fault_file
        if not path:
            return 0
        try:
            size = os.stat(path).st_size
        except OSError:
            return 0
        if size < self._fault_file_pos:
            self._fault_file_pos = 0
        if size == self._fault_file_pos:
            return 0
        try:
            with open(path, "rb") as f:
                f.seek(self._fault_file_pos)
                blob = f.read(size - self._fault_file_pos)
        except OSError as e:
            log.error("chip-fault file %s unreadable: %s", path, e)
            return 0
        # Only complete lines are consumed: an injector caught
        # mid-write leaves its partial tail for the next poll.
        consumed = blob.rfind(b"\n") + 1
        if consumed == 0:
            return 0
        self._fault_file_pos += consumed
        applied = 0
        for raw in blob[:consumed].decode("utf-8", "replace").splitlines():
            if self._apply_fault_line(raw.strip()):
                applied += 1
        return applied

    def _apply_fault_line(self, line: str) -> bool:
        if not line or line.startswith("#"):
            return False
        tokens = line.split()
        kind = tokens[0].lower()
        try:
            if kind == "fault" and 2 <= len(tokens) <= 3:
                code = int(tokens[2]) if len(tokens) == 3 else 48
                counters.inc("health.fault_file.events")
                trace.event("health.fault_file", kind="fault",
                            device=tokens[1], code=code)
                self.catch_error(TpuErrorEvent(
                    code=code, device=tokens[1],
                    message="injected via chip-fault file"))
                return True
            if kind == "clear" and len(tokens) == 2:
                counters.inc("health.fault_file.events")
                trace.event("health.fault_file", kind="clear",
                            device=tokens[1])
                self.clear_device(tokens[1])
                return True
            raise ValueError("want 'fault <dev> [code]' or "
                             "'clear <dev>'")
        except ValueError as e:
            # The TPU_FAULT_SPEC rule: a malformed injector line must
            # never take the health checker down.
            counters.inc("health.fault_file.malformed")
            log.error("ignoring malformed chip-fault line %r: %s",
                      line, e)
            return False

    def clear_device(self, name: str) -> int:
        """External all-clear for one device: expire its quiescence
        window NOW and run the normal recovery sweep — same queue,
        same ``health.recovered`` accounting, no second state machine.
        The flap history is forgiven too: an operator's explicit clear
        asserts the cause is FIXED, which is exactly the evidence the
        flap-backoff escalation lacks.  Returns devices recovered."""
        with self._mu:
            if name not in self._unhealthy_since:
                return 0
            self._unhealthy_since[name] = float("-inf")
            self._flaps.pop(name, None)
        if not self.recovery_window_s:
            # Recovery disabled (strict reference semantics): even an
            # external clear must not re-announce — maybe_recover
            # would refuse, so say so instead of silently no-opping.
            log.warning("chip-fault clear for %s ignored: recovery "
                        "is disabled", name)
            return 0
        return self.maybe_recover()

    # -- recovery ------------------------------------------------------------

    def maybe_recover(self, now: Optional[float] = None) -> int:
        """Re-announce devices whose quiescence window has passed.

        Called from the event loop every wakeup; public so tests (and
        operators via a debug hook) can drive it deterministically.
        Returns the number of devices recovered this pass.
        """
        # Falsy (None or 0) means disabled — 0 must never mean "recover
        # instantly": the CLI documents 0 as off, and an accidental 0
        # would silently defeat health monitoring.
        if not self.recovery_window_s:
            return 0
        now = time.monotonic() if now is None else now
        recovered = []
        with self._mu:
            for name, since in list(self._unhealthy_since.items()):
                window = self._window_for(name)
                if now - since < window:
                    continue
                del self._unhealthy_since[name]
                first = self._unhealthy_first.pop(name, since)
                self._recovered_at[name] = now
                recovered.append((name, window, now - first))
        announced = 0
        for name, window, outage_s in recovered:
            if name not in self.manager.devices:
                # Hotplug/repartition removed it while Unhealthy; there
                # is nothing to re-announce.
                log.info("device %s vanished while unhealthy; dropping", name)
                continue
            log.warning(
                "device %s quiet for %.0fs after critical fault: "
                "re-announcing Healthy", name, window,
            )
            counters.inc("health.recovered")
            # Whole-episode outage latency (first fault → re-announce);
            # the marker span correlates it with the rest of the trace.
            histo.observe("health.recovery", outage_s)
            trace.event("health.recover", device=name,
                        outage_s=round(outage_s, 3), window_s=window)
            self.manager.health_events.put(Device(id=name, health=HEALTHY))
            announced += 1
        return announced
