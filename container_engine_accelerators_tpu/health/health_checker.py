"""TPU health checker: error-event stream → Unhealthy devices.

TPU-native port of the reference's NVML Xid health checker
(ref: pkg/gpu/nvidia/health_check/health_checker.go:31-245).  The event
source is tpulib's error-event stream (driver/runtime fault queue) instead
of NVML's Xid events; the state machine is the same:

- only *critical* codes flip a device to Unhealthy; the default set plus
  any codes from node config / TPU_ERR_CONFIG (health_checker.go:40-62);
- an event with no device attribution marks ALL devices Unhealthy
  (health_checker.go:192-201);
- transitions are pushed into the manager's health queue, which
  ListAndWatch drains and re-announces to the kubelet
  (beta_plugin.go:39-54);
- RECOVERY (ours; the reference has no path back to Healthy): a device
  that has seen no further critical events for ``recovery_window_s``
  is re-announced Healthy through the same queue.  TPU faults are
  frequently transient at the node level — a runtime restart clears a
  TensorCore hang, a re-init clears most ICI link flaps — and without
  recovery a single blip permanently shrinks the node's allocatable
  count until a human deletes the pod.  Every fresh critical event
  re-stamps the quiescence clock, so a genuinely sick chip that keeps
  faulting never recovers — and a chip that re-faults shortly AFTER a
  recovery (load-triggered faults are invisible while nothing schedules
  on it) gets an exponentially escalating window (flap backoff), so it
  decays toward permanently-out rather than killing a workload per
  cycle.  Transition counts are exported through metrics/counters.py
  (``health.unhealthy``, ``health.recovered``, ``health.flap_backoff``).

TPU error code registry (ours; the Xid-number analog):
  48  HBM uncorrectable ECC error          (critical by default, like Xid 48)
  63  ICI link fatal error
  72  TensorCore hang / watchdog timeout
  31  invalid HBM memory access            (the Xid-31 fault-injection demo)
  13  program abort (user error)           (non-critical by default)
  80  host maintenance imminent            (non-critical by default; the
                                            maintenance watcher posts it —
                                            configure via TPU_ERR_CONFIG
                                            for proactive device drain)

The registry is a PROVISIONAL contract: libtpu publishes no numeric
fault table, so these codes are defined by this stack and grounded by
``health/runtime_map.py``, which classifies the error strings the
runtime actually raises (captured on-chip transcripts in
demo/tpu-error/hbm-oom/RESULTS.md) into registry codes and feeds the
same event queue.  Swapping in a future official libtpu event table
means updating runtime_map's patterns, not this state machine.
"""

import logging
import threading
import time
from typing import Dict, Iterable, Optional, Set

from container_engine_accelerators_tpu.metrics import counters
from container_engine_accelerators_tpu.obs import histo, trace
from container_engine_accelerators_tpu.tpulib.types import TpuErrorEvent, TpuLib
from container_engine_accelerators_tpu.utils import faults
from container_engine_accelerators_tpu.utils.device import (
    HEALTHY,
    UNHEALTHY,
    Device,
)

log = logging.getLogger(__name__)

DEFAULT_CRITICAL_CODES = frozenset({48})
EVENT_WAIT_TIMEOUT_S = 5.0  # nvml.WaitForEvent(5000) analog
# Default quiescence window before an Unhealthy device is re-announced
# Healthy.  Chosen >> the event stream's own latency so a fault burst in
# flight can't race the recovery, and long enough that CrashLooping
# workloads on the sick chip have drained.  Tests pass tiny values.
DEFAULT_RECOVERY_WINDOW_S = 300.0
# Quiescence alone cannot see load-triggered faults: an unscheduled bad
# chip is quiet BECAUSE nothing touches it.  A re-fault within
# FLAP_RESET_FACTOR windows of a recovery therefore counts as a flap and
# doubles the next window (capped at 2**MAX_FLAP_DOUBLINGS = 64x, 300s →
# ~5.3h), so a chip that only breaks under traffic decays toward
# effectively-permanent Unhealthy instead of killing a workload every
# 300s forever.
FLAP_RESET_FACTOR = 4
MAX_FLAP_DOUBLINGS = 6


class TpuHealthChecker:
    def __init__(
        self,
        manager,
        lib: TpuLib,
        critical_codes: Optional[Iterable[int]] = None,
        recovery_window_s: Optional[float] = DEFAULT_RECOVERY_WINDOW_S,
        event_wait_timeout_s: float = EVENT_WAIT_TIMEOUT_S,
    ):
        self.manager = manager
        self.lib = lib
        self.critical_codes: Set[int] = set(DEFAULT_CRITICAL_CODES)
        self.critical_codes.update(critical_codes or [])
        self.event_wait_timeout_s = event_wait_timeout_s
        # None disables recovery (strict reference semantics: Unhealthy
        # is forever).
        self.recovery_window_s = recovery_window_s
        self._unhealthy_since: Dict[str, float] = {}
        # First fault of the current Unhealthy episode (NOT re-stamped
        # by repeat faults): the unhealthy→recovered latency histogram
        # measures the whole outage, not just the final quiet window.
        self._unhealthy_first: Dict[str, float] = {}
        self._recovered_at: Dict[str, float] = {}
        self._flaps: Dict[str, int] = {}
        self._mu = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        log.info(
            "starting TPU health checker; critical codes: %s",
            sorted(self.critical_codes),
        )
        self._thread = threading.Thread(
            target=self._listen_to_events, name="tpu-health", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2 * self.event_wait_timeout_s)

    # -- event loop ----------------------------------------------------------

    def _listen_to_events(self) -> None:
        while not self._stop.is_set():
            event = None
            try:
                faults.check("health.stream")
                event = self.lib.wait_for_event(self.event_wait_timeout_s)
            except Exception as e:
                # Keep monitoring alive across transient backend errors
                # (and injected ``health.stream`` faults), but back off
                # so a persistent failure can't spin the CPU.
                log.error("TPU event wait failed: %s; backing off", e)
                counters.inc("health.stream.errors")
                self._stop.wait(self.event_wait_timeout_s)
            if event is not None:
                self.catch_error(event)
            # Recovery runs even while the event stream is down: an
            # outage of the *detector* must not pin devices Unhealthy.
            self.maybe_recover()

    def catch_error(self, event: TpuErrorEvent) -> None:
        """Decide which devices an event takes down
        (ref: health_checker.go:179-226).  Public so tests can feed
        synthetic events, like the reference's catchError tests.

        The whole decision is one span (``health.event``, histogram of
        the same name): event→unhealthy latency is the time from the
        stream handing us the event to the transitions being queued."""
        with trace.span("health.event", histogram="health.event",
                        code=event.code, device=event.device):
            self._catch_error(event)

    def _catch_error(self, event: TpuErrorEvent) -> None:
        if event.code not in self.critical_codes:
            log.info(
                "TPU error code %d is not critical; skipping (device=%s, %s)",
                event.code,
                event.device,
                event.message,
            )
            return
        if event.device is None:
            log.error(
                "critical TPU error %d with no device attribution: marking "
                "ALL devices unhealthy (%s)",
                event.code,
                event.message,
            )
            for name in list(self.manager.devices):
                self._mark_unhealthy(name)
            return
        if event.device not in self.manager.devices:
            log.warning(
                "critical TPU error %d for unknown device %r; ignoring",
                event.code,
                event.device,
            )
            return
        log.error(
            "critical TPU error %d on %s: %s",
            event.code,
            event.device,
            event.message,
        )
        self._mark_unhealthy(event.device)

    def _mark_unhealthy(self, name: str) -> None:
        now = time.monotonic()
        with self._mu:
            # Re-stamp on EVERY critical event: a device that keeps
            # faulting keeps pushing its quiescence window out.
            self._unhealthy_since[name] = now
            self._unhealthy_first.setdefault(name, now)
            recovered_at = self._recovered_at.pop(name, None)
            if recovered_at is not None and self.recovery_window_s:
                window = self._window_for(name)
                if now - recovered_at < FLAP_RESET_FACTOR * window:
                    # Broke again soon after we re-announced it Healthy:
                    # likely a load-triggered fault that quiescence can't
                    # see.  Escalate the next window.
                    self._flaps[name] = min(
                        self._flaps.get(name, 0) + 1, MAX_FLAP_DOUBLINGS
                    )
                    counters.inc("health.flap_backoff")
                else:
                    self._flaps.pop(name, None)  # stayed good: forgiven
        counters.inc("health.unhealthy")
        self.manager.health_events.put(Device(id=name, health=UNHEALTHY))

    def _window_for(self, name: str) -> float:
        """Effective quiescence window: doubled per recorded flap."""
        return self.recovery_window_s * (2 ** self._flaps.get(name, 0))

    # -- recovery ------------------------------------------------------------

    def maybe_recover(self, now: Optional[float] = None) -> int:
        """Re-announce devices whose quiescence window has passed.

        Called from the event loop every wakeup; public so tests (and
        operators via a debug hook) can drive it deterministically.
        Returns the number of devices recovered this pass.
        """
        # Falsy (None or 0) means disabled — 0 must never mean "recover
        # instantly": the CLI documents 0 as off, and an accidental 0
        # would silently defeat health monitoring.
        if not self.recovery_window_s:
            return 0
        now = time.monotonic() if now is None else now
        recovered = []
        with self._mu:
            for name, since in list(self._unhealthy_since.items()):
                window = self._window_for(name)
                if now - since < window:
                    continue
                del self._unhealthy_since[name]
                first = self._unhealthy_first.pop(name, since)
                self._recovered_at[name] = now
                recovered.append((name, window, now - first))
        announced = 0
        for name, window, outage_s in recovered:
            if name not in self.manager.devices:
                # Hotplug/repartition removed it while Unhealthy; there
                # is nothing to re-announce.
                log.info("device %s vanished while unhealthy; dropping", name)
                continue
            log.warning(
                "device %s quiet for %.0fs after critical fault: "
                "re-announcing Healthy", name, window,
            )
            counters.inc("health.recovered")
            # Whole-episode outage latency (first fault → re-announce);
            # the marker span correlates it with the rest of the trace.
            histo.observe("health.recovery", outage_s)
            trace.event("health.recover", device=name,
                        outage_s=round(outage_s, 3), window_s=window)
            self.manager.health_events.put(Device(id=name, health=HEALTHY))
            announced += 1
        return announced
