"""TPU health checker: error-event stream → Unhealthy devices.

TPU-native port of the reference's NVML Xid health checker
(ref: pkg/gpu/nvidia/health_check/health_checker.go:31-245).  The event
source is tpulib's error-event stream (driver/runtime fault queue) instead
of NVML's Xid events; the state machine is the same:

- only *critical* codes flip a device to Unhealthy; the default set plus
  any codes from node config / TPU_ERR_CONFIG (health_checker.go:40-62);
- an event with no device attribution marks ALL devices Unhealthy
  (health_checker.go:192-201);
- transitions are pushed into the manager's health queue, which
  ListAndWatch drains and re-announces to the kubelet
  (beta_plugin.go:39-54).

TPU error code registry (ours; the Xid-number analog):
  48  HBM uncorrectable ECC error          (critical by default, like Xid 48)
  63  ICI link fatal error
  72  TensorCore hang / watchdog timeout
  31  invalid HBM memory access            (the Xid-31 fault-injection demo)
  13  program abort (user error)           (non-critical by default)
  80  host maintenance imminent            (non-critical by default; the
                                            maintenance watcher posts it —
                                            configure via TPU_ERR_CONFIG
                                            for proactive device drain)

The registry is a PROVISIONAL contract: libtpu publishes no numeric
fault table, so these codes are defined by this stack and grounded by
``health/runtime_map.py``, which classifies the error strings the
runtime actually raises (captured on-chip transcripts in
demo/tpu-error/hbm-oom/RESULTS.md) into registry codes and feeds the
same event queue.  Swapping in a future official libtpu event table
means updating runtime_map's patterns, not this state machine.
"""

import logging
import threading
from typing import Iterable, Optional, Set

from container_engine_accelerators_tpu.tpulib.types import TpuErrorEvent, TpuLib
from container_engine_accelerators_tpu.utils.device import UNHEALTHY, Device

log = logging.getLogger(__name__)

DEFAULT_CRITICAL_CODES = frozenset({48})
EVENT_WAIT_TIMEOUT_S = 5.0  # nvml.WaitForEvent(5000) analog


class TpuHealthChecker:
    def __init__(
        self,
        manager,
        lib: TpuLib,
        critical_codes: Optional[Iterable[int]] = None,
    ):
        self.manager = manager
        self.lib = lib
        self.critical_codes: Set[int] = set(DEFAULT_CRITICAL_CODES)
        self.critical_codes.update(critical_codes or [])
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        log.info(
            "starting TPU health checker; critical codes: %s",
            sorted(self.critical_codes),
        )
        self._thread = threading.Thread(
            target=self._listen_to_events, name="tpu-health", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2 * EVENT_WAIT_TIMEOUT_S)

    # -- event loop ----------------------------------------------------------

    def _listen_to_events(self) -> None:
        while not self._stop.is_set():
            try:
                event = self.lib.wait_for_event(EVENT_WAIT_TIMEOUT_S)
            except Exception as e:
                # Keep monitoring alive across transient backend errors, but
                # back off so a persistent failure can't spin the CPU.
                log.error("TPU event wait failed: %s; backing off", e)
                self._stop.wait(EVENT_WAIT_TIMEOUT_S)
                continue
            if event is None:
                continue
            self.catch_error(event)

    def catch_error(self, event: TpuErrorEvent) -> None:
        """Decide which devices an event takes down
        (ref: health_checker.go:179-226).  Public so tests can feed
        synthetic events, like the reference's catchError tests."""
        if event.code not in self.critical_codes:
            log.info(
                "TPU error code %d is not critical; skipping (device=%s, %s)",
                event.code,
                event.device,
                event.message,
            )
            return
        if event.device is None:
            log.error(
                "critical TPU error %d with no device attribution: marking "
                "ALL devices unhealthy (%s)",
                event.code,
                event.message,
            )
            for name in list(self.manager.devices):
                self._mark_unhealthy(name)
            return
        if event.device not in self.manager.devices:
            log.warning(
                "critical TPU error %d for unknown device %r; ignoring",
                event.code,
                event.device,
            )
            return
        log.error(
            "critical TPU error %d on %s: %s",
            event.code,
            event.device,
            event.message,
        )
        self._mark_unhealthy(event.device)

    def _mark_unhealthy(self, name: str) -> None:
        self.manager.health_events.put(Device(id=name, health=UNHEALTHY))
