"""Host-maintenance watcher: drain TPU nodes BEFORE the host goes away.

Beyond the reference's scope (GPUs there fail reactively via Xids), but
a first-class TPU operational concern: Cloud TPU hosts publish upcoming
maintenance through the GCE metadata server
(``/instance/maintenance-event`` → ``NONE`` /
``MIGRATE_ON_HOST_MAINTENANCE`` / ``TERMINATE_ON_HOST_MAINTENANCE``),
and a TPU slice cannot live-migrate — a terminate event means every
chip on this host will vanish.  Reacting only after the fact turns into
the health checker's reactive flow; this watcher converts the advance
notice into a proactive drain:

1. taint the node ``google.com/tpu-maintenance=<event>:NoSchedule`` so
   the scheduler stops placing new TPU pods here;
2. drop a code-80 event into the health queue
   (``/var/run/tpu/events``).  Code 80 is informational by default —
   add ``80`` to ``TPU_ERR_CONFIG`` to also flip this node's devices
   Unhealthy ahead of the window (full drain semantics).

When the event clears back to ``NONE`` the taint is removed, so a
migrated (non-TPU-impacting) window heals without operator action.
"""

import logging
import time
from typing import Callable, List, Optional

from container_engine_accelerators_tpu.obs import trace
from container_engine_accelerators_tpu.scheduler.k8s import ApiException
from container_engine_accelerators_tpu.tpulib.sysfs import write_event_file
from container_engine_accelerators_tpu.utils import faults

log = logging.getLogger(__name__)

MAINTENANCE_CODE = 80
TAINT_KEY = "google.com/tpu-maintenance"
METADATA_PATH = "/instance/maintenance-event"
DEFAULT_INTERVAL_S = 60.0
DEFAULT_EVENTS_DIR = "/var/run/tpu/events"

Fetcher = Callable[[str], Optional[str]]


def current_event(fetch: Fetcher) -> Optional[str]:
    """The pending maintenance event, or None when NONE/unreadable."""
    raw = fetch(METADATA_PATH)
    if raw is None:
        return None
    value = raw.strip()
    return value if value and value != "NONE" else None


def _with_taint(taints: List[dict], event: str) -> List[dict]:
    out = [t for t in taints if t.get("key") != TAINT_KEY]
    out.append({"key": TAINT_KEY, "value": event, "effect": "NoSchedule"})
    return out


def _without_taint(taints: List[dict]) -> List[dict]:
    return [t for t in taints if t.get("key") != TAINT_KEY]


_CONFLICT_RETRIES = 3


def reconcile(
    api,
    node_name: str,
    fetch: Fetcher,
    events_dir: str = DEFAULT_EVENTS_DIR,
) -> Optional[str]:
    """One pass: read metadata, converge the node taint, emit the event.

    The taint update is a read-modify-write of the FULL taint list
    (``spec.taints`` is atomic under strategic merge — see
    ``patch_node_taints``), so each write carries the read's
    ``resourceVersion`` and retries on 409 Conflict: a taint added
    concurrently by another controller between our read and patch must
    re-enter the list we send, not get silently wiped.  Fault site
    ``k8s.patch`` fires before each patch; its ``conflict`` mode
    (``k8s.patch:conflict@1``) exercises this exact retry loop from a
    chaos spec.

    Returns the active maintenance event (None when clear).
    """
    event = current_event(fetch)
    for attempt in range(_CONFLICT_RETRIES):
        node = api.read_node(node_name)
        taints = (node.get("spec") or {}).get("taints") or []
        rv = (node.get("metadata") or {}).get("resourceVersion")
        current = next(
            (t.get("value") for t in taints if t.get("key") == TAINT_KEY),
            None,
        )
        try:
            if event and current != event:
                # New maintenance notice OR an escalation (e.g. MIGRATE
                # -> TERMINATE) while already tainted: converge the
                # taint value and post a fresh event — consumers
                # selecting on TERMINATE must see the escalation, not
                # the stale first notice.
                with trace.span("k8s.patch", histogram="k8s.patch",
                                node=node_name, attempt=attempt):
                    faults.check("k8s.patch")
                    api.patch_node_taints(
                        node_name, _with_taint(taints, event),
                        resource_version=rv,
                    )
                write_event_file(
                    events_dir, MAINTENANCE_CODE, None,
                    f"host maintenance imminent: {event}",
                )
                log.warning(
                    "maintenance %s: tainted node %s and posted code %d",
                    event, node_name, MAINTENANCE_CODE,
                )
            elif not event and current is not None:
                with trace.span("k8s.patch", histogram="k8s.patch",
                                node=node_name, attempt=attempt):
                    faults.check("k8s.patch")
                    api.patch_node_taints(
                        node_name, _without_taint(taints),
                        resource_version=rv,
                    )
                log.info("maintenance cleared: untainted node %s", node_name)
        except (ApiException, faults.FaultInjectedError) as e:
            # An injected InjectedConflict carries status=409 just like
            # a real stale-resourceVersion rejection; both retry here.
            if getattr(e, "status", None) == 409 \
                    and attempt < _CONFLICT_RETRIES - 1:
                log.info("taint update conflicted (409); re-reading node")
                continue
            raise
        break
    return event


def run_forever(
    api,
    node_name: str,
    fetch: Fetcher,
    interval_s: float = DEFAULT_INTERVAL_S,
    events_dir: str = DEFAULT_EVENTS_DIR,
):
    while True:
        try:
            reconcile(api, node_name, fetch, events_dir)
        except Exception as e:  # noqa: BLE001 — keep the watcher alive
            log.error("maintenance reconcile failed: %s", e)
        time.sleep(interval_s)
