"""Public re-exports for the health package."""
from container_engine_accelerators_tpu.health.health_checker import (
    TpuHealthChecker,
    DEFAULT_CRITICAL_CODES,
    DEFAULT_RECOVERY_WINDOW_S,
)

__all__ = [
    "TpuHealthChecker",
    "DEFAULT_CRITICAL_CODES",
    "DEFAULT_RECOVERY_WINDOW_S",
]
