"""Core-sharing runtime gate — the isMpsHealthy analog.

The reference refuses to serve MPS-shared GPUs until it has proven the
co-tenancy mechanism is alive: it execs ``mps-control`` and checks the
daemon answers (ref: pkg/gpu/nvidia/manager.go:376-386).  TPU
core-sharing has no control daemon — enforcement lives in the libtpu
that every co-tenant container loads, keyed off the env contract the
plugin injects (``TPU_VISIBLE_DEVICES`` + ``TPU_CORE_PERCENTAGE`` /
``TPU_HBM_LIMIT_BYTES``).  So "is the mechanism live" becomes: the
libtpu the plugin mounts into containers must (a) exist on the node and
(b) actually consume the visibility env — probed by scanning the shared
object for the env-var name, which libtpu embeds as a string constant.
Without that, the env contract is decoration and every co-tenant would
silently see (and could OOM) the whole chip; the gate refuses instead.

Verification runs in full at manager start, and cheaply (stat
comparison) on every Allocate so a driver re-install or wiped host
directory mid-flight stops handing out shared devices.

KNOWN LIMITATION (stated contract, VERDICT round 2): the probe is a
string scan, so a stripped or unusually-built libtpu that *does*
enforce visibility would be refused (fail-closed, safe), while a
hypothetical build embedding the string without enforcing it would be
admitted (fail-open, undetectable from the node agent — actual
enforcement happens inside the tenant's own libtpu at runtime).  This
mirrors the reference's trust model: isMpsHealthy proves the MPS
daemon ANSWERS, not that it partitions correctly
(manager.go:376-386).  The contract is documented in
cmd/device-plugin.yaml and README §sharing.
"""

import logging
import os
from typing import List, Optional, Tuple

from container_engine_accelerators_tpu.utils.device import Mount

log = logging.getLogger(__name__)

# The env libtpu consults to restrict a process to its assigned chips —
# the enforcement half of the sharing contract.  Present as a literal
# string in any libtpu that supports co-tenancy.
VISIBILITY_ENV_MARKER = b"TPU_VISIBLE_DEVICES"

# Relative locations of libtpu under the driver-install mount
# (libtpu-installer/ubuntu/entrypoint.sh:82-88 ships lib64/libtpu.so).
_LIBTPU_RELPATHS = ("lib64/libtpu.so", "libtpu.so")

_SCAN_CHUNK = 1 << 20


class CoreSharingGateError(RuntimeError):
    """The co-tenancy mechanism is not enforceable on this node."""


class CoreSharingGate:
    def __init__(self, mount_paths: List[Mount]):
        self.mount_paths = mount_paths
        # (path, size, mtime_ns) of the verified libtpu; None = unverified.
        self._verified: Optional[Tuple[str, int, int]] = None

    def find_libtpu(self) -> Optional[str]:
        for mount in self.mount_paths:
            for rel in _LIBTPU_RELPATHS:
                path = os.path.join(mount.host_path, rel)
                if os.path.isfile(path):
                    return path
        return None

    def verify(self) -> None:
        """Full check; raises CoreSharingGateError when unenforceable."""
        path = self.find_libtpu()
        if path is None:
            raise CoreSharingGateError(
                "core-sharing requires libtpu on the node (searched "
                f"{[m.host_path for m in self.mount_paths]}); the installer "
                "DaemonSet has not delivered it"
            )
        st = os.stat(path)
        if st.st_size == 0:
            raise CoreSharingGateError(
                f"core-sharing gate: {path} is empty; broken install"
            )
        if not self._scan_for_marker(path):
            raise CoreSharingGateError(
                f"core-sharing gate: {path} does not consume "
                f"{VISIBILITY_ENV_MARKER.decode()}; this libtpu cannot "
                "enforce co-tenant chip visibility — refusing to advertise "
                "shared devices"
            )
        self._verified = (path, st.st_size, st.st_mtime_ns)
        log.info("core-sharing gate: %s verified enforceable", path)

    def check_allocatable(self) -> None:
        """Cheap per-Allocate re-check; full re-verify when the install
        changed underneath us.  Raises ValueError so the service maps it
        onto the allocation-rejection path."""
        try:
            if self._verified is not None:
                path, size, mtime_ns = self._verified
                st = os.stat(path)
                if (st.st_size, st.st_mtime_ns) == (size, mtime_ns):
                    return
            self.verify()
        except (OSError, CoreSharingGateError) as e:
            self._verified = None
            raise ValueError(
                f"core-sharing co-tenancy mechanism not enforceable: {e}"
            )

    def _scan_for_marker(self, path: str) -> bool:
        """Stream the .so looking for the visibility-env string (chunked
        with overlap so a marker spanning a chunk boundary still hits)."""
        overlap = len(VISIBILITY_ENV_MARKER) - 1
        tail = b""
        try:
            with open(path, "rb") as f:
                while True:
                    chunk = f.read(_SCAN_CHUNK)
                    if not chunk:
                        return False
                    if VISIBILITY_ENV_MARKER in tail + chunk:
                        return True
                    tail = chunk[-overlap:]
        except OSError as e:
            raise CoreSharingGateError(f"core-sharing gate: cannot read {path}: {e}")
