"""Public re-exports for the sharing package."""
from container_engine_accelerators_tpu.sharing.sharing import (
    SharingStrategy,
    is_virtual_device_id,
    validate_request,
    virtual_to_physical_device_id,
    virtual_device_ids,
)

__all__ = [
    "SharingStrategy",
    "is_virtual_device_id",
    "validate_request",
    "virtual_to_physical_device_id",
    "virtual_device_ids",
]
