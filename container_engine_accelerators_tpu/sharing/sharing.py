"""TPU sharing: virtual-device ID scheme and request validation.

TPU analog of the reference's GPU-sharing layer
(ref: pkg/gpu/nvidia/gpusharing/gpusharing.go:23-84).

Two strategies:

- ``time-sharing`` — N virtual devices time-multiplexed onto one chip; a
  container may claim at most one virtual device (there is no isolation, so
  claiming several buys nothing).
- ``core-sharing`` — the MPS analog (SURVEY.md §2.3): co-tenant processes
  share a chip, each given a TensorCore fraction and an HBM limit through
  the env contract (TPU_CORE_PERCENTAGE / TPU_HBM_LIMIT_BYTES, computed in
  the manager).  Multiple virtual devices per request are allowed only on
  single-chip nodes, mirroring the reference's MPS rule
  (gpusharing.go:40-50).

Virtual IDs:

- plain chip:  ``accel0/vtpu1``  → physical ``accel0``
- sub-slice:   ``slice0/vtpu1``  → physical ``slice0``
  (a sub-slice partition — a contiguous chip group on the host ICI mesh —
  is treated as one physical device, like a MIG partition in the
  reference).
"""

import enum
import re
from typing import List, Optional


class SharingStrategy(str, enum.Enum):
    UNDEFINED = ""
    TIME_SHARING = "time-sharing"
    CORE_SHARING = "core-sharing"

    @classmethod
    def parse(cls, value: str) -> "SharingStrategy":
        # Accept the reference's "mps" spelling as an alias for migrators.
        if value == "mps":
            return cls.CORE_SHARING
        try:
            return cls(value)
        except ValueError:
            raise ValueError(
                f"invalid TPU sharing strategy: {value!r}, should be one of "
                f"time-sharing or core-sharing"
            )


_CHIP_VIRTUAL_RE = re.compile(r"^accel([0-9]+)/vtpu([0-9]+)$")
_SLICE_VIRTUAL_RE = re.compile(r"^slice([0-9]+)/vtpu([0-9]+)$")
_VTPU_SUFFIX_RE = re.compile(r"/vtpu([0-9]+)$")


def is_virtual_device_id(device_id: str) -> bool:
    return bool(
        _CHIP_VIRTUAL_RE.match(device_id) or _SLICE_VIRTUAL_RE.match(device_id)
    )


def virtual_to_physical_device_id(virtual_device_id: str) -> str:
    """``accel0/vtpu1`` → ``accel0``; ``slice2/vtpu1`` → ``slice2``."""
    if not is_virtual_device_id(virtual_device_id):
        raise ValueError(f"virtual device ID ({virtual_device_id}) is not valid")
    return _VTPU_SUFFIX_RE.split(virtual_device_id)[0]


def virtual_device_ids(physical_device_id: str, max_clients: int) -> List[str]:
    """Expand one physical device into its virtual device IDs."""
    return [f"{physical_device_id}/vtpu{i}" for i in range(max_clients)]


def validate_request(
    request_device_ids: List[str],
    device_count: int,
    strategy: Optional[SharingStrategy],
) -> None:
    """Reject invalid sharing requests (ref: gpusharing.go:40-50).

    time-sharing: at most one virtual device per request.
    core-sharing: multiple virtual devices only on single-chip nodes.
    """
    if len(request_device_ids) > 1 and is_virtual_device_id(request_device_ids[0]):
        if strategy == SharingStrategy.TIME_SHARING:
            raise ValueError(
                "invalid request for sharing TPU (time-sharing), at most 1 "
                "google.com/tpu can be requested on TPU-sharing nodes"
            )
        if strategy == SharingStrategy.CORE_SHARING and device_count > 1:
            raise ValueError(
                "invalid request for sharing TPU (core-sharing), at most 1 "
                "google.com/tpu can be requested on multi-chip nodes"
            )
