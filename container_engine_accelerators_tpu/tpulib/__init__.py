"""tpulib — the NVML-analog device library for TPU nodes.

The reference talks to GPUs through NVML (two Go bindings, go.mod:6-7):
device enumeration, memory/utilization sampling, and the Xid error-event
stream.  TPU chips have no NVML; the kernel driver exposes everything the
node stack needs as a filesystem contract:

    <root>/dev/accelN                            char device per chip
    <root>/sys/class/accel/accelN/device/
        chip_id           int
        pci_addr          "0000:00:05.0"
        coords            "x,y,z" ICI mesh coordinates of this chip
        topology          "XxYxZ" host-local mesh bounds (same on all chips)
        hbm_total_bytes   int
        hbm_used_bytes    int
        duty_cycle_pct    int   (0-100 TensorCore busy fraction)
        health            "ok" | "error:<code>"
    <root>/var/run/tpu/events/                   error-event queue
        <seq>.json   {"code": int, "device": "accelN"|null, "message": str}

Two interchangeable backends implement it:

- :class:`~container_engine_accelerators_tpu.tpulib.sysfs.SysfsTpuLib` —
  pure Python, used by tests and as fallback.
- :class:`~container_engine_accelerators_tpu.tpulib.native.NativeTpuLib` —
  ctypes binding over the C++ ``libtpushim.so`` (native/tpushim/), which
  owns the inotify event loop; the role NVML's C library plays in the
  reference (pkg/gpu/nvidia/metrics/util.go:17-73).

Tests fabricate the sysfs tree in a tempdir exactly like the reference
fabricates ``/proc/driver/nvidia/capabilities`` (beta_plugin_test.go:385-439).
"""

from container_engine_accelerators_tpu.tpulib.types import (
    ChipInfo,
    HbmInfo,
    TpuErrorEvent,
    TpuLib,
)
from container_engine_accelerators_tpu.tpulib.sysfs import (
    SysfsTpuLib,
    write_fixture,
    write_libtpu_install,
)


def open_lib(root: str = "/", prefer_native: bool = True) -> TpuLib:
    """Open the best available tpulib backend rooted at ``root``."""
    if prefer_native:
        try:
            from container_engine_accelerators_tpu.tpulib.native import NativeTpuLib

            return NativeTpuLib(root)
        except (ImportError, OSError):
            pass
    return SysfsTpuLib(root)


__all__ = [
    "ChipInfo",
    "HbmInfo",
    "TpuErrorEvent",
    "TpuLib",
    "SysfsTpuLib",
    "write_fixture",
    "open_lib",
]
