"""ctypes binding over the C++ tpushim library (native/tpushim/).

The native backend owns the inotify event loop and filesystem sampling —
the role NVML's C library plays for the reference (cgo helper,
pkg/gpu/nvidia/metrics/util.go:17-73).  The Python contract is identical
to SysfsTpuLib; ``open_lib`` prefers this backend when the .so is built.

Search order for the library: $TPUSHIM_PATH, the in-repo build dir,
then the system loader.
"""

import ctypes
import os
from typing import List, Optional

from container_engine_accelerators_tpu.tpulib.types import (
    ChipInfo,
    HbmInfo,
    TpuErrorEvent,
    TpuLib,
)

_NAME_LEN = 32
_ADDR_LEN = 32
_MSG_LEN = 256
_HEALTH_LEN = 64


class _ChipInfoStruct(ctypes.Structure):
    _fields_ = [
        ("name", ctypes.c_char * _NAME_LEN),
        ("index", ctypes.c_int32),
        ("chip_id", ctypes.c_int32),
        ("pci_addr", ctypes.c_char * _ADDR_LEN),
        ("coords", ctypes.c_int32 * 3),
        ("topology", ctypes.c_int32 * 3),
    ]


def _to_chip_info(s: "_ChipInfoStruct") -> ChipInfo:
    return ChipInfo(
        name=s.name.decode(),
        index=s.index,
        chip_id=s.chip_id,
        pci_addr=s.pci_addr.decode(),
        coords=tuple(s.coords),
        topology=tuple(s.topology),
    )


class _EventStruct(ctypes.Structure):
    _fields_ = [
        ("code", ctypes.c_int32),
        ("device", ctypes.c_char * _NAME_LEN),
        ("message", ctypes.c_char * _MSG_LEN),
    ]


def _find_library() -> ctypes.CDLL:
    env = os.environ.get("TPUSHIM_PATH")
    if env:
        # An explicit override must never fall through to another copy.
        if not os.path.exists(env):
            raise OSError(f"TPUSHIM_PATH={env} does not exist")
        return ctypes.CDLL(env)
    here = os.path.dirname(os.path.abspath(__file__))
    repo = os.path.dirname(os.path.dirname(here))
    candidates = [
        os.path.join(repo, "native", "tpushim", "build", "libtpushim.so"),
        "libtpushim.so",  # system loader
    ]
    errors = []
    for c in candidates:
        try:
            return ctypes.CDLL(c)
        except OSError as e:
            errors.append(f"{c}: {e}")
    raise OSError(
        "libtpushim.so not found; build with `make native`. Tried: "
        + "; ".join(errors)
    )


def _load() -> ctypes.CDLL:
    lib = _find_library()
    lib.tpu_open.argtypes = [ctypes.c_char_p]
    lib.tpu_open.restype = ctypes.c_void_p
    lib.tpu_close.argtypes = [ctypes.c_void_p]
    lib.tpu_chip_count.argtypes = [ctypes.c_void_p]
    lib.tpu_chip_info.argtypes = [
        ctypes.c_void_p,
        ctypes.c_int,
        ctypes.POINTER(_ChipInfoStruct),
    ]
    lib.tpu_chip_info_all.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(_ChipInfoStruct),
        ctypes.c_int,
    ]
    lib.tpu_hbm_info.argtypes = [
        ctypes.c_void_p,
        ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int64),
    ]
    lib.tpu_duty_cycle.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.tpu_health.argtypes = [
        ctypes.c_void_p,
        ctypes.c_char_p,
        ctypes.c_char_p,
        ctypes.c_int,
    ]
    lib.tpu_wait_for_event.argtypes = [
        ctypes.c_void_p,
        ctypes.c_int,
        ctypes.POINTER(_EventStruct),
    ]
    lib.tpushim_version.restype = ctypes.c_char_p
    return lib


class NativeTpuLib(TpuLib):
    def __init__(self, root: str = "/"):
        self._lib = _load()
        self._ctx = self._lib.tpu_open(root.encode())
        if not self._ctx:
            raise OSError("tpu_open failed")
        self.root = root

    def close(self) -> None:
        if self._ctx:
            self._lib.tpu_close(self._ctx)
            self._ctx = None

    def __del__(self):  # best-effort
        try:
            self.close()
        except Exception:  # lint: disable=swallowed-exception
            pass  # finalizers must never raise (interpreter teardown)

    # -- enumeration ---------------------------------------------------------

    def chip_count(self) -> int:
        return max(0, self._lib.tpu_chip_count(self._ctx))

    _MAX_CHIPS = 256

    def chips(self) -> List[ChipInfo]:
        # One native call, one directory scan: a consistent snapshot that
        # can't race hotplug mid-enumeration.  Grow the buffer until the
        # scan fits so enumeration never silently truncates.
        capacity = self._MAX_CHIPS
        while True:
            arr = (_ChipInfoStruct * capacity)()
            n = self._lib.tpu_chip_info_all(self._ctx, arr, capacity)
            if n < 0:
                raise OSError(f"tpu_chip_info_all failed: {n}")
            if n < capacity:
                return [_to_chip_info(s) for s in arr[:n]]
            capacity *= 2

    def chip_info(self, name: str) -> ChipInfo:
        for chip in self.chips():
            if chip.name == name:
                return chip
        raise ValueError(f"not a TPU chip name: {name!r}")

    # -- sampling ------------------------------------------------------------

    def hbm_info(self, name: str) -> HbmInfo:
        total = ctypes.c_int64()
        used = ctypes.c_int64()
        rc = self._lib.tpu_hbm_info(
            self._ctx, name.encode(), ctypes.byref(total), ctypes.byref(used)
        )
        if rc != 0:
            raise OSError(f"tpu_hbm_info({name}) failed: {rc}")
        return HbmInfo(total_bytes=total.value, used_bytes=used.value)

    def duty_cycle(self, name: str) -> int:
        rc = self._lib.tpu_duty_cycle(self._ctx, name.encode())
        return max(0, rc)

    def model(self, name: str) -> str:
        # The C shim samples counters; the model string is a static sysfs
        # attribute, read directly from the same tree the shim is rooted at.
        p = os.path.join(self.root, "sys/class/accel", name, "device", "model")
        try:
            with open(p) as f:
                return f.read().strip()
        except OSError:
            return "tpu"

    def health(self, name: str) -> str:
        buf = ctypes.create_string_buffer(_HEALTH_LEN)
        rc = self._lib.tpu_health(self._ctx, name.encode(), buf, _HEALTH_LEN)
        if rc != 0:
            raise OSError(f"tpu_health({name}) failed: {rc}")
        return buf.value.decode()

    # -- events --------------------------------------------------------------

    def wait_for_event(self, timeout_s: float) -> Optional[TpuErrorEvent]:
        ev = _EventStruct()
        rc = self._lib.tpu_wait_for_event(
            self._ctx, int(timeout_s * 1000), ctypes.byref(ev)
        )
        if rc < 0:
            # A hard error must not look like a timeout: the health checker
            # would spin at 100% CPU retrying instantly forever.
            raise OSError(f"tpu_wait_for_event failed: {rc}")
        if rc == 0:
            return None
        device = ev.device.decode()
        return TpuErrorEvent(
            code=ev.code,
            device=device or None,
            message=ev.message.decode(),
        )
