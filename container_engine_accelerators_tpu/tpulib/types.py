"""tpulib data types and backend interface."""

import dataclasses
from typing import List, Optional, Tuple


@dataclasses.dataclass(frozen=True)
class ChipInfo:
    name: str  # "accelN"
    index: int
    chip_id: int
    pci_addr: str
    coords: Tuple[int, int, int]  # ICI mesh coordinates
    topology: Tuple[int, int, int]  # host-local mesh bounds


@dataclasses.dataclass(frozen=True)
class HbmInfo:
    total_bytes: int
    used_bytes: int


@dataclasses.dataclass(frozen=True)
class TpuErrorEvent:
    """A TPU runtime/driver error event — the Xid analog
    (ref: health_check/health_checker.go:179-226)."""

    code: int
    device: Optional[str]  # "accelN", or None = whole-node event
    message: str = ""


class TpuLib:
    """Backend interface; seam for mocks, mirroring the reference's
    ``callDevice`` interface (health_checker.go:170-177)."""

    def chip_count(self) -> int:
        raise NotImplementedError

    def chips(self) -> List[ChipInfo]:
        raise NotImplementedError

    def chip_info(self, name: str) -> ChipInfo:
        raise NotImplementedError

    def hbm_info(self, name: str) -> HbmInfo:
        raise NotImplementedError

    def duty_cycle(self, name: str) -> int:
        """0-100 TensorCore busy percentage (NVML duty-cycle analog)."""
        raise NotImplementedError

    def model(self, name: str) -> str:
        """Chip model string for metric labels, e.g. "tpu-v5e" (the
        NVML device-name analog; metrics labels carry it like the
        reference's model label, metrics.go:59-115).  Backends without
        model info return "tpu"."""
        return "tpu"

    def health(self, name: str) -> str:
        """"ok" or "error:<code>"."""
        raise NotImplementedError

    def wait_for_event(self, timeout_s: float) -> Optional[TpuErrorEvent]:
        """Block up to timeout_s for the next error event; None on timeout
        (ref: nvml.WaitForEvent 5000ms poll, health_checker.go:238-243)."""
        raise NotImplementedError

    def close(self) -> None:
        pass
