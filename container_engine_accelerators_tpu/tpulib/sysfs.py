"""Pure-Python tpulib backend over the sysfs contract.

Reads the filesystem layout documented in tpulib/__init__.py.  Event
consumption is a polling tail of ``<root>/var/run/tpu/events`` (the native
C++ backend uses inotify instead); consumed events are removed so the
directory acts as a queue.
"""

import json
import logging
import os
import time
from typing import List, Optional, Tuple

from container_engine_accelerators_tpu.tpulib.types import (
    ChipInfo,
    HbmInfo,
    TpuErrorEvent,
    TpuLib,
)
from container_engine_accelerators_tpu.utils.devname import DEVICE_RE as ACCEL_RE

log = logging.getLogger(__name__)
EVENT_POLL_INTERVAL_S = 0.05


def _parse_triple(raw: str, sep: str) -> Tuple[int, int, int]:
    parts = [int(p) for p in raw.strip().split(sep)]
    while len(parts) < 3:
        parts.append(1)
    return tuple(parts[:3])


class SysfsTpuLib(TpuLib):
    def __init__(self, root: str = "/"):
        self.root = root
        self.sys_dir = os.path.join(root, "sys/class/accel")
        self.events_dir = os.path.join(root, "var/run/tpu/events")

    # -- enumeration --------------------------------------------------------

    def _names(self) -> List[str]:
        if not os.path.isdir(self.sys_dir):
            return []
        names = [n for n in os.listdir(self.sys_dir) if ACCEL_RE.match(n)]
        return sorted(names, key=lambda n: int(ACCEL_RE.match(n).group(1)))

    def chip_count(self) -> int:
        return len(self._names())

    def chips(self) -> List[ChipInfo]:
        return [self.chip_info(n) for n in self._names()]

    def _attr(self, name: str, attr: str, default: Optional[str] = None) -> str:
        p = os.path.join(self.sys_dir, name, "device", attr)
        try:
            with open(p) as f:
                return f.read().strip()
        except OSError:
            if default is not None:
                return default
            raise

    def chip_info(self, name: str) -> ChipInfo:
        m = ACCEL_RE.match(name)
        if not m:
            raise ValueError(f"not a TPU chip name: {name!r}")
        return ChipInfo(
            name=name,
            index=int(m.group(1)),
            chip_id=int(self._attr(name, "chip_id", default="0")),
            pci_addr=self._attr(name, "pci_addr", default=""),
            coords=_parse_triple(self._attr(name, "coords", default="0,0,0"), ","),
            topology=_parse_triple(
                self._attr(name, "topology", default="1x1x1"), "x"
            ),
        )

    # -- sampling -----------------------------------------------------------

    def hbm_info(self, name: str) -> HbmInfo:
        return HbmInfo(
            total_bytes=int(self._attr(name, "hbm_total_bytes", default="0")),
            used_bytes=int(self._attr(name, "hbm_used_bytes", default="0")),
        )

    def duty_cycle(self, name: str) -> int:
        return int(self._attr(name, "duty_cycle_pct", default="0"))

    def health(self, name: str) -> str:
        return self._attr(name, "health", default="ok")

    def model(self, name: str) -> str:
        return self._attr(name, "model", default="tpu")

    # -- events -------------------------------------------------------------

    def _next_event_file(self) -> Optional[str]:
        if not os.path.isdir(self.events_dir):
            return None
        entries = sorted(
            e for e in os.listdir(self.events_dir) if e.endswith(".json")
        )
        return os.path.join(self.events_dir, entries[0]) if entries else None

    def wait_for_event(self, timeout_s: float) -> Optional[TpuErrorEvent]:
        deadline = time.monotonic() + timeout_s
        while True:
            path = self._next_event_file()
            if path is not None:
                obj = None
                try:
                    with open(path) as f:
                        obj = json.load(f)
                except OSError:
                    pass  # racing consumer took it
                except (json.JSONDecodeError, ValueError, TypeError):
                    log.warning("discarding malformed TPU event file %s", path)
                try:
                    os.unlink(path)
                except OSError:
                    pass
                if isinstance(obj, dict):
                    return TpuErrorEvent(
                        code=int(obj.get("code", -1)),
                        device=obj.get("device"),
                        message=obj.get("message", ""),
                    )
                # malformed/raced: fall through to the deadline check
            if time.monotonic() >= deadline:
                return None
            time.sleep(min(EVENT_POLL_INTERVAL_S, max(0.0, deadline - time.monotonic())))


# ---- test-fixture helper ---------------------------------------------------


def write_fixture(
    root: str,
    num_chips: int,
    topology: str = "2x2x1",
    hbm_total: int = 16 * 2**30,
    with_dev_nodes: bool = True,
) -> None:
    """Fabricate the sysfs/dev contract under ``root`` for tests, like the
    reference fabricates MIG capability trees (beta_plugin_test.go:385-439).

    Chips are laid out row-major over the host topology.
    """
    bounds = _parse_triple(topology, "x")
    os.makedirs(os.path.join(root, "var/run/tpu/events"), exist_ok=True)
    if with_dev_nodes:
        os.makedirs(os.path.join(root, "dev"), exist_ok=True)
    for i in range(num_chips):
        x = i % bounds[0]
        y = (i // bounds[0]) % bounds[1]
        z = i // (bounds[0] * bounds[1])
        d = os.path.join(root, "sys/class/accel", f"accel{i}", "device")
        os.makedirs(d, exist_ok=True)
        attrs = {
            "chip_id": str(i),
            "pci_addr": f"0000:00:{4+i:02x}.0",
            "coords": f"{x},{y},{z}",
            "topology": topology,
            "hbm_total_bytes": str(hbm_total),
            "hbm_used_bytes": "0",
            "duty_cycle_pct": "0",
            "health": "ok",
        }
        for k, v in attrs.items():
            with open(os.path.join(d, k), "w") as f:
                f.write(v + "\n")
        if with_dev_nodes:
            open(os.path.join(root, "dev", f"accel{i}"), "w").close()


def write_libtpu_install(root: str) -> str:
    """Fabricate the installer's libtpu delivery under ``root`` (the
    node contract the core-sharing gate probes:
    libtpu-installer/ubuntu/entrypoint.sh:82-88).  Returns the host dir
    to mount.  The fake .so carries the visibility-env marker a real
    libtpu embeds."""
    host_dir = os.path.join(root, "home/kubernetes/bin/tpu")
    lib64 = os.path.join(host_dir, "lib64")
    os.makedirs(lib64, exist_ok=True)
    with open(os.path.join(lib64, "libtpu.so"), "wb") as f:
        f.write(b"\x7fELF-fake-libtpu\x00TPU_VISIBLE_DEVICES\x00")
    return host_dir


def write_event_file(
    events_dir: str, code: int, device: Optional[str], message: str = ""
) -> str:
    """Atomically drop one event file into a queue directory.

    THE event-queue producer: the fault-injection demo and the
    runtime-error mapper both route through here, so the file contract
    (atomic tmp+rename, monotonic-ns name, {code,device,message} JSON)
    lives in exactly one place opposite the consumer above.
    """
    os.makedirs(events_dir, exist_ok=True)
    seq = time.monotonic_ns()
    tmp = os.path.join(events_dir, f".{seq}.tmp")
    with open(tmp, "w") as f:
        json.dump({"code": code, "device": device, "message": message}, f)
    final = os.path.join(events_dir, f"{seq}.json")
    os.rename(tmp, final)
    return final


def post_event(root: str, code: int, device: Optional[str], message: str = "") -> None:
    """Drop an error event into the queue (test + fault-injection helper)."""
    write_event_file(os.path.join(root, "var/run/tpu/events"), code, device,
                     message)
