"""Lock-protected log2-bucket latency histograms.

Flat counters (metrics/counters.py) say *how often* the self-healing
paths fire; these say *how long* they take — the difference between "40
reconnects" and "40 reconnects, p99 3.1s" is the difference between a
blip and a flapping link.  Durations are bucketed by the power of two
of their microsecond value (``2^k us`` upper bounds), so the whole
histogram is a handful of integers per op: cheap enough for the DCN
hot path, exact enough for order-of-magnitude percentiles.

The MetricServer exports the registry as the
``agent_latency{op=...,bucket=...}`` gauge family (cumulative
Prometheus-style ``le`` buckets in microseconds, plus ``+Inf`` = total
count) next to ``agent_events``; ``snapshot()``/``percentile()`` serve
in-process consumers (the flight recorder, bench p50/p99 reporting).

**Trace exemplars**: each bucket additionally remembers the trace id
of its worst (longest) sample, so a histogram is never a dead end —
the scrape's ``agent_exemplar{op,bucket,trace}`` row names the exact
trace whose JSONL tree explains the tail
(``cmd/agent_trace.py --exemplar <op>``).  ``obs.trace.span(...,
histogram=op)`` wires the id through automatically; direct
``observe()`` callers may pass ``trace_id`` themselves or leave the
bucket exemplar-less.

Stdlib-only, like the rest of obs/: importable from utils/ and
parallel/ without prometheus_client.
"""

import threading
from typing import Dict, Iterable, List, Optional, Tuple

_lock = threading.Lock()


class _Histo:
    __slots__ = ("buckets", "count", "sum_s", "exemplars")

    def __init__(self):
        self.buckets: Dict[int, int] = {}  # exponent k -> count (le 2^k us)
        self.count = 0
        self.sum_s = 0.0
        # exponent k -> (trace_id, worst duration s) for that bucket
        self.exemplars: Dict[int, Tuple[str, float]] = {}


_registry: Dict[str, _Histo] = {}


def bucket_le_us(seconds: float) -> int:
    """The log2 bucket a duration falls into: the smallest ``2^k``
    microseconds >= the duration (sub-microsecond clamps to 1us)."""
    us = int(seconds * 1e6)
    if us <= 1:
        return 1
    return 1 << (us - 1).bit_length()


def observe(op: str, seconds: float,
            trace_id: Optional[str] = None) -> None:
    """Record one duration for ``op`` (created on first observation).
    With ``trace_id`` set, the sample competes for its bucket's
    exemplar slot: the bucket keeps the id of its WORST sample."""
    le = bucket_le_us(seconds)
    exp = le.bit_length() - 1
    with _lock:
        h = _registry.get(op)
        if h is None:
            h = _registry[op] = _Histo()
        h.buckets[exp] = h.buckets.get(exp, 0) + 1
        h.count += 1
        h.sum_s += seconds
        if trace_id is not None:
            worst = h.exemplars.get(exp)
            if worst is None or seconds > worst[1]:
                h.exemplars[exp] = (trace_id, seconds)


def snapshot() -> Dict[str, dict]:
    """Point-in-time copy: ``{op: {count, sum_us, buckets{le_us: n},
    exemplars{le_us: {trace, dur_us}}}}`` with non-cumulative
    per-bucket counts (the exporter accumulates)."""
    with _lock:
        return {
            op: {
                "count": h.count,
                "sum_us": round(h.sum_s * 1e6, 1),
                "buckets": {
                    str(1 << exp): n
                    for exp, n in sorted(h.buckets.items())
                },
                "exemplars": {
                    str(1 << exp): {"trace": t,
                                    "dur_us": round(d * 1e6, 1)}
                    for exp, (t, d) in sorted(h.exemplars.items())
                },
            }
            for op, h in _registry.items()
        }


def exemplar(op: str) -> Optional[Tuple[str, float]]:
    """The op's overall worst sample as ``(trace_id, seconds)`` — the
    one-hop answer to "which trace blew the p99?".  None for an
    unknown op or one whose observations carried no trace id."""
    with _lock:
        h = _registry.get(op)
        if h is None or not h.exemplars:
            return None
        return max(h.exemplars.values(), key=lambda td: td[1])


def percentile(op: str, q: float) -> Optional[float]:
    """Upper-bound estimate of the ``q``-quantile (0 < q <= 1) in
    seconds: the bucket boundary at which the cumulative count reaches
    ``q * count``.  None for an unknown/empty op."""
    with _lock:
        h = _registry.get(op)
        if h is None or h.count == 0:
            return None
        target = q * h.count
        seen = 0
        for exp in sorted(h.buckets):
            seen += h.buckets[exp]
            if seen >= target:
                return (1 << exp) / 1e6
        return (1 << max(h.buckets)) / 1e6  # pragma: no cover — q <= 1


def percentiles(op: str, qs: Iterable[float]) -> List[Optional[float]]:
    return [percentile(op, q) for q in qs]


def delta_percentile_us(op: str, baseline: Dict[str, int],
                        q: float) -> Optional[float]:
    """Upper-bound ``q``-quantile in µs of the observations made
    SINCE ``baseline`` (a ``snapshot()[op]['buckets']`` mapping taken
    earlier).  The registry is process-global and cumulative, so
    anything judging one run/lifetime — fleet SLOs, the serving
    frontend's adaptive hedge deadline — must quantile the delta, not
    the whole process history.  None when nothing was observed since
    the baseline."""
    now = snapshot().get(op, {}).get("buckets", {})
    delta = {int(le): n - baseline.get(le, 0)
             for le, n in now.items()
             if n - baseline.get(le, 0) > 0}
    total = sum(delta.values())
    if not total:
        return None
    target = q * total
    seen = 0
    for le in sorted(delta):
        seen += delta[le]
        if seen >= target:
            return float(le)
    return float(max(delta))  # pragma: no cover — q <= 1


def reset() -> None:
    """Drop every histogram — test isolation only; production
    histograms are cumulative for the agent's life, like counters."""
    with _lock:
        _registry.clear()
