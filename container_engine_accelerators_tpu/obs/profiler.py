"""Continuous stack-sampling profiler: where does the CPU go?

The critical-path engine (obs/critpath.py) answers "where did the wall
time go" per phase, and the exposed-communication accounting says how
much DCN time hides behind staging — but neither can attribute a
single CPU-second to a line of code.  PR 13 closed with the staging
memcpy and the read-out copy named as the shm lane's remaining floor;
this module is the tool that can prove (or refute) that claim with
data instead of intuition.

A timer thread wakes at ``TPU_PROF_HZ`` (default ~67 Hz — off the
100 Hz harmonic most periodic work sits on), walks
``sys._current_frames()``, folds each thread's stack into one
semicolon-joined line (root first — the flamegraph collapsed format),
and classifies it by a **subsystem map**:

- ``shm-staging``  — any first-party frame in ``parallel/dcn_shm.py``
  or whose function name contains ``shm`` (the staging memcpy, the
  read-out copy, ring post/poll, segment land/commit);
- ``dcn_pipeline`` — the chunked/striped client data plane
  (``parallel/dcn_pipeline.py`` / ``dcn.py`` / ``dcn_client.py`` /
  ``dcn_tune.py``);
- ``xferd``        — the PyXferd daemon (``fleet/xferd.py``);
- ``serving``      — the serving frontend/breakers (``serving/``);
- ``idle``         — the idle-vs-GIL heuristic: a leaf frame parked in
  a *stdlib* waiter (``threading.wait``, ``queue.get``,
  ``selectors.select``, socket ``accept``/``readinto``, …) is a thread
  burning nothing.  A wall-clock sampler cannot see the GIL, so a
  thread blocked inside a first-party function (e.g. ``netio`` socket
  IO mid-chunk) stays attributed to its subsystem — that IS the
  socket-IO share;
- ``other``        — everything else (bench drivers, coordinator glue).

Aggregation is **bounded**: at most ``MAX_STACKS`` distinct folded
stacks are held; admitting a new stack past the cap evicts the
coldest quarter (smallest count, oldest last-seen) and their samples
are counted in ``prof.dropped`` — never silently lost.  Snapshot /
reset semantics mirror ``obs/timeseries.py``; every aggregated sample
bumps a process-wide cursor, so ``scrape(since=<cursor>)`` returns
only the stacks that changed — what the MetricServer's ``/profile``
endpoint serves and the fleet aggregator pages.

Overhead is accounted, not assumed: the sampler times its own passes
and publishes the cumulative ``prof.overhead_ratio`` gauge (sampling
seconds / wall seconds since the sampler started); ``make prof``
additionally gates the measured throughput cost on the pipelined
bench below 5 %.

Kill switch ``TPU_PROF=0`` disables ``start()`` entirely; a malformed
``TPU_PROF_HZ`` degrades to the default (the TPU_FAULT_SPEC rule).
The sampler takes NO first-party lock while walking frames: the walk
and fold run lock-free, and only the finished fold list is folded
into the registry under the module lock (``make race`` runs this
suite under the lockwatch shim to keep it that way).

Stdlib-only, like the rest of obs/.
"""

import json
import logging
import os
import sys
import threading
import time
import urllib.request
from typing import Dict, List, Optional, Tuple

from container_engine_accelerators_tpu.metrics import counters
from container_engine_accelerators_tpu.obs import timeseries

log = logging.getLogger(__name__)

PROF_ENV = "TPU_PROF"          # "0" = kill switch (default: enabled)
HZ_ENV = "TPU_PROF_HZ"         # sampling rate; malformed -> default
DEFAULT_HZ = 67.0
MIN_HZ, MAX_HZ = 1.0, 1000.0

# Bounded aggregation: distinct folded stacks held at once, frames
# folded per stack, and the /profile response bounds.
MAX_STACKS = 256
MAX_DEPTH = 48
SCRAPE_DEFAULT_LIMIT = 64
SCRAPE_MAX_LIMIT = 512

SUBSYSTEMS = ("shm-staging", "dcn_pipeline", "xferd", "serving",
              "idle", "other")

# The idle-vs-GIL heuristic's stdlib waiter leaves: a thread whose
# innermost frame is one of these, in a NON-first-party file, is
# parked, not computing.
IDLE_FUNCS = frozenset((
    "wait", "_wait_for_tstate_lock", "get", "select", "poll",
    "accept", "acquire", "readinto", "readline", "_try_wait",
    "_recv_msg", "read",
))

_PKG_PREFIX = os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))).replace(os.sep, "/") + "/"

_DCN_PIPELINE_FILES = frozenset((
    "parallel/dcn_pipeline.py", "parallel/dcn.py",
    "parallel/dcn_client.py", "parallel/dcn_tune.py",
))


class _Stack:
    __slots__ = ("count", "subsystem", "seq")

    def __init__(self, subsystem: str):
        self.count = 0
        self.subsystem = subsystem
        self.seq = 0


_lock = threading.Lock()
_stacks: Dict[str, _Stack] = {}
_subsystems: Dict[str, int] = {}
_samples = 0          # total thread-stacks aggregated (the cursor)
_dropped = 0          # samples lost to LRU eviction
_sample_time_s = 0.0  # cumulative time spent inside sampling passes
_started_mono: Optional[float] = None
_thread: Optional[threading.Thread] = None
_stop_event: Optional[threading.Event] = None


# -- knobs -------------------------------------------------------------------


def enabled(env=None) -> bool:
    """The ``TPU_PROF`` kill switch (default on — the profiler is a
    low-rate always-on surface, like the span ring)."""
    env = os.environ if env is None else env
    raw = str(env.get(PROF_ENV, "1")).strip().lower()
    return raw not in ("0", "false", "off", "no")


def resolve_hz(env=None) -> float:
    """``TPU_PROF_HZ``, clamped to [1, 1000]; malformed or
    non-positive values degrade to the default (the TPU_FAULT_SPEC
    rule: a config typo must never blind — or stampede — an agent)."""
    env = os.environ if env is None else env
    raw = env.get(HZ_ENV)
    if raw is None:
        return DEFAULT_HZ
    try:
        hz = float(raw)
        if not hz > 0:
            raise ValueError("rate must be > 0")
    except ValueError:
        log.error("ignoring malformed %s=%r; using %g", HZ_ENV, raw,
                  DEFAULT_HZ)
        return DEFAULT_HZ
    return min(max(hz, MIN_HZ), MAX_HZ)


# -- fold + classify (lock-free: runs while walking frames) ------------------


def classify(frames: List[Tuple[Optional[str], str]]) -> str:
    """Subsystem for one stack, ``frames`` leaf-first as
    ``(package-relative path or None, function name)``.  The leaf
    decides idle (stdlib waiter = parked thread).  Otherwise a stack
    passing through the shm machinery ANYWHERE is ``shm-staging`` —
    the shm code lives inside the pipeline and daemon modules, and
    its leaf-side helpers (control ops, span plumbing) would
    otherwise steal its samples; among the rest, the innermost
    matching first-party frame wins."""
    if frames:
        rel, func = frames[0]
        if rel is None and func in IDLE_FUNCS:
            return "idle"
    first = None
    for rel, func in frames:
        if rel is None:
            continue
        if rel == "parallel/dcn_shm.py" or "shm" in func:
            return "shm-staging"
        if first is None:
            if rel in _DCN_PIPELINE_FILES:
                first = "dcn_pipeline"
            elif rel == "fleet/xferd.py":
                first = "xferd"
            elif rel.startswith("serving/"):
                first = "serving"
    return first or "other"


def fold(frame) -> Tuple[str, str]:
    """One thread's stack as ``(folded, subsystem)``: the folded form
    is root-first, semicolon-joined ``module.function`` labels — the
    flamegraph collapsed format, ready for ``flamegraph.pl`` via
    ``agent_prof --folded``."""
    frames: List[Tuple[Optional[str], str]] = []
    labels: List[str] = []
    f = frame
    while f is not None and len(frames) < MAX_DEPTH:
        code = f.f_code
        fn = code.co_filename.replace(os.sep, "/")
        func = code.co_name
        rel: Optional[str] = None
        if fn.startswith(_PKG_PREFIX):
            rel = fn[len(_PKG_PREFIX):]
            mod = rel[:-3] if rel.endswith(".py") else rel
            labels.append(mod.replace("/", ".") + "." + func)
        else:
            base = fn.rsplit("/", 1)[-1]
            if base.endswith(".py"):
                base = base[:-3]
            labels.append(base + "." + func)
        frames.append((rel, func))
        f = f.f_back
    labels.reverse()
    return ";".join(labels), classify(frames)


# Fold cache: most threads are parked on the same stack tick after
# tick, so folding is memoized by the stack's code-object tuple (the
# stack's identity at function granularity — strong refs keep ids
# stable).  Plain dict, GIL-atomic get/set, cleared wholesale past the
# cap; read/written only from the sampling pass, never under _lock.
_fold_cache: Dict[tuple, Tuple[str, str]] = {}
_FOLD_CACHE_MAX = 2048


def _fold_cached(frame) -> Tuple[str, str]:
    codes = []
    f = frame
    while f is not None and len(codes) < MAX_DEPTH:
        codes.append(f.f_code)
        f = f.f_back
    key = tuple(codes)
    hit = _fold_cache.get(key)
    if hit is not None:
        return hit
    result = fold(frame)
    if len(_fold_cache) >= _FOLD_CACHE_MAX:
        _fold_cache.clear()
    _fold_cache[key] = result
    return result


# -- aggregation -------------------------------------------------------------


def _evict_locked() -> int:
    """Make room for a new stack: drop the coldest quarter (smallest
    count, ties oldest last-seen) and return how many samples they
    held — the caller counts them dropped, never silently gone."""
    victims = sorted(_stacks.items(),
                     key=lambda kv: (kv[1].count, kv[1].seq))
    victims = victims[:max(1, MAX_STACKS // 4)]
    gone = 0
    for name, entry in victims:
        gone += entry.count
        del _stacks[name]
    return gone


def _ingest_locked(folded: str, subsystem: str, n: int) -> int:
    """Fold ``n`` samples of one stack into the registry; caller
    holds the lock.  Returns samples evicted to make room."""
    global _samples
    dropped = 0
    _samples += n
    _subsystems[subsystem] = _subsystems.get(subsystem, 0) + n
    entry = _stacks.get(folded)
    if entry is None:
        if len(_stacks) >= MAX_STACKS:
            dropped = _evict_locked()
        entry = _stacks[folded] = _Stack(subsystem)
    entry.count += n
    entry.seq = _samples
    return dropped


def sample_once() -> int:
    """One sampling pass over every OTHER thread's current stack;
    returns how many thread-stacks were aggregated.  The frame walk
    and fold run with NO lock held (first-party or otherwise); only
    the finished fold list touches the registry."""
    global _dropped, _sample_time_s, _started_mono
    t0 = time.perf_counter()
    me = threading.get_ident()
    folds = [_fold_cached(frame)
             for ident, frame in sys._current_frames().items()
             if ident != me]
    dropped_now = 0
    with _lock:
        if _started_mono is None:
            _started_mono = time.monotonic()
        for folded, subsystem in folds:
            dropped_now += _ingest_locked(folded, subsystem, 1)
        _dropped += dropped_now
        _sample_time_s += time.perf_counter() - t0
        ratio = _overhead_ratio_locked()
    if folds:
        counters.inc("prof.samples", len(folds))
    if dropped_now:
        counters.inc("prof.dropped", dropped_now)
    if ratio is not None:
        timeseries.gauge("prof.overhead_ratio", ratio)
    return len(folds)


def ingest(folded: str, subsystem: str, n: int = 1) -> None:
    """Seed the registry with pre-folded samples — demo tours and
    merge tooling; does NOT claim real sampling happened (the
    ``prof.*`` counters are untouched)."""
    global _dropped
    sub = subsystem if subsystem in SUBSYSTEMS else "other"
    with _lock:
        _dropped += _ingest_locked(folded, sub, max(1, int(n)))


def _overhead_ratio_locked() -> Optional[float]:
    if _started_mono is None:
        return None
    elapsed = time.monotonic() - _started_mono
    if elapsed <= 0:
        return None
    return _sample_time_s / elapsed


# -- read side ---------------------------------------------------------------


def _payload_locked(rows: List[Tuple[str, str, int]],
                    cursor: int) -> dict:
    ratio = _overhead_ratio_locked()
    return {
        "cursor": cursor,
        "samples": _samples,
        "dropped": _dropped,
        "hz": resolve_hz(),
        "running": _thread is not None and _thread.is_alive(),
        "overhead_ratio": (round(ratio, 6)
                           if ratio is not None else None),
        "subsystems": dict(_subsystems),
        "stacks": [{"stack": n, "subsystem": s, "count": c}
                   for n, s, c in rows],
    }


def scrape(since: int = 0, limit: Optional[int] = None) -> dict:
    """The ``/profile`` response body: cumulative totals plus every
    stack whose count changed after the ``since`` cursor,
    oldest-change first.  When ``limit`` truncates the page, the
    returned ``cursor`` advances only past what was actually returned
    (the ``/spans`` contract: nothing is silently skipped — the next
    page picks up the rest); an unchanged registry scrapes as an
    empty ``stacks`` list."""
    since = max(0, int(since))
    with _lock:
        changed = sorted((e.seq, name, e.subsystem, e.count)
                         for name, e in _stacks.items()
                         if e.seq > since)
        cursor = _samples
        if limit is not None and len(changed) > max(0, int(limit)):
            changed = changed[:max(0, int(limit))]
            cursor = changed[-1][0] if changed else since
        return _payload_locked(
            [(n, s, c) for _seq, n, s, c in changed], cursor)


def snapshot(top: Optional[int] = None) -> dict:
    """Point-in-time copy of the whole registry, count-descending
    (``top`` caps the stack rows) — same contract as
    ``timeseries.snapshot``; the display-ordered sibling of the
    cursor-paged :func:`scrape`."""
    with _lock:
        rows = sorted(((name, e.subsystem, e.count)
                       for name, e in _stacks.items()),
                      key=lambda r: (-r[2], r[0]))
        if top is not None:
            rows = rows[:max(0, int(top))]
        return _payload_locked(rows, _samples)


def fetch(url: str, timeout_s: float = 10.0) -> dict:
    """One GET of a ``/profile`` endpoint -> the parsed body dict —
    the ONE wire fetcher every consumer (agent_top's hotspot panel,
    agent_prof, fleet telemetry) shares.  Raises OSError/ValueError
    on transport or parse trouble; callers own their degradation."""
    with urllib.request.urlopen(url, timeout=timeout_s) as resp:
        obj = json.loads(resp.read().decode("utf-8", "replace"))
    if not isinstance(obj, dict):
        raise ValueError("profile body is not a JSON object")
    return obj


def summary(top: int = 10) -> dict:
    """The flight recorder's compact slice: totals, the subsystem
    rollup, and the top-N stacks — where every thread was stuck."""
    snap = snapshot(top=top)
    return {
        "samples": snap["samples"],
        "dropped": snap["dropped"],
        "overhead_ratio": snap["overhead_ratio"],
        "subsystems": snap["subsystems"],
        "top": snap["stacks"],
    }


def subsystem_shares(baseline: Optional[Dict[str, int]] = None,
                     include_idle: bool = False) -> Dict[str, float]:
    """Per-subsystem sample shares, optionally as a delta against an
    earlier ``snapshot()['subsystems']`` (the per-cell attribution
    ``dcn_bench`` records).  Idle samples are excluded by default —
    a parked thread pool would otherwise drown every busy share."""
    with _lock:
        subs = dict(_subsystems)
    if baseline:
        subs = {k: v - baseline.get(k, 0) for k, v in subs.items()}
    subs = {k: v for k, v in subs.items()
            if v > 0 and (include_idle or k != "idle")}
    total = sum(subs.values())
    if not total:
        return {}
    return {k: v / total for k, v in subs.items()}


# -- lifecycle ---------------------------------------------------------------


def _loop(stop: threading.Event, interval: float) -> None:
    while not stop.wait(interval):
        try:
            sample_once()
        except Exception as e:  # noqa: BLE001 — sampler never kills host
            log.error("profiler sampling pass failed: %s", e)


def running() -> bool:
    return _thread is not None and _thread.is_alive()


def start(hz: Optional[float] = None) -> bool:
    """Arm the sampling thread (idempotent); returns whether the
    sampler is running afterwards.  ``TPU_PROF=0`` makes this a
    documented no-op — the one-knob kill switch."""
    global _thread, _stop_event, _started_mono
    if not enabled():
        return False
    rate = resolve_hz() if hz is None else min(max(float(hz), MIN_HZ),
                                               MAX_HZ)
    with _lock:
        if _thread is not None and _thread.is_alive():
            return True
        if _started_mono is None:
            _started_mono = time.monotonic()
        stop_event = threading.Event()
        t = threading.Thread(target=_loop,
                             args=(stop_event, 1.0 / rate),
                             name="tpu-prof", daemon=True)
        _stop_event, _thread = stop_event, t
        # Started under the lock: a concurrent start() must observe
        # this thread as alive, or it would overwrite the globals and
        # leak an unstoppable duplicate sampler.
        t.start()
    return True


def stop() -> None:
    """Park the sampler; the aggregate registry stays readable."""
    global _thread, _stop_event
    with _lock:
        t, ev = _thread, _stop_event
        _thread = _stop_event = None
    if ev is not None:
        ev.set()
    if t is not None and t.is_alive():
        t.join(timeout=2.0)


def reset() -> None:
    """Stop the sampler and drop every aggregate — test isolation
    only, same contract as ``timeseries.reset()``."""
    global _samples, _dropped, _sample_time_s, _started_mono
    stop()
    _fold_cache.clear()
    with _lock:
        _stacks.clear()
        _subsystems.clear()
        _samples = 0
        _dropped = 0
        _sample_time_s = 0.0
        _started_mono = None
