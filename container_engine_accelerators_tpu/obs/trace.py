"""Dependency-free span tracing for the node agents.

The self-healing layer made recovery *countable* (metrics/counters.py);
this makes it *traceable*: where does a slow or flapping transfer spend
its time?  A span records a named, monotonic-clocked interval with
attributes, a trace/span id pair, and a parent link taken from
thread-local context, so the DCN client's reconnect, the flow replay it
triggers, and the retried op that rode it all hang off one trace.

Spans land in two places:

- an in-memory **ring buffer** (always on, bounded) — the flight
  recorder (obs/flight.py) dumps its tail on SIGUSR1 or terminal
  failure;
- a **JSONL sink** when ``TPU_TRACE_FILE`` names a path — one JSON
  object per completed span, summarized offline by
  ``cmd/agent_trace.py`` the way ``cmd/trace_summary.py`` digests XLA
  xplanes.

JSONL schema (one line per span)::

    {"trace": "9f2c…", "span": "a1b2…", "parent": "c3d4…"|null,
     "name": "dcn.send", "ts": 1722650000.123, "dur_us": 152.4,
     "status": "ok"|"error", "thread": "MainThread", "attrs": {...}}

``ts`` is wall-clock (correlation with logs/Prometheus scrapes);
``dur_us`` comes from the monotonic clock (immune to NTP steps).

Kept stdlib-only, like metrics/counters.py, so utils/ and parallel/
import it without dragging in prometheus_client or grpc.  A sink write
failure is logged once and disables the sink — tracing must never take
down a node agent.
"""

import contextlib
import json
import logging
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

log = logging.getLogger(__name__)

TRACE_FILE_ENV = "TPU_TRACE_FILE"
RING_CAPACITY_ENV = "TPU_TRACE_RING"
DEFAULT_RING_CAPACITY = 512


class Span:
    """One named interval.  Mutable while active (annotate()); frozen
    into a dict when it finishes."""

    __slots__ = (
        "trace_id", "span_id", "parent_id", "name", "attrs",
        "status", "ts", "_t0", "duration_s", "thread",
    )

    def __init__(self, name: str, trace_id: str, span_id: str,
                 parent_id: Optional[str], attrs: Dict[str, Any]):
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.attrs = attrs
        self.status = "ok"
        self.ts = time.time()
        self._t0 = time.monotonic()
        self.duration_s: float = 0.0
        self.thread = threading.current_thread().name

    def annotate(self, **attrs: Any) -> "Span":
        self.attrs.update(attrs)
        return self

    def to_dict(self) -> Dict[str, Any]:
        return {
            "trace": self.trace_id,
            "span": self.span_id,
            "parent": self.parent_id,
            "name": self.name,
            "ts": round(self.ts, 6),
            "dur_us": round(self.duration_s * 1e6, 1),
            "status": self.status,
            "thread": self.thread,
            "attrs": self.attrs,
        }


def _env_int(name: str, default: int) -> int:
    """A malformed tuning knob degrades to the default — config typos
    must never take a node agent down (the TPU_FAULT_SPEC rule)."""
    raw = os.environ.get(name)
    if raw is None:
        return default
    try:
        return int(raw)
    except ValueError:
        log.error("ignoring malformed %s=%r; using %d", name, raw, default)
        return default


_local = threading.local()  # .stack: List[Span] per thread
_lock = threading.Lock()  # ring + sink
_ring: "deque[Dict[str, Any]]" = deque(
    maxlen=_env_int(RING_CAPACITY_ENV, DEFAULT_RING_CAPACITY)
)
# Sink states: None = unresolved (consult env on next span), False =
# resolved-off, file object = resolved-on.
_sink = None
_sink_path: Optional[str] = None


def _new_id(nbytes: int) -> str:
    return os.urandom(nbytes).hex()


def _stack() -> List[Span]:
    stack = getattr(_local, "stack", None)
    if stack is None:
        stack = _local.stack = []
    return stack


def current() -> Optional[Span]:
    """The active span on this thread, or None."""
    stack = _stack()
    return stack[-1] if stack else None


def annotate(**attrs: Any) -> None:
    """Attach attributes to the active span; no-op without one (so
    instrumented leaf code never needs to know whether a caller
    traced it — faults.py uses this to stamp ``fault=<site>``)."""
    span = current()
    if span is not None:
        span.annotate(**attrs)


def _resolve_sink():
    """Open the JSONL sink from TPU_TRACE_FILE (lazily, once)."""
    global _sink, _sink_path
    if _sink is None:
        path = _sink_path or os.environ.get(TRACE_FILE_ENV)
        if not path:
            _sink = False
        else:
            try:
                _sink = open(path, "a", buffering=1)
                _sink_path = path
            except OSError as e:
                log.error("cannot open trace sink %s: %s; tracing to "
                          "ring buffer only", path, e)
                _sink = False
    return _sink


def _record(span: Span) -> None:
    d = span.to_dict()
    global _sink
    with _lock:
        _ring.append(d)
        sink = _resolve_sink()
        if sink:
            try:
                sink.write(json.dumps(d) + "\n")
            except (OSError, ValueError) as e:  # ValueError: closed file
                log.error("trace sink write failed: %s; disabling sink", e)
                _sink = False


@contextlib.contextmanager
def span(name: str, histogram: Optional[str] = None, **attrs: Any):
    """Open a span; it closes (and records) when the block exits.

    ``histogram=<op>`` additionally feeds the span's duration into
    ``obs.histo`` under that op — one call site, both surfaces.  An
    exception marks the span ``status="error"`` (with the repr in
    ``attrs.error``) and propagates untouched.
    """
    parent = current()
    s = Span(
        name,
        trace_id=parent.trace_id if parent else _new_id(8),
        span_id=_new_id(4),
        parent_id=parent.span_id if parent else None,
        attrs=dict(attrs),
    )
    stack = _stack()
    stack.append(s)
    try:
        yield s
    except BaseException as e:
        s.status = "error"
        s.attrs.setdefault("error", repr(e))
        raise
    finally:
        s.duration_s = time.monotonic() - s._t0
        stack.pop()
        _record(s)
        if histogram is not None:
            from container_engine_accelerators_tpu.obs import histo

            histo.observe(histogram, s.duration_s)


def event(name: str, **attrs: Any) -> None:
    """A zero-duration marker span (a point in the timeline — health
    transitions, announcements)."""
    with span(name, **attrs):
        pass


def tail(n: Optional[int] = None) -> List[Dict[str, Any]]:
    """The last ``n`` completed spans (all buffered ones when None),
    oldest first — what the flight recorder dumps."""
    with _lock:
        spans = list(_ring)
    return spans if n is None else spans[-n:]


def configure(path: Optional[str] = None,
              ring_capacity: Optional[int] = None) -> None:
    """Point the sink at ``path`` (None ⇒ re-resolve from env on next
    span) and optionally resize the ring.  Tests and long-lived agents
    rotating their trace file use this; plain processes just set
    ``TPU_TRACE_FILE`` before the first span."""
    global _sink, _sink_path, _ring
    with _lock:
        if _sink:
            try:
                _sink.close()
            except OSError:
                pass
        _sink = None
        _sink_path = path
        if ring_capacity is not None:
            _ring = deque(_ring, maxlen=ring_capacity)


def reset() -> None:
    """Drop buffered spans and forget the resolved sink (test
    isolation; the next span re-reads TPU_TRACE_FILE)."""
    global _sink, _sink_path
    with _lock:
        _ring.clear()
        if _sink:
            try:
                _sink.close()
            except OSError:
                pass
        _sink = None
        _sink_path = None
