"""Dependency-free span tracing for the node agents.

The self-healing layer made recovery *countable* (metrics/counters.py);
this makes it *traceable*: where does a slow or flapping transfer spend
its time?  A span records a named, monotonic-clocked interval with
attributes, a trace/span id pair, and a parent link taken from
thread-local context, so the DCN client's reconnect, the flow replay it
triggers, and the retried op that rode it all hang off one trace.

Spans land in two places:

- an in-memory **ring buffer** (always on, bounded) — the flight
  recorder (obs/flight.py) dumps its tail on SIGUSR1 or terminal
  failure;
- a **JSONL sink** when ``TPU_TRACE_FILE`` names a path — one JSON
  object per completed span, summarized offline by
  ``cmd/agent_trace.py`` the way ``cmd/trace_summary.py`` digests XLA
  xplanes.

JSONL schema (one line per span)::

    {"trace": "9f2c…", "span": "a1b2…", "parent": "c3d4…"|null,
     "name": "dcn.send", "ts": 1722650000.123, "dur_us": 152.4,
     "status": "ok"|"error", "thread": "MainThread", "attrs": {...}}

``ts`` is wall-clock (correlation with logs/Prometheus scrapes);
``dur_us`` comes from the monotonic clock (immune to NTP steps).

Cross-process context: a trace is not bounded by one process.  A parent
process (the fleet coordinator, a test rig) exports
``TPU_TRACE_CONTEXT="<trace>:<span>"``; children call
:func:`attach_from_env` so their root spans join the parent's trace.
The DCN control protocol and the fleet data-plane frames carry the same
pair, so one cross-node transfer reads as ONE trace across every
process it touched (merge the JSONLs with ``cmd/agent_trace.py a.jsonl
b.jsonl --trace ID``).

Head sampling: ``TPU_TRACE_SAMPLE=<rate>`` (0.0–1.0) samples whole
traces into the JSONL sink by a deterministic hash of the trace id, so
every span of one trace — in every process, because the id travels —
shares a fate.  The in-memory ring is NOT sampled (the flight recorder
must always have the tail).  A malformed rate degrades to
sample-everything: a config typo must never blind a node agent.

Sink bounding: ``TPU_TRACE_MAX_BYTES`` caps the JSONL sink with a
size-triggered rotation — when the file passes the cap it is renamed
to ``<path>.1`` (ONE kept generation, the previous ``.1`` replaced)
and a fresh file is opened, so a long fleet/serving run can hold at
most ~2x the cap on disk.  Unset/0 means unbounded (the historical
behavior); a malformed value degrades to unbounded, and a failed
rotation disables rotation but never the sink.

Ring cursor: every recorded span gets a process-wide sequence number;
:func:`tail_since` returns the spans recorded after a cursor (bounded
by the ring) plus the new cursor and how many were evicted unseen —
what the MetricServer's ``/spans?since=`` endpoint and the fleet
telemetry span collector page through.

Kept stdlib-only, like metrics/counters.py, so utils/ and parallel/
import it without dragging in prometheus_client or grpc.  A sink write
failure is logged once and disables the sink — tracing must never take
down a node agent.
"""

import contextlib
import json
import logging
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

log = logging.getLogger(__name__)

TRACE_FILE_ENV = "TPU_TRACE_FILE"
RING_CAPACITY_ENV = "TPU_TRACE_RING"
TRACE_SAMPLE_ENV = "TPU_TRACE_SAMPLE"
TRACE_CONTEXT_ENV = "TPU_TRACE_CONTEXT"
TRACE_MAX_BYTES_ENV = "TPU_TRACE_MAX_BYTES"
DEFAULT_RING_CAPACITY = 512


class Span:
    """One named interval.  Mutable while active (annotate()); frozen
    into a dict when it finishes."""

    __slots__ = (
        "trace_id", "span_id", "parent_id", "name", "attrs",
        "status", "ts", "_t0", "duration_s", "thread",
    )

    def __init__(self, name: str, trace_id: str, span_id: str,
                 parent_id: Optional[str], attrs: Dict[str, Any]):
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.attrs = attrs
        self.status = "ok"
        self.ts = time.time()
        self._t0 = time.monotonic()
        self.duration_s: float = 0.0
        self.thread = threading.current_thread().name

    def annotate(self, **attrs: Any) -> "Span":
        self.attrs.update(attrs)
        return self

    def to_dict(self) -> Dict[str, Any]:
        return {
            "trace": self.trace_id,
            "span": self.span_id,
            "parent": self.parent_id,
            "name": self.name,
            "ts": round(self.ts, 6),
            "dur_us": round(self.duration_s * 1e6, 1),
            "status": self.status,
            "thread": self.thread,
            "attrs": self.attrs,
        }


def _env_int(name: str, default: int) -> int:
    """A malformed tuning knob degrades to the default — config typos
    must never take a node agent down (the TPU_FAULT_SPEC rule)."""
    raw = os.environ.get(name)
    if raw is None:
        return default
    try:
        return int(raw)
    except ValueError:
        log.error("ignoring malformed %s=%r; using %d", name, raw, default)
        return default


_local = threading.local()  # .stack: List[Span] per thread
_lock = threading.Lock()  # ring + sink
_ring: "deque[Dict[str, Any]]" = deque(
    maxlen=_env_int(RING_CAPACITY_ENV, DEFAULT_RING_CAPACITY)
)
# Sink states: None = unresolved (consult env on next span), False =
# resolved-off, file object = resolved-on.
_sink = None
_sink_path: Optional[str] = None
# Sample rate: None = unresolved (consult env on next span).
_sample_rate: Optional[float] = None
# Sink rotation cap: None = unresolved, 0 = unbounded.
_max_bytes: Optional[int] = None
# Process-wide cursor: sequence number of the most recently recorded
# span (ring and sink share it; tail_since pages by it).
_seq = 0


def _new_id(nbytes: int) -> str:
    return os.urandom(nbytes).hex()


def _resolve_sample_rate() -> float:
    """Parse TPU_TRACE_SAMPLE once.  Anything that is not a float in
    [0, 1] degrades to 1.0 (sample everything) — the TPU_FAULT_SPEC
    rule: a config typo must never blind a node agent."""
    global _sample_rate
    if _sample_rate is None:
        raw = os.environ.get(TRACE_SAMPLE_ENV)
        if raw is None:
            _sample_rate = 1.0
        else:
            try:
                rate = float(raw)
                if not 0.0 <= rate <= 1.0:
                    raise ValueError("rate outside [0, 1]")
                _sample_rate = rate
            except ValueError as e:
                log.error("ignoring malformed %s=%r (%s); sampling "
                          "everything", TRACE_SAMPLE_ENV, raw, e)
                _sample_rate = 1.0
    return _sample_rate


# Hash denominator for the head-sampling decision: the first 8 hex chars
# of the trace id interpreted as an integer, uniform over 32 bits.
_SAMPLE_MOD = 1 << 32


def sampled(trace_id: str) -> bool:
    """Head-sampling decision for a whole trace, deterministic by trace
    id — every span of the trace, in every process the id travels to,
    shares one fate.  Non-hex (foreign) ids sample in: losing evidence
    is worse than an oversized JSONL."""
    rate = _resolve_sample_rate()
    if rate >= 1.0:
        return True
    if rate <= 0.0:
        return False
    try:
        return int(trace_id[:8], 16) < rate * _SAMPLE_MOD
    except (ValueError, TypeError):
        return True


def _stack() -> List[Span]:
    stack = getattr(_local, "stack", None)
    if stack is None:
        stack = _local.stack = []
    return stack


def current() -> Optional[Span]:
    """The active span on this thread, or None."""
    stack = _stack()
    return stack[-1] if stack else None


def annotate(**attrs: Any) -> None:
    """Attach attributes to the active span; no-op without one (so
    instrumented leaf code never needs to know whether a caller
    traced it — faults.py uses this to stamp ``fault=<site>``)."""
    span = current()
    if span is not None:
        span.annotate(**attrs)


def _resolve_sink():
    """Open the JSONL sink from TPU_TRACE_FILE (lazily, once)."""
    global _sink, _sink_path
    if _sink is None:
        path = _sink_path or os.environ.get(TRACE_FILE_ENV)
        if not path:
            _sink = False
        else:
            try:
                _sink = open(path, "a", buffering=1)
                _sink_path = path
            except OSError as e:
                log.error("cannot open trace sink %s: %s; tracing to "
                          "ring buffer only", path, e)
                _sink = False
    return _sink


def _resolve_max_bytes() -> int:
    """Parse TPU_TRACE_MAX_BYTES once; <= 0 or malformed means
    unbounded (the TPU_FAULT_SPEC rule: a typo'd cap must not cost
    evidence)."""
    global _max_bytes
    if _max_bytes is None:
        _max_bytes = max(0, _env_int(TRACE_MAX_BYTES_ENV, 0))
    return _max_bytes


def _maybe_rotate(sink) -> None:
    """Size-capped sink rotation: past the cap, the live file becomes
    ``<path>.1`` (replacing any previous generation) and a fresh file
    opens.  Called under _lock.  A failed rotation disables rotation
    for this process — never the sink itself."""
    global _sink, _max_bytes
    cap = _resolve_max_bytes()
    if not cap or not _sink_path:
        return
    try:
        if sink.tell() < cap:
            return
        # Multi-writer guard: several processes may share one
        # TPU_TRACE_FILE path (fleet workers inherit the coordinator's
        # env).  Only the writer whose fd still IS the live path may
        # rename it — if another process rotated first, our fd now
        # points at the .1 generation, and renaming the path again
        # would clobber that process's fresh live file with it.  Skip
        # the rename and just reopen the live path instead.
        try:
            live = os.stat(_sink_path)
            fd = os.fstat(sink.fileno())
            owns_live = (fd.st_ino == live.st_ino
                         and fd.st_dev == live.st_dev)
        except OSError:
            owns_live = False  # path vanished: nothing to rename
        sink.close()
        if owns_live:
            os.replace(_sink_path, _sink_path + ".1")
        _sink = open(_sink_path, "a", buffering=1)
    except OSError as e:
        log.error("trace sink rotation of %s failed: %s; rotation "
                  "disabled (sink stays on)", _sink_path, e)
        _max_bytes = 0
        if _sink is None or _sink is False or _sink.closed:
            try:
                _sink = open(_sink_path, "a", buffering=1)
            except OSError as e2:
                log.error("trace sink reopen failed: %s; disabling "
                          "sink", e2)
                _sink = False


def _record(span: Span) -> None:
    d = span.to_dict()
    global _sink, _seq
    with _lock:
        # The ring is never sampled: the flight recorder's tail must
        # exist even at aggressive sink sampling rates.
        _ring.append(d)
        _seq += 1
        if not sampled(span.trace_id):
            return
        sink = _resolve_sink()
        if sink:
            try:
                sink.write(json.dumps(d) + "\n")
                _maybe_rotate(_sink)
            except (OSError, ValueError) as e:  # ValueError: closed file
                log.error("trace sink write failed: %s; disabling sink", e)
                _sink = False


@contextlib.contextmanager
def span(name: str, histogram: Optional[str] = None, **attrs: Any):
    """Open a span; it closes (and records) when the block exits.

    ``histogram=<op>`` additionally feeds the span's duration into
    ``obs.histo`` under that op — one call site, both surfaces.  An
    exception marks the span ``status="error"`` (with the repr in
    ``attrs.error``) and propagates untouched.
    """
    parent = current()
    s = Span(
        name,
        trace_id=parent.trace_id if parent else _new_id(8),
        span_id=_new_id(4),
        parent_id=parent.span_id if parent else None,
        attrs=dict(attrs),
    )
    stack = _stack()
    stack.append(s)
    try:
        yield s
    except BaseException as e:
        s.status = "error"
        s.attrs.setdefault("error", repr(e))
        raise
    finally:
        s.duration_s = time.monotonic() - s._t0
        stack.pop()
        _record(s)
        if histogram is not None:
            from container_engine_accelerators_tpu.obs import histo

            # The span's own trace id rides along so the histogram
            # bucket can keep a trace exemplar for its worst sample.
            histo.observe(histogram, s.duration_s, trace_id=s.trace_id)


def event(name: str, **attrs: Any) -> None:
    """A zero-duration marker span (a point in the timeline — health
    transitions, announcements)."""
    with span(name, **attrs):
        pass


def record_span(name: str, duration_s: float,
                end_ts: Optional[float] = None,
                trace_id: Optional[str] = None,
                parent_id: Optional[str] = None,
                status: str = "ok", **attrs: Any) -> Span:
    """Record an already-measured interval as a completed span — for
    phases whose start and end were observed on DIFFERENT threads
    (serving queue wait: submitted on the caller's thread, cut on the
    batcher's), where no ``with span(...)`` block can bracket them.
    ``end_ts`` is the wall-clock end (now when None); trace/parent
    default to the calling thread's active span so recorded phases
    nest like live ones."""
    cur = current()
    s = Span(
        name,
        trace_id=trace_id or (cur.trace_id if cur else _new_id(8)),
        span_id=_new_id(4),
        parent_id=parent_id or (cur.span_id if cur else None),
        attrs=dict(attrs),
    )
    s.status = status
    s.duration_s = max(0.0, float(duration_s))
    s.ts = (end_ts if end_ts is not None else time.time()) \
        - s.duration_s
    _record(s)
    return s


@contextlib.contextmanager
def attach(trace_id: Optional[str], parent_span_id: Optional[str] = None):
    """Join a trace started elsewhere (another process, the far side of
    a DCN transfer): spans opened inside the block carry ``trace_id``
    and hang off ``parent_span_id``.  The placeholder itself is never
    recorded — the remote side already owns that span.  A falsy
    ``trace_id`` makes this a no-op, so protocol handlers can pass
    whatever the wire carried without checking."""
    if not trace_id:
        yield None
        return
    s = Span("remote", trace_id=str(trace_id),
             span_id=str(parent_span_id) if parent_span_id else _new_id(4),
             parent_id=None, attrs={})
    stack = _stack()
    stack.append(s)
    try:
        yield s
    finally:
        stack.pop()


def context() -> Optional[Dict[str, str]]:
    """The active (trace, span) pair as wire/env fields, or None.  What
    the DCN client stamps on control requests and the fleet daemon
    stamps on data-plane frames."""
    cur = current()
    if cur is None:
        return None
    return {"trace": cur.trace_id, "span": cur.span_id}


def context_env() -> Optional[str]:
    """The active context in TPU_TRACE_CONTEXT form ("<trace>:<span>"),
    for a coordinator exporting it to child processes."""
    cur = current()
    if cur is None:
        return None
    return f"{cur.trace_id}:{cur.span_id}"


def attach_from_env(env=None):
    """Context manager joining the trace named by TPU_TRACE_CONTEXT
    ("<trace>:<span>", set by the process that spawned us).  Unset or
    malformed values yield a no-op attach — a worker must boot with or
    without a coordinator."""
    env = env if env is not None else os.environ
    raw = env.get(TRACE_CONTEXT_ENV, "")
    trace_id, _, span_id = raw.partition(":")
    if raw and (not trace_id or not span_id or ":" in span_id):
        log.error("ignoring malformed %s=%r (want '<trace>:<span>')",
                  TRACE_CONTEXT_ENV, raw)
        trace_id = span_id = ""
    return attach(trace_id or None, span_id or None)


def tail(n: Optional[int] = None) -> List[Dict[str, Any]]:
    """The last ``n`` completed spans (all buffered ones when None),
    oldest first — what the flight recorder dumps."""
    with _lock:
        spans = list(_ring)
    return spans if n is None else spans[-n:]


def tail_since(cursor: int, limit: Optional[int] = None):
    """Cursor-paged ring read: ``(spans, next_cursor, dropped)`` where
    ``spans`` are the (oldest-first) spans recorded after ``cursor``
    that are still in the ring, ``next_cursor`` is what the caller
    passes next time, and ``dropped`` counts spans recorded after the
    cursor but already evicted (the ring outran the reader).  With
    ``limit``, at most that many are returned and the cursor advances
    only past them — nothing is silently skipped.  What the
    ``/spans?since=`` endpoint serves."""
    cursor = max(0, int(cursor))
    with _lock:
        last = _seq
        behind = max(0, last - cursor)
        avail = min(len(_ring), behind)
        dropped = behind - avail
        if limit is not None and avail > int(limit):
            take = max(0, int(limit))
            spans = list(_ring)[-avail:][:take]
            return spans, cursor + dropped + take, dropped
        spans = list(_ring)[-avail:] if avail else []
        return spans, last, dropped


def configure(path: Optional[str] = None,
              ring_capacity: Optional[int] = None) -> None:
    """Point the sink at ``path`` (None ⇒ re-resolve from env on next
    span) and optionally resize the ring.  Tests and long-lived agents
    rotating their trace file use this; plain processes just set
    ``TPU_TRACE_FILE`` before the first span."""
    global _sink, _sink_path, _ring, _sample_rate, _max_bytes
    with _lock:
        if _sink:
            try:
                _sink.close()
            except OSError:
                pass
        _sink = None
        _sink_path = path
        _sample_rate = None  # re-resolve TPU_TRACE_SAMPLE too
        _max_bytes = None  # re-resolve TPU_TRACE_MAX_BYTES too
        if ring_capacity is not None:
            _ring = deque(_ring, maxlen=ring_capacity)


def reset() -> None:
    """Drop buffered spans and forget the resolved sink, sample rate,
    and ring cursor (test isolation; the next span re-reads
    TPU_TRACE_FILE / TPU_TRACE_SAMPLE).  Production readers never see
    this — a live agent's cursor only moves forward."""
    global _sink, _sink_path, _sample_rate, _max_bytes, _seq
    with _lock:
        _ring.clear()
        _seq = 0
        if _sink:
            try:
                _sink.close()
            except OSError:
                pass
        _sink = None
        _sink_path = None
        _sample_rate = None
        _max_bytes = None
