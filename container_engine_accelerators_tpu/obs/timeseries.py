"""Windowed time-series: ring-bucket rates and explicit gauges.

Counters (metrics/counters.py) and histograms (obs/histo.py) are
cumulative for the life of the process — the right contract for
Prometheus, useless for the question an operator actually asks when a
transfer stalls: what is this node doing NOW?  Forty million bytes
transferred since boot says nothing about whether the link moved a
byte in the last second.

This module closes that gap with two primitives, both stdlib-only like
the rest of obs/:

- **Series**: a ring of time buckets (``BUCKET_S`` seconds each,
  ``NUM_BUCKETS`` deep).  ``record(name, value)`` adds into the bucket
  the current moment falls in; ``rate(name, window_s)`` sums the
  buckets inside the window and divides — a per-second rate that
  decays to zero by construction when traffic stops (old buckets fall
  out of the window; nothing ever needs a background thread).  Every
  ``counters.inc`` feeds its series automatically, so every counter
  has a windowed rate for free (exported as ``agent_rate{event=...}``),
  and byte-valued series (``*.bytes``, ``goodput.*``) give bandwidth.

- **Gauges**: instantaneous values — in-flight chunks, active stripes,
  retransmit ratios, SLO verdicts — set or nudged directly
  (``gauge``/``gauge_add``), exported as ``agent_gauge{name=...}``.

Naming convention for series: counter names stay themselves
(``dcn.frames.deduped``); throughput series end in ``.bytes``
(``xferd.rx.bytes``); goodput series are
``goodput.<scope>.<name>`` with scope ``flow``/``link``/``node`` —
the MetricServer splits that prefix into the
``agent_goodput{scope=...,name=...}`` family.  Goodput means bytes
that LANDED usefully: dedup-dropped replays and link-eaten frames
never count.

Every function takes an optional ``now`` (monotonic seconds) so tests
drive the clock instead of sleeping through real windows.
"""

import os
import threading
import time
from typing import Dict, List, Optional, Tuple

BUCKET_S = 1.0
NUM_BUCKETS = 64  # ~64s of history; windows beyond that clamp
RATE_WINDOW_ENV = "TPU_RATE_WINDOW_S"
DEFAULT_WINDOW_S = 10.0

# Series names are unbounded in principle (per-flow goodput names are
# unique per transfer), so the registry self-prunes: once it holds more
# than MAX_SERIES entries, creating a series evicts every series whose
# last traffic fell out of the ring entirely (at most once per bucket
# epoch, so a creation storm cannot turn every insert into a rescan).
# A stopped flow therefore exports an explicit 0.0 for a full ring
# span (~NUM_BUCKETS seconds — long enough for any scraper to see it
# die), then vanishes instead of leaking memory and label cardinality.
# HARD_MAX_SERIES is the true bound for a storm of still-live names:
# past it, the oldest quarter is evicted outright — losing tail series
# under pathological churn beats unbounded exporter cardinality.
MAX_SERIES = 512
HARD_MAX_SERIES = 4 * MAX_SERIES

GOODPUT_PREFIX = "goodput."

_lock = threading.Lock()


class _Series:
    __slots__ = ("sums", "epochs")

    def __init__(self):
        self.sums: List[float] = [0.0] * NUM_BUCKETS
        # Which absolute bucket epoch each slot currently holds; a slot
        # whose epoch is stale is logically empty (lazily recycled).
        self.epochs: List[int] = [-1] * NUM_BUCKETS


_series: Dict[str, _Series] = {}
_gauges: Dict[str, float] = {}
_last_prune_epoch = -1


def default_window_s() -> float:
    """Export window, env-tunable; malformed values degrade to the
    default (the TPU_FAULT_SPEC rule)."""
    raw = os.environ.get(RATE_WINDOW_ENV)
    if raw is None:
        return DEFAULT_WINDOW_S
    try:
        w = float(raw)
        if not w > 0:
            raise ValueError("window must be > 0")
        return min(w, NUM_BUCKETS * BUCKET_S)
    except ValueError:
        return DEFAULT_WINDOW_S


def _prune_locked(epoch: int) -> None:
    """Evict stale (then, under a storm, oldest) series; caller holds
    the lock.  Stale pruning runs at most once per bucket epoch; the
    hard-cap eviction amortizes by dropping a whole quarter at once."""
    global _last_prune_epoch
    if epoch != _last_prune_epoch:
        _last_prune_epoch = epoch
        floor = epoch - NUM_BUCKETS
        for name in [n for n, s in _series.items()
                     if max(s.epochs) < floor]:
            del _series[name]
    if len(_series) >= HARD_MAX_SERIES:
        by_age = sorted(_series, key=lambda n: max(_series[n].epochs))
        for name in by_age[:HARD_MAX_SERIES // 4]:
            del _series[name]


def record(name: str, value: float = 1.0,
           now: Optional[float] = None) -> None:
    """Add ``value`` into ``name``'s current time bucket (series
    created on first record)."""
    now = time.monotonic() if now is None else now
    epoch = int(now / BUCKET_S)
    idx = epoch % NUM_BUCKETS
    with _lock:
        s = _series.get(name)
        if s is None:
            if len(_series) >= MAX_SERIES:
                _prune_locked(epoch)
            s = _series[name] = _Series()
        if s.epochs[idx] != epoch:
            s.sums[idx] = 0.0
            s.epochs[idx] = epoch
        s.sums[idx] += value


def _rate_locked(s: _Series, floor: int, epoch: int,
                 window_s: float) -> float:
    return sum(s.sums[i] for i in range(NUM_BUCKETS)
               if floor <= s.epochs[i] <= epoch) / window_s


def _window_bounds(window_s: Optional[float],
                   now: Optional[float]):
    window_s = default_window_s() if window_s is None else window_s
    window_s = max(BUCKET_S, min(window_s, NUM_BUCKETS * BUCKET_S))
    now = time.monotonic() if now is None else now
    epoch = int(now / BUCKET_S)
    floor = epoch - int(window_s / BUCKET_S) + 1
    return window_s, epoch, floor


def rate(name: str, window_s: Optional[float] = None,
         now: Optional[float] = None) -> float:
    """Per-second rate of ``name`` over the trailing window (0.0 for an
    unknown series — an absent series and an idle one look the same,
    which is exactly what a dashboard wants)."""
    window_s, epoch, floor = _window_bounds(window_s, now)
    with _lock:
        s = _series.get(name)
        if s is None:
            return 0.0
        return _rate_locked(s, floor, epoch, window_s)


def rates(window_s: Optional[float] = None,
          now: Optional[float] = None) -> Dict[str, float]:
    """Every known series' windowed rate (idle series report 0.0 —
    a stopped flow must scrape as zero, not vanish).  One clock
    reading and one lock hold for the whole snapshot, so every series
    on a scrape is judged against the SAME window."""
    window_s, epoch, floor = _window_bounds(window_s, now)
    with _lock:
        return {name: _rate_locked(s, floor, epoch, window_s)
                for name, s in _series.items()}


def gauge(name: str, value: float) -> None:
    """Set an explicit instantaneous gauge."""
    with _lock:
        _gauges[name] = float(value)


def gauge_add(name: str, delta: float) -> float:
    """Nudge a gauge (created at 0); returns the new value.  The
    in-flight-count idiom: +1 on dispatch, -1 on settle."""
    with _lock:
        value = _gauges.get(name, 0.0) + delta
        _gauges[name] = value
        return value


def gauges() -> Dict[str, float]:
    with _lock:
        return dict(_gauges)


def split_goodput(name: str) -> Optional[Tuple[str, str]]:
    """``goodput.<scope>.<rest>`` -> (scope, rest), None for anything
    else — the exporter's one parsing rule."""
    if not name.startswith(GOODPUT_PREFIX):
        return None
    rest = name[len(GOODPUT_PREFIX):]
    scope, _, ident = rest.partition(".")
    if not scope or not ident:
        return None
    return scope, ident


def snapshot(window_s: Optional[float] = None,
             now: Optional[float] = None) -> Dict[str, dict]:
    """One blob for the flight recorder / fleet aggregator:
    ``{"window_s": w, "rates": {name: per_s}, "gauges": {name: v}}``."""
    window_s = default_window_s() if window_s is None else window_s
    return {
        "window_s": window_s,
        "rates": rates(window_s, now),
        "gauges": gauges(),
    }


def least_squares_slope(points) -> float:
    """Ordinary-least-squares slope of ``(x, y)`` pairs — the windowed
    trend gate behind the soak world's leak sentinel (fleet/soak.py):
    a resource series whose fitted slope exceeds its per-window budget
    is a leak, whatever its instantaneous wobble.  Fewer than two
    points, or zero x-variance, judge nothing and return 0.0."""
    pts = [(float(x), float(y)) for x, y in points]
    n = len(pts)
    if n < 2:
        return 0.0
    mean_x = sum(x for x, _ in pts) / n
    mean_y = sum(y for _, y in pts) / n
    var_x = sum((x - mean_x) ** 2 for x, _ in pts)
    if var_x <= 0.0:
        return 0.0
    cov = sum((x - mean_x) * (y - mean_y) for x, y in pts)
    return cov / var_x


def reset() -> None:
    """Drop every series and gauge — test isolation only, same contract
    as counters.reset()."""
    global _last_prune_epoch
    with _lock:
        _series.clear()
        _gauges.clear()
        _last_prune_epoch = -1
