"""Grey-failure detection: peer-relative anomaly scoring with a
hysteresis verdict ladder.

The fleet's health machinery is crash-detector-shaped: a dead worker
misses its scrape, a partitioned link drops frames, a chip fault fires
an Xid — every one of them emits a SIGNAL.  The soak world's grey
faults are designed NOT to: ``grey:`` (shim latency + CPU burn),
``slow_ring`` (a crawling completer), ``slow_shm`` (a throttled shm
commit) keep every health check green while a node quietly costs the
fleet half its goodput.  Today nothing notices until a post-hoc
sentinel or SLO breach; this module is the live detector.

**Scoring is peer-relative**, the run-ledger discipline
(obs/history.py) applied across space instead of time: per metric per
window, each entity's value is scored as a robust z against its
same-tier peers —

    z = bad_direction_deviation / max(MAD, 5% * |median|, abs_floor)

so one sick node among N healthy peers scores enormous (the healthy
majority pins the median and the MAD collapses to the floor), while a
GLOBAL slowdown — every node slower because the host is loaded —
scores ~0 for everyone: the median moves with the fleet.  Windows
where the peers carry no signal at all (an idle fleet: median ~0 and
MAD ~0 against an absolute floor of 0 evidence) contribute nothing —
degenerate dispersion is not evidence, exactly like the ledger's
``no_baseline`` verdict.

**Verdicts step, never flap**: per-window instantaneous scores fold
into an EWMA suspicion score per entity, and the verdict ladder is
hysteretic —

    healthy --(window z >= suspect_z)--> suspect
    suspect --(confirm_windows consecutive hot windows)--> confirmed
    {suspect,confirmed} --(clear_windows consecutive EWMA < clear_z)--> healthy

Hot windows are judged on the instantaneous per-window z (the EWMA
lags by design, and a spike's decay tail must not impersonate
sustained evidence); quiet windows on the EWMA (one calm window must
not clear a deep suspicion).  A single-window spike suspects; only
sustained deviation confirms; a heal must hold quiet for
``clear_windows`` before the verdict clears.
An entity that was absent from a window (down, stale scrape) HOLDS its
state — no observation is not evidence of health.

Confirmation is observation-first (``TPU_ANOMALY=0`` kill switch): it
fires a flight-recorder dump and an ``anomaly.confirmed`` trace
marker, publishes ``anomaly.score.<entity>`` / ``anomaly.state.<entity>``
gauges and ``anomaly.{suspect,confirmed,cleared}`` counters, and can
feed the placement search a :meth:`AnomalyDetector.scheduler_penalty`
surcharge — evidence for the schedulers, never a veto.

The headline gate is closed-loop: the soak world knows its seeded
:class:`~container_engine_accelerators_tpu.fleet.soak.SoakSchedule`,
so :func:`detection_report` judges the detector against ground truth —
recall over the seeded grey windows (each must be flagged within K
windows of onset), false positives only on CLEAN windows (collateral
suspicion while chaos is in flight is the fleet being honest, not the
detector being wrong), and the ``max_grey_detection_windows`` SLO /
``anomaly.detect_windows_max`` ledger metric carrying the latency.

Stdlib-only, like the rest of obs/.
"""

import logging
import os
from dataclasses import dataclass, field
from typing import Callable, Dict, Iterable, List, Optional, Set

from container_engine_accelerators_tpu.metrics import counters
from container_engine_accelerators_tpu.obs import timeseries, trace

log = logging.getLogger(__name__)

# Kill switch: TPU_ANOMALY=0 disables scoring and every side effect
# (gauges, counters, dumps, penalties) — the standard observation-first
# rollout contract (TPU_DCN_TUNE's first life).
KILL_SWITCH_ENV = "TPU_ANOMALY"

# Verdict states, published as the anomaly.state.<entity> gauge.
HEALTHY, SUSPECT, CONFIRMED = 0, 1, 2
STATE_NAMES = {HEALTHY: "healthy", SUSPECT: "suspect",
               CONFIRMED: "confirmed-grey"}

# Default detection-latency allowance (windows from fault onset to
# first flag) the closed-loop judge and the soak SLO use.
DETECT_WINDOWS_K = 2


def enabled() -> bool:
    """The kill switch verdict (default ON; ``TPU_ANOMALY=0`` off)."""
    return os.environ.get(KILL_SWITCH_ENV, "1") != "0"


@dataclass
class AnomalyConfig:
    """Detector knobs.  The defaults are deliberately conservative:
    suspicion needs a 3-sigma-equivalent robust deviation, confirmation
    needs it sustained, and clearing needs sustained quiet."""

    suspect_z: float = 3.0       # EWMA score that steps healthy->suspect
    clear_z: float = 1.5         # EWMA score below which quiet windows count
    confirm_windows: int = 2     # consecutive hot windows to confirm
    clear_windows: int = 2       # consecutive quiet windows to clear
    ewma_alpha: float = 0.5      # fold weight of the newest window
    score_cap: float = 12.0      # per-window clip: one absurd sample
    # must not take ages to decay
    rel_mad_floor: float = 0.05  # MAD floor as a fraction of |median|
    min_peers: int = 3           # fewer entities than this = no verdict
    # Observed windows to swallow before scoring: boot windows carry
    # cold-start transients (first-connection legs, half-warmed
    # histograms) with no meaningful peer baseline behind them.
    warmup_windows: int = 0


@dataclass
class Evidence:
    """One metric's per-entity values for one window.

    ``direction`` names which deviation is SICK: ``"high"`` (latency,
    RTT, busy share — bigger is worse) or ``"low"`` (goodput — smaller
    is worse).  ``abs_floor`` is the metric's absolute dispersion
    floor, in its own units: deviations under it are measurement
    noise, and a window whose every value sits under it is
    degenerate — an idle fleet, not evidence.  ``rel_floor``, when
    set, overrides the config's ``rel_mad_floor`` for THIS stream —
    the knob for streams whose healthy per-window dispersion is a
    large fraction of their magnitude (windowed byte counts quantize
    on payload boundaries: a node can honestly read half its peers'
    bytes one window and double the next).  At 0.5 such a stream can
    sustain suspicion in the EWMA but never convict on its own."""

    metric: str
    values: Dict[str, float]
    direction: str = "high"
    abs_floor: float = 0.0
    rel_floor: Optional[float] = None


def _median(xs: List[float]) -> float:
    s = sorted(xs)
    n = len(s)
    mid = n // 2
    return s[mid] if n % 2 else (s[mid - 1] + s[mid]) / 2.0


def robust_zscores(values: Dict[str, float], *, direction: str = "high",
                   abs_floor: float = 0.0, rel_mad_floor: float = 0.05,
                   min_peers: int = 3) -> Dict[str, float]:
    """Peer-relative robust z per entity: bad-direction deviation from
    the peer median over ``max(MAD, rel_mad_floor*|median|,
    abs_floor)``.  Only bad-direction deviations score (a node FASTER
    than its peers is not sick); degenerate windows — fewer than
    ``min_peers`` entities, or an idle fleet whose EVERY value sits
    under the absolute floor — score everyone 0.0: no dispersion
    baseline means no evidence, never a conviction.  Idleness is
    judged on every value, not the median: a 65ms outlier among
    sub-floor peers is the textbook one-sick-of-N, and a median test
    would wave it through as idle."""
    if len(values) < max(2, int(min_peers)):
        return {k: 0.0 for k in values}
    xs = list(values.values())
    med = _median(xs)
    mad = _median([abs(x - med) for x in xs])
    if all(abs(x) <= abs_floor for x in xs):
        return {k: 0.0 for k in values}  # idle fleet: not evidence
    denom = max(mad, rel_mad_floor * abs(med), abs_floor)
    if denom <= 0.0:
        return {k: 0.0 for k in values}
    out = {}
    for k, v in values.items():
        dev = (v - med) if direction == "high" else (med - v)
        out[k] = max(0.0, dev) / denom
    return out


class AnomalyDetector:
    """The fleet's grey-failure verdict machine: feed it one window of
    :class:`Evidence` per scrape round, read per-entity EWMA scores and
    ladder states back.  All side effects (gauges, counters, the
    confirm dump/marker) honor the kill switch; with it off,
    :meth:`observe` is inert and every entity stays healthy."""

    def __init__(self, cfg: Optional[AnomalyConfig] = None, *,
                 dump_on_confirm: bool = True):
        self.cfg = cfg or AnomalyConfig()
        self.enabled = enabled()
        self.dump_on_confirm = bool(dump_on_confirm)
        self.score: Dict[str, float] = {}
        self.state: Dict[str, int] = {}
        self._hot: Dict[str, int] = {}    # consecutive windows >= suspect_z
        self._quiet: Dict[str, int] = {}  # consecutive windows < clear_z
        # Every window in which an entity was flagged (suspect or
        # worse) — the closed-loop judge's input.
        self.flagged: Dict[str, List[int]] = {}
        self.confirmations: List[dict] = []
        self.windows_observed = 0

    # -- the per-window fold -------------------------------------------------

    def observe(self, window: int, evidence: Iterable[Evidence],
                absent: Optional[Set[str]] = None) -> Dict[str, float]:
        """Fold one window of evidence.  Each entity's instantaneous
        score is its WORST robust z across the window's metrics
        (clipped at ``score_cap``); absent entities hold their state
        and score untouched — a stale scrape is not health."""
        if not self.enabled:
            return {}
        cfg = self.cfg
        absent = absent or set()
        self.windows_observed += 1
        if self.windows_observed <= cfg.warmup_windows:
            return {}
        inst: Dict[str, float] = {}
        for ev in evidence:
            present = {k: v for k, v in ev.values.items()
                       if k not in absent}
            zs = robust_zscores(present, direction=ev.direction,
                                abs_floor=ev.abs_floor,
                                rel_mad_floor=(
                                    ev.rel_floor
                                    if ev.rel_floor is not None
                                    else cfg.rel_mad_floor),
                                min_peers=cfg.min_peers)
            for k, z in zs.items():
                inst[k] = max(inst.get(k, 0.0), min(z, cfg.score_cap))
        for entity, z in inst.items():
            prev = self.score.get(entity, 0.0)
            score = (1 - cfg.ewma_alpha) * prev + cfg.ewma_alpha * z
            self.score[entity] = score
            self._step(window, entity, score, z)
        for entity in inst:
            timeseries.gauge(f"anomaly.score.{entity}",
                             round(self.score[entity], 3))
            timeseries.gauge(f"anomaly.state.{entity}",
                             float(self.state.get(entity, HEALTHY)))
        return inst

    def _step(self, window: int, entity: str, score: float,
              inst: float) -> None:
        # Hotness is judged on the INSTANTANEOUS z: the EWMA lags by
        # design (a 12-cap spike reads 6 then 3 on the two windows
        # after), so counting consecutive hot windows on the EWMA
        # would let one spike's decay tail impersonate sustained
        # evidence and confirm.  Quiet is judged on the EWMA — the
        # slow side of the hysteresis — so clearing still demands the
        # whole suspicion to have drained, not one calm window.
        cfg = self.cfg
        state = self.state.get(entity, HEALTHY)
        hot = inst >= cfg.suspect_z
        quiet = score < cfg.clear_z
        self._hot[entity] = self._hot.get(entity, 0) + 1 if hot else 0
        self._quiet[entity] = (self._quiet.get(entity, 0) + 1
                               if quiet else 0)
        if state == HEALTHY and hot:
            state = SUSPECT
            counters.inc("anomaly.suspect")
            log.warning("anomaly: %s SUSPECT (score %.2f, window %d)",
                        entity, score, window)
        elif state == SUSPECT \
                and self._hot[entity] >= cfg.confirm_windows:
            state = CONFIRMED
            counters.inc("anomaly.confirmed")
            log.warning("anomaly: %s CONFIRMED grey (score %.2f, "
                        "window %d)", entity, score, window)
            self.confirmations.append(
                {"entity": entity, "window": window,
                 "score": round(score, 3)})
            trace.event("anomaly.confirmed", entity=entity,
                        window=window, score=round(score, 3))
            if self.dump_on_confirm:
                # Lazy import: flight pulls profiler/trace machinery
                # this module must not cost its importers.
                from container_engine_accelerators_tpu.obs import flight
                flight.dump(f"anomaly confirmed: {entity}")
        elif state in (SUSPECT, CONFIRMED) \
                and self._quiet[entity] >= cfg.clear_windows:
            state = HEALTHY
            counters.inc("anomaly.cleared")
            log.info("anomaly: %s cleared (score %.2f, window %d)",
                     entity, score, window)
        self.state[entity] = state
        if state != HEALTHY:
            self.flagged.setdefault(entity, []).append(window)

    # -- read-side -----------------------------------------------------------

    def verdicts(self) -> Dict[str, dict]:
        return {
            entity: {"state": STATE_NAMES[self.state.get(entity,
                                                         HEALTHY)],
                     "score": round(self.score.get(entity, 0.0), 3)}
            for entity in sorted(self.score)
        }

    def report(self) -> dict:
        """The ``report.anomaly`` section: per-entity verdicts, every
        confirmation with its window, and the flagged-window history
        the closed-loop judge consumes."""
        return {
            "enabled": self.enabled,
            "windows": self.windows_observed,
            "verdicts": self.verdicts(),
            "confirmations": list(self.confirmations),
            "flagged_windows": {k: list(v)
                                for k, v in sorted(self.flagged.items())},
        }

    # -- the placement feed --------------------------------------------------

    def scheduler_penalty(self, *, suspect_surcharge: float = 50.0,
                          confirmed_surcharge: float = 500.0,
                          ) -> Callable[[dict, dict], float]:
        """A distance-penalty callable for
        ``calculate_pods_assignment(link_penalty=)``, the CommGraph
        idiom (collectives/topo.py): candidate nodes map back to fleet
        nodes by the HOST label, a pair touching a suspect entity pays
        ``suspect_surcharge`` (confirmed pays more), unknown hosts pay
        nothing, and the surcharge is always finite — suspicion adds
        evidence, it never vetoes a placement."""
        from container_engine_accelerators_tpu.scheduler import (
            topology as sched_topo,
        )

        def penalty(node_a: dict, node_b: dict) -> float:
            if not self.enabled:
                return 0.0
            cost = 0.0
            for cand in (node_a, node_b):
                host = (cand.get("node_labels") or {}).get(
                    sched_topo.HOST_LABEL)
                state = self.state.get(host, HEALTHY) \
                    if host is not None else HEALTHY
                if state == CONFIRMED:
                    cost += confirmed_surcharge
                elif state == SUSPECT:
                    cost += suspect_surcharge
            return cost

        return penalty


# ---------------------------------------------------------------------------
# scraped-histogram evidence: per-window p99 from cumulative le buckets
# ---------------------------------------------------------------------------


def bucket_delta_p99_us(buckets: Dict[str, float],
                        baseline: Dict[str, float],
                        q: float = 0.99) -> Optional[float]:
    """Upper-bound q-quantile (µs) of the observations BETWEEN two
    scrapes of one ``agent_latency{op,bucket}`` family: cumulative le
    buckets (``+Inf`` = total) deltaed against the previous scrape.
    The scrape exports cumulative-per-bucket counts, so the delta is
    de-accumulated back to per-bucket before walking.  None when
    nothing was observed in the window (or a respawn made the delta
    nonsensical — callers reset baselines on generation change)."""
    def finite(b: Dict[str, float]) -> List[tuple]:
        out = []
        for le, n in b.items():
            if str(le) in ("+Inf", "inf"):
                continue
            try:
                out.append((float(le), float(n)))
            except (TypeError, ValueError):
                continue
        out.sort()
        return out

    cur = finite(buckets)
    base = finite(baseline)

    def base_cum_at(le: float) -> float:
        cum = 0.0
        for ble, bcum in base:
            if ble <= le:
                cum = bcum
            else:
                break
        return cum

    per_bucket: List[tuple] = []
    prev_delta_cum = 0.0
    for le, cum in cur:
        delta_cum = cum - base_cum_at(le)
        d = delta_cum - prev_delta_cum
        if d < -1e-9:
            return None  # counter went backwards: respawn, not evidence
        per_bucket.append((le, max(0.0, d)))
        prev_delta_cum = delta_cum
    total = sum(n for _, n in per_bucket)
    if total <= 0:
        return None
    target = q * total
    seen = 0.0
    for le, n in per_bucket:
        seen += n
        if seen >= target:
            return le
    return per_bucket[-1][0]


# ---------------------------------------------------------------------------
# the closed-loop judge: detector verdicts vs the seeded ground truth
# ---------------------------------------------------------------------------


@dataclass
class TruthWindow:
    """One seeded grey fault as ground truth: ``node`` was made grey
    at ``window`` for ``lifetime`` windows by fault ``kind``."""

    node: str
    window: int
    lifetime: int = 1
    kind: str = "grey"

    @property
    def end(self) -> int:
        return self.window + max(1, int(self.lifetime))


def detection_report(truth: List[TruthWindow],
                     flagged: Dict[str, List[int]],
                     windows: int, *,
                     k: int = DETECT_WINDOWS_K,
                     settle_windows: int = 4,
                     chaos_windows: Optional[Set[int]] = None,
                     ) -> dict:
    """Judge the detector against the seeded schedule.

    **Recall**: every truth entry must see its node flagged within
    ``k`` windows of onset (a flag at ``window + k`` still counts —
    evidence needs a window to accumulate).  **False positives** count
    only on CLEAN windows: a window with NO scheduled fault of any
    kind in flight fleet-wide (``chaos_windows`` — the full schedule's
    footprint, each entry padded by ``settle_windows`` of decay
    allowance after its end).  A healthy peer scored up while a grey
    node drags the whole ring is the fleet being honest about shared
    fate, not a detector bug — only a flag in a quiet fleet is.  And
    only a PERSISTENT one: a clean-window flag counts only when it is
    part of a run of consecutive flagged clean windows — the same
    persistence bar the verdict ladder demands before convicting.  A
    single hot window on a loaded host that self-clears next window
    is the hysteresis working, not a page.

    No truth at all is vacuous: recall 1.0, detect latency 0.0 — a
    clean run must never fail its own gate."""
    chaos: Set[int] = set(chaos_windows or set())
    for t in truth:
        for w in range(t.window, t.end + max(0, int(settle_windows))
                       + 1):
            chaos.add(w)
    detections = []
    missed = []
    latencies = []
    for t in truth:
        hit = None
        for w in flagged.get(t.node, []):
            if t.window <= w <= t.window + k:
                hit = w
                break
        entry = {"node": t.node, "kind": t.kind, "window": t.window,
                 "detected_window": hit,
                 "detect_windows": (hit - t.window
                                    if hit is not None else None)}
        detections.append(entry)
        if hit is None:
            missed.append(entry)
        else:
            latencies.append(hit - t.window)
    false_positives = []
    for node, ws in sorted(flagged.items()):
        clean = sorted({w for w in ws if w < windows
                        and w not in chaos})
        for i, w in enumerate(clean):
            persistent = ((i > 0 and clean[i - 1] == w - 1)
                          or (i + 1 < len(clean)
                              and clean[i + 1] == w + 1))
            if persistent:
                false_positives.append({"node": node, "window": w})
    recall = (1.0 if not truth
              else (len(truth) - len(missed)) / len(truth))
    return {
        "truth": len(truth),
        "recall": round(recall, 3),
        "k": int(k),
        "detections": detections,
        "missed": missed,
        "detect_windows_max": float(max(latencies) if latencies
                                    else 0.0),
        "false_positives": false_positives,
        "false_positive_count": len(false_positives),
        "clean_windows": max(0, windows - len(
            [w for w in chaos if 0 <= w < windows])),
    }
