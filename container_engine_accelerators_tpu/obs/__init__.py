"""Node-agent observability: spans, latency histograms, flight recorder.

Everything here is stdlib-only by contract — utils/, parallel/, and
health/ sit on these modules, and they must stay importable in
containers without prometheus_client or grpc (the MetricServer is the
one that imports *us*, exporting histograms as ``agent_latency`` next
to the ``agent_events`` counters).  tests/test_obs.py enforces the
contract with a blocked-import subprocess.

- ``obs.trace``       spans: trace/span ids, thread-local context,
                      JSONL sink (``TPU_TRACE_FILE``) + ring buffer
- ``obs.critpath``    critical-path engine: span-tree reconstruction,
                      per-phase self time, exposed-communication math
- ``obs.histo``       log2-bucket latency histograms with percentiles
                      and per-bucket trace exemplars
- ``obs.timeseries``  windowed ring-bucket rates + explicit gauges
                      (goodput, in-flight, SLO status)
- ``obs.flight``      flight recorder: SIGUSR1 / terminal-failure dumps
- ``obs.profiler``    continuous stack-sampling profiler: folded
                      stacks keyed by subsystem, ``/profile`` scrape
- ``obs.history``     persistent run ledger (``TPU_HISTORY_DIR``) +
                      median/MAD trend engine with attributed
                      regression verdicts
- ``obs.promtext``    the one Prometheus text-exposition parser every
                      scrape surface (agent_top, fleet telemetry) uses
"""

from container_engine_accelerators_tpu.obs import (
    critpath,
    flight,
    histo,
    history,
    profiler,
    promtext,
    timeseries,
    trace,
)

__all__ = ["critpath", "flight", "histo", "history", "profiler",
           "promtext", "timeseries", "trace"]
