"""Prometheus text-exposition parsing, shared by every scrape surface.

The agent exports one format (`metrics/metrics.py`), but two consumers
grew their own regex parsers for it — ``cmd/agent_top.py`` (live
console) and ``fleet/telemetry.py`` (process-mode fleet aggregation) —
and the copies had already drifted: one tolerated unlabeled samples
and unescaped label values, the other didn't.  This module is the one
parser both import, stdlib-only like the rest of ``obs/``.
"""

import re
from typing import Dict, List, Tuple

# Sample line: `family{label="v",...} value` — the label block is
# optional (`family value` is a legal exposition line).
_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})?\s+(\S+)$")
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:\\.|[^"\\])*)"')

Samples = Dict[str, List[Tuple[dict, float]]]


# Single pass: sequential str.replace would corrupt values where one
# escape's output forms another's input (`\\n` — escaped backslash then
# a literal n — must stay `\n`, not become a newline).
_ESCAPE_RE = re.compile(r"\\(.)")


def _unescape(raw: str) -> str:
    return _ESCAPE_RE.sub(
        lambda m: "\n" if m.group(1) == "n" else m.group(1), raw)


def parse_samples(text: str) -> Samples:
    """Exposition text -> ``{family: [(labels, value), ...]}``.
    Comment/blank/malformed lines and non-float values are skipped —
    a scrape surface must tolerate families it has never heard of."""
    families: Samples = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            continue
        family, labels_raw, value_raw = m.groups()
        try:
            value = float(value_raw)
        except ValueError:
            continue
        labels = {k: _unescape(v)
                  for k, v in _LABEL_RE.findall(labels_raw or "")}
        families.setdefault(family, []).append((labels, value))
    return families
