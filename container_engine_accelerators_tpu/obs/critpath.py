"""Critical-path engine: where did the wall-clock actually go?

The stack emits spans (obs/trace.py), exemplars (obs/histo.py), and
per-second rates (obs/timeseries.py) — evidence of WHAT happened.  This
module answers the next operational question: of one slow transfer or
serving request, which phase *dominated*?  It reconstructs span trees
from the flat JSONL / ring-buffer span dicts (trace → parent/child via
the existing trace/span ids), computes per-phase **self time** (a
span's duration minus the union of its children's intervals), walks the
**critical path** (the chain of dominant children from a root to a
leaf), and derives **exposed-communication time** — DCN time NOT
overlapped with staging/compute, the signal the fine-grained-overlap
direction (T3, PAPERS.md) and the self-tuning data plane both need:
you cannot hide or tune the DCN leg until you can attribute it.

Inputs are plain span dicts (the JSONL schema in obs/trace.py):
``{"trace", "span", "parent", "name", "ts", "dur_us", ...}``.  ``ts``
is a wall-clock start and ``dur_us`` a monotonic duration, so a span's
interval is ``[ts, ts + dur_us/1e6)``; intervals from different
processes on one host compare well enough for attribution (and every
child is clipped to its parent, so clock skew degrades percentages,
never produces negative time).

Two layers:

- **interval algebra** (``merge`` / ``covered_s`` / ``subtract`` /
  ``exposed_s``) — shared with the LIVE accounting in
  ``parallel/dcn_pipeline.py``, which feeds the ``dcn.exposed`` /
  ``dcn.comm`` histograms and the ``dcn.exposed_ratio`` gauge from the
  same math this module applies offline;
- **tree analysis** (``build_trees`` / ``critical_path`` /
  ``phase_rollup`` / ``analyze``) — what ``cmd/agent_trace.py
  --critical-path``, the fleet report's ``critical_path`` section, and
  the tests consume.

Known request shapes (``analyze``): a pipelined transfer
(``dcn.pipeline`` → stage vs send vs wait vs read, per chunk/stripe), a
serving batch (``serving.batch`` → queue wait vs batch wait vs attempt
vs hedge), a fleet leg (``fleet.leg``), a serial exchange
(``dcn.exchange``), and a bench transfer (``bench.xfer``).  Unknown
trees still work through ``critical_path`` — the shapes are starting
points, not a schema.

Stdlib-only, like the rest of obs/.
"""

from collections import defaultdict
from typing import Any, Dict, Iterable, List, Optional, Tuple

Interval = Tuple[float, float]

# Root span names analyze() rolls up.  HEADLINE_PRIORITY orders the
# overall dominant-phase pick: the specific request shapes (a
# pipelined transfer, a serving batch) answer "where did the time go"
# better than the enclosing fleet.leg, whose own rollup they dominate
# anyway — fleet.leg is the fallback, not the headline.
SHAPE_ROOTS = (
    "fleet.leg",
    "serving.batch",
    "collective.run",
    "dcn.pipeline",
    "dcn.exchange",
    "bench.xfer",
)
HEADLINE_PRIORITY = (
    "collective.run",
    "dcn.pipeline",
    "serving.batch",
    "dcn.exchange",
    "bench.xfer",
    "fleet.leg",
)

# serving.attempt spans split by their hedge role so the breakdown
# answers "attempt vs hedge", not just "attempt".
_ATTEMPT = "serving.attempt"


# ---------------------------------------------------------------------------
# interval algebra (shared with the live exposed-comm accounting)
# ---------------------------------------------------------------------------


def merge(intervals: Iterable[Interval]) -> List[Interval]:
    """Sorted, overlap-free union of ``(t0, t1)`` pairs; empty and
    inverted inputs are dropped."""
    ivs = sorted((t0, t1) for t0, t1 in intervals if t1 > t0)
    out: List[Interval] = []
    for t0, t1 in ivs:
        if out and t0 <= out[-1][1]:
            if t1 > out[-1][1]:
                out[-1] = (out[-1][0], t1)
        else:
            out.append((t0, t1))
    return out


def covered_s(intervals: Iterable[Interval]) -> float:
    """Total time covered by the union of ``intervals``, seconds."""
    return sum(t1 - t0 for t0, t1 in merge(intervals))


def subtract(intervals: Iterable[Interval],
             cover: Iterable[Interval]) -> List[Interval]:
    """The parts of ``intervals`` NOT covered by ``cover`` (both merged
    first)."""
    out: List[Interval] = []
    cov = merge(cover)
    for t0, t1 in merge(intervals):
        cur = t0
        for c0, c1 in cov:
            if c1 <= cur:
                continue
            if c0 >= t1:
                break
            if c0 > cur:
                out.append((cur, c0))
            cur = max(cur, c1)
            if cur >= t1:
                break
        if cur < t1:
            out.append((cur, t1))
    return out


def exposed_s(comm: Iterable[Interval],
              overlap: Iterable[Interval]) -> float:
    """Exposed-communication time: seconds of ``comm`` not hidden
    behind ``overlap`` (staging/compute).  The T3 measure — a serial
    leg exposes everything (ratio 1.0); a perfectly pipelined one
    exposes only the protrusion past its staging."""
    return covered_s(subtract(comm, overlap))


def clip(iv: Interval, bound: Interval) -> Optional[Interval]:
    t0, t1 = max(iv[0], bound[0]), min(iv[1], bound[1])
    return (t0, t1) if t1 > t0 else None


# ---------------------------------------------------------------------------
# span trees
# ---------------------------------------------------------------------------


def interval_of(span: Dict[str, Any]) -> Interval:
    t0 = float(span.get("ts") or 0.0)
    return (t0, t0 + float(span.get("dur_us") or 0.0) / 1e6)


def build_trees(spans: Iterable[Dict[str, Any]],
                trace_id: Optional[str] = None):
    """``(roots, children)`` for one trace (or every trace when
    ``trace_id`` is None): ``children`` maps span id → child spans,
    start-ordered; a span whose parent is absent (evicted from the
    ring, lost to sampling, or remote) is treated as a root — partial
    evidence degrades to a forest, never an error."""
    mine = [s for s in spans
            if (trace_id is None or s.get("trace") == trace_id)
            and s.get("span") is not None]
    mine.sort(key=lambda s: float(s.get("ts") or 0.0))
    ids = {s["span"] for s in mine}
    children: Dict[str, List[dict]] = defaultdict(list)
    roots: List[dict] = []
    for s in mine:
        parent = s.get("parent")
        if parent in ids and parent != s["span"]:
            children[parent].append(s)
        else:
            roots.append(s)
    return roots, children


def self_time_s(span: Dict[str, Any], children: List[dict]) -> float:
    """A span's duration minus the union of its children's intervals
    (clipped to the span): the time the phase itself held, with every
    attributed sub-phase carved out.  Thread-parallel children (the
    pipeline's stage/stripe workers) union, so overlapped work is
    never double-subtracted."""
    iv = interval_of(span)
    kids = [c for c in (clip(interval_of(ch), iv) for ch in children)
            if c is not None]
    return max(0.0, (iv[1] - iv[0]) - covered_s(kids))


def coverage_of(span: Dict[str, Any], children: List[dict]) -> float:
    """Fraction of the span's wall-clock covered by its (clipped,
    unioned) direct children — the "attributed to named child phases"
    number the critical-path acceptance gates on.  1.0 for a leaf
    (everything is its own phase)."""
    iv = interval_of(span)
    dur = iv[1] - iv[0]
    if dur <= 0:
        return 1.0
    if not children:
        return 1.0
    kids = [c for c in (clip(interval_of(ch), iv) for ch in children)
            if c is not None]
    return min(1.0, covered_s(kids) / dur)


def phase_key(span: Dict[str, Any]) -> str:
    """The phase a span contributes to: its name, except hedge
    attempts split out so the serving breakdown separates "attempt"
    from "hedge"."""
    name = span.get("name", "?")
    if name == _ATTEMPT and (span.get("attrs") or {}).get("role") == \
            "hedge":
        return _ATTEMPT + ".hedge"
    return name


def _descend(span: dict, children: Dict[str, List[dict]], out: list,
             depth: int = 0) -> None:
    if depth > 64:  # defensive: ids are random, but evidence is input
        return
    out.append(span)
    for ch in children.get(span["span"], ()):
        _descend(ch, children, out, depth + 1)


def phase_rollup(root: dict,
                 children: Dict[str, List[dict]]) -> Dict[str, float]:
    """Per-phase SELF time (seconds) over the whole subtree of
    ``root``, keyed by :func:`phase_key`; the root's own uncovered time
    lands under ``<root-name> (self)``.  Within one thread the self
    times are disjoint; across threads they are WORK time (a stage
    worker and two stripe senders running concurrently sum past the
    wall-clock, exactly like cumulative CPU time in a profile) — which
    is what a share-of-work breakdown should weigh."""
    nodes: List[dict] = []
    _descend(root, children, nodes)
    out: Dict[str, float] = defaultdict(float)
    for s in nodes:
        self_s = self_time_s(s, children.get(s["span"], []))
        key = phase_key(s)
        if s is root:
            key = f"{key} (self)"
        out[key] += self_s
    return dict(out)


def critical_path(root: dict,
                  children: Dict[str, List[dict]]) -> List[dict]:
    """The dominant chain root → leaf: at every level, descend into
    the child covering the most of its parent (clipped).  Each hop
    reports its duration, share of the ROOT's wall-clock, self time,
    and how much of it the next level attributes (``coverage``)."""
    root_iv = interval_of(root)
    root_dur = max(root_iv[1] - root_iv[0], 1e-12)
    chain: List[dict] = []
    node = root
    seen: set = set()
    while True:
        # Corrupt evidence is expected input: a parent-id cycle (torn
        # writes, 4-byte span-id collisions across merged files) must
        # terminate the walk, not hang it — same guard as _descend.
        if node["span"] in seen or len(chain) > 64:
            return chain
        seen.add(node["span"])
        kids = children.get(node["span"], [])
        iv = interval_of(node)
        chain.append({
            "name": node.get("name", "?"),
            "span": node.get("span"),
            "dur_us": round((iv[1] - iv[0]) * 1e6, 1),
            "pct_of_root": round(
                min(1.0, (iv[1] - iv[0]) / root_dur) * 100, 1),
            "self_us": round(
                self_time_s(node, kids) * 1e6, 1),
            "coverage": round(coverage_of(node, kids), 4),
        })
        if not kids:
            return chain
        node = max(
            kids,
            key=lambda ch: (lambda c: c[1] - c[0] if c else 0.0)(
                clip(interval_of(ch), iv)),
        )


# ---------------------------------------------------------------------------
# the report-level analyzer
# ---------------------------------------------------------------------------


def _worst_root(roots: List[dict]) -> dict:
    return max(roots, key=lambda s: float(s.get("dur_us") or 0.0))


def analyze(spans: Iterable[Dict[str, Any]],
            shape_roots: Iterable[str] = SHAPE_ROOTS) -> Dict[str, Any]:
    """The fleet report's ``critical_path`` section: for every known
    request shape present in ``spans``, the per-phase self-time
    breakdown across ALL instances, the dominant phase, and the worst
    instance's critical path.  ``dominant_phase`` at the top level is
    the dominant phase of the largest shape (by aggregate wall-clock)
    — "where did this run's time go" in one key."""
    spans = [s for s in spans
             if isinstance(s, dict) and "span" in s and "name" in s]
    roots, children = build_trees(spans)
    by_name: Dict[str, List[dict]] = defaultdict(list)
    # A shape root need not be a TRACE root (dcn.pipeline hangs off
    # fleet.leg): index every span by name, not just the forest roots.
    for s in spans:
        by_name[s.get("name", "?")].append(s)
    shapes: Dict[str, Any] = {}
    for shape in shape_roots:
        instances = by_name.get(shape)
        if not instances:
            continue
        rollup: Dict[str, float] = defaultdict(float)
        total_s = 0.0
        cov_sum = 0.0
        for inst in instances:
            total_s += float(inst.get("dur_us") or 0.0) / 1e6
            cov_sum += coverage_of(inst,
                                   children.get(inst["span"], []))
            for key, sec in phase_rollup(inst, children).items():
                rollup[key] += sec
        attributed = sum(rollup.values()) or 1e-12
        phases = {
            key: {"self_ms": round(sec * 1e3, 3),
                  "pct": round(sec / attributed * 100, 1)}
            for key, sec in sorted(rollup.items(),
                                   key=lambda kv: -kv[1])
        }
        dominant = max(rollup, key=rollup.get)
        worst = _worst_root(instances)
        shapes[shape] = {
            "count": len(instances),
            "total_ms": round(total_s * 1e3, 3),
            "coverage": round(cov_sum / len(instances), 4),
            "phases": phases,
            "dominant_phase": dominant,
            "worst": {"trace": worst.get("trace"),
                      "dur_us": worst.get("dur_us")},
            "path": critical_path(worst, children),
        }
    dominant_phase = None
    if shapes:
        headline = next((s for s in HEADLINE_PRIORITY if s in shapes),
                        None)
        if headline is not None:
            dominant_phase = shapes[headline]["dominant_phase"]
        else:  # only unknown shapes: fall back to the largest
            biggest = max(shapes.values(),
                          key=lambda s: s["total_ms"])
            dominant_phase = biggest["dominant_phase"]
    return {
        "spans": len(spans),
        "traces": len({s.get("trace") for s in spans}),
        "shapes": shapes,
        "dominant_phase": dominant_phase,
    }
