"""Fleet history ledger: persistent run records + attributed trends.

Every gate in the repo judges one run against pinned constants —
correct for catching a cliff, structurally blind to a slope.  A 10%
per-week bleed in goodput, busbw, p99, or leak slope never trips a
hard floor until it has already cost weeks, and when it finally does
trip, nothing in the verdict says *why*.  This module is the
longitudinal layer under all of them:

- **RunLedger**: an append-only JSONL file under ``TPU_HISTORY_DIR``
  (``ledger.jsonl``), one record per bench cell / fleet_sim run /
  soak run.  Each record carries the headline metrics, the per-run
  ``cpu_attr`` subsystem shares (obs/profiler.py), the critical-path
  dominant phase (obs/critpath.py), sentinel leak slopes, SLO
  verdicts, and a ``VERSION`` + seed + config-key stamp so records
  are comparable (same config key) and joinable (same ``run_id`` as
  the raw bench JSONL).  Appends are single ``O_APPEND`` writes, so
  two processes recording concurrently interleave whole lines; the
  sink rotates at a size cap exactly like the trace sink
  (``<path>.1`` keeps the previous generation, inode-guarded so only
  the writer that still owns the live file rotates it).  Corrupt or
  torn lines are counted (``history.skipped``) and skipped on read —
  never a crash.  A malformed ``TPU_HISTORY_DIR`` (a file where a
  directory should be, an uncreatable path) degrades to
  recording-off with a counted ``history.disabled`` event: the
  TPU_FAULT_SPEC rule — a typo'd env var costs the history, not the
  run.

- **trend engine**: per ``(metric, config key)`` robust baseline
  from the last ``BASELINE_N`` runs — median + MAD (median absolute
  deviation), the estimator that one outlier run cannot drag — and
  regression verdicts with **attribution**: when p99 or goodput
  regresses past ``median ± k·MAD``, the verdict names which
  subsystem share moved (``cpu_attr`` delta in points vs the
  baseline median share) and which critical-path phase grew, so the
  report says "p99 +18%, serving share flat, shm-staging share
  +9pts, dominant phase dcn.chunk.stage" instead of a bare number.

- **learned thresholds**: :func:`learned_limit` turns prior runs'
  observations (e.g. soak leak slopes) into a sentinel threshold —
  ``median + k·MAD`` — with a pinned-constant fallback when history
  is thinner than ``min_runs`` and a hard ceiling the learned value
  can never relax past (by default the pinned constant itself: the
  fleet's history may tighten a budget, never loosen it).

Stdlib-only, like everything in obs/ — the CLIs, fleet/soak.py, and
agent_top all sit on this module.
"""

import json
import logging
import os
import time
import uuid
from typing import Dict, Iterable, List, Optional, Tuple

from container_engine_accelerators_tpu.metrics import counters

log = logging.getLogger(__name__)

HISTORY_DIR_ENV = "TPU_HISTORY_DIR"
HISTORY_CAP_ENV = "TPU_HISTORY_MAX_BYTES"
LEDGER_NAME = "ledger.jsonl"
SCHEMA_VERSION = 1

# Sink rotation cap (live file + one rotated generation ≈ 2x on
# disk); a malformed env degrades to this default, never to a crash.
DEFAULT_CAP_BYTES = 4 << 20

# Baseline window: the last N comparable runs feed the median/MAD.
BASELINE_N = 8
# Fewer prior runs than this and the trend engine refuses to judge
# (``no_baseline``) and learned thresholds fall back to the pinned
# constant — two points fit any line.
MIN_BASELINE_RUNS = 3
# Regression threshold: |value - median| > k·MAD (same k the learned
# sentinel thresholds use).
DEFAULT_K = 3.0
# MAD floor, as a fraction of |median|: a perfectly flat history has
# MAD 0 and would flag scheduling noise as a regression — the floor
# grants every baseline a minimum tolerance band.
MAD_FLOOR_FRAC = 0.05
# Attribution: subsystem share moves under this many points are
# reported as "flat".
ATTR_FLAT_PTS = 2.0

# Metric direction: is a bigger number better?  Names not matched by
# either list default to higher-is-better (throughput-shaped) — the
# registry is consulted suffix-blind on dotted names.
_LOWER_IS_BETTER = (
    "p99", "p50", "_ms", "ratio", "errors", "shed", "slope",
    "exposed", "elapsed", "lost", "overhead", "detect_windows",
    "false_positives",
)
_HIGHER_IS_BETTER = (
    "mbps", "qps", "goodput", "busbw", "pct_of_memcpy",
    "images_per_sec", "tokens", "value",
)


def metric_direction(name: str) -> str:
    """``"lower"`` or ``"higher"`` — which way this metric regresses.
    Substring match, lower-is-better wins ties (``p99`` inside any
    name means latency-shaped, whatever else the name says)."""
    low = name.lower()
    if any(tok in low for tok in _LOWER_IS_BETTER):
        return "lower"
    if any(tok in low for tok in _HIGHER_IS_BETTER):
        return "higher"
    return "higher"


def new_run_id() -> str:
    """A fresh run id every emitter stamps into its raw JSONL and its
    ledger record — the join key between the two."""
    return uuid.uuid4().hex[:16]


def repo_version() -> str:
    """The VERSION stamp (repo root), ``"unknown"`` when the tree
    layout does not carry one (installed package, trimmed image)."""
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    try:
        with open(os.path.join(root, "VERSION"),
                  encoding="utf-8") as fh:
            v = fh.read().strip()
        return v or "unknown"
    except OSError:
        return "unknown"


def config_key(*parts) -> str:
    """A stable comparability stamp: runs share a baseline only when
    their config keys match.  ``None`` parts are skipped."""
    return ":".join(str(p) for p in parts if p is not None)


class LedgerError(Exception):
    """The ledger EXISTS but cannot be read (permissions, a directory
    where the file should be) — the agent_trend exit-2 signal.  A
    missing ledger is just an empty history, never this."""


def _env_cap() -> int:
    raw = os.environ.get(HISTORY_CAP_ENV)
    if raw is None:
        return DEFAULT_CAP_BYTES
    try:
        return max(0, int(raw))
    except ValueError:
        log.error("malformed %s=%r; using default %d",
                  HISTORY_CAP_ENV, raw, DEFAULT_CAP_BYTES)
        return DEFAULT_CAP_BYTES


class RunLedger:
    """The append-only run history under one directory.

    ``root=None`` resolves ``TPU_HISTORY_DIR``; an unset env means
    recording is off (``enabled`` False) and every ``record`` is a
    silent no-op — benches run identically with and without history.
    A *malformed* root (uncreatable, or a file) also disables
    recording, but loudly: logged once and counted as
    ``history.disabled``.
    """

    def __init__(self, root: Optional[str] = None,
                 cap_bytes: Optional[int] = None):
        if root is None:
            root = os.environ.get(HISTORY_DIR_ENV)
        self.root = root
        self.cap_bytes = _env_cap() if cap_bytes is None \
            else max(0, int(cap_bytes))
        self._disabled_reason: Optional[str] = None
        if not root:
            self._disabled_reason = "unconfigured"
            return
        try:
            os.makedirs(root, exist_ok=True)
            if not os.path.isdir(root):
                raise NotADirectoryError(root)
        except OSError as e:
            # The TPU_FAULT_SPEC rule: a typo'd TPU_HISTORY_DIR costs
            # the history, never the run.
            counters.inc("history.disabled")
            log.error("history recording disabled: %s is unusable "
                      "(%s)", root, e)
            self._disabled_reason = f"unusable dir: {e}"

    @property
    def enabled(self) -> bool:
        return self._disabled_reason is None

    @property
    def path(self) -> Optional[str]:
        if not self.root:
            return None
        return os.path.join(self.root, LEDGER_NAME)

    # -- append ----------------------------------------------------------

    def record(self, kind: str, cfg_key: str,
               metrics: Dict[str, float], *,
               run_id: Optional[str] = None,
               seed: Optional[int] = None,
               cpu_attr: Optional[Dict[str, float]] = None,
               dominant_phase: Optional[str] = None,
               sentinels: Optional[dict] = None,
               slo: Optional[dict] = None,
               version: Optional[str] = None,
               ts: Optional[float] = None) -> Optional[dict]:
        """Append one run record; returns it (or None when recording
        is off).  Never raises: an IO failure mid-append disables
        recording for this ledger with a counted ``history.disabled``
        — history is evidence, not a dependency."""
        if not self.enabled:
            return None
        rec = {
            "schema": SCHEMA_VERSION,
            "run_id": run_id or new_run_id(),
            "version": repo_version() if version is None else version,
            "ts": time.time() if ts is None else float(ts),
            "kind": str(kind),
            "config_key": str(cfg_key),
            "seed": seed,
            "metrics": {str(k): float(v)
                        for k, v in (metrics or {}).items()},
        }
        if cpu_attr:
            rec["cpu_attr"] = {str(k): round(float(v), 4)
                               for k, v in cpu_attr.items()}
        if dominant_phase is not None:
            rec["dominant_phase"] = str(dominant_phase)
        if sentinels is not None:
            rec["sentinels"] = sentinels
        if slo is not None:
            rec["slo"] = slo
        line = (json.dumps(rec, sort_keys=True) + "\n").encode("utf-8")
        try:
            # One O_APPEND write per record: concurrent recorders
            # interleave whole lines, no lock needed (and a torn
            # final line from a killed writer is a counted skip on
            # the read side, never a crash).
            fd = os.open(self.path,
                         os.O_WRONLY | os.O_CREAT | os.O_APPEND,
                         0o644)
            try:
                os.write(fd, line)
                self._maybe_rotate(fd)
            finally:
                os.close(fd)
        except OSError as e:
            counters.inc("history.disabled")
            log.error("history append to %s failed (%s); recording "
                      "disabled", self.path, e)
            self._disabled_reason = f"append failed: {e}"
            return None
        counters.inc("history.records")
        return rec

    def _maybe_rotate(self, fd: int) -> None:
        """Size-capped rotation, the trace-sink discipline: past the
        cap the live file becomes ``<path>.1`` (previous generation
        dropped) — but only when this writer's fd still IS the live
        path (another recorder may have rotated between our append
        and this check; renaming the fresh file would throw away a
        generation).  A failed rotation turns rotation off for this
        ledger, never the sink."""
        cap = self.cap_bytes
        if not cap:
            return
        try:
            if os.fstat(fd).st_size < cap:
                return
            if os.stat(self.path).st_ino != os.fstat(fd).st_ino:
                return  # someone else already rotated under us
            os.replace(self.path, self.path + ".1")
        except OSError as e:
            log.error("history rotation of %s failed (%s); rotation "
                      "disabled", self.path, e)
            self.cap_bytes = 0
            return
        counters.inc("history.rotated")

    # -- read ------------------------------------------------------------

    def records(self, kind: Optional[str] = None,
                cfg_key: Optional[str] = None,
                metric: Optional[str] = None) -> List[dict]:
        """Every readable record, oldest first (rotated generation
        before the live file), filtered.  Corrupt/torn lines are
        counted (``history.skipped``) and skipped.  Raises
        :class:`LedgerError` only when a ledger file EXISTS but
        cannot be read — a missing one is an empty history."""
        if not self.path:
            return []
        out: List[dict] = []
        for path in (self.path + ".1", self.path):
            if not os.path.exists(path):
                continue
            try:
                with open(path, "rb") as fh:
                    raw = fh.read()
            except OSError as e:
                raise LedgerError(
                    f"ledger {path} unreadable: {e}") from e
            for line in raw.split(b"\n"):
                if not line.strip():
                    continue
                try:
                    rec = json.loads(line.decode("utf-8"))
                    if not isinstance(rec, dict) \
                            or "metrics" not in rec:
                        raise ValueError("not a run record")
                except (ValueError, UnicodeDecodeError):
                    counters.inc("history.skipped")
                    continue
                if kind is not None and rec.get("kind") != kind:
                    continue
                if cfg_key is not None \
                        and rec.get("config_key") != cfg_key:
                    continue
                if metric is not None \
                        and metric not in (rec.get("metrics") or {}):
                    continue
                out.append(rec)
        return out


def fleet_report_evidence(report: dict):
    """Pull one fleet report's ledger evidence: ``(metrics,
    cpu_attr, dominant_phase)`` — the SLO measurements as headline
    metrics, the fleet-wide profiler subsystem sample counts
    normalized to busy shares (idle excluded, like
    profiler.subsystem_shares), and the critical-path dominant
    phase.  Absent sections attribute nothing rather than raising —
    works on fleet_sim, soak, and serving reports alike."""
    metrics: Dict[str, float] = {}
    measured = (report.get("slo") or {}).get("measured") or {}
    for key, val in measured.items():
        if key in ("elapsed_s", "stale_entries_skipped"):
            continue
        try:
            metrics[key] = float(val)
        except (TypeError, ValueError):
            continue
    cpu_attr = None
    subs = ((report.get("profile") or {}).get("fleet") or {}) \
        .get("subsystems") or {}
    busy = {k: float(v) for k, v in subs.items()
            if k != "idle" and isinstance(v, (int, float)) and v > 0}
    total = sum(busy.values())
    if total > 0:
        cpu_attr = {k: v / total for k, v in busy.items()}
    phase = (report.get("critical_path") or {}).get("dominant_phase")
    return metrics, cpu_attr, phase


# ---------------------------------------------------------------------------
# robust baseline math
# ---------------------------------------------------------------------------


def median(values: Iterable[float]) -> float:
    xs = sorted(float(v) for v in values)
    if not xs:
        return 0.0
    n = len(xs)
    mid = n // 2
    if n % 2:
        return xs[mid]
    return (xs[mid - 1] + xs[mid]) / 2.0


def mad(values: Iterable[float],
        med: Optional[float] = None) -> float:
    """Median absolute deviation — the spread estimator one outlier
    run cannot drag (unlike stddev)."""
    xs = [float(v) for v in values]
    if not xs:
        return 0.0
    if med is None:
        med = median(xs)
    return median(abs(x - med) for x in xs)


def baseline(values: Iterable[float]) -> dict:
    xs = [float(v) for v in values]
    med = median(xs)
    return {"n": len(xs), "median": med, "mad": mad(xs, med)}


def _band(med: float, spread: float) -> float:
    """The tolerance half-width: MAD floored at a fraction of the
    median so a perfectly flat history still tolerates noise."""
    return max(spread, MAD_FLOOR_FRAC * abs(med), 1e-12)


def learned_limit(values: Iterable[float], pinned: float, *,
                  k: float = DEFAULT_K,
                  min_runs: int = MIN_BASELINE_RUNS,
                  kind: str = "ceiling",
                  ceiling: Optional[float] = None) -> dict:
    """A sentinel threshold learned from prior runs' observations:
    ``median + k·MAD`` for a ceiling-shaped budget (``median -
    k·MAD`` for a floor), MAD floored, with a pinned-constant
    fallback when history is thinner than ``min_runs`` and a hard
    bound the learned value can never relax past — ``ceiling``
    defaults to the pinned constant itself, so history may *tighten*
    a budget but never loosen it (a ceiling never rises above it, a
    floor never sinks below it)."""
    xs = [float(v) for v in values]
    ceiling = float(pinned) if ceiling is None else float(ceiling)
    if len(xs) < max(1, int(min_runs)):
        return {"limit": float(pinned), "source": "pinned",
                "n": len(xs), "median": None, "mad": None,
                "ceiling": ceiling}
    b = baseline(xs)
    band = k * _band(b["median"], b["mad"])
    if kind == "floor":
        limit = max(b["median"] - band, ceiling)
    else:
        limit = min(b["median"] + band, ceiling)
    return {"limit": limit, "source": "learned",
            "n": b["n"], "median": b["median"], "mad": b["mad"],
            "ceiling": ceiling}


# ---------------------------------------------------------------------------
# trend verdicts with attribution
# ---------------------------------------------------------------------------


def attribute(current_cpu_attr: Optional[Dict[str, float]],
              current_phase: Optional[str],
              prior: List[dict]) -> dict:
    """The *why* behind a regression: which ``cpu_attr`` subsystem
    share moved (points vs the baseline median share) and whether the
    critical-path dominant phase changed.  Works from whatever
    evidence the records carry — a bench with no profiler attributes
    nothing rather than failing."""
    out: dict = {"subsystems": [], "flat": [],
                 "dominant_phase": current_phase,
                 "prior_dominant_phase": None}
    phases = [r.get("dominant_phase") for r in prior
              if r.get("dominant_phase")]
    if phases:
        # Modal prior phase (ties break to the most recent).
        tally: Dict[str, int] = {}
        for p in phases:
            tally[p] = tally.get(p, 0) + 1
        out["prior_dominant_phase"] = max(
            reversed(phases), key=lambda p: tally[p])
    if current_cpu_attr:
        subs = set(current_cpu_attr)
        prior_attrs = [r.get("cpu_attr") for r in prior
                       if r.get("cpu_attr")]
        for attr in prior_attrs:
            subs.update(attr)
        movers: List[Tuple[float, str, float, float]] = []
        for sub in sorted(subs):
            cur = float(current_cpu_attr.get(sub, 0.0)) * 100.0
            base = median(float(a.get(sub, 0.0)) * 100.0
                          for a in prior_attrs) if prior_attrs else 0.0
            delta = cur - base
            movers.append((delta, sub, cur, base))
        movers.sort(key=lambda m: -abs(m[0]))
        for delta, sub, cur, base in movers:
            entry = {"subsystem": sub, "share_pts": round(cur, 1),
                     "baseline_pts": round(base, 1),
                     "delta_pts": round(delta, 1)}
            if abs(delta) >= ATTR_FLAT_PTS:
                out["subsystems"].append(entry)
            else:
                out["flat"].append(sub)
    return out


def format_attribution(attr: dict) -> str:
    """One human-readable clause list: movers first, flats named, the
    dominant phase last — the "+9pts shm-staging" sentence."""
    bits: List[str] = []
    for m in attr.get("subsystems", []):
        sign = "+" if m["delta_pts"] >= 0 else ""
        bits.append(f"{m['subsystem']} share "
                    f"{sign}{m['delta_pts']}pts")
    flat = attr.get("flat") or []
    if flat:
        bits.append(", ".join(flat[:3]) + " share flat")
    phase = attr.get("dominant_phase")
    prior = attr.get("prior_dominant_phase")
    if phase and prior and phase != prior:
        bits.append(f"dominant phase {phase} (was {prior})")
    elif phase:
        bits.append(f"dominant phase {phase}")
    return ", ".join(bits)


def trend_verdict(prior: List[dict], metric: str, value: float, *,
                  k: float = DEFAULT_K,
                  min_runs: int = MIN_BASELINE_RUNS,
                  n: int = BASELINE_N,
                  cpu_attr: Optional[Dict[str, float]] = None,
                  dominant_phase: Optional[str] = None) -> dict:
    """Judge ``value`` against the last ``n`` comparable prior
    records' ``metric``: OK inside ``median ± k·MAD`` (regression
    side only — an *improvement* past the band reports ``improved``,
    which never gates), ``no_baseline`` when history is thinner than
    ``min_runs``.  A regression carries the attribution."""
    window = [r for r in prior
              if metric in (r.get("metrics") or {})][-int(n):]
    values = [float(r["metrics"][metric]) for r in window]
    verdict = {
        "metric": metric, "value": float(value),
        "direction": metric_direction(metric),
        "n": len(values), "ok": True, "status": "no_baseline",
        "median": None, "mad": None, "delta_pct": None,
        "attribution": None,
    }
    if len(values) < max(1, int(min_runs)):
        return verdict
    b = baseline(values)
    band = k * _band(b["median"], b["mad"])
    verdict["median"] = b["median"]
    verdict["mad"] = b["mad"]
    if b["median"]:
        verdict["delta_pct"] = round(
            (float(value) - b["median"]) / abs(b["median"]) * 100, 1)
    worse = (float(value) > b["median"] + band
             if verdict["direction"] == "lower"
             else float(value) < b["median"] - band)
    better = (float(value) < b["median"] - band
              if verdict["direction"] == "lower"
              else float(value) > b["median"] + band)
    if worse:
        verdict["ok"] = False
        verdict["status"] = "regressed"
        verdict["attribution"] = attribute(
            cpu_attr, dominant_phase, window)
    else:
        verdict["status"] = "improved" if better else "ok"
    return verdict


def format_verdict(v: dict) -> str:
    """The one-line rendering agent_top and the trend gates print:
    ``p99_e2e_ms REGRESSED +18.2% vs median 41.0 (n=8): shm-staging
    share +9pts, serving share flat, dominant phase
    dcn.chunk.stage``."""
    status = v["status"].upper()
    if v["status"] == "no_baseline":
        return (f"{v['metric']} NO-BASELINE "
                f"(history n={v['n']} too thin)")
    delta = v.get("delta_pct")
    sign = "+" if (delta or 0) >= 0 else ""
    line = (f"{v['metric']} {status} {sign}{delta}% vs median "
            f"{round(v['median'], 3)} (n={v['n']})")
    if v.get("attribution"):
        rendered = format_attribution(v["attribution"])
        if rendered:
            line += ": " + rendered
    return line
