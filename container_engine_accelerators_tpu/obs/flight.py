"""Flight recorder: dump the last N spans + counters on demand or death.

A node agent that goes terminal (DCN retry budget exhausted, resilient
client latched) usually gets its pod deleted before anyone attaches a
debugger — the evidence of *why* dies with it.  The flight recorder
closes that gap: on SIGUSR1, or whenever a terminal-failure hook fires,
it emits ONE self-contained JSON blob holding

- the tail of the span ring buffer (obs/trace.py),
- the full robustness counter snapshot (metrics/counters.py),
- every latency histogram (obs/histo.py),
- the windowed-rate/gauge snapshot (obs/timeseries.py) and the SLO
  verdict gauges (``slo.*``) — what the node was *doing* when it died,
  not just its lifetime totals,
- the continuous profiler's top folded stacks (obs/profiler.py) —
  where every thread was stuck or spinning, so a hung worker's
  postmortem shows the code, not just the open spans,

to stderr (always — `kubectl logs` is the collection path that needs no
infrastructure) and appended to ``TPU_FLIGHT_FILE`` when set.

Hooked today: ``utils/retry.py`` on budget exhaustion and
``parallel/dcn_client.py`` when the resilient client latches terminal.
Agents arm the signal path with ``install()``
(cmd/tpu_device_plugin.py does).  The SIGUSR1 handler hands the dump to
a short-lived thread: the handler itself runs between bytecodes on the
main thread, which may be holding the very locks the dump needs.

Stdlib-only; a dump failure is swallowed (the recorder must never turn
a bad day into a worse one).
"""

import json
import logging
import os
import signal
import sys
import threading
import time
from typing import Optional

from container_engine_accelerators_tpu.metrics import counters
from container_engine_accelerators_tpu.obs import (
    histo,
    profiler,
    timeseries,
    trace,
)

log = logging.getLogger(__name__)

FLIGHT_FILE_ENV = "TPU_FLIGHT_FILE"
FLIGHT_SPANS_ENV = "TPU_FLIGHT_SPANS"
DEFAULT_SPANS = 64
STDERR_MARKER = "TPU_FLIGHT_RECORDER"


def snapshot(reason: str) -> dict:
    """Assemble the dump blob without emitting it."""
    n = trace._env_int(FLIGHT_SPANS_ENV, DEFAULT_SPANS)
    rates = timeseries.snapshot()
    return {
        "flight_recorder": 1,  # schema tag for offline tooling
        "reason": reason,
        "ts": round(time.time(), 3),
        "pid": os.getpid(),
        "spans": trace.tail(n),
        "counters": counters.snapshot(),
        "histograms": histo.snapshot(),
        # What the node was DOING at death, not just lifetime totals:
        # windowed per-second rates, live gauges, and any SLO verdict
        # gauges the fleet aggregator (fleet/telemetry.py) published.
        "rates": rates,
        "slo": {name: value for name, value in rates["gauges"].items()
                if name.startswith("slo.")},
        # Where every thread was STUCK, not just which spans were
        # open: the continuous profiler's top folded stacks — a hung
        # worker's postmortem names the code burning (or parking) its
        # threads.
        "profile": profiler.summary(),
    }


def dump(reason: str, file: Optional[str] = None) -> Optional[dict]:
    """Emit one flight-recorder blob; returns it (None if assembly
    itself failed — nothing useful to return, nothing to raise)."""
    try:
        blob = snapshot(reason)
        line = json.dumps(blob)
    except Exception as e:  # noqa: BLE001 — recorder never raises
        log.error("flight-recorder snapshot failed: %s", e)
        return None
    counters.inc("flight.dumps")
    path = file or os.environ.get(FLIGHT_FILE_ENV)
    if path:
        try:
            with open(path, "a") as f:
                f.write(line + "\n")
        except OSError as e:
            log.error("flight-recorder file %s unwritable: %s", path, e)
    try:
        sys.stderr.write(f"{STDERR_MARKER} {line}\n")
        sys.stderr.flush()
    except (OSError, ValueError):
        pass  # stderr redirected to a closed pipe: file copy stands
    return blob


def on_terminal(reason: str) -> None:
    """The hook terminal-failure paths call (retry exhaustion, the
    resilient DCN client latching terminal)."""
    dump(f"terminal: {reason}")


def _handler(signum: int, frame) -> None:
    # Detach from the interrupted main thread: it may hold the ring /
    # counter locks the dump reads.
    threading.Thread(
        target=dump, args=(f"signal {signum}",),
        name="flight-recorder", daemon=True,
    ).start()


def install(signum: int = signal.SIGUSR1) -> bool:
    """Arm the on-demand dump signal; False when not on the main
    thread (signal handlers are main-thread-only in CPython)."""
    try:
        signal.signal(signum, _handler)
        return True
    except ValueError:
        log.warning("flight recorder: not on main thread; signal %d "
                    "not armed", signum)
        return False
