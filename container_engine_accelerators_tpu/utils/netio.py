"""Short-write/short-read hardened socket IO.

Every raw data-plane send and receive in the stack routes through
these helpers instead of bare ``socket.sendall`` / ``recv``:

- the bench rig's loopback stack truncates very large single-syscall
  payloads (an ``sendmsg`` quirk several container runtimes share), so
  sends are capped at :data:`SENDALL_CAP` per syscall and explicitly
  loop on the kernel's own short-write accounting — ``sendall``
  semantics that hold even where the platform's ``sendall`` does not;
- receives always loop ``recv_into`` against an exact byte budget: a
  frame is either fully read or the connection is reported dead,
  never a silently-short buffer.

The wire *formats* stay where they live (fleet/xferd.py and its
deliberate client-side duplicates in parallel/dcn_pipeline.py); this
module owns only the byte movement.
"""

import socket
from typing import Iterable

# Per-syscall send cap.  1 MiB is far above the point where another
# syscall costs anything measurable, and far below every truncation
# threshold observed in the wild.
SENDALL_CAP = 1 << 20


def sendall(sock: socket.socket, data, cap: int = SENDALL_CAP) -> None:
    """``sock.sendall(data)`` with an explicit short-write loop and a
    per-syscall size cap.  Accepts bytes/bytearray/memoryview."""
    view = memoryview(data)
    off = 0
    n = len(view)
    while off < n:
        sent = sock.send(view[off:off + min(cap, n - off)])
        if sent <= 0:
            raise ConnectionError("socket closed mid-send")
        off += sent


def sendall_parts(sock: socket.socket, parts: Iterable) -> None:
    """Send each buffer in ``parts`` back to back (header + name +
    payload as separate buffers — no concat copy of the payload)."""
    for part in parts:
        sendall(sock, part)


def recv_exact(sock: socket.socket, n: int) -> bytes:
    """Read exactly ``n`` bytes or raise ``ConnectionError``."""
    buf = bytearray(n)
    recv_exact_into(sock, memoryview(buf))
    return bytes(buf)


def recv_exact_into(sock: socket.socket, view: memoryview) -> None:
    """Fill ``view`` completely from the socket or raise
    ``ConnectionError`` — never a silent short read."""
    got = 0
    n = len(view)
    while got < n:
        r = sock.recv_into(view[got:], n - got)
        if not r:
            raise ConnectionError("connection closed mid-read")
        got += r
