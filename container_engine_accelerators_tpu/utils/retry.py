"""Shared retry policy for everything that talks to a socket.

Every node-agent data path has the same failure shape: a daemon or the
kubelet restarts underneath an established connection, the call fails
with a transient OSError/RpcError, and the correct response is
exponential backoff with jitter under a bounded budget — never an
unbounded spin, never a one-strike crash.  Before this module each
component hand-rolled (or skipped) that loop; now ``RetryPolicy`` is
the single place the budget lives:

- ``parallel/dcn_client.py``  reconnect + flow replay against dcnxferd
- ``deviceplugin/manager.py`` kubelet Register after kubelet restarts
- ``models/checkpoint.py``    checkpoint saves over flaky filesystems
- ``collectives/bench.py``    bench accounting riding the DCN daemon

Jitter is multiplicative (±``jitter`` fraction) to de-synchronize a
node's worth of agents retrying against one restarted daemon; the
optional ``deadline_s`` caps the whole loop's wall clock so a retry
budget can never outlive, say, a kubelet plugin-socket poll interval.
"""

import dataclasses
import logging
import random
import time
from typing import Callable, Iterator, Optional, Tuple, Type

from container_engine_accelerators_tpu.metrics import counters
from container_engine_accelerators_tpu.obs import flight, trace

log = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Exponential backoff + jitter + deadline.

    ``max_attempts`` counts total tries (first try included).  Sleeps
    happen *between* attempts: ``backoff_s(0)`` is the delay after the
    first failure.
    """

    max_attempts: int = 5
    initial_backoff_s: float = 0.05
    max_backoff_s: float = 2.0
    multiplier: float = 2.0
    jitter: float = 0.1  # ± fraction of the computed backoff
    deadline_s: Optional[float] = None

    def backoff_s(self, attempt: int, rng: Callable[[], float] = random.random
                  ) -> float:
        base = min(
            self.initial_backoff_s * (self.multiplier ** attempt),
            self.max_backoff_s,
        )
        if self.jitter:
            base *= 1.0 + self.jitter * (2.0 * rng() - 1.0)
        return max(base, 0.0)

    def attempts(
        self,
        sleep: Callable[[float], object] = time.sleep,
        monotonic: Callable[[], float] = time.monotonic,
    ) -> Iterator[int]:
        """Yield attempt indices 0..max_attempts-1, sleeping the backoff
        between yields and stopping early once ``deadline_s`` would be
        exceeded.  The caller breaks out on success; exhausting the
        iterator means the budget is spent::

            for attempt in policy.attempts():
                try:
                    return do_thing()
                except OSError as e:
                    last = e
            raise TerminalError(...) from last

        ``sleep`` is injectable so servers can wait on a stop event
        (``sleep=stop.wait``) and tests can run the loop instantly.
        """
        start = monotonic()
        for attempt in range(max(1, self.max_attempts)):
            yield attempt
            if attempt + 1 >= self.max_attempts:
                break
            delay = self.backoff_s(attempt)
            if (
                self.deadline_s is not None
                and monotonic() - start + delay > self.deadline_s
            ):
                log.debug("retry deadline %.1fs reached after attempt %d",
                          self.deadline_s, attempt + 1)
                break
            counters.inc("retry.attempts")
            sleep(delay)

    def call(
        self,
        fn: Callable,
        *args,
        retry_on: Tuple[Type[BaseException], ...] = (OSError,),
        sleep: Callable[[float], object] = time.sleep,
        on_retry: Optional[Callable[[int, BaseException], object]] = None,
        **kwargs,
    ):
        """Run ``fn`` under this policy; re-raises the last error once
        the budget is exhausted.  Each attempt gets its own span
        (``retry.attempt`` with fn/attempt attrs), so a trace of a slow
        recovery shows every try and every failure, not one opaque
        blob."""
        name = getattr(fn, "__name__", str(fn))
        last: Optional[BaseException] = None
        for attempt in self.attempts(sleep=sleep):
            try:
                with trace.span("retry.attempt", fn=name, attempt=attempt):
                    return fn(*args, **kwargs)
            except retry_on as e:  # noqa: PERF203 — the loop IS the feature
                last = e
                if on_retry is not None:
                    on_retry(attempt, e)
                log.warning("attempt %d/%d of %s failed: %s", attempt + 1,
                            self.max_attempts, name, e)
        counters.inc("retry.exhausted")
        flight.on_terminal(f"retry budget exhausted: {name}")
        assert last is not None
        raise last
