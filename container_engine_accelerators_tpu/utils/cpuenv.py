"""Build an environment that forces an n-device virtual CPU mesh.

Single source of truth for escaping the axon TPU harness: its
sitecustomize (keyed off ``PALLAS_AXON_POOL_IPS``) pre-initializes JAX
with the remote TPU backend at interpreter startup, so CPU-mesh code
must run in a fresh process with this environment.  Used by
``__graft_entry__.dryrun_multichip``, ``bench.py``'s CPU fallback, and
``tests/conftest.py``'s re-exec — keep them in sync by keeping them
here.
"""

import os
import re

# Env vars that arm TPU sitecustomize hooks; removed for CPU subprocesses.
_TPU_HOOK_VARS = ("PALLAS_AXON_POOL_IPS",)

_COUNT_FLAG = "--xla_force_host_platform_device_count"


def cpu_mesh_env(n_devices=None, base=None):
    """Return an env dict forcing the CPU platform.

    ``n_devices``: also force that many virtual CPU devices (rewriting
    any existing count flag, which may be smaller).  ``base`` defaults to
    ``os.environ``.
    """
    env = dict(os.environ if base is None else base)
    for var in _TPU_HOOK_VARS:
        env.pop(var, None)
    env["JAX_PLATFORMS"] = "cpu"
    if n_devices is not None:
        flags = re.sub(
            _COUNT_FLAG + r"=\d+", "", env.get("XLA_FLAGS", "")
        )
        env["XLA_FLAGS"] = (
            flags + f" {_COUNT_FLAG}={n_devices}"
        ).strip()
    return env


def in_tpu_harness(environ=None) -> bool:
    """True when a TPU sitecustomize hook owns this process's JAX."""
    environ = os.environ if environ is None else environ
    return any(environ.get(v) for v in _TPU_HOOK_VARS)
