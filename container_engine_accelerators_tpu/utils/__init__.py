"""Public re-exports for the utils package."""
from container_engine_accelerators_tpu.utils.devname import (
    device_name_from_path,
    device_path_from_name,
)
from container_engine_accelerators_tpu.utils.config import (
    TPUConfig,
    TPUSharingConfig,
)
from container_engine_accelerators_tpu.utils.retry import RetryPolicy

__all__ = [
    "device_name_from_path",
    "device_path_from_name",
    "RetryPolicy",
    "TPUConfig",
    "TPUSharingConfig",
]
