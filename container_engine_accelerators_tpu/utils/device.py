"""Shared device-plugin data types (kubelet DevicePlugin v1beta1 shapes)."""

import dataclasses

HEALTHY = "Healthy"
UNHEALTHY = "Unhealthy"


@dataclasses.dataclass
class Device:
    id: str
    health: str = HEALTHY


@dataclasses.dataclass(frozen=True)
class DeviceSpec:
    host_path: str
    container_path: str
    permissions: str = "mrw"


@dataclasses.dataclass(frozen=True)
class Mount:
    host_path: str
    container_path: str
    read_only: bool = False
