"""Deterministic fault injection for the node-agent data paths.

The chaos suite (tests/test_chaos.py, `make chaos`) has to prove the
self-healing layer closes every failure loop — but monkeypatching
sockets proves only that the *test's* failure shape recovers.  Instead,
the production code itself carries named fault sites, armed from the
``TPU_FAULT_SPEC`` environment variable, so the exact same binary that
runs on a node can be told "fail the 3rd DCN send" by a demo pod spec
(demo/tpu-error is the same idea for HBM faults).

Sites wired today:

    dcn.connect       DcnXferClient socket connect
    dcn.send          every control-socket call (send/readline path)
    health.stream     the health checker's event-wait loop
    kubelet.register  device-plugin Register RPC against the kubelet
    checkpoint.save   TrainCheckpointer.save
    k8s.patch         maintenance watcher's node-taint patch

Spec grammar (``;`` or ``,`` separated)::

    TPU_FAULT_SPEC="dcn.send:fail@3;health.stream:drop@1x2;dcn.connect:fail@1x*"

    site:mode[@N][xK]   fire on the Nth hit of the site (1-based,
                        default 1), for K consecutive hits (default 1,
                        ``*`` = forever).

Modes: ``fail`` raises FaultInjectedError, ``drop`` raises
InjectedConnectionDrop — both are OSError subclasses, so the existing
socket/except paths treat them exactly like the real failure.
``conflict`` raises InjectedConflict, which carries ``status = 409``
so call sites that retry on HTTP 409 Conflict (the maintenance
watcher's read-modify-write taint patch) exercise their retry loop
against the injected fault exactly as against a real stale
``resourceVersion``.  A malformed entry is logged and skipped; a bad
spec must never take down a node agent (the whole point is surviving
bad days).

When a site fires inside an active trace span the span is annotated
``fault=<site>`` (obs/trace.py), so a chaos run's JSONL shows exactly
which attempt the injection killed.
"""

import contextlib
import dataclasses
import logging
import os
import threading
from typing import Dict, List, Optional

from container_engine_accelerators_tpu.metrics import counters
from container_engine_accelerators_tpu.obs import trace

log = logging.getLogger(__name__)

TPU_FAULT_SPEC_ENV = "TPU_FAULT_SPEC"


class FaultInjectedError(OSError):
    """An armed fault site fired (generic failure)."""


class InjectedConnectionDrop(FaultInjectedError):
    """An armed fault site fired emulating the peer dropping the link."""


class InjectedConflict(FaultInjectedError):
    """An armed fault site fired emulating an HTTP 409 Conflict (the
    ``status`` attribute is what 409-retry loops key on)."""

    status = 409


_MODES = {
    "fail": FaultInjectedError,
    "drop": InjectedConnectionDrop,
    "conflict": InjectedConflict,
}
FOREVER = -1


@dataclasses.dataclass
class FaultRule:
    site: str
    mode: str
    at: int = 1  # fire starting at the Nth hit (1-based)
    times: int = 1  # consecutive hits to fire for; FOREVER = every hit

    def fires(self, hit: int) -> bool:
        if hit < self.at:
            return False
        return self.times == FOREVER or hit < self.at + self.times


def parse_spec(spec: str) -> List[FaultRule]:
    """Parse a TPU_FAULT_SPEC string; malformed entries are logged and
    skipped, never raised."""
    rules: List[FaultRule] = []
    for entry in spec.replace(",", ";").split(";"):
        entry = entry.strip()
        if not entry:
            continue
        try:
            site, _, action = entry.partition(":")
            if not site or not action:
                raise ValueError("expected site:mode[@N][xK]")
            mode, _, position = action.partition("@")
            at, times = 1, 1
            if position:
                n, _, k = position.partition("x")
                at = int(n)
                if k == "*":
                    times = FOREVER
                elif k:
                    # Validate BEFORE any sentinel mapping: "x-1" must be
                    # rejected, not collide with the FOREVER sentinel.
                    times = int(k)
                    if times < 1:
                        raise ValueError("xK must be >= 1")
            if mode not in _MODES:
                raise ValueError(f"unknown mode {mode!r}")
            if at < 1:
                raise ValueError("@N must be >= 1")
            rules.append(FaultRule(site=site, mode=mode, at=at, times=times))
        except (ValueError, TypeError) as e:
            log.error("ignoring malformed %s entry %r: %s",
                      TPU_FAULT_SPEC_ENV, entry, e)
    return rules


class FaultInjector:
    """Hit-counting fault arming for named sites (thread-safe)."""

    def __init__(self, rules: Optional[List[FaultRule]] = None):
        self._rules = list(rules or [])
        self._hits: Dict[str, int] = {}
        self._fired: Dict[str, int] = {}
        self._lock = threading.Lock()

    @classmethod
    def from_spec(cls, spec: str) -> "FaultInjector":
        return cls(parse_spec(spec))

    @classmethod
    def from_env(cls, env: Optional[dict] = None) -> "FaultInjector":
        env = env if env is not None else os.environ
        return cls.from_spec(env.get(TPU_FAULT_SPEC_ENV, ""))

    @property
    def rules(self) -> List[FaultRule]:
        return list(self._rules)

    def check(self, site: str) -> None:
        """Record a hit on ``site``; raise if an armed rule fires."""
        if not self._rules:  # fast path: injection off (production default)
            return
        with self._lock:
            hit = self._hits.get(site, 0) + 1
            self._hits[site] = hit
            rule = next(
                (r for r in self._rules
                 if r.site == site and r.fires(hit)), None,
            )
            if rule is None:
                return
            self._fired[site] = self._fired.get(site, 0) + 1
        counters.inc(f"fault.fired.{site}")
        # Stamp the active span (if the hit happened inside one): a
        # chaos trace then shows which attempt the injection killed.
        trace.annotate(fault=site, fault_mode=rule.mode)
        log.warning("fault injection: %s %s at hit %d", site, rule.mode, hit)
        raise _MODES[rule.mode](
            f"injected {rule.mode} at fault site {site!r} (hit {hit})"
        )

    def fired(self, site: Optional[str] = None) -> int:
        with self._lock:
            if site is not None:
                return self._fired.get(site, 0)
            return sum(self._fired.values())

    def reset(self) -> None:
        with self._lock:
            self._hits.clear()
            self._fired.clear()


# ---- process-global injector (what production call sites use) --------------

_global: Optional[FaultInjector] = None
_global_lock = threading.Lock()


def injector() -> FaultInjector:
    """The process injector, lazily armed from TPU_FAULT_SPEC."""
    global _global
    # Lock-free fast path: check() sits on every DCN control message and
    # the health loop; once armed (or parsed-empty) the reference is
    # stable and a plain read suffices.  The lock only guards the first
    # parse (the benign race would at worst parse the env twice).
    inj = _global
    if inj is not None:
        return inj
    with _global_lock:
        if _global is None:
            _global = FaultInjector.from_env()
            if _global.rules:
                log.warning("fault injection ARMED: %s", _global.rules)
        return _global


def check(site: str) -> None:
    """The one-liner production call sites use: no-op unless armed."""
    injector().check(site)


def set_injector(inj: Optional[FaultInjector]) -> Optional[FaultInjector]:
    """Swap the process injector (None ⇒ re-arm lazily from env);
    returns the previous one."""
    global _global
    with _global_lock:
        prev, _global = _global, inj
        return prev


def reload(env: Optional[dict] = None) -> FaultInjector:
    """Re-parse the spec (tests and demo pods after mutating env)."""
    set_injector(FaultInjector.from_env(env))
    return injector()


@contextlib.contextmanager
def armed(spec: str):
    """Scope an explicit spec over the process injector (chaos tests)::

        with faults.armed("dcn.send:fail@2") as inj:
            ...
            assert inj.fired("dcn.send") == 1
    """
    inj = FaultInjector.from_spec(spec)
    prev = set_injector(inj)
    try:
        yield inj
    finally:
        set_injector(prev)
