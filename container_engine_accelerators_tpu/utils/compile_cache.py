"""Persistent XLA compilation cache for the evidence tooling.

Round-5 rationale (VERDICT.md round 4, next-round item 1): the axon
tunnel's observed up-windows are minutes long, and its dominant failure
mode is a first heavy compile that never returns (round-4 window log in
BENCH_HW.md).  A compile that completes ONCE must therefore be free in
every later window — otherwise each new window re-pays the exact
compile that killed the previous one.  JAX's persistent compilation
cache (keyed by HLO + backend) provides that: ``enable()`` points it at
a repo-local directory shared by every bench/watcher stage, so the
escalating workload ladder (cmd/hw_watcher.py) resumes where the last
window died instead of starting over.

The reference caches its expensive build artifact the same way — the
driver installer keys its installed driver by version and skips the
rebuild on every later boot (reference
nvidia-driver-installer/cos/entrypoint.sh's cache check); here the
expensive artifact is the XLA executable.

``enable()`` is deliberately tolerant: an older jax without these
config names, or a read-only checkout, must never break a benchmark —
the cache is an accelerant, not a dependency.
"""

import os
import sys

# One shared env name: jax itself reads it, the watcher exports it to
# every stage, and enable() falls back to it — a stage that never calls
# enable() still gets the directory (with jax's default >=1s
# min-compile-time gate, which only skips compiles too cheap to matter).
CACHE_DIR_ENV = "JAX_COMPILATION_CACHE_DIR"

_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
DEFAULT_CACHE_DIR = os.path.join(_REPO_ROOT, ".jax_compile_cache")


def cache_dir() -> str:
    """The cache directory of record: env override, else repo-local."""
    return os.environ.get(CACHE_DIR_ENV) or DEFAULT_CACHE_DIR


def cache_enabled(environ=None) -> bool:
    """The kill-switch convention, in ONE place: ``enable()`` and the
    watcher's stage-env export (cmd/hw_watcher.py) must agree, or
    setting TPU_COMPILE_CACHE=0 would still export the dir and jax
    would re-enable the cache behind the operator's back."""
    environ = os.environ if environ is None else environ
    return environ.get("TPU_COMPILE_CACHE", "1") != "0"


def enable(path=None, min_compile_seconds=0.5):
    """Turn on the persistent compilation cache; returns the directory
    actually configured, or None when this jax cannot (never raises).

    ``min_compile_seconds`` drops to 0.5 s from jax's 1.0 s default so
    the ladder's smaller rungs (whose compiles are seconds, not
    minutes) are banked too; sub-half-second compiles stay uncached —
    they cost less than the disk round-trip.
    """
    if not cache_enabled():
        return None
    import jax

    path = path or cache_dir()
    try:
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs",
            float(min_compile_seconds))
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    except Exception as e:  # noqa: BLE001 — accelerant, not dependency
        print(f"compile_cache: not enabled ({e!r})", file=sys.stderr)
        return None
    return path
