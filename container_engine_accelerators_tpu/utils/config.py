"""Node TPU configuration: the three config tiers of the reference, TPU-side.

Tier 2 of the reference's config system is a JSON file the node bootstrap
drops at ``/etc/nvidia/gpu_config.json``; ours is ``/etc/tpu/tpu_config.json``
(ref: cmd/nvidia_gpu/nvidia_gpu.go:54-71, pkg/gpu/nvidia/manager.go:68-133).
Tier 3 is env: the reference reads critical Xid codes from ``XID_CONFIG``;
we read critical TPU error codes from ``TPU_ERR_CONFIG``.

Schema (accepts both lowerCamel and the reference's Go-style keys):

    {
      "tpuPartitionSize": "2x2",            # sub-slice topology, MIG analog
      "tpuSharingConfig": {
        "tpuSharingStrategy": "time-sharing" | "core-sharing",
        "maxSharedClientsPerTpu": 4
      },
      "healthCriticalCodes": [48]
    }
"""

import dataclasses
import json
import logging
import os
from typing import List, Optional

from container_engine_accelerators_tpu.sharing import SharingStrategy

log = logging.getLogger(__name__)

# Valid sub-slice partition sizes for a 4-chip (2x2) tray / 8-chip host.
# TPU analog of the reference's MIG partition-size table (mig.go:33-46).
VALID_PARTITION_SIZES = ("1x1", "2x1", "2x2", "2x2x1", "2x2x2")

TPU_ERR_CONFIG_ENV = "TPU_ERR_CONFIG"


@dataclasses.dataclass
class TPUSharingConfig:
    strategy: SharingStrategy = SharingStrategy.UNDEFINED
    max_shared_clients_per_tpu: int = 0


@dataclasses.dataclass
class TPUConfig:
    """Settings used to configure the TPUs on a node (ref: manager.go:68-84)."""

    partition_size: str = ""
    # Deprecated in favor of sharing.  Kept for config-file parity with the
    # reference's MaxTimeSharedClientsPerGPU (manager.go:71-73).
    max_time_shared_clients_per_tpu: int = 0
    sharing: TPUSharingConfig = dataclasses.field(default_factory=TPUSharingConfig)
    health_critical_codes: List[int] = dataclasses.field(default_factory=list)

    # ---- parsing -----------------------------------------------------------

    @classmethod
    def from_file(cls, path: str) -> "TPUConfig":
        """Parse the node config JSON.  Missing file ⇒ empty config, like the
        reference (nvidia_gpu.go:56-59)."""
        if not os.path.exists(path):
            return cls()
        with open(path) as f:
            raw = f.read().strip()
        if not raw:
            return cls()
        return cls.from_json(json.loads(raw))

    @classmethod
    def from_json(cls, obj: dict) -> "TPUConfig":
        def pick(d, *keys, default=None):
            for k in keys:
                if k in d:
                    return d[k]
            return default

        sharing_obj = pick(obj, "tpuSharingConfig", "TPUSharingConfig", default={})
        strategy_raw = pick(
            sharing_obj, "tpuSharingStrategy", "TPUSharingStrategy", default=""
        )
        sharing = TPUSharingConfig(
            strategy=SharingStrategy.parse(strategy_raw)
            if strategy_raw
            else SharingStrategy.UNDEFINED,
            max_shared_clients_per_tpu=int(
                pick(
                    sharing_obj,
                    "maxSharedClientsPerTpu",
                    "MaxSharedClientsPerTPU",
                    default=0,
                )
            ),
        )
        return cls(
            partition_size=pick(
                obj, "tpuPartitionSize", "TPUPartitionSize", default=""
            ),
            max_time_shared_clients_per_tpu=int(
                pick(
                    obj,
                    "maxTimeSharedClientsPerTpu",
                    "MaxTimeSharedClientsPerTPU",
                    default=0,
                )
            ),
            sharing=sharing,
            health_critical_codes=list(
                pick(obj, "healthCriticalCodes", "HealthCriticalCodes", default=[])
            ),
        )

    # ---- defaulting / validation ------------------------------------------

    def add_defaults_and_validate(self) -> None:
        """Defaulting + validation, mirroring manager.go:86-111.

        The deprecated max_time_shared_clients_per_tpu wins over the sharing
        block when both are set; a strategy requires max clients > 0 and
        vice versa.
        """
        if self.max_time_shared_clients_per_tpu > 0:
            self.sharing.strategy = SharingStrategy.TIME_SHARING
            self.sharing.max_shared_clients_per_tpu = (
                self.max_time_shared_clients_per_tpu
            )
        else:
            s = self.sharing.strategy
            if s in (SharingStrategy.TIME_SHARING, SharingStrategy.CORE_SHARING):
                if self.sharing.max_shared_clients_per_tpu <= 0:
                    raise ValueError(
                        "maxSharedClientsPerTpu should be > 0 for time-sharing "
                        "or core-sharing TPU sharing strategies"
                    )
            elif s == SharingStrategy.UNDEFINED:
                if self.sharing.max_shared_clients_per_tpu > 0:
                    raise ValueError(
                        "TPU sharing strategy needs to be specified when "
                        "maxSharedClientsPerTpu > 0"
                    )
            else:  # pragma: no cover - parse() already rejects unknowns
                raise ValueError(f"invalid TPU sharing strategy: {s}")

        if self.partition_size and self.partition_size not in VALID_PARTITION_SIZES:
            raise ValueError(
                f"invalid tpuPartitionSize {self.partition_size!r}, "
                f"should be one of {VALID_PARTITION_SIZES}"
            )

    def add_health_critical_codes(
        self, env: Optional[dict] = None
    ) -> None:
        """Parse critical error codes from TPU_ERR_CONFIG env (csv ints),
        mirroring AddHealthCriticalXid (manager.go:113-133).

        A malformed entry is logged and skipped — NEVER raised: this
        runs at node-agent startup, and one typo'd env var crashing the
        device plugin into CrashLoopBackOff takes every TPU on the node
        offline.  If no entry parses, the existing (file/default) codes
        are kept.
        """
        env = env if env is not None else os.environ
        raw = env.get(TPU_ERR_CONFIG_ENV, "")
        if not raw:
            return
        codes = []
        for part in raw.split(","):
            part = part.strip()
            if not part:
                continue
            try:
                codes.append(int(part))
            except ValueError:
                log.error(
                    "ignoring invalid %s entry %r (keeping defaults for it)",
                    TPU_ERR_CONFIG_ENV, part,
                )
        if codes:
            self.health_critical_codes = codes
        else:
            log.error(
                "%s=%r contained no valid codes; keeping %s",
                TPU_ERR_CONFIG_ENV, raw, self.health_critical_codes,
            )
