"""Graceful-preemption support for the training drivers.

The infra side of a TPU host maintenance event already exists: the
maintenance watcher sees the GCE advance notice, taints the node, and
drops a code-80 event into the health queue
(``health/maintenance.py:15-33``) — then Kubernetes drains the pod with
SIGTERM and a grace period.  TPU slices cannot live-migrate, so the
only way a training Job survives the drain with its progress is to
convert that SIGTERM into a final synchronous checkpoint before the
SIGKILL lands.  The reference leaves this to its demo images' restart
semantics (demo/gpu-training/generate_job.sh:54-70 restarts from
``--model_dir``); here the driver itself closes the loop.

Usage (both train drivers)::

    guard = PreemptionGuard()          # installs the SIGTERM handler
    for step in range(start, steps):
        state, metrics = step_fn(state, ...)
        if guard.should_stop:
            checkpoint_and_exit(checkpointer, state, step,
                                args.checkpoint_interval)

Exit is NON-zero (80, matching the maintenance event code) on purpose:
a Kubernetes Job that sees exit 0 counts the pod as a completion and
never reschedules it, which would turn every maintenance drain into a
silently truncated training run.  Code 80 makes the Job controller
restart the pod, and the restart resumes from the just-saved step via
``TrainCheckpointer.restore_latest``.
"""

import logging
import signal
import threading

log = logging.getLogger(__name__)

# Mirrors health.maintenance.MAINTENANCE_CODE: the same event, seen
# from inside the workload instead of from the node agent.
PREEMPTED_EXIT_CODE = 80


class PreemptionGuard:
    """Latch SIGTERM into a flag the training loop polls between steps.

    The handler only sets an event — never checkpoints from signal
    context: the main thread may be inside a blocking XLA dispatch, and
    orbax save must run on the thread that owns the arrays.  Polling
    between steps bounds the reaction time to one train step, well
    inside any sane terminationGracePeriod.
    """

    def __init__(self, signals=(signal.SIGTERM,)):
        self._stop = threading.Event()
        self._signum = None
        self._previous = {}
        for s in signals:
            self._previous[s] = signal.signal(s, self._handle)

    def _handle(self, signum, frame):  # noqa: ARG002 — signal signature
        self._signum = signum
        self._stop.set()
        log.warning("received signal %d: will checkpoint and exit %d "
                    "after the current step", signum, PREEMPTED_EXIT_CODE)

    @property
    def should_stop(self) -> bool:
        return self._stop.is_set()

    @property
    def signum(self):
        return self._signum

    def uninstall(self) -> None:
        """Restore previous handlers (test hygiene)."""
        for s, prev in self._previous.items():
            signal.signal(s, prev)
        self._previous.clear()

    # Context-manager form so drivers and tests cannot leak the SIGTERM
    # handler past their scope (a leaked handler redirects a LATER
    # test's/process-phase's SIGTERM into a stale guard's flag):
    #
    #     with PreemptionGuard() as guard:
    #         ...
    def __enter__(self) -> "PreemptionGuard":
        return self

    def __exit__(self, *exc) -> bool:
        self.uninstall()
        return False


def checkpoint_and_exit(checkpointer, state, step: int,
                        checkpoint_interval: int,
                        profiling: bool = False):
    """The drivers' shared SIGTERM tail: final synchronous checkpoint,
    then a Job-restartable exit.

    ``step`` is the loop index just completed; the driver's interval
    save may already have covered it, in which case ``close()``'s
    wait is all that is needed (a second ``save`` of the same step
    would collide in orbax).  Always raises ``SystemExit`` with
    :data:`PREEMPTED_EXIT_CODE`.
    """
    import jax

    if profiling:
        jax.profiler.stop_trace()
    if checkpointer:
        jax.block_until_ready(state.params)
        if (step + 1) % checkpoint_interval != 0:
            checkpointer.save(state, wait=True)
        checkpointer.close()
        log.warning("preempted at step %d: checkpoint saved; exiting "
                    "%d for Job restart + resume", step + 1,
                    PREEMPTED_EXIT_CODE)
    else:
        log.warning("preempted at step %d with no --checkpoint-dir: "
                    "progress is lost", step + 1)
    raise SystemExit(PREEMPTED_EXIT_CODE)
