"""Device-name utilities for TPU accelerator nodes.

TPU VMs expose one char device per chip as ``/dev/accel0`` .. ``/dev/accelN``
(plus ``/dev/vfio/*`` when bound through vfio).  This is the TPU analog of
the reference's ``/dev/nvidiaN`` naming helper
(ref: pkg/gpu/nvidia/util/util.go:22-29).
"""

import re

DEVICE_RE = re.compile(r"^accel([0-9]+)$")
DEVICE_PATH_RE = re.compile(r"^/dev/(accel[0-9]+)$")


def device_name_from_path(path: str) -> str:
    """Map ``/dev/accelN`` to the canonical device name ``accelN``.

    Raises ValueError for paths that are not TPU accelerator device nodes.
    """
    m = DEVICE_PATH_RE.match(path)
    if not m:
        raise ValueError(f"{path!r} is not a TPU device path (/dev/accelN)")
    return m.group(1)


def device_path_from_name(name: str) -> str:
    """Map canonical device name ``accelN`` to its ``/dev`` path."""
    if not DEVICE_RE.match(name):
        raise ValueError(f"{name!r} is not a TPU device name (accelN)")
    return f"/dev/{name}"


def device_index(name: str) -> int:
    """Return N for device name ``accelN``."""
    m = DEVICE_RE.match(name)
    if not m:
        raise ValueError(f"{name!r} is not a TPU device name (accelN)")
    return int(m.group(1))
