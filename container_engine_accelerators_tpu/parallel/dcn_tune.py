"""Closed-loop pipeline control: the data plane tunes its own grid.

The pipelined data plane (parallel/dcn_pipeline.py) runs a fixed
``TPU_DCN_CHUNK_BYTES``/``TPU_DCN_STRIPES`` grid, so a link that
degrades mid-run — loss, latency, partition-and-heal — either
collapses goodput or burns retry rounds until an operator retunes.
Yet every signal a controller needs is already live: the per-round
retransmit ratio, confirmed-bytes goodput, stripe utilization, and
the exposed-communication ratio (obs/critpath.py math recorded by
``send_pipelined`` itself).  This module closes the loop — the
robustness analog of FlexLink's dynamic multi-path traffic
distribution, with the exposed ratio as the objective in the spirit
of T3's overlap accounting (PAPERS.md).

One :class:`FlowTuner` per *destination daemon* (``host:port`` — the
link identity the signals describe; fleet flow NAMES are unique per
round, so per-name state would never learn).  The control law is
AIMD-shaped, one move per observation, strictly ordered so reactions
to trouble always outrank optimism:

- **shrink-on-retransmit** (multiplicative decrease): a round whose
  retransmit ratio reaches ``shrink_retx`` halves the chunk size
  (floor ``min_chunk_bytes``) — smaller chunks mean a lossy link
  re-pays less per loss;
- **back-off-on-loss**: at ``backoff_retx`` the stripe count also
  drops by one (floor ``min_stripes``) — heavy loss means the fan-out
  is feeding a link that cannot carry it;
- **grow-while-goodput-scales** (additive increase, probe/evaluate):
  after ``grow_clean_rounds`` consecutive clean observations the
  tuner probes one more stripe and keeps it only if total goodput
  improved by ``grow_margin`` AND the exposed-communication ratio did
  not get worse than ``exposed_slack`` — per-stripe goodput that
  stopped scaling, or overlap that got worse, reverts the probe and
  remembers the ceiling until the link's conditions change (the next
  loss event clears it);
- **recover-to-base**: ``recover_clean_rounds`` clean observations
  double a shrunken chunk back toward the configured grid — the
  post-heal half of "survives degradation without operator knobs";
- **hysteresis**: at most one adaptation per observation, a cooldown
  of ``cooldown_obs`` observations between moves, and growth streaks
  that any retransmit resets — a noisy signal hovering around a
  threshold ratchets gently in one direction instead of flapping.

Chunk decisions LATCH AT TRANSFER BOUNDARIES: a transfer's chunk grid
pins its client-assigned seq block, and retransmit rounds must re-send
under the SAME seqs for the receiver's dedup window to referee
exactly-once — so mid-transfer the tuner adapts only the stripe
count (re-striping pending chunk indices is seq-safe), and the chunk
move it decided applies to the destination's next transfer.  Zero-copy
shm rounds have no stripe fan-out at all: they bypass stripe
adaptation and keep chunk adaptation, exactly as the lane bypasses the
stager threads.

``TPU_DCN_TUNE`` is the kill switch — and the loop is ON by default
now that the continuous soak world (fleet/soak.py) gates every
presubmit on its convergence: ``TPU_DCN_TUNE=0`` still pins today's
static grid byte-for-byte.  Learned state never survives a daemon
respawn by construction — a restarted daemon binds a fresh data port,
which is a fresh controller key; the stale key ages out of the
bounded registry.

Decisions are observable like everything else in this stack:
``dcn.tune.*`` counters per decision kind, ``dcn.tune.chunk_bytes`` /
``dcn.tune.stripes`` gauges carrying the latest plan, an ``agent_top``
tuner line, and a bounded per-tuner decision HISTORY
(:func:`decision_history`) that the soak world's oscillation sentinel
replays.  The profiler bridge is observation-only: each observation
records the ``shm-staging`` subsystem share next to goodput, and the
``dcn.tune.cpu_bound`` gauge flips to 1.0 when staging share grows
while goodput stalls — the host is the bottleneck, so no grid move
can help and the tuner (deliberately) takes none.
"""

import logging
import os
import statistics
import threading
from typing import Dict, Optional, Tuple

from container_engine_accelerators_tpu.metrics import counters
from container_engine_accelerators_tpu.obs import timeseries, trace

log = logging.getLogger(__name__)

TUNE_ENV = "TPU_DCN_TUNE"
MIN_CHUNK_ENV = "TPU_DCN_TUNE_MIN_CHUNK"
MAX_STRIPES_ENV = "TPU_DCN_TUNE_MAX_STRIPES"

DEFAULT_MIN_CHUNK_BYTES = 64 << 10
DEFAULT_MAX_STRIPES = 8

# Bounded registry of per-destination tuners: a long-lived process
# talking to churning fleets must not leak controller state — past the
# cap the least-recently-planned destination is evicted (its daemon is
# gone or idle; a fresh key relearns from the static grid).
MAX_TUNERS = 64

# Bounded per-tuner decision history: every observation appends one
# entry (decision or None), the soak world's oscillation sentinel
# replays the tail, and the cap keeps a days-long soak from turning
# the controller into a leak of its own.
MAX_HISTORY = 512

# The profiler bridge verdict (observation-only): ``cpu_bound`` means
# staging share grew at least this much while goodput failed to beat
# the last observation by more than scheduling slack — evidence the
# HOST, not the link, is the bottleneck, so no grid move can help.
CPU_BOUND_SHARE_STEP = 0.05
CPU_BOUND_GOODPUT_SLACK = 1.02


def _profiler_staging_share() -> Optional[float]:
    """The ``shm-staging`` subsystem share from the in-process
    profiler, or None when the profiler is not running — the default
    observation source for the tuner's cpu-bound verdict.  Injectable
    per tuner for tests (and for the soak driver's synthetic rigs)."""
    from container_engine_accelerators_tpu.obs import profiler
    if not profiler.running():
        return None
    try:
        return float(profiler.subsystem_shares().get("shm-staging",
                                                     0.0))
    except Exception:  # pragma: no cover - defensive: never block a plan
        return None


def tune_enabled(env=None) -> bool:
    """The kill switch.  Default ON: the continuous soak world
    (fleet/soak.py, ``make soak``) is the standing evidence the loop
    converges and never limit-cycles under mixed load, so absent the
    env var the closed loop runs.  ``TPU_DCN_TUNE=0`` (or any falsy
    spelling, including explicitly empty) pins the static grid."""
    env = env if env is not None else os.environ
    return env.get(TUNE_ENV, "1") not in ("0", "false", "off", "")


class TuneConfig:
    """Control-law constants, env-overridable floors/ceilings.  The
    *base* chunk/stripe grid comes per plan() call from the
    PipelineConfig, so one tuner serves callers with different
    configured grids without preferring the first it saw."""

    def __init__(self, env=None, *,
                 min_chunk_bytes: Optional[int] = None,
                 max_stripes: Optional[int] = None,
                 min_stripes: int = 1,
                 shrink_retx: float = 0.05,
                 backoff_retx: float = 0.25,
                 grow_margin: float = 1.10,
                 exposed_slack: float = 0.10,
                 grow_clean_rounds: int = 2,
                 recover_clean_rounds: int = 3,
                 cooldown_obs: int = 1,
                 probe_patience: int = 3,
                 bound_ttl_obs: int = 12):
        env = env if env is not None else os.environ
        if min_chunk_bytes is None:
            min_chunk_bytes = _env_int(env, MIN_CHUNK_ENV,
                                       DEFAULT_MIN_CHUNK_BYTES)
        if max_stripes is None:
            max_stripes = _env_int(env, MAX_STRIPES_ENV,
                                   DEFAULT_MAX_STRIPES)
        self.min_chunk_bytes = max(1, int(min_chunk_bytes))
        self.min_stripes = max(1, int(min_stripes))
        self.max_stripes = max(self.min_stripes, int(max_stripes))
        self.shrink_retx = float(shrink_retx)
        self.backoff_retx = max(float(backoff_retx), self.shrink_retx)
        self.grow_margin = float(grow_margin)
        self.exposed_slack = float(exposed_slack)
        self.grow_clean_rounds = max(1, int(grow_clean_rounds))
        self.recover_clean_rounds = max(1, int(recover_clean_rounds))
        self.cooldown_obs = max(0, int(cooldown_obs))
        # A probe is kept the first observation that qualifies and
        # reverted only after this many that do not: goodput samples
        # arrive under scheduling noise, and one slow draw must not
        # pin a wrong bound.
        self.probe_patience = max(1, int(probe_patience))
        # Reverted-probe bounds EXPIRE after this many observations:
        # on a loss-free link nothing else ever clears them, and both
        # "the measurement was a noise artifact" and "the competing
        # load went away" deserve a (bounded, infrequent) re-probe.
        self.bound_ttl_obs = max(1, int(bound_ttl_obs))


def _env_int(env, key: str, default: int) -> int:
    """Malformed values degrade to the default — the TPU_FAULT_SPEC
    rule: a typo'd knob must never take the data plane down."""
    raw = env.get(key)
    if raw is None:
        return default
    try:
        v = int(raw)
        if v <= 0:
            raise ValueError("must be > 0")
        return v
    except ValueError:
        log.error("ignoring malformed %s=%r (want a positive int)",
                  key, raw)
        return default


class FlowTuner:
    """The per-destination controller.  Pure decision logic — the
    pipeline feeds observations (:meth:`on_round`, :meth:`on_transfer`)
    and reads plans (:meth:`plan`, :meth:`stripes_now`); nothing here
    touches a socket, which is what makes the decision table unit-
    testable row by row."""

    def __init__(self, key: str, cfg: Optional[TuneConfig] = None,
                 staging_share=None):
        self.key = key
        self.cfg = cfg or TuneConfig()
        self._lock = threading.Lock()
        # Profiler bridge (observation-only): a zero-arg callable
        # returning the staging-memcpy subsystem share, or None when
        # unknown.  Injectable so the verdict is unit-testable without
        # a live profiler.
        self._staging_share = (staging_share if staging_share
                               is not None else _profiler_staging_share)
        self._last_share: Optional[float] = None
        self._last_goodput: Optional[float] = None
        self._cpu_bound = False
        # Bounded observation log for the oscillation sentinel.
        self._history: list = []
        self._decisions = 0
        # Learned grid deltas, applied to the caller's base grid:
        # chunk_scale is a power-of-two divisor (1 = the base grid),
        # stripe_delta an additive offset.  Keeping deltas instead of
        # absolutes means a caller that reconfigures its base mid-run
        # still gets the learned *adjustment*, not a stale absolute.
        self._chunk_scale = 1
        self._stripe_delta = 0
        self._base_chunk = 0  # last seen, for the gauges/logs only
        self._base_stripes = 0
        # Signal state.
        self._clean_streak = 0
        self._since_move = 10 ** 9  # observations since the last move
        self._last_exposed: Optional[float] = None
        # Recent clean-goodput window: probe baselines use its median.
        self._goodputs: list = []
        # Stripe probe in flight:
        # [baseline_goodput, baseline_exposed, direction, tries_left].
        # Every post-probe observation runs on the probed grid (plan()
        # at the next transfer, stripes_now() at the next retry
        # round): kept the first observation that qualifies, reverted
        # after ``probe_patience`` that do not.
        self._probe: Optional[list] = None
        # Remembered bounds from reverted probes — the values that
        # measurably did not help, in either direction; cleared by the
        # next loss event (conditions changed, worth re-probing).
        self._stripe_ceiling: Optional[int] = None
        self._stripe_floor: Optional[int] = None
        self._bound_set_obs = 0
        # True while the stripe count sits below base BECAUSE of a
        # loss backoff: only then does recovery toward base get the
        # lenient non-regression margin — a count the tuner chose to
        # narrow on a clean link must be beaten fair and square, or
        # borderline rigs would oscillate around it.
        self._loss_backed_off = False
        self.observations = 0

    # -- plans ---------------------------------------------------------------

    def plan(self, chunk_bytes: int, stripes: int) -> Tuple[int, int]:
        """The grid for a NEW transfer toward this destination:
        the caller's base grid with the learned adjustments applied,
        clamped to the floors/ceilings.  Publishes the plan gauges."""
        with self._lock:
            self._base_chunk = int(chunk_bytes)
            self._base_stripes = int(stripes)
            chunk, stripes_out = self._plan_locked()
        timeseries.gauge("dcn.tune.chunk_bytes", float(chunk))
        timeseries.gauge("dcn.tune.stripes", float(stripes_out))
        return chunk, stripes_out

    def _plan_locked(self) -> Tuple[int, int]:
        # The chunk floor bounds how far SHRINKING goes; a base grid
        # already below it is the operator's call and stays put —
        # clamping a small base UP would change static behavior the
        # moment the switch flips.
        floor = min(self.cfg.min_chunk_bytes, self._base_chunk)
        chunk = max(floor, self._base_chunk // self._chunk_scale)
        ceiling = self.cfg.max_stripes
        if self._stripe_ceiling is not None:
            ceiling = min(ceiling, self._stripe_ceiling)
        stripes = max(self.cfg.min_stripes,
                      min(self._base_stripes + self._stripe_delta,
                          ceiling))
        return chunk, stripes

    def stripes_now(self) -> int:
        """The stripe count for the NEXT retry round of an in-flight
        transfer — stripe moves apply mid-transfer (re-striping pending
        chunks is seq-safe); chunk moves wait for :meth:`plan`."""
        with self._lock:
            return self._plan_locked()[1]

    # -- observations --------------------------------------------------------

    def on_round(self, attempted: int, failed: int,
                 bytes_confirmed: int, elapsed_s: float,
                 lane: str = "socket",
                 full_round: bool = True) -> Optional[str]:
        """Feed one retry round's outcome; returns the decision taken
        (a ``dcn.tune.*`` counter suffix) or None.  ``lane == "shm"``
        rounds have no stripe fan-out: stripe decisions are bypassed,
        chunk decisions still apply.  ``full_round=False`` marks a
        partial retry round (a handful of re-sent chunks): its B/s is
        fixed-overhead-dominated and incomparable with full rounds, so
        it feeds the loss laws but never the capability window or a
        probe verdict."""
        if attempted <= 0:
            return None
        retx = failed / attempted
        goodput = (bytes_confirmed / elapsed_s if elapsed_s > 0
                   else 0.0)
        return self._observe(retx, goodput, exposed=None, lane=lane,
                             full=full_round)

    def on_transfer(self, ok: bool,
                    exposed_ratio: Optional[float] = None) -> None:
        """Transfer epilogue: a completed transfer contributes the
        exposed-communication ratio (only computable whole-transfer)
        to the NEXT decision's evidence; a failed transfer (round
        budget spent — the link is in real trouble) counts as a
        fully-lost observation so the decrease laws fire even when no
        round produced a verdict."""
        if ok:
            with self._lock:
                if exposed_ratio is not None:
                    self._last_exposed = float(exposed_ratio)
            return
        self._observe(1.0, 0.0, exposed=None, lane="socket",
                      full=True)

    def _observe(self, retx: float, goodput: float,
                 exposed: Optional[float], lane: str,
                 full: bool = True) -> Optional[str]:
        # Profiler read OUTSIDE the lock: the provider may sample
        # /proc or walk frames — never under the decision lock.
        try:
            share = self._staging_share()
        except Exception:
            share = None
        with self._lock:
            self.observations += 1
            self._since_move += 1
            exposed = exposed if exposed is not None \
                else self._last_exposed
            decision = self._decide_locked(retx, goodput, exposed,
                                           lane, full)
            chunk, stripes = self._plan_locked()
            # cpu-bound verdict (observation-only, never a decision
            # input): staging share grew while goodput stalled — the
            # host is the bottleneck, no grid move can help.
            if (share is not None and self._last_share is not None
                    and self._last_goodput is not None):
                self._cpu_bound = (
                    share > self._last_share + CPU_BOUND_SHARE_STEP
                    and goodput <= (self._last_goodput
                                    * CPU_BOUND_GOODPUT_SLACK))
            if share is not None:
                self._last_share = share
            self._last_goodput = goodput
            cpu_bound = self._cpu_bound
            if decision:
                self._decisions += 1
            self._history.append({
                "obs": self.observations,
                "decision": decision,
                "retx": round(retx, 4),
                "goodput_bps": round(goodput, 1),
                "staging_share": (round(share, 4)
                                  if share is not None else None),
                "chunk_bytes": chunk,
                "stripes": stripes,
            })
            del self._history[:-MAX_HISTORY]
        if decision:
            counters.inc(f"dcn.tune.{decision}")
            trace.event("dcn.tune.decision", key=self.key,
                        decision=decision, retx=round(retx, 4),
                        goodput_bps=round(goodput, 1),
                        chunk_bytes=chunk, stripes=stripes)
            log.info("dcn tuner %s: %s -> chunk=%d stripes=%d "
                     "(retx=%.3f, goodput=%.0f B/s)", self.key,
                     decision, chunk, stripes, retx, goodput)
        timeseries.gauge("dcn.tune.chunk_bytes", float(chunk))
        timeseries.gauge("dcn.tune.stripes", float(stripes))
        timeseries.gauge("dcn.tune.cpu_bound",
                         1.0 if cpu_bound else 0.0)
        return decision

    # -- the decision table (caller holds the lock) --------------------------

    def _decide_locked(self, retx: float, goodput: float,
                       exposed: Optional[float], lane: str,
                       full: bool = True) -> Optional[str]:
        cfg = self.cfg
        lossy = retx >= cfg.shrink_retx
        if lossy:
            self._clean_streak = 0
            # Conditions changed: remembered probe bounds and the
            # capability window from a clean-link era no longer
            # describe this link (stale pre-degrade highs would
            # sandbag every post-heal recovery probe).
            self._stripe_ceiling = None
            self._stripe_floor = None
            self._goodputs.clear()
        else:
            self._clean_streak += 1
            if (self._stripe_ceiling is not None
                    or self._stripe_floor is not None) \
                    and (self.observations - self._bound_set_obs
                         >= cfg.bound_ttl_obs):
                # Bounds age out on loss-free links: a bound pinned by
                # one noisy measurement (or by load that has since
                # moved on) must not freeze the grid forever —
                # re-exploration stays bounded and infrequent.
                self._stripe_ceiling = None
                self._stripe_floor = None

        if not lossy and full and lane != "shm":
            # Short capability window: probe baselines use its median
            # (the typical recent capability under scheduling noise).
            # Only FULL socket rounds are comparable samples: shm
            # rounds run at memcpy class, and a partial retry round's
            # B/s is fixed-overhead-dominated — either would skew
            # every later probe verdict.
            self._goodputs.append(goodput)
            del self._goodputs[:-4]

        # A probe's verdict: kept the FIRST post-probe observation
        # that qualifies, reverted only after ``probe_patience`` that
        # do not — judged before any other law moves.  Partial rounds
        # are not comparable evidence: they neither keep nor spend
        # patience (loss still judges immediately).
        if self._probe is not None and lane != "shm" \
                and (full or lossy):
            base_goodput, base_exposed, direction, tries = self._probe
            probed = self._base_stripes + self._stripe_delta
            if lossy and direction < 0:
                # A narrower fan-out that rode into loss: the loss is
                # its own verdict and it AGREES with the reduction —
                # keep it without marking a floor, and let the
                # decrease laws below respond to the loss itself.
                self._probe = None
            else:
                # Growth probes ABOVE the configured base must prove
                # the fan-out scales (+grow_margin); growth recovering
                # TOWARD base — known-good, operator-blessed territory
                # a loss backoff left — only has to not regress.  A
                # DOWNWARD probe must measurably pay (the same margin),
                # or flat noise would drift every clean link to one
                # stripe.
                if direction > 0 and probed <= self._base_stripes \
                        and self._loss_backed_off:
                    margin = 1.0
                else:
                    margin = cfg.grow_margin
                qualifies = (not lossy
                             and goodput >= base_goodput * margin
                             and not _exposed_worse(
                                 exposed, base_exposed,
                                 cfg.exposed_slack))
                if qualifies:
                    self._probe = None
                    self._since_move = 0
                    if direction > 0 and probed >= self._base_stripes:
                        self._loss_backed_off = False
                    return "keep_stripe"
                if not lossy and tries > 1:
                    # One slow sample is scheduling noise, not a
                    # verdict: spend a patience try, keep watching.
                    self._probe[3] = tries - 1
                    return None
                # Out of patience (or loss failing a growth probe):
                # one move per observation — the revert IS this
                # observation's move.  The remembered bound is the
                # last KNOWN-GOOD count (one step back from the
                # probe), so the failed value is never re-probed until
                # a loss event says conditions changed — that re-probe
                # loop would be the flap the hysteresis exists to
                # prevent.
                self._probe = None
                self._since_move = 0
                self._bound_set_obs = self.observations
                if direction > 0:
                    self._stripe_ceiling = max(
                        self.cfg.min_stripes, probed - 1)
                else:
                    self._stripe_floor = min(
                        self.cfg.max_stripes, probed + 1)
                self._stripe_delta -= direction
                return "revert_stripe"

        # Decrease laws: reactions to trouble outrank optimism AND
        # hysteresis — the cooldown exists to stop flapping between
        # opposing moves, never to delay a loss response.  Repeated
        # lossy observations keep decreasing (the TCP-shaped
        # multiplicative half of AIMD).
        if lossy:
            if retx >= cfg.backoff_retx and lane != "shm":
                _, cur_stripes = self._plan_locked()
                if cur_stripes > cfg.min_stripes:
                    self._stripe_delta -= 1
                    self._since_move = 0
                    self._loss_backed_off = True
                    return "backoff_stripe"
                # At the floor: fall through to the chunk shrink —
                # the one remaining lever.
            cur_chunk, _ = self._plan_locked()
            if cur_chunk > cfg.min_chunk_bytes:
                self._chunk_scale *= 2
                self._since_move = 0
                return "shrink_chunk"
            counters.inc("dcn.tune.clamped")
            return None

        if self._since_move <= cfg.cooldown_obs:
            return None  # hysteresis: let the last move settle

        # Increase laws, clean observations only.
        if self._chunk_scale > 1 \
                and self._clean_streak >= cfg.recover_clean_rounds:
            self._chunk_scale //= 2
            self._clean_streak = 0
            self._since_move = 0
            return "grow_chunk"
        if lane != "shm" and full \
                and self._clean_streak >= cfg.grow_clean_rounds:
            if self._cpu_bound:
                # The PR 16 profiler verdict, acted on: staging share
                # is climbing while goodput is flat — the plane is
                # CPU-bound, not link-bound, a regime AIMD's loss/
                # goodput laws cannot see.  More stripes would add
                # thread fan-out to a saturated CPU, so both stripe
                # probes are held (not reverted — no move, no
                # hysteresis reset) until the latch clears.  The
                # latch this decision sees is the PREVIOUS
                # observation's (cpu_bound is recomputed after the
                # decision), one observation of lag by design.
                counters.inc("dcn.tune.cpu_hold")
                return None
            _, cur_stripes = self._plan_locked()
            ceiling = cfg.max_stripes
            if self._stripe_ceiling is not None:
                ceiling = min(ceiling, self._stripe_ceiling)
            floor = cfg.min_stripes
            if self._stripe_floor is not None:
                floor = max(floor, self._stripe_floor)
            # Median, not max: the baseline is the TYPICAL recent
            # capability — a probe judged against the luckiest recent
            # draw could never win on a noisy rig, and one judged
            # against the unluckiest would keep anything.
            base_goodput = (statistics.median(self._goodputs)
                            if self._goodputs else goodput)
            patience = cfg.probe_patience
            if cur_stripes + 1 <= ceiling:
                # Add stripes while per-stripe goodput still scales.
                self._probe = [base_goodput, exposed, +1, patience]
                self._stripe_delta += 1
                self._clean_streak = 0
                self._since_move = 0
                return "grow_stripe"
            if cur_stripes - 1 >= floor:
                # Growth is capped (reverted, or at the ceiling): try
                # the OTHER direction — on rigs where fan-out costs
                # more than it buys (loopback; a saturated NIC), fewer
                # stripes IS the optimum, and a controller that can
                # only match the operator's base can never beat the
                # best hand-tuned grid.  Kept only if it measurably
                # pays, so flat noise never drains stripes.
                self._probe = [base_goodput, exposed, -1, patience]
                self._stripe_delta -= 1
                self._clean_streak = 0
                self._since_move = 0
                return "narrow_stripe"
        return None

    # -- lifecycle -----------------------------------------------------------

    def snapshot(self) -> dict:
        with self._lock:
            chunk, stripes = self._plan_locked()
            return {
                "key": self.key,
                "chunk_bytes": chunk,
                "stripes": stripes,
                "chunk_scale": self._chunk_scale,
                "stripe_delta": self._stripe_delta,
                "stripe_ceiling": self._stripe_ceiling,
                "clean_streak": self._clean_streak,
                "observations": self.observations,
                "probing": self._probe is not None,
                "decisions": self._decisions,
                "cpu_bound": self._cpu_bound,
            }

    def history(self) -> list:
        """The bounded observation log — one entry per observation
        (``decision`` is None when no law fired), newest last.  The
        soak world's convergence sentinel replays this to tell a
        settling controller from a limit cycle."""
        with self._lock:
            return [dict(e) for e in self._history]


def _exposed_worse(now: Optional[float], before: Optional[float],
                   slack: float) -> bool:
    """The objective check: did the overlap get worse?  Unknown on
    either side judges nothing (the goodput law still referees)."""
    if now is None or before is None:
        return False
    return now > before + slack


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------

_lock = threading.Lock()
_tuners: Dict[str, FlowTuner] = {}
_order: Dict[str, int] = {}  # key -> last-plan tick (LRU eviction)
_tick = 0


def tuner_for(key: str,
              cfg: Optional[TuneConfig] = None) -> FlowTuner:
    """The per-destination tuner.  The kill switch is the CALLER's
    decision (``PipelineConfig.tuned`` — env-resolved, per-config
    overridable): a disabled pipeline simply never asks.  A
    destination is a daemon address (``host:port``): a SIGKILLed
    worker respawns on a fresh port, so its learned state is reset
    cleanly by construction — the dead key just ages out."""
    global _tick
    with _lock:
        _tick += 1
        tuner = _tuners.get(key)
        if tuner is None:
            if len(_tuners) >= MAX_TUNERS:
                oldest = min(_order, key=_order.get)
                del _tuners[oldest]
                del _order[oldest]
            tuner = _tuners[key] = FlowTuner(key, cfg)
        _order[key] = _tick
        timeseries.gauge("dcn.tune.flows", float(len(_tuners)))
        return tuner


def snapshot() -> Dict[str, dict]:
    with _lock:
        items = list(_tuners.values())
    return {t.key: t.snapshot() for t in items}


def decision_history() -> Dict[str, list]:
    """Every live tuner's bounded observation log, keyed like
    :func:`snapshot` — the export the soak oscillation sentinel (and
    the soak report's tuner section) consumes."""
    with _lock:
        items = list(_tuners.values())
    return {t.key: t.history() for t in items}


def reset() -> None:
    """Drop every tuner — test isolation and scenario boots, same
    contract as counters.reset()."""
    with _lock:
        _tuners.clear()
        _order.clear()
