"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

Long-context scaling over the slice fabric.  The reference has no model
code at this altitude — its sequence-length-scaling analog is bandwidth
scaling via multi-NIC GPUDirect + topology packing (SURVEY.md §5
"Long-context / sequence parallelism") — so these are the TPU-native
first-class equivalents: the sequence axis is sharded across devices and
the attention collectives ride ICI.

Two standard schemes, both jittable under ``shard_map`` over an existing
mesh axis (no new infrastructure):

- :func:`ring_attention` — K/V blocks rotate around the ring with
  ``lax.ppermute`` while each device accumulates flash-style online
  softmax statistics for its resident Q block.  Per-step traffic is one
  K/V block to the ICI neighbor, overlapping compute and transfer the
  way the scaling-book recipe prescribes; memory per device is
  O(seq/n_devices).
- :func:`ulysses_attention` — ``lax.all_to_all`` reshuffles the
  sequence shard into a head shard so each device runs *dense* attention
  over the full sequence for heads/n_devices heads, then shuffles back.
  Cheaper compute pattern for moderate sequence lengths; requires
  num_heads % axis_size == 0.

Both are numerically equivalent to single-device attention (see
tests/test_seq_parallel.py for the replicated-reference check).
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

NEG_INF = -1e30


def _pvary(x, axis_name):
    """Mark ``x`` as varying over ``axis_name`` (shard_map type system).

    ``lax.pcast(..., to="varying")`` replaced ``lax.pvary``; support both
    so the module imports on the JAX range pyproject allows.
    """
    if hasattr(lax, "pcast"):
        return lax.pcast(x, (axis_name,), to="varying")
    return lax.pvary(x, (axis_name,))


def dense_attention(q, k, v, causal=False, scale=None):
    """Scaled-dot-product attention, softmax statistics in float32.

    The single source of attention numerics: the transformer's dense
    branch and the Ulysses post-all_to_all attention both call this, and
    the ring path accumulates in f32 to match, so every scheme agrees in
    bf16 — logits and the exp/sum run in f32 regardless of input dtype,
    only the two matmuls stay in the input precision.
    """
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q * scale, k,
        preferred_element_type=jnp.float32,
    )
    if causal:
        t_q, t_k = q.shape[1], k.shape[1]
        mask = jnp.tril(jnp.ones((t_q, t_k), bool))
        s = jnp.where(mask[None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum(
        "bhqk,bkhd->bqhd", p, v, preferred_element_type=jnp.float32
    )
    return o.astype(q.dtype)


def _block_attend(q, k, v, m, l, o, causal_mask=None):
    """One flash-attention accumulation step against a K/V block.

    q: [B, Tq, H, D]; k/v: [B, Tk, H, D]; m/l running max/denominator
    float32 [B, H, Tq]; o unnormalized f32 accumulator [B, Tq, H, D].
    Statistics run in f32 so the ring result matches
    :func:`dense_attention` in bf16; the QK/PV matmuls keep the input
    precision with f32 accumulation (``preferred_element_type``).
    """
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    )
    if causal_mask is not None:
        s = jnp.where(causal_mask, s, NEG_INF)
    m_new = jnp.maximum(m, s.max(axis=-1))
    # Rescale previous accumulator to the new max, then add this block.
    correction = jnp.exp(m - m_new)
    p = jnp.exp(s - m_new[..., None])
    l_new = l * correction + p.sum(axis=-1)
    o_new = o * correction.transpose(0, 2, 1)[..., None] + jnp.einsum(
        "bhqk,bkhd->bqhd", p.astype(v.dtype), v,
        preferred_element_type=jnp.float32,
    )
    return m_new, l_new, o_new


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    causal: bool = False,
    scale: Optional[float] = None,
) -> jax.Array:
    """Ring self-attention over a sequence-sharded axis.

    Call inside ``shard_map``; q/k/v are the per-device sequence shards
    ``[batch, seq/n, heads, head_dim]``.  K/V rotate n-1 times via
    ``ppermute`` to the next ring neighbor; a ``lax.scan`` over ring
    steps keeps the jitted program free of Python-level unrolling.
    """
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    q = q * scale

    b, tq, h, d = q.shape
    tk = k.shape[1]
    # Mark the running stats as varying over the ring axis up front: the
    # scan carry must keep one type, and the outputs vary (they depend on
    # this device's Q block and ring position).  Statistics are f32 so the
    # ring matches dense_attention in bf16.
    m0 = _pvary(jnp.full((b, h, tq), NEG_INF, jnp.float32), axis_name)
    l0 = _pvary(jnp.zeros((b, h, tq), jnp.float32), axis_name)
    o0 = _pvary(jnp.zeros((b, tq, h, d), jnp.float32), axis_name)

    q_pos = idx * tq + jnp.arange(tq)  # global positions of resident Q

    perm = [(i, (i + 1) % n) for i in range(n)]

    def attend(m, l, o, k_blk, v_blk, step_idx):
        # The K/V block resident at ring step s arrived from rank idx - s.
        src = (idx - step_idx) % n
        if causal:
            k_pos = src * tk + jnp.arange(tk)
            mask = q_pos[:, None] >= k_pos[None, :]  # [Tq, Tk]
            mask = mask[None, None, :, :]
        else:
            mask = None
        return _block_attend(q, k_blk, v_blk, m, l, o, mask)

    def step(carry, step_idx):
        m, l, o, k_blk, v_blk = carry
        m, l, o = attend(m, l, o, k_blk, v_blk, step_idx)
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        return (m, l, o, k_blk, v_blk), None

    # n-1 rotations: the scan attends+rotates for steps 0..n-2; the last
    # arriving block is attended outside so its K/V are never forwarded
    # (a final ppermute would be dead ICI traffic).
    (m, l, o, k_last, v_last), _ = lax.scan(
        step, (m0, l0, o0, k, v), jnp.arange(n - 1)
    )
    m, l, o = attend(m, l, o, k_last, v_last, n - 1)
    out = o * (1.0 / l).transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    causal: bool = False,
    scale: Optional[float] = None,
) -> jax.Array:
    """All-to-all (DeepSpeed-Ulysses style) sequence parallelism.

    Inside ``shard_map`` with q/k/v ``[batch, seq/n, heads, head_dim]``:
    an all-to-all converts the sequence shard into a head shard
    ``[batch, seq, heads/n, head_dim]``, each device attends densely over
    the full sequence for its heads, and a reverse all-to-all restores
    the sequence shard.
    """
    n = lax.axis_size(axis_name)
    scale = scale if scale is not None else q.shape[-1] ** -0.5

    def seq_to_heads(x):
        # [B, T/n, H, D] -> [B, T, H/n, D]
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    def heads_to_seq(x):
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    if q.shape[2] % n:
        raise ValueError(
            f"ulysses needs num_heads ({q.shape[2]}) divisible by the "
            f"sequence-parallel degree ({n})"
        )
    qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    oh = dense_attention(qh, kh, vh, causal=causal, scale=scale)
    return heads_to_seq(oh)


def make_sequence_parallel_attention(
    mesh: Mesh,
    kind: str = "ring",
    causal: bool = False,
    axis_name: str = "data",
):
    """Jit a sequence-parallel attention over ``mesh``.

    Returns ``fn(q, k, v) -> out`` taking GLOBAL ``[B, T, H, D]`` arrays
    sharded (or shardable) on ``axis_name`` along T; the wrapper applies
    ``shard_map`` + jit with the sequence axis sharded and batch/heads
    replicated across that axis.
    """
    kinds = {"ring": ring_attention, "ulysses": ulysses_attention}
    if kind not in kinds:
        raise ValueError(
            f"kind must be one of {'|'.join(sorted(kinds))}, got {kind!r}"
        )
    inner = kinds[kind]
    spec = P(None, axis_name, None, None)

    @functools.partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    def sharded(q, k, v):
        return inner(q, k, v, axis_name=axis_name, causal=causal)

    sharding = NamedSharding(mesh, spec)
    return jax.jit(
        sharded,
        in_shardings=(sharding, sharding, sharding),
        out_shardings=sharding,
    )
