"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

Long-context scaling over the slice fabric.  The reference has no model
code at this altitude — its sequence-length-scaling analog is bandwidth
scaling via multi-NIC GPUDirect + topology packing (SURVEY.md §5
"Long-context / sequence parallelism") — so these are the TPU-native
first-class equivalents: the sequence axis is sharded across devices and
the attention collectives ride ICI.

Two standard schemes, both jittable under ``shard_map`` over an existing
mesh axis (no new infrastructure):

- :func:`ring_attention` — K/V blocks rotate around the ring with
  ``lax.ppermute`` while each device accumulates flash-style online
  softmax statistics for its resident Q block.  Per-step traffic is one
  K/V block to the ICI neighbor, overlapping compute and transfer the
  way the scaling-book recipe prescribes; memory per device is
  O(seq/n_devices).  ``layout="zigzag"`` adds the causally-balanced
  striped layout + fully-masked-chunk skipping (~2x causal critical
  path at scale — an executed-work accounting pinned by tests, NOT a
  measured wall-clock claim: the 8-way virtual CPU mesh measures
  1.19x because its ranks share cores, and >= 2 real chips are needed
  to verify the dedicated-hardware number; always report both, see
  BENCH_HW.md round 4.  Layout comment above
  :func:`zigzag_permutation`).
- :func:`ulysses_attention` — ``lax.all_to_all`` reshuffles the
  sequence shard into a head shard so each device runs *dense* attention
  over the full sequence for heads/n_devices heads, then shuffles back.
  Cheaper compute pattern for moderate sequence lengths; requires
  num_heads % axis_size == 0.

Both are numerically equivalent to single-device attention (see
tests/test_seq_parallel.py for the replicated-reference check).
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

NEG_INF = -1e30


def _pvary(x, axis_name):
    """Mark ``x`` as varying over ``axis_name`` (shard_map type system).

    ``lax.pcast(..., to="varying")`` replaced ``lax.pvary``; support both
    so the module imports on the JAX range pyproject allows.
    """
    if hasattr(lax, "pcast"):
        return lax.pcast(x, (axis_name,), to="varying")
    return lax.pvary(x, (axis_name,))


def dense_attention(q, k, v, causal=False, scale=None):
    """Scaled-dot-product attention, softmax statistics in float32.

    The single source of attention numerics: the transformer's dense
    branch and the Ulysses post-all_to_all attention both call this, and
    the ring path accumulates in f32 to match, so every scheme agrees in
    bf16 — logits and the exp/sum run in f32 regardless of input dtype,
    only the two matmuls stay in the input precision.
    """
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q * scale, k,
        preferred_element_type=jnp.float32,
    )
    if causal:
        t_q, t_k = q.shape[1], k.shape[1]
        mask = jnp.tril(jnp.ones((t_q, t_k), bool))
        s = jnp.where(mask[None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum(
        "bhqk,bkhd->bqhd", p, v, preferred_element_type=jnp.float32
    )
    return o.astype(q.dtype)


# Largest K-chunk a ring step scores at once: bounds the live logits
# intermediate to [B, H, Tq, RING_CHUNK] f32 — O(Tq) per device, never
# O(Tq * Tk) — so ring memory stays linear in the sequence shard.
RING_CHUNK = 512


def _chunk_attend(q, k, v, m, l, o, q_pos=None, k_pos=None):
    """One flash-attention accumulation step against ONE K/V chunk.

    q: [B, Tq, H, D]; k/v: [B, C, H, D]; m/l running max/denominator
    float32 [B, H, Tq]; o unnormalized f32 accumulator [B, Tq, H, D].
    Statistics run in f32 so the ring result matches
    :func:`dense_attention` in bf16; the QK/PV matmuls keep the input
    precision with f32 accumulation (``preferred_element_type``).
    ``q_pos``/``k_pos`` are global token positions; when given, keys at
    positions above the query are causally masked.
    """
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    )
    if q_pos is not None:
        mask = q_pos[:, None] >= k_pos[None, :]  # [Tq, C]
        s = jnp.where(mask[None, None], s, NEG_INF)
    m_new = jnp.maximum(m, s.max(axis=-1))
    # Rescale previous accumulator to the new max, then add this chunk.
    correction = jnp.exp(m - m_new)
    p = jnp.exp(s - m_new[..., None])
    l_new = l * correction + p.sum(axis=-1)
    o_new = o * correction.transpose(0, 2, 1)[..., None] + jnp.einsum(
        "bhqk,bkhd->bqhd", p.astype(v.dtype), v,
        preferred_element_type=jnp.float32,
    )
    return m_new, l_new, o_new


def _chunks_of(tk: int) -> tuple:
    """(chunk, nc) splitting a K block of tk columns into RING_CHUNK runs
    (single chunk when ragged — correct, more memory)."""
    chunk = min(tk, RING_CHUNK)
    if tk % chunk:
        chunk = tk
    return chunk, tk // chunk


def _fully_masked(q_pos, k_pos):
    """True when every (q, k) pair in this chunk is causally masked —
    the chunk contributes nothing and its matmuls can be skipped."""
    return k_pos.min() > q_pos.max()


# ---- zigzag layout ---------------------------------------------------------
#
# Contiguous sequence sharding makes causal ring attention imbalanced:
# rank 0's queries attend almost nothing, rank n-1's attend everything,
# and each ring step's wall time is set by the busiest rank (ppermute
# synchronizes), so skipping masked work buys no wall time.  The zigzag
# layout (striped ring attention) gives every rank one EARLY and one
# LATE half-chunk — chunks i and 2n-1-i — so at every ring step every
# rank has about half a block of real work: combined with the
# fully-masked-chunk skip, causal attention FLOPs on the critical path
# drop ~2x at scale, with identical numerics (positions travel with the
# data; the mask math never assumes contiguity).
#
# The skip only REALIZES that ~2x when each Q half is attended
# separately (_ring_forward.attend): a rank's late half sits at the
# global tail, so judged against the whole resident Q the arriving
# chunks are never fully masked and nothing is skipped.  Split, exactly
# 2 of the 4 (q-half × k-half) matmuls survive per step (3 on the
# diagonal) on EVERY rank — critical path 4n/(2n+1) ≈ 2x better than
# contiguous, where the tail rank always executes all 4
# (:func:`ring_skip_stats` is the committed accounting of exactly the
# decisions _block_attend makes).


def zigzag_permutation(t: int, n: int):
    """Global position -> zigzag storage order for n ring devices.

    Storage order: device i holds chunks i and 2n-1-i of size t/(2n).
    Returns int32 index array ``perm`` with ``stored = x[..., perm]``;
    invert with ``jnp.argsort(perm)``.
    """
    if t % (2 * n):
        raise ValueError(f"zigzag needs seq {t} divisible by 2*{n}")
    half = t // (2 * n)
    order = []
    for i in range(n):
        order.append(jnp.arange(half) + i * half)
        order.append(jnp.arange(half) + (2 * n - 1 - i) * half)
    return jnp.concatenate(order).astype(jnp.int32)


def to_zigzag(x, n: int, axis: int = 1):
    """Reorder a GLOBAL sequence axis into zigzag storage order."""
    return jnp.take(x, zigzag_permutation(x.shape[axis], n), axis=axis)


def from_zigzag(x, n: int, axis: int = 1):
    """Inverse of :func:`to_zigzag`."""
    perm = zigzag_permutation(x.shape[axis], n)
    return jnp.take(x, jnp.argsort(perm), axis=axis)


def _ring_positions(layout: str, rank, tq: int, n: int):
    """Global token positions of the shard stored on ``rank``.

    rank may be a traced scalar (lax.axis_index).  contiguous: one run
    of tq.  zigzag: halves from chunks rank and 2n-1-rank.
    """
    if layout == "zigzag":
        half = tq // 2
        lo = rank * half + jnp.arange(half)
        hi = (2 * n - 1 - rank) * half + jnp.arange(half)
        return jnp.concatenate([lo, hi])
    return rank * tq + jnp.arange(tq)


def ring_skip_stats(t: int, n: int, layout: str = "contiguous",
                    ring_chunk: Optional[int] = None) -> dict:
    """Analytic critical-path accounting of the causal chunk skip.

    Replays every (rank, ring step) of a causal ring pass over a global
    sequence of ``t`` tokens on ``n`` devices, making EXACTLY the skip
    decisions the implementation makes — the same
    :func:`_ring_positions` / :func:`_chunks_of` / :func:`_fully_masked`
    helpers, including the zigzag Q-half split — and charges every
    executed (q rows × k-chunk) matmul its full ``rows × cols`` cost
    (chunks are computed densely; within-chunk masking saves nothing).

    Returns ``{"per_step_max", "critical", "total"}`` in (q row × k col)
    pair units.  ``critical`` = Σ over ring steps of the busiest rank's
    executed cost: ``ppermute`` synchronizes every step, so wall time is
    proportional to this — the zigzag-vs-contiguous ``critical`` ratio
    is the layout's claimed ~2x (→ 4n/(2n+1), asymptotically 2).
    """
    tq = tk = t // n

    def _k_chunks(k_pos):
        """K ranges exactly as the implementation cuts them: zigzag
        splits at the half boundary first (both halves always — see
        _ring_forward.attend), then RING_CHUNK within each piece."""
        pieces = (
            [k_pos[: tk // 2], k_pos[tk // 2:]]
            if layout == "zigzag" else [k_pos]
        )
        out = []
        for piece in pieces:
            size = int(piece.shape[0])
            chunk, nc = _chunks_of(size)
            if ring_chunk is not None:
                chunk = ring_chunk if size % ring_chunk == 0 else size
                nc = size // chunk
            out.extend(
                piece[c * chunk:(c + 1) * chunk] for c in range(nc)
            )
        return out

    per_step_max = []
    total = 0.0
    for s in range(n):
        worst = 0.0
        for r in range(n):
            src = (r - s) % n
            q_pos = _ring_positions(layout, r, tq, n)
            k_pos = _ring_positions(layout, src, tk, n)
            q_blocks = (
                [q_pos[: tq // 2], q_pos[tq // 2:]]
                if layout == "zigzag" else [q_pos]
            )
            cost = 0
            for qp in q_blocks:
                for kp in _k_chunks(k_pos):
                    if not bool(_fully_masked(qp, kp)):
                        cost += int(qp.shape[0]) * int(kp.shape[0])
            worst = max(worst, cost)
            total += cost
        per_step_max.append(float(worst))
    return {
        "per_step_max": per_step_max,
        "critical": float(sum(per_step_max)),
        "total": float(total),
    }


def _block_attend(q, k, v, m, l, o, q_pos=None, k_pos=None):
    """Accumulate attention of resident Q against one ring K/V block,
    streaming the block in RING_CHUNK-sized K chunks (flash-style inner
    loop) so the score intermediate never materializes [Tq, Tk].

    Under causal masking (positions given), chunks whose every key lies
    in the queries' future are SKIPPED via ``lax.cond`` — they would
    contribute only -inf logits.  On the contiguous layout this saves
    energy but not wall time (ring steps synchronize on the busiest
    rank); with the zigzag layout it is the ~2x critical-path win.
    """
    chunk, nc = _chunks_of(k.shape[1])

    def attend_or_skip(ks, vs, kp, carry):
        m, l, o = carry
        if q_pos is None:
            return _chunk_attend(q, ks, vs, m, l, o)
        return lax.cond(
            _fully_masked(q_pos, kp),
            lambda c: c,
            lambda c: _chunk_attend(q, ks, vs, *c, q_pos, kp),
            (m, l, o),
        )

    if nc == 1:
        return attend_or_skip(k, v, k_pos, (m, l, o))

    def body(c, carry):
        k_blk = lax.dynamic_slice_in_dim(k, c * chunk, chunk, axis=1)
        v_blk = lax.dynamic_slice_in_dim(v, c * chunk, chunk, axis=1)
        kp = (
            lax.dynamic_slice_in_dim(k_pos, c * chunk, chunk, axis=0)
            if k_pos is not None
            else None
        )
        return attend_or_skip(k_blk, v_blk, kp, carry)

    return lax.fori_loop(0, nc, body, (m, l, o))


def _block_backward(q_s, do, delta, lse, k_blk, v_blk, scale, axis_name,
                    q_pos=None, k_pos=None):
    """Gradient contributions of one ring K/V block (FA2-style recompute).

    q_s is the pre-scaled query shard; lse/delta are [B, H, Tq] f32 row
    statistics (logsumexp of the scaled logits; rowsum(do*o)).  Returns
    (dq_partial [B,Tq,H,D] f32, dk_blk [B,Tk,H,D] f32, dv_blk same):
    P is recomputed chunk-by-chunk from lse — the O(T^2) matrix never
    exists in HBM, forward or backward.
    """
    tk = k_blk.shape[1]
    chunk, nc = _chunks_of(tk)
    b, tq, h, d = q_s.shape

    def one_chunk(ks, vs, kp):
        s = jnp.einsum(
            "bqhd,bkhd->bhqk", q_s, ks, preferred_element_type=jnp.float32
        )
        if q_pos is not None:
            mask = q_pos[:, None] >= kp[None, :]
            s = jnp.where(mask[None, None], s, NEG_INF)
        p = jnp.exp(s - lse[..., None])  # [B,H,Tq,C]; 0 where masked
        dv_c = jnp.einsum(
            "bhqk,bqhd->bkhd", p.astype(do.dtype), do,
            preferred_element_type=jnp.float32,
        )
        dp = jnp.einsum(
            "bqhd,bkhd->bhqk", do, vs, preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta[..., None])  # d/d(scaled logits)
        dq_c = scale * jnp.einsum(
            "bhqk,bkhd->bqhd", ds.astype(ks.dtype), ks,
            preferred_element_type=jnp.float32,
        )
        dk_c = jnp.einsum(
            "bhqk,bqhd->bkhd", ds.astype(q_s.dtype), q_s,
            preferred_element_type=jnp.float32,
        )
        return dq_c, dk_c, dv_c

    def grads_or_skip(ks, vs, kp):
        """Chunk gradients, skipping fully-masked chunks (see
        _block_attend): P is exactly 0 there, so all three grads are."""
        if q_pos is None:
            return one_chunk(ks, vs, None)
        # Zeros marked varying so both cond branches agree under the
        # shard_map type system (one_chunk outputs vary over the ring).
        zeros = tuple(
            _pvary(jnp.zeros(s, jnp.float32), axis_name)
            for s in ((b, tq, h, d), (b, ks.shape[1], h, d),
                      (b, ks.shape[1], h, d))
        )
        return lax.cond(
            _fully_masked(q_pos, kp),
            lambda: zeros,
            lambda: one_chunk(ks, vs, kp),
        )

    if nc == 1:
        dq, dk, dv = grads_or_skip(k_blk, v_blk, k_pos)
        return dq, dk, dv

    def body(c, carry):
        dq, dk, dv = carry
        ks = lax.dynamic_slice_in_dim(k_blk, c * chunk, chunk, axis=1)
        vs = lax.dynamic_slice_in_dim(v_blk, c * chunk, chunk, axis=1)
        kp = (
            lax.dynamic_slice_in_dim(k_pos, c * chunk, chunk, axis=0)
            if k_pos is not None
            else None
        )
        dq_c, dk_c, dv_c = grads_or_skip(ks, vs, kp)
        dk = lax.dynamic_update_slice_in_dim(dk, dk_c, c * chunk, axis=1)
        dv = lax.dynamic_update_slice_in_dim(dv, dv_c, c * chunk, axis=1)
        return dq + dq_c, dk, dv

    # Fresh zeros inside shard_map are unvaried constants; the fori_loop
    # carry must match the varying outputs, so mark them up front.
    z = _pvary(jnp.zeros((b, tk, h, d), jnp.float32), axis_name)
    dq0 = _pvary(jnp.zeros((b, tq, h, d), jnp.float32), axis_name)
    return lax.fori_loop(0, nc, body, (dq0, z, z))


def _ring_forward(q, k, v, axis_name, causal, scale, layout="contiguous"):
    """Ring forward pass -> (out, lse [B, H, Tq] f32)."""
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    q_s = q * scale

    b, tq, h, d = q.shape
    tk = k.shape[1]
    # Mark the running stats as varying over the ring axis up front: the
    # scan carry must keep one type, and the outputs vary (they depend on
    # this device's Q block and ring position).  Statistics are f32 so the
    # ring matches dense_attention in bf16.
    m0 = _pvary(jnp.full((b, h, tq), NEG_INF, jnp.float32), axis_name)
    l0 = _pvary(jnp.zeros((b, h, tq), jnp.float32), axis_name)
    o0 = _pvary(jnp.zeros((b, tq, h, d), jnp.float32), axis_name)

    q_pos = _ring_positions(layout, idx, tq, n)  # resident Q positions

    perm = [(i, (i + 1) % n) for i in range(n)]

    def attend(m, l, o, k_blk, v_blk, step_idx):
        # The K/V block resident at ring step s arrived from rank idx - s.
        src = (idx - step_idx) % n
        if not causal:
            return _block_attend(q_s, k_blk, v_blk, m, l, o)
        k_pos = _ring_positions(layout, src, tk, n)
        if layout != "zigzag":
            return _block_attend(q_s, k_blk, v_blk, m, l, o, q_pos, k_pos)
        # Zigzag: attend each (Q half × K half) pair separately.  The
        # resident shard is one EARLY and one LATE global half-chunk
        # whose position ranges are disjoint; run whole-block, the late
        # half's huge max position makes _fully_masked almost never
        # fire (the busiest rank holds the global tail and would attend
        # every chunk — no critical-path win at any chunk granularity).
        # Split on BOTH sides, each half-pair skips independently
        # regardless of RING_CHUNK vs shard size: exactly 2 of the 4
        # half-pair matmuls survive per ring step (3 on the diagonal),
        # which IS the ~2x claimed by the layout comment above
        # :func:`zigzag_permutation` (accounting:
        # :func:`ring_skip_stats`).
        half_q, half_k = tq // 2, tk // 2
        outs = []
        for qs, qe in ((0, half_q), (half_q, tq)):
            c = (m[:, :, qs:qe], l[:, :, qs:qe], o[:, qs:qe])
            for ks, ke in ((0, half_k), (half_k, tk)):
                c = _block_attend(
                    q_s[:, qs:qe], k_blk[:, ks:ke], v_blk[:, ks:ke],
                    *c, q_pos[qs:qe], k_pos[ks:ke],
                )
            outs.append(c)
        (m0_, l0_, o0_), (m1_, l1_, o1_) = outs
        return (
            jnp.concatenate([m0_, m1_], axis=2),
            jnp.concatenate([l0_, l1_], axis=2),
            jnp.concatenate([o0_, o1_], axis=1),
        )

    def step(carry, step_idx):
        m, l, o, k_blk, v_blk = carry
        m, l, o = attend(m, l, o, k_blk, v_blk, step_idx)
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        return (m, l, o, k_blk, v_blk), None

    # n-1 rotations: the scan attends+rotates for steps 0..n-2; the last
    # arriving block is attended outside so its K/V are never forwarded
    # (a final ppermute would be dead ICI traffic).
    (m, l, o, k_last, v_last), _ = lax.scan(
        step, (m0, l0, o0, k, v), jnp.arange(n - 1)
    )
    m, l, o = attend(m, l, o, k_last, v_last, n - 1)
    out = o * (1.0 / l).transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype), m + jnp.log(l)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _ring_attention(q, k, v, axis_name, causal, scale,
                    layout="contiguous"):
    out, _ = _ring_forward(q, k, v, axis_name, causal, scale, layout)
    return out


def _ring_attention_fwd(q, k, v, axis_name, causal, scale,
                        layout="contiguous"):
    out, lse = _ring_forward(q, k, v, axis_name, causal, scale, layout)
    return out, (q, k, v, out, lse)


def _ring_attention_bwd(axis_name, causal, scale, layout, res, do):
    """Ring backward: a second ring pass with FA2-style recompute.

    Plain AD through the forward scan would save every chunk's [Tq, C]
    probabilities as residuals — re-materializing O(Tq*Tk) per device and
    defeating the long-context point (ADVICE.md round 1) — so the
    backward instead recomputes P from the saved logsumexp while
    (k, v, dk, dv) rotate together around the ring: n compute+rotate
    cycles return each dk/dv block to its home rank fully accumulated.
    dq accumulates locally.  Twice the forward's ICI traffic (the dk/dv
    blocks ride along, in f32 so late large contributions still land).
    """
    q, k, v, o, lse = res
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    b, tq, h, d = q.shape
    tk = k.shape[1]
    q_s = q * scale
    delta = jnp.einsum(
        "bqhd,bqhd->bhq", do.astype(jnp.float32), o.astype(jnp.float32)
    )
    q_pos = _ring_positions(layout, idx, tq, n)
    perm = [(i, (i + 1) % n) for i in range(n)]

    dq0 = _pvary(jnp.zeros((b, tq, h, d), jnp.float32), axis_name)
    dk0 = _pvary(jnp.zeros((b, tk, h, d), jnp.float32), axis_name)
    dv0 = _pvary(jnp.zeros((b, tk, h, d), jnp.float32), axis_name)

    def step(carry, step_idx):
        dq, k_blk, v_blk, dk_blk, dv_blk = carry
        src = (idx - step_idx) % n
        if causal and layout == "zigzag":
            # Per-(Q half × K half) backward, mirroring the forward's
            # split (see _ring_forward.attend): each half-pair's fully-
            # masked chunks contribute exact zeros and are skipped.
            k_pos = _ring_positions(layout, src, tk, n)
            half_q, half_k = tq // 2, tk // 2
            dq_parts = []
            dk_halves = [0.0, 0.0]
            dv_halves = [0.0, 0.0]
            for qs, qe in ((0, half_q), (half_q, tq)):
                dq_h = 0.0
                for ki, (ks, ke) in enumerate(
                    ((0, half_k), (half_k, tk))
                ):
                    dq_p, dk_p, dv_p = _block_backward(
                        q_s[:, qs:qe], do[:, qs:qe], delta[:, :, qs:qe],
                        lse[:, :, qs:qe], k_blk[:, ks:ke],
                        v_blk[:, ks:ke], scale, axis_name,
                        q_pos[qs:qe], k_pos[ks:ke],
                    )
                    dq_h = dq_h + dq_p
                    dk_halves[ki] = dk_halves[ki] + dk_p
                    dv_halves[ki] = dv_halves[ki] + dv_p
                dq_parts.append(dq_h)
            dq_c = jnp.concatenate(dq_parts, axis=1)
            dk_c = jnp.concatenate(dk_halves, axis=1)
            dv_c = jnp.concatenate(dv_halves, axis=1)
        elif causal:
            k_pos = _ring_positions(layout, src, tk, n)
            dq_c, dk_c, dv_c = _block_backward(
                q_s, do, delta, lse, k_blk, v_blk, scale, axis_name,
                q_pos, k_pos,
            )
        else:
            dq_c, dk_c, dv_c = _block_backward(
                q_s, do, delta, lse, k_blk, v_blk, scale, axis_name
            )
        dq = dq + dq_c
        dk_blk = dk_blk + dk_c
        dv_blk = dv_blk + dv_c
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        dk_blk = lax.ppermute(dk_blk, axis_name, perm)
        dv_blk = lax.ppermute(dv_blk, axis_name, perm)
        return (dq, k_blk, v_blk, dk_blk, dv_blk), None

    (dq, _, _, dk, dv), _ = lax.scan(
        step, (dq0, k, v, dk0, dv0), jnp.arange(n)
    )
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_ring_attention.defvjp(_ring_attention_fwd, _ring_attention_bwd)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    causal: bool = False,
    scale: Optional[float] = None,
    layout: str = "contiguous",
) -> jax.Array:
    """Ring self-attention over a sequence-sharded axis.

    Call inside ``shard_map``; q/k/v are the per-device sequence shards
    ``[batch, seq/n, heads, head_dim]``.  K/V rotate n-1 times via
    ``ppermute`` to the next ring neighbor; a ``lax.scan`` over ring
    steps keeps the jitted program free of Python-level unrolling.
    Differentiable with O(seq/n) memory in BOTH directions via a custom
    VJP (see :func:`_ring_attention_bwd`).

    ``layout="zigzag"``: shards are in zigzag storage order (reorder the
    GLOBAL sequence with :func:`to_zigzag` before sharding) — balances
    causal work across ranks so the fully-masked-chunk skip becomes a
    ~2x critical-path win (see the layout comment above
    :func:`zigzag_permutation`).  Requires an even per-device shard.
    """
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    if layout not in ("contiguous", "zigzag"):
        raise ValueError(f"unknown ring layout {layout!r}")
    if layout == "zigzag" and q.shape[1] % 2:
        raise ValueError(
            f"zigzag needs an even per-device shard, got {q.shape[1]}"
        )
    return _ring_attention(q, k, v, axis_name, causal, scale, layout)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    causal: bool = False,
    scale: Optional[float] = None,
) -> jax.Array:
    """All-to-all (DeepSpeed-Ulysses style) sequence parallelism.

    Inside ``shard_map`` with q/k/v ``[batch, seq/n, heads, head_dim]``:
    an all-to-all converts the sequence shard into a head shard
    ``[batch, seq, heads/n, head_dim]``, each device attends densely over
    the full sequence for its heads, and a reverse all-to-all restores
    the sequence shard.
    """
    n = lax.axis_size(axis_name)
    scale = scale if scale is not None else q.shape[-1] ** -0.5

    def seq_to_heads(x):
        # [B, T/n, H, D] -> [B, T, H/n, D]
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    def heads_to_seq(x):
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    if q.shape[2] % n:
        raise ValueError(
            f"ulysses needs num_heads ({q.shape[2]}) divisible by the "
            f"sequence-parallel degree ({n})"
        )
    qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    oh = dense_attention(qh, kh, vh, causal=causal, scale=scale)
    return heads_to_seq(oh)


def make_sequence_parallel_attention(
    mesh: Mesh,
    kind: str = "ring",
    causal: bool = False,
    axis_name: str = "data",
    layout: str = "contiguous",
):
    """Jit a sequence-parallel attention over ``mesh``.

    Returns ``fn(q, k, v) -> out`` taking GLOBAL ``[B, T, H, D]`` arrays
    sharded (or shardable) on ``axis_name`` along T; the wrapper applies
    ``shard_map`` + jit with the sequence axis sharded and batch/heads
    replicated across that axis.  ``layout`` (ring only): see
    :func:`ring_attention` — callers reorder the global sequence with
    :func:`to_zigzag` / :func:`from_zigzag`.
    """
    kinds = {"ring": ring_attention, "ulysses": ulysses_attention}
    if kind not in kinds:
        raise ValueError(
            f"kind must be one of {'|'.join(sorted(kinds))}, got {kind!r}"
        )
    inner = kinds[kind]
    extra = {"layout": layout} if kind == "ring" else {}
    spec = P(None, axis_name, None, None)

    @functools.partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    def sharded(q, k, v):
        return inner(q, k, v, axis_name=axis_name, causal=causal, **extra)

    sharding = NamedSharding(mesh, spec)
    return jax.jit(
        sharded,
        in_shardings=(sharding, sharding, sharding),
        out_shardings=sharding,
    )
