"""Pipelined DCN transfers: chunked phase overlap + striped streams.

The serial ``exchange_shard`` hot path pays the SUM of its phases:
stage the whole payload, wait, send the whole payload, wait, read —
even though stage/send/land are independent per chunk.  This module is
the client half of the pipelined mode (the daemon half lives in
``fleet/xferd.py``): payloads above a threshold are split into chunks
and striped across N concurrent data-plane/control connections, so
chunk *k+1* is being staged into the local daemon while chunk *k* is
in flight to the peer — the FlexLink striping + T3 phase-overlap
result (PAPERS.md) applied to the daemon protocol this stack already
has.

Anatomy of one pipelined transfer (``send_pipelined``):

- the payload is cut on a fixed chunk grid (``TPU_DCN_CHUNK_BYTES``);
- a dedicated STAGER thread streams chunks into the LOCAL daemon over
  one persistent data-plane socket (v2 frames with
  ``off``/``tot``/``xid`` meta and seq 0 — dedup-exempt staging),
  while N STRIPE senders, each owning its own control connection,
  issue offset-``send`` ops — the daemon parks each op until its chunk
  finishes landing locally, so chunk *k+1* is staging while chunk *k*
  streams to the peer, and each stripe's sends ride a distinct
  persistent daemon→peer TCP stream;
- every chunk carries its own client-assigned per-flow seq, so the
  receiver's dedup window gives exactly-once PER CHUNK: a retransmit
  round re-sends under the SAME seqs and only genuinely-lost chunks
  land;
- retry rounds: chunks whose send failed transport-level, or whose
  fleet-link verdict came back ``dropped``, are re-staged and re-sent
  (the primary resilient client heals the control plane between
  rounds); chunks that landed dedup away.

The defaults (1 MiB chunks, 2 stripes) are tuned for the loopback
rig, where per-chunk thread handoffs cost more than bandwidth and
wide fan-out loses to scheduling; on real cross-slice NICs smaller
chunks and more stripes is the FlexLink +27% — that is exactly what
the env knobs are for.  With ``TPU_DCN_TUNE`` on, the static grid is
only the BASE: a per-destination closed-loop controller
(``parallel/dcn_tune.py``) adapts chunk size and stripe count from
the transfer's own telemetry — chunk moves latch at transfer
boundaries (the seq/dedup contract pins the grid mid-transfer),
stripe moves also apply between retry rounds.

``read_pipelined`` is the stripe reader: it waits for the peer's frame
to finish assembling (the daemon's blocking ``wait`` op), then fetches
contiguous slabs in parallel over raw data-plane ``DXR1`` requests —
no base64, no 512 KiB control-socket chunking.

On top of both sits the **memcpy-speed same-host plane** (ISSUE 6 +
ISSUE 13): when the daemon advertises ``shm`` in its handshake AND
its ``host_id`` matches this process's boot identity, staging becomes
memoryview writes into the flow's mmap segment plus one
``shm_commit`` control op, and read-back becomes ``shm_read`` + a
client-side mapping instead of DXR1 socket copies.  Per-chunk control
ops collapse too: the client posts the round's (off, len, seq)
descriptors into the flow's ring file and fires ONE ``shm_post``
doorbell — deliberately BEFORE the staging memcpy, so the daemon's
completer (parked on the descriptors' stage-waits) finishes the round
behind the memcpy and the lane's exposed-comm ratio drops instead of
sitting serial-shaped — then polls the completion cursor lock-free
out of its own mapping.  The daemon→peer leg takes the daemon↔daemon
segment lane on its own handshake when the PEER is co-hosted too
(fleet/xferd.py), and every control decision (seq assignment, dedup,
``wait``, fabric verdicts) is untouched, so exactly-once semantics
are identical on every lane.  Lane selection happens PER RETRY ROUND:
a daemon that restarts without the capability mid-transfer downgrades
the remaining rounds to the socket lane (``dcn.shm.fallback``) under
the same chunk seqs — cross-host peers and capability-less daemons
simply never leave it; ring trouble falls back to per-chunk control
ops (``dcn.shm.ring.fallback``) without leaving the shm lane.

All of it falls back loudly (``DcnXferError``) rather than silently:
the callers (``dcn.exchange_shard``, the fleet ring workload) own the
serial fallback and the leg-level retry.
"""

import json
import logging
import os
import socket
import struct
import threading
import time
import uuid
from typing import Dict, List, Optional, Tuple

from container_engine_accelerators_tpu.metrics import counters
from container_engine_accelerators_tpu.obs import critpath, histo, timeseries, trace
from container_engine_accelerators_tpu.parallel import dcn_shm, dcn_tune
from container_engine_accelerators_tpu.parallel.dcn_client import (
    DcnWaitUnsupported,
    DcnXferClient,
    DcnXferError,
)
from container_engine_accelerators_tpu.utils import netio

log = logging.getLogger(__name__)

CHUNK_BYTES_ENV = "TPU_DCN_CHUNK_BYTES"
STRIPES_ENV = "TPU_DCN_STRIPES"
PIPELINE_ENV = "TPU_DCN_PIPELINE"
SHM_ENV = dcn_shm.SHM_ENV

DEFAULT_CHUNK_BYTES = 1 << 20
DEFAULT_STRIPES = 2
DEFAULT_MAX_ROUNDS = 3

# Hard cap on chunks per transfer: a retransmit must be able to re-send
# chunk 1 after every other chunk landed, so the whole transfer's seq
# span has to fit inside the receiver's dedup window with headroom
# (fleet/xferd.py DEDUP_WINDOW = 256; the cross-test in
# tests/test_dcn_pipeline.py pins 2 * MAX_CHUNKS <= DEDUP_WINDOW).
# Oversized payloads get their chunk size raised, not their tail cut.
MAX_CHUNKS_PER_TRANSFER = 128

# Wire constants — deliberately duplicated from fleet/xferd.py, the
# same way DcnXferClient.put duplicates the DXF1 header: the client
# must be importable without the fleet package, and the cross-test in
# tests/test_dcn_pipeline.py pins both sides to the same bytes.
_MAGIC_V2 = b"DXF2"
_MAGIC_READ = b"DXR1"


class PipelineConfig:
    """Chunk/stripe knobs, resolved env-first (the Job manifest
    contract, like DCN_UDS_DIR)."""

    def __init__(self, chunk_bytes: Optional[int] = None,
                 stripes: Optional[int] = None,
                 max_rounds: int = DEFAULT_MAX_ROUNDS,
                 env=None, shm: Optional[bool] = None,
                 tuned: Optional[bool] = None,
                 shm_direct: Optional[bool] = None,
                 ring: Optional[bool] = None):
        env = env if env is not None else os.environ
        if chunk_bytes is None:
            chunk_bytes = int(env.get(CHUNK_BYTES_ENV,
                                      DEFAULT_CHUNK_BYTES))
        if stripes is None:
            stripes = int(env.get(STRIPES_ENV, DEFAULT_STRIPES))
        self.chunk_bytes = max(1, int(chunk_bytes))
        self.stripes = max(1, int(stripes))
        self.max_rounds = max(1, int(max_rounds))
        self.enabled = env.get(PIPELINE_ENV, "1") not in ("0", "false",
                                                          "off")
        # Zero-copy same-host lane kill switch (TPU_DCN_SHM): ``shm``
        # here means "MAY take the lane" — the daemon capability and
        # the host-identity match still gate each transfer.
        self.shm = (dcn_shm.shm_enabled(env) if shm is None
                    else bool(shm))
        # Daemon↔daemon segment lane pin (TPU_DCN_SHM_DIRECT): False
        # stamps ``direct: 0`` on every send op, pinning the daemon's
        # peer leg to TCP — how the bench keeps its socket series
        # honest and the parity scenarios choose their lane.  True
        # leaves the daemon's own probe-and-fallback in charge.
        self.shm_direct = (dcn_shm.shm_direct_enabled(env)
                           if shm_direct is None else bool(shm_direct))
        # Descriptor-ring handoff pin (TPU_DCN_SHM_RING): False keeps
        # shm rounds on per-chunk control ops — the legacy-shape
        # chaos tests' handle, and the escape hatch if a ring
        # regression ever ships.
        self.ring = (dcn_shm.shm_ring_enabled(env)
                     if ring is None else bool(ring))
        # Closed-loop grid control (parallel/dcn_tune.py): the
        # configured chunk/stripe grid becomes the controller's BASE,
        # adapted per destination from its own telemetry.  ON by
        # default (the soak world gates the loop); TPU_DCN_TUNE=0 is
        # the kill switch pinning the static grid byte-for-byte.
        self.tuned = (dcn_tune.tune_enabled(env) if tuned is None
                      else bool(tuned))

    def __repr__(self):
        return (f"PipelineConfig(chunk_bytes={self.chunk_bytes}, "
                f"stripes={self.stripes}, shm={self.shm}, "
                f"shm_direct={self.shm_direct}, "
                f"tuned={self.tuned})")


def plan_chunks(nbytes: int, chunk_bytes: int) -> List[Tuple[int, int]]:
    """The fixed chunk grid for one payload: (offset, length) pairs
    covering [0, nbytes) exactly, every chunk ``chunk_bytes`` long
    except a shorter tail."""
    return [(off, min(chunk_bytes, nbytes - off))
            for off in range(0, nbytes, chunk_bytes)]


def should_pipeline(client, nbytes: int,
                    cfg: Optional[PipelineConfig] = None) -> bool:
    """Pipeline iff it can help AND the daemon speaks the protocol:
    more than one chunk's worth of payload, a v2-frame daemon with the
    pipeline extensions (PyXferd; the native daemon is DXF1-only until
    its DXF2 port lands — ROADMAP), and no env kill switch."""
    cfg = cfg or PipelineConfig()
    if not cfg.enabled or nbytes <= cfg.chunk_bytes:
        return False
    try:
        return (client.frame_version() >= 2
                and client.supports_pipeline())
    except (DcnXferError, OSError, AttributeError):
        return False


def shm_same_host(client) -> bool:
    """The daemon offers the shm lane AND lives on this machine.
    Identity is the handshake's ``host_id`` (boot id + hostname)
    compared to ours — never the socket address: a forwarded UDS or a
    shared loopback across a netns boundary is "same address" without
    being "same filesystem"."""
    try:
        caps = client.capabilities()
    except (DcnXferError, OSError, AttributeError):
        return False
    return (bool(caps.get("shm"))
            and caps.get("host_id") == dcn_shm.host_identity())


def ring_same_host(client) -> bool:
    """The daemon offers the UNIVERSAL submission ring AND lives on
    this machine — the socket lane's descriptor-handoff gate.  The
    ring file is mmapped (descriptors and cursors, not payload), so
    the same host-identity rule as the shm lane applies: never the
    socket address."""
    try:
        caps = client.capabilities()
    except (DcnXferError, OSError, AttributeError):
        return False
    return (bool(caps.get("ring"))
            and caps.get("host_id") == dcn_shm.host_identity())


def _chunk_frame_header(flow: str, payload_len: int,
                        meta: dict) -> bytes:
    """v2 frame header for a seq-0 staging chunk (the payload follows
    separately so large chunks need no concat copy)."""
    name = flow.encode()
    meta_b = json.dumps(meta).encode()
    return (_MAGIC_V2 + struct.pack("<I", len(name))
            + struct.pack("<Q", payload_len) + struct.pack("<Q", 0)
            + struct.pack("<I", len(meta_b)) + name + meta_b)


def _read_request(flow: str, offset: int, nbytes: int) -> bytes:
    """One DXR1 request — same deliberate duplication as
    `_chunk_frame_header` (pinned against fleet/xferd's
    ``encode_read_request`` in tests/test_dcn_pipeline.py)."""
    name = flow.encode()
    return (_MAGIC_READ + struct.pack("<I", len(name))
            + struct.pack("<Q", offset) + struct.pack("<Q", nbytes)
            + name)


def fetch_range(host: str, port: int, flow: str, offset: int,
                nbytes: int, sock: Optional[socket.socket] = None,
                timeout_s: float = 30.0) -> bytes:
    """One DXR1 binary read-back: staged bytes [offset, offset+nbytes)
    of ``flow`` from the daemon's data port, raw over TCP.  Returns
    short (possibly empty) when the flow has no completed frame there.
    """
    req = _read_request(flow, offset, nbytes)
    own = sock is None
    if own:
        sock = socket.create_connection((host, port), timeout=timeout_s)
        _set_nodelay(sock)
    try:
        netio.sendall(sock, req)
        hdr = _recv_exact(sock, 8)
        avail = struct.unpack("<Q", hdr)[0]
        return _recv_exact(sock, avail)
    finally:
        if own:
            sock.close()


def _set_nodelay(sock: socket.socket) -> None:
    """Header+payload write pairs lose milliseconds per chunk to
    Nagle/delayed-ACK coupling; the pipeline's win lives there."""
    try:
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    except OSError:
        pass


# Exact reads and capped short-write-proof sends live in utils/netio
# (this rig's stack truncates very large single-syscall payloads).
_recv_exact = netio.recv_exact


class _StripeResult:
    """Shared per-transfer scoreboard: chunk index -> verdict, plus
    the monotonic phase intervals the exposed-communication accounting
    needs (``stage`` = local staging, ``comm`` = daemon round trips
    that move/settle bytes toward the peer)."""

    def __init__(self):
        self.verdicts: Dict[int, str] = {}
        self.errors: List[BaseException] = []
        self.phases: Dict[str, List[Tuple[float, float]]] = {}
        self._lock = threading.Lock()

    def record(self, idx: int, verdict: str) -> None:
        with self._lock:
            self.verdicts[idx] = verdict

    def fail(self, exc: BaseException) -> None:
        with self._lock:
            self.errors.append(exc)

    def phase(self, kind: str, t0: float, t1: float) -> None:
        with self._lock:
            self.phases.setdefault(kind, []).append((t0, t1))


def _stage_worker(data_host: str, data_port: int, flow: str, data,
                  chunks, idxs, xid: str, total: int,
                  timeout_s: float, result: _StripeResult,
                  ctx: Optional[dict]) -> None:
    """The stager: stream chunks into the LOCAL daemon over one
    persistent data socket, as fast as the kernel takes them.  The
    stripe senders' offset-sends park daemon-side until each chunk has
    landed, so staging chunk *k+1* genuinely overlaps sending chunk
    *k* — the phase-overlap half of the pipeline."""
    view = memoryview(data)
    dsock = None
    try:
        with trace.attach(ctx.get("trace") if ctx else None,
                          ctx.get("span") if ctx else None):
            dsock = socket.create_connection((data_host, data_port),
                                             timeout=timeout_s)
            _set_nodelay(dsock)
            for idx in idxs:
                off, ln = chunks[idx]
                t0 = time.monotonic()
                try:
                    with trace.span("dcn.chunk.stage",
                                    histogram="dcn.chunk.stage",
                                    flow=flow, off=off, bytes=ln):
                        netio.sendall_parts(dsock, (
                            _chunk_frame_header(flow, ln, {
                                "off": off, "tot": total, "xid": xid,
                            }),
                            view[off:off + ln],
                        ))
                finally:
                    result.phase("stage", t0, time.monotonic())
    except (DcnXferError, OSError) as e:
        result.fail(e)
    finally:
        if dsock is not None:
            try:
                dsock.close()
            except OSError:
                pass


def _send_chunk(ctl, flow: str, chunks, seqs, idx: int, xid: str,
                host: str, port: int, total: int, timeout_s: float,
                result: _StripeResult,
                lane: Optional[str] = None,
                direct: Optional[int] = None) -> None:
    """Issue one offset-send and score its verdict — shared by the
    stripe workers and the shm round, so the settled-verdict set and
    the confirmed-chunk accounting can never diverge between lanes.
    ``direct=0`` pins the daemon's peer leg to TCP (the socket
    series' honesty guarantee); None leaves the daemon's own
    shm_direct probe in charge.  Raises on control-connection
    trouble; the caller owns what the unrecorded chunks mean then."""
    off, ln = chunks[idx]
    span_attrs = {"lane": lane} if lane else {}
    req = dict(
        op="send", flow=flow, host=host, port=str(port),
        seq=seqs[idx], offset=off, bytes=ln, total=total, xid=xid,
        stage_wait_ms=int(min(timeout_s, 5.0) * 1e3),
    )
    if direct is not None:
        req["direct"] = direct
    timeseries.gauge_add("dcn.chunks.inflight", 1)
    t0 = time.monotonic()
    try:
        with trace.span("dcn.chunk.send", histogram="dcn.chunk.send",
                        flow=flow, off=off, bytes=ln, seq=seqs[idx],
                        **span_attrs):
            resp = ctl._call(**req)
    finally:
        timeseries.gauge_add("dcn.chunks.inflight", -1)
        result.phase("comm", t0, time.monotonic())
    verdict = resp.get("verdict", "sent")
    if verdict in ("sent", "landed", "dup"):
        # Count CONFIRMED chunks only (the README table's contract);
        # dropped/unmatched retransmit attempts show up in
        # dcn.pipeline.retry_rounds instead.
        counters.inc("dcn.pipeline.chunks")
        timeseries.record("dcn.pipeline.tx.bytes", ln)
    result.record(idx, verdict)


def _send_worker(uds_dir: str, flow: str, chunks, seqs, idxs,
                 xid: str, host: str, port: int, total: int,
                 timeout_s: float, result: _StripeResult,
                 ctx: Optional[dict],
                 direct: Optional[int] = None) -> None:
    """One stripe sender: its own control connection, issuing
    offset-sends for its share of the chunk grid.  Each stripe's
    chunks ride a distinct persistent daemon→peer stream (the daemon
    keys outbound connections by control connection), which is the
    striping half of the pipeline."""
    ctl = None
    timeseries.gauge_add("dcn.stripes.active", 1)
    try:
        with trace.attach(ctx.get("trace") if ctx else None,
                          ctx.get("span") if ctx else None):
            ctl = DcnXferClient(uds_dir, timeout_s=max(timeout_s, 10.0))
            for idx in idxs:
                _send_chunk(ctl, flow, chunks, seqs, idx, xid, host,
                            port, total, timeout_s, result,
                            direct=direct)
    except (DcnXferError, OSError) as e:
        # The scoreboard decides what to retry; this stripe's remaining
        # chunks simply stay unrecorded.
        result.fail(e)
    finally:
        timeseries.gauge_add("dcn.stripes.active", -1)
        if ctl is not None:
            try:
                ctl.close()
            except OSError:
                pass


def _shm_stage(ctl, flow: str, data, chunks, attach_resp: dict,
               xid: str, result: _StripeResult) -> None:
    """Memcpy the payload into the flow's segment and declare it
    staged with ONE in-place ``shm_commit``.  Raises on any shortfall
    (segment unmappable, commit refused) — the caller owns what that
    means for the round."""
    nbytes = len(data)
    seg = None
    t0 = time.monotonic()
    try:
        with trace.span("dcn.shm.stage", histogram="dcn.shm.stage",
                        flow=flow, bytes=nbytes, xid=xid):
            seg = dcn_shm.map_segment(
                attach_resp.get("path", ""),
                int(attach_resp.get("bytes") or 0))
            if seg.size < nbytes:
                raise OSError("segment smaller than payload")
            src = memoryview(data)
            for off, ln in chunks:
                seg.view[off:off + ln] = src[off:off + ln]
            ctl.shm_commit(flow, nbytes, xid)
    finally:
        if seg is not None:
            seg.close()
        result.phase("stage", t0, time.monotonic())
    timeseries.record("dcn.shm.tx.bytes", nbytes)
    timeseries.record("dcn.lane.shm.bytes", nbytes)
    timeseries.gauge_add("dcn.lane.shm.total_bytes", nbytes)


# Completion-poll backoff: the cursor lives in shared memory, so each
# read is effectively free — but on an in-process rig (the bench, the
# unit suites) the daemon needs the GIL to make progress, so the poll
# yields from the very first iteration (sleep(0) = GIL release) and
# backs off to 50 µs / 500 µs — still far below one control round
# trip per chunk, which is the whole point of the handoff.
_RING_SPIN_FAST = 50
_RING_SPIN_SLOW = 400


def _score_ring_slots(batch, chunks, statuses, scored,
                      result: _StripeResult) -> None:
    """Score the first ``scored`` slots of one posted batch.  The
    ring's publication order (slot status BEFORE cursor advance)
    makes every slot below the cursor valid even when the round
    timed out mid-completion — partial credit, so a SIGKILLed
    completer costs only the genuinely unconfirmed chunks."""
    for slot in range(scored):
        idx = batch[slot]
        verdict = dcn_shm.RING_VERDICTS.get(statuses[slot], "error")
        if verdict in ("sent", "landed", "dup"):
            # Same confirmed-chunk accounting as _send_chunk — the
            # two handoff shapes must never diverge in the books.
            counters.inc("dcn.pipeline.chunks")
            timeseries.record("dcn.pipeline.tx.bytes", chunks[idx][1])
        result.record(idx, verdict)


def _ring_round(ctl, ring, flow: str, data, chunks, seqs, idxs,
                xid: str, host: str, port: int, timeout_s: float,
                result: _StripeResult, attach_resp: dict,
                staged_already: bool, direct_pin: Optional[int],
                stage=None) -> Optional[bool]:
    """One descriptor-ring round: post (off, len, seq) descriptors
    into the flow's ring, fire ONE ``shm_post`` doorbell, stage the
    payload while the daemon's completer parks on the descriptors'
    stage-waits, then poll the completion cursor lock-free out of the
    client's own mapping and score the per-slot verdicts.

    The doorbell deliberately precedes the staging memcpy: the
    daemon-side completion window then COVERS the staging interval,
    so the exposed-communication accounting shows the handoff hiding
    control time behind the memcpy — the GPU-initiated-networking
    shape (post work once, let the data plane complete it).

    ``stage`` overrides the whole-payload memcpy+commit with a
    caller-supplied per-batch callback ``stage(ctl, attach_resp,
    batch_idxs)`` — the producer-fed overlap path stages each chunk
    as it is produced, AFTER the doorbell, so production itself
    hides the DCN leg.

    Rounds larger than the ring post in ring-sized batches: the
    poster BLOCKS until the previous batch's cursor caught up
    (``dcn.ring.backpressure`` per extra doorbell) — descriptors are
    never dropped.

    Returns True (round ran; scoreboard holds the verdicts — possibly
    with chunks left pending for the next round), False (the shm
    staging itself broke: caller downgrades to the socket lane), or
    None (the ring handoff is unusable while shm staging may still
    be fine: caller falls back to per-chunk control ops)."""
    nbytes = len(data)
    try:
        slots = ring.slots
    except (OSError, struct.error):
        return None
    deadline = time.monotonic() + timeout_s
    staged = staged_already
    for bstart in range(0, len(idxs), slots):
        batch = idxs[bstart:bstart + slots]
        n = len(batch)
        if bstart:
            # Only reachable after the previous batch's completion
            # poll drained — the blocked-poster half of the
            # backpressure contract.
            counters.inc("dcn.ring.backpressure")
        try:
            rnd = ring.post([(chunks[i][0], chunks[i][1], seqs[i])
                             for i in batch])
        except (OSError, ValueError, struct.error):
            return None if not bstart else True
        t0 = time.monotonic()
        timeseries.gauge_add("dcn.chunks.inflight", n)
        timed_out = False
        scored = 0
        try:
            # ONE span from doorbell to completion: this is the ring
            # lane's whole DCN leg as the client sees it, so injected
            # link latency (and any daemon-side stall) attributes HERE
            # in a critical-path walk — the `dcn.chunk.send` analog.
            # The staging memcpy nests under it as a child, which is
            # exactly the overlap story the exposed-comm accounting
            # tells.
            with trace.span("dcn.shm.post", histogram="dcn.shm.post",
                            flow=flow, chunks=n, xid=xid):
                try:
                    ctl.shm_post(flow, n, rnd, xid, nbytes, host,
                                 port, direct=direct_pin,
                                 stage_wait_ms=int(min(timeout_s, 5.0)
                                                   * 1e3))
                except (DcnXferError, OSError) as e:
                    result.fail(e)
                    return None if not bstart else True
                if not staged or stage is not None:
                    try:
                        if stage is not None:
                            stage(ctl, attach_resp, batch)
                        else:
                            _shm_stage(ctl, flow, data, chunks,
                                       attach_resp, xid, result)
                            staged = True
                    except (DcnXferError, OSError) as e:
                        # The posted descriptors' stage-waits expire
                        # on the daemon side; nothing lands under
                        # their seqs.
                        result.fail(e)
                        return False
                spins = 0
                while True:
                    try:
                        crnd, done = ring.completion()
                    except (ValueError, struct.error):
                        return None if not bstart else True
                    cur = done if crnd == rnd else 0
                    if cur >= n:
                        scored = n
                        break
                    if time.monotonic() >= deadline:
                        timed_out = True
                        scored = cur
                        break
                    spins += 1
                    if spins > _RING_SPIN_SLOW:
                        time.sleep(0.0005)
                    elif spins > _RING_SPIN_FAST:
                        time.sleep(0.00005)
                    else:
                        time.sleep(0)  # GIL yield: daemon may BE us
                try:
                    statuses = ring.statuses(n)
                except (ValueError, struct.error):
                    return None if not bstart else True
        finally:
            timeseries.gauge_add("dcn.chunks.inflight", -n)
            result.phase("comm", t0, time.monotonic())
        _score_ring_slots(batch, chunks, statuses, scored, result)
        if timed_out:
            # Unfinished handoff: unscored chunks stay pending; the
            # next retry round re-sends them under the SAME seqs
            # (the completer's late sends dedup away).
            result.fail(DcnXferError(
                f"ring round for {flow!r} timed out at "
                f"{scored}/{n}"))
            return True
    return True


def _shm_round(uds_dir: str, flow: str, data, chunks, seqs, idxs,
               xid: str, host: str, port: int, timeout_s: float,
               result: _StripeResult, ctx: Optional[dict],
               already_staged: bool = False,
               direct_pin: Optional[int] = None,
               use_ring: bool = True,
               stage=None, prepare=None) -> bool:
    """One zero-copy-lane round: descriptor-ring handoff when the
    daemon offers it (one doorbell per round, completion polled out
    of shared memory), per-chunk offset-sends on a dedicated
    fail-fast control connection otherwise — either way no stager
    thread and no stripe fan-out: staging is a memcpy, and this rig's
    thread handoffs cost more than they buy.

    ``already_staged`` means an earlier round of THIS transfer staged
    and committed the whole frame; when the daemon still holds it
    (``shm_attach`` reports the full ``frame_bytes`` — a restart would
    have reset that to 0 through flow replay), the memcpy and the
    re-commit are skipped and the round pays only for the chunks it
    re-sends.

    ``stage``/``prepare`` are the producer-overlap hooks: ``stage``
    replaces the whole-payload memcpy inside a ring round with a
    per-batch producer-fed callback; ``prepare`` (materialize the
    producer fully) runs before any NON-ring staging, whose
    whole-payload memcpy needs every byte present.

    Returns False when the shm machinery itself is unusable (attach
    rejected, segment unmappable, daemon gone) — the caller's signal
    to run the socket lane instead.  Send failures after a successful
    stage return True with the chunks left pending: the normal retry
    round re-sends them under the SAME seqs, on whichever lane is
    alive then."""
    nbytes = len(data)
    ctl = None
    ring_seg = None
    try:
        with trace.attach(ctx.get("trace") if ctx else None,
                          ctx.get("span") if ctx else None):
            try:
                ctl = DcnXferClient(uds_dir,
                                    timeout_s=max(timeout_s, 10.0))
                resp = ctl.shm_attach(flow, nbytes, ring=use_ring)
            except (DcnXferError, OSError) as e:
                result.fail(e)
                return False
            staged_already = (already_staged
                              and int(resp.get("frame_bytes") or 0)
                              >= nbytes)
            ring = None
            if use_ring and resp.get("ring_path"):
                try:
                    ring_seg = dcn_shm.map_segment(
                        resp["ring_path"],
                        dcn_shm.ring_bytes(
                            int(resp.get("ring_slots") or 0)))
                    ring = dcn_shm.RingView(ring_seg.view)
                except OSError:
                    ring = None
            if ring is not None:
                ran = _ring_round(ctl, ring, flow, data, chunks,
                                  seqs, idxs, xid, host, port,
                                  timeout_s, result, resp,
                                  staged_already, direct_pin,
                                  stage=stage)
                if ran is not None:
                    return ran
                counters.inc("dcn.shm.ring.fallback")
            # Per-chunk handoff (ring-less daemons, broken rings):
            # stage first, then serial offset-sends.
            if not staged_already:
                try:
                    if prepare is not None:
                        prepare()
                    _shm_stage(ctl, flow, data, chunks, resp, xid,
                               result)
                except (DcnXferError, OSError) as e:
                    result.fail(e)
                    return False
            for idx in idxs:
                try:
                    _send_chunk(ctl, flow, chunks, seqs, idx, xid,
                                host, port, nbytes, timeout_s, result,
                                lane="shm", direct=direct_pin)
                except (DcnXferError, OSError) as e:
                    # Staged fine; these chunks simply stay pending
                    # for the next round (same seqs, any lane).
                    result.fail(e)
                    return True
            return True
    finally:
        if ring_seg is not None:
            ring_seg.close()
        if ctl is not None:
            try:
                ctl.close()
            except OSError:
                pass


def _ring_socket_round(uds_dir: str, data_port: int, flow: str, data,
                       chunks, seqs, idxs, xid: str, host: str,
                       port: int, timeout_s: float,
                       result: _StripeResult, ctx: Optional[dict],
                       direct_pin: Optional[int],
                       fill_to=None) -> Optional[bool]:
    """The socket lane's descriptor-ring round: ``ring_attach`` maps
    the flow's ring WITHOUT a data segment, descriptors post + ONE
    ``shm_post`` doorbell, then the batch's chunk frames stream to
    the LOCAL daemon over one data socket while its completer drives
    the descriptors through the normal send machinery — the client
    never issues a per-chunk control op, and completion is polled
    lock-free out of the mmapped cursor.  Payload bytes still ride
    TCP; only submission/completion moved into shared memory, which
    is where the socket lane's exposed-comm time lived.

    ``fill_to`` is the producer hook: when set, each chunk is pulled
    from the producer right before its staging frame — production
    happens INSIDE the completion window, the overlap the T3 shape
    wants.

    Rounds larger than the ring post in ring-sized batches under
    backpressure, like :func:`_ring_round`.  Returns None when the
    ring handoff is unusable (no capability, attach refused, doorbell
    lost before any batch completed) — the caller falls back to the
    classic threaded round (``dcn.ring.fallback``) and re-sends the
    SAME seqs, which the receiver's dedup window referees.  True
    means the round ran; unconfirmed chunks stay pending."""
    nbytes = len(data)
    ctl = None
    ring_seg = None
    dsock = None
    try:
        with trace.attach(ctx.get("trace") if ctx else None,
                          ctx.get("span") if ctx else None):
            try:
                ctl = DcnXferClient(uds_dir,
                                    timeout_s=max(timeout_s, 10.0))
                resp = ctl.ring_attach(flow)
            except (DcnXferError, OSError) as e:
                result.fail(e)
                return None
            if not resp.get("ring_path"):
                return None
            try:
                ring_seg = dcn_shm.map_segment(
                    resp["ring_path"],
                    dcn_shm.ring_bytes(
                        int(resp.get("ring_slots") or 0)))
                ring = dcn_shm.RingView(ring_seg.view)
                slots = ring.slots
            except (OSError, ValueError, struct.error):
                return None
            try:
                dsock = socket.create_connection(
                    ("127.0.0.1", data_port), timeout=timeout_s)
                _set_nodelay(dsock)
            except OSError as e:
                result.fail(e)
                return None
            src = memoryview(data)
            deadline = time.monotonic() + timeout_s
            for bstart in range(0, len(idxs), slots):
                batch = idxs[bstart:bstart + slots]
                n = len(batch)
                if bstart:
                    # Blocked-poster backpressure: reached only after
                    # the previous batch's cursor drained.
                    counters.inc("dcn.ring.backpressure")
                try:
                    rnd = ring.post(
                        [(chunks[i][0], chunks[i][1], seqs[i])
                         for i in batch])
                except (OSError, ValueError, struct.error):
                    return None if not bstart else True
                t0 = time.monotonic()
                timeseries.gauge_add("dcn.chunks.inflight", n)
                timed_out = False
                scored = 0
                try:
                    with trace.span("dcn.ring.post",
                                    histogram="dcn.ring.post",
                                    flow=flow, chunks=n, xid=xid):
                        try:
                            ctl.shm_post(
                                flow, n, rnd, xid, nbytes, host, port,
                                direct=direct_pin,
                                stage_wait_ms=int(min(timeout_s, 5.0)
                                                  * 1e3))
                        except (DcnXferError, OSError) as e:
                            result.fail(e)
                            return None if not bstart else True
                        # Stage the batch AFTER the doorbell: frames
                        # stream while the completer parks on their
                        # stage-waits, so staging (and production)
                        # time hides inside the completion window.
                        try:
                            for i in batch:
                                off, ln = chunks[i]
                                ts0 = time.monotonic()
                                try:
                                    with trace.span(
                                            "dcn.chunk.stage",
                                            histogram="dcn.chunk.stage",
                                            flow=flow, off=off,
                                            bytes=ln):
                                        if fill_to is not None:
                                            fill_to(off + ln)
                                        netio.sendall_parts(dsock, (
                                            _chunk_frame_header(
                                                flow, ln, {
                                                    "off": off,
                                                    "tot": nbytes,
                                                    "xid": xid,
                                                }),
                                            src[off:off + ln],
                                        ))
                                finally:
                                    result.phase(
                                        "stage", ts0,
                                        time.monotonic())
                        except (DcnXferError, OSError) as e:
                            # Staging died mid-batch: the unstaged
                            # descriptors' stage-waits expire daemon-
                            # side; poll out whatever completed.
                            result.fail(e)
                        spins = 0
                        while True:
                            try:
                                crnd, done = ring.completion()
                            except (ValueError, struct.error):
                                return None if not bstart else True
                            cur = done if crnd == rnd else 0
                            if cur >= n:
                                scored = n
                                break
                            if time.monotonic() >= deadline:
                                timed_out = True
                                scored = cur
                                break
                            spins += 1
                            if spins > _RING_SPIN_SLOW:
                                time.sleep(0.0005)
                            elif spins > _RING_SPIN_FAST:
                                time.sleep(0.00005)
                            else:
                                time.sleep(0)  # GIL yield
                        try:
                            statuses = ring.statuses(n)
                        except (ValueError, struct.error):
                            return None if not bstart else True
                finally:
                    timeseries.gauge_add("dcn.chunks.inflight", -n)
                    result.phase("comm", t0, time.monotonic())
                _score_ring_slots(batch, chunks, statuses, scored,
                                  result)
                if timed_out:
                    result.fail(DcnXferError(
                        f"ring round for {flow!r} timed out at "
                        f"{scored}/{n}"))
                    return True
            return True
    finally:
        if dsock is not None:
            try:
                dsock.close()
            except OSError:
                pass
        if ring_seg is not None:
            ring_seg.close()
        if ctl is not None:
            try:
                ctl.close()
            except OSError:
                pass


def _observe_exposed(span, comm_iv, stage_iv) -> Optional[float]:
    """Exposed-communication time for one completed transfer: DCN
    round-trip time NOT overlapped by local staging (obs/critpath's
    interval algebra — the same math the offline analyzer applies to
    span trees).  Feeds the ``dcn.exposed`` / ``dcn.comm`` histogram
    pair (their run-delta sums are the ``max_exposed_comm_ratio`` SLO
    input) and the live ``dcn.exposed_ratio`` gauge: 1.0 = nothing
    hidden (the serial shape), 0.0 = the whole DCN leg rode behind
    staging (the T3 goal)."""
    comm_s = critpath.covered_s(comm_iv)
    if comm_s <= 0:
        return None
    exp_s = critpath.exposed_s(comm_iv, stage_iv)
    histo.observe("dcn.exposed", exp_s, trace_id=span.trace_id)
    histo.observe("dcn.comm", comm_s, trace_id=span.trace_id)
    ratio = exp_s / comm_s
    timeseries.gauge("dcn.exposed_ratio", ratio)
    span.annotate(exposed_ms=round(exp_s * 1e3, 3),
                  exposed_ratio=round(ratio, 4))
    return ratio


def _producer_buffer(producer, nbytes: int):
    """Materialize-on-demand buffer over a producer: a bytearray the
    transfer sends from, plus ``fill_to(end)`` pulling the iterator
    until ``[0, end)`` is filled.  The buffer doubles as the
    retransmit source — retry rounds re-send the SAME bytes under the
    SAME seqs out of it, so the exactly-once contract survives a
    producer that can only be consumed once."""
    it = iter(producer() if callable(producer) else producer)
    buf = bytearray(nbytes)
    state = {"filled": 0}

    def fill_to(end: int) -> None:
        end = min(int(end), nbytes)
        while state["filled"] < end:
            try:
                piece = next(it)
            except StopIteration:
                raise DcnXferError(
                    f"producer ended early at {state['filled']}/"
                    f"{nbytes} bytes") from None
            take = len(piece)
            if state["filled"] + take > nbytes:
                raise DcnXferError(
                    f"producer overran {nbytes} bytes")
            buf[state["filled"]:state["filled"] + take] = piece
            state["filled"] += take

    return buf, fill_to


def _producer_shm_stage(fill_to, flow: str, data, chunks, xid: str,
                        nbytes: int, result: _StripeResult):
    """Per-batch shm staging for the producer-fed ring round: pull
    each chunk from the producer, memcpy it into the segment, and
    declare just that range staged with a range ``shm_commit`` — the
    completer's stage-wait for that descriptor unblocks the moment
    the chunk exists, never waiting on the whole shard."""
    src = memoryview(data)

    def stage(ctl, attach_resp, batch) -> None:
        seg = dcn_shm.map_segment(
            attach_resp.get("path", ""),
            int(attach_resp.get("bytes") or 0))
        staged_bytes = 0
        try:
            if seg.size < nbytes:
                raise OSError("segment smaller than payload")
            for i in batch:
                off, ln = chunks[i]
                t0 = time.monotonic()
                try:
                    with trace.span("dcn.chunk.stage",
                                    histogram="dcn.chunk.stage",
                                    flow=flow, off=off, bytes=ln):
                        fill_to(off + ln)
                        seg.view[off:off + ln] = src[off:off + ln]
                        ctl.shm_commit(flow, ln, xid, offset=off,
                                       total=nbytes)
                finally:
                    result.phase("stage", t0, time.monotonic())
                staged_bytes += ln
        finally:
            seg.close()
            if staged_bytes:
                timeseries.record("dcn.shm.tx.bytes", staged_bytes)
                timeseries.record("dcn.lane.shm.bytes", staged_bytes)
                timeseries.gauge_add("dcn.lane.shm.total_bytes",
                                     staged_bytes)

    return stage


def send_pipelined(client, flow: str, data, host: str,
                   port: int, cfg: Optional[PipelineConfig] = None,
                   timeout_s: float = 60.0,
                   producer=None, nbytes: Optional[int] = None
                   ) -> dict:
    """Stage + send ``data`` on ``flow`` to the peer daemon at
    (host, port), chunked and striped, with chunk-granular retransmit.

    ``client`` is the primary (usually resilient) control client: it
    owns the flow registration, the per-flow seq counter, and the
    control-plane healing between retry rounds.  Returns
    ``{bytes, chunks, stripes, rounds, lane}`` (``lane`` is ``shm``,
    ``socket``, or ``shm+socket`` when a mid-transfer downgrade mixed
    them); raises :class:`DcnXferError` once the round budget is spent
    (callers own the serial fallback / leg retry).

    Lane selection is per retry round: a same-host daemon advertising
    ``shm`` gets the zero-copy staging round (no threads, one commit,
    serial sends); everything else — cross-host, capability-less,
    kill-switched, or a lane that broke mid-transfer
    (``dcn.shm.fallback``) — gets the threaded socket round.  Chunk
    seqs are fixed up front, so retransmits are exactly-once no matter
    which lane a round ran on.

    Producer mode (``producer`` + ``nbytes``, ``data=None``): the
    payload is pulled from an iterable of byte chunks AS THE FIRST
    ROUND STAGES, after the round's ONE doorbell — production
    overlaps the DCN leg instead of preceding it (the stage-then-send
    baseline).  A ring-less first round materializes the producer
    fully (``dcn.ring.fallback``) and runs the classic path; retry
    rounds always send from the materialized buffer under the SAME
    seqs.
    """
    cfg = cfg or PipelineConfig()
    fill_to = None
    if producer is not None:
        if data is not None:
            raise ValueError("pass data OR producer, not both")
        if not nbytes or int(nbytes) <= 0:
            raise ValueError("producer mode needs nbytes > 0")
        data, fill_to = _producer_buffer(producer, int(nbytes))
        counters.inc("dcn.ring.producer.transfers")
    nbytes = len(data)
    # Closed-loop grid control: the tuner (one per destination daemon)
    # turns the configured grid into this transfer's plan.  The chunk
    # grid LATCHES here for the whole transfer — it pins the seq block
    # the dedup window referees — while stripe moves also apply
    # between retry rounds below.  Kill switch off: tuner is None and
    # the static grid runs byte-for-byte.
    tuner = (dcn_tune.tuner_for(f"{host}:{port}")
             if cfg.tuned else None)
    if tuner is not None:
        chunk_bytes, planned_stripes = tuner.plan(cfg.chunk_bytes,
                                                  cfg.stripes)
    else:
        chunk_bytes, planned_stripes = cfg.chunk_bytes, cfg.stripes
    if nbytes > chunk_bytes * MAX_CHUNKS_PER_TRANSFER:
        # More chunks than the dedup window can referee would turn a
        # late retransmit into a silent 'dup' drop; grow the chunks.
        # For a tuned plan this is the protocol floor the shrink lever
        # cannot pass (nbytes/128 beats any learned grid), so the plan
        # gauge is republished with the EFFECTIVE chunk — the wire and
        # the dashboard must not disagree.
        grid = chunk_bytes
        chunk_bytes = -(-nbytes // MAX_CHUNKS_PER_TRANSFER)
        if tuner is not None:
            timeseries.gauge("dcn.tune.chunk_bytes",
                             float(chunk_bytes))
        log.warning(
            "chunk size raised %d -> %d for a %d-byte transfer "
            "(dedup-window cap of %d chunks)", grid,
            chunk_bytes, nbytes, MAX_CHUNKS_PER_TRANSFER,
        )
    chunks = plan_chunks(nbytes, chunk_bytes)
    if not chunks:
        # Empty payloads never reach here through should_pipeline, but
        # the public contract must not divide by the chunk count.
        return {"bytes": 0, "chunks": 0, "stripes": 0, "rounds": 0,
                "lane": "none"}
    stripes = min(planned_stripes, len(chunks))
    # One logical transfer = one xid (the receiver's assembly key) and
    # one contiguous block of per-flow seqs.  A retransmit round reuses
    # BOTH: that is what lets the dedup window kill replays per chunk.
    xid = uuid.uuid4().hex[:12]
    base = client._send_seq.get(flow, 0)
    client._send_seq[flow] = base + len(chunks)
    seqs = [base + 1 + i for i in range(len(chunks))]
    counters.inc("dcn.pipeline.transfers")
    # Stripe utilization = dcn.stripes.active / dcn.stripes.configured
    # on the scrape; configured reflects the most recent transfer.
    timeseries.gauge("dcn.stripes.configured", stripes)
    uds_dir = client._uds_dir
    # Daemon↔daemon lane pin for every send op of this transfer:
    # ``0`` forces the peer leg onto TCP, None defers to the sending
    # daemon's own probe (host-identity handshake + env switch).
    direct_pin = None if cfg.shm_direct else 0
    pending = list(range(len(chunks)))
    resent = 0  # chunk-sends beyond the first round (retransmits)
    lanes = set()  # lanes that actually ran a round
    shm_broken = False  # shm machinery failed once: stay on sockets
    ring_broken = False  # socket-ring handoff failed once: classic
    # Exposed-communication accounting across ALL rounds: staging
    # intervals vs daemon-round-trip intervals, unioned per transfer —
    # retransmit rounds are honest cost, not excluded noise.
    stage_iv: List[Tuple[float, float]] = []
    comm_iv: List[Tuple[float, float]] = []
    with trace.span("dcn.pipeline", histogram="dcn.pipeline",
                    flow=flow, bytes=nbytes, chunks=len(chunks),
                    stripes=stripes, xid=xid) as span:
        ctx = trace.context()
        last_errors: List[BaseException] = []
        # One wall-clock budget for the WHOLE transfer, rounds and
        # joins included — not timeout_s per join per round, which
        # would multiply a wedged daemon's stall by rounds * stripes.
        deadline = time.monotonic() + timeout_s
        for rnd in range(cfg.max_rounds):
            if time.monotonic() >= deadline:
                break
            if rnd:
                counters.inc("dcn.pipeline.retry_rounds")
                resent += len(pending)
                if fill_to is not None:
                    # Retry rounds send from the materialized buffer:
                    # a first round that died mid-production must not
                    # retransmit half-filled chunks.
                    fill_to(nbytes)
                # Heal before retrying: a resilient primary reconnects
                # and replays the flow table here, so the fresh stripe
                # connections below land on a daemon that knows `flow`
                # — and re-probes capabilities, which is how a daemon
                # that restarted WITHOUT shm downgrades the remaining
                # rounds to the socket lane.
                client.ping()
                if tuner is not None:
                    # Stripe moves apply BETWEEN retry rounds too:
                    # re-striping pending chunk indices is seq-safe
                    # (the chunk grid and its seqs stay latched).
                    stripes = min(max(1, tuner.stripes_now()),
                                  len(pending))
                    timeseries.gauge("dcn.stripes.configured",
                                     stripes)
            attempted = len(pending)
            round_t0 = time.monotonic()
            result = _StripeResult()
            # Zero-copy lane, decided per round: kill switch off, the
            # machinery has not failed this transfer, and the daemon
            # both offers shm and shares our boot identity.
            ran_shm = False
            producer_round = fill_to is not None and rnd == 0
            if cfg.shm and not shm_broken and shm_same_host(client):
                stage_cb = (_producer_shm_stage(fill_to, flow, data,
                                                chunks, xid, nbytes,
                                                result)
                            if producer_round and cfg.ring else None)
                prepare_cb = ((lambda: fill_to(nbytes))
                              if producer_round else None)
                ran_shm = _shm_round(uds_dir, flow, data, chunks,
                                     seqs, list(pending), xid, host,
                                     port, timeout_s, result, ctx,
                                     already_staged="shm" in lanes,
                                     direct_pin=direct_pin,
                                     use_ring=cfg.ring,
                                     stage=stage_cb,
                                     prepare=prepare_cb)
                if ran_shm:
                    if "shm" not in lanes:
                        counters.inc("dcn.shm.transfers")
                    lanes.add("shm")
                else:
                    shm_broken = True
                    counters.inc("dcn.shm.fallback")
                    log.warning(
                        "shm staging of %r unavailable (%s); falling "
                        "back to the socket lane", flow,
                        result.errors[-1] if result.errors else "?",
                    )
            ran_ring = False
            if (not ran_shm and cfg.ring and not ring_broken
                    and ring_same_host(client)):
                # Descriptor-driven socket lane: same universal ring,
                # payload over TCP — no per-chunk control op on the
                # hot path.
                ran = _ring_socket_round(
                    uds_dir, client.data_port(), flow, data, chunks,
                    seqs, list(pending), xid, host, port, timeout_s,
                    result, ctx, direct_pin,
                    fill_to=fill_to if producer_round else None)
                if ran is None:
                    # Completer/capability gone (daemon death, ring
                    # refused): transparent downgrade to the classic
                    # per-chunk path — the SAME seqs re-send below,
                    # so late completer sends dedup away.
                    ring_broken = True
                    counters.inc("dcn.ring.fallback")
                    log.warning(
                        "socket-ring handoff of %r unavailable (%s); "
                        "falling back to the classic socket round",
                        flow,
                        result.errors[-1] if result.errors else "?",
                    )
                else:
                    ran_ring = True
                    lanes.add("socket")
                    counters.inc("dcn.ring.socket.rounds")
            if not ran_shm and not ran_ring:
                if fill_to is not None:
                    # Ring-less classic round: the stage worker
                    # memcpys from the buffer, so materialize first.
                    fill_to(nbytes)
                lanes.add("socket")
                data_port = client.data_port()
                # The round's "wait" phase: the coordinator parked on
                # its stage/stripe workers.  The worker spans parent
                # UNDER it (wctx), so its SELF time is exactly the
                # un-attributed remainder — thread spawn + join tail —
                # and a critical-path walk descends through it into
                # whichever worker phase dominated.
                with trace.span("dcn.chunk.wait",
                                histogram="dcn.chunk.wait", flow=flow,
                                round=rnd, chunks=len(pending)):
                    wctx = trace.context()
                    workers = [threading.Thread(
                        target=_stage_worker,
                        args=("127.0.0.1", data_port, flow, data,
                              chunks, list(pending), xid, nbytes,
                              timeout_s, result, wctx),
                        name=f"dcn-stage-{flow}",
                        daemon=True,
                    )]
                    for s in range(stripes):
                        idxs = pending[s::stripes]
                        if not idxs:
                            continue
                        workers.append(threading.Thread(
                            target=_send_worker,
                            args=(uds_dir, flow, chunks, seqs, idxs,
                                  xid, host, port, nbytes, timeout_s,
                                  result, wctx, direct_pin),
                            name=f"dcn-stripe-{flow}-{s}",
                            daemon=True,
                        ))
                    for t in workers:
                        t.start()
                    for t in workers:
                        t.join(timeout=max(0.0,
                                           deadline
                                           - time.monotonic()))
                if any(t.is_alive() for t in workers):
                    # Budget spent with workers still wedged (daemon
                    # hung mid-op): surface now; the daemon-thread
                    # workers die with their sockets and later frames
                    # dedup away.
                    if tuner is not None:
                        tuner.on_transfer(False)
                    raise DcnXferError(
                        f"pipelined send of {flow!r} exceeded its "
                        f"{timeout_s:.1f}s budget with stripe workers "
                        "still blocked"
                    )
            # A chunk is settled ONLY on a verdict that means the peer
            # has (or had) the bytes: "sent" (standalone TCP, no
            # fabric verdict), "landed", or "dup".  Everything else —
            # "dropped" (link ate it), "unmatched" (receiver had no
            # flow yet), "rejected", a missing record, any future
            # verdict — goes again under the same seq.
            settled_bytes = sum(
                chunks[i][1] for i, v in result.verdicts.items()
                if v in ("sent", "landed", "dup"))
            pending = [i for i in pending
                       if result.verdicts.get(i)
                       not in ("sent", "landed", "dup")]
            last_errors = result.errors
            stage_iv.extend(result.phases.get("stage", ()))
            comm_iv.extend(result.phases.get("comm", ()))
            span.annotate(round=rnd, pending=len(pending),
                          lane="+".join(sorted(lanes)))
            # Published after EVERY round, with the chunks this round
            # just lost counted in: the tuner and the SLO judge see
            # mid-transfer loss the moment it is known, not once the
            # transfer completes — a controller steering on a
            # completion-time gauge would always be one transfer late.
            timeseries.gauge("dcn.pipeline.retransmit_ratio",
                             (resent + len(pending)) / len(chunks))
            if tuner is not None:
                tuner.on_round(
                    attempted=attempted, failed=len(pending),
                    bytes_confirmed=settled_bytes,
                    elapsed_s=time.monotonic() - round_t0,
                    lane="shm" if ran_shm else "socket",
                    # A partial retry round's B/s is overhead-bound —
                    # loss evidence, not capability evidence.
                    full_round=attempted == len(chunks))
            if not pending:
                ratio = _observe_exposed(span, comm_iv, stage_iv)
                if tuner is not None:
                    tuner.on_transfer(True, exposed_ratio=ratio)
                return {"bytes": nbytes, "chunks": len(chunks),
                        "stripes": stripes, "rounds": rnd + 1,
                        "lane": "+".join(sorted(lanes))}
        if tuner is not None:
            # Round budget spent with chunks still unconfirmed: the
            # strongest degradation signal the controller gets.
            tuner.on_transfer(False)
        raise DcnXferError(
            f"pipelined send of {flow!r} left {len(pending)}/"
            f"{len(chunks)} chunk(s) unconfirmed after "
            f"{cfg.max_rounds} round(s)"
            + (f": {last_errors[0]}" if last_errors else "")
        )


def read_pipelined(client, flow: str, nbytes: int,
                   cfg: Optional[PipelineConfig] = None,
                   timeout_s: float = 60.0) -> bytes:
    """Binary read-back of ``flow``'s completed frame: wait for
    assembly to finish (blocking wait op), then fetch chunk-sized
    slabs over ONE persistent DXR1 stream — raw TCP instead of
    base64-over-JSON, which is where the serial read's time goes.

    One stream, not one per stripe: on loopback (and anything short of
    a saturated NIC) parallel read connections lose to thread-schedule
    overhead — measured 17–32 ms against 12–15 ms for 4 MiB on the
    bench rig.  Chunk-sized requests keep the daemon's per-request
    copy bounded, so read-back still pipelines with the daemon's other
    work.  Falls back to the base64 control read for daemons without
    the wait op.

    A same-host daemon offering ``shm`` skips DXR1 entirely: one
    ``shm_read`` control op and the frame is read out of the client's
    own mapping of the flow's segment — a buffer reference, not a
    socket stream (``dcn.shm.reads``; any shm trouble falls back to
    DXR1 under ``dcn.shm.fallback``)."""
    if nbytes <= 0:
        return b""
    cfg = cfg or PipelineConfig()
    try:
        # The read side's "wait" phase gets its own span so a
        # critical-path walk separates "the peer was slow to finish
        # assembling" from "the read-back itself was slow".
        with trace.span("dcn.wait", histogram="dcn.wait", flow=flow,
                        bytes=nbytes):
            client.wait_rx(flow, nbytes, timeout_s=timeout_s,
                           mode="frame")
    except (DcnWaitUnsupported, AttributeError):
        # Wait-less daemon: land-wait by polling, then the base64
        # read — with the same short-read check as the DXR1 path, so
        # a not-yet-landed frame surfaces instead of returning b"".
        from container_engine_accelerators_tpu.parallel import dcn

        dcn.wait_flow_rx(client, flow, nbytes, timeout_s=timeout_s)
        got = client.read(flow, nbytes)
        if len(got) != nbytes:
            raise DcnXferError(
                f"short read of {flow!r}: {len(got)} != {nbytes}"
            )
        return got
    if cfg.shm and shm_same_host(client):
        try:
            return _read_shm(client, flow, nbytes)
        except (DcnXferError, OSError) as e:
            counters.inc("dcn.shm.fallback")
            log.warning("shm read of %r failed (%s); falling back to "
                        "DXR1", flow, e)
    data_port = client.data_port()
    out = bytearray(nbytes)
    with trace.span("dcn.chunk.read", histogram="dcn.chunk.read",
                    flow=flow, bytes=nbytes):
        sock = socket.create_connection(("127.0.0.1", data_port),
                                        timeout=timeout_s)
        _set_nodelay(sock)
        try:
            for off, ln in plan_chunks(nbytes, cfg.chunk_bytes):
                got = fetch_range("127.0.0.1", data_port, flow, off,
                                  ln, sock=sock, timeout_s=timeout_s)
                if len(got) != ln:
                    raise DcnXferError(
                        f"short pipelined read of {flow!r} at {off}: "
                        f"{len(got)} != {ln}"
                    )
                out[off:off + ln] = got
        except ConnectionError as e:
            raise DcnXferError(f"pipelined read of {flow!r} failed: "
                               f"{e}")
        finally:
            sock.close()
    timeseries.record("dcn.pipeline.rx.bytes", nbytes)
    return bytes(out)


def _read_shm(client, flow: str, nbytes: int) -> bytes:
    """The zero-copy read-back: ask the daemon to surface the
    completed frame in the flow's segment, map it, copy the payload
    out of shared pages.  Raises on any shortfall — the caller owns
    the DXR1 fallback."""
    with trace.span("dcn.shm.read", histogram="dcn.shm.read",
                    flow=flow, bytes=nbytes):
        resp = client.shm_read(flow, nbytes)
        frame = int(resp.get("frame_bytes") or 0)
        if frame < nbytes:
            raise DcnXferError(
                f"short shm read of {flow!r}: {frame} != {nbytes}"
            )
        seg = dcn_shm.map_segment(resp.get("path", ""),
                                  int(resp.get("bytes") or 0))
        try:
            if seg.size < nbytes:
                raise OSError("segment smaller than frame")
            out = bytes(seg.view[:nbytes])
        finally:
            seg.close()
    counters.inc("dcn.shm.reads")
    timeseries.record("dcn.shm.rx.bytes", nbytes)
    return out
