"""Client for the dcnxferd DCN transfer daemon (native/dcnxferd/).

The role the NCCL GPUDirect plugin plays against tcpgpudmarxd's UDS
control socket (SURVEY.md §2.2): workers doing cross-slice DCN transfers
register flows with the per-node daemon, which owns the pinned staging
buffers; accounting rides the same socket.  Newline-delimited JSON.
"""

import base64
import json
import socket
import struct
from typing import Optional

DEFAULT_UDS_DIR = "/run/tpu-dcn"
SOCKET_NAME = "xferd.sock"


class DcnXferError(Exception):
    pass


class DcnXferClient:
    def __init__(self, uds_dir: str = DEFAULT_UDS_DIR, timeout_s: float = 10.0):
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._sock.settimeout(timeout_s)
        self._sock.connect(f"{uds_dir.rstrip('/')}/{SOCKET_NAME}")
        self._rfile = self._sock.makefile("r")
        self._broken = False

    def close(self) -> None:
        """Closing releases every flow this client registered (the daemon
        ties buffer lifetime to the connection, like rxdm)."""
        self._rfile.close()
        self._sock.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def _call(self, **req) -> dict:
        if self._broken:
            raise DcnXferError(
                "connection broken by earlier timeout; reconnect"
            )
        try:
            self._sock.sendall((json.dumps(req) + "\n").encode())
            line = self._rfile.readline()
        except (socket.timeout, OSError) as e:
            # After a timeout the buffered reader may hold a partial line;
            # any retry would consume a stale response.  Poison the client.
            self._broken = True
            raise DcnXferError(f"daemon connection failed: {e}")
        if not line:
            self._broken = True
            raise DcnXferError("daemon closed the connection")
        resp = json.loads(line)
        if not resp.get("ok"):
            raise DcnXferError(resp.get("error", "unknown daemon error"))
        return resp

    # ---- operations --------------------------------------------------------

    def version(self) -> str:
        return self._call(op="version")["version"]

    def ping(self) -> None:
        self._call(op="ping")

    def register_flow(self, flow: str, peer: str = "",
                      bytes: Optional[int] = None) -> dict:
        req = {"op": "register_flow", "flow": flow, "peer": peer}
        if bytes is not None:
            req["bytes"] = bytes
        return self._call(**req)

    def record_transfer(self, flow: str, nbytes: int) -> int:
        return self._call(op="record_transfer", flow=flow,
                          bytes=nbytes)["flow_bytes"]

    def release_flow(self, flow: str) -> None:
        self._call(op="release_flow", flow=flow)

    def data_port(self) -> int:
        """TCP port of the daemon's data-plane listener."""
        return int(self._call(op="data_port")["port"])

    def send(self, flow: str, host: str, port: int,
             nbytes: Optional[int] = None) -> dict:
        """Stream the flow's staging buffer to a peer daemon's data port.

        Returns {bytes, micros, gbps}.  This is the DCN data path the
        reference drives through its NCCL plugin; here the daemon itself
        moves the bytes and reports achieved throughput.
        """
        req = {"op": "send", "flow": flow, "host": host, "port": str(port)}
        if nbytes is not None:
            req["bytes"] = nbytes
        return self._call(**req)

    READ_CHUNK = 512 << 10  # daemon caps per-call reads (outbuf bound)

    def read(self, flow: str, nbytes: int, offset: int = 0) -> bytes:
        """Read back staged bytes (what a peer daemon landed into the
        flow, or what ``put`` staged locally).  Base64 over the control
        socket; reads larger than the daemon's 512 KiB per-call cap are
        chunked by offset.  The daemon bounds reads by the last
        completed frame's length (``frame_bytes`` in each response), so
        a read past the staged payload returns short rather than stale
        buffer tail."""
        out = bytearray()
        while len(out) < nbytes:
            chunk = min(nbytes - len(out), self.READ_CHUNK)
            resp = self._call(op="read", flow=flow, bytes=chunk,
                              offset=offset + len(out))
            data = base64.b64decode(resp["data"])
            if not data:
                break
            out.extend(data)
            if len(data) < chunk:
                break  # clamped at the staged frame's end
            frame = int(resp.get("frame_bytes", 0))
            if frame and offset + len(out) >= frame:
                # Exactly at the frame boundary: the next chunk's offset
                # would be rejected by the daemon, so stop here (a frame
                # that is an exact multiple of READ_CHUNK otherwise
                # turns a legitimate short read into an error).
                break
        return bytes(out)

    def put(self, flow: str, data: bytes, host: str = "127.0.0.1",
            port: Optional[int] = None) -> None:
        """Stage ``data`` into a flow's buffer via the data plane.

        Frames the payload exactly as a peer daemon's ``send`` would
        ("DXF1" magic, u32 LE name length, u64 LE payload length), so
        local staging and remote landing exercise the same RX path.
        """
        if port is None:
            port = self.data_port()
        name = flow.encode()
        hdr = b"DXF1" + struct.pack("<I", len(name)) + struct.pack(
            "<Q", len(data)
        )
        with socket.create_connection((host, port), timeout=30) as s:
            s.sendall(hdr + name + data)

    def stats(self) -> dict:
        return self._call(op="stats")
