"""Client for the dcnxferd DCN transfer daemon (native/dcnxferd/).

The role the NCCL GPUDirect plugin plays against tcpgpudmarxd's UDS
control socket (SURVEY.md §2.2): workers doing cross-slice DCN transfers
register flows with the per-node daemon, which owns the pinned staging
buffers; accounting rides the same socket.  Newline-delimited JSON.

Two clients, two contracts:

- :class:`DcnXferClient` is fail-fast: the first transport failure
  poisons it (a buffered partial response must never satisfy a retry).
- :class:`ResilientDcnXferClient` layers reconnect-with-backoff and
  flow-table replay on top, for callers that must survive the daemon
  restarting underneath them (the self-healing node-agent contract;
  see tests/test_chaos.py).
"""

import base64
import json
import logging
import socket
import struct
import time
from typing import Dict, Optional

from container_engine_accelerators_tpu.metrics import counters
from container_engine_accelerators_tpu.obs import flight, timeseries, trace
from container_engine_accelerators_tpu.utils import faults, netio
from container_engine_accelerators_tpu.utils.retry import RetryPolicy

log = logging.getLogger(__name__)

DEFAULT_UDS_DIR = "/run/tpu-dcn"
SOCKET_NAME = "xferd.sock"


class DcnXferError(Exception):
    pass


class DcnWaitUnsupported(DcnXferError):
    """The daemon has no blocking ``wait`` op (the native daemon, the
    test stub) — callers fall back to adaptive polling."""


class DcnXferClient:
    def __init__(self, uds_dir: str = DEFAULT_UDS_DIR, timeout_s: float = 10.0):
        self._uds_dir = uds_dir.rstrip("/")
        self._timeout_s = timeout_s
        self._sock: Optional[socket.socket] = None
        self._rfile = None
        self._broken = False
        # Per-flow monotonic frame sequence for `send` (client-owned:
        # it must survive daemon restarts, which reset daemon state).
        self._send_seq: Dict[str, int] = {}
        # Daemon capability cache (version-op response), valid for ONE
        # connection — _connect() resets it so a daemon restart is
        # re-probed, never trusted stale; tri-state for the wait op so
        # the unsupported path is probed exactly once per connection.
        self._caps: Optional[dict] = None
        self._wait_supported: Optional[bool] = None
        self._connect()

    def _connect(self) -> None:
        """(Re)establish the control connection.  Fault site
        ``dcn.connect`` fires here, before the real connect."""
        with trace.span("dcn.connect", histogram="dcn.connect",
                        uds=self._uds_dir):
            faults.check("dcn.connect")
            sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            sock.settimeout(self._timeout_s)
            try:
                sock.connect(f"{self._uds_dir}/{SOCKET_NAME}")
            except OSError:
                sock.close()
                raise
        self._sock = sock
        self._rfile = sock.makefile("r")
        self._broken = False
        # Capabilities are a property of the CONNECTION, not the
        # client: the daemon on the other end of a reconnect may be a
        # different binary (a restart downgraded/upgraded it), so every
        # cached handshake answer is re-probed on the next use instead
        # of trusted stale — the shm/pipeline lane selection depends
        # on this.
        self._caps = None
        self._wait_supported = None

    def close(self) -> None:
        """Closing releases every flow this client registered (the daemon
        ties buffer lifetime to the connection, like rxdm)."""
        if self._rfile is not None:
            self._rfile.close()
        if self._sock is not None:
            self._sock.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def _call(self, **req) -> dict:
        with trace.span("dcn.send", histogram="dcn.send",
                        op=req.get("op")):
            if self._broken:
                raise DcnXferError(
                    "connection broken by earlier timeout; reconnect"
                )
            try:
                faults.check("dcn.send")
                # Stamp the active trace on the request: daemons that
                # understand it (fleet/xferd.py) join their spans to
                # this trace, so one cross-node transfer reads as ONE
                # story across processes.  The native daemon ignores
                # unknown fields.
                ctx = trace.context()
                if ctx is not None:
                    req.setdefault("trace", ctx["trace"])
                    req.setdefault("span", ctx["span"])
                netio.sendall(self._sock,
                              (json.dumps(req) + "\n").encode())
                line = self._rfile.readline()
            except (socket.timeout, OSError) as e:
                # After a timeout the buffered reader may hold a partial
                # line; any retry would consume a stale response.  Poison
                # the client.
                self._broken = True
                raise DcnXferError(f"daemon connection failed: {e}")
            if not line:
                self._broken = True
                raise DcnXferError("daemon closed the connection")
            resp = json.loads(line)
            if not resp.get("ok"):
                raise DcnXferError(resp.get("error", "unknown daemon error"))
            return resp

    # ---- operations --------------------------------------------------------

    def version(self) -> str:
        return self._call(op="version")["version"]

    def capabilities(self) -> dict:
        """The version-op response, cached PER CONNECTION: daemons
        advertise protocol extensions here (``frame_version``,
        ``pipeline``, and the shm lane's ``shm``/``shm_dir``/
        ``host_id`` triple); absent keys mean the native DXF1-only
        daemon.  The cache dies with the connection — after a
        reconnect the next call re-probes, so a daemon that restarted
        into a different capability set is never trusted stale."""
        if self._caps is None:
            self._caps = self._call(op="version")
        return self._caps

    def frame_version(self) -> int:
        return int(self.capabilities().get("frame_version", 1))

    def supports_pipeline(self) -> bool:
        return bool(self.capabilities().get("pipeline", 0))

    def supports_shm(self) -> bool:
        """The daemon OFFERS the shm lane.  Whether this client can
        take it also needs the same-host identity check — that lives
        in ``parallel.dcn_pipeline.shm_same_host`` next to the lane
        selection."""
        return bool(self.capabilities().get("shm", 0))

    def supports_forward(self) -> bool:
        """The daemon serves the ``forward`` op (daemon-routed
        schedule legs).  False for the native daemon and forward-less
        daemons — the routed collective runner's signal to downgrade
        that node's legs to coordinator-routed sends mid-schedule."""
        return bool(self.capabilities().get("forward", 0))

    def supports_ring(self) -> bool:
        """The daemon OFFERS the universal submission ring (descriptor
        posting + doorbell on ANY lane).  Whether this client can take
        it also needs the same-host identity check — that lives in
        ``parallel.dcn_pipeline.ring_same_host`` next to the lane
        selection."""
        return bool(self.capabilities().get("ring", 0))

    # -- shm lane ops (zero-copy same-host staging; fleet/xferd.py) ----------

    def shm_attach(self, flow: str, nbytes: int,
                   ring: bool = False) -> dict:
        """Ask the daemon for the flow's mmap segment; returns
        ``{path, bytes, frame_bytes}``.  Idempotent, grows in place.
        ``ring=True`` additionally asks for the flow's descriptor-ring
        file (``ring_path``/``ring_slots`` in the response); a daemon
        that predates the handoff just omits them — the caller's
        signal to run per-chunk sends instead."""
        req = {"op": "shm_attach", "flow": flow, "bytes": int(nbytes)}
        if ring:
            req["ring"] = 1
        return self._call(**req)

    def shm_post(self, flow: str, count: int, rnd: int, xid: str,
                 total: int, host: str, port: int,
                 direct: Optional[int] = None,
                 stage_wait_ms: Optional[int] = None) -> dict:
        """The descriptor-ring doorbell: tell the daemon that ``count``
        chunk descriptors for round ``rnd`` are posted in the flow's
        ring, to be completed toward the peer at (host, port).  ONE
        control round trip replaces ``count`` per-chunk send ops; the
        daemon publishes per-slot verdicts and a completion cursor
        into the ring itself, which the caller polls out of its own
        mapping — no further control traffic."""
        req = {"op": "shm_post", "flow": flow, "count": int(count),
               "round": int(rnd), "xid": xid, "total": int(total),
               "host": host, "port": str(port)}
        if direct is not None:
            req["direct"] = int(direct)
        if stage_wait_ms is not None:
            req["stage_wait_ms"] = int(stage_wait_ms)
        return self._call(**req)

    def ring_attach(self, flow: str) -> dict:
        """Map the flow's descriptor ring WITHOUT a data segment —
        the universal ring's socket-lane entry point.  Returns
        ``{ring_path, ring_slots}``; a daemon that predates the op
        (or has the ring disabled) errors, the caller's signal to
        run the classic per-chunk path."""
        return self._call(op="ring_attach", flow=flow)

    def shm_commit(self, flow: str, nbytes: int, xid: str = "",
                   offset: Optional[int] = None,
                   total: Optional[int] = None) -> dict:
        """Declare ``[0, nbytes)`` of the attached segment a completed
        staged frame (in-place landing; dedup-exempt like any other
        staging, idempotent by construction).  With ``offset`` +
        ``total``, declare just ``[offset, offset+nbytes)`` of a
        ``total``-byte frame staged — the producer-fed overlap path's
        per-chunk commit."""
        req = {"op": "shm_commit", "flow": flow, "bytes": int(nbytes),
               "xid": xid}
        if offset is not None:
            req["offset"] = int(offset)
            req["total"] = int(total or 0)
        return self._call(**req)

    def shm_read(self, flow: str, nbytes: int) -> dict:
        """Make the flow's completed frame visible in its segment and
        return ``{path, bytes, frame_bytes}`` for the caller to map —
        the read-back that never puts payload bytes on a socket."""
        return self._call(op="shm_read", flow=flow, bytes=int(nbytes))

    def ping(self) -> None:
        self._call(op="ping")

    def register_flow(self, flow: str, peer: str = "",
                      bytes: Optional[int] = None) -> dict:
        req = {"op": "register_flow", "flow": flow, "peer": peer}
        if bytes is not None:
            req["bytes"] = bytes
        return self._call(**req)

    def record_transfer(self, flow: str, nbytes: int) -> int:
        return self._call(op="record_transfer", flow=flow,
                          bytes=nbytes)["flow_bytes"]

    def release_flow(self, flow: str) -> None:
        self._call(op="release_flow", flow=flow)
        # A re-registered flow is a fresh incarnation on both ends:
        # its frame numbering restarts with it.
        self._send_seq.pop(flow, None)

    def data_port(self) -> int:
        """TCP port of the daemon's data-plane listener."""
        return int(self._call(op="data_port")["port"])

    def send(self, flow: str, host: str, port: int,
             nbytes: Optional[int] = None,
             direct: Optional[int] = None) -> dict:
        """Stream the flow's staging buffer to a peer daemon's data port.

        Returns {bytes, micros, gbps}.  This is the DCN data path the
        reference drives through its NCCL plugin; here the daemon itself
        moves the bytes and reports achieved throughput.

        Each call stamps the frame with a per-flow monotonic ``seq`` —
        assigned ONCE per send() invocation, so a transport-level replay
        of the same op (the resilient client retrying after a connection
        loss) re-sends the SAME seq and a dedup-aware receiver
        (fleet/xferd.py) lands the frame exactly once.  A caller-level
        retry of a whole leg is a new send() and a new frame.

        ``direct=0`` pins the daemon's peer leg to TCP (the bench's
        serial series must measure the TCP path, not the daemon↔daemon
        segment lane); None leaves the daemon's own probe in charge.
        """
        seq = self._send_seq.get(flow, 0) + 1
        self._send_seq[flow] = seq
        req = {"op": "send", "flow": flow, "host": host, "port": str(port),
               "seq": seq}
        if nbytes is not None:
            req["bytes"] = nbytes
        if direct is not None:
            req["direct"] = int(direct)
        resp = self._call(**req)
        timeseries.record("dcn.tx.bytes", resp.get("bytes", 0))
        return resp

    def forward(self, flow: str, host: str, port: int, nbytes: int,
                offset: int = 0, seq: int = 0, total: int = 0,
                reduce: bool = False,
                attempts: Optional[int] = None,
                stage_wait_ms: Optional[int] = None) -> dict:
        """Post one routed schedule leg: the daemon re-sends its
        staged ``[offset, offset+nbytes)`` of ``flow`` straight to
        the peer daemon at (host, port) — a daemon→daemon hop.  This
        round trip is CONTROL-ONLY: zero payload bytes cross this
        socket (no ``dcn.tx/rx.bytes`` movement; the daemon accounts
        the hop under ``dcn.lane.forward.*``), which is the lane-level
        proof the routed collective runner leans on.

        ``seq`` is CALLER-ASSIGNED (required > 0, unlike ``send``):
        the destination flow's dedup window is shared by every source
        daemon forwarding into it, so only the schedule's author can
        hand out non-colliding numbers — and a caller-level re-post
        of a failed leg reuses the seq it burned, landing exactly
        once.  Returns the daemon's response (bytes/micros/lane/
        verdict/attempts); raises :class:`DcnXferError` when the hop
        stayed undelivered after the daemon's bounded per-hop retry.
        """
        req = {"op": "forward", "flow": flow, "host": host,
               "port": str(port), "bytes": int(nbytes),
               "offset": int(offset), "seq": int(seq)}
        if total:
            req["total"] = int(total)
        if reduce:
            req["reduce"] = 1
        if attempts is not None:
            req["attempts"] = int(attempts)
        if stage_wait_ms is not None:
            req["stage_wait_ms"] = int(stage_wait_ms)
        return self._call(**req)

    def put_range(self, flow: str, data: bytes, offset: int, seq: int,
                  host: str, port: int, reduce: bool = False,
                  total: int = 0) -> None:
        """Coordinator-routed fallback for one forward leg: frame
        ``data`` exactly as a peer daemon's forward would — same
        forward meta, same caller-assigned seq, so landing, reduce
        combining and dedup on the destination are indistinguishable
        from the daemon→daemon hop (a leg downgraded mid-schedule
        composes with forwarded replays of itself) — and write it to
        the DESTINATION daemon's data port.  Payload bytes DO cross
        this client, which is the point of the downgrade accounting:
        ``dcn.stage.bytes`` moves, the forward lane does not."""
        name = flow.encode()
        meta = {"fwd": 1, "off": int(offset), "tot": int(total),
                "red": 1 if reduce else 0}
        ctx = trace.context()
        if ctx is not None:
            meta.update(ctx)
        meta_b = json.dumps(meta).encode()
        hdr = (b"DXF2" + struct.pack("<I", len(name))
               + struct.pack("<Q", len(data))
               + struct.pack("<Q", int(seq))
               + struct.pack("<I", len(meta_b)))
        with socket.create_connection((host, port), timeout=30) as s:
            netio.sendall_parts(s, (hdr, name, meta_b, data))
        timeseries.record("dcn.stage.bytes", len(data))

    READ_CHUNK = 512 << 10  # daemon caps per-call reads (outbuf bound)

    def read(self, flow: str, nbytes: int, offset: int = 0) -> bytes:
        """Read back staged bytes (what a peer daemon landed into the
        flow, or what ``put`` staged locally).  Base64 over the control
        socket; reads larger than the daemon's 512 KiB per-call cap are
        chunked by offset.  The daemon bounds reads by the last
        completed frame's length (``frame_bytes`` in each response), so
        a read past the staged payload returns short rather than stale
        buffer tail."""
        with trace.span("dcn.read", histogram="dcn.read", flow=flow,
                        bytes=nbytes) as s:
            out = bytearray()
            while len(out) < nbytes:
                chunk = min(nbytes - len(out), self.READ_CHUNK)
                resp = self._call(op="read", flow=flow, bytes=chunk,
                                  offset=offset + len(out))
                data = base64.b64decode(resp["data"])
                if not data:
                    break
                out.extend(data)
                if len(data) < chunk:
                    break  # clamped at the staged frame's end
                frame = int(resp.get("frame_bytes", 0))
                if frame and offset + len(out) >= frame:
                    # Exactly at the frame boundary: the next chunk's
                    # offset would be rejected by the daemon, so stop here
                    # (a frame that is an exact multiple of READ_CHUNK
                    # otherwise turns a legitimate short read into an
                    # error).
                    break
            s.annotate(read=len(out))
            timeseries.record("dcn.rx.bytes", len(out))
            return bytes(out)

    def put(self, flow: str, data: bytes, host: str = "127.0.0.1",
            port: Optional[int] = None) -> None:
        """Stage ``data`` into a flow's buffer via the data plane.

        Frames the payload exactly as a peer daemon's ``send`` would
        ("DXF1" magic, u32 LE name length, u64 LE payload length), so
        local staging and remote landing exercise the same RX path.
        """
        if port is None:
            port = self.data_port()
        name = flow.encode()
        hdr = b"DXF1" + struct.pack("<I", len(name)) + struct.pack(
            "<Q", len(data)
        )
        with socket.create_connection((host, port), timeout=30) as s:
            # Separate buffers (no concat copy of the payload) through
            # the short-write-proof capped sender — multi-MiB frames
            # must survive platforms whose sendmsg truncates.
            netio.sendall_parts(s, (hdr, name, data))
        timeseries.record("dcn.stage.bytes", len(data))

    def stats(self, flow: Optional[str] = None) -> dict:
        """Daemon stats.  ``flow`` asks a filter-aware daemon
        (PyXferd) for just that flow's entry; daemons that predate the
        filter ignore the key and return everything — callers must
        still select their flow from the list."""
        req = {"op": "stats"}
        if flow is not None:
            req["flow"] = flow
        return self._call(**req)

    # Wait-op slice: short enough that a daemon thread is never parked
    # long on a dead client, long enough that slicing costs nothing.
    WAIT_SLICE_S = 1.0

    def wait_rx(self, flow: str, nbytes: int, timeout_s: float = 60.0,
                mode: str = "rx") -> dict:
        """Block INSIDE the daemon until ``flow`` has ``nbytes`` of rx
        accounting (mode ``rx``) or a completed frame of at least
        ``nbytes`` (mode ``frame``).

        This replaces the 20 ms client-side poll quantum with a
        condition-variable wakeup: small transfers stop paying up to a
        full quantum of idle tax per phase.  The wait is sliced so the
        daemon never holds a thread past :data:`WAIT_SLICE_S` per
        round trip.  Raises :class:`DcnWaitUnsupported` (once probed,
        instantly) for daemons without the op, and ``TimeoutError``
        past the deadline — the same contract as the polling fallback
        in ``parallel.dcn.wait_flow_rx``.
        """
        if self._wait_supported is False:
            raise DcnWaitUnsupported("daemon has no wait op")
        deadline = time.monotonic() + timeout_s
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise TimeoutError(
                    f"flow {flow!r} never reached {nbytes} bytes "
                    f"({mode})"
                )
            try:
                resp = self._call(
                    op="wait", flow=flow, bytes=nbytes, mode=mode,
                    timeout_ms=int(min(remaining, self.WAIT_SLICE_S)
                                   * 1e3),
                )
            except DcnXferError as e:
                if "unknown op" in str(e):
                    self._wait_supported = False
                    raise DcnWaitUnsupported(str(e))
                if "unknown flow" in str(e):
                    # Same contract as the polling fallback: a flow
                    # that is not registered YET (mid-restart replay on
                    # the other side of a race) is "zero bytes so far",
                    # not an error — keep waiting until the deadline.
                    self._wait_supported = True
                    time.sleep(0.005)
                    continue
                raise
            self._wait_supported = True
            if resp.get("done"):
                return resp


# Reconnect budget tuned to ride out a daemon restart (the DaemonSet's
# CrashLoopBackOff floor is 10s) without masking a genuinely dead node:
# connect refusals fail instantly, so coverage is the SUM of the sleeps —
# 0.05+0.1+0.2+0.4+0.8+1.6+3+3+3+3+3 ≈ 18s (> the 10s floor), with the
# 30s deadline as the hard wall-clock cap.
DEFAULT_DCN_RETRY = RetryPolicy(
    max_attempts=12,
    initial_backoff_s=0.05,
    max_backoff_s=3.0,
    deadline_s=30.0,
)


class ResilientDcnXferClient(DcnXferClient):
    """A :class:`DcnXferClient` that survives daemon churn.

    The base client is deliberately fail-fast: one connection failure
    poisons it, because the buffered reader may hold a stale partial
    response and the daemon has already released its flows (buffer
    lifetime is tied to the connection, like rxdm).  That is the right
    *transport* semantic — but a node agent or bench that dies because
    the sidecar daemon restarted is a robustness hole.  This subclass
    closes the loop:

    - connection failures trigger reconnect with exponential backoff
      under a bounded :class:`RetryPolicy` budget;
    - a client-side **flow table** (flow → register args) is replayed
      after every reconnect — mandatory for correctness, not a
      convenience: the restarted/reconnected daemon has no memory of
      this client's flows, so any op on an unreplayed flow would fail
      with ``unknown flow``;
    - daemon-level errors (``ok:false`` responses) still fail fast:
      retrying a rejected request is wrong, only transport loss is
      retried;
    - once the budget is exhausted the client turns terminal: every
      further call raises a clear ``DcnXferError`` immediately
      (graceful degradation instead of hammering a dead socket).

    Retrying an op whose response was lost cannot double-account on the
    daemon: the connection's death released the server-side flow, so
    the replayed registration starts from zero and the retried op runs
    against fresh state.
    """

    def __init__(
        self,
        uds_dir: str = DEFAULT_UDS_DIR,
        timeout_s: float = 10.0,
        retry: Optional[RetryPolicy] = None,
    ):
        self._retry = retry or DEFAULT_DCN_RETRY
        self._flows: Dict[str, dict] = {}
        # Last payload this client staged per flow (via put): the daemon
        # loses its staging buffers on restart, so a post-restart read
        # transparently restages from here instead of surfacing an empty
        # frame to the caller.  Dropped on release_flow.
        self._staged: Dict[str, bytes] = {}
        self._exhausted = False
        # The initial connect rides the same budget: the client may come
        # up before its node sidecar does.
        self._retry.call(
            super().__init__, uds_dir, timeout_s, retry_on=(OSError,)
        )

    # -- reconnect machinery -------------------------------------------------

    def _reconnect_and_replay(self) -> None:
        with trace.span("dcn.replay", histogram="dcn.replay",
                        flows=len(self._flows)):
            try:
                self.close()
            except OSError:  # a half-dead socket may refuse even close()
                pass
            counters.inc("dcn.reconnect.attempts")
            self._connect()  # OSError propagates to the retry loop
            counters.inc("dcn.reconnect.success")
            for flow, kw in list(self._flows.items()):
                try:
                    DcnXferClient._call(
                        self, op="register_flow", flow=flow, **kw
                    )
                    counters.inc("dcn.replayed_flows")
                except DcnXferError as e:
                    if self._broken:
                        raise  # transport died again: retry loop handles it
                    if "exist" in str(e).lower():
                        # An alive-but-slow daemon may not have processed
                        # the old connection's EOF yet, so our own previous
                        # registration still holds the name.  Mark broken
                        # and surface as transport-level: the outer retry's
                        # backoff gives the daemon time to release it.
                        self._broken = True
                        raise DcnXferError(
                            f"flow replay raced old-connection cleanup: {e}"
                        )
                    # Other daemon-level rejection (e.g. another client
                    # took the name): keep replaying the rest; ops on this
                    # flow will surface the daemon's own error.
                    log.error("replay of flow %r failed: %s", flow, e)
        log.warning(
            "dcn control connection re-established; %d flow(s) replayed",
            len(self._flows),
        )

    def _with_budget(self, attempt, what: str, latch: bool,
                     op: Optional[str] = None):
        """Run ``attempt`` under the retry budget; daemon-level errors
        (ok:false with an intact transport) fail fast, transport loss
        retries.  ``latch=True`` turns the client terminal on
        exhaustion; the data plane passes False so a data-port-only
        outage cannot poison still-healthy control-plane ops.

        The whole budget runs inside ONE ``dcn.op`` span, so every
        attempt's send/connect/replay span hangs off the same trace —
        a recovered op reads as one story in the JSONL, not as
        disconnected fragments."""
        if self._exhausted:
            raise DcnXferError(
                "dcn retry budget exhausted; client is terminal "
                "(daemon stayed unreachable through "
                f"{self._retry.max_attempts} attempts)"
            )
        with trace.span("dcn.op", target=what, op=op) as span:
            return self._budget_loop(attempt, what, latch, span)

    def _budget_loop(self, attempt, what: str, latch: bool, span):
        last: Optional[BaseException] = None
        attempts = 0
        for _attempt in self._retry.attempts():
            attempts = _attempt + 1
            try:
                result = attempt()
                span.annotate(attempts=attempts)
                return result
            except DcnXferError as e:
                if not self._broken or self._exhausted:
                    # Daemon-level error, or a nested control-plane call
                    # already latched terminal: fail fast — looping a
                    # second budget over a terminal client only doubles
                    # the hang.
                    raise
                last = e  # transport loss: reconnect on the next attempt
            except OSError as e:  # reconnect/data-plane connect failed
                last = e
        span.annotate(attempts=attempts)
        if latch:
            self._exhausted = True
        counters.inc("dcn.retry.exhausted")
        if latch:
            # The client just went terminal: capture the evidence while
            # it still exists (the pod is usually deleted minutes later).
            flight.on_terminal(f"dcn {what} client latched terminal")
        raise DcnXferError(
            f"dcn {what} unreachable after "
            f"{self._retry.max_attempts} attempts: {last}"
        )

    def _call(self, **req) -> dict:
        def attempt():
            if self._broken or self._sock is None:
                self._reconnect_and_replay()
            return DcnXferClient._call(self, **req)

        return self._with_budget(attempt, "transfer daemon", latch=True,
                                 op=req.get("op"))

    # -- flow-table bookkeeping ----------------------------------------------

    def register_flow(self, flow: str, peer: str = "",
                      bytes: Optional[int] = None) -> dict:
        resp = super().register_flow(flow, peer, bytes)
        kw = {"peer": peer}
        if bytes is not None:
            kw["bytes"] = bytes
        self._flows[flow] = kw
        return resp

    def release_flow(self, flow: str) -> None:
        super().release_flow(flow)
        self._flows.pop(flow, None)
        self._staged.pop(flow, None)

    def put(self, flow: str, data: bytes, host: str = "127.0.0.1",
            port: Optional[int] = None) -> None:
        """Data-plane staging with the same budget.  After a failure the
        port is re-resolved via the (self-healing) control plane: a
        restarted daemon binds a fresh ephemeral data port, so a cached
        one dials a dead listener."""
        state = {"port": port}

        def attempt():
            try:
                return DcnXferClient.put(self, flow, data, host,
                                         state["port"])
            except OSError:
                state["port"] = None
                raise

        result = self._with_budget(attempt, "data plane", latch=False,
                                   op="put")
        self._staged[flow] = bytes(data)
        return result

    def put_range(self, flow: str, data: bytes, offset: int, seq: int,
                  host: str, port: int, reduce: bool = False,
                  total: int = 0) -> None:
        """Downgraded-leg staging under the data-plane budget.  No
        port re-resolution on failure (the destination is a REMOTE
        daemon — only the routed runner can re-resolve its port), and
        no restage cache: a replay of the same leg carries the same
        seq, so the destination's dedup window makes the retry safe
        whether or not the first frame landed."""
        def attempt():
            return DcnXferClient.put_range(self, flow, data, offset,
                                           seq, host, port, reduce,
                                           total)

        return self._with_budget(attempt, "data plane", latch=False,
                                 op="put_range")

    # How long a restage waits for its own payload to finish landing
    # through the local data plane before re-reading/re-sending.
    RESTAGE_RX_TIMEOUT_S = 30.0

    def send(self, flow: str, host: str, port: int,
             nbytes: Optional[int] = None,
             direct: Optional[int] = None) -> dict:
        """`send` that survives the daemon losing the staged payload.

        A send issued (or retried) after a connection loss lands on a
        daemon whose flow table was replayed but whose staging buffers
        are gone (a restarted daemon, or the old one releasing the flow
        with the dead connection).  The native daemon would silently
        stream the blank buffer — zero-filled bytes to the peer — so
        when this client staged the payload itself it FIRST checks the
        flow's ``frame_bytes`` and restages on blank; a daemon that
        instead answers "nothing staged" (fleet/xferd.py) is healed
        reactively the same way.  The re-send reuses the frame seq the
        failed attempt burned: if that attempt actually delivered
        before its response was lost, the receiver's dedup window drops
        the replay — exactly-once either way."""
        data = self._staged.get(flow)
        if data is not None:
            st = next((f for f in self.stats(flow=flow)["flows"]
                       if f["flow"] == flow), None)
            if st is not None and not st.get("frame_bytes", len(data)):
                self._restage(flow, data)
        try:
            return super().send(flow, host, port, nbytes, direct)
        except DcnXferError as e:
            if "nothing staged" not in str(e) or data is None:
                raise
            self._restage(flow, data)
            # Re-issue under the seq the failed attempt burned.
            self._send_seq[flow] -= 1
            return super().send(flow, host, port, nbytes, direct)

    def _restage(self, flow: str, data: bytes,
                 op: str = "send") -> None:
        counters.inc(f"dcn.{op}.restaged")
        with trace.span("dcn.restage", histogram="dcn.restage",
                        flow=flow, bytes=len(data), op=op):
            self.put(flow, data)
            self._wait_rx(flow, len(data), self.RESTAGE_RX_TIMEOUT_S)

    def forward(self, flow: str, host: str, port: int, nbytes: int,
                offset: int = 0, seq: int = 0, total: int = 0,
                reduce: bool = False,
                attempts: Optional[int] = None,
                stage_wait_ms: Optional[int] = None) -> dict:
        """``forward`` that survives the daemon-side flow dying with
        the control connection (a daemon releases a flow when the
        conn that registered it breaks, and this client's reconnect
        replays the registration EMPTY).  When this client staged the
        flow itself, a "not staged"/"unknown flow" answer restages
        from the local cache and re-issues the SAME caller-assigned
        seq — if the lost attempt actually delivered before its
        answer vanished, the destination's dedup window drops the
        replay: exactly-once either way.  Peer contributions landed
        into the flow mid-round have no local cache and cannot be
        healed here; the routed runner's verification phase is the
        backstop that fails such a round."""
        try:
            return super().forward(flow, host, port, nbytes,
                                   offset=offset, seq=seq,
                                   total=total, reduce=reduce,
                                   attempts=attempts,
                                   stage_wait_ms=stage_wait_ms)
        except DcnXferError as e:
            data = self._staged.get(flow)
            msg = str(e)
            if data is None or ("not staged" not in msg
                                and "unknown flow" not in msg):
                raise
            self._restage(flow, data, op="forward")
            return super().forward(flow, host, port, nbytes,
                                   offset=offset, seq=seq,
                                   total=total, reduce=reduce,
                                   attempts=attempts,
                                   stage_wait_ms=stage_wait_ms)

    def read(self, flow: str, nbytes: int, offset: int = 0) -> bytes:
        """`read` that survives a daemon restart eating the staged
        frame: an EMPTY read of a flow this client itself staged means
        the daemon came back with fresh (blank) buffers — replaying the
        flow table restored the registration but not the bytes.  The
        client restages the cached payload through the data plane, waits
        for it to land, and reads again, so callers never see the
        daemon's "nothing staged" for payloads they already handed us.
        (Reads of peer-landed flows have no local cache and still
        surface the blank — only the peer can re-send those bytes.)"""
        data = self._staged.get(flow)
        try:
            out = super().read(flow, nbytes, offset)
            if out or nbytes <= 0 or data is None:
                return out
        except DcnXferError as e:
            # The native daemon answers a blank flow with an explicit
            # "no completed frame" error; PyXferd and the stub with an
            # empty read.  Both mean the same thing: the staging went
            # with the old process.
            if data is None or "no completed frame" not in str(e):
                raise
        counters.inc("dcn.read.restaged")
        with trace.span("dcn.restage", histogram="dcn.restage",
                        flow=flow, bytes=len(data)):
            self.put(flow, data)
            self._wait_rx(flow, len(data), self.RESTAGE_RX_TIMEOUT_S)
        return super().read(flow, nbytes, offset)

    def _wait_rx(self, flow: str, nbytes: int, timeout_s: float) -> None:
        """parallel.dcn.wait_flow_rx under this client's error contract
        (lazy import mirrors dcn.py's own lazy import of this module)."""
        from container_engine_accelerators_tpu.parallel import dcn

        try:
            dcn.wait_flow_rx(self, flow, nbytes, timeout_s=timeout_s)
        except TimeoutError as e:
            raise DcnXferError(f"restage failed: {e}")

