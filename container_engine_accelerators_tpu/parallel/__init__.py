"""Public re-exports for the parallel package."""
from container_engine_accelerators_tpu.parallel.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    batch_sharding,
    create_hybrid_mesh,
    create_mesh,
    replicated,
    shard_params,
    shard_params_fsdp,
)
from container_engine_accelerators_tpu.parallel import dcn
from container_engine_accelerators_tpu.parallel.seq import (
    make_sequence_parallel_attention,
    ring_attention,
    ulysses_attention,
)

__all__ = [
    "DATA_AXIS",
    "MODEL_AXIS",
    "batch_sharding",
    "create_hybrid_mesh",
    "create_mesh",
    "make_sequence_parallel_attention",
    "replicated",
    "ring_attention",
    "shard_params",
    "shard_params_fsdp",
    "ulysses_attention",
    "dcn",
]
