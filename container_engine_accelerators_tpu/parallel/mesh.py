"""Device mesh and sharding helpers — the TPU scaling fabric.

The reference scales through infrastructure (NCCL over GPUDirect-TCPX,
topology-packed placement); the TPU-native equivalent is a
``jax.sharding.Mesh`` whose *data* axis rides ICI within a slice and DCN
across slices, with XLA inserting the collectives (SURVEY.md §2.3, §5
"Distributed communication backend").

- :func:`create_mesh` — single-slice mesh with (data, model) axes.
- :func:`create_hybrid_mesh` — multi-slice: DCN axis outermost so
  cross-slice traffic is data-parallel gradient all-reduce (the
  cheap/latency-tolerant collective) and model axes stay on ICI.
- :func:`shard_params` — GSPMD tensor-parallel param layout: shard the
  largest weight axis divisible by the model-axis size; replicate the
  rest.  Batch arrays shard over the data axis.
"""

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"


def create_mesh(
    data: int = -1,
    model: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a (data, model) mesh over the slice's devices.

    ``data=-1`` means "all remaining devices".  mesh_utils lays devices out
    so neighboring mesh coordinates are ICI neighbors.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if data == -1:
        if n % model != 0:
            raise ValueError(f"{n} devices not divisible by model={model}")
        data = n // model
    if data * model != n:
        raise ValueError(f"mesh {data}x{model} != {n} devices")
    mesh_devices = mesh_utils.create_device_mesh((data, model), devices=devices)
    return Mesh(mesh_devices, (DATA_AXIS, MODEL_AXIS))


def create_hybrid_mesh(
    ici_data: int,
    ici_model: int = 1,
    num_slices: int = 1,
) -> Mesh:
    """Multi-slice mesh: (dcn, data, model) with the DCN axis outermost.

    Cross-slice communication then only carries the data-parallel gradient
    all-reduce; tensor-parallel traffic stays on ICI (scaling-book recipe).
    """
    if num_slices <= 1:
        return create_mesh(ici_data, ici_model)
    devices = jax.devices()
    if getattr(devices[0], "slice_index", None) is not None:
        # Real multi-slice hardware: let mesh_utils honor slice boundaries.
        # A shape error here is a misconfiguration and must surface — a
        # silent flat fallback would route ICI-axis traffic over DCN.
        mesh_devices = mesh_utils.create_hybrid_device_mesh(
            mesh_shape=(ici_data, ici_model),
            dcn_mesh_shape=(num_slices, 1),
        )
        # Returned shape is (num_slices*ici_data, ici_model) slice-major;
        # reshape to expose the DCN axis.
        mesh_devices = np.asarray(mesh_devices).reshape(
            num_slices, ici_data, ici_model
        )
    else:
        # Devices without slice_index (CPU mesh in tests, single-slice
        # simulation): slice-major assignment over the flat device list.
        need = num_slices * ici_data * ici_model
        if len(devices) < need:
            raise ValueError(
                f"hybrid mesh needs {need} devices, have {len(devices)}"
            )
        mesh_devices = np.array(devices[:need]).reshape(
            num_slices, ici_data, ici_model
        )
    return Mesh(mesh_devices, ("dcn", DATA_AXIS, MODEL_AXIS))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Shard the leading (batch) axis over data (and dcn when present)."""
    if "dcn" in mesh.axis_names:
        return NamedSharding(mesh, P(("dcn", DATA_AXIS)))
    return NamedSharding(mesh, P(DATA_AXIS))


def _largest_divisible_axis(shape, size, taken=(), prefer_trailing=True):
    """Index of the largest axis divisible by ``size`` (and >= 2*size,
    so a shard never degenerates below 2 rows), skipping ``taken``
    axes; None if nothing qualifies.  ``prefer_trailing`` breaks ties
    toward the output-feature axis (the Megatron convention)."""
    best_axis, best_dim = None, 0
    for axis in range(len(shape)):
        dim = shape[axis]
        better = dim >= best_dim if prefer_trailing else dim > best_dim
        if (axis not in taken and dim % size == 0 and better
                and dim >= 2 * size):
            best_axis, best_dim = axis, dim
    return best_axis


def _param_spec(shape: Tuple[int, ...], model_size: int) -> P:
    if model_size <= 1 or not shape:
        return P()
    # Shard the largest axis divisible by the model-parallel degree; ties
    # break toward the trailing (output-feature) axis, which for convs and
    # dense layers makes this Megatron-style output-channel sharding.
    best_axis = _largest_divisible_axis(shape, model_size)
    if best_axis is None:
        return P()
    spec = [None] * len(shape)
    spec[best_axis] = MODEL_AXIS
    return P(*spec)


def shard_params(params, mesh: Mesh):
    """NamedShardings for a param pytree: tensor-parallel over MODEL_AXIS."""
    model_size = mesh.shape.get(MODEL_AXIS, 1)
    return jax.tree_util.tree_map(
        lambda x: NamedSharding(mesh, _param_spec(np.shape(x), model_size)),
        params,
    )


def _param_spec_fsdp(shape, data_size: int, model_size: int) -> P:
    """FSDP/ZeRO layout: the Megatron model-axis rule first, then the
    largest REMAINING axis divisible by the data-axis size carries
    DATA_AXIS.  Params (and their same-shaped optimizer buffers) thus
    occupy 1/(dp*tp) of HBM per chip; GSPMD all-gathers a layer's
    weights just-in-time for its matmul and reduce-scatters its grads —
    the scaling-book ZeRO-3 pattern, no hand-written collectives."""
    base = _param_spec(shape, model_size)
    spec = list(base) + [None] * (len(shape) - len(base))
    taken = tuple(i for i, s in enumerate(spec) if s is not None)
    best_axis = _largest_divisible_axis(
        shape, data_size, taken=taken, prefer_trailing=True
    )
    if best_axis is not None:
        spec[best_axis] = DATA_AXIS
    return P(*spec)


def shard_params_fsdp(params, mesh: Mesh):
    """NamedShardings for fully-sharded data parallelism (+ tp).

    Every param shards over the data axis too (ZeRO-3 / FSDP): with
    replicated-per-chip optimizer state the params' Adam moments are
    the dominant HBM term at scale, and dp-degree chips each holding a
    full copy is pure waste.  Small tensors that don't divide stay
    replicated — they are not the memory term.
    """
    data_size = mesh.shape.get(DATA_AXIS, 1)
    model_size = mesh.shape.get(MODEL_AXIS, 1)
    return jax.tree_util.tree_map(
        lambda x: NamedSharding(
            mesh, _param_spec_fsdp(np.shape(x), data_size, model_size)
        ),
        params,
    )


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
