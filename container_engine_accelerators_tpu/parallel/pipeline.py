"""Pipeline parallelism — GPipe-style microbatch schedule over ICI.

The reference has no in-repo model parallelism (SURVEY.md §2.3): its
scaling story is infrastructure.  This module rounds out the TPU-native
parallelism layer (dp/tp in ``mesh.py``, sp in ``seq.py``) with the
remaining classic axis: **pipeline** parallelism, for models whose
layers don't fit one chip's HBM.

Design (the scaling-book collective-permute recipe, TPU-first):

- Layer params arrive **stacked** on a leading axis — exactly the layout
  ``nn.scan`` produces for the transformer (models/transformer.py) — and
  are reshaped to ``[S, L/S, ...]``: stage-sharded over the mesh's
  ``pipe`` axis, layers within a stage scanned locally.
- The schedule is GPipe with M microbatches: at step t every stage runs
  its local layer scan, then activations hop one stage down the ring via
  ``lax.ppermute`` (ICI neighbor traffic only — stages are laid out so
  hop distance is 1).  ``M + S - 1`` steps total; warmup/drain bubbles
  compute on zeros, the standard trade against per-step dispatch.
- Everything lives inside ONE ``shard_map`` + ``lax.fori_loop`` with a
  static trip count, so XLA sees a single compiled program
  (data-dependent Python control flow never enters the jit).
- Reverse-mode AD falls out: static-bound fori_loop lowers to scan, and
  ppermute transposes to the reverse permutation, which IS the backward
  pipeline schedule — no hand-written backward pass.

Composes with data parallelism: the mesh is ``(pipe, data)``; microbatch
batch dims shard over ``data``, params over ``pipe``.
"""

from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
try:  # jax >= 0.6 promotes shard_map out of experimental
    from jax import shard_map
except ImportError:  # pragma: no cover — older pinned jax
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from container_engine_accelerators_tpu.parallel.mesh import DATA_AXIS

PIPE_AXIS = "pipe"


def create_pipeline_mesh(
    pipe: int,
    data: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """(pipe, data) mesh; consecutive devices form a stage ring so the
    ppermute hops ride neighbor ICI links."""
    devices = list(devices if devices is not None else jax.devices())
    if pipe * data != len(devices):
        raise ValueError(
            f"mesh {pipe}x{data} != {len(devices)} devices"
        )
    arr = np.array(devices).reshape(pipe, data)
    return Mesh(arr, (PIPE_AXIS, DATA_AXIS))


def stage_params(stacked_params, num_stages: int):
    """Reshape every stacked-layer leaf [L, ...] -> [S, L/S, ...]."""

    def r(x):
        if x.shape[0] % num_stages != 0:
            raise ValueError(
                f"{x.shape[0]} layers not divisible by {num_stages} stages"
            )
        return x.reshape(num_stages, x.shape[0] // num_stages, *x.shape[1:])

    return jax.tree_util.tree_map(r, stacked_params)


def unstage_params(staged_params):
    """Inverse of :func:`stage_params`: [S, L/S, ...] -> [L, ...]."""
    return jax.tree_util.tree_map(
        lambda x: x.reshape(x.shape[0] * x.shape[1], *x.shape[2:]),
        staged_params,
    )


def staged_sharding(mesh: Mesh, staged_params):
    """NamedShardings placing the leading stage axis on PIPE_AXIS."""
    return jax.tree_util.tree_map(
        lambda x: NamedSharding(mesh, P(PIPE_AXIS)), staged_params
    )


def make_pipeline_apply(
    layer_fn: Callable,
    mesh: Mesh,
    num_microbatches: int,
):
    """Build ``apply(staged_params, x) -> y`` running all L layers as an
    S-stage pipeline.

    ``layer_fn(layer_params, x) -> x`` is one layer (shape-preserving);
    ``staged_params`` leaves are [S, L/S, ...] placed with
    :func:`staged_sharding`; ``x`` is [B, ...] with B divisible by
    ``num_microbatches`` (and the microbatch by the data-axis size).
    """
    S = mesh.shape[PIPE_AXIS]
    M = num_microbatches

    def local_stage(chunk, x):
        def body(c, p):
            return layer_fn(p, c), None

        y, _ = jax.lax.scan(body, x, chunk)
        return y

    def device_fn(staged, xs):
        # staged leaves here: [1, L/S, ...] (this stage's chunk).
        chunk = jax.tree_util.tree_map(lambda a: a[0], staged)
        s = jax.lax.axis_index(PIPE_AXIS)

        def vary_pipe(v):
            # xs is replicated over pipe; the loop carry becomes
            # pipe-varying after the first hop, so the initial value
            # must carry that type too.
            if hasattr(jax.lax, "pcast"):
                return jax.lax.pcast(v, (PIPE_AXIS,), to="varying")
            return jax.lax.pvary(v, (PIPE_AXIS,))

        buf = vary_pipe(jnp.zeros_like(xs[0]))
        outs = vary_pipe(jnp.zeros_like(xs))

        def body(t, carry):
            buf, outs = carry
            # Stage 0 feeds microbatch t (clamped during drain); others
            # consume the activation shifted in from the previous stage.
            mb = jax.lax.dynamic_index_in_dim(
                xs, jnp.minimum(t, M - 1), 0, keepdims=False
            )
            inp = jnp.where(s == 0, mb, buf)
            y = local_stage(chunk, inp)
            # The last stage emits microbatch t-(S-1) once it's real.
            oidx = t - (S - 1)
            emit = (s == S - 1) & (oidx >= 0)
            updated = jax.lax.dynamic_update_index_in_dim(
                outs, y, jnp.clip(oidx, 0, M - 1), 0
            )
            outs = jnp.where(emit, updated, outs)
            if S > 1:
                buf = jax.lax.ppermute(
                    y, PIPE_AXIS, [(i, i + 1) for i in range(S - 1)]
                )
            return buf, outs

        _, outs = jax.lax.fori_loop(0, M + S - 1, body, (buf, outs))
        # Replicate the result over the pipe axis (only the last stage
        # holds it); a masked psum is the differentiable broadcast.
        return jax.lax.psum(
            jnp.where(s == S - 1, outs, jnp.zeros_like(outs)), PIPE_AXIS
        )

    mapped = shard_map(
        device_fn,
        mesh=mesh,
        in_specs=(P(PIPE_AXIS), P(None, DATA_AXIS)),
        out_specs=P(None, DATA_AXIS),
    )

    def apply(staged_params, x):
        b = x.shape[0]
        if b % M != 0:
            raise ValueError(f"batch {b} not divisible by {M} microbatches")
        xs = x.reshape(M, b // M, *x.shape[1:])
        ys = mapped(staged_params, xs)
        return ys.reshape(b, *x.shape[1:])

    return apply
