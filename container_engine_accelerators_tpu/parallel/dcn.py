"""Multi-host JAX initialization inside Kubernetes.

The reference launches multi-host jobs with MPI + ssh between pods
(gpudirect-tcpx/nccl-test.yaml, nccl-config.yaml:31-35).  The TPU-native
launcher is ``jax.distributed.initialize`` with deterministic coordinator
addressing from the Job's headless Service DNS — no ssh, no MPI
(SURVEY.md §7 hard part (e)).

Env contract (set by the Job manifest, deploy/xla-collectives/):

    TPU_WORKER_ID         process index        (or JOB_COMPLETION_INDEX)
    TPU_WORKER_COUNT      number of processes  (Job parallelism)
    TPU_COORDINATOR_ADDR  host:port of process 0; when unset it is derived
                          as <job>-0.<service>:8476 from JOB_NAME/SERVICE.
"""

import logging
import os
from typing import Optional, Tuple

log = logging.getLogger(__name__)

DEFAULT_COORDINATOR_PORT = 8476


def resolve_cluster(env=None) -> Tuple[Optional[str], int, int]:
    """Return (coordinator_address, num_processes, process_id) from env.

    Returns (None, 1, 0) for single-process runs.
    """
    env = env if env is not None else os.environ
    num = int(env.get("TPU_WORKER_COUNT", env.get("NUM_TPU_WORKERS", "1")))
    if num <= 1:
        return None, 1, 0
    pid_raw = env.get("TPU_WORKER_ID", env.get("JOB_COMPLETION_INDEX"))
    if pid_raw is None:
        raise ValueError(
            "TPU_WORKER_COUNT > 1 but neither TPU_WORKER_ID nor "
            "JOB_COMPLETION_INDEX is set"
        )
    process_id = int(pid_raw)
    if not 0 <= process_id < num:
        raise ValueError(f"process id {process_id} outside [0, {num})")

    addr = env.get("TPU_COORDINATOR_ADDR")
    if not addr:
        job = env.get("JOB_NAME")
        service = env.get("TPU_SERVICE_NAME", job)
        if not job:
            raise ValueError(
                "multi-host run needs TPU_COORDINATOR_ADDR or JOB_NAME to "
                "derive the coordinator from headless-service DNS"
            )
        # Indexed Jobs give pod 0 the stable DNS name <job>-0.<service>.
        addr = f"{job}-0.{service}:{DEFAULT_COORDINATOR_PORT}"
    elif ":" not in addr:
        addr = f"{addr}:{DEFAULT_COORDINATOR_PORT}"
    return addr, num, process_id


def initialize(env=None) -> Tuple[int, int]:
    """Initialize jax.distributed from the K8s env contract.

    Safe to call in single-process runs (no-op).  Returns
    (num_processes, process_id).
    """
    import jax

    addr, num, pid = resolve_cluster(env)
    if num <= 1:
        return 1, 0
    log.info(
        "jax.distributed.initialize(coordinator=%s, num_processes=%d, "
        "process_id=%d)", addr, num, pid,
    )
    jax.distributed.initialize(
        coordinator_address=addr, num_processes=num, process_id=pid
    )
    return num, pid
