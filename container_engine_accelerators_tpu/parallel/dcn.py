"""Multi-host JAX initialization inside Kubernetes.

The reference launches multi-host jobs with MPI + ssh between pods
(gpudirect-tcpx/nccl-test.yaml, nccl-config.yaml:31-35).  The TPU-native
launcher is ``jax.distributed.initialize`` with deterministic coordinator
addressing from the Job's headless Service DNS — no ssh, no MPI
(SURVEY.md §7 hard part (e)).

Env contract (set by the Job manifest, deploy/xla-collectives/):

    TPU_WORKER_ID         process index        (or JOB_COMPLETION_INDEX)
    TPU_WORKER_COUNT      number of processes  (Job parallelism)
    TPU_COORDINATOR_ADDR  host:port of process 0; when unset it is derived
                          as <job>-0.<service>:8476 from JOB_NAME/SERVICE.
    DCN_UDS_DIR           UDS directory of the node dcnxferd sidecar; when
                          set, make_xfer_client()/exchange_shard() stage
                          cross-slice legs through the daemon.
"""

import logging
import os
import time
from typing import Callable, Optional, Tuple

log = logging.getLogger(__name__)

DEFAULT_COORDINATOR_PORT = 8476

# Env contract for the node dcnxferd sidecar (set by the Job manifest
# next to the worker-id/coordinator vars above).
DCN_UDS_ENV = "DCN_UDS_DIR"


def resolve_cluster(env=None) -> Tuple[Optional[str], int, int]:
    """Return (coordinator_address, num_processes, process_id) from env.

    Returns (None, 1, 0) for single-process runs.
    """
    env = env if env is not None else os.environ
    num = int(env.get("TPU_WORKER_COUNT", env.get("NUM_TPU_WORKERS", "1")))
    if num <= 1:
        return None, 1, 0
    pid_raw = env.get("TPU_WORKER_ID", env.get("JOB_COMPLETION_INDEX"))
    if pid_raw is None:
        raise ValueError(
            "TPU_WORKER_COUNT > 1 but neither TPU_WORKER_ID nor "
            "JOB_COMPLETION_INDEX is set"
        )
    process_id = int(pid_raw)
    if not 0 <= process_id < num:
        raise ValueError(f"process id {process_id} outside [0, {num})")

    addr = env.get("TPU_COORDINATOR_ADDR")
    if not addr:
        job = env.get("JOB_NAME")
        service = env.get("TPU_SERVICE_NAME", job)
        if not job:
            raise ValueError(
                "multi-host run needs TPU_COORDINATOR_ADDR or JOB_NAME to "
                "derive the coordinator from headless-service DNS"
            )
        # Indexed Jobs give pod 0 the stable DNS name <job>-0.<service>.
        addr = f"{job}-0.{service}:{DEFAULT_COORDINATOR_PORT}"
    elif ":" not in addr:
        addr = f"{addr}:{DEFAULT_COORDINATOR_PORT}"
    return addr, num, process_id


def initialize(env=None) -> Tuple[int, int]:
    """Initialize jax.distributed from the K8s env contract.

    Safe to call in single-process runs (no-op).  Returns
    (num_processes, process_id).
    """
    import jax

    addr, num, pid = resolve_cluster(env)
    if num <= 1:
        return 1, 0
    log.info(
        "jax.distributed.initialize(coordinator=%s, num_processes=%d, "
        "process_id=%d)", addr, num, pid,
    )
    jax.distributed.initialize(
        coordinator_address=addr, num_processes=num, process_id=pid
    )
    return num, pid


# ---- dcnxferd transfer path -------------------------------------------------


def make_xfer_client(
    uds_dir: Optional[str] = None,
    resilient: bool = True,
    env=None,
    **kwargs,
):
    """Build the node dcnxferd client from the pod env contract.

    Resolution order: explicit ``uds_dir`` arg, then ``DCN_UDS_DIR``
    env.  Returns None when neither is set (no sidecar on this node —
    callers degrade to pure in-process collectives).  ``resilient=True``
    (the default for workloads) returns a
    :class:`~container_engine_accelerators_tpu.parallel.dcn_client.ResilientDcnXferClient`
    that rides out daemon restarts; pass False for the fail-fast
    transport client.
    """
    from container_engine_accelerators_tpu.parallel.dcn_client import (
        DcnXferClient,
        ResilientDcnXferClient,
    )

    env = env if env is not None else os.environ
    uds = uds_dir or env.get(DCN_UDS_ENV)
    if not uds:
        return None
    cls = ResilientDcnXferClient if resilient else DcnXferClient
    return cls(uds, **kwargs)


# Adaptive poll bounds for daemons without the blocking wait op: start
# fine-grained so small transfers stop paying a poll-quantum tax, back
# off to the old 20 ms ceiling so a long wait stays cheap.
POLL_MIN_S = 0.0005
POLL_MAX_S = 0.02


def wait_flow_rx(client, flow: str, nbytes: int,
                 timeout_s: float = 60.0) -> None:
    """Block until ``flow`` has landed ``nbytes`` (RX accounting is
    asynchronous on the daemon side).

    Fast path: the daemon's blocking ``wait`` control op (PyXferd) —
    a condition-variable wakeup instead of polling.  Fallback for
    wait-less daemons (the native daemon, the test stub): adaptive
    poll, doubling from :data:`POLL_MIN_S` to :data:`POLL_MAX_S`, each
    poll asking ``stats`` for just this flow (filter-aware daemons
    answer in O(1); older ones return everything and the client-side
    ``next`` does the filtering as before).
    """
    from container_engine_accelerators_tpu.parallel.dcn_client import (
        DcnWaitUnsupported,
    )

    wait_rx = getattr(client, "wait_rx", None)
    if wait_rx is not None:
        try:
            wait_rx(flow, nbytes, timeout_s=timeout_s)
            return
        except DcnWaitUnsupported:
            pass  # wait-less daemon: poll below
        except TimeoutError:
            raise TimeoutError(
                f"flow {flow!r} never received {nbytes} bytes"
            )
    deadline = time.monotonic() + timeout_s
    interval = POLL_MIN_S
    while time.monotonic() < deadline:
        f = next(
            (x for x in client.stats(flow=flow)["flows"]
             if x["flow"] == flow),
            None,
        )
        if f is not None and f["rx_bytes"] >= nbytes:
            return
        time.sleep(interval)
        interval = min(interval * 2, POLL_MAX_S)
    raise TimeoutError(f"flow {flow!r} never received {nbytes} bytes")


def exchange_shard(
    client,
    *,
    local_flow: str,
    peer_flow: str,
    data: Optional[bytes] = None,
    peer_host: str,
    peer_port: int,
    barrier: Optional[Callable[[], object]] = None,
    timeout_s: float = 60.0,
    pipelined: Optional[bool] = None,
    producer=None,
    nbytes: Optional[int] = None,
) -> bytes:
    """One cross-pod leg of a DCN collective, staged through dcnxferd.

    Registers both directions (``local_flow`` to send, ``peer_flow`` to
    land the peer's shard), stages ``data`` via the data plane, streams
    it to the peer daemon, and returns the peer's shard read back out of
    the local daemon — the pattern the jax.distributed integration rig
    drives (tests/dcn_xfer_worker.py).  ``barrier`` runs after flow
    registration and before the send: the peer must have registered its
    landing flow or the payload counts as unmatched and is dropped
    (``multihost_utils.sync_global_devices`` in real workers).

    With a resilient client the leg survives a daemon restart at any
    point on the LOCAL side: flows are replayed on reconnect, ``put``'s
    retry budget restages during staging, and a restart after a
    completed put is healed by the client itself — a ``send`` that hits
    the restarted daemon's blank staging restages the cached payload
    and re-sends under the same frame seq, and ``read`` does the
    equivalent for read-back (``dcn.send.restaged`` /
    ``dcn.read.restaged``).  What no client can heal alone is the
    PEER's staged shard dying with the peer daemon after it landed —
    the rx wait times out and callers retry the whole leg, which asks
    the peer to re-send.

    Payloads above the pipeline threshold (``TPU_DCN_CHUNK_BYTES``,
    daemon permitting) take the chunked/striped pipelined path: stage
    and send overlap per chunk and read-back is raw DXR1 instead of
    base64 — pass ``pipelined=False``/``True`` to force either leg.
    A pipelined failure falls back to the serial path once
    (``dcn.pipeline.fallback``) before surfacing, so a daemon that
    lost its pipeline capability mid-leg degrades instead of failing.
    Empty shards short-circuit after the barrier: registration keeps
    the rendezvous honest, but no bytes are staged, sent, or read.

    Producer mode (``producer`` + ``nbytes``, ``data=None``): the
    shard is pulled from an iterable (or zero-arg callable returning
    one) of byte chunks AS THE PIPELINED LEG STAGES — on a ring-
    capable daemon, production overlaps the DCN leg instead of
    preceding it, which is what pulls ``dcn.exposed_ratio`` below the
    stage-then-send baseline.  Every consumed chunk is captured, so
    the serial fallback (and a ring-less daemon) still sees the full
    payload; the producer itself is consumed at most once.
    """
    from container_engine_accelerators_tpu.metrics import counters
    from container_engine_accelerators_tpu.obs import histo, timeseries, trace
    from container_engine_accelerators_tpu.parallel import dcn_pipeline
    from container_engine_accelerators_tpu.parallel.dcn_client import (
        DcnXferError,
    )

    produced = []
    producer_iter = None
    src = None
    if producer is not None:
        if data is not None:
            raise ValueError("pass data OR producer, not both")
        if not nbytes or int(nbytes) <= 0:
            raise ValueError("producer mode needs nbytes > 0")
        nbytes = int(nbytes)
        src = iter(producer() if callable(producer) else producer)

        def _capture(it=src):
            # Tee every consumed chunk: a fallback leg (serial path,
            # ring-less daemon) can then materialize the full shard
            # even though the producer is one-shot.
            for piece in it:
                produced.append(bytes(piece))
                yield piece

        producer_iter = _capture()
    else:
        nbytes = len(data)

    def _materialize() -> bytes:
        whole = b"".join(produced) + b"".join(bytes(p) for p in src)
        if len(whole) != nbytes:
            raise DcnXferError(
                f"producer yielded {len(whole)} bytes for "
                f"{local_flow!r}, expected {nbytes}")
        return whole
    try:
        # One span per leg, one child span per phase: a slow exchange
        # decomposes into register / barrier / stage / send / land /
        # read in the trace instead of a single opaque wall-clock.
        with trace.span("dcn.exchange", histogram="dcn.exchange",
                        local_flow=local_flow, peer_flow=peer_flow,
                        bytes=nbytes, peer=peer_host):
            # Registration inside the try: if the SECOND register fails
            # (max_flows, name collision) the finally still releases the
            # first instead of leaking it into every retry of the leg.
            with trace.span("dcn.exchange.register"):
                client.register_flow(local_flow, peer=peer_host,
                                     bytes=nbytes)
                client.register_flow(peer_flow, bytes=nbytes)
            if barrier is not None:
                with trace.span("dcn.exchange.barrier",
                                histogram="dcn.exchange.barrier"):
                    barrier()
            if nbytes == 0:
                # Nothing to move: the barrier already proved both
                # sides showed up, and the peer's empty shard has
                # nothing to land here either.
                counters.inc("dcn.exchange.empty")
                return b""
            cfg = dcn_pipeline.PipelineConfig()
            use_pipe = (pipelined if pipelined is not None
                        else dcn_pipeline.should_pipeline(client, nbytes,
                                                          cfg))
            if use_pipe:
                try:
                    return _exchange_pipelined(
                        client, local_flow, peer_flow, data, peer_host,
                        peer_port, cfg, timeout_s,
                        producer=producer_iter, nbytes=nbytes)
                except (DcnXferError, OSError) as e:
                    if pipelined:  # explicitly forced: surface it
                        raise
                    counters.inc("dcn.pipeline.fallback")
                    log.warning(
                        "pipelined exchange of %r failed (%s); "
                        "falling back to the serial leg",
                        local_flow, e,
                    )
            if data is None:
                # Producer mode on the serial path: materialize the
                # captured prefix plus the rest of the iterator —
                # stage-then-send, the baseline shape.
                data = _materialize()
            with trace.span("dcn.exchange.stage",
                            histogram="dcn.exchange.stage"):
                client.put(local_flow, data)
                wait_flow_rx(client, local_flow, nbytes, timeout_s)
            t_comm0 = time.monotonic()
            with trace.span("dcn.exchange.send",
                            histogram="dcn.exchange.send"):
                client.send(local_flow, peer_host, peer_port, nbytes)
            with trace.span("dcn.exchange.land",
                            histogram="dcn.exchange.land"):
                wait_flow_rx(client, peer_flow, nbytes, timeout_s)
            # The serial leg by construction overlaps NOTHING with its
            # send+land phases: its whole DCN time is exposed.  Feed
            # the same histograms the pipelined lane feeds so the
            # exposed-comm ratio compares the shapes honestly
            # (ratio 1.0 is the serial baseline the pipeline beats).
            comm_s = time.monotonic() - t_comm0
            if comm_s > 0:
                cur = trace.current()
                tid = cur.trace_id if cur is not None else None
                histo.observe("dcn.exposed", comm_s, trace_id=tid)
                histo.observe("dcn.comm", comm_s, trace_id=tid)
                timeseries.gauge("dcn.exposed_ratio", 1.0)
            got = client.read(peer_flow, nbytes)
            if len(got) != nbytes:
                # With chunked peers, rx accounting can reach nbytes
                # while the landed frame is still assembling (or spans
                # two attempts); a short read here is "not landed yet",
                # never data — surface it so the caller's leg retry
                # asks the peer to re-send.
                raise DcnXferError(
                    f"peer shard {peer_flow!r} read short: "
                    f"{len(got)} != {nbytes}"
                )
            return got
    finally:
        # Release both flows so repeated legs on a long-lived client
        # neither hit the daemon's duplicate-flow rejection nor leak
        # staging buffers toward max_flows/pool exhaustion.  By here the
        # peer's send into peer_flow has landed (we waited + read), and
        # local_flow's payload has been streamed out, so the releases
        # touch only this node's daemon state.
        for flow in (local_flow, peer_flow):
            try:
                client.release_flow(flow)
            except (DcnXferError, OSError):
                pass  # cleanup: a restarted daemon already forgot it


def _exchange_pipelined(client, local_flow, peer_flow, data, peer_host,
                        peer_port, cfg, timeout_s, producer=None,
                        nbytes=None) -> bytes:
    """The pipelined leg body: overlapped chunked stage+send of the
    local shard, then land-wait and read-back of the peer's (zero-copy
    shm when the daemon is same-host, DXR1 otherwise).  Flows are
    already registered; the caller owns release."""
    from container_engine_accelerators_tpu.obs import trace
    from container_engine_accelerators_tpu.parallel import dcn_pipeline
    from container_engine_accelerators_tpu.parallel.dcn_client import (
        DcnXferError,
    )

    nbytes = len(data) if data is not None else int(nbytes)
    with trace.span("dcn.exchange.pipeline",
                    histogram="dcn.exchange.pipeline",
                    local_flow=local_flow, bytes=nbytes):
        if cfg.shm and dcn_pipeline.shm_same_host(client):
            # Attach the LANDING flow's segment before the peer's
            # chunks arrive: they then assemble straight into the
            # mmap and the shm read below is a pure buffer reference.
            # Best effort — without it, shm_read migrates with one
            # copy, which still beats any socket stream.
            try:
                client.shm_attach(peer_flow, nbytes)
            except (DcnXferError, OSError):
                pass
        dcn_pipeline.send_pipelined(client, local_flow, data,
                                    peer_host, peer_port, cfg,
                                    timeout_s=timeout_s,
                                    producer=producer, nbytes=nbytes)
        with trace.span("dcn.exchange.land",
                        histogram="dcn.exchange.land"):
            wait_flow_rx(client, peer_flow, nbytes, timeout_s)
        return dcn_pipeline.read_pipelined(client, peer_flow, nbytes,
                                           cfg, timeout_s=timeout_s)
