"""Client half of the zero-copy same-host staging lane.

Every local stage/send/read used to cross a TCP socket even when the
client and its daemon share a host — the analog of the CPU-proxy hop
GPU-Initiated Networking removes from the transfer path (PAPERS.md).
The shm lane removes ours: a daemon that advertises ``shm`` in its
``version`` handshake owns per-flow ``mmap``-backed segments under
``shm_dir``; a same-host client writes chunk ``memoryview``s straight
into the segment and reads landed frames back out of it, so the two
client↔daemon payload passes become memcpys while the daemon→peer
leg (the actual network) and EVERY control op — seq assignment,
dedup, ``wait``, fabric verdicts — stay exactly where they were.
Exactly-once semantics are therefore unchanged: the shm lane moves
bytes, never authority.

Same-host detection compares **boot identity**, not addresses: two
containers can share ``127.0.0.1`` across a netns boundary without
sharing a filesystem, and a daemon behind a forwarded UDS may be on
another machine entirely.  ``host_identity()`` is the kernel boot id
plus hostname (override: ``TPU_DCN_HOST_ID``, which is also how tests
fake a cross-host daemon); the daemon stamps its own into the
handshake and the client only takes the lane on an exact match — and
even then, a segment that fails to map falls back to the socket lane
(``dcn.shm.fallback``) rather than failing the transfer.

This module owns host identity, the client-side segment mapping, and
the **descriptor-ring** layout both halves of the handoff protocol
share (ISSUE 13): instead of one control round trip per chunk, the
client writes (off, len, seq) descriptors into a per-flow ring file,
rings ONE ``shm_post`` doorbell, and the daemon completes the
descriptors in place — per-slot verdict codes plus a completion
cursor the client polls lock-free out of its own mapping.  Lane
*selection* and the transfer logic live in
``parallel/dcn_pipeline.py``, the daemon half in ``fleet/xferd.py``.
"""

import mmap
import os
import socket
import struct
from typing import List, Optional, Tuple

HOST_ID_ENV = "TPU_DCN_HOST_ID"
SHM_ENV = "TPU_DCN_SHM"
SHM_DIRECT_ENV = "TPU_DCN_SHM_DIRECT"
SHM_RING_ENV = "TPU_DCN_SHM_RING"

_BOOT_ID_PATH = "/proc/sys/kernel/random/boot_id"
_host_id_cache: Optional[str] = None


def host_identity(env=None) -> str:
    """This process's host identity: ``<boot_id>:<hostname>``, with
    ``TPU_DCN_HOST_ID`` as the explicit override (tests, and operators
    whose mounts make the default ambiguous)."""
    env = env if env is not None else os.environ
    override = env.get(HOST_ID_ENV)
    if override:
        return override
    global _host_id_cache
    if _host_id_cache is None:
        try:
            with open(_BOOT_ID_PATH) as f:
                boot = f.read().strip()
        except OSError:
            boot = "no-boot-id"
        _host_id_cache = f"{boot}:{socket.gethostname()}"
    return _host_id_cache


def shm_enabled(env=None) -> bool:
    """The env kill switch, same grammar as ``TPU_DCN_PIPELINE``."""
    env = env if env is not None else os.environ
    return env.get(SHM_ENV, "1") not in ("0", "false", "off")


def shm_direct_enabled(env=None) -> bool:
    """Kill switch for the daemon↔daemon same-host lane (segments
    instead of the peer TCP stream).  Same grammar as the other data-
    plane switches; consulted by BOTH halves — the sending daemon's
    env gates the lane, and a client can pin it off per transfer
    (``PipelineConfig.shm_direct`` → the send op's ``direct`` key)."""
    env = env if env is not None else os.environ
    return env.get(SHM_DIRECT_ENV, "1") not in ("0", "false", "off")


def shm_ring_enabled(env=None) -> bool:
    """Kill switch for the descriptor-ring handoff (client side:
    whether shm rounds request a ring and post descriptors, or fall
    back to per-chunk control ops).  Same grammar as the rest."""
    env = env if env is not None else os.environ
    return env.get(SHM_RING_ENV, "1") not in ("0", "false", "off")


class Segment:
    """One client-side mapping of a daemon-owned segment file.  The
    daemon owns creation, sizing, and unlinking; the client only maps
    what the ``shm_attach`` / ``shm_read`` response named — a path it
    cannot open or map is a lane fallback, never an error surface."""

    def __init__(self, path: str, size: int):
        self.path = path
        self.size = int(size)
        f = open(path, "r+b")
        try:
            self.map = mmap.mmap(f.fileno(), self.size)
        except ValueError as e:
            # mmap says ValueError when the file is smaller than the
            # advertised size (a crash-restarted daemon recreated the
            # segment at minimum size); normalize to the documented
            # OSError so the lane-fallback handlers catch it.
            raise OSError(f"segment {path!r} unmappable: {e}") from e
        finally:
            f.close()
        self.view = memoryview(self.map)

    def close(self) -> None:
        try:
            self.view.release()
        except (BufferError, AttributeError):
            pass
        try:
            self.map.close()
        except (BufferError, ValueError):
            pass  # an exported slice keeps the map alive until GC


def map_segment(path: str, size: int) -> Segment:
    """Map a daemon-advertised segment; raises ``OSError`` (the
    caller's fallback signal) when the path is gone or undersized."""
    if size <= 0:
        raise OSError(f"segment {path!r} has no size")
    return Segment(path, size)


# ---------------------------------------------------------------------------
# Descriptor ring (ISSUE 13): the shared-memory work queue of the
# handoff protocol.  One ring per flow, living in its own file next to
# the data segment so payload offsets never shift.  All fields are
# little-endian at fixed offsets; the client owns `round`/`posted` and
# the descriptor slots, the daemon owns `completed_round`/`completed`
# and the per-slot status bytes — single-writer per field, so neither
# side ever takes a lock to touch the ring (the poll/wait paths the
# race gate runs under lockwatch are lock-free by construction).
#
#    0  u32  magic "DRG1"
#    4  u32  slots (capacity)
#    8  u64  round            client: bumped once per shm_post
#   16  u64  posted           client: descriptor count for `round`
#   24  u64  completed_round  daemon: the round `completed` refers to
#   32  u64  completed        daemon: descriptors completed so far
#   40  slot[i] (32 bytes):  u64 off | u64 len | u64 seq | u32 status
#                            | u32 pad
#
# Publication order is the contract: the daemon writes a slot's status
# BEFORE advancing `completed`, and writes `completed = 0` BEFORE
# echoing `completed_round` — a client that observes
# (completed_round == round and completed >= n) can trust every status
# it then reads.  Status codes mirror send verdicts.
# ---------------------------------------------------------------------------

RING_MAGIC = 0x31475244  # "DRG1" little-endian
RING_HDR_BYTES = 40
RING_SLOT_BYTES = 32

RING_PENDING = 0
RING_SENT = 1
RING_LANDED = 2
RING_DUP = 3
RING_DROPPED = 4
RING_UNMATCHED = 5
RING_REJECTED = 6
RING_ERROR = 7
RING_STALE = 8

# Status code <-> the verdict strings the scoreboard already speaks
# (one mapping, derived both ways: the ring lane must never report a
# different status than the per-chunk lane for the same verdict).
RING_VERDICTS = {
    RING_SENT: "sent", RING_LANDED: "landed", RING_DUP: "dup",
    RING_DROPPED: "dropped", RING_UNMATCHED: "unmatched",
    RING_REJECTED: "rejected", RING_ERROR: "error",
    RING_STALE: "stale",
}
RING_STATUS_BY_VERDICT = {v: k for k, v in RING_VERDICTS.items()}


def ring_bytes(slots: int) -> int:
    return RING_HDR_BYTES + RING_SLOT_BYTES * int(slots)


class RingView:
    """Typed accessors over one mapping of a ring file.  Works on any
    writable buffer (the daemon's ``mmap``, the client's
    :class:`Segment` view); does no locking — see the layout note."""

    def __init__(self, buf):
        self.buf = buf

    def init(self, slots: int) -> None:
        struct.pack_into("<II", self.buf, 0, RING_MAGIC, slots)
        struct.pack_into("<QQQQ", self.buf, 8, 0, 0, 0, 0)

    @property
    def slots(self) -> int:
        magic, slots = struct.unpack_from("<II", self.buf, 0)
        if magic != RING_MAGIC:
            raise OSError("ring magic mismatch (stale or torn file)")
        return slots

    # -- client half ---------------------------------------------------------

    def post(self, descs: List[Tuple[int, int, int]]) -> int:
        """Write one round's descriptors and bump ``round``; returns
        the round number the doorbell op must quote.  Descriptor order
        is completion order — the daemon walks slots [0, n)."""
        if len(descs) > self.slots:
            raise OSError(f"{len(descs)} descriptors > "
                          f"{self.slots} ring slots")
        for i, (off, ln, seq) in enumerate(descs):
            struct.pack_into("<QQQII", self.buf,
                             RING_HDR_BYTES + i * RING_SLOT_BYTES,
                             off, ln, seq, RING_PENDING, 0)
        rnd = struct.unpack_from("<Q", self.buf, 8)[0] + 1
        struct.pack_into("<Q", self.buf, 16, len(descs))
        struct.pack_into("<Q", self.buf, 8, rnd)
        return rnd

    def completion(self) -> Tuple[int, int]:
        """(completed_round, completed) — the daemon's published
        cursor."""
        return struct.unpack_from("<QQ", self.buf, 24)

    def statuses(self, n: int) -> List[int]:
        return [struct.unpack_from(
                    "<I", self.buf,
                    RING_HDR_BYTES + i * RING_SLOT_BYTES + 24)[0]
                for i in range(n)]

    # -- daemon half ---------------------------------------------------------

    def read_descs(self, n: int) -> List[Tuple[int, int, int]]:
        return [struct.unpack_from(
                    "<QQQ", self.buf,
                    RING_HDR_BYTES + i * RING_SLOT_BYTES)[:3]
                for i in range(n)]

    def begin_round(self, rnd: int) -> None:
        """Publish "working on `rnd`, nothing done yet" — ``completed``
        first, then the round echo (the order the client trusts)."""
        struct.pack_into("<Q", self.buf, 32, 0)
        struct.pack_into("<Q", self.buf, 24, rnd)

    def complete(self, i: int, status: int, done: int) -> None:
        """Publish slot ``i``'s verdict, then advance the cursor."""
        struct.pack_into("<I", self.buf,
                         RING_HDR_BYTES + i * RING_SLOT_BYTES + 24,
                         status)
        struct.pack_into("<Q", self.buf, 32, done)
