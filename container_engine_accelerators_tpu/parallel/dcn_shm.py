"""Client half of the zero-copy same-host staging lane.

Every local stage/send/read used to cross a TCP socket even when the
client and its daemon share a host — the analog of the CPU-proxy hop
GPU-Initiated Networking removes from the transfer path (PAPERS.md).
The shm lane removes ours: a daemon that advertises ``shm`` in its
``version`` handshake owns per-flow ``mmap``-backed segments under
``shm_dir``; a same-host client writes chunk ``memoryview``s straight
into the segment and reads landed frames back out of it, so the two
client↔daemon payload passes become memcpys while the daemon→peer
leg (the actual network) and EVERY control op — seq assignment,
dedup, ``wait``, fabric verdicts — stay exactly where they were.
Exactly-once semantics are therefore unchanged: the shm lane moves
bytes, never authority.

Same-host detection compares **boot identity**, not addresses: two
containers can share ``127.0.0.1`` across a netns boundary without
sharing a filesystem, and a daemon behind a forwarded UDS may be on
another machine entirely.  ``host_identity()`` is the kernel boot id
plus hostname (override: ``TPU_DCN_HOST_ID``, which is also how tests
fake a cross-host daemon); the daemon stamps its own into the
handshake and the client only takes the lane on an exact match — and
even then, a segment that fails to map falls back to the socket lane
(``dcn.shm.fallback``) rather than failing the transfer.

This module owns host identity and the client-side segment mapping;
lane *selection* and the transfer logic live in
``parallel/dcn_pipeline.py``, the daemon half in ``fleet/xferd.py``.
"""

import mmap
import os
import socket
from typing import Optional

HOST_ID_ENV = "TPU_DCN_HOST_ID"
SHM_ENV = "TPU_DCN_SHM"

_BOOT_ID_PATH = "/proc/sys/kernel/random/boot_id"
_host_id_cache: Optional[str] = None


def host_identity(env=None) -> str:
    """This process's host identity: ``<boot_id>:<hostname>``, with
    ``TPU_DCN_HOST_ID`` as the explicit override (tests, and operators
    whose mounts make the default ambiguous)."""
    env = env if env is not None else os.environ
    override = env.get(HOST_ID_ENV)
    if override:
        return override
    global _host_id_cache
    if _host_id_cache is None:
        try:
            with open(_BOOT_ID_PATH) as f:
                boot = f.read().strip()
        except OSError:
            boot = "no-boot-id"
        _host_id_cache = f"{boot}:{socket.gethostname()}"
    return _host_id_cache


def shm_enabled(env=None) -> bool:
    """The env kill switch, same grammar as ``TPU_DCN_PIPELINE``."""
    env = env if env is not None else os.environ
    return env.get(SHM_ENV, "1") not in ("0", "false", "off")


class Segment:
    """One client-side mapping of a daemon-owned segment file.  The
    daemon owns creation, sizing, and unlinking; the client only maps
    what the ``shm_attach`` / ``shm_read`` response named — a path it
    cannot open or map is a lane fallback, never an error surface."""

    def __init__(self, path: str, size: int):
        self.path = path
        self.size = int(size)
        f = open(path, "r+b")
        try:
            self.map = mmap.mmap(f.fileno(), self.size)
        except ValueError as e:
            # mmap says ValueError when the file is smaller than the
            # advertised size (a crash-restarted daemon recreated the
            # segment at minimum size); normalize to the documented
            # OSError so the lane-fallback handlers catch it.
            raise OSError(f"segment {path!r} unmappable: {e}") from e
        finally:
            f.close()
        self.view = memoryview(self.map)

    def close(self) -> None:
        try:
            self.view.release()
        except (BufferError, AttributeError):
            pass
        try:
            self.map.close()
        except (BufferError, ValueError):
            pass  # an exported slice keeps the map alive until GC


def map_segment(path: str, size: int) -> Segment:
    """Map a daemon-advertised segment; raises ``OSError`` (the
    caller's fallback signal) when the path is gone or undersized."""
    if size <= 0:
        raise OSError(f"segment {path!r} has no size")
    return Segment(path, size)
