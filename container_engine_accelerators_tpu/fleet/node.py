"""EmulatedNode: one simulated host, full node-agent stack included.

Each node the fleet boots is the real single-node machinery, not a
mock: a :class:`TpuManager` discovering a fabricated sysfs/dev tree, a
:class:`TpuHealthChecker` with the production quiescence/flap-backoff
state machine, a :class:`PyXferd` transfer daemon with a live data
plane, a :class:`ResilientDcnXferClient` with the production
reconnect/replay/restage behavior, and (opt-in) a per-node
:class:`MetricServer` on an ephemeral port.  Chaos at the fleet level
therefore exercises exactly the code paths a real node would run —
the same reason the chaos suite injects faults into production call
sites instead of monkeypatching sockets.

Health is pumped deterministically (the controller drains the manager's
health queue between rounds, like ListAndWatch would) so scenarios are
reproducible: no background thread races the fault schedule.
"""

import logging
import os
from typing import Dict, Optional

from container_engine_accelerators_tpu.deviceplugin.manager import TpuManager
from container_engine_accelerators_tpu.fleet.topology import NodeSpec
from container_engine_accelerators_tpu.fleet.xferd import PyXferd
from container_engine_accelerators_tpu.health import TpuHealthChecker
from container_engine_accelerators_tpu.metrics import counters
from container_engine_accelerators_tpu.obs import trace
from container_engine_accelerators_tpu.parallel.dcn_client import (
    ResilientDcnXferClient,
)
from container_engine_accelerators_tpu.tpulib import SysfsTpuLib, write_fixture
from container_engine_accelerators_tpu.tpulib.types import TpuErrorEvent
from container_engine_accelerators_tpu.utils.config import TPUConfig
from container_engine_accelerators_tpu.utils.device import HEALTHY
from container_engine_accelerators_tpu.utils.retry import RetryPolicy

log = logging.getLogger(__name__)

# Node-agent retry budget at simulation timescale: same shape as
# production DEFAULT_DCN_RETRY, milliseconds instead of seconds.
FLEET_CLIENT_RETRY = RetryPolicy(
    max_attempts=8, initial_backoff_s=0.01, max_backoff_s=0.1,
    deadline_s=15.0,
)

DEFAULT_RECOVERY_WINDOW_S = 0.05


class EmulatedNode:
    def __init__(
        self,
        spec: NodeSpec,
        root: str,
        net=None,
        recovery_window_s: float = DEFAULT_RECOVERY_WINDOW_S,
        metrics: bool = False,
        client_retry: Optional[RetryPolicy] = None,
        metrics_interval_s: float = 30.0,
    ):
        self.spec = spec
        self.name = spec.name
        self.root = root
        self.net = net
        self.down = False  # daemon intentionally killed by the scenario
        # Parity fields with fleet/proc.ProcNode, so reports carry one
        # schema whichever mode booted the node: an in-process node is
        # never budget-limited, but its restarts are still counted.
        self.permanently_down = False
        self.restarts = 0

        write_fixture(root, spec.chips, topology=spec.topology)
        cfg_json = ({"tpuPartitionSize": spec.partition_size}
                    if spec.partition_size else {})
        cfg = TPUConfig.from_json(cfg_json)
        cfg.add_defaults_and_validate()
        self.lib = SysfsTpuLib(root)
        self.manager = TpuManager(
            os.path.join(root, "dev"), [], cfg, lib=self.lib
        )
        self.manager.start()
        self.health = TpuHealthChecker(
            self.manager, self.lib, recovery_window_s=recovery_window_s
        )
        self.daemon = PyXferd(
            os.path.join(root, "tpu-dcn"), node=spec.name, net=net
        ).start()
        if net is not None:
            net.register(spec.name, self.daemon)
        self.client = ResilientDcnXferClient(
            os.path.join(root, "tpu-dcn"),
            retry=client_retry or FLEET_CLIENT_RETRY,
        )
        self.metrics = None
        if metrics:
            # Per-node exporter on an ephemeral port; the pod-resources
            # socket does not exist in the sim and its absence is
            # absorbed (the production contract).
            from container_engine_accelerators_tpu.metrics.metrics import (
                MetricServer,
                TpuMetricsCollector,
            )

            self.metrics = MetricServer(
                collector=TpuMetricsCollector(self.lib),
                port=0,
                collection_interval_s=metrics_interval_s,
                pod_resources_socket=os.path.join(root, "noresources.sock"),
            )
            self.metrics.start()

    # -- health --------------------------------------------------------------

    def pump_health(self) -> int:
        """Drain queued health transitions into device state, as the
        kubelet-facing ListAndWatch announcement loop would."""
        n = 0
        while True:
            try:
                d = self.manager.health_events.get_nowait()
            except Exception:  # queue.Empty
                return n
            self.manager.set_device_health(d.id, d.health)
            n += 1

    def inject_chip_fault(self, chip: str, code: int = 48) -> None:
        trace.event("fleet.chip_fault", node=self.name, chip=chip,
                    code=code)
        self.health.catch_error(TpuErrorEvent(code=code, device=chip))
        self.pump_health()

    def recover(self, now: Optional[float] = None) -> int:
        # The external injector file is polled on the same deterministic
        # cadence as recovery: scenarios pump between rounds, so a
        # fault line written from OUTSIDE the coordinator RPC lands
        # with the next round's health sweep (TPU_CHIP_FAULT_FILE —
        # proc workers inherit the env path from their coordinator).
        self.health.poll_fault_file()
        n = self.health.maybe_recover(now=now)
        self.pump_health()
        return n

    def force_recover(self) -> int:
        """Drive every pending quiescence window closed (a scenario's
        explicit ``chip_recover`` action — deterministic, no sleeps)."""
        import time as _time

        return self.recover(now=_time.monotonic() + 1e6)

    def device_health(self) -> Dict[str, str]:
        return {d.id: d.health
                for d in self.manager.list_devices().values()}

    def all_healthy(self) -> bool:
        health = self.device_health()
        return bool(health) and all(h == HEALTHY for h in health.values())

    # -- daemon churn --------------------------------------------------------

    def kill_daemon(self) -> None:
        trace.event("fleet.node_kill", node=self.name)
        self.down = True
        if self.net is not None:
            self.net.unregister(self.name)
        self.daemon.stop(crash=True)

    def restart_daemon(self) -> bool:
        trace.event("fleet.node_restart", node=self.name)
        self.daemon.start()
        if self.net is not None:
            self.net.register(self.name, self.daemon)
        self.down = False
        self.restarts += 1
        counters.inc("fleet.node.restarts")
        return True

    # -- reporting -----------------------------------------------------------

    def snapshot(self) -> dict:
        health = self.device_health()
        snap = {
            "rack": self.spec.rack,
            "devices": health,
            "healthy": sum(1 for h in health.values() if h == HEALTHY),
            "total": len(health),
            "daemon_generation": self.daemon.generation,
            "down": self.down,
            "restarts": self.restarts,
            "permanently_down": self.permanently_down,
        }
        if self.metrics is not None:
            snap["metrics_port"] = self.metrics.port
        return snap

    def close(self) -> None:
        for action in (
            lambda: self.client.close(),
            lambda: self.daemon.stop(),
            lambda: self.metrics.stop() if self.metrics else None,
        ):
            try:
                action()
            except OSError:
                pass
