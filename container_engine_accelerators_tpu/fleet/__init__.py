"""Fleet simulation rig: N emulated nodes, link-level faults, fleet
observability.

Everything else in this stack is *single-node*: one ``TpuManager``, one
health checker, one ``dcnxferd`` double.  The reference's whole reason
to exist is multi-host accelerator infrastructure — topology-aware
placement, per-node daemons, high-bandwidth collectives across racks —
and collective behavior under *link-level* asymmetry (one rack
partitioned, one direction lossy) is qualitatively different from the
endpoint churn the chaos suite already covers (TACCL, PAPERS.md).  This
package is the rig that makes those scenarios testable on a laptop:

- ``fleet.topology``   fleet model: racks/hosts/slices as NodeSpecs,
                       labeled with the SAME keys the scheduler sorts
                       on (scheduler/topology.py), so link tiers fall
                       out of the production distance function;
- ``fleet.links``      the link table — per-(src,dst) state every
                       inter-node DCN frame routes through, and the
                       fault surface: partition / loss / latency,
                       armed from a compact spec grammar;
- ``fleet.xferd``      PyXferd, a protocol-faithful Python transfer
                       daemon with a real data plane: per-flow frame
                       sequencing, receiver-side dedup, trace-context
                       propagation on both control ops and frames;
- ``fleet.node``       EmulatedNode: TpuManager + health checker +
                       PyXferd + resilient client (+ optional
                       MetricServer), one per simulated host;
- ``fleet.proc``       process mode: each node as its own OS process
                       (worker entrypoint + coordinator-side ProcNode
                       with real SIGKILL, supervised restart, and
                       handshake/reap hygiene) — ``proc: true``
                       scenarios run chaos against real process
                       boundaries and aggregate telemetry by scraping
                       each worker's MetricServer over HTTP;
- ``fleet.controller`` FleetController: declarative scenarios (nodes,
                       topology, fault schedule, workload rounds) and
                       the per-node / per-link report.

Drive it with ``python cmd/fleet_sim.py`` or ``make fleet``; the
scenario spec schema is documented in the README ("Fleet simulation").
"""

from container_engine_accelerators_tpu.fleet.controller import (
    DEFAULT_PROC_SCENARIO,
    DEFAULT_SCENARIO,
    FleetController,
    load_scenario,
)
from container_engine_accelerators_tpu.fleet.links import (
    FleetNet,
    LinkPartitioned,
    LinkTable,
)
from container_engine_accelerators_tpu.fleet.node import EmulatedNode
from container_engine_accelerators_tpu.fleet.proc import (
    ProcHandshakeError,
    ProcNode,
)
from container_engine_accelerators_tpu.fleet.topology import (
    FleetTopology,
    NodeSpec,
)
from container_engine_accelerators_tpu.fleet.xferd import PyXferd

__all__ = [
    "DEFAULT_PROC_SCENARIO",
    "DEFAULT_SCENARIO",
    "EmulatedNode",
    "FleetController",
    "FleetNet",
    "FleetTopology",
    "LinkPartitioned",
    "LinkTable",
    "NodeSpec",
    "ProcHandshakeError",
    "ProcNode",
    "PyXferd",
    "load_scenario",
]
